#!/usr/bin/env python
"""Benchmark harness: prints one JSON line per metric
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "mfu": f, "vpu_frac": f, "membw_frac": f, "bound": "mxu|vpu|hbm"}

The default run covers the full claimed surface — the reference-scale VFI
solve, the Krusell-Smith panel throughput, and the north-star scale solve —
so the driver artifact records every headline number, not just the easiest
one. `--metric {vfi,ks,scale}` selects a single line.

Primary metric (BASELINE.json): Aiyagari VFI wall-clock to policy convergence
at the reference scale (400-point quadratic grid, 7 Tauchen states, tol 1e-5),
reported against the framework's own vectorized NumPy implementation measured
in-process (BASELINE.md denominator policy: the reference publishes no
numbers). vs_baseline = numpy_seconds / accelerator_seconds (speedup, >1 is
faster than baseline). The mfu/vpu_frac/membw_frac fields are absolute
%-of-peak figures from the analytic cost models in diagnostics/roofline.py
(null on CPU fallback runs, whose peaks we do not model).

Usage: python bench.py [--grid 400] [--quick] [--metric {all,vfi,ks,scale}]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time
import warnings

import numpy as np

_BASELINE_JSON = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BASELINE.json")


def _machine_fingerprint() -> str:
    """CPU identity of the box the denominator was measured on. The frozen
    denominator is only trusted when this matches — a different machine's
    NumPy seconds are not comparable."""
    import platform

    model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for ln in f:
                if ln.startswith("model name"):
                    model = ln.split(":", 1)[1].strip()
                    break
    except OSError:
        model = platform.processor()
    return f"{platform.machine()}|{model}|cores={os.cpu_count()}|numpy={np.__version__}"


def _baseline_model_400():
    """The NumPy denominator's model inputs, f64. The preset requests f64
    device arrays; under a TPU-attached process x64 stays off and jax warns
    per truncated array — suppress HERE (the arrays are only read back into
    f64 NumPy below, and the spam used to be ~80% of the driver artifact,
    VERDICT round 2)."""
    from aiyagari_tpu.models.aiyagari import aiyagari_preset

    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message=".*requested in a.*is not available.*")
        warnings.filterwarnings("ignore", message=".*float64.*")
        base = aiyagari_preset(grid_size=400)
    return base


def _measure_numpy_vfi400(n_runs: int, tol: float = 1e-5,
                          max_iter: int = 1000) -> list[float]:
    from aiyagari_tpu.solvers import numpy_backend as nb
    from aiyagari_tpu.utils.firm import wage_from_r

    base = _baseline_model_400()
    a = np.asarray(base.a_grid, np.float64)
    s = np.asarray(base.s, np.float64)
    P = np.asarray(base.P, np.float64)
    w = float(wage_from_r(0.04, base.config.technology.alpha,
                          base.config.technology.delta))
    times = []
    for _ in range(n_runs):
        t0 = time.perf_counter()
        nb.vfi_numpy(np.zeros((len(s), len(a))), a, s, P, 0.04, w,
                     sigma=base.preferences.sigma, beta=base.preferences.beta,
                     tol=tol, max_iter=max_iter)
        times.append(time.perf_counter() - t0)
    return sorted(times)


@functools.lru_cache(maxsize=1)
def _numpy_ks_panel_inputs():
    """Inputs for the K-S panel denominator, f64 NumPy: the bench policy
    table (0.9*k_grid broadcast) and the PRNGKey(0) shock panel at reference
    scale (Krusell_Smith_VFI.m:10-11). The shock DRAW's dtype lineage does
    not affect the loop's cost — the denominator is a time, not a path.
    Cached: bench_ks_agents builds the same panel for the TPU numerator, and
    the underlying jit programs are shared, so the second build inside one
    process is pure recompute."""
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu.config import KrusellSmithConfig
    from aiyagari_tpu.models.krusell_smith import KrusellSmithModel
    from aiyagari_tpu.sim.ks_panel import (
        simulate_aggregate_shocks,
        simulate_employment_panel,
    )

    cfg = KrusellSmithConfig()
    T, pop = 1100, 10_000
    model = KrusellSmithModel.from_config(cfg, jnp.float32)
    kz, ke = jax.random.split(jax.random.PRNGKey(0))
    z = simulate_aggregate_shocks(model.pz, kz, T=T)
    eps = simulate_employment_panel(z, model.eps_trans, cfg.shocks.u_good,
                                    cfg.shocks.u_bad, ke, T=T, population=pop)
    k_opt = 0.9 * np.broadcast_to(
        np.asarray(model.k_grid, np.float64)[None, None, :],
        (4, cfg.K_size, cfg.k_size)).copy()
    return (k_opt, np.asarray(model.k_grid, np.float64),
            np.asarray(model.K_grid, np.float64),
            np.asarray(z), np.asarray(eps), T, pop)


def _numpy_ks_panel_seconds(k_opt_np, k_grid_np, K_grid_np, z_np, eps_np,
                            T: int, pop: int, T_base: int) -> float:
    """One timed NumPy panel simulation (the reference's per-t step,
    Krusell_Smith_VFI.m:222-248, vectorized with np.interp per state),
    run for T_base-1 steps and scaled to the full T-1."""
    k_pop = np.full(pop, K_grid_np[0])
    t0 = time.perf_counter()
    for t_i in range(T_base - 1):
        K_t = k_pop.mean()
        iK = np.clip(np.searchsorted(K_grid_np, K_t) - 1, 0, len(K_grid_np) - 2)
        tK = (K_t - K_grid_np[iK]) / (K_grid_np[iK + 1] - K_grid_np[iK])
        pol = k_opt_np[:, iK, :] * (1 - tK) + k_opt_np[:, iK + 1, :] * tK
        s_t = z_np[t_i] % 2 + 2 * eps_np[t_i]
        new_k = np.empty(pop)
        for s_i in range(4):
            m = s_t == s_i
            if m.any():
                new_k[m] = np.interp(k_pop[m], k_grid_np, pol[s_i])
        k_pop = new_k
    return (time.perf_counter() - t0) * (T - 1) / (T_base - 1)


def _measure_numpy_ks_panel(n_runs: int) -> list[float]:
    inputs = _numpy_ks_panel_inputs()
    return sorted(_numpy_ks_panel_seconds(*inputs, T_base=300)
                  for _ in range(n_runs))


# Every frozen-denominator entry in BASELINE.json: name -> (measure fn
# returning n_runs sorted seconds, workload parameters the measurement
# embodies). Adding a metric's denominator here gives it the frozen/live
# policy and --refresh-baseline coverage automatically. The workload dict is
# written into the frozen entry and compared on load: a frozen number
# measured under different workload parameters (e.g. a changed tol or
# T_base) must not silently keep feeding vs_baseline.
_DENOMINATORS = {
    "numpy_vfi_400": (_measure_numpy_vfi400,
                      {"grid": 400, "states": 7, "tol": 1e-5,
                       "max_iter": 1000}),
    "numpy_ks_panel_10000x1100": (_measure_numpy_ks_panel,
                                  {"population": 10_000, "T": 1100,
                                   "T_base": 300}),
}


def frozen_denominator(name: str, n_live: int = 3) -> dict:
    """A NumPy denominator robust to CPU load (VERDICT round 2 #2): prefer
    the FROZEN median recorded in BASELINE.json (python bench.py
    --refresh-baseline, idle box, fingerprinted), so a contended denominator
    draw cannot move vs_baseline; always ALSO measure live (median-of-n,
    spread recorded) so the artifact shows this run's actual machine state
    next to the frozen constant."""
    measure, workload = _DENOMINATORS[name]
    live = measure(n_live)
    med = live[len(live) // 2]
    out = {
        "baseline_live_seconds": round(med, 4),
        "baseline_live_spread": [round(live[0], 4), round(live[-1], 4)],
    }
    frozen = None
    try:
        with open(_BASELINE_JSON) as f:
            frozen = json.load(f).get("frozen_denominators", {}).get(name)
    except (OSError, json.JSONDecodeError):
        pass
    if (frozen and frozen.get("fingerprint") == _machine_fingerprint()
            and frozen.get("workload") == workload):
        out["seconds"] = float(frozen["median_seconds"])
        out["baseline_source"] = "frozen"
    elif frozen:
        out["seconds"] = med
        out["baseline_source"] = "live-median (frozen entry mismatch)"
    else:
        out["seconds"] = med
        out["baseline_source"] = "live-median (no frozen baseline)"
    return out


def numpy_vfi400_denominator() -> dict:
    return frozen_denominator("numpy_vfi_400")


def refresh_frozen_baseline(n_runs: int = 7) -> dict:
    """Measure every registered NumPy denominator n_runs times and freeze
    the medians (+ spread + machine fingerprint + date) into BASELINE.json.
    Run on an IDLE box: a loaded denominator would inflate every future
    vs_baseline."""
    entries = {}
    for name, (measure, workload) in _DENOMINATORS.items():
        times = measure(n_runs)
        entries[name] = {
            "median_seconds": round(times[len(times) // 2], 4),
            "spread_seconds": [round(times[0], 4), round(times[-1], 4)],
            "n_runs": n_runs,
            "workload": workload,
            "fingerprint": _machine_fingerprint(),
            "frozen_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
    with open(_BASELINE_JSON) as f:
        data = json.load(f)
    data.setdefault("frozen_denominators", {}).update(entries)
    with open(_BASELINE_JSON, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    return entries


def bench_aiyagari_vfi(grid_size: int, quick: bool) -> dict:
    import jax
    import jax.numpy as jnp
    from functools import partial

    from aiyagari_tpu.models.aiyagari import aiyagari_preset
    from aiyagari_tpu.solvers import numpy_backend as nb
    from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi
    from aiyagari_tpu.utils.firm import wage_from_r

    r = 0.04
    tol, max_iter = 1e-5, 1000

    # On-accelerator dtype: f32 on TPU (native), f64 elsewhere. The f32 path
    # uses the same absolute tolerance; convergence is verified below.
    platform = jax.default_backend()
    dtype = jnp.float32 if platform == "tpu" else jnp.float64
    model = aiyagari_preset(grid_size=grid_size, dtype=dtype)
    prefs = model.preferences
    w = float(wage_from_r(r, model.config.technology.alpha, model.config.technology.delta))
    v0 = jnp.zeros((model.P.shape[0], grid_size), dtype)

    # Amortized timing: the dev/bench TPU here is reached over an experimental
    # remote transport whose per-call round trip (~100 ms measured) dwarfs the
    # device time of a reference-scale solve (~3 ms). Chain `reps` full
    # cold-start solves inside ONE jitted program — each solve's v_init
    # data-depends on the previous solve's result (v0 + 0*prev, which XLA
    # cannot fold away: 0*NaN != 0), so all `reps` fixed points execute
    # sequentially on device — fetch once, and report wall-clock / reps.
    # Every solve runs from v=0 to the reference's criterion max|dv| < 1e-5
    # (Aiyagari_VFI.m:49-50,85). Solver config is platform-adaptive — measured
    # on this image: on TPU the plain dense sweep (reference-faithful operator
    # sequence, same as the NumPy baseline) is fastest (~3 ms/solve; Howard's
    # policy-gather sweeps cost more than they save); on CPU 50 Howard
    # policy-evaluation sweeps per improvement are a 14x win (0.08 s vs
    # 1.1 s). Both reach the identical fixed point (pinned by test_solvers).
    howard = 0 if platform == "tpu" else 50

    @partial(jax.jit, static_argnames=("reps",))
    def chained(v_init, *, reps):
        def one(carry, _):
            sol = solve_aiyagari_vfi(
                v_init + 0.0 * carry, model.a_grid, model.s, model.P, r, w,
                sigma=prefs.sigma, beta=prefs.beta, tol=tol, max_iter=max_iter,
                howard_steps=howard)
            return sol.distance.astype(v_init.dtype), (sol.iterations, sol.distance)
        carry, (its, dists) = jax.lax.scan(
            one, jnp.array(0.0, v_init.dtype), None, length=reps)
        return its[-1], dists[-1]

    reps = (10 if quick else 50) if platform == "tpu" else (2 if quick else 5)
    out = chained(v0, reps=reps)
    float(out[1])                     # compile + converge warmup, fenced
    times = []
    for _ in range(1 if quick else 3):
        t0 = time.perf_counter()
        out = chained(v0, reps=reps)
        float(out[1])                 # scalar transfer = timing fence
        times.append(time.perf_counter() - t0)
    t_jax = min(times) / reps
    iters_jax = int(out[0])
    assert float(out[1]) < tol, "accelerated path failed to converge"

    # Baseline: vectorized NumPy, f64. At the reference scale (400) the
    # denominator comes from the frozen/fingerprinted record so CPU load
    # cannot move vs_baseline; other grids measure live (best-of-3).
    if grid_size == 400:
        den = numpy_vfi400_denominator()
        t_np = den.pop("seconds")
    else:
        a = np.asarray(model.a_grid, np.float64)
        s = np.asarray(model.s, np.float64)
        P = np.asarray(model.P, np.float64)
        prefs = model.preferences
        w = wage_from_r(r, model.config.technology.alpha, model.config.technology.delta)
        t_np = np.inf
        for _ in range(3):
            t0 = time.perf_counter()
            *_, iters_np = nb.vfi_numpy(np.zeros((len(s), len(a))), a, s, P, r, w,
                                        sigma=prefs.sigma, beta=prefs.beta, tol=tol,
                                        max_iter=max_iter)
            t_np = min(t_np, time.perf_counter() - t0)
        den = {"baseline_source": "live-best-of-3 (non-reference grid)"}

    from aiyagari_tpu.diagnostics.roofline import utilization, vfi_sweep_cost

    cost = iters_jax * vfi_sweep_cost(int(model.P.shape[0]), grid_size,
                                      jnp.dtype(dtype).itemsize)
    return {
        "metric": f"aiyagari_vfi_wallclock_grid{grid_size}",
        "value": round(t_jax, 4),
        "unit": "seconds",
        "vs_baseline": round(t_np / t_jax, 2),
        "baseline_seconds": round(t_np, 4),
        **den,
        **utilization(t_jax, cost, platform),
    }


def _available_memory_bytes() -> int | None:
    """Host MemAvailable in bytes, or None where /proc is unreadable."""
    try:
        with open("/proc/meminfo") as f:
            for ln in f:
                if ln.startswith("MemAvailable"):
                    return int(ln.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return None


def _size_scale_grid(grid_scale: int, platform: str, itemsize: int) -> tuple[int, dict]:
    """Shrink the north-star grid to what THIS host can hold (ISSUE 2
    satellite: the round-5 battery died mid-run with a 208 GB
    RESOURCE_EXHAUSTED inside bench_scale's solve on the CPU fallback,
    taking every later metric with it). The dominant allocation on the
    XLA:CPU route is the windowed power-grid inversion's materialized
    compare buffer — measured 208.9e9 bytes at na=400k f64, and the window
    width scales with na, so bytes ~= 7 * na * (na/43) * itemsize (which
    reproduces the measurement). TPU executions fuse the window loop into
    the kernel and never materialize that buffer, so sizing applies
    off-TPU only; halve until the estimate fits in half of MemAvailable,
    flooring at the --quick cap. The artifact records both the requested
    and the sized grid so the workload change is explicit, and the
    per-metric OOM guard in main() remains the backstop for allocations
    this model does not see."""
    if platform == "tpu":
        return grid_scale, {}
    fields: dict = {}
    sized = grid_scale
    # Throughput cap first: a CPU-fallback session is a degraded-but-
    # recordable run (the north-star number is a TPU claim), and the
    # windowed sweep costs ~2.3 ms per 1k gridpoints per solve on this
    # class of host (measured: 22.5 s at 10k, 45.6 s at 20k) — the
    # requested 400k would be ~15 min PER SOLVE in a battery that runs
    # several, i.e. a guaranteed probe-timeout, which kills the later
    # metrics exactly like the OOM did.
    cpu_cap = 12_000
    if sized > cpu_cap:
        sized = cpu_cap
        fields = {"grid_requested": grid_scale, "grid_sized": sized,
                  "grid_sized_reason": "cpu-throughput"}
    avail = _available_memory_bytes()
    if avail is not None:
        est = lambda na: 7.0 * na * (na / 43.0) * itemsize
        budget = 0.5 * avail
        while sized > 4_000 and est(sized) > budget:
            sized //= 2
        if "grid_sized" in fields and sized != fields["grid_sized"]:
            fields.update(grid_sized=sized, grid_sized_reason="memory",
                          mem_available_gb=round(avail / 1e9, 1),
                          est_peak_gb_at_requested=
                          round(est(grid_scale) / 1e9, 1))
        elif not fields and sized != grid_scale:
            fields = {"grid_requested": grid_scale, "grid_sized": sized,
                      "grid_sized_reason": "memory",
                      "mem_available_gb": round(avail / 1e9, 1),
                      "est_peak_gb_at_requested":
                          round(est(grid_scale) / 1e9, 1)}
    return sized, fields


def bench_scale(grid_scale: int, quick: bool, scale_solver: str = "vfi",
                noise_floor_ulp: float | None = None,
                pallas_inversion: bool = False,
                accel: bool = False) -> dict:
    """The BASELINE.json north star: a 1000x-finer asset grid than the
    reference's 400 points at equal wall-clock. Solves the household problem
    on `grid_scale` points with an O(na)-per-sweep solver — the
    continuous-choice VFI (golden section over a', closed-form power-grid
    locator) or EGM — and reports its wall-clock; vs_baseline =
    numpy-VFI-at-400 seconds / this, so >= 1.0 means the 1000x target is met
    or beaten."""
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu.models.aiyagari import aiyagari_preset
    from aiyagari_tpu.utils.firm import wage_from_r

    if quick:
        grid_scale = min(grid_scale, 40_000)   # 100x grid: fast smoke run
    r, tol, max_iter = 0.04, 1e-5, 2000
    platform = jax.default_backend()
    dtype = jnp.float32 if platform == "tpu" else jnp.float64
    grid_scale, sized_fields = _size_scale_grid(
        grid_scale, platform, jnp.dtype(dtype).itemsize)
    model = aiyagari_preset(grid_size=grid_scale, dtype=dtype)
    w = float(wage_from_r(r, model.config.technology.alpha, model.config.technology.delta))

    if noise_floor_ulp is None:
        # f32's sup-norm noise band at fine grids sits at ~6-16 ulp of
        # max|C| (measured at 400k, BENCHMARKS.md); 24 clears it. In f64 the
        # floor is ~1e-14 — never engaged — so the flag is harmless there.
        noise_floor_ulp = 24.0 if platform == "tpu" else 0.0

    if scale_solver == "egm":
        # Grid-sequenced: coarse-grid stages cost microseconds and leave the
        # final grid only ~10 sweeps from its fixed point (vs ~290 cold).
        # --accel additionally runs every ladder stage under safeguarded
        # Anderson mixing (ops/accel.py, shipped defaults) — same fixed
        # point, fewer sweeps per stage.
        from aiyagari_tpu.config import AccelConfig
        from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_multiscale

        accel_cfg = AccelConfig() if accel else None

        def run():
            return solve_aiyagari_egm_multiscale(
                model.a_grid, model.s, model.P, r, w, model.amin,
                sigma=model.preferences.sigma, beta=model.preferences.beta,
                tol=tol, max_iter=max_iter,
                grid_power=model.config.grid.power,
                noise_floor_ulp=noise_floor_ulp,
                egm_kernel="pallas_inverse" if pallas_inversion else "xla",
                accel=accel_cfg,
            )
    else:
        out = _bench_scale_vfi(model, grid_scale, quick, r, w, tol, max_iter,
                               noise_floor_ulp, platform, dtype)
        out.update(sized_fields)
        return out

    sol = run()
    float(sol.distance)   # compile+converge warmup, fenced
    # Best-of-3 like the CPU denominator: the noise-floor stop makes the
    # solve short enough (~0.5 s at 400k) that per-run device/transport
    # jitter is a visible fraction of it.
    t_scale = np.inf
    for _ in range(1 if quick else 3):
        t0 = time.perf_counter()
        sol = run()
        dist = float(sol.distance)
        t_scale = min(t_scale, time.perf_counter() - t0)
    # A non-converged (or NaN) solve must fail loudly, not be recorded as a
    # fast time: NaN >= tol is False, so the fixed point exits immediately.
    # The acceptance bound is the stopping rule the solver actually applied:
    # tol, or the measured f32 ulp-noise floor when that is engaged
    # (EGMSolution.tol_effective; solvers/egm.py noise_floor_ulp docstring).
    tol_ok = max(tol, float(getattr(sol, "tol_effective", 0.0)))
    assert dist < tol_ok, f"scale solve failed to converge: distance {dist}"

    # Baseline: NumPy discrete VFI at the reference's 400-point scale —
    # frozen/fingerprinted denominator (numpy_vfi400_denominator), with this
    # run's live median + spread recorded alongside so the met/unmet call is
    # reproducible (VERDICT round 2 #2).
    den = numpy_vfi400_denominator()
    t_np = den.pop("seconds")

    # Companion strict-tolerance number: when the f32 noise-floor stopping
    # rule is engaged, the headline value stops at tol_effective =
    # max(tol, 24 ulp of max|C|) while the NumPy denominator ran strict
    # 1e-5 (at 400 points, where the band never engages). Time one strict
    # solve too so the comparison's asymmetry is IN the artifact, not only
    # in BENCHMARKS.md prose (the f64 yardstick there shows the floored
    # policy is 4.4x CLOSER to the true fixed point than the strict-f32
    # one — strictness at the band is sweeps, not accuracy).
    strict = {}
    if scale_solver == "egm" and noise_floor_ulp > 0.0 and not quick:
        from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_multiscale

        def run_strict():
            # Same kernel as the headline value (incl. the Pallas routing):
            # the strict-vs-floored delta must isolate the stopping rule,
            # not conflate it with a kernel choice.
            return solve_aiyagari_egm_multiscale(
                model.a_grid, model.s, model.P, r, w, model.amin,
                sigma=model.preferences.sigma, beta=model.preferences.beta,
                tol=tol, max_iter=max_iter,
                grid_power=model.config.grid.power,
                noise_floor_ulp=0.0,
                egm_kernel="pallas_inverse" if pallas_inversion else "xla",
            )

        sols = run_strict()
        float(sols.distance)
        t_strict = np.inf
        for _ in range(2):
            t0 = time.perf_counter()
            sols = run_strict()
            d_s = float(sols.distance)
            t_strict = min(t_strict, time.perf_counter() - t0)
        strict = {
            "value_strict_tol": round(t_strict, 4),
            "strict_converged": bool(d_s < tol),
            "tol_effective": float(getattr(sol, "tol_effective", tol)),
        }

    if scale_solver == "egm" and not quick:
        # Accuracy IN the artifact, not just speed: off-grid Euler residuals
        # (utils/accuracy.py, Judd's E_EE, log10 consumption units) of the
        # shipped solution, over unconstrained midpoints — the noise-floor
        # stop's effect is then visible as solution accuracy, which is the
        # quantity the f64 yardstick (BENCHMARKS.md) shows it preserves.
        from aiyagari_tpu.utils.accuracy import euler_equation_errors

        errs, mask = euler_equation_errors(
            sol.policy_c, sol.policy_k, model.a_grid, model.s, model.P,
            r, w, model.amin, sigma=model.preferences.sigma,
            beta=model.preferences.beta)
        vals = np.asarray(errs)[np.asarray(mask)]
        strict["euler_log10_median"] = round(float(np.median(vals)), 2)
        strict["euler_log10_p99"] = round(float(np.percentile(vals, 99)), 2)

    # Utilization model: final-stage sweeps only (the coarse ladder stages
    # are ~7% of wall-clock at 400k — BENCHMARKS.md stage timings), over the
    # whole measured time, so the fractions are conservative. Modeled for the
    # EGM solver only: the continuous VFI's golden-section/index-search
    # rounds have no analytic cost model here (vfi_sweep_cost describes the
    # dense precomputed-U Bellman sweep, which this path never runs — using
    # it would claim physically impossible byte counts at 400k).
    from aiyagari_tpu.diagnostics.roofline import egm_sweep_cost, utilization

    sweeps = int(sol.iterations)
    N, itemsize = int(model.P.shape[0]), jnp.dtype(dtype).itemsize
    util = utilization(t_scale, sweeps * egm_sweep_cost(N, grid_scale, itemsize),
                       platform)
    return {
        "metric": f"aiyagari_{scale_solver}_scale_grid{grid_scale}_wallclock",
        "value": round(t_scale, 4),
        "unit": "seconds",
        "vs_baseline": round(t_np / t_scale, 2),
        "baseline_seconds": round(t_np, 4),
        "accel": bool(accel),
        "final_stage_sweeps": sweeps,
        **den,
        **strict,
        **util,
        **sized_fields,
    }


def _bench_scale_vfi(model, grid_scale: int, quick: bool, r: float, w: float,
                     tol: float, max_iter: int, noise_floor_ulp: float,
                     platform: str, dtype) -> dict:
    """The north-star scale measured with the solver BASELINE.json names
    (VFI), using the round-5 cross-method warm start: the converged EGM
    policy (O(na) per sweep, ~0.2 s at 400k) seeds the slab VFI, whose
    improvement loop then only VERIFIES the policy (1-2 rounds) instead of
    walking to it — the headline value is the full recipe wall (EGM leg +
    warm VFI leg). The cold solve is timed alongside, and the row carries
    convergence, iteration, accuracy, and roofline-utilization fields
    (VERDICT round 4 weak #1/#2: the bare-wall-clock row)."""
    import jax.numpy as jnp

    from aiyagari_tpu.diagnostics.roofline import utilization, vfi_slab_cost
    from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_multiscale
    from aiyagari_tpu.solvers.vfi import (
        solve_aiyagari_vfi_egm_warmstart,
        solve_aiyagari_vfi_multiscale,
    )
    from aiyagari_tpu.utils.accuracy import euler_equation_errors

    # noise_floor_ulp: same rationale as rounds 3-4 (BENCHMARKS.md) — the
    # value criterion's f32 rounding band at 400k (~24 ulp of max|v|)
    # makes the strict 1e-5 unreachable there. The warm leg runs the
    # solver's tuned defaults (3-stage ladder, hs=15); the cold reference
    # pins the round-4-comparable hs=25 / 4-stage configuration.
    kw = dict(sigma=model.preferences.sigma, beta=model.preferences.beta,
              tol=tol, max_iter=max_iter, grid_power=model.config.grid.power,
              noise_floor_ulp=noise_floor_ulp)

    def run_egm():
        return solve_aiyagari_egm_multiscale(
            model.a_grid, model.s, model.P, r, w, model.amin, **kw)

    sol_egm = run_egm()
    float(sol_egm.distance)   # compile + warmup, fenced
    t_egm = np.inf
    for _ in range(1 if quick else 2):
        t0 = time.perf_counter()
        sol_egm = run_egm()
        float(sol_egm.distance)
        t_egm = min(t_egm, time.perf_counter() - t0)

    def run_warm():
        # Tuned defaults (3-stage ladder, howard_steps=15 — the solver's
        # own measured-best recipe; the cold reference keeps the
        # round-4-comparable hs=25 / 4-stage configuration).
        return solve_aiyagari_vfi_egm_warmstart(
            model.a_grid, model.s, model.P, r, w, model.amin,
            egm_solution=sol_egm, **kw)

    warm = run_warm()
    float(warm.distance)
    t_warm = np.inf
    for _ in range(1 if quick else 3):
        t0 = time.perf_counter()
        warm = run_warm()
        d_w = float(warm.distance)
        t_warm = min(t_warm, time.perf_counter() - t0)
    tol_eff = max(tol, float(warm.tol_effective))
    assert d_w < tol_eff, f"warm VFI failed to converge: distance {d_w}"

    # Cold reference: one timed run (it is ~10x the warm wall; best-of-N
    # would double the battery for a comparison field).
    def run_cold():
        # Every round-4-comparable knob pinned EXPLICITLY (not inherited
        # from multiscale defaults, which a future tuning could move the
        # way this round moved the warm wrapper's): hs=25, 4-stage ladder.
        return solve_aiyagari_vfi_multiscale(
            model.a_grid, model.s, model.P, r, w, model.amin,
            howard_steps=25, coarsest=400, refine_factor=10, **kw)

    cold = run_cold()
    float(cold.distance)
    t0 = time.perf_counter()
    cold = run_cold()
    d_c = float(cold.distance)
    t_cold = time.perf_counter() - t0

    # Accuracy IN the artifact (VERDICT round 4 weak #1): off-grid Euler
    # residuals of the shipped warm solution, plus its sup-gap to the EGM
    # policy it verified (the EGM row's own euler/f64 pedigree then chains).
    errs, mask = euler_equation_errors(
        warm.policy_c, warm.policy_k, model.a_grid, model.s, model.P,
        r, w, model.amin, sigma=model.preferences.sigma,
        beta=model.preferences.beta)
    vals = np.asarray(errs)[np.asarray(mask)]
    gap = float(jnp.max(jnp.abs(warm.policy_k - sol_egm.policy_k)))

    den = numpy_vfi400_denominator()
    t_np = den.pop("seconds")
    t_total = t_egm + t_warm

    # Roofline: the slab-path cost model (diagnostics/roofline.vfi_slab_cost)
    # over the VFI leg's wall, with the final-stage round/sweep counts the
    # solver itself reports — no more null utilization fields.
    N, itemsize = int(model.P.shape[0]), jnp.dtype(dtype).itemsize
    cost = vfi_slab_cost(N, grid_scale, itemsize,
                         improve_rounds=max(int(warm.iterations), 1),
                         eval_sweeps=int(warm.eval_sweeps))
    return {
        # Renamed from aiyagari_vfi_scale_grid{N}_wallclock when the
        # measured workload became the EGM-warm-start recipe (round 5): the
        # old name's round-over-round comparability would silently break.
        # `recipe` keys the workload explicitly for artifact consumers;
        # cold_vfi_seconds remains the first-class cold-solve metric below.
        "metric": f"aiyagari_vfi_scale_grid{grid_scale}_warmstart_wallclock",
        "recipe": "egm_warmstart",
        "value": round(t_total, 4),
        "unit": "seconds",
        "vs_baseline": round(t_np / t_total, 2),
        "baseline_seconds": round(t_np, 4),
        **den,
        "egm_warmstart_seconds": round(t_egm, 4),
        "warm_vfi_seconds": round(t_warm, 4),
        "cold_vfi_seconds": round(t_cold, 4),
        "converged": bool(d_w < tol_eff),
        "tol_effective": tol_eff,
        "improve_rounds_warm": int(warm.iterations),
        "eval_sweeps_warm": int(warm.eval_sweeps),
        "improve_rounds_cold": int(cold.iterations),
        "eval_sweeps_cold": int(cold.eval_sweeps),
        "cold_converged": bool(d_c < max(tol, float(cold.tol_effective))),
        "policy_gap_vs_egm": round(gap, 6),
        "euler_log10_median": round(float(np.median(vals)), 2),
        "euler_log10_p99": round(float(np.percentile(vals, 99)), 2),
        **utilization(t_warm, cost, platform),
    }


def bench_ge_batched(quick: bool, grid_size: int = 400, batch: int = 8) -> dict:
    """Serial-vs-batched general-equilibrium wall-clock (the batched-GE
    tentpole, equilibrium/batched.py): solve the SAME economy to the same
    |K_s - K_d| < tol root with (a) the reference's serial bisection — one
    household solve + aggregation per candidate rate — and (b) the
    parallel-bracket solver — `batch` candidates per device round through
    one vmapped excess-demand kernel. vs_baseline = serial/batched wall.
    The structural win is the DEVICE-ROUND count (each serial iteration is
    ~2 sequential device programs + fetches; each batched round is 1), which
    is what hides launch/transport latency on an accelerator — both counts
    are in the artifact. EGM household solves (continuous policies, so the
    gap criterion can actually fire) with the deterministic histogram
    closure; eq.tol=1e-3 sits above the inner solver's ~1e-4 supply noise."""
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu.config import EquilibriumConfig, SolverConfig
    from aiyagari_tpu.equilibrium.batched import solve_equilibrium_batched
    from aiyagari_tpu.equilibrium.bisection import solve_equilibrium_distribution
    from aiyagari_tpu.models.aiyagari import aiyagari_preset

    if quick:
        grid_size = min(grid_size, 100)
    platform = jax.default_backend()
    dtype = jnp.float32 if platform == "tpu" else jnp.float64
    model = aiyagari_preset(grid_size=grid_size, dtype=dtype)
    sv = SolverConfig(method="egm")
    eq_tol = 1e-3
    ser_eq = EquilibriumConfig(max_iter=25, tol=eq_tol)
    bat_eq = EquilibriumConfig(batch=batch, max_iter=8, tol=eq_tol)

    def run_serial():
        return solve_equilibrium_distribution(model, solver=sv, eq=ser_eq)

    def run_batched():
        return solve_equilibrium_batched(model, solver=sv, eq=bat_eq)

    run_serial()                     # compile warmup (both loops fetch
    run_batched()                    # scalars internally — self-fencing)
    t0 = time.perf_counter()
    ser = run_serial()
    t_serial = time.perf_counter() - t0
    t0 = time.perf_counter()
    bat = run_batched()
    t_batched = time.perf_counter() - t0

    return {
        "metric": f"aiyagari_ge_batched_grid{grid_size}",
        "value": round(t_batched, 4),
        "unit": "seconds",
        "vs_baseline": round(t_serial / t_batched, 2),
        "baseline_seconds": round(t_serial, 4),
        "baseline_source": "serial bisection, same economy/tol (in-process)",
        "batch": batch,
        "serial_iterations": int(ser.iterations),
        "batched_rounds": int(bat.iterations),
        # Sequential device programs each schedule executed: the serial loop
        # launches (household solve + distribution) per iteration; a batched
        # round is ONE fused program.
        "device_rounds_serial": int(ser.iterations) * 2,
        "device_rounds_batched": int(bat.iterations),
        "r_serial": round(float(ser.r), 8),
        "r_batched": round(float(bat.r), 8),
        "r_agreement": round(abs(float(ser.r) - float(bat.r)), 10),
        "serial_converged": bool(ser.converged),
        "batched_converged": bool(bat.converged),
    }


def bench_ge_fused(quick: bool, grid_size: int = 100, batch: int = 8) -> dict:
    """One-program equilibrium (ISSUE 18 tentpole, equilibrium/fused.py):
    the SAME bisection root solved with (a) the host outer loop — one
    dispatch + fetch per candidate rate (equilibrium/bisection.py) — and
    (b) the fused device loop — the whole bracket search inside one
    compiled lax.while_loop, ONE dispatch and ONE device_get per
    equilibrium. Three gated claims, one frozen record
    (BENCH_r17_ge_fused.json, gated by tests/test_bench_ci.py):

      wall_ratio_device_over_host <= 0.8 — the fused loop must beat the
        host loop by erasing per-iteration dispatch/fetch latency (warm
        walls, interleaved min-of-reps: the ratio discipline of
        bench_precision's timed_pair);
      r_agreement <= 1e-10 — both loops run the same bracket arithmetic,
        so the equilibrium rate must match to round-off, not just to tol;
      donation — the donate_argnums build's XLA peak-memory proxy
        (argument + output + temp - alias bytes, memory_analysis()) must
        sit STRICTLY below the undonated build's, and the donated warm
        buffer must come back is_deleted() (the aliasing actually
        happened; a silently-ignored donation shows up as equality).

    The batched leg times the vmapped candidate round inside the same
    program (solve_equilibrium_fused_batched) for the round-count story;
    it shares the record but is not ratio-gated (B lanes of household
    work per round trade wall for rounds by design)."""
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu.config import EquilibriumConfig, SolverConfig
    from aiyagari_tpu.equilibrium.bisection import solve_equilibrium_distribution
    from aiyagari_tpu.equilibrium.fused import (
        fused_ge_operands,
        fused_ge_program,
        solve_equilibrium_fused,
        solve_equilibrium_fused_batched,
    )
    from aiyagari_tpu.models.aiyagari import aiyagari_preset

    if quick:
        grid_size = min(grid_size, 100)
    platform = jax.default_backend()
    dtype = jnp.float32 if platform == "tpu" else jnp.float64
    model = aiyagari_preset(grid_size=grid_size, dtype=dtype)
    sv = SolverConfig(method="egm")
    eq_tol = 1e-3
    eq = EquilibriumConfig(max_iter=30, tol=eq_tol)
    bat_eq = EquilibriumConfig(batch=batch, max_iter=10, tol=eq_tol)

    def run_host():
        return solve_equilibrium_distribution(model, solver=sv, eq=eq)

    def run_device():
        return solve_equilibrium_fused(model, solver=sv, eq=eq)

    def run_batched():
        return solve_equilibrium_fused_batched(model, solver=sv, eq=bat_eq)

    # Warm EVERY path before timing: compiles, route caches, and the host
    # loop's per-iteration program cache. Both loops fetch their scalars
    # internally (one device_get for the fused paths) — self-fencing.
    host, dev, bat = run_host(), run_device(), run_batched()
    reps = 2 if quick else 4
    best = [np.inf, np.inf, np.inf]
    for _ in range(reps):
        # Interleaved min-of-reps (bench_precision's timed_pair rationale):
        # a RATIO gate needs both sides sampled under the same host drift.
        for i, fn in enumerate((run_host, run_device, run_batched)):
            t0 = time.perf_counter()
            fn()
            best[i] = min(best[i], time.perf_counter() - t0)
    t_host, t_dev, t_bat = best

    # Donation accounting: XLA's own memory analysis of the two builds of
    # the IDENTICAL program. The proxy counts every buffer class the run
    # must hold minus what aliasing reuses — the number donate_argnums
    # exists to shrink.
    def memory_of(donate: bool) -> dict:
        fn = fused_ge_program(model, solver=sv, eq=eq, donate=donate)
        mem = fn.lower(*fused_ge_operands(model, eq, solver=sv)).compile(
        ).memory_analysis()
        arg, out_b, tmp, alias = (
            int(mem.argument_size_in_bytes), int(mem.output_size_in_bytes),
            int(mem.temp_size_in_bytes), int(mem.alias_size_in_bytes))
        return {"argument_bytes": arg, "output_bytes": out_b,
                "temp_bytes": tmp, "alias_bytes": alias,
                "peak_proxy_bytes": arg + out_b + tmp - alias}

    mem_donated, mem_undonated = memory_of(True), memory_of(False)
    ops = fused_ge_operands(model, eq, solver=sv)
    warm_buf = ops[3]
    jax.block_until_ready(
        fused_ge_program(model, solver=sv, eq=eq, donate=True)(*ops)["sol"])
    donated_input_deleted = bool(warm_buf.is_deleted())

    # Roofline price of the measured device solve: one fused round at the
    # run's MEAN inner-iteration counts (diagnostics/roofline.py), times
    # the round count — the bench multiplies because rounds-per-solve is
    # data-dependent (ge_fused_round_cost docstring).
    from aiyagari_tpu.diagnostics.roofline import (
        dtype_itemsize,
        ge_fused_round_cost,
    )

    N, na = int(model.P.shape[0]), int(model.a_grid.shape[0])
    mean_si = float(np.mean([r["solver_iterations"]
                             for r in dev.per_iteration]) or 1.0)
    mean_di = float(np.mean([r["distribution_iterations"]
                             for r in dev.per_iteration]) or 1.0)
    cost = int(dev.iterations) * ge_fused_round_cost(
        N, na, dtype_itemsize(dtype), policy_sweeps=max(mean_si, 1.0),
        dist_sweeps=max(mean_di, 1.0))

    record = {
        "metric": f"aiyagari_ge_fused_grid{grid_size}",
        "value": round(t_dev, 4),
        "unit": "seconds",
        "vs_baseline": round(t_host / t_dev, 2),
        "wall_ratio_device_over_host": round(t_dev / t_host, 4),
        "baseline_seconds": round(t_host, 4),
        "baseline_source": "host outer loop, same economy/tol (in-process)",
        "batched_seconds": round(t_bat, 4),
        "batch": batch,
        "host_iterations": int(host.iterations),
        "device_rounds": int(dev.iterations),
        "batched_rounds": int(bat.iterations),
        # Sequential device programs the host must schedule: the host loop
        # launches (household solve + distribution) per iteration and
        # fetches between them; each fused path is ONE program + ONE get.
        "device_programs_host_loop": int(host.iterations) * 2,
        "device_programs_fused": 1,
        "r_host": round(float(host.r), 12),
        "r_device": round(float(dev.r), 12),
        "r_batched": round(float(bat.r), 12),
        "r_agreement": abs(float(host.r) - float(dev.r)),
        "r_agreement_batched": round(abs(float(host.r) - float(bat.r)), 10),
        "host_converged": bool(host.converged),
        "device_converged": bool(dev.converged),
        "batched_converged": bool(bat.converged),
        "memory_donated": mem_donated,
        "memory_undonated": mem_undonated,
        "donation_saves_bytes": (mem_undonated["peak_proxy_bytes"]
                                 - mem_donated["peak_proxy_bytes"]),
        "donated_input_deleted": donated_input_deleted,
        "modeled_solve": {"mxu_flops": cost.mxu_flops,
                          "vpu_ops": cost.vpu_ops,
                          "hbm_bytes": cost.hbm_bytes,
                          "mean_solver_iterations": round(mean_si, 2),
                          "mean_distribution_iterations": round(mean_di, 2)},
        "eq_tol": eq_tol,
        "platform": platform,
    }
    # EVERY run (the ci preset included) freezes the round-17 artifact —
    # the attribution/serve pattern: the ci battery IS the freeze.
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r17_ge_fused.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
    return record


def bench_sweep(quick: bool, grid_size: int = 200) -> dict:
    """Scenario-sweep throughput (dispatch.sweep): S independent economies
    (a beta x sigma grid around the reference calibration) solved to GE as
    ONE lockstep batched program — the scenarios/sec axis the north star
    names ("as many scenarios as you can imagine"). vs_baseline = solving
    the same scenarios one-at-a-time with the serial loop / sweep wall
    (skipped in --quick: it re-runs every scenario)."""
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu import sweep
    from aiyagari_tpu.config import (
        AiyagariConfig,
        EquilibriumConfig,
        SolverConfig,
    )

    import dataclasses

    from aiyagari_tpu.config import BackendConfig

    if quick:
        grid_size = min(grid_size, 80)
    platform = jax.default_backend()
    betas = [0.94, 0.95, 0.96]
    sigmas = [3.0, 4.0, 5.0]
    if quick:
        betas, sigmas = betas[:2], sigmas[:2]
    base = AiyagariConfig()
    base = dataclasses.replace(
        base, grid=dataclasses.replace(base.grid, n_points=grid_size))
    eq = EquilibriumConfig(max_iter=20, tol=1e-3)
    backend = BackendConfig(
        dtype="float32" if platform == "tpu" else "float64")

    res = sweep(base, method="egm", beta=betas, sigma=sigmas,
                equilibrium=eq, backend=backend)   # compile warmup
    res = sweep(base, method="egm", beta=betas, sigma=sigmas,
                equilibrium=eq, backend=backend)
    out = {
        "metric": "sweep_scenarios_per_sec",
        "value": round(res.scenarios_per_sec, 3),
        "unit": "scenarios/sec",
        "scenarios": res.scenarios,
        "grid": grid_size,
        "rounds": res.rounds,
        "converged": int(np.sum(np.asarray(res.converged))),
        "sweep_seconds": round(res.solve_seconds, 4),
    }
    if not quick:
        from aiyagari_tpu.equilibrium.bisection import (
            solve_equilibrium_distribution,
        )
        from aiyagari_tpu.models.aiyagari import AiyagariModel

        dtype = jnp.float32 if platform == "tpu" else jnp.float64
        t0 = time.perf_counter()
        for p in res.params:
            prefs = dataclasses.replace(base.preferences, **p)
            cfg_i = dataclasses.replace(base, preferences=prefs)
            m_i = AiyagariModel.from_config(cfg_i, dtype)
            solve_equilibrium_distribution(
                m_i, solver=SolverConfig(method="egm"), eq=eq)
        t_serial = time.perf_counter() - t0
        out["baseline_seconds"] = round(t_serial, 4)
        out["baseline_source"] = "one-at-a-time serial GE, same scenarios"
        out["vs_baseline"] = round(t_serial / res.solve_seconds, 2)
    else:
        out["vs_baseline"] = None
    return out


def bench_transition(quick: bool, grid_size: int = 200, T: int = 150) -> dict:
    """MIT-shock transition-path solver (transition/, the ISSUE 2 tentpole):
    wall-clock and round count of the Newton solve — the sequence-space-
    Jacobian update, each round ONE fused backward+forward device program —
    against the damped (Boppart-Krusell-Mitman) fixed point on the same
    shock/tolerance as its in-process baseline. The stationary anchor and
    the fake-news Jacobian build are timed separately (both are one-off,
    amortized over every shock studied on the same economy), and a lockstep
    scenario sweep (dispatch.sweep_transitions) records
    `sweep_transitions_per_sec` — the transition analogue of the GE sweep's
    scenarios/sec axis."""
    import jax

    import aiyagari_tpu as at

    if quick:
        grid_size, T = min(grid_size, 60), min(T, 40)
    platform = jax.default_backend()
    backend = at.BackendConfig(
        dtype="float32" if platform == "tpu" else "float64")
    cfg = at.AiyagariConfig(
        grid=at.GridSpecConfig(n_points=grid_size))
    shock = at.MITShock(param="tfp", size=0.01, rho=0.9)
    tol = 1e-5 if platform == "tpu" else 1e-7
    tc = at.TransitionConfig(T=T, tol=tol, method="newton", max_iter=20)

    t0 = time.perf_counter()
    cold = at.solve_transition(cfg, shock, transition=tc, backend=backend,
                               keep_policies=False)
    t_cold = time.perf_counter() - t0
    # Warm solve: ss + Jacobian amortized — the marginal cost per shock.
    t0 = time.perf_counter()
    res = at.solve_transition(cfg, shock, transition=tc, backend=backend,
                              ss=cold.ss, jacobian=cold.jacobian,
                              keep_policies=False)
    t_newton = time.perf_counter() - t0
    t0 = time.perf_counter()
    damped = at.solve_transition(
        cfg, shock, backend=backend, ss=cold.ss, keep_policies=False,
        transition=at.TransitionConfig(T=T, tol=tol, method="damped",
                                       max_iter=500, damping=0.5))
    t_damped = time.perf_counter() - t0

    # Lockstep scenario sweep: a size x persistence grid of TFP shocks plus
    # a discount-factor shock — the mixed-parameter batch the vmapped path
    # program exists for.
    shocks = [at.MITShock("tfp", sz, rh)
              for sz in (0.005, 0.01) for rh in (0.8, 0.9, 0.95)]
    shocks += [at.MITShock("beta", 0.002, 0.8), at.MITShock("sigma", 0.05, 0.8)]
    if quick:
        shocks = shocks[:4]
    sw = at.sweep_transitions(cfg, shocks, transition=tc, backend=backend,
                              ss=cold.ss, jacobian=cold.jacobian)

    return {
        "metric": f"transition_newton_T{T}_grid{grid_size}",
        "value": round(t_newton, 4),
        "unit": "seconds",
        "vs_baseline": round(t_damped / t_newton, 2),
        "baseline_seconds": round(t_damped, 4),
        "baseline_source": "damped (BKM) update, same shock/tol (in-process)",
        "newton_rounds": int(res.rounds),
        "damped_rounds": int(damped.rounds),
        "converged": bool(res.converged),
        "damped_converged": bool(damped.converged),
        "max_excess": float(res.max_excess_history[-1]),
        "cold_seconds": round(t_cold, 4),   # incl. ss anchor + Jacobian
        "sweep_transitions_per_sec": round(sw.transitions_per_sec, 3),
        "sweep_scenarios": sw.scenarios,
        "sweep_rounds": int(sw.rounds),
        "sweep_converged": int(np.sum(np.asarray(sw.converged))),
    }


def bench_transition_fused(quick: bool, grid_size: int = 40,
                           T: int = 24) -> dict:
    """One-program transitions (ISSUE 19 tentpole, transition/fused.py):
    the SAME MIT-shock Newton path solved with (a) the host round loop —
    one fused path-record program + ONE stacked device_get per round
    (transition/mit.py) — and (b) the fused device loop — backward scan,
    forward push, excess demand, and the Jacobian-inverse Newton step all
    inside one compiled lax.while_loop: ONE launch and ONE small
    device_get per solve. Three gated claims, one frozen record
    (BENCH_r18_transition_fused.json, gated by tests/test_bench_ci.py):

      wall_ratio_device_over_host <= 0.8 — the fused loop must beat the
        host loop by erasing per-round dispatch/fetch latency (warm
        walls, interleaved min-of-reps). The calibration is pinned at
        the dispatch-bound point (grid 40, T=24, ~4 Newton rounds):
        larger economies push both loops into the same compute-bound
        regime where the ratio drifts toward 1 by construction — the
        fused win is the LAUNCH count, and that is what this gate prices
        (measured under the ci virtual mesh: 0.60 at grid 40/T 24,
        0.73 at grid 60/T 40, 0.85 at grid 100/T 40);
      r_agreement <= 1e-10 — both loops apply the identical hoisted
        Jacobian-inverse matmul to the identical excess-demand curve, so
        the price path must match to round-off (measured ~1e-16);
      donation — the donate_argnums build's XLA peak-memory proxy
        (argument + output + temp - alias bytes, memory_analysis()) must
        sit STRICTLY below the undonated build's, and the donated r-path
        carry must come back is_deleted() (the aliasing happened; the
        loop-invariant anchor operands may stay alive — XLA's
        once-per-compile "not usable" warning — so the r0 carry is the
        gated buffer).

    The sweep leg times the vmapped lockstep round inside the same
    while_loop (solve_transitions_sweep_fused) against the host lockstep
    sweep for the scenarios/sec story; it shares the record but is not
    ratio-gated (the host sweep already amortizes its launches over S
    lanes)."""
    import jax
    import jax.numpy as jnp

    import aiyagari_tpu as at
    from aiyagari_tpu.transition.fused import (
        fused_transition_operands,
        fused_transition_program,
        solve_transition_fused,
        solve_transitions_sweep_fused,
    )
    from aiyagari_tpu.transition.mit import (
        solve_transition as host_solve,
        solve_transitions_sweep as host_sweep,
        stationary_anchor,
        transition_jacobian,
    )
    from aiyagari_tpu.models.aiyagari import aiyagari_preset

    platform = jax.default_backend()
    dtype = jnp.float32 if platform == "tpu" else jnp.float64
    model = aiyagari_preset(grid_size=grid_size, dtype=dtype)
    shock = at.MITShock(param="tfp", size=0.01, rho=0.9)
    tol = 1e-5 if platform == "tpu" else 1e-7
    tc = at.TransitionConfig(T=T, tol=tol, method="newton", max_iter=20)
    ss = stationary_anchor(model)
    jac = transition_jacobian(model, ss, T)
    shocks = [at.MITShock("tfp", sz, rh)
              for sz in (0.005, 0.01) for rh in (0.8, 0.9)]
    kw = dict(trans=tc, ss=ss, jacobian=jac, dtype=dtype)

    def run_host():
        return host_solve(model, shock, keep_policies=False, **kw)

    def run_device():
        return solve_transition_fused(model, shock, keep_policies=False,
                                      **kw)

    def run_host_sweep():
        return host_sweep(model, shocks, **kw)

    def run_dev_sweep():
        return solve_transitions_sweep_fused(model, shocks, **kw)

    # Warm EVERY path before timing: compiles and the anchor dtype
    # caches. Both loops fetch internally (the host loop ONE stacked get
    # per round, the fused loop one per solve) — self-fencing.
    host, dev = run_host(), run_device()
    hsw, dsw = run_host_sweep(), run_dev_sweep()
    reps = 3 if quick else 5
    best = [np.inf, np.inf, np.inf, np.inf]
    for _ in range(reps):
        # Interleaved min-of-reps (bench_precision's timed_pair
        # rationale): a RATIO gate needs both sides sampled under the
        # same host drift.
        for i, fn in enumerate((run_host, run_device, run_host_sweep,
                                run_dev_sweep)):
            t0 = time.perf_counter()
            fn()
            best[i] = min(best[i], time.perf_counter() - t0)
    t_host, t_dev, t_hsw, t_dsw = best

    # Donation accounting: XLA's own memory analysis of the two builds of
    # the IDENTICAL program (the bench_ge_fused proxy).
    jac_inv = np.linalg.inv(np.asarray(jac, np.float64))

    def memory_of(donate: bool) -> dict:
        fn = fused_transition_program(model, trans=tc, donate=donate)
        mem = fn.lower(*fused_transition_operands(
            model, shock, tc, ss=ss, jac_inv=jac_inv,
            dtype=dtype)).compile().memory_analysis()
        arg, out_b, tmp, alias = (
            int(mem.argument_size_in_bytes), int(mem.output_size_in_bytes),
            int(mem.temp_size_in_bytes), int(mem.alias_size_in_bytes))
        return {"argument_bytes": arg, "output_bytes": out_b,
                "temp_bytes": tmp, "alias_bytes": alias,
                "peak_proxy_bytes": arg + out_b + tmp - alias}

    mem_donated, mem_undonated = memory_of(True), memory_of(False)
    ops = fused_transition_operands(model, shock, tc, ss=ss,
                                    jac_inv=jac_inv, dtype=dtype)
    r0_buf = ops[0]
    jax.block_until_ready(
        fused_transition_program(model, trans=tc, donate=True)(*ops)["r"])
    donated_input_deleted = bool(r0_buf.is_deleted())

    # Roofline price of the measured device solve: one fused round —
    # T backward EGM sweeps + T push-forward sweeps + the Newton tail —
    # times the round count (transition_fused_round_cost docstring: the
    # bench multiplies because rounds-per-solve is data-dependent).
    from aiyagari_tpu.diagnostics.roofline import (
        dtype_itemsize,
        transition_fused_round_cost,
    )

    N, na = int(model.P.shape[0]), int(model.a_grid.shape[0])
    cost = int(dev.rounds) * transition_fused_round_cost(
        N, na, T, dtype_itemsize(dtype))

    record = {
        "metric": f"transition_fused_T{T}_grid{grid_size}",
        "value": round(t_dev, 4),
        "unit": "seconds",
        "grid": grid_size,
        "T": T,
        "vs_baseline": round(t_host / t_dev, 2),
        "wall_ratio_device_over_host": round(t_dev / t_host, 4),
        "baseline_seconds": round(t_host, 4),
        "baseline_source": "host round loop, same shock/tol (in-process)",
        "host_rounds": int(host.rounds),
        "device_rounds": int(dev.rounds),
        # Sequential device programs the host must schedule: the host
        # loop launches one fused path-record program + one stacked fetch
        # per round; the fused solve is ONE program + ONE small get.
        "device_programs_host_loop": int(host.rounds),
        "device_programs_fused": 1,
        "host_converged": bool(host.converged),
        "device_converged": bool(dev.converged),
        "r_agreement": float(np.max(np.abs(np.asarray(dev.r_path)
                                           - np.asarray(host.r_path)))),
        "max_excess": float(dev.max_excess_history[-1]),
        "sweep_seconds_host": round(t_hsw, 4),
        "sweep_seconds_fused": round(t_dsw, 4),
        "sweep_scenarios": int(dsw.scenarios),
        "sweep_rounds_host": int(hsw.rounds),
        "sweep_rounds_fused": int(dsw.rounds),
        "sweep_converged": int(np.sum(np.asarray(dsw.converged))),
        "sweep_r_agreement": float(np.max(np.abs(
            np.asarray(dsw.r_paths) - np.asarray(hsw.r_paths)))),
        "sweep_transitions_per_sec": round(float(dsw.scenarios) / t_dsw, 3),
        "memory_donated": mem_donated,
        "memory_undonated": mem_undonated,
        "donation_saves_bytes": (mem_undonated["peak_proxy_bytes"]
                                 - mem_donated["peak_proxy_bytes"]),
        "donated_input_deleted": donated_input_deleted,
        "modeled_solve": {"mxu_flops": cost.mxu_flops,
                          "vpu_ops": cost.vpu_ops,
                          "hbm_bytes": cost.hbm_bytes},
        "tol": tol,
        "platform": platform,
    }
    # EVERY run (the ci preset included) freezes the round-18 artifact —
    # the attribution/serve/ge_fused pattern: the ci battery IS the
    # freeze.
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r18_transition_fused.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
    return record


def bench_accel(quick: bool, grid_size: int = 400) -> dict:
    """Fixed-point acceleration telemetry (ISSUE 3): the same cold EGM
    household solve and Young stationary-distribution solve run PLAIN and
    ACCELERATED (safeguarded Anderson carry transformers, ops/accel.py),
    reporting per-solve ITERATION COUNTS next to the walls so the speedup is
    measured, not asserted. value = accelerated EGM+distribution wall;
    vs_baseline = plain wall / accelerated wall. The structural claim is the
    sweep-count pair — >=2x fewer EGM sweeps and >=3x fewer distribution
    sweeps at the default tolerances — which tests/test_bench_ci.py asserts
    (accelerated <= plain) on the tiny-grid ci battery, so acceleration
    regressions fail tier-1 rather than silently rotting."""
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu.config import AccelConfig
    from aiyagari_tpu.models.aiyagari import aiyagari_preset
    from aiyagari_tpu.sim.distribution import stationary_distribution
    from aiyagari_tpu.solvers.egm import (
        initial_consumption_guess,
        solve_aiyagari_egm,
    )
    from aiyagari_tpu.utils.firm import wage_from_r

    if quick:
        grid_size = min(grid_size, 100)
    r, tol, max_iter = 0.04, 1e-5, 2000
    platform = jax.default_backend()
    dtype = jnp.float32 if platform == "tpu" else jnp.float64
    model = aiyagari_preset(grid_size=grid_size, dtype=dtype)
    w = float(wage_from_r(r, model.config.technology.alpha,
                          model.config.technology.delta))
    C0 = initial_consumption_guess(model.a_grid, model.s, r, w)
    accel = AccelConfig()          # anderson, the shipped default knobs
    kw = dict(sigma=model.preferences.sigma, beta=model.preferences.beta,
              tol=tol, max_iter=max_iter)

    def egm_run(acc):
        return solve_aiyagari_egm(C0, model.a_grid, model.s, model.P, r, w,
                                  model.amin, accel=acc, **kw)

    def timed(fn):
        sol = fn()
        float(sol.distance)            # compile + converge warmup, fenced
        best = np.inf
        for _ in range(1 if quick else 3):
            t0 = time.perf_counter()
            sol = fn()
            float(sol.distance)        # scalar transfer = timing fence
            best = min(best, time.perf_counter() - t0)
        return sol, best

    egm_plain, t_egm_plain = timed(lambda: egm_run(None))
    egm_accel, t_egm_accel = timed(lambda: egm_run(accel))
    assert float(egm_plain.distance) < tol and float(egm_accel.distance) < tol

    # Distribution tolerance is dtype-aware: 1e-10 sits AT the f32 sweep's
    # roundoff floor (eps * |mu| ~ 1e-10 at mu ~ 1e-3), where the power
    # iteration can plateau without crossing it — on the TPU f32 route the
    # comparison runs at 1e-7, well above the noise band, and the ratio
    # claim is unchanged (sweep counts scale with log(tol)/log(rate) for
    # both routes alike).
    dist_tol = 1e-10 if jnp.finfo(dtype).eps < 1e-10 else 1e-7

    def dist_run(acc):
        return stationary_distribution(egm_plain.policy_k, model.a_grid,
                                       model.P, tol=dist_tol,
                                       max_iter=20_000, accel=acc)

    dist_plain, t_dist_plain = timed(lambda: dist_run(None))
    dist_accel, t_dist_accel = timed(lambda: dist_run(accel))
    # BOTH routes must actually converge — a max_iter'd plain baseline
    # would silently inflate dist_sweep_ratio instead of failing loudly.
    assert float(dist_plain.distance) < dist_tol, "plain distribution failed"
    assert float(dist_accel.distance) < dist_tol, "accelerated distribution failed"

    t_plain = t_egm_plain + t_dist_plain
    t_accel = t_egm_accel + t_dist_accel
    ep, ea = int(egm_plain.iterations), int(egm_accel.iterations)
    dp, da = int(dist_plain.iterations), int(dist_accel.iterations)
    return {
        "metric": f"accel_fixed_point_grid{grid_size}",
        "value": round(t_accel, 4),
        "unit": "seconds",
        "vs_baseline": round(t_plain / t_accel, 2),
        "baseline_seconds": round(t_plain, 4),
        "baseline_source": "plain first-order iteration, same solves (in-process)",
        "accel_method": accel.method,
        "accel_memory": accel.memory,
        "accel_delay": accel.delay,
        "egm_sweeps_plain": ep,
        "egm_sweeps_accel": ea,
        "egm_sweep_ratio": round(ep / max(ea, 1), 2),
        "egm_seconds_plain": round(t_egm_plain, 4),
        "egm_seconds_accel": round(t_egm_accel, 4),
        "dist_sweeps_plain": dp,
        "dist_sweeps_accel": da,
        "dist_sweep_ratio": round(dp / max(da, 1), 2),
        "dist_seconds_plain": round(t_dist_plain, 4),
        "dist_seconds_accel": round(t_dist_accel, 4),
    }


def bench_precision(quick: bool, grid_size: int = 4000) -> dict:
    """Mixed-precision solve ladder telemetry (ISSUE 4): the same cold EGM
    household solve and Young stationary-distribution solve run PURE-F64 and
    LADDERED (f32 hot sweeps -> error-controlled f64 polish, ops/precision.py
    via SolverConfig.ladder / BackendConfig(dtype="mixed")), reporting
    per-stage sweep counts, the residual at the dtype switch, walls, and the
    analytic-roofline ACHIEVED GB/s per stage (diagnostics/roofline.
    distribution_sweep_cost / egm_sweep_cost with per-stage dtype_itemsize —
    each stage's program is also run single-stage, so its bandwidth is a
    direct measurement, not a split of one wall). value = laddered
    EGM+distribution wall; vs_baseline = pure-f64 wall / laddered wall. The
    f32-stage-vs-f64 PER-SWEEP speedup (the memory-bound roofline claim:
    half the bytes) is recorded per loop, and the full run freezes the whole
    record into BENCH_r07_precision.json."""
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu.config import PrecisionLadderConfig, precision_scope
    from aiyagari_tpu.diagnostics.roofline import (
        achieved_bandwidth_gbs,
        distribution_sweep_cost,
        dtype_itemsize,
        egm_sweep_cost,
    )
    from aiyagari_tpu.ops.precision import default_ladder

    if quick:
        grid_size = min(grid_size, 400)

    # The reference dtype of this metric is f64 on EVERY platform (the
    # ladder's whole claim is parity with the f64 solve); precision_scope
    # enables x64 locally on TPU sessions where the global flag is off.
    with precision_scope("mixed"):
        from aiyagari_tpu.models.aiyagari import aiyagari_preset
        from aiyagari_tpu.sim.distribution import stationary_distribution
        from aiyagari_tpu.solvers.egm import (
            initial_consumption_guess,
            solve_aiyagari_egm,
        )
        from aiyagari_tpu.utils.firm import wage_from_r

        r, tol, max_iter = 0.04, 1e-5, 4000
        ladder = default_ladder()
        hot = PrecisionLadderConfig(stage_dtypes=("float32",),
                                    matmul_precision=("default",))
        model = aiyagari_preset(grid_size=grid_size, dtype=jnp.float64)
        N = int(model.P.shape[0])
        w = float(wage_from_r(r, model.config.technology.alpha,
                              model.config.technology.delta))
        C0 = initial_consumption_guess(model.a_grid, model.s, r, w)

        def timed_pair(fn_a, fn_b, rounds):
            """Interleaved best-of timing of two workloads: alternate them
            round-robin and keep each side's min. On this class of shared
            host, wall drift between two back-to-back measurement blocks
            was measured at up to 3x — interleaving samples both sides of
            the pair under the same drift, which is what a RATIO needs."""
            sols = [fn_a(), fn_b()]
            for s in sols:
                float(s.distance)          # compile + converge, fenced
            best = [np.inf, np.inf]
            for _ in range(rounds):
                for i, fn in enumerate((fn_a, fn_b)):
                    t0 = time.perf_counter()
                    s = fn()
                    float(s.distance)      # scalar transfer = timing fence
                    best[i] = min(best[i], time.perf_counter() - t0)
            return sols[0], sols[1], best[0], best[1]

        # min-of-3 even at ci sizes: with a single interleaved round the
        # per-side wall is one sample, and one scheduler burst on one side
        # skews the gated ladder/f64 ratio far past its true ~1.05-1.1
        # (measured 1.4x in-battery vs 1.04-1.10 standalone).
        rounds = 3

        def egm_run(ld, stage_tol, floor=0.0, cap=max_iter):
            return solve_aiyagari_egm(
                C0, model.a_grid, model.s, model.P, r, w, model.amin,
                tol=stage_tol, max_iter=cap, noise_floor_ulp=floor,
                ladder=ld, sigma=model.preferences.sigma,
                beta=model.preferences.beta)

        egm_f64, egm_mix, t_egm_f64, t_egm_mix = timed_pair(
            lambda: egm_run(None, tol), lambda: egm_run(ladder, tol), rounds)
        assert float(egm_f64.distance) < tol
        assert float(egm_mix.distance) < tol

        # Distribution tolerance: the reference f64 criterion.
        dist_tol, dist_cap = 1e-10, 50_000
        pk64 = egm_f64.policy_k

        def dist_run(ld, dtol, floor=0.0, cap=dist_cap):
            return stationary_distribution(
                pk64, model.a_grid, model.P, tol=dtol, max_iter=cap,
                noise_floor_ulp=floor, ladder=ld)

        dist_f64, dist_mix, t_dist_f64, t_dist_mix = timed_pair(
            lambda: dist_run(None, dist_tol),
            lambda: dist_run(ladder, dist_tol), rounds)
        assert float(dist_f64.distance) < dist_tol
        assert float(dist_mix.distance) < dist_tol
        mass_err = abs(float(jnp.sum(dist_mix.mu.astype(jnp.float64))) - 1.0)

        # Per-STAGE per-sweep walls, measured at a FIXED sweep count
        # (tol=0.0 runs the loop to exactly max_iter): the same program the
        # ladder's hot/polish stages execute, same sweep count for both
        # dtypes, so the interleaved ratio isolates the dtype — full-solve
        # walls divide by data-dependent iteration counts and are too noisy
        # on a shared CPU host for a stage claim. The hot program is the
        # single-stage f32 ladder (floor 0.0, so the fixed count runs).
        K_EGM, K_DIST = (10, 60) if quick else (40, 300)
        _, _, t_egm_sw64, t_egm_sw32 = timed_pair(
            lambda: egm_run(None, 0.0, cap=K_EGM),
            lambda: egm_run(hot, 0.0, cap=K_EGM), rounds + 1)
        _, _, t_dist_sw64, t_dist_sw32 = timed_pair(
            lambda: dist_run(None, 0.0, cap=K_DIST),
            lambda: dist_run(hot, 0.0, cap=K_DIST), rounds + 1)

        # The Euler-RHS block — u'(C) -> expectation matmul -> u'^{-1} —
        # iterated as its own fixed-count loop: the EGM sweep's compute
        # kernel isolated from the dtype-NEUTRAL scalar ops around it
        # (XLA:CPU's searchsorted gathers / cummax scan price f32 and f64
        # identically, and they dilute the full-sweep ratio on the host to
        # ~1.0-1.1x — measured, BENCHMARKS.md round 7). This is where the
        # CPU host shows the dtype effect the TPU roofline generalizes:
        # pow chains vectorize ~1.6x wider and sgemm runs ~3x dgemm here.
        from aiyagari_tpu.ops.bellman import expectation
        from aiyagari_tpu.utils.utility import (
            crra_marginal,
            crra_marginal_inverse,
        )

        K_RHS = 30 if quick else 100
        sig = float(model.preferences.sigma)

        def euler_rhs_loop(dtype, precision):
            C = C0.astype(dtype)
            P = model.P.astype(dtype)

            @jax.jit
            def loop(C):
                def body(_, y):
                    RHS = (1.0 + r) * expectation(P, crra_marginal(y, sig),
                                                  0.96, precision=precision)
                    return crra_marginal_inverse(RHS, sig)
                return jax.lax.fori_loop(0, K_RHS, body, C)

            def run():
                out = loop(C)
                out.block_until_ready()
                return out
            return run

        rhs64 = euler_rhs_loop(jnp.float64, jax.lax.Precision.HIGHEST)
        rhs32 = euler_rhs_loop(jnp.float32, None)
        rhs64(); rhs32()
        t_rhs64 = t_rhs32 = np.inf
        for _ in range(rounds + 3):
            t0 = time.perf_counter(); rhs64()
            t_rhs64 = min(t_rhs64, time.perf_counter() - t0)
            t0 = time.perf_counter(); rhs32()
            t_rhs32 = min(t_rhs32, time.perf_counter() - t0)

    def gbs(cost_fn, dtype, t, k):
        return achieved_bandwidth_gbs(
            k * cost_fn(N, grid_size, dtype_itemsize(dtype)), t)

    egm_hot_sw = int(egm_mix.hot_iterations)
    egm_pol_sw = int(egm_mix.iterations) - egm_hot_sw
    dist_hot_sw = int(dist_mix.hot_iterations)
    dist_pol_sw = int(dist_mix.iterations) - dist_hot_sw
    egm_speedup = t_egm_sw64 / t_egm_sw32
    dist_speedup = t_dist_sw64 / t_dist_sw32
    rhs_speedup = t_rhs64 / t_rhs32
    t_plain = t_egm_f64 + t_dist_f64
    t_ladder = t_egm_mix + t_dist_mix
    rnd = lambda x, k=4: (None if x is None else round(x, k))
    record = {
        "metric": f"precision_ladder_grid{grid_size}",
        "value": round(t_ladder, 4),
        "unit": "seconds",
        "vs_baseline": round(t_plain / t_ladder, 2),
        "baseline_seconds": round(t_plain, 4),
        "baseline_source": "pure-f64 solves, same workloads (in-process)",
        "ladder": {"stage_dtypes": list(ladder.stage_dtypes),
                   "switch_ulp": ladder.switch_ulp,
                   "matmul_precision": list(ladder.matmul_precision)},
        # EGM household fixed point.
        "egm_sweeps_f64": int(egm_f64.iterations),
        "egm_sweeps_f32_stage": egm_hot_sw,
        "egm_sweeps_f64_polish": egm_pol_sw,
        "egm_switch_residual": float(egm_mix.switch_distance),
        "egm_wall_f64": rnd(t_egm_f64),
        "egm_wall_ladder": rnd(t_egm_mix),
        "egm_f32_stage_sweep_speedup": round(egm_speedup, 2),
        "egm_gbs_f64_stage": rnd(gbs(egm_sweep_cost, "float64",
                                     t_egm_sw64, K_EGM), 2),
        "egm_gbs_f32_stage": rnd(gbs(egm_sweep_cost, "float32",
                                     t_egm_sw32, K_EGM), 2),
        # Young stationary-distribution power iteration.
        "dist_sweeps_f64": int(dist_f64.iterations),
        "dist_sweeps_f32_stage": dist_hot_sw,
        "dist_sweeps_f64_polish": dist_pol_sw,
        "dist_switch_residual": float(dist_mix.switch_distance),
        "dist_wall_f64": rnd(t_dist_f64),
        "dist_wall_ladder": rnd(t_dist_mix),
        "dist_f32_stage_sweep_speedup": round(dist_speedup, 2),
        "dist_gbs_f64_stage": rnd(gbs(distribution_sweep_cost, "float64",
                                      t_dist_sw64, K_DIST), 2),
        "dist_gbs_f32_stage": rnd(gbs(distribution_sweep_cost, "float32",
                                      t_dist_sw32, K_DIST), 2),
        "dist_mass_error_after_polish": mass_err,
        # The Euler-RHS kernel loop (u' -> P@ -> u'^-1, the EGM sweep's
        # compute block): the CPU-host hot loop where the f32 stage's dtype
        # effect is visible undiluted by XLA:CPU's dtype-neutral scalar ops
        # (scatter/searchsorted/cummax) — and the shape of the win the TPU
        # roofline doubles via bf16/HBM bytes.
        "euler_rhs_iters": K_RHS,
        "euler_rhs_wall_f64": rnd(t_rhs64),
        "euler_rhs_wall_f32_stage": rnd(t_rhs32),
        "euler_rhs_f32_speedup": round(rhs_speedup, 2),
        # The acceptance claim: the f32 stage beats pure f64 by >= 1.3x on
        # at least one CPU-host hot loop (memory-bound roofline: half the
        # bytes; on the CPU host the carrier is the Euler-RHS kernel loop).
        "f32_stage_sweep_speedup_best": round(
            max(egm_speedup, dist_speedup, rhs_speedup), 2),
    }
    if not quick:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_r07_precision.json")
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
    return record


def bench_pushforward(quick: bool, grid_size: int = 4000) -> dict:
    """Distribution push-forward backend walls (ISSUE 5): the SAME
    fixed-sweep Young stationary-distribution program run on every
    DistributionBackend (ops/pushforward.py) — scatter reference,
    monotone-transpose, banded block-matmul, fused Pallas — interleaved
    round-robin per the BENCHMARKS.md methodology (ratios need both sides
    sampled under the same host drift), with the per-route achieved GB/s
    from the round-7 roofline helpers (distribution_sweep_cost now prices
    each route's own bytes/FLOPs) and converged-mu parity against the
    scatter reference. value = best scatter-free per-sweep wall;
    vs_baseline = scatter per-sweep wall / value. Off-TPU the Pallas route
    runs the INTERPRETER — a correctness vehicle, not a perf route — so it
    is timed at a reduced sweep count, flagged `interpreted`, and excluded
    from the best-scatter-free claim (tests/test_bench_ci.py gates the
    claim on the CPU host at ci sizes). The full run freezes
    BENCH_r08_pushforward.json."""
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu.diagnostics.roofline import (
        achieved_bandwidth_gbs,
        distribution_sweep_cost,
        dtype_itemsize,
    )
    from aiyagari_tpu.models.aiyagari import aiyagari_preset
    from aiyagari_tpu.ops.pushforward import DEFAULT_BAND_WIDTH
    from aiyagari_tpu.sim.distribution import stationary_distribution
    from aiyagari_tpu.solvers.egm import (
        initial_consumption_guess,
        solve_aiyagari_egm,
    )
    from aiyagari_tpu.utils.firm import wage_from_r

    if quick:
        grid_size = min(grid_size, 200)
    platform = jax.default_backend()
    dtype = jnp.float32 if platform == "tpu" else jnp.float64
    model = aiyagari_preset(grid_size=grid_size, dtype=dtype)
    N = int(model.P.shape[0])
    r = 0.04
    w = float(wage_from_r(r, model.config.technology.alpha,
                          model.config.technology.delta))
    C0 = initial_consumption_guess(model.a_grid, model.s, r, w)
    sol = solve_aiyagari_egm(C0, model.a_grid, model.s, model.P, r, w,
                             model.amin, sigma=model.preferences.sigma,
                             beta=model.preferences.beta, tol=1e-5,
                             max_iter=2000)
    assert float(sol.distance) < 1e-5

    routes = ("scatter", "transpose", "banded", "pallas")
    # Fixed-sweep programs (tol=0.0 runs the loop to exactly max_iter):
    # the same while_loop the solvers execute, identical sweep counts per
    # route, so the interleaved ratio isolates the push-forward kernel.
    K = 60 if quick else 300
    K_by_route = {rt: K for rt in routes}
    if platform != "tpu":
        K_by_route["pallas"] = 3 if quick else 5

    def run(rt):
        return stationary_distribution(
            sol.policy_k, model.a_grid, model.P, tol=0.0,
            max_iter=K_by_route[rt], pushforward=rt)

    best = {rt: np.inf for rt in routes}
    for rt in routes:
        float(run(rt).distance)            # compile + warmup, fenced
    for _ in range(2 if quick else 4):
        for rt in routes:                  # round-robin: shared drift
            t0 = time.perf_counter()
            float(run(rt).distance)        # scalar transfer = timing fence
            best[rt] = min(best[rt], time.perf_counter() - t0)
    per_sweep = {rt: best[rt] / K_by_route[rt] for rt in routes}

    # Converged-mu parity pins against the scatter reference (the
    # acceptance contract: scatter-free defaults with parity pinned).
    dist_tol = 1e-10 if jnp.finfo(dtype).eps < 1e-10 else 1e-7

    def conv(rt, mu_init=None):
        return stationary_distribution(
            sol.policy_k, model.a_grid, model.P, tol=dist_tol,
            max_iter=20_000, mu_init=mu_init, pushforward=rt)

    ref = conv("scatter")
    assert float(ref.distance) < dist_tol

    def parity_of(rt):
        # The interpreted Pallas route off-TPU costs ~40 ms/sweep — seed
        # its solve AT the reference fixed point (a handful of sweeps to
        # re-certify) instead of paying ~1,200 interpreter sweeps for the
        # same parity pin.
        seed = ref.mu if (rt == "pallas" and platform != "tpu") else None
        return float(jnp.max(jnp.abs(conv(rt, seed).mu - ref.mu)))

    parity = {rt: parity_of(rt) for rt in routes[1:]}

    item = dtype_itemsize(dtype)
    route_recs = {}
    for rt in routes:
        cost = distribution_sweep_cost(N, grid_size, item, route=rt,
                                       band_width=DEFAULT_BAND_WIDTH)
        gbs = achieved_bandwidth_gbs(cost, per_sweep[rt])
        route_recs[rt] = {
            "wall_per_sweep_us": round(per_sweep[rt] * 1e6, 3),
            "sweeps_timed": K_by_route[rt],
            "achieved_gbs": None if gbs is None else round(gbs, 2),
            "parity_vs_scatter": parity.get(rt),
            "interpreted": rt == "pallas" and platform != "tpu",
        }

    scatter_ps = per_sweep["scatter"]
    contenders = {rt: per_sweep[rt] for rt in ("transpose", "banded")}
    if platform == "tpu":
        contenders["pallas"] = per_sweep["pallas"]
    best_route = min(contenders, key=contenders.get)
    record = {
        "metric": f"pushforward_sweep_grid{grid_size}",
        "value": round(contenders[best_route], 8),
        "unit": "seconds_per_sweep",
        "vs_baseline": round(scatter_ps / contenders[best_route], 2),
        "baseline_seconds": round(scatter_ps, 8),
        "baseline_source": "scatter-add reference route, same program "
                           "(in-process, interleaved)",
        "platform": platform,
        "dtype": "float64" if item == 8 else "float32",
        "best_scatter_free_route": best_route,
        "routes": route_recs,
    }
    if not quick:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_r08_pushforward.json")
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
    return record


def bench_egm_fused(quick: bool, grid_size: int = 4000) -> dict:
    """Fused Pallas EGM sweep vs the XLA op chain (ISSUE 11): the SAME
    fixed-sweep solve_aiyagari_egm program run on both egm_kernel routes
    (solvers/egm.py), interleaved round-robin per the BENCHMARKS.md
    methodology, with per-route achieved GB/s from the roofline cost
    models — egm_sweep_cost for the op chain, egm_fused_sweep_cost for the
    fused kernel, so the one-read-one-write byte claim is PRICED in the
    artifact, not asserted — and single-sweep operator parity between the
    routes. Off-TPU the fused route runs the Pallas INTERPRETER — a
    correctness vehicle, not a perf route — so it is timed at a reduced
    sweep count, flagged `interpreted`, and the host wall ratio is
    advisory only (tests/test_bench_ci.py gates parity and the priced
    bytes, never the host speedup — the speedup claim is TPU-side, like
    the pushforward pallas route). value = fused per-sweep wall;
    vs_baseline = XLA per-sweep wall / value. The full run freezes
    BENCH_r10_egm_fused.json."""
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu.diagnostics.roofline import (
        achieved_bandwidth_gbs,
        dtype_itemsize,
        egm_fused_sweep_cost,
        egm_sweep_cost,
    )
    from aiyagari_tpu.models.aiyagari import aiyagari_preset
    from aiyagari_tpu.ops.egm import egm_step
    from aiyagari_tpu.solvers.egm import (
        initial_consumption_guess,
        solve_aiyagari_egm,
    )
    from aiyagari_tpu.utils.firm import wage_from_r

    if quick:
        grid_size = min(grid_size, 200)
    platform = jax.default_backend()
    dtype = jnp.float32 if platform == "tpu" else jnp.float64
    model = aiyagari_preset(grid_size=grid_size, dtype=dtype)
    N = int(model.P.shape[0])
    r = 0.04
    w = float(wage_from_r(r, model.config.technology.alpha,
                          model.config.technology.delta))
    sigma, beta = model.preferences.sigma, model.preferences.beta
    C0 = initial_consumption_guess(model.a_grid, model.s, r, w)

    routes = ("xla", "pallas_fused")
    # Fixed-sweep programs (tol=0.0 runs the while_loop to exactly
    # max_iter): identical sweep counts per route, so the interleaved
    # ratio isolates the sweep kernel. The interpreted fused route off-TPU
    # costs ~ms-scale Python-dispatch sweeps — a reduced count times it
    # honestly without dominating the ci battery.
    K = 60 if quick else 300
    K_by_route = {rt: K for rt in routes}
    if platform != "tpu":
        K_by_route["pallas_fused"] = 3 if quick else 6

    def run(rt):
        return solve_aiyagari_egm(
            C0, model.a_grid, model.s, model.P, r, w, model.amin,
            sigma=sigma, beta=beta, tol=0.0, max_iter=K_by_route[rt],
            egm_kernel=rt)

    for rt in routes:
        sol = run(rt)                      # compile + warmup, fenced
        assert int(sol.iterations) == K_by_route[rt]
    best = {rt: np.inf for rt in routes}
    for _ in range(2 if quick else 4):
        for rt in routes:                  # round-robin: shared drift
            t0 = time.perf_counter()
            float(run(rt).distance)        # scalar transfer = timing fence
            best[rt] = min(best[rt], time.perf_counter() - t0)
    per_sweep = {rt: best[rt] / K_by_route[rt] for rt in routes}

    # Operator parity from the same iterate (the solver-level trajectories
    # are pinned to 1e-9 by tier-1; this puts the number in the artifact).
    want = egm_step(C0, model.a_grid, model.s, model.P, r, w, model.amin,
                    sigma=sigma, beta=beta)
    got = egm_step(C0, model.a_grid, model.s, model.P, r, w, model.amin,
                   sigma=sigma, beta=beta, egm_kernel="pallas_fused")
    parity = float(jnp.max(jnp.abs(want[0].astype(jnp.float64)
                                   - got[0].astype(jnp.float64))))

    item = dtype_itemsize(dtype)
    costs = {
        "xla": egm_sweep_cost(N, grid_size, item, windowed=False),
        "pallas_fused": egm_fused_sweep_cost(N, grid_size, item),
    }
    route_recs = {}
    for rt in routes:
        gbs = achieved_bandwidth_gbs(costs[rt], per_sweep[rt])
        route_recs[rt] = {
            "wall_per_sweep_us": round(per_sweep[rt] * 1e6, 3),
            "sweeps_timed": K_by_route[rt],
            "model_hbm_bytes_per_sweep": int(costs[rt].hbm_bytes),
            "achieved_gbs": None if gbs is None else round(gbs, 3),
            "interpreted": rt == "pallas_fused" and platform != "tpu",
        }

    record = {
        "metric": f"egm_fused_sweep_grid{grid_size}",
        "value": round(per_sweep["pallas_fused"], 8),
        "unit": "seconds_per_sweep",
        "vs_baseline": round(per_sweep["xla"] / per_sweep["pallas_fused"], 3),
        "baseline_seconds": round(per_sweep["xla"], 8),
        "baseline_source": "XLA op-chain sweep, same fixed-sweep program "
                           "(in-process, interleaved)",
        "platform": platform,
        "dtype": "float64" if item == 8 else "float32",
        "parity_vs_xla": parity,
        "routes": route_recs,
    }
    if not quick:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_r10_egm_fused.json")
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
    return record


def bench_telemetry(grid_size: int = 400, quick: bool = False) -> dict:
    """The flight-recorder cost sheet (ISSUE 6): recorder-ON vs recorder-OFF
    walls for the two hot loops telemetry instruments — fixed-sweep EGM and
    stationary-distribution programs, interleaved best-of timings so the
    ratio isolates the ring-buffer carry. Also pins the structural
    zero-cost-when-off claims in the artifact itself: the OFF solve's
    policies are BITWISE identical to the ON solve's (the recorder is
    write-only — it must never perturb the iterates), and the OFF jaxpr
    carries no ring buffer at all (the recorder compiles out, so the off
    path is the pre-telemetry program; `off_overhead_pct` is the measured
    timing delta between two interleaved passes of that same executable —
    scheduling noise, the honest floor of the <= 2% gate). value = the
    recorder-ON EGM+distribution wall; vs_baseline = off wall / on wall.
    The full run freezes BENCH_r09_telemetry.json."""
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu.config import TelemetryConfig
    from aiyagari_tpu.models.aiyagari import aiyagari_preset
    from aiyagari_tpu.sim.distribution import stationary_distribution
    from aiyagari_tpu.solvers.egm import (
        initial_consumption_guess,
        solve_aiyagari_egm,
    )
    from aiyagari_tpu.utils.firm import wage_from_r

    if quick:
        grid_size = min(grid_size, 200)
    platform = jax.default_backend()
    dtype = jnp.float32 if platform == "tpu" else jnp.float64
    model = aiyagari_preset(grid_size=grid_size, dtype=dtype)
    r = 0.04
    w = float(wage_from_r(r, model.config.technology.alpha,
                          model.config.technology.delta))
    C0 = initial_consumption_guess(model.a_grid, model.s, r, w)
    tele_cfg = TelemetryConfig()
    # Sweep counts size each timed wall to ~0.4-0.7 s even at ci grids: the
    # off-overhead gate (<= 2%, tests/test_bench_ci.py) compares best-of
    # minima of the SAME executable, and this host's scheduler/steal noise
    # only drops below the gate once walls reach a few hundred ms (measured:
    # 13% apart at 70 ms walls, 0.5% at 400 ms — same program both times).
    K_egm = 4000 if quick else 2000
    K_dist = 12000 if quick else 8000

    # Converged policy for the distribution loop.
    sol = solve_aiyagari_egm(C0, model.a_grid, model.s, model.P, r, w,
                             model.amin, sigma=model.preferences.sigma,
                             beta=model.preferences.beta, tol=1e-5,
                             max_iter=2000)
    assert float(sol.distance) < 1e-5

    # Fixed-sweep programs (tol=0.0 runs exactly max_iter sweeps), one per
    # (loop, recorder) cell; "off2" re-times the SAME off executable so the
    # off-overhead number is the interleaved noise floor of this box.
    def egm_run(tele):
        return solve_aiyagari_egm(
            C0, model.a_grid, model.s, model.P, r, w, model.amin,
            sigma=model.preferences.sigma, beta=model.preferences.beta,
            tol=0.0, max_iter=K_egm, telemetry=tele)

    def dist_run(tele):
        return stationary_distribution(
            sol.policy_k, model.a_grid, model.P, tol=0.0, max_iter=K_dist,
            telemetry=tele)

    cells = {"egm": (egm_run, K_egm), "dist": (dist_run, K_dist)}
    variants = [("off", None), ("off2", None), ("on", tele_cfg)]
    times = {(c, v): [] for c in cells for v, _ in variants}
    for c, (run, _) in cells.items():
        for _, tele in variants:
            float(run(tele).distance)          # compile + warmup, fenced
    for rep in range(7):
        # Rotate the variant order per rep: this host shows a POSITIONAL
        # timing bias (the second call of a back-to-back pair of the same
        # executable runs measurably slower), and rotation lets every
        # variant's min sample every slot.
        order = variants[rep % 3:] + variants[: rep % 3]
        for c, (run, _) in cells.items():
            for v, tele in order:              # interleaved: shared drift
                t0 = time.perf_counter()
                float(run(tele).distance)      # scalar transfer = fence
                times[(c, v)].append(time.perf_counter() - t0)
    best = {k: min(v) for k, v in times.items()}

    # Structural zero-cost-when-off pins, recorded in the artifact.
    sol_on, sol_off = egm_run(tele_cfg), egm_run(None)
    off_bit_identical = bool(
        jnp.all(sol_on.policy_c == sol_off.policy_c)
        & jnp.all(sol_on.policy_k == sol_off.policy_k)
        & (sol_on.distance == sol_off.distance))
    cap = int(tele_cfg.capacity)
    jaxpr_off = str(jax.make_jaxpr(lambda C: egm_run(None))(C0))
    jaxpr_on = str(jax.make_jaxpr(lambda C: egm_run(tele_cfg))(C0))
    ring_sig = f"f32[{cap}]"
    off_jaxpr_noop = (ring_sig not in jaxpr_off) and (ring_sig in jaxpr_on)

    loops = {}
    for c, (_, K) in cells.items():
        off, on = best[(c, "off")], best[(c, "on")]
        # Same executable timed twice: the interleaved noise floor. Take the
        # min over PAIRED per-rep deltas — a sustained steal burst inflates
        # both samples of a rep equally and cancels in the pair, where the
        # cross-rep min-vs-min would carry the burst into the number.
        pair_pct = min(
            abs(t2 - t1) / t1
            for t1, t2 in zip(times[(c, "off")], times[(c, "off2")]))
        loops[c] = {
            "sweeps_timed": K,
            "wall_off_s": round(off, 6),
            "wall_on_s": round(on, 6),
            "on_overhead_pct": round(100.0 * (on - off) / off, 3),
            "off_overhead_pct": round(100.0 * pair_pct, 3),
        }
    wall_on = best[("egm", "on")] + best[("dist", "on")]
    wall_off = best[("egm", "off")] + best[("dist", "off")]
    record = {
        "metric": f"telemetry_recorder_grid{grid_size}",
        "value": round(wall_on, 6),
        "unit": "seconds",
        "vs_baseline": round(wall_off / wall_on, 4),
        "baseline_seconds": round(wall_off, 6),
        "baseline_source": "identical fixed-sweep programs with the "
                           "recorder compiled out (in-process, interleaved)",
        "platform": platform,
        "dtype": str(np.dtype("float32" if dtype == jnp.float32
                              else "float64")),
        "capacity": cap,
        "on_overhead_pct": round(100.0 * (wall_on - wall_off) / wall_off, 3),
        "off_overhead_pct": max(loops["egm"]["off_overhead_pct"],
                                loops["dist"]["off_overhead_pct"]),
        "off_bit_identical": off_bit_identical,
        "off_jaxpr_noop": off_jaxpr_noop,
        "loops": loops,
    }
    if not quick:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_r09_telemetry.json")
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
    return record


def _ks_panel_throughput(T: int, pop: int, *, reps: int, outer: int) -> dict:
    """One K-S panel throughput measurement at (T, pop): chain `reps` full
    panel simulations inside ONE jitted program — each repetition's initial
    cross-section data-depends on the previous repetition's final aggregate
    (k0 + 0*prev; XLA cannot fold 0*x away since 0*NaN != 0), so all reps
    run sequentially on device — fetch once, and take the MEDIAN of `outer`
    such timings. Median, not min (VERDICT round 3 weak #1): the shipped
    artifact number must be what a re-run reproduces, and the min of a few
    draws over the remote transport rides the best-case tail that a
    different session does not hit; the per-rep min/max spread is recorded
    alongside so the artifact carries this run's variability."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    from aiyagari_tpu.config import KrusellSmithConfig
    from aiyagari_tpu.models.krusell_smith import KrusellSmithModel
    from aiyagari_tpu.sim.ks_panel import (
        simulate_aggregate_shocks,
        simulate_capital_path,
        simulate_employment_panel,
    )

    cfg = KrusellSmithConfig()
    platform = jax.default_backend()
    dtype = jnp.float32 if platform == "tpu" else jnp.float64
    model = KrusellSmithModel.from_config(cfg, dtype)
    kz, ke = jax.random.split(jax.random.PRNGKey(0))
    z = simulate_aggregate_shocks(model.pz, kz, T=T)
    eps = simulate_employment_panel(z, model.eps_trans, cfg.shocks.u_good,
                                    cfg.shocks.u_bad, ke, T=T, population=pop)
    k_opt = 0.9 * jnp.broadcast_to(
        model.k_grid[None, None, :], (4, cfg.K_size, cfg.k_size)).astype(dtype)
    K0 = float(model.K_grid[0])

    @partial(jax.jit, static_argnames=("reps",))
    def chained(*, reps):
        def one(carry, _):
            k0 = jnp.full((pop,), K0, dtype) + 0.0 * carry
            K_ts, _ = simulate_capital_path(k_opt, model.k_grid, model.K_grid,
                                            z, eps, k0, T=T,
                                            grid_power=float(cfg.k_power))
            return K_ts[-1], K_ts[-1]
        _, lasts = jax.lax.scan(one, jnp.array(0.0, dtype), None, length=reps)
        return lasts[-1]

    float(chained(reps=reps))  # compile + warmup, fenced
    times = []
    for _ in range(outer):
        t0 = time.perf_counter()
        float(chained(reps=reps))   # scalar transfer = timing fence
        times.append(time.perf_counter() - t0)
    times.sort()
    t = times[len(times) // 2] / reps
    spread = [round(times[0] / reps, 5), round(times[-1] / reps, 5)]
    return {"model": model, "k_opt": k_opt, "z": z, "eps": eps, "cfg": cfg,
            "dtype": dtype, "platform": platform, "t": t,
            "per_sim_spread": spread}


def bench_ks_agents(quick: bool) -> dict:
    """Krusell-Smith panel-simulation throughput (agents*steps/sec) at the
    reference scale: 10,000 agents x 1,100 periods (Krusell_Smith_VFI.m:10)."""
    import jax
    import jax.numpy as jnp

    T, pop = (300, 10_000) if quick else (1100, 10_000)
    platform = jax.default_backend()
    # reps amortize the per-program fetch round trip (~100 ms on this
    # image's remote transport): at ~25 ms/sim on TPU, 8 reps left ~12% of
    # the per-sim number to the fence — the measured gap between the
    # BENCHMARKS headline and the round-3 driver artifact. 24 reps cut the
    # fence share below ~2%. CPU sims are ~100x slower; small reps suffice.
    if platform == "tpu":
        reps, outer = (4, 1) if quick else (24, 5)
    else:
        reps, outer = (1, 1) if quick else (2, 3)
    m = _ks_panel_throughput(T, pop, reps=reps, outer=outer)
    t = m["t"]
    agent_steps = pop * (T - 1)

    # NumPy baseline: same panel step, vectorized with np.interp per state
    # (_numpy_ks_panel_seconds). The driver-facing (non-quick) path takes
    # the frozen/live denominator policy; quick mode — a smoke path, not an
    # artifact — just measures a short live loop at the quick T and stays
    # contention-sensitive.
    if quick:
        k_opt_np = np.asarray(m["k_opt"], np.float64)
        t_np = _numpy_ks_panel_seconds(
            k_opt_np, np.asarray(m["model"].k_grid, np.float64),
            np.asarray(m["model"].K_grid, np.float64), np.asarray(m["z"]),
            np.asarray(m["eps"]), T, pop, T_base=min(T, 120))
        base_fields = {}
    else:
        den = frozen_denominator("numpy_ks_panel_10000x1100")
        t_np = den.pop("seconds")
        base_fields = {"baseline_seconds": round(t_np, 4), **den}

    from aiyagari_tpu.diagnostics.roofline import panel_step_cost, utilization

    cfg = m["cfg"]
    cost = (T - 1) * panel_step_cost(pop, ns=4, nk=cfg.k_size,
                                     itemsize=jnp.dtype(m["dtype"]).itemsize,
                                     # Model the route actually executed
                                     # (the simulator picks it from k_power).
                                     analytic=float(cfg.k_power) > 0)

    # Width-batched companion (round 5, VERDICT round 4 weak #7): the
    # single 10k-agent panel is LAUNCH-bound (membw_frac ~0.3), so W=8
    # independent sims through one scan amortize the per-step overhead —
    # the aggregate throughput when sims are embarrassingly parallel
    # (seed batteries, bootstrap SEs). The headline `value` stays the
    # single-panel reference workload.
    batch_fields = {}
    if platform == "tpu" and not quick:
        from aiyagari_tpu.sim.ks_panel import (
            simulate_aggregate_shocks,
            simulate_capital_paths_batch,
            simulate_employment_panel,
        )

        W = 8
        model, dtype = m["model"], m["dtype"]
        keys = jax.random.split(jax.random.PRNGKey(7), 2 * W)
        zs, epss = [], []
        for i in range(W):
            zb = simulate_aggregate_shocks(model.pz, keys[2 * i], T=T)
            zs.append(zb)
            epss.append(simulate_employment_panel(
                zb, model.eps_trans, cfg.shocks.u_good, cfg.shocks.u_bad,
                keys[2 * i + 1], T=T, population=pop))
        z_paths, eps_panels = jnp.stack(zs), jnp.stack(epss)
        k0s = jnp.full((W, pop), float(model.K_grid[0]), dtype)

        def run_batch():
            K_ts, _ = simulate_capital_paths_batch(
                m["k_opt"], model.k_grid, model.K_grid, z_paths,
                eps_panels, k0s, T=T, grid_power=float(cfg.k_power))
            return float(K_ts[-1, -1])   # scalar transfer = timing fence

        run_batch()
        bt = []
        for _ in range(5):
            t0 = time.perf_counter()
            run_batch()
            bt.append(time.perf_counter() - t0)
        bt.sort()
        tb = bt[len(bt) // 2]
        batch_fields = {
            "batch8_agent_steps_per_sec": round(W * agent_steps / tb, 1),
            "batch8_per_sim_seconds": round(tb / W, 5),
        }

    return {
        "metric": "ks_panel_agent_steps_per_sec",
        "value": round(agent_steps / t, 1),
        "unit": "agent_steps/sec",
        "vs_baseline": round(t_np / t, 2),
        "per_sim_seconds_spread": m["per_sim_spread"],
        **base_fields,
        **batch_fields,
        **utilization(t, cost, platform),
    }


def bench_ks_agents_large(quick: bool) -> dict:
    """K-S panel throughput at 100,000 agents per device — the DP-scaling
    axis where the analytic-bucket interpolation's win lives (measured
    1.84x over the one-hot route at this population; BENCHMARKS.md round 3
    — prose-only until this record). Shorter T than the reference panel:
    the quantity is steady-state per-step throughput, which T=300 already
    measures (the scan body is T-invariant), and the 10x population keeps
    total agent-steps comparable. vs_baseline is a LIVE NumPy run of the
    same 100k-agent panel (no frozen entry: this workload is framework-
    defined, not the reference's — flagged in baseline_source)."""
    import jax
    import jax.numpy as jnp

    T, pop = (120, 100_000) if quick else (300, 100_000)
    platform = jax.default_backend()
    if platform == "tpu":
        reps, outer = (2, 1) if quick else (8, 5)
    else:
        reps, outer = (1, 1) if quick else (1, 3)
    m = _ks_panel_throughput(T, pop, reps=reps, outer=outer)
    t = m["t"]
    agent_steps = pop * (T - 1)

    # Live NumPy denominator at the same population (scaled from a short
    # loop like the reference-scale denominator's T_base policy).
    t_np = np.inf
    for _ in range(1 if quick else 2):
        t_np = min(t_np, _numpy_ks_panel_seconds(
            np.asarray(m["k_opt"], np.float64),
            np.asarray(m["model"].k_grid, np.float64),
            np.asarray(m["model"].K_grid, np.float64), np.asarray(m["z"]),
            np.asarray(m["eps"]), T, pop, T_base=min(T, 60)))

    from aiyagari_tpu.diagnostics.roofline import panel_step_cost, utilization

    cfg = m["cfg"]
    cost = (T - 1) * panel_step_cost(pop, ns=4, nk=cfg.k_size,
                                     itemsize=jnp.dtype(m["dtype"]).itemsize,
                                     analytic=float(cfg.k_power) > 0)
    return {
        "metric": "ks_panel_agent_steps_per_sec_pop100k",
        "value": round(agent_steps / t, 1),
        "unit": "agent_steps/sec",
        "vs_baseline": round(t_np / t, 2),
        "baseline_seconds": round(t_np, 4),
        "baseline_source": "live-best-of-2 (framework-defined workload)",
        "per_sim_seconds_spread": m["per_sim_spread"],
        **utilization(t, cost, platform),
    }


def bench_ks_fine(quick: bool, k_size: int = 1000, method: str = "egm") -> dict:
    """Fine-grid Krusell-Smith GE accuracy record (VERDICT round 3 #8a):
    full ALM fixed point at k_size points (mixed precision, Anderson,
    histogram closure — the round-3 fine-grid configuration), reporting the
    per-regime R^2 AND the Den Haan dynamic-forecast error
    (utils/accuracy.alm_dynamic_path_error) — the statistic that certifies
    what the R^2 cannot along the near-unit-root ridge (the fine-grid
    identification caveat, BENCHMARKS.md). Part of --metric all since
    round 5 (VERDICT round 4 weak #3: the headline accuracy statistic must
    live in the driver artifact, not only in prose): ~72 s at k=1000 on
    the chip, well inside the battery's 3600 s budget."""
    import aiyagari_tpu as at
    from aiyagari_tpu.utils.accuracy import alm_dynamic_path_error

    if quick:
        k_size = min(k_size, 200)
    t0 = time.perf_counter()
    res = at.solve(
        at.KrusellSmithConfig(k_size=k_size), method=method,
        backend=at.BackendConfig(dtype="mixed"),
        alm=at.ALMConfig(acceleration="anderson"),
        aggregation="distribution",
    )
    wall = time.perf_counter() - t0
    err_max, err_mean = alm_dynamic_path_error(
        res.K_ts, res.z_path, res.B, discard=100)
    return {
        "metric": f"ks_fine_ge_k{k_size}_{method}",
        "value": round(wall, 2),
        "unit": "seconds",
        "vs_baseline": None,
        "converged": bool(res.converged),
        "iterations": int(res.iterations),
        "diff_B": float(res.diff_B),
        "r2_good": round(float(res.r2[0]), 7),
        "r2_bad": round(float(res.r2[1]), 7),
        "den_haan_max_rel_err": round(err_max, 6),
        "den_haan_mean_rel_err": round(err_mean, 6),
        "B": [round(float(b), 5) for b in res.B],
    }


def bench_resilience(quick: bool, grid_size: int = 60) -> dict:
    """Injected-fault battery (ISSUE 10): drive every fault-injection
    point of diagnostics/faults.py through its recovery path and record
    (a) the rescue success rate — gated at 100% by tests/test_bench_ci.py:
    every injection either recovers through the rescue ladder or would
    fail loudly with a structured verdict; (b) the sentinel's early-exit
    sweep savings on a stalled distribution iteration (vs burning the full
    max_iter); (c) the quarantine contract — a sweep with exactly ONE
    poisoned scenario returns exactly one quarantined lane with every
    other lane parity-equal to an unpoisoned sweep — and the quarantine
    machinery's overhead on a CLEAN sweep (host-side masks only; gated
    <= 1.1x)."""
    import time

    import numpy as np

    from aiyagari_tpu import solve, sweep
    from aiyagari_tpu.config import (
        AiyagariConfig,
        EquilibriumConfig,
        FaultPlan,
        GridSpecConfig,
        RescueConfig,
        SentinelConfig,
        SolverConfig,
    )
    from aiyagari_tpu.diagnostics.errors import ConvergenceError

    grid_size = min(grid_size, 60) if quick else grid_size
    cfg = AiyagariConfig(grid=GridSpecConfig(n_points=grid_size))
    eq = EquilibriumConfig(max_iter=20, tol=1e-3)
    sentinel = SentinelConfig()

    # (a) the per-solve injection points, each through dispatch's rescue
    # ladder. force_fallback recovers WITHOUT the ladder (the compiled-in
    # scatter fallback is its recovery path — the base attempt converges);
    # the others fail their base attempt with a structured verdict and the
    # ladder escalates until a stage clears the fault.
    points = {
        "nan_sweep": FaultPlan(nan_sweep=3),
        "force_escape": FaultPlan(force_escape=True),
        "force_fallback": FaultPlan(force_fallback=True),
        "rescue_stage_failure": FaultPlan(nan_sweep=0,
                                          fail_stage="plain,safe"),
    }
    battery = {}
    recovered = 0
    for name, plan in points.items():
        t0 = time.perf_counter()
        try:
            res = solve(cfg, method="egm", aggregation="distribution",
                        solver=SolverConfig(method="egm", sentinel=sentinel,
                                            faults=plan),
                        equilibrium=eq, rescue=RescueConfig())
            attempts = res.rescue_attempts
            ok = bool(res.converged) and bool(np.isfinite(res.r))
        except ConvergenceError as e:
            attempts = e.attempts
            ok = False
        battery[name] = {
            "recovered": ok,
            "stages": [a.stage for a in attempts],
            "failed_attempts": sum(1 for a in attempts if not a.converged),
            "seconds": round(time.perf_counter() - t0, 3),
        }
        recovered += int(ok)

    # (b) sentinel stall early-exit: an unreachable tolerance stalls the
    # distribution iteration at its noise floor; the sentinel exits after
    # stall_window wasted sweeps where the plain loop burns max_iter.
    from aiyagari_tpu.models.aiyagari import AiyagariModel
    from aiyagari_tpu.sim.distribution import stationary_distribution
    from aiyagari_tpu.solvers.egm import (
        initial_consumption_guess,
        solve_aiyagari_egm,
    )

    m = AiyagariModel.from_config(cfg)
    C0 = initial_consumption_guess(m.a_grid, m.s, 0.02, 1.2)
    hh = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.02, 1.2, m.amin,
                            sigma=cfg.preferences.sigma,
                            beta=cfg.preferences.beta, tol=1e-6,
                            max_iter=1000)
    cap = 3000
    plain = stationary_distribution(hh.policy_k, m.a_grid, m.P, tol=1e-30,
                                    max_iter=cap)
    sent = stationary_distribution(hh.policy_k, m.a_grid, m.P, tol=1e-30,
                                   max_iter=cap, sentinel=sentinel)
    from aiyagari_tpu.diagnostics.sentinel import verdict_name

    stall = {
        "max_iter": cap,
        "plain_sweeps": int(plain.iterations),
        "sentinel_sweeps": int(sent.iterations),
        "sweeps_saved": int(plain.iterations) - int(sent.iterations),
        "verdict": verdict_name(sent.sentinel.verdict),
    }

    # (c) quarantine: poisoned sweep vs clean sweep. Exactly one lane
    # quarantined+rescued; the other lanes' rates parity-equal the clean
    # sweep's (the lockstep rounds are unchanged by the frozen lane).
    betas = [0.94, 0.95, 0.96]
    sweep_kw = dict(method="egm", beta=betas, equilibrium=eq)
    clean = sweep(cfg, solver=SolverConfig(method="egm"), **sweep_kw)
    poisoned = sweep(cfg,
                     solver=SolverConfig(method="egm",
                                         faults=FaultPlan(poison_scenario=1)),
                     rescue=RescueConfig(), **sweep_kw)
    n_quar = int(np.sum(np.asarray(poisoned.quarantined)))
    others = [i for i in range(len(betas)) if i != 1]
    parity = float(np.max(np.abs(np.asarray(poisoned.r)[others]
                                 - np.asarray(clean.r)[others])))
    quarantine_ok = (n_quar == 1 and bool(poisoned.quarantined[1])
                     and poisoned.verdicts[1] in ("rescued", "nan")
                     and all(poisoned.verdicts[i] == clean.verdicts[i]
                             for i in others))

    # Quarantine-machinery overhead on a CLEAN sweep: host masks only, so
    # the ratio sits at ~1.0. The gate downstream is 1.1x on ~1s walls,
    # which this host's scheduler noise can swing (the PR 6 telemetry
    # lesson: one burst on one side skews a min-of-1 ratio) — so the
    # measurement is interleaved min-of-5, rotating which variant runs
    # first, with the compiled round program shared by both variants (the
    # quarantine knob is host logic only; no retrace between them).
    from aiyagari_tpu.equilibrium.batched import (
        solve_equilibrium_sweep,
        stack_scenarios,
    )

    import dataclasses as _dc

    models = [AiyagariModel.from_config(
        _dc.replace(cfg, preferences=_dc.replace(cfg.preferences, beta=b)))
        for b in betas]
    batch = stack_scenarios(models)
    walls = {True: [], False: []}
    for rep in range(5):
        order = (True, False) if rep % 2 == 0 else (False, True)
        for q in order:
            t0 = time.perf_counter()
            solve_equilibrium_sweep(batch, solver=SolverConfig(method="egm"),
                                    eq=eq, quarantine=q)
            walls[q].append(time.perf_counter() - t0)
    overhead = min(walls[True]) / min(walls[False])

    rate = recovered / len(points)
    return {
        "metric": "resilience_fault_battery",
        "value": round(rate, 3),
        "unit": "recovery rate",
        "grid": grid_size,
        "injection_points": battery,
        "recovered": recovered,
        "points": len(points),
        "sentinel_stall": stall,
        "quarantine": {
            "scenarios": len(betas),
            "quarantined_lanes": n_quar,
            "poisoned_lane_verdict": poisoned.verdicts[1],
            "unpoisoned_parity": parity,
            "contract_ok": bool(quarantine_ok),
        },
        "quarantine_overhead": round(overhead, 4),
        "quarantine_walls": {"on": round(min(walls[True]), 4),
                             "off": round(min(walls[False]), 4)},
    }


def bench_mesh2d(quick: bool, grid_size: int = 1024, scenarios: int = 8,
                 rounds: int = 3) -> dict:
    """Pod-scale 2-D sharding (ISSUE 13): FIXED-WORK scenario-sweep walls
    across mesh topologies over 8 (virtual) host devices — 1-D
    scenarios-only vs 1-D grid-only vs the 2-D (scenarios x grid) mesh —
    with an unsharded reference for the parity pin. Fixed work = exactly
    `rounds` lockstep GE rounds (tol=0 never converges, so every topology
    executes the identical round count), timed interleaved min-of-reps
    with rotated order (the PR 6/10 one-burst-skews-a-ratio lesson).

    Per-topology record: wall, parity vs the unsharded sweep (gated
    <= 1e-12 by tests/test_bench_ci.py — reassociation noise only), and
    the roofline-priced cross-axis collective bytes (diagnostics/roofline.
    mesh2d_collective_cost: ICI for the grid axis, DCN for the scenario
    axis on a multi-host layout) — so the scaling claim ships with its
    priced communication, not just a wall. Every run freezes
    BENCH_r12_mesh2d.json (the attribution pattern: the ci battery is the
    canonical producer). On this one-core CPU host the virtual devices
    share the core, so the walls measure partitioning/collective OVERHEAD
    at equal total work — the honest off-TPU claim; the chips-scale claim
    is the priced-bytes column."""
    import dataclasses as _dc
    import time

    import jax
    import numpy as np

    from aiyagari_tpu.config import (
        AiyagariConfig,
        EquilibriumConfig,
        GridSpecConfig,
        SolverConfig,
    )
    from aiyagari_tpu.diagnostics.roofline import mesh2d_collective_cost
    from aiyagari_tpu.equilibrium.batched import (
        solve_equilibrium_sweep,
        stack_scenarios,
    )
    from aiyagari_tpu.models.aiyagari import AiyagariModel
    from aiyagari_tpu.parallel.mesh import make_mesh_2d

    if quick:
        # ci sizing: the walls are overhead measurements (wall_semantics
        # below) and the parity pin needs one solve per topology — two
        # fixed rounds keep the battery's share of tier-1 small.
        grid_size = min(grid_size, 64)
        rounds = min(rounds, 2)
    ndev = len(jax.devices())
    if ndev < 8:
        return {"metric": "mesh2d_sweep",
                "skipped": f"needs >= 8 devices, found {ndev} (the battery "
                           "forces the 8-virtual-device host mesh; a bare "
                           "run must set XLA_FLAGS)"}
    S = scenarios
    betas = np.linspace(0.94, 0.961, S)
    cfg = AiyagariConfig(grid=GridSpecConfig(n_points=grid_size))
    models = [AiyagariModel.from_config(
        _dc.replace(cfg, preferences=_dc.replace(cfg.preferences,
                                                 beta=float(b))))
        for b in betas]
    N = int(models[0].P.shape[0])
    solver = SolverConfig(method="egm", tol=1e-6, max_iter=400)
    eq = EquilibriumConfig(max_iter=rounds, tol=0.0)   # fixed work
    kw = dict(solver=solver, eq=eq, dist_tol=1e-8, dist_max_iter=300)

    topologies = {
        "unsharded": None,
        "scenarios8": (8, 1),
        "grid8": (1, 8),
        "2x4": (2, 4),
    }
    batches = {}
    for name, axes in topologies.items():
        mesh = None if axes is None else make_mesh_2d(scenarios=axes[0],
                                                      grid=axes[1])
        batches[name] = stack_scenarios(models, mesh=mesh)

    # Warmup (compile) once per topology, then interleaved min-of-reps.
    results = {}
    for name, batch in batches.items():
        results[name] = solve_equilibrium_sweep(batch, **kw)
    reps = 2 if quick else 3
    walls = {name: [] for name in topologies}
    names = list(topologies)
    for rep in range(reps):
        order = names[rep % len(names):] + names[:rep % len(names)]
        for name in order:
            t0 = time.perf_counter()
            solve_equilibrium_sweep(batches[name], **kw)
            walls[name].append(time.perf_counter() - t0)

    ref = results["unsharded"]
    topo_out = {}
    for name, axes in topologies.items():
        res = results[name]
        entry = {
            "wall_s": round(min(walls[name]), 4),
            "axes": ({} if axes is None
                     else {"scenarios": axes[0], "grid": axes[1]}),
            "rounds": int(res.rounds),
        }
        if axes is not None:
            entry["parity_vs_unsharded"] = float(
                np.max(np.abs(np.asarray(res.capital)
                              - np.asarray(ref.capital))))
            entry["r_equal"] = bool(
                np.array_equal(np.asarray(res.r), np.asarray(ref.r)))
            entry["collectives_per_sweep"] = mesh2d_collective_cost(
                S, N, grid_size, scenarios=axes[0], grid=axes[1],
                itemsize=8, sweeps=1, rounds=rounds)
        topo_out[name] = entry

    best_1d = min(("scenarios8", "grid8"),
                  key=lambda n: topo_out[n]["wall_s"])
    record = {
        "metric": "mesh2d_sweep",
        "value": topo_out["2x4"]["wall_s"],
        "unit": "seconds",
        "scenarios": S,
        "grid": grid_size,
        "rounds": rounds,
        "devices": ndev,
        "reps": reps,
        "topologies": topo_out,
        "best_1d": best_1d,
        "vs_best_1d": round(topo_out["2x4"]["wall_s"]
                            / topo_out[best_1d]["wall_s"], 4),
        "baseline_seconds": topo_out["unsharded"]["wall_s"],
        "wall_semantics": (
            "virtual devices share this host's core: topology walls are "
            "partitioning/collective OVERHEAD at equal total work (less "
            "sharding is always faster here); the cross-topology scaling "
            "claim rides collectives_per_sweep (ICI/DCN lower bounds), "
            "where the 2-D mesh pays only the sum of its axes' own "
            "traffic — no cross-axis term"),
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r12_mesh2d.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    return record


def _bench_virtual_mesh_leg(args, metric: str) -> dict:
    """A virtual-mesh leg (mesh2d / observatory) of a real `--metric all`
    battery, in its OWN interpreter: the 8-virtual-device request is an
    XLA_FLAGS env flag that must precede jax init and is process-wide, so
    forcing it in the battery session would re-topologize every other
    metric's environment (see the scoping note in main). The child
    (`--metric <name>`) forces it itself and still freezes its artifact;
    this parent relays the record into the battery output."""
    import subprocess

    cmd = [sys.executable, os.path.abspath(__file__), "--metric", metric]
    if args.quick:
        cmd.append("--quick")
    if args.platform:
        cmd += ["--platform", args.platform]
    if args.ledger:
        # Append-only JSONL (RunLedger opens "a" per event): the child's
        # events interleave whole-line-safe with the parent's.
        cmd += ["--ledger", args.ledger]
    out = subprocess.run(
        cmd, capture_output=True, text=True, timeout=900,
        env=dict(os.environ, _AIYAGARI_BENCH_CHILD="1"),
    )
    for line in out.stdout.splitlines():
        if line.startswith('{"metric"'):
            return json.loads(line)
    raise RuntimeError(
        f"{metric} child produced no metric record (rc={out.returncode}):\n"
        f"{(out.stderr or out.stdout)[-800:]}")


def bench_analysis() -> dict:
    """Static-analysis gate (ISSUE 9): the same run as `python -m
    aiyagari_tpu.analysis --format json`, in-process (the battery already
    paid the jax import). The record's `value` is the ACTIVE finding count
    — 0 on a healthy tree, gated at exactly 0 by tests/test_bench_ci.py —
    and the per-rule counts ride along so a regression names its rule in
    the artifact. When the battery runs with --ledger, run_analysis also
    emits the `analysis` ledger event (per-rule counts) on the active run
    ledger."""
    import time

    from aiyagari_tpu.analysis import run_analysis

    t0 = time.perf_counter()
    report = run_analysis()
    wall = time.perf_counter() - t0
    return {
        "metric": "static_analysis_findings",
        "value": float(report.active_count),
        "unit": "findings",
        "rule_counts": report.rule_counts(),
        "programs_audited": len(report.programs_audited),
        "programs_skipped": [n for n, _ in report.programs_skipped],
        "files_linted": report.files_linted,
        "suppressed_findings": len(report.findings) - report.active_count,
        "wall_seconds": round(wall, 3),
    }


def bench_attribution(quick: bool) -> dict:
    """Route observatory (ISSUE 12): (a) compile every registry program
    and join XLA's cost_analysis()/memory_analysis() against the
    analytic roofline price (analysis/attribution.py) — the modeled-vs-
    compiled byte ratio is the structural fusion-regression oracle
    tests/test_bench_ci.py gates for the audited EGM + push-forward
    programs; (b) run the measured route probes for every contested
    "auto" knob (tuning/autotuner.autotune) into an isolated bench-owned
    cache file, so the record carries the evidence behind each
    route_decision. `value` is the number of programs attributed. EVERY
    run (the ci preset included) freezes BENCH_r11_attribution.json —
    the frozen table is the band future rounds diff against, and the ci
    battery is its canonical producer (the acceptance contract), unlike
    the timing rounds whose full-size runs own their freeze."""
    import tempfile

    import jax

    from aiyagari_tpu.analysis.attribution import run_attribution
    from aiyagari_tpu.tuning.autotuner import autotune, grid_bucket

    report = run_attribution()
    programs = {}
    for rec in report.records:
        programs[rec["program"]] = {
            "compiled_bytes": rec["compiled"].get("bytes_accessed"),
            "compiled_flops": rec["compiled"].get("flops"),
            "peak_bytes": rec["compiled"].get("peak_bytes"),
            "modeled_bytes": (rec["modeled"]["hbm_bytes"]
                              if rec.get("modeled") else None),
            "byte_ratio": rec.get("byte_ratio"),
            "flop_ratio": rec.get("flop_ratio"),
            "flagged": rec.get("flagged", False),
        }

    # Probes land in an ISOLATED cache file: a bench/ci battery's
    # low-rep throwaway walls must never steer a tuning-enabled user's
    # solves or overwrite a deliberate `python -m aiyagari_tpu tune`
    # result — the user cache belongs to the tune CLI alone. The walls
    # themselves are the artifact, frozen in the record below.
    na = 512 if quick else 4096
    cache_path = os.path.join(
        tempfile.mkdtemp(prefix="aiyagari-bench-tuning-"), "tuning.json")
    entries = autotune(na=na, reps=2 if quick else 3, cache_path=cache_path)
    knobs = {}
    for key, entry in entries.items():
        knob = key.split("|", 1)[0]
        knobs[knob] = {
            "choice": entry["choice"],
            "walls_us": entry["walls_us"],
            "bucket": grid_bucket(entry["na"]),
            "na": entry["na"],
            "reps": entry["reps"],
        }

    record = {
        "metric": "route_attribution",
        "value": float(len(report.records)),
        "unit": "programs",
        "platform": jax.default_backend(),
        "programs": programs,
        "programs_skipped": [n for n, _ in report.skipped],
        "flagged": [r["program"] for r in report.flagged],
        "knobs": knobs,
        "tuning_cache": cache_path,
        "attribution_wall_seconds": round(report.wall_seconds, 3),
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r11_attribution.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    return record


def bench_observatory(quick: bool, grid_size: int = 64,
                      scenarios: int = 4) -> dict:
    """Pod observatory (ISSUE 14): exercise the whole multi-host toolchain
    on the 8-virtual-device mesh so an on-pod validation run inherits
    working tooling instead of printf archaeology. Four legs, one record:

      skew      — fenced per-axis rendezvous probes on the 2x4 mesh
                  (diagnostics/skew.py), gauges + straggler verdicts;
      heartbeat — the live-watch path: a ledger'd sweep with stride-1
                  heartbeats, plus the structural pin that arming
                  heartbeats changes NO compiled program (the stride is
                  host-side fan-out only — jaxpr-identical, bitwise
                  results);
      merge     — a simulated two-host shard pair (shared run id,
                  interleaved writes, one torn tail line) merged back
                  into one ordered stream (ledger.merge_ledgers);
      watch     — the `python -m aiyagari_tpu watch` table rendered from
                  the sweep's own ledger (per-scenario/per-host rows).

    value = the observatory wall (all four legs). EVERY run (the ci
    preset included) freezes BENCH_r13_observatory.json — the ci battery
    is the canonical producer, the attribution/mesh2d pattern."""
    import tempfile
    import time

    import jax
    import jax.numpy as jnp

    from aiyagari_tpu import dispatch
    from aiyagari_tpu.config import (
        AiyagariConfig,
        EquilibriumConfig,
        GridSpecConfig,
        MeshConfig,
        SolverConfig,
    )
    from aiyagari_tpu.diagnostics.ledger import (
        RunLedger,
        merge_ledgers,
        read_ledger,
    )
    from aiyagari_tpu.diagnostics.progress import configure_heartbeat
    from aiyagari_tpu.diagnostics.skew import SkewConfig, probe_mesh_skew
    from aiyagari_tpu.diagnostics.watch import build_state, render_state
    from aiyagari_tpu.models.aiyagari import aiyagari_preset
    from aiyagari_tpu.parallel.mesh import make_mesh_2d
    from aiyagari_tpu.solvers.egm import (
        initial_consumption_guess,
        solve_aiyagari_egm,
    )
    from aiyagari_tpu.utils.firm import wage_from_r

    ndev = len(jax.devices())
    if ndev < 8:
        return {"metric": "pod_observatory",
                "skipped": f"needs >= 8 devices, found {ndev} (the battery "
                           "forces the 8-virtual-device host mesh; a bare "
                           "run must set XLA_FLAGS)"}
    t_start = time.perf_counter()

    # Leg 1 — skew probes on the 2-D mesh (host_skew events land on the
    # battery's active ledger; gauges per axis).
    mesh = make_mesh_2d(scenarios=2, grid=4)
    probe = probe_mesh_skew(
        mesh, config=SkewConfig(reps=2 if quick else 5),
        price={"S": scenarios, "N": 7, "na": grid_size})
    axes = {}
    for rec in probe["axes"]:
        axes[rec["axis"]] = {
            "size": rec["size"],
            "rendezvous_seconds": rec["rendezvous_seconds"],
            "lag_spread_seconds": rec["lag_spread_seconds"],
            "verdict": rec["verdict"],
            "reconciliation": rec.get("reconciliation"),
        }

    # Leg 2 — heartbeat structural pins: with the in-jit progress callback
    # COMPILED IN (progress_every > 0), arming the ledger heartbeat stride
    # must not touch the program (it is host-side fan-out), and the
    # iterates must stay bitwise identical.
    dtype = jnp.float32 if jax.default_backend() == "tpu" else jnp.float64
    model = aiyagari_preset(grid_size=grid_size, dtype=dtype)
    r = 0.04
    w = float(wage_from_r(r, model.config.technology.alpha,
                          model.config.technology.delta))
    C0 = initial_consumption_guess(model.a_grid, model.s, r, w)

    def egm_run(C):
        return solve_aiyagari_egm(
            C, model.a_grid, model.s, model.P, r, w, model.amin,
            sigma=model.preferences.sigma, beta=model.preferences.beta,
            tol=1e-6, max_iter=200, progress_every=5)

    configure_heartbeat(0)
    jaxpr_off = str(jax.make_jaxpr(egm_run)(C0))
    sol_off = egm_run(C0)
    configure_heartbeat(3)
    jaxpr_on = str(jax.make_jaxpr(egm_run)(C0))
    sol_on = egm_run(C0)
    configure_heartbeat(0)
    jax.effects_barrier()
    off_jaxpr_identical = jaxpr_on == jaxpr_off
    off_bit_identical = bool(
        jnp.all(sol_on.policy_c == sol_off.policy_c)
        & (sol_on.distance == sol_off.distance))

    # Leg 3 — a ledger'd sweep with stride-1 heartbeats + the skew knob on
    # its own 2-D mesh activation, then the watch table from its shards.
    tmp = tempfile.mkdtemp(prefix="aiyagari-observatory-")
    sweep_ledger = os.path.join(tmp, "sweep.jsonl")
    betas = np.linspace(0.94, 0.955, scenarios)
    configure_heartbeat(1)
    try:
        dispatch.sweep(
            AiyagariConfig(grid=GridSpecConfig(n_points=grid_size)),
            method="egm", beta=[float(b) for b in betas],
            solver=SolverConfig(method="egm", tol=1e-6, max_iter=200),
            equilibrium=EquilibriumConfig(max_iter=2 if quick else 3,
                                          tol=0.0),
            mesh=MeshConfig(scenarios=2, grid=4, skew_probe=True),
            ledger=sweep_ledger)
    finally:
        configure_heartbeat(0)
    sweep_events = read_ledger(sweep_ledger)
    heartbeat_events = [e for e in sweep_events if e["kind"] == "heartbeat"]
    state = build_state(sweep_events)
    table = render_state(state)
    watch_rows = sum(len(run["rows"]) for run in state.values())

    # Leg 4 — simulated two-host shard merge: one run id across two
    # shards, interleaved writes, a torn tail on the live shard.
    base = os.path.join(tmp, "pod.jsonl")
    run_id = "podrun0000000001"
    led0 = RunLedger(base, run_id=run_id, process_index=0, process_count=2,
                     meta={"entry": "observatory-sim"})
    led1 = RunLedger(base, run_id=run_id, process_index=1, process_count=2,
                     meta={"entry": "observatory-sim"})
    written = 2  # the two run_start events
    for k in range(4):
        (led0 if k % 2 == 0 else led1).event(
            "heartbeat", context="sim", round=k, gap=[0.1 * (k + 1)])
        written += 1
    with open(led1.path, "a") as f:
        f.write('{"run_id": "podrun0000000001", "torn')
    merged = merge_ledgers([base])
    # Independent ordering pins (NOT a re-derivation of the merge's own
    # sort key): timestamps must never go backwards, and each host's
    # events must appear in their original per-shard sequence.
    ts_ok = all(merged[i]["ts"] <= merged[i + 1]["ts"]
                for i in range(len(merged) - 1))
    host_seqs: dict = {}
    for e in merged:
        host_seqs.setdefault(e["process_index"], []).append(e["seq"])
    seq_ok = all(s == sorted(set(s)) for s in host_seqs.values())
    merge_rec = {
        "shards": 2,
        "events_written": written,
        "events_merged": len(merged),
        "run_joined": len({e["run_id"] for e in merged}) == 1,
        "ordered": bool(ts_ok and seq_ok),
        "torn_tolerated": len(merged) == written,
    }

    wall = time.perf_counter() - t_start
    record = {
        "metric": "pod_observatory",
        "value": round(wall, 4),
        "unit": "seconds",
        "devices": ndev,
        "scenarios": scenarios,
        "grid": grid_size,
        "platform": jax.default_backend(),
        "skew": {"axes": axes, "processes": probe["processes"]},
        "heartbeat": {
            "off_jaxpr_identical": off_jaxpr_identical,
            "off_bit_identical": off_bit_identical,
            "events": len(heartbeat_events),
            "per_scenario": all(
                len(e.get("gap", [])) == scenarios
                for e in heartbeat_events),
        },
        "merge": merge_rec,
        "watch": {"rows": watch_rows, "rendered_chars": len(table)},
        "sweep_event_kinds": sorted({e["kind"] for e in sweep_events}),
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r13_observatory.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    return record


def bench_serve(quick: bool, grid_size: int = 40) -> dict:
    """Persistent solve service (ISSUE 15): measured load against an
    in-process SolveService at the ci calibration, five regimes —

      cold         — cache disabled, max_batch=1, CLOSED loop: every
                     request is a full GE solve served one at a time (the
                     baseline p50/p99);
      warm         — cache primed with N nearby calibrations, then N
                     perturbed-within-radius requests: each is a secant
                     polish warm-started from its cached neighbor (gated:
                     warm p50 <= 0.5x cold p50);
      hit          — the primed calibrations re-requested exactly:
                     replayed from the cache with no solve at all;
      serial_trans — N transition requests of one economy, cache off,
                     one at a time: each pays its OWN stationary anchor +
                     fake-news Jacobian (the one-at-a-time requests/sec
                     denominator);
      coalesced    — the same N transition requests submitted together,
                     max_batch=N: ONE lockstep dispatch.sweep_transitions
                     where one anchor and one Jacobian serve every lane —
                     the coalescing win that exists even on one core
                     (gated: coalesced requests/sec >= serial, measured
                     well above 2x).

    A sixth, RECORDED-ONLY regime (coalesced_steady) batches the steady-
    state requests through dispatch.sweep: on this one-core host lockstep
    lanes buy no wall-clock (equal compute, max-trip rounds — the
    recorded ratio documents it); the steady coalescing win is parallel
    lanes on real hardware (the PR 13 scenarios axis), while the
    shared-anchor transition batch above is the single-host win.

    Compile walls are excluded the honest way — one untimed warmup pass
    per regime program (the warm pool covers a real server's boot). Every
    request's ledger trail (serve_request/cache_hit/coalesce + dispatch's
    route decisions and spans) and the Prometheus serve gauges are
    checked structurally and counted into the record. value = coalesced
    transition requests/sec. EVERY run (the ci preset included) freezes
    BENCH_r14_serve.json — the attribution/mesh2d pattern."""
    import tempfile
    import time

    import jax
    import numpy as np

    from aiyagari_tpu.config import (
        AiyagariConfig,
        EquilibriumConfig,
        GridSpecConfig,
        MITShock,
        TransitionConfig,
    )
    from aiyagari_tpu.diagnostics import metrics as metrics_mod
    from aiyagari_tpu.diagnostics.ledger import RunLedger, read_ledger
    from aiyagari_tpu.serve import ServeConfig, SolveRequest, SolveService
    from aiyagari_tpu.serve.load import run_load

    t_start = time.perf_counter()
    n_req = 4 if quick else 8
    n_trans = 3 if quick else 6
    resolution = 1e-3
    # grid 40 / tol 2e-4: every cold AND warm calibration below
    # converges in 13-18 bisection rounds (coarser grids make the
    # histogram supply step-like at the 1e-4 scale and strand the
    # bracket on a jump — measured; the status taxonomy stays clean).
    eq = EquilibriumConfig(max_iter=48, tol=2e-4)
    trans = TransitionConfig(T=24, max_iter=20, tol=1e-6)
    base = AiyagariConfig(grid=GridSpecConfig(n_points=grid_size))

    def with_beta(beta):
        import dataclasses

        return dataclasses.replace(
            base, preferences=dataclasses.replace(base.preferences,
                                                  beta=round(beta, 6)))

    # Distinct calibrations, one per request; the warm regime perturbs
    # each INSIDE the neighbor radius so every lookup is a warm polish,
    # never an exact replay.
    betas = np.linspace(0.935, 0.952, n_req)
    cold_cfgs = [with_beta(b) for b in betas]
    warm_cfgs = [with_beta(b + 3.0 * resolution) for b in betas]
    shocks = [MITShock(param="tfp", size=s, rho=0.9)
              for s in np.linspace(0.004, 0.01, n_trans)]

    tmp = tempfile.mkdtemp(prefix="aiyagari_serve_bench_")
    ledger_path = os.path.join(tmp, "serve_ledger.jsonl")
    led = RunLedger(ledger_path, meta={"entry": "bench_serve"})

    def svc_config(**kw):
        kw.setdefault("method", "egm")
        kw.setdefault("aggregation", "distribution")
        kw.setdefault("equilibrium", eq)
        kw.setdefault("transition", trans)
        kw.setdefault("warm_pool", False)   # compile handling is explicit
        kw.setdefault("rescue", False)      # timing regimes: no ladder
        kw.setdefault("resolution", resolution)
        return ServeConfig(**kw)

    def t_req(shock):
        return SolveRequest(base, kind="transition", shock=shock)

    # -- regime 1: cold / one-at-a-time steady states ---------------------
    svc = SolveService(svc_config(cache_bytes=0, max_batch=1), ledger=led)
    svc.start()
    svc.solve(with_beta(0.9312), timeout=600)   # untimed compile pass
    cold = run_load(svc, [SolveRequest(c) for c in cold_cfgs], closed=True)
    svc.stop()

    # -- regimes 2+3: warm polish, then exact hits ------------------------
    svc = SolveService(svc_config(max_batch=1), ledger=led)
    svc.start()
    prime = run_load(svc, [SolveRequest(c) for c in cold_cfgs], closed=True)
    warm = run_load(svc, [SolveRequest(c) for c in warm_cfgs], closed=True)
    hits = run_load(svc, [SolveRequest(c) for c in cold_cfgs], closed=True)
    # -- the offered-rps ramp + latency-SLO gate (ISSUE 16 satellite) -----
    # Escalating open-loop rates of exact-hit traffic against the primed
    # service: run_ramp reports the KNEE where p99 crosses the SLO (or
    # the server stops keeping the offered schedule). The gate is that a
    # knee EXISTS — the service meets the SLO at the lowest offered rate;
    # hit traffic is cache replay, so a miss here is a serving-layer
    # regression (queue/coalescing overhead), never solver wall noise.
    from aiyagari_tpu.serve.load import run_ramp

    slo_s = max(0.25, 20.0 * (hits["p50_s"] or 0.01))

    def _hit_requests(n, step):
        cycled = (cold_cfgs * ((n + len(cold_cfgs) - 1)
                               // len(cold_cfgs)))[:n]
        return [SolveRequest(c) for c in cycled]

    ramp = run_ramp(svc, _hit_requests, rates=(4.0, 16.0, 64.0),
                    n_per_rate=n_req, slo_s=slo_s)
    cache_stats = svc.cache.stats()
    svc.stop()

    # -- regime 4: one-at-a-time transitions (each pays its own anchor) ---
    svc = SolveService(svc_config(cache_bytes=0, max_batch=1), ledger=led)
    svc.start()
    svc.solve(base, kind="transition", shock=MITShock(param="tfp",
                                                      size=0.003, rho=0.9),
              timeout=600)                       # untimed compile pass
    serial_trans = run_load(svc, [t_req(s) for s in shocks], closed=True)
    svc.stop()

    # -- regime 5: coalesced transitions (one anchor serves the batch) ----
    svc = SolveService(svc_config(cache_bytes=0, max_batch=n_trans,
                                  max_wait_s=0.5), ledger=led)
    svc.start()
    run_load(svc, [t_req(s) for s in shocks])    # compile S=N sweep pass
    coalesced = run_load(svc, [t_req(s) for s in shocks])
    svc.stop()

    # -- recorded-only: lockstep steady batch on this host ----------------
    svc = SolveService(svc_config(cache_bytes=0, max_batch=n_req,
                                  max_wait_s=0.5), ledger=led)
    svc.start()
    run_load(svc, [SolveRequest(c) for c in cold_cfgs])  # compile pass
    coalesced_steady = run_load(svc, [SolveRequest(c) for c in cold_cfgs])
    svc.stop()

    # -- the flight record + scrape surface, checked structurally ---------
    events = read_ledger(ledger_path)
    kinds: dict = {}
    for ev in events:
        kinds[ev["kind"]] = kinds.get(ev["kind"], 0) + 1
    prom = metrics_mod.render_prometheus()
    gauges_exported = {
        name: (name in prom)
        for name in ("aiyagari_serve_queue_depth",
                     "aiyagari_serve_batch_size",
                     "aiyagari_serve_cache_hit_rate")}

    warm_vs_cold = (warm["p50_s"] / cold["p50_s"]
                    if cold["p50_s"] else float("inf"))
    coalesced_vs_serial = (coalesced["rps"] / serial_trans["rps"]
                           if serial_trans["rps"] else 0.0)
    record = {
        "metric": "serve_load",
        "value": coalesced["rps"],
        "unit": "requests/sec (coalesced transitions)",
        "grid": grid_size,
        "requests_per_regime": n_req,
        "transition_requests": n_trans,
        "transition_T": trans.T,
        "resolution": resolution,
        "regimes": {
            "cold": cold,
            "warm": warm,
            "hit": hits,
            "serial_transition": serial_trans,
            "coalesced": coalesced,
            "coalesced_steady": coalesced_steady,
            "prime": {"requests": prime["requests"],
                      "wall_s": prime["wall_s"]},
        },
        "warm_vs_cold_p50": round(warm_vs_cold, 4),
        "hit_p50_s": hits["p50_s"],
        "ramp": ramp,
        "slo_gate": {"slo_s": round(slo_s, 6),
                     "knee_rps": ramp["knee_rps"],
                     "met": ramp["knee_rps"] is not None},
        "coalesced_vs_serial": round(coalesced_vs_serial, 4),
        "coalesced_steady_vs_cold": (
            round(coalesced_steady["rps"] * cold["p50_s"], 4)
            if cold["p50_s"] else None),
        "cache": cache_stats,
        "ledger_events": {k: kinds.get(k, 0)
                          for k in ("serve_request", "cache_hit", "coalesce",
                                    "route_decision", "span", "verdict")},
        "prometheus_gauges": gauges_exported,
        "wall_seconds": round(time.perf_counter() - t_start, 3),
        "platform": jax.default_backend(),
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r14_serve.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
    return record


def bench_amortized(quick: bool, grid_size: int = 40) -> dict:
    """Amortized solving (ISSUE 16): a sustained MIXED workload —
    clustered, then drifting, calibration traffic plus transitions —
    through the real SolveService, measuring how far the predictor
    ladder (exact hit -> multi-neighbor blend -> ledger-trained policy
    surrogate -> anchor reuse / cross-bucket anchor warm starts with
    interpolated fake-news Jacobians) drives the COLD-SOLVE FRACTION.

    Phases, all closed-loop against ONE service so cache and surrogate
    state accumulate exactly as production traffic would build it:

      seed_cold    — cluster centers, spaced beyond neighbor_radius:
                     every request is a true cold solve (the latency
                     denominators);
      clustered    — requests within a bucket or two of the centers:
                     multi-neighbor blended warm starts (blend/neighbor);
      hits         — the centers replayed exactly (cache replays);
      drift        — the calibration distribution walks OUT of every
                     cached neighborhood: no neighbor in radius, so the
                     service consults the surrogate (trained from the
                     converged solves above) for its warm start;
      transitions  — cold anchors, exact replays, a same-economy/new-
                     shock anchor reuse, and cross-bucket requests served
                     by anchor warm starts + interpolated Jacobians.

    Correctness band, exercised deliberately: one cached steady payload
    and one cached anchor Jacobian are POISONED and re-requested — the
    bad guesses must degrade to cold solves (counted `degradation`
    events) whose answers are verified BITWISE against a fresh cold
    service (`wrong_answer_degradations`, gated at zero). value = the
    cold-solve fraction (degraded requests count as cold — the honest
    accounting). EVERY run freezes BENCH_r15_amortized.json."""
    import dataclasses
    import tempfile
    import time
    from collections import Counter

    import jax
    import numpy as np

    from aiyagari_tpu.config import (
        AiyagariConfig,
        EquilibriumConfig,
        GridSpecConfig,
        MITShock,
        TransitionConfig,
    )
    from aiyagari_tpu.diagnostics import metrics as metrics_mod
    from aiyagari_tpu.diagnostics.ledger import RunLedger, read_ledger
    from aiyagari_tpu.serve import ServeConfig, SolveRequest, SolveService

    t_start = time.perf_counter()
    resolution = 1e-3
    radius = 2.0
    eq = EquilibriumConfig(max_iter=48, tol=2e-4)
    trans = TransitionConfig(T=24, max_iter=20, tol=1e-6)
    base = AiyagariConfig(grid=GridSpecConfig(n_points=grid_size))

    def with_beta(beta):
        return dataclasses.replace(
            base, preferences=dataclasses.replace(base.preferences,
                                                  beta=round(beta, 6)))

    # Cluster centers 10 buckets apart (>> radius: seeds are true colds);
    # clustered traffic sits 1-2 buckets off a center (inside radius);
    # drift points sit 3+ buckets from EVERY cached entry (outside radius
    # — the surrogate's regime) and 3+ apart from each other so an early
    # drift solve cannot serve a later one as a neighbor.
    centers = (0.931, 0.941, 0.951)
    offsets = (-2.0, -1.0, 1.0) if quick else (-2.0, -1.0, 1.0, 2.0)
    cluster = [c + s * resolution for c in centers for s in offsets]
    drift = (0.936, 0.946) if quick else (0.9265, 0.936, 0.946, 0.9565)
    shock_a = MITShock(param="tfp", size=0.008, rho=0.9)
    shock_b = MITShock(param="tfp", size=0.005, rho=0.9)
    t_betas = (0.931, 0.951)

    tmp = tempfile.mkdtemp(prefix="aiyagari_amortized_bench_")
    ledger_path = os.path.join(tmp, "amortized_ledger.jsonl")
    led = RunLedger(ledger_path, meta={"entry": "bench_amortized"})

    def cold_config(**kw):
        return ServeConfig(equilibrium=eq, transition=trans,
                           resolution=resolution, warm_pool=False,
                           rescue=False, surrogate=False, cache_bytes=0,
                           max_batch=1, **kw)

    # Untimed compile passes on a throwaway cold service: jit caches are
    # per-process, so the measured service never pays XLA walls (the
    # bench_serve convention — a real server's warm pool covers boot).
    boot = SolveService(cold_config())
    boot.start()
    boot.solve(with_beta(0.9295), timeout=600)
    boot.solve(with_beta(0.9295), kind="transition", shock=shock_a,
               timeout=600)
    boot.stop()

    svc = SolveService(ServeConfig(
        equilibrium=eq, transition=trans, resolution=resolution,
        warm_pool=False, rescue=False, max_batch=1,
        neighbor_radius=radius, blend_neighbors=4, surrogate=True,
        surrogate_min_samples=6, surrogate_fit_every=2), ledger=led)
    svc.start()
    rows: list = []

    def run(phase, requests, timeout=600.0):
        out = []
        for spec in requests:
            beta, kind, shock = spec
            resp = svc.solve(with_beta(beta), kind=kind, shock=shock,
                             timeout=timeout)
            rows.append((phase, resp))
            out.append(resp)
        return out

    def steady(betas):
        return [(b, "steady_state", None) for b in betas]

    run("seed_cold", steady(centers))
    run("clustered", steady(cluster))
    run("hits", steady(centers))
    run("drift", steady(drift))
    run("transition_cold",
        [(b, "transition", shock_a) for b in t_betas])
    run("transition_hit",
        [(b, "transition", shock_a) for b in t_betas])
    # Same economy, NEW shock: the anchor (ss + Jacobian) replays even
    # though the transition memo misses.
    run("transition_anchor", [(0.951, "transition", shock_b)])
    run("transition_anchor_warm",
        [(b + 0.5 * resolution, "transition", shock_a) for b in t_betas])

    # -- the correctness band, forced ------------------------------------
    # Poison one cached steady payload (a wildly wrong rate, no policy)
    # and cap the polish at a single evaluation: the guess CANNOT close,
    # so the request must degrade to the cold path. Its answer is then
    # compared bitwise against a fresh cold service below.
    with svc.cache._lock:
        ent = svc.cache._entries[svc.cache.key_for(with_beta(0.951))]
        ent.payload = dict(ent.payload, r=0.04, slope=None, warm=None)
    steps0 = svc.config.polish_steps
    svc.config = dataclasses.replace(svc.config, polish_steps=1)
    forced_steady = run("degraded_steady", steady([0.9515]))[0]
    svc.config = dataclasses.replace(svc.config, polish_steps=steps0)
    # Poison the 0.931 anchor's fake-news Jacobian (wrong sign AND
    # magnitude: Newton steps the wrong way and must exhaust max_iter),
    # then request that economy under a new shock: exact anchor hit ->
    # non-convergence -> degrade-to-cold.
    akey = svc.cache.key_for(with_beta(0.931), kind="anchor",
                             extra=(trans.T,))
    with svc.cache._lock:
        aent = svc.cache._entries[akey]
        aent.payload = dict(aent.payload, jacobian=(
            -0.05 * np.asarray(aent.payload["jacobian"])))
    forced_trans = run("degraded_transition",
                       [(0.931, "transition", shock_b)])[0]

    warm_sources = dict(svc.warm_sources)
    cold_fraction = svc.cold_fraction()
    degradations = svc.degradations
    surrogate_stats = svc.surrogate.stats()
    svc.stop()

    # Bitwise verification of every forced degraded answer against a
    # FRESH cold service (no cache, no surrogate): the degrade path's
    # contract is that a bad guess costs latency, never correctness.
    verify = SolveService(cold_config())
    verify.start()
    wrong = 0
    if forced_steady.degraded:
        vs = verify.solve(with_beta(0.9515), timeout=600)
        if float(vs.r) != float(forced_steady.r):
            wrong += 1
    if forced_trans.degraded:
        vt = verify.solve(with_beta(0.931), kind="transition",
                          shock=shock_b, timeout=600)
        if not np.array_equal(np.asarray(vt.r_path),
                              np.asarray(forced_trans.r_path)):
            wrong += 1
    verify.stop()

    def lat_stats(kind, sources, phases=None):
        xs = sorted(r.latency_s for p, r in rows
                    if r.kind == kind and r.warm_source in sources
                    and (phases is None or p in phases))
        if not xs:
            return {"count": 0, "p50_s": None, "p99_s": None}
        a = np.asarray(xs, np.float64)
        return {"count": len(xs),
                "p50_s": round(float(np.percentile(a, 50)), 6),
                "p99_s": round(float(np.percentile(a, 99)), 6)}

    steady_sources = {
        s: lat_stats("steady_state", (s,))
        for s in ("hit", "blend", "neighbor", "surrogate", "cold")}
    trans_sources = {
        s: lat_stats("transition", (s,))
        for s in ("hit", "anchor", "anchor_warm", "cold")}
    # Denominators come from the PURE cold phases (degraded requests pay
    # guess + cold and would flatter the ratios).
    cold_steady = lat_stats("steady_state", ("cold",), phases=("seed_cold",))
    cold_trans = lat_stats("transition", ("cold",),
                           phases=("transition_cold",))

    def ratio(num, den):
        if num["p50_s"] and den["p50_s"]:
            return round(num["p50_s"] / den["p50_s"], 4)
        return None

    events = read_ledger(ledger_path)
    kinds = Counter(ev["kind"] for ev in events)
    prom = metrics_mod.render_prometheus()

    record = {
        "metric": "serve_amortized",
        "value": round(cold_fraction, 4),
        "unit": "cold-solve fraction (lower is better)",
        "grid": grid_size,
        "requests": len(rows),
        "resolution": resolution,
        "neighbor_radius": radius,
        "transition_T": trans.T,
        "cold_fraction": round(cold_fraction, 4),
        "warm_sources": warm_sources,
        "steady_by_source": steady_sources,
        "transition_by_source": trans_sources,
        "surrogate_vs_cold_p50": ratio(steady_sources["surrogate"],
                                       cold_steady),
        "blend_vs_cold_p50": ratio(steady_sources["blend"], cold_steady),
        "anchor_warm_vs_cold_p50": ratio(trans_sources["anchor_warm"],
                                         cold_trans),
        "degradations": degradations,
        "wrong_answer_degradations": wrong,
        "forced_degradations": {
            "steady": bool(forced_steady.degraded),
            "transition": bool(forced_trans.degraded)},
        "surrogate": surrogate_stats,
        "ledger_events": {k: kinds.get(k, 0)
                          for k in ("serve_request", "cache_hit",
                                    "surrogate_fit", "degradation",
                                    "route_decision")},
        "prometheus_gauges": {
            "aiyagari_serve_cold_fraction":
                "aiyagari_serve_cold_fraction" in prom,
            "aiyagari_serve_warm_source_latency_seconds":
                "aiyagari_serve_warm_source_latency_seconds" in prom},
        "wall_seconds": round(time.perf_counter() - t_start, 3),
        "platform": jax.default_backend(),
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r15_amortized.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
    return record


def bench_calibration(quick: bool, grid_size: int = 16) -> dict:
    """Gradient-based calibration (ISSUE 17): planted-parameter recovery
    through the FULL differentiable solve stack — Rouwenhorst -> EGM fixed
    point -> stationary distribution -> GE rate, every stage an IFT
    adjoint (ops/implicit.py) — driven by dispatch.calibrate.

    Three claims, one record:

      grad_fd_max_rel_err  — jax.grad of the moment-distance objective vs
                             central finite differences, per z coordinate
                             at the (offset) starting point: the adjoint
                             chain's correctness evidence, in the ~1e-7
                             band the IFT parity tests pin;
      recovery_max_abs_err — a 2-lane Adam + BFGS fit started a few
                             percent off the planted (beta, sigma, rho,
                             sigma_e) must land within 1e-3 of ALL FOUR
                             (the ISSUE 17 acceptance; measured ~1e-11);
      wall_per_gradient    — one warm vmapped value_and_grad of the full
                             chain (both lanes), the cost unit the fit's
                             budget multiplies.

    EVERY run (the ci preset included) freezes BENCH_r16_calibration.json;
    tests/test_bench_ci.py gates the parity and recovery bands."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from aiyagari_tpu.calibrate.economy import steady_state_map
    from aiyagari_tpu.calibrate.loss import moment_loss, pack, unpack
    from aiyagari_tpu.calibrate.moments import model_moments, moments_of
    from aiyagari_tpu.config import (
        AiyagariConfig,
        GridSpecConfig,
        HouseholdPreferences,
        IncomeProcess,
    )
    from aiyagari_tpu.dispatch import calibrate
    from aiyagari_tpu.models.aiyagari import AiyagariModel

    t_start = time.perf_counter()
    planted = {"beta": 0.95, "sigma": 4.5, "rho": 0.70, "sigma_e": 0.70}
    start = {"beta": 0.955, "sigma": 4.8, "rho": 0.72, "sigma_e": 0.73}
    names = ("beta", "sigma", "rho", "sigma_e")
    n_states = 3
    grid = GridSpecConfig(n_points=grid_size)
    truth = AiyagariConfig(
        preferences=HouseholdPreferences(beta=planted["beta"],
                                         sigma=planted["sigma"]),
        income=IncomeProcess(rho=planted["rho"], sigma_e=planted["sigma_e"],
                             n_states=n_states, method="rouwenhorst"),
        grid=grid)
    base = AiyagariConfig(
        preferences=HouseholdPreferences(beta=start["beta"],
                                         sigma=start["sigma"]),
        income=IncomeProcess(rho=start["rho"], sigma_e=start["sigma_e"],
                             n_states=n_states, method="rouwenhorst"),
        grid=grid)
    ss_kwargs = dict(bisect_iters=45, hh_tol=1e-12, hh_max_iter=4000,
                     dist_tol=1e-13, dist_max_iter=20_000)
    targets = model_moments(truth, **ss_kwargs)

    # --- gradient parity at the starting point ------------------------
    model = AiyagariModel.from_config(base)
    tech = base.technology

    def objective(z):
        th = unpack(z, names)
        state = steady_state_map(
            th["beta"], th["sigma"], th["rho"], th["sigma_e"],
            model.a_grid, n_states=n_states, alpha=tech.alpha,
            delta=tech.delta, amin=float(model.amin), **ss_kwargs)
        return moment_loss(moments_of(state, model.a_grid,
                                      alpha=tech.alpha), targets)

    z0 = jnp.asarray(pack(start, names))
    grad = np.asarray(jax.grad(objective)(z0))
    h = 1e-5
    fd = np.zeros_like(grad)
    for i in range(z0.size):
        e = jnp.zeros_like(z0).at[i].set(h)
        fd[i] = float((objective(z0 + e) - objective(z0 - e)) / (2 * h))
    denom = np.maximum(np.abs(fd), 1e-12)
    grad_fd_max_rel_err = float(np.max(np.abs(grad - fd) / denom))

    # --- warm gradient wall (the fit's cost unit, both lanes) ---------
    vg = jax.jit(jax.vmap(jax.value_and_grad(objective)))
    z2 = jnp.stack([z0, z0 + 0.01])
    jax.block_until_ready(vg(z2))          # compile (shared with the fit)
    t0 = time.perf_counter()
    reps = 2 if quick else 4
    for _ in range(reps):
        jax.block_until_ready(vg(z2))
    wall_per_gradient = (time.perf_counter() - t0) / reps

    # --- planted recovery ---------------------------------------------
    t0 = time.perf_counter()
    res = calibrate(base, targets, names, lanes=2, steps=6, lr=0.05,
                    seed=0, jitter=0.01, stage_dtypes=("float64",),
                    ss_kwargs=ss_kwargs)
    fit_wall = time.perf_counter() - t0
    recovery = {k: float("nan") for k in names}
    if res.theta is not None:
        recovery = {k: abs(res.theta[k] - planted[k]) for k in names}
    recovery_max_abs_err = float(max(recovery.values()))

    record = {
        "metric": "calibration_recovery",
        "value": recovery_max_abs_err,
        "unit": "max |theta_fit - theta_planted| (lower is better)",
        "grid": grid_size,
        "n_states": n_states,
        "params": list(names),
        "status": res.status,
        "converged": res.status == "converged",
        "loss": res.loss,
        "steps": int(res.steps),
        "grad_evals": int(res.grad_evals),
        "lanes": res.lanes,
        "recovery_abs_err": {k: (round(v, 12) if np.isfinite(v) else None)
                             for k, v in recovery.items()},
        "recovery_max_abs_err": recovery_max_abs_err,
        "grad_fd_max_rel_err": grad_fd_max_rel_err,
        "fd_step": h,
        "wall_per_gradient_seconds": round(wall_per_gradient, 4),
        "fit_wall_seconds": round(fit_wall, 3),
        "targets": {k: round(float(v), 10) for k, v in targets.items()},
        "wall_seconds": round(time.perf_counter() - t_start, 3),
        "platform": jax.default_backend(),
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r16_calibration.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
    return record


def bench_fleet(quick: bool, grid_size: int = 40) -> dict:
    """Solve fabric (ISSUE 20): the tiered cache + AOT warm pool + fleet
    front, measured in four regimes —

      aot_walls    — `python -m aiyagari_tpu warmup --na G --families ''
                     --aot` twice in subprocesses against EMPTY caches:
                     run 1 compiles fresh and exports AOT executables,
                     run 2 restores them (no retrace). Gated: for every
                     program run 2 restored, restore wall <= 0.5x the
                     fresh compile wall (host_callback-bearing programs
                     are legitimately unexportable and recorded as such).
      throughput   — 1 spawned serve worker vs 2, each primed with the
                     same calibrations, then driven with exact-hit
                     traffic over real HTTP. This box is single-core, so
                     the 2-worker number is AGGREGATE FLEET CAPACITY:
                     each worker is driven separately (sequentially) and
                     the per-worker rates are summed — the number a
                     one-core-per-worker deployment serves. Gated:
                     aggregate >= 1.6x the single worker. A concurrent
                     multi-URL round-robin drive through the same
                     HttpServiceClient is recorded informationally (on
                     one core it measures GIL interleaving, not
                     capacity).
      l2_cold_frac — two fresh services serving the same calibrations,
                     once sharing an L2 directory a first service
                     populated, once without: the L2-fed service's cold
                     fraction must be STRICTLY below the L2-less one
                     (cross-worker reuse is real). L2 finds surface as
                     outcome "warm" — never "hit" — so every payload
                     re-enters the polish ladder.
      poisoned_l2  — the L2 document for a solved calibration is
                     rewritten in place with a VALID stamp but a garbage
                     payload (far-off rate, bogus slope); a fresh service
                     with polish_steps=2 and no surrogate must DEGRADE to
                     a cold re-solve whose answer is bitwise the clean
                     cold answer: wrong_answer_degradations == 0 is the
                     gate (the tier can cost wall time, never a wrong
                     answer).

    value = 2-worker aggregate hit requests/sec. EVERY run (the ci
    preset included) freezes BENCH_r19_fleet.json."""
    import pickle
    import subprocess
    import tempfile
    import time

    import jax
    import numpy as np

    from aiyagari_tpu.config import (
        AiyagariConfig,
        EquilibriumConfig,
        GridSpecConfig,
        TransitionConfig,
    )
    from aiyagari_tpu.serve import ServeConfig, SolveRequest, SolveService
    from aiyagari_tpu.serve.fleet import Fleet
    from aiyagari_tpu.serve.load import HttpServiceClient, run_load

    t_start = time.perf_counter()
    n_req = 3 if quick else 4
    hit_rounds = 3                       # each primed beta re-requested
    resolution = 1e-3
    eq = EquilibriumConfig(max_iter=48, tol=2e-4)
    trans = TransitionConfig(T=24, max_iter=20, tol=1e-6)
    base = AiyagariConfig(grid=GridSpecConfig(n_points=grid_size))

    def with_beta(beta):
        import dataclasses

        return dataclasses.replace(
            base, preferences=dataclasses.replace(base.preferences,
                                                  beta=round(beta, 6)))

    betas = np.linspace(0.935, 0.952, n_req)
    cfgs = [with_beta(b) for b in betas]

    tmp = tempfile.mkdtemp(prefix="aiyagari_fleet_bench_")

    # -- regime 1: AOT restore vs fresh compile walls ---------------------
    # Both runs in subprocesses against caches rooted in a fresh tmp dir
    # (the env empties nothing outside it): run 1 pays every trace+compile
    # and exports, run 2 restores the serialized executables. The gate
    # compares PER-PROGRAM walls for the programs run 2 restored.
    aot_cache = os.path.join(tmp, "xla")
    aot_dir = os.path.join(tmp, "aot")
    warm_cmd = [sys.executable, "-m", "aiyagari_tpu", "warmup",
                "--na", str(grid_size), "--families", "", "--aot",
                "--aot-dir", aot_dir, "--cache-dir", aot_cache, "--json"]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"

    def _warm_run():
        out = subprocess.run(warm_cmd, capture_output=True, text=True,
                             timeout=600, env=env)
        if out.returncode != 0:
            raise RuntimeError(f"warmup subprocess failed "
                               f"(rc={out.returncode}): {out.stderr[-500:]}")
        return json.loads(out.stdout)

    fresh = _warm_run()
    restored = _warm_run()
    aot_programs = {}
    restore_ratios = []
    for name, rec in restored["programs"].items():
        f_rec = fresh["programs"].get(name, {})
        row = {"fresh_s": f_rec.get("compile_seconds"),
               "restore_s": rec["compile_seconds"],
               "restored": bool(rec.get("restored")),
               "aot": rec.get("aot", "off")}
        if row["restored"] and row["fresh_s"]:
            row["restore_vs_fresh"] = round(
                row["restore_s"] / row["fresh_s"], 4)
            restore_ratios.append(row["restore_vs_fresh"])
        aot_programs[name] = row
    aot_walls = {
        "programs": aot_programs,
        "restored_count": restored["restored"],
        "fresh_wall_s": fresh["wall_seconds"],
        "restored_wall_s": restored["wall_seconds"],
        "worst_restore_vs_fresh": (max(restore_ratios)
                                   if restore_ratios else None),
        "gate_met": bool(restore_ratios)
        and max(restore_ratios) <= 0.5,
    }

    # -- regime 2: 1-worker vs 2-worker hit throughput --------------------
    # Real spawned workers over real HTTP; hits bypass the queue (the
    # service's fast path), so the measured rate is the serving layer.
    worker_args = dict(
        grids=(grid_size,), method="egm", max_batch=1, cache_mb=64.0,
        warm_families="", platform="cpu",
        extra_args=("--tol", "2e-4", "--max-iter", "48", "--no-warm",
                    "--no-surrogate"))
    hit_cfgs = (cfgs * hit_rounds)

    def _drive_worker(port):
        with HttpServiceClient(base, port, timeout=600.0) as client:
            prime = run_load(client, [SolveRequest(c) for c in cfgs],
                             closed=True)
            hits = run_load(client, [SolveRequest(c) for c in hit_cfgs],
                            closed=True)
        return prime, hits

    fleet1 = Fleet(workers=1, **worker_args)
    fleet1.start(ready_timeout=600)
    try:
        _, hits_1 = _drive_worker(fleet1.workers[0].port)
    finally:
        fleet1.stop()
    rps_1 = hits_1["rps"] or 0.0

    ledger_path = os.path.join(tmp, "fleet_ledger.jsonl")
    fleet2 = Fleet(workers=2, ledger=ledger_path, **worker_args)
    fleet2.start(ready_timeout=600)
    try:
        per_worker = [_drive_worker(w.port)[1] for w in fleet2.workers]
        # Informational: the same hit schedule round-robined over BOTH
        # base URLs at once (per-thread keep-alive socket per port).
        ports = tuple(w.port for w in fleet2.workers)
        with HttpServiceClient(base, ports, timeout=600.0) as client:
            concurrent = run_load(client,
                                  [SolveRequest(c) for c in hit_cfgs])
        fleet_health = fleet2.health(max_age_s=0.0)
    finally:
        fleet2.stop()
    aggregate_rps = sum(h["rps"] or 0.0 for h in per_worker)
    throughput = {
        "single_worker": hits_1,
        "per_worker": per_worker,
        "aggregate_rps": round(aggregate_rps, 4),
        "aggregate_vs_single": (round(aggregate_rps / rps_1, 4)
                                if rps_1 else None),
        "semantics": "aggregate fleet capacity: per-worker rates measured "
                     "sequentially and summed (single-core host; each "
                     "worker owns the core while measured)",
        "concurrent_multiport": concurrent,
        "health": {"workers": len(fleet_health.get("workers", [])),
                   "l2_hits": fleet_health.get("l2_hits")},
        "gate_met": bool(rps_1) and aggregate_rps >= 1.6 * rps_1,
    }

    # -- regimes 3+4: shared in-process services --------------------------
    def svc_config(**kw):
        kw.setdefault("method", "egm")
        kw.setdefault("aggregation", "distribution")
        kw.setdefault("equilibrium", eq)
        kw.setdefault("transition", trans)
        kw.setdefault("warm_pool", False)
        kw.setdefault("rescue", False)
        kw.setdefault("surrogate", False)
        kw.setdefault("max_batch", 1)
        kw.setdefault("resolution", resolution)
        return ServeConfig(**kw)

    def cold_frac(row):
        n = row["requests"] or 1
        return row["cache_outcomes"].get("cold", 0) / n

    def _serve_pair(l2_dir):
        """Populate with one service instance, serve the same traffic
        from a FRESH one (empty L1) — with/without the shared L2."""
        kw = {"l2_dir": l2_dir} if l2_dir else {}
        svc = SolveService(svc_config(**kw))
        svc.start()
        svc.solve(with_beta(0.9312), timeout=600)   # untimed compile pass
        run_load(svc, [SolveRequest(c) for c in cfgs], closed=True)
        svc.stop()
        svc = SolveService(svc_config(**kw))
        svc.start()
        served = run_load(svc, [SolveRequest(c) for c in cfgs],
                          closed=True)
        stats = svc.cache.stats()
        svc.stop()
        return served, stats

    l2_dir = os.path.join(tmp, "l2")
    served_on, stats_on = _serve_pair(l2_dir)
    served_off, _ = _serve_pair(None)
    frac_on, frac_off = cold_frac(served_on), cold_frac(served_off)
    l2_cold = {
        "with_l2": served_on,
        "without_l2": served_off,
        "cold_fraction_on": round(frac_on, 4),
        "cold_fraction_off": round(frac_off, 4),
        "l2_stats": stats_on.get("l2"),
        "hits_never_from_l2": served_on["cache_outcomes"].get("hit", 0)
        == 0,
        "gate_met": frac_on < frac_off,
    }

    # -- regime 4: poisoned L2 entry --------------------------------------
    poison_dir = os.path.join(tmp, "l2poison")
    target = cfgs[0]
    svc = SolveService(svc_config(l2_dir=poison_dir))
    svc.start()
    ref = svc.solve(target, timeout=600)            # the clean cold answer
    svc.stop()
    poisoned_files = 0
    for fname in os.listdir(poison_dir):
        if not fname.endswith(".pkl"):
            continue
        path = os.path.join(poison_dir, fname)
        with open(path, "rb") as f:
            doc = pickle.load(f)
        p = dict(doc["payload"])
        p["r"] = float(ref.r) + 0.03                # far outside the polish
        p["slope"] = 1e12                           # secant step ~= 0
        p["warm"] = None
        doc["payload"] = p                          # stamp stays VALID
        with open(path, "wb") as f:
            pickle.dump(doc, f, protocol=pickle.HIGHEST_PROTOCOL)
        poisoned_files += 1
    svc = SolveService(svc_config(l2_dir=poison_dir, polish_steps=2))
    svc.start()
    poisoned = svc.solve(target, timeout=600)
    degr = int(svc.degradations)
    svc.stop()
    bitwise_equal = (poisoned.r == ref.r and poisoned.w == ref.w
                     and poisoned.capital == ref.capital)
    wrong_answers = 0 if bitwise_equal else 1
    poison = {
        "poisoned_files": poisoned_files,
        "served_from": poisoned.cache,
        "warm_source": poisoned.warm_source,
        "degraded": bool(poisoned.degraded),
        "degradations": degr,
        "reference_r": float(ref.r),
        "poisoned_r": float(poisoned.r),
        "bitwise_equal": bitwise_equal,
        "wrong_answer_degradations": wrong_answers,
        "gate_met": bool(poisoned.degraded) and wrong_answers == 0,
    }

    record = {
        "metric": "fleet",
        "value": round(aggregate_rps, 4),
        "unit": "requests/sec (2-worker aggregate hit traffic)",
        "grid": grid_size,
        "requests_per_regime": n_req,
        "hit_rounds": hit_rounds,
        "resolution": resolution,
        "aot_walls": aot_walls,
        "throughput": throughput,
        "l2_cold_fraction": l2_cold,
        "poisoned_l2": poison,
        "gates": {
            "aot_restore_le_half_fresh": aot_walls["gate_met"],
            "aggregate_ge_1p6x_single": throughput["gate_met"],
            "l2_cold_fraction_below": l2_cold["gate_met"],
            "poisoned_l2_degrades_bitwise": poison["gate_met"],
        },
        "wall_seconds": round(time.perf_counter() - t_start, 3),
        "platform": jax.default_backend(),
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r19_fleet.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
    return record


def _run_in_child(timeout_s: float) -> int | None:
    """Re-exec this benchmark in a child process with a hard timeout and relay
    its JSON line. Returns the exit code, or None if the child timed out or
    produced no result (caller then falls back to CPU in-process).

    Why a child: the remote-TPU transport in this image can hang device
    initialization indefinitely when the tunnel is down, and a wedged
    in-process backend cannot be recovered (the platform lock prevents a CPU
    retry). The child owns the ONLY device client — an earlier design probed
    jax.devices() in a throwaway subprocess first, and the probe client's
    teardown reproducibly crashed the remote worker under the main process
    (UNAVAILABLE: TPU worker process crashed) — so probe and measurement must
    be the same process."""
    import subprocess

    env = dict(os.environ, _AIYAGARI_BENCH_CHILD="1")
    try:
        out = subprocess.run(
            [sys.executable, __file__, *sys.argv[1:]],
            timeout=timeout_s, env=env, capture_output=True, text=True,
        )
    except (subprocess.TimeoutExpired, OSError) as e:
        stderr = getattr(e, "stderr", None)
        if stderr:
            sys.stderr.write(stderr if isinstance(stderr, str) else stderr.decode())
        # TimeoutExpired carries the partial stdout: metrics measured on
        # the real device before the hang must be relayed, not re-run on
        # CPU as wrong-platform duplicates (same contract as the
        # partial-battery path below).
        stdout = getattr(e, "stdout", None)
        if stdout:
            stdout = stdout if isinstance(stdout, str) else stdout.decode()
            lines = [l for l in stdout.splitlines()
                     if l.startswith('{"metric"')]
            if lines:
                print("\n".join(lines), flush=True)
                print(f"bench: child timed out after {len(lines)} metric(s); "
                      "partial results relayed above", file=sys.stderr)
                return 1
        print(f"bench: child run failed ({type(e).__name__} after "
              f"{timeout_s:.0f}s); falling back to --platform cpu", file=sys.stderr)
        return None
    sys.stderr.write(out.stderr)
    # Relay every measurement line wherever it sits in stdout — a stray print
    # around the JSON records must not turn a successful run into a failure,
    # and a metric that dies MID-BATTERY (e.g. a transient remote-compile
    # transport error on the 4th of 5 metrics — observed live) must not
    # discard the lines already measured on the real device.
    lines = [l for l in out.stdout.splitlines() if l.startswith('{"metric"')]
    if lines:
        print("\n".join(lines), flush=True)
    if out.returncode == 0 and lines:
        return 0
    if lines:
        # Partial battery: the device lines above are the artifact; a CPU
        # fallback would re-run EVERYTHING off-device and append
        # wrong-platform duplicates. Surface the failure code instead.
        print(f"bench: child died after {len(lines)} metric(s) "
              f"(rc={out.returncode}); partial results relayed above",
              file=sys.stderr)
        return out.returncode or 1
    # Only device-layer failures degrade to a (stderr-flagged) CPU
    # measurement; a solver bug / failed convergence assert must surface as a
    # failure, not be laundered into a CPU number recorded with exit code 0.
    device_failure = any(
        pat in out.stderr
        for pat in ("UNAVAILABLE", "Unable to initialize backend",
                    "TPU initialization failed", "DEADLINE_EXCEEDED",
                    "remote_compile")
    )
    if device_failure:
        print(f"bench: child hit a device failure (rc={out.returncode}); "
              "falling back to --platform cpu", file=sys.stderr)
        return None
    print(f"bench: child failed (rc={out.returncode}); not a device failure, "
          "propagating", file=sys.stderr)
    return out.returncode or 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int, default=400)
    ap.add_argument("--grid-scale", type=int, default=400_000)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--metric",
                    choices=["all", "vfi", "ks", "ks_large", "ks_fine",
                             "scale", "scale_vfi", "ge", "ge_fused", "sweep",
                             "transition", "transition_fused", "accel",
                             "precision",
                             "pushforward", "egm_fused", "telemetry",
                             "resilience", "mesh2d", "attribution",
                             "observatory", "serve", "amortized",
                             "fleet", "calibration", "analysis"],
                    default="all",
                    help="'all' (default) emits one JSON line per headline "
                         "metric — reference-scale VFI, K-S panel throughput "
                         "(reference + 100k-agent populations), and the "
                         "north-star scale for both solver families — in one "
                         "device session")
    ap.add_argument("--platform", choices=["cpu", "tpu"], default=None,
                    help="force a jax platform (the JAX_PLATFORMS env var is "
                         "overridden by this image's TPU plugin, so use this flag)")
    ap.add_argument("--probe-timeout", type=float, default=None,
                    help="seconds to allow the device child run before falling "
                         "back to CPU (default: 900, or 3600 for the full-size "
                         "scale metric, whose legitimate runtime is minutes)")
    ap.add_argument("--scale-solver", choices=["vfi", "egm"], default="egm",
                    help="household solver for --metric scale (egm: O(na) per "
                         "sweep, the scalable default; vfi: continuous-choice "
                         "VFI, O(na log na) per sweep but gather-bound on TPU)")
    ap.add_argument("--noise-floor-ulp", type=float, default=None,
                    help="EGM stopping-rule noise floor in ulp of max|C| "
                         "(default: 24 on TPU f32, 0 elsewhere; "
                         "solvers/egm.py docstring)")
    ap.add_argument("--pallas-inversion", action="store_true",
                    help="route the scale metric's EGM grid inversion through "
                         "the fused Pallas kernel (egm_kernel='pallas_inverse', "
                         "ops/pallas_inverse.py)")
    ap.add_argument("--accel", action="store_true",
                    help="run the scale metric's EGM ladder stages under "
                         "safeguarded Anderson mixing (ops/accel.py, shipped "
                         "defaults); EGM scale solver only")
    ap.add_argument("--refresh-baseline", action="store_true",
                    help="re-measure the NumPy VFI-400 denominator (7 runs, "
                         "median + spread + machine fingerprint) and freeze it "
                         "into BASELINE.json; run on an IDLE box")
    ap.add_argument("--ledger", default=None,
                    help="append every metric record (plus the run's config "
                         "fingerprint and spans) to a JSONL run ledger "
                         "(diagnostics/ledger.py); render with "
                         "`python -m aiyagari_tpu report <path>`")
    ap.add_argument("--check-history", action="store_true",
                    help="after the battery, diff this run's records "
                         "against the frozen BENCH_r*.json trajectory "
                         "(diagnostics/bench_history.py): structural "
                         "regressions (parities, pins, table sizes, skip "
                         "status) and catastrophic walls are flagged as a "
                         "final bench_history_check record + "
                         "bench_regression ledger events. On by default "
                         "in --preset ci")
    ap.add_argument("--preset", choices=["ci"], default=None,
                    help="'ci': tiny-grid CPU smoke battery (in-process, no "
                         "device child) covering every bench code path that "
                         "has previously broken a round — vfi, the "
                         "multiscale+windowed-inversion scale solve, batched "
                         "GE, the scenario sweep, and the transition solver "
                         "— sized to finish in ~a minute. Invoked by the "
                         "tier-1 smoke test (tests/test_bench_ci.py) so "
                         "bench-breaking regressions like the round-5 OOM "
                         "surface before a bench round does")
    args = ap.parse_args()

    if args.preset == "ci":
        # Tiny grids, forced CPU (in-process: the child/probe machinery is
        # for real device sessions), quick timings. grid_scale=8000 still
        # exercises the grid-sequenced ladder (> LADDER_MIN_FINE) AND the
        # windowed power-grid inversion (> INVERSE_DENSE_CUTOFF) — the code
        # paths behind the round-5 OOM — at ~MB-scale buffers.
        args.platform = args.platform or "cpu"
        args.quick = True
        args.grid = min(args.grid, 100)
        args.grid_scale = min(args.grid_scale, 8000)
        # The bench-history watchdog is part of the ci contract: the
        # battery's own records are diffed against the frozen trajectory
        # before the process exits (tests/test_bench_ci.py gates zero
        # findings).
        args.check_history = True

    if args.metric in ("mesh2d", "observatory") or args.preset == "ci":
        # The mesh2d battery needs a multi-device mesh; on hosts without
        # accelerators this is the 8-virtual-device CPU mesh (SURVEY.md
        # §4.4 — same shardings and collectives as a v5e-8 slice). Must
        # run BEFORE jax initializes its backend; only affects the host
        # CPU platform (a TPU session's chips are untouched). Set here so
        # a re-exec'd device child inherits it through the environment.
        # Scoped to the mesh2d-only invocation and the ci smoke preset
        # (whose tier-1 gates are calibrated under the virtual mesh and
        # never gate walls): a real `--metric all` round instead re-execs
        # the mesh2d leg in its own interpreter (_bench_mesh2d_leg), so
        # every other metric keeps the session's native device topology —
        # an 8-way-split host CPU shrinks per-device thread pools and
        # silently shifts walls against previously frozen records.
        from aiyagari_tpu.parallel.mesh import force_host_device_count

        force_host_device_count(8)

    if args.refresh_baseline:
        # Pure-CPU measurement: never touch the TPU tunnel for this.
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
        entries = refresh_frozen_baseline()
        print(json.dumps({"frozen_denominators": entries}))
        return 0

    if args.probe_timeout is None:
        args.probe_timeout = (3600.0 if (args.metric in ("scale", "all") and not args.quick)
                              else 900.0)

    if args.platform is None and os.environ.get("_AIYAGARI_BENCH_CHILD") != "1":
        # Degrade rather than hang: run the real measurement in a child with
        # a timeout; a CPU fallback (flagged on stderr) is recordable, a
        # wedged benchmark is not.
        rc = _run_in_child(args.probe_timeout)
        if rc is not None:
            return rc
        args.platform = "cpu"

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    import jax

    # AFTER the platform choice: the cache directory is keyed by it (a
    # CPU-forced run must not share AOT artifacts with TPU-attached runs —
    # io_utils/compile_cache.py).
    from aiyagari_tpu.io_utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()

    # Off-TPU the benchmarks run in f64; enable x64 or jnp.float64 silently
    # canonicalizes to f32 (whose ulp at |v|~O(100) sits near the 1e-5 tol).
    if jax.default_backend() != "tpu":
        jax.config.update("jax_enable_x64", True)

    runners = {
        "vfi": lambda: bench_aiyagari_vfi(args.grid, args.quick),
        "ks": lambda: bench_ks_agents(args.quick),
        "ks_large": lambda: bench_ks_agents_large(args.quick),
        "ks_fine": lambda: bench_ks_fine(args.quick),
        "scale": lambda: bench_scale(args.grid_scale, args.quick, args.scale_solver,
                                     args.noise_floor_ulp, args.pallas_inversion,
                                     args.accel),
        "scale_vfi": lambda: bench_scale(args.grid_scale, args.quick, "vfi",
                                         args.noise_floor_ulp, False),
        "ge": lambda: bench_ge_batched(args.quick),
        "ge_fused": lambda: bench_ge_fused(args.quick,
                                           min(args.grid, 100)),
        "sweep": lambda: bench_sweep(args.quick),
        "transition": lambda: bench_transition(args.quick),
        "transition_fused": lambda: bench_transition_fused(args.quick),
        "accel": lambda: bench_accel(args.quick),
        "precision": lambda: bench_precision(args.quick),
        "pushforward": lambda: bench_pushforward(args.quick),
        "egm_fused": lambda: bench_egm_fused(args.quick),
        "telemetry": lambda: bench_telemetry(args.grid, args.quick),
        "resilience": lambda: bench_resilience(args.quick,
                                               min(args.grid, 100)),
        # In-process only when this session WAS topologized for it (the
        # metric-only invocation or the ci smoke preset); a real `all`
        # battery runs the virtual-mesh legs in their own interpreters.
        "mesh2d": (lambda: bench_mesh2d(args.quick))
        if (args.metric == "mesh2d" or args.preset == "ci")
        else (lambda: _bench_virtual_mesh_leg(args, "mesh2d")),
        "attribution": lambda: bench_attribution(args.quick),
        "observatory": (lambda: bench_observatory(args.quick))
        if (args.metric == "observatory" or args.preset == "ci")
        else (lambda: _bench_virtual_mesh_leg(args, "observatory")),
        "serve": lambda: bench_serve(args.quick, min(args.grid, 40)),
        "amortized": lambda: bench_amortized(args.quick,
                                             min(args.grid, 40)),
        "fleet": lambda: bench_fleet(args.quick, min(args.grid, 40)),
        "calibration": lambda: bench_calibration(args.quick,
                                                 min(args.grid, 16)),
        "analysis": lambda: bench_analysis(),
    }
    # 'all' runs the full claimed surface in this one device session (vfi
    # first: it is BASELINE.json's primary metric and must be the first line
    # even if a later, longer metric dies; ks_fine carries the Den Haan
    # accuracy statistic into the artifact; scale_vfi last — the declared
    # north-star metric names VFI, so the artifact measures it at the
    # north-star scale too, not only the EGM carrier).
    if args.preset == "ci":
        # An explicit --metric narrows the ci battery to that one metric
        # (still at ci sizes) instead of being silently ignored.
        # "analysis" last: it audits the same programs the battery just
        # exercised, and a perf metric dying mid-battery should not also
        # cost the static gate its record.
        names = (("vfi", "scale", "ge", "ge_fused", "sweep", "transition",
                  "transition_fused", "accel", "precision", "pushforward",
                  "egm_fused", "telemetry", "resilience", "mesh2d",
                  "attribution", "observatory", "serve", "amortized",
                  "fleet", "calibration", "analysis")
                 if args.metric == "all" else (args.metric,))
    elif args.metric == "all":
        names = ("vfi", "ks", "ks_large", "scale", "ge", "ge_fused",
                 "sweep", "transition", "transition_fused", "accel",
                 "precision", "pushforward", "egm_fused", "telemetry",
                 "resilience", "mesh2d", "attribution", "observatory",
                 "serve", "amortized", "fleet", "calibration", "ks_fine",
                 "scale_vfi")
    else:
        names = (args.metric,)
    led = None
    if args.ledger:
        from aiyagari_tpu.diagnostics.ledger import RunLedger, activate

        led = RunLedger(args.ledger,
                        meta={"entry": "bench", "metric": args.metric,
                              "preset": args.preset or "",
                              "platform": args.platform or "auto"})
    produced: list = []
    history = None
    if getattr(args, "check_history", False):
        # Snapshot the frozen trajectory BEFORE the battery runs: several
        # legs (mesh2d, attribution, observatory) refreeze their own
        # BENCH_r*.json in place, and a watchdog that read the refrozen
        # files afterwards would only ever compare a record against
        # itself — a regression could never be flagged.
        from aiyagari_tpu.diagnostics.bench_history import load_history

        history = load_history(os.path.dirname(os.path.abspath(__file__)))
    for name in names:
        try:
            if led is not None:
                with activate(led):
                    result = runners[name]()
            else:
                result = runners[name]()
        except Exception as e:  # noqa: BLE001 — filtered to OOM below
            # Per-metric OOM guard (ISSUE 2 satellite): an allocation the
            # sizing model did not foresee must cost ONE metric, not the
            # rest of the battery — emit a machine-readable skip record and
            # keep going, exiting 0. Anything that is not an OOM (solver
            # bugs, failed convergence asserts) still propagates loudly.
            msg = f"{type(e).__name__}: {e}"
            is_oom = (isinstance(e, MemoryError)
                      or "RESOURCE_EXHAUSTED" in msg
                      or "Out of memory" in msg)
            if not is_oom:
                raise
            result = {"metric": name, "skipped": "oom", "error": msg[:300]}
        if led is not None:
            led.metric(result)
        produced.append(result)
        print(json.dumps(result), flush=True)

    if history is not None:
        # The bench-history watchdog (ISSUE 14 satellite): diff what this
        # battery just produced against the trajectory as it stood BEFORE
        # this run — any finding is a real structural drift from the last
        # frozen round (or a catastrophic wall).
        from aiyagari_tpu.diagnostics.bench_history import check_records

        findings, matched = check_records(produced, history=history)
        hist_rec = {
            "metric": "bench_history_check",
            "value": float(len(findings)),
            "unit": "findings",
            "structural_findings": sum(
                1 for f in findings if f["severity"] == "structural"),
            "matched_metrics": matched,
            "history_metrics": len(history),
            "findings": findings,
        }
        if led is not None:
            for f in findings:
                led.event("bench_regression", **f)
            led.metric(hist_rec)
        print(json.dumps(hist_rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
