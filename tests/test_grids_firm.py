"""Grid-builder and firm-block unit tests (SURVEY.md §4.1)."""

import numpy as np

from aiyagari_tpu.config import AiyagariConfig, KrusellSmithConfig
from aiyagari_tpu.utils.firm import capital_demand, ks_price_tables, r_from_K, w_from_K, wage_from_r
from aiyagari_tpu.utils.grids import (
    aiyagari_asset_bounds,
    aiyagari_asset_grid,
    ks_K_grid,
    ks_k_grid,
    power_grid,
)


class TestGrids:
    def test_power_grid_endpoints_and_density(self):
        g = power_grid(0.0, 10.0, 100, 2.0)
        assert g[0] == 0.0 and g[-1] == 10.0
        # Quadratic spacing: increments increase monotonically.
        assert (np.diff(np.diff(g)) > -1e-12).all()

    def test_aiyagari_bounds_formulas(self):
        # amin = min(b, wmin*s_min) = 0 with b=0; amax from kmax = delta^(1/(alpha-1))
        # (Aiyagari_VFI.m:53-56).
        cfg = AiyagariConfig()
        amin, amax = aiyagari_asset_bounds(cfg)
        alpha, delta = cfg.technology.alpha, cfg.technology.delta
        kmax = delta ** (1 / (alpha - 1))
        assert amin == 0.0
        np.testing.assert_allclose(amax, kmax**alpha + (1 - delta) * kmax)

    def test_aiyagari_grid_matches_reference_formula(self):
        cfg = AiyagariConfig()
        g = aiyagari_asset_grid(cfg)
        amin, amax = aiyagari_asset_bounds(cfg)
        want = amin + (amax - amin) * np.linspace(0, 1, 400) ** 2
        np.testing.assert_allclose(g, want, atol=1e-12)

    def test_ks_grids(self):
        cfg = KrusellSmithConfig()
        k = ks_k_grid(cfg)
        K = ks_K_grid(cfg)
        assert k[0] == cfg.k_min and k[-1] == cfg.k_max and len(k) == 100
        np.testing.assert_allclose(K, [30.0, 36.0 + 2.0 / 3.0, 43.0 + 1.0 / 3.0, 50.0])


class TestFirm:
    def test_price_duals_invert(self):
        # w(r) via r->K/L ratio: r = alpha (K/L)^(alpha-1) and
        # w = (1-alpha)(K/L)^alpha must be consistent.
        alpha, delta = 0.36, 0.08
        r = 0.03
        k_over_l = (alpha / (r + delta)) ** (1 / (1 - alpha))
        w = wage_from_r(r, alpha, delta)
        np.testing.assert_allclose(w, (1 - alpha) * k_over_l**alpha, rtol=1e-12)
        # And the marginal products at that ratio reproduce (r+delta, w).
        np.testing.assert_allclose(r_from_K(k_over_l, 1.0, 1.0, alpha), r + delta, rtol=1e-12)
        np.testing.assert_allclose(w_from_K(k_over_l, 1.0, 1.0, alpha), w, rtol=1e-12)

    def test_capital_demand_downward_sloping(self):
        rs = np.linspace(-0.02, 0.04, 20)
        kd = capital_demand(rs, 1.0, 0.36, 0.08)
        assert (np.diff(kd) < 0).all()

    def test_ks_price_tables_shape_and_values(self):
        cfg = KrusellSmithConfig()
        z = np.array([1.01, 0.99, 1.01, 0.99])
        L = np.array([cfg.l_bar * 0.96, cfg.l_bar * 0.90] * 2)
        K = ks_K_grid(cfg)
        w, r = ks_price_tables(z, L, K, cfg.technology.alpha)
        assert w.shape == (4, 4) and r.shape == (4, 4)
        # Spot check one cell against the scalar formula (Krusell_Smith_VFI.m:113-114).
        np.testing.assert_allclose(
            r[0, 0], 0.36 * 1.01 * K[0] ** (0.36 - 1) * L[0] ** (1 - 0.36), rtol=1e-12
        )
        # Wage increasing in K, interest decreasing in K.
        assert (np.diff(w, axis=1) > 0).all() and (np.diff(r, axis=1) < 0).all()
