"""Tier-1 gates for the route observatory's measurement half (ISSUE 12):
the modeled-vs-compiled attribution table (analysis/attribution.py) —
XLA's cost_analysis()/memory_analysis() of every registry program joined
against the roofline price — its fusion-regression flag, and the
observability surface (ledger events, gauges, the report CLI rendering).
"""

import jax
import jax.numpy as jnp
import pytest

from aiyagari_tpu.analysis.attribution import (
    DEFAULT_FLAG_RATIO,
    attribute_program,
    modeled_cost,
    run_attribution,
)
from aiyagari_tpu.analysis.registry import ProgramSpec
from aiyagari_tpu.diagnostics import metrics
from aiyagari_tpu.diagnostics.ledger import RunLedger, activate, read_ledger

# Programs whose compiled artifact is the production artifact on this CPU
# host AND carry an analytic model — the fusion-regression band is gated
# on exactly these (the interpreted Pallas programs and the mesh-padded
# sharded sweep are joined but band-exempt by design).
GATED = ("egm/sweep", "egm/sweep_f32_stage", "egm/sweep_sentinel",
         "egm/sweep_labor", "vfi/step", "distribution/step_scatter",
         "distribution/step_transpose", "distribution/step_banded",
         "distribution/stationary")


@pytest.fixture(scope="module")
def report():
    return run_attribution()


class TestAttributionTable:
    def test_covers_the_registry(self, report):
        # Tier-1 runs on the 8-virtual-device mesh, so even the sharded
        # sweep compiles; >= 10 is the ISSUE 12 acceptance floor.
        assert len(report.records) >= 13
        names = {r["program"] for r in report.records}
        assert set(GATED) <= names
        assert "egm/sweep_fused" in names

    def test_compiled_numbers_present(self, report):
        for rec in report.records:
            assert rec["compiled"]["bytes_accessed"] > 0, rec
            assert rec["compiled"]["flops"] > 0, rec
            assert rec["compiled"]["peak_bytes"] > 0, rec

    def test_gated_programs_modeled_and_in_band(self, report):
        by = report.by_program()
        for name in GATED:
            rec = by[name]
            assert rec["modeled"] is not None, name
            assert rec["modeled"]["hbm_bytes"] > 0, name
            # Compiled bytes sit in the normal padding/remat band above
            # the analytic lower bound — the shipped tree measures
            # 1.7-8.5x at the registry shapes; a fusion regression lands
            # at 10-100x (DEFAULT_FLAG_RATIO).
            assert 0.5 <= rec["byte_ratio"] <= 20.0, (name, rec)
            assert rec["flagged"] is False, (name, rec)

    def test_interpreted_and_sharded_programs_never_flag(self, report):
        by = report.by_program()
        for name in ("egm/sweep_fused", "egm/sweep_fused_f32_stage",
                     "egm/sweep_sharded"):
            rec = by[name]
            # Joined (the compiled numbers are real) ...
            assert rec["compiled"]["bytes_accessed"] > 0
            # ... but exempt from the band: the off-TPU artifact is the
            # Pallas interpreter / the mesh-padded replica, not the
            # production kernel the model prices.
            assert rec["flagged"] is False, (name, rec)

    def test_unmodeled_composites_join_without_ratios(self, report):
        by = report.by_program()
        for name in ("equilibrium/ge_round_batched", "transition/round",
                     "ks/distribution_step"):
            rec = by[name]
            assert rec["modeled"] is None
            assert rec["byte_ratio"] is None
            assert rec["flagged"] is False

    def test_modeled_cost_helper_matches_roofline(self):
        from aiyagari_tpu.diagnostics.roofline import egm_sweep_cost

        cost = modeled_cost("egm/sweep")
        assert cost.hbm_bytes == egm_sweep_cost(3, 16, 8).hbm_bytes
        assert modeled_cost("transition/round") is None


class TestFusionRegressionFlag:
    def test_defused_program_trips_the_flag(self):
        """The oracle actually fires: a 'distribution/step_scatter' whose
        chain materializes a large broadcast (the compiler now streams
        bytes the model assumed fused away) must flag."""
        from aiyagari_tpu.sim.distribution import distribution_step

        def defused(mu, idx, w_lo, P):
            out = distribution_step(mu, idx, w_lo, P, backend="scatter")
            # A broadcast forced across a fusion barrier (dot operands
            # must materialize): ~3 MB of compiled traffic against a
            # ~3 KB model price -> ratio far past the flag threshold.
            big = jnp.broadcast_to(mu.reshape(-1)[None, :], (600, 48))
            z = jnp.dot(big, big.T)
            return out + jnp.tanh(jnp.sum(z)) * 1e-30

        spec = ProgramSpec(
            name="distribution/step_scatter", family="fixture",
            build_off=lambda: (defused, (
                jax.ShapeDtypeStruct((3, 16), jnp.float64),
                jax.ShapeDtypeStruct((3, 16), jnp.int32),
                jax.ShapeDtypeStruct((3, 16), jnp.float64),
                jax.ShapeDtypeStruct((3, 3), jnp.float64))))
        rec = attribute_program(spec)
        assert rec["byte_ratio"] > DEFAULT_FLAG_RATIO, rec
        assert rec["flagged"] is True


class TestObservability:
    def test_ledger_events_and_gauges(self, tmp_path):
        metrics.reset()
        led = RunLedger(tmp_path / "led.jsonl")
        with activate(led):
            rep = run_attribution(families=("distribution",))
        events = [e for e in read_ledger(led.path)
                  if e["kind"] == "attribution"]
        # 4 push-forward routes + the ISSUE 17 distribution/adjoint
        # backward-pass program.
        assert len(events) == len(rep.records) == 5
        for ev in events:
            assert ev["compiled"]["bytes_accessed"] > 0
            assert ev["flagged"] is False
        gauges = {(g["name"], g["labels"].get("program")): g["value"]
                  for g in metrics.render_json()["gauges"]}
        assert gauges[("aiyagari_attribution_compiled_bytes",
                       "distribution/step_scatter")] > 0
        assert gauges[("aiyagari_attribution_byte_ratio",
                       "distribution/step_transpose")] > 0

    def test_report_cli_renders_observatory_events(self, tmp_path, capsys):
        """The drive-by satellite: `python -m aiyagari_tpu report` renders
        route_decision / attribution / analysis / tuning_probe events as
        formatted rows instead of the generic key=value fallback."""
        from aiyagari_tpu.diagnostics.health import report_main

        led = RunLedger(tmp_path / "led.jsonl")
        led.event("route_decision", knob="pushforward", choice="scatter",
                  source="measured", bucket="b512", dtype="float64",
                  evidence={"walls_us": {"scatter": 1.5, "transpose": 3.0}})
        led.event("route_decision", knob="egm_kernel", choice="xla",
                  source="default", bucket="any", dtype="any", evidence={})
        led.event("attribution", program="egm/sweep", family="egm",
                  compiled={"bytes_accessed": 21115.0},
                  modeled={"hbm_bytes": 3840.0}, byte_ratio=5.5,
                  flop_ratio=2.4, flagged=False)
        led.event("attribution", program="egm/bad", family="egm",
                  compiled={"bytes_accessed": 999999.0},
                  modeled={"hbm_bytes": 100.0}, byte_ratio=9999.99,
                  flagged=True)
        led.event("analysis", findings=0, rules={}, programs_audited=15,
                  programs_skipped=[], files_linted=83, wall_seconds=2.0)
        led.event("tuning_probe", knob="bucket_index", choice="scan",
                  walls_us={"scan": 10.0, "sort": 20.0}, na=512,
                  dtype="float64")
        rc = report_main([str(led.path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "route pushforward -> scatter [measured, b512/float64]" in out
        assert "scatter=1.5us" in out
        assert "route egm_kernel -> xla [default, any/any] shipped default" \
            in out
        assert "attribution egm/sweep: compiled 21115.0 B vs modeled " \
               "3840.0 B (x5.5)" in out
        assert "FUSION-REGRESSION FLAG" in out
        assert "analysis: 0 active finding(s) over 15 program(s)" in out
        assert "probe bucket_index -> scan" in out
