"""Roofline cost-model accounting (diagnostics/roofline.py, SURVEY.md §6)."""

import pytest

from aiyagari_tpu.diagnostics.roofline import (
    CHIP_PEAKS,
    KernelCost,
    egm_sweep_cost,
    panel_step_cost,
    utilization,
    vfi_sweep_cost,
)


class TestKernelCosts:
    def test_vfi_sweep_counts(self):
        c = vfi_sweep_cost(7, 400, 4)
        assert c.mxu_flops == 2 * 7 * 7 * 400
        assert c.vpu_ops == 3 * 7 * 400 * 400
        assert c.hbm_bytes == 4 * (7 * 400 * 400 + 4 * 7 * 400)

    def test_egm_routes_split_at_dense_cutoff(self):
        dense = egm_sweep_cost(7, 4096, 4)
        windowed = egm_sweep_cost(7, 4097, 4)
        # Dense route is quadratic in na; windowed is linear with a 3*L
        # constant — at the cutoff boundary dense is the bigger count.
        assert dense.vpu_ops > windowed.vpu_ops * 0.25
        assert egm_sweep_cost(7, 400_000, 4).vpu_ops < egm_sweep_cost(
            7, 400_000, 4, windowed=False).vpu_ops

    def test_windowed_scaling_is_near_linear(self):
        # Dominant 3*L*na term is linear in na; the level-1 block locate
        # (na^2/qblock) adds a sub-10% superlinear correction at these sizes.
        a = egm_sweep_cost(7, 100_000, 4)
        b = egm_sweep_cost(7, 400_000, 4)
        assert b.vpu_ops == pytest.approx(4 * a.vpu_ops, rel=0.10)

    def test_cost_algebra(self):
        c = panel_step_cost(10_000)
        s = 3 * c + c
        assert s.mxu_flops == 4 * c.mxu_flops
        assert s.hbm_bytes == 4 * c.hbm_bytes

    def test_vfi_slab_counts(self):
        # Round-5 model for the slab VFI (the scale_vfi artifact's
        # utilization source): cells = N * ceil(na/sq) * sq * (kb*mw);
        # improvement ~16 ops/cell, evaluation ~3 ops/cell + the u_pol
        # add; slab DMA is mw*kb cells per sq queries.
        from aiyagari_tpu.diagnostics.roofline import vfi_slab_cost

        N, na = 7, 1024
        cells = N * 4 * 256 * 1536          # ceil(1024/256)=4 blocks
        imp = vfi_slab_cost(N, na, 4, improve_rounds=1, eval_sweeps=0)
        ev = vfi_slab_cost(N, na, 4, improve_rounds=0, eval_sweeps=1)
        assert imp.vpu_ops == 16 * cells
        assert ev.vpu_ops == 3 * cells + N * na
        assert imp.mxu_flops == ev.mxu_flops == 2 * N * N * na
        assert imp.hbm_bytes == 4 * (N * 4 * 1536 + 8 * N * na)
        # Linearity in the two counters (the bench multiplies by the
        # solver-reported rounds/sweeps).
        both = vfi_slab_cost(N, na, 4, improve_rounds=2, eval_sweeps=5)
        assert both.vpu_ops == 2 * imp.vpu_ops + 5 * ev.vpu_ops
        # The slab VFI is VPU-bound under this model at any plausible wall
        # (the scale_vfi row's "bound": "vpu").
        u = utilization(1.0, both, "tpu")
        assert u["bound"] == "vpu"


class TestUtilization:
    def test_fractions_against_documented_peaks(self):
        cost = KernelCost(mxu_flops=0.985e12, vpu_ops=6.8e10, hbm_bytes=8.19e6)
        u = utilization(0.01, cost, "tpu")
        peaks = CHIP_PEAKS["tpu"]
        assert u["mfu"] == pytest.approx(
            (cost.mxu_flops + cost.vpu_ops) / (0.01 * peaks.matmul_flops), abs=1e-3)
        assert u["vpu_frac"] == pytest.approx(1.0, abs=1e-3)   # 6.8e10 in 10 ms = VPU peak
        assert u["membw_frac"] == pytest.approx(0.001, abs=1e-4)
        assert u["bound"] == "vpu"

    def test_unknown_platform_yields_nulls(self):
        u = utilization(1.0, vfi_sweep_cost(7, 400), "cpu")
        assert u == {"mfu": None, "vpu_frac": None, "membw_frac": None, "bound": None}

    def test_bound_picks_the_saturated_resource(self):
        hbm_heavy = KernelCost(mxu_flops=1.0, vpu_ops=1.0, hbm_bytes=8.19e11)
        assert utilization(1.0, hbm_heavy, "tpu")["bound"] == "hbm"
        mxu_heavy = KernelCost(mxu_flops=1.97e14, vpu_ops=1.0, hbm_bytes=1.0)
        assert utilization(1.0, mxu_heavy, "tpu")["bound"] == "mxu"
