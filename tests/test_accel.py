"""Tests for the shared fixed-point acceleration layer (ops/accel.py) and its
wiring through the EGM solvers, the stationary distribution, and the KS ALM.

What these pin, in order of importance:
  1. PARITY: every accelerated route (EGM, labor EGM, sharded EGM,
     stationary distribution, ALM host step) reaches the same fixed point
     as the plain route within the stopping rule's certified error band —
     acceleration changes the trajectory, never the answer;
  2. the accelerated solves actually use FEWER sweeps (the whole point; the
     bench ci battery asserts the same so regressions fail tier-1);
  3. simplex invariants: an Anderson-extrapolated distribution iterate is
     re-projected (nonnegative, unit mass) at every step, not just at exit;
  4. the safeguard: on an adversarial map whose residual jumps, the
     plain-step fallback engages (AccelState.trips > 0) and the solve still
     converges instead of diverging.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.config import AccelConfig, SolverConfig
from aiyagari_tpu.models.aiyagari import aiyagari_labor_preset, aiyagari_preset
from aiyagari_tpu.ops.accel import (
    accel_init,
    accel_step,
    fixed_point_iterate,
    host_anderson_step,
    project_floor,
    project_simplex,
)
from aiyagari_tpu.sim.distribution import (
    distribution_step,
    stationary_distribution,
    young_lottery,
)
from aiyagari_tpu.solvers.egm import (
    initial_consumption_guess,
    solve_aiyagari_egm,
    solve_aiyagari_egm_labor,
)
from aiyagari_tpu.utils.firm import wage_from_r

R_TEST = 0.04
ANDERSON = AccelConfig(method="anderson")
SQUAREM = AccelConfig(method="squarem")


def _egm_problem(n=200):
    m = aiyagari_preset(grid_size=n)
    w = float(wage_from_r(R_TEST, m.config.technology.alpha,
                          m.config.technology.delta))
    C0 = initial_consumption_guess(m.a_grid, m.s, R_TEST, w)
    kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
              tol=1e-5, max_iter=1000)
    return m, w, C0, kw


class TestAccelCore:
    """The carry transformer on synthetic maps, where the answer is exact."""

    def _linear_map(self, n=40, rho_max=0.96, seed=0):
        rng = np.random.default_rng(seed)
        Q = np.linalg.qr(rng.standard_normal((n, n)))[0]
        lam = rng.uniform(0.4, rho_max, n)
        A = jnp.asarray(Q @ np.diag(lam) @ Q.T)
        b = jnp.asarray(rng.standard_normal(n))
        x_star = jnp.asarray(np.linalg.solve(np.eye(n) - np.asarray(A),
                                             np.asarray(b)))
        return (lambda x: A @ x + b), x_star

    @pytest.mark.parametrize("accel", [ANDERSON, SQUAREM],
                             ids=["anderson", "squarem"])
    def test_linear_map_same_fixed_point_fewer_iters(self, accel):
        F, x_star = self._linear_map()
        x0 = jnp.zeros_like(x_star)
        _, it_plain, _, _ = fixed_point_iterate(F, x0, tol=1e-10,
                                                max_iter=2000)
        x, it_acc, dist, _ = fixed_point_iterate(F, x0, accel=accel,
                                                 tol=1e-10, max_iter=2000)
        assert float(dist) < 1e-10
        # Residual < tol certifies |x - x*| <= tol / (1 - rho_max).
        np.testing.assert_allclose(np.asarray(x), np.asarray(x_star),
                                   atol=1e-8)
        assert int(it_acc) < int(it_plain) / 2

    def test_delay_takes_plain_steps_and_records_nothing(self):
        F, _ = self._linear_map()
        accel = AccelConfig(delay=4)
        x = jnp.zeros(40)
        st = accel_init(x, accel)
        for k in range(4):
            gx = F(x)
            x_next, st = accel_step(st, x, gx, accel=accel)
            np.testing.assert_array_equal(np.asarray(x_next), np.asarray(gx))
            assert int(st.count) == 0 and int(st.trips) == 0
            x = x_next
        # First post-delay call starts recording (still a plain step — the
        # window is empty) and the one after can extrapolate.
        x_next, st = accel_step(st, x, F(x), accel=accel)
        assert int(st.count) == 1

    def test_safeguard_residual_increase_falls_back_to_plain(self):
        # Manufactured state: pretend the previous proposal drove the
        # residual way down (prev_res tiny), so this call's residual is a
        # huge increase -> the step MUST be the plain damped image and the
        # history must restart to the current pair only.
        accel = AccelConfig(delay=0, memory=3)
        x = jnp.asarray(np.linspace(1.0, 2.0, 8))
        gx = x + 0.5
        st = accel_init(x, accel)
        # Build two history entries so an extrapolation would be available.
        _, st = accel_step(st, x, gx, accel=accel)
        _, st = accel_step(st, x + 0.1, gx + 0.1, accel=accel)
        assert int(st.count) == 2
        st = dataclasses.replace(st, prev_res=jnp.asarray(1e-12))
        trips_before = int(st.trips)
        x_next, st = accel_step(st, x, gx, accel=accel)
        np.testing.assert_allclose(np.asarray(x_next), np.asarray(gx),
                                   rtol=0, atol=0)
        assert int(st.trips) == trips_before + 1
        assert int(st.count) == 1          # history restarted

    def test_safeguard_nonfinite_extrapolation_falls_back(self):
        # Poisoned history -> non-finite proposal; the step must still be
        # the finite plain image.
        accel = AccelConfig(delay=0, memory=2)
        x = jnp.ones(6)
        gx = x + 0.1
        st = accel_init(x, accel)
        _, st = accel_step(st, x, gx, accel=accel)
        st = dataclasses.replace(
            st, hist_g=st.hist_g.at[0].set(jnp.inf), prev_res=jnp.inf)
        x_next, st = accel_step(st, x, gx, accel=accel)
        assert bool(jnp.all(jnp.isfinite(x_next)))
        np.testing.assert_allclose(np.asarray(x_next), np.asarray(gx))

    def test_adversarial_cycle_trips_safeguard_and_still_converges(self):
        # The real EGM operator under a strict no-growth safeguard: its
        # kinked early trajectory makes Anderson's residual genuinely
        # non-monotone (extrapolation -> residual bump -> the plain-step
        # fallback + history restart MUST engage), and the safeguarded
        # solve must still converge rather than cycle or diverge.
        m, w, C0, kw = _egm_problem(100)
        accel = AccelConfig(delay=0, memory=5, safeguard_growth=1.0)
        sol = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                                 accel=accel, **kw)
        assert float(sol.distance) < kw["tol"]

        # Re-drive the identical loop by hand to read the trip counter
        # (the solver's carry drops the accel state on exit).
        from aiyagari_tpu.ops.egm import egm_step

        proj = project_floor()
        C, st = C0, accel_init(C0, accel)
        for _ in range(kw["max_iter"]):
            C_new, _ = egm_step(C, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                                sigma=kw["sigma"], beta=kw["beta"])
            if float(jnp.max(jnp.abs(C_new - C))) < kw["tol"]:
                break
            C, st = accel_step(st, C, C_new, accel=accel, project=proj)
        assert int(st.trips) >= 1

    def test_project_simplex_clips_and_renormalizes(self):
        x = jnp.asarray([[0.5, -0.2], [0.4, 0.3]])
        p = project_simplex(x)
        assert float(p.min()) >= 0.0
        assert float(p.sum()) == pytest.approx(1.0, abs=1e-12)
        np.testing.assert_allclose(np.asarray(p),
                                   np.asarray([[0.5, 0.0], [0.4, 0.3]]) / 1.2)

    def test_project_floor_preserves_interior_values(self):
        proj = project_floor()
        x = jnp.asarray([100.0, 0.01, -5.0])
        p = proj(x)
        assert float(p[0]) == 100.0 and float(p[1]) == 0.01
        assert float(p[2]) > 0.0

    @pytest.mark.parametrize("bad", [
        AccelConfig(method="nope"), AccelConfig(memory=0),
        AccelConfig(damping=0.0), AccelConfig(damping=1.5),
        AccelConfig(regularization=-1.0), AccelConfig(delay=-1),
        AccelConfig(safeguard_growth=0.5),
        AccelConfig(method="squarem", damping=0.5),
    ])
    def test_validation_rejects_bad_configs(self, bad):
        with pytest.raises(ValueError):
            accel_init(jnp.zeros(3), bad)


class TestEGMParity:
    @pytest.mark.parametrize("accel", [ANDERSON, SQUAREM],
                             ids=["anderson", "squarem"])
    def test_accelerated_matches_plain_within_tolerance_band(self, accel):
        m, w, C0, kw = _egm_problem()
        plain = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, R_TEST, w,
                                   m.amin, **kw)
        sol = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                                 accel=accel, **kw)
        assert float(sol.distance) < kw["tol"]
        # Both satisfy |F(C)-C| < tol, so each sits within tol/(1-beta)
        # (= 25*tol at beta=.96) of the unique fixed point.
        band = 2 * kw["tol"] / (1.0 - m.preferences.beta)
        np.testing.assert_allclose(np.asarray(sol.policy_c),
                                   np.asarray(plain.policy_c), atol=band)
        np.testing.assert_allclose(np.asarray(sol.policy_k),
                                   np.asarray(plain.policy_k), atol=band)
        assert int(sol.iterations) < int(plain.iterations)

    def test_anderson_at_least_halves_egm_sweeps(self):
        # The ISSUE 3 acceptance target on the reference calibration: >= 2x
        # fewer EGM sweeps at default tolerances (bench.py --metric accel
        # records the same pair; this pins it in tier-1).
        m, w, C0, kw = _egm_problem(400)
        plain = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, R_TEST, w,
                                   m.amin, **kw)
        sol = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                                 accel=ANDERSON, **kw)
        assert int(sol.iterations) * 2 <= int(plain.iterations)

    @pytest.mark.parametrize("accel", [ANDERSON, SQUAREM],
                             ids=["anderson", "squarem"])
    def test_labor_family_parity(self, accel):
        m = aiyagari_labor_preset(grid_size=150)
        w = float(wage_from_r(R_TEST, m.config.technology.alpha,
                              m.config.technology.delta))
        C0 = initial_consumption_guess(m.a_grid, m.s, R_TEST, w)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  psi=m.preferences.psi, eta=m.preferences.eta,
                  tol=1e-5, max_iter=1000)
        plain = solve_aiyagari_egm_labor(C0, m.a_grid, m.s, m.P, R_TEST, w,
                                         m.amin, **kw)
        sol = solve_aiyagari_egm_labor(C0, m.a_grid, m.s, m.P, R_TEST, w,
                                       m.amin, accel=accel, **kw)
        assert float(sol.distance) < kw["tol"]
        band = 2 * kw["tol"] / (1.0 - m.preferences.beta)
        for a, b in [(sol.policy_c, plain.policy_c),
                     (sol.policy_k, plain.policy_k),
                     (sol.policy_l, plain.policy_l)]:
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=band)
        assert int(sol.iterations) < int(plain.iterations)

    @pytest.mark.slow  # ~9 s: the multiscale+accel wiring contract; the
    # plain accel parity bands stay tier-1 here and the multiscale ladder's
    # own mechanics in test_precision_ladder.
    def test_multiscale_ladder_accepts_accel(self):
        from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_multiscale

        m, w, _, kw = _egm_problem(2000)
        plain = solve_aiyagari_egm_multiscale(
            m.a_grid, m.s, m.P, R_TEST, w, m.amin,
            grid_power=float(m.config.grid.power), **kw)
        sol = solve_aiyagari_egm_multiscale(
            m.a_grid, m.s, m.P, R_TEST, w, m.amin,
            grid_power=float(m.config.grid.power), accel=ANDERSON, **kw)
        assert float(sol.distance) < kw["tol"] and not bool(sol.escaped)
        band = 2 * kw["tol"] / (1.0 - m.preferences.beta)
        np.testing.assert_allclose(np.asarray(sol.policy_c),
                                   np.asarray(plain.policy_c), atol=band)


class TestShardedParity:
    @pytest.mark.slow  # ~22 s: the labor variant below pins the same
    # psum'd-normal-equations/pmax'd-safeguard sharded composition tier-1
    # (strictly more machinery), and the unsharded accel parity stays in
    # TestEGMParity.
    def test_sharded_accelerated_trajectory_matches_single_device(self):
        # Iterate-by-iterate equality of the ACCELERATED trajectory: the
        # psum'd normal equations/pmax'd safeguards must reproduce the
        # single-device extrapolation up to matmul reassociation (same
        # bound as the plain sharded route's pin).
        from aiyagari_tpu.parallel.mesh import make_mesh
        from aiyagari_tpu.solvers.egm_sharded import solve_aiyagari_egm_sharded

        n = 8_192
        m = aiyagari_preset(grid_size=n)
        w = float(wage_from_r(R_TEST, m.config.technology.alpha,
                              m.config.technology.delta))
        C0 = initial_consumption_guess(m.a_grid, m.s, R_TEST, w)
        accel = AccelConfig(delay=2, memory=3)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  tol=1e-30, max_iter=8, accel=accel,
                  grid_power=float(m.config.grid.power))
        ref = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                                 **kw)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_sharded(mesh, C0, m.a_grid, m.s, m.P,
                                         R_TEST, w, m.amin, **kw)
        assert int(sol.iterations) == int(ref.iterations) == 8
        assert not bool(sol.escaped)
        np.testing.assert_allclose(np.asarray(sol.policy_c),
                                   np.asarray(ref.policy_c), atol=1e-9)

    def test_sharded_labor_accelerated_trajectory_matches_single_device(self):
        # Bounded-sweep trajectory equality for the LABOR family's sharded
        # acceleration (per-sweep agreement pins the composition as hard as
        # full convergence; the converged variant is the slow test below).
        from aiyagari_tpu.parallel.mesh import make_mesh
        from aiyagari_tpu.solvers.egm_sharded import (
            solve_aiyagari_egm_labor_sharded,
        )

        n = 4_096
        m = aiyagari_labor_preset(grid_size=n)
        w = float(wage_from_r(R_TEST, m.config.technology.alpha,
                              m.config.technology.delta))
        C0 = initial_consumption_guess(m.a_grid, m.s, R_TEST, w)
        accel = AccelConfig(delay=2, memory=3)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  psi=m.preferences.psi, eta=m.preferences.eta,
                  tol=1e-30, max_iter=8, accel=accel,
                  grid_power=float(m.config.grid.power))
        ref = solve_aiyagari_egm_labor(C0, m.a_grid, m.s, m.P, R_TEST, w,
                                       m.amin, **kw)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_labor_sharded(mesh, C0, m.a_grid, m.s, m.P,
                                               R_TEST, w, m.amin, **kw)
        assert int(sol.iterations) == int(ref.iterations) == 8
        assert not bool(sol.escaped)
        np.testing.assert_allclose(np.asarray(sol.policy_c),
                                   np.asarray(ref.policy_c), atol=1e-9)
        np.testing.assert_allclose(np.asarray(sol.policy_l),
                                   np.asarray(ref.policy_l), atol=1e-9)

    @pytest.mark.slow
    def test_sharded_labor_accelerated_converges_to_plain_fixed_point(self):
        from aiyagari_tpu.parallel.mesh import make_mesh
        from aiyagari_tpu.solvers.egm_sharded import (
            solve_aiyagari_egm_labor_sharded,
        )

        n = 4_096
        m = aiyagari_labor_preset(grid_size=n)
        w = float(wage_from_r(R_TEST, m.config.technology.alpha,
                              m.config.technology.delta))
        C0 = initial_consumption_guess(m.a_grid, m.s, R_TEST, w)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  psi=m.preferences.psi, eta=m.preferences.eta,
                  tol=1e-5, max_iter=1000,
                  grid_power=float(m.config.grid.power))
        plain = solve_aiyagari_egm_labor(C0, m.a_grid, m.s, m.P, R_TEST, w,
                                         m.amin, **kw)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_labor_sharded(mesh, C0, m.a_grid, m.s, m.P,
                                               R_TEST, w, m.amin,
                                               accel=ANDERSON, **kw)
        assert not bool(sol.escaped)
        assert float(sol.distance) < kw["tol"]
        assert int(sol.iterations) < int(plain.iterations)
        band = 2 * kw["tol"] / (1.0 - m.preferences.beta)
        np.testing.assert_allclose(np.asarray(sol.policy_c),
                                   np.asarray(plain.policy_c), atol=band)


class TestDistributionAcceleration:
    @pytest.fixture(scope="class")
    def policies(self):
        m, w, C0, kw = _egm_problem(200)
        sol = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                                 **kw)
        return m, sol

    @pytest.mark.parametrize("accel", [ANDERSON, SQUAREM],
                             ids=["anderson", "squarem"])
    def test_parity_and_fewer_sweeps(self, policies, accel):
        m, sol = policies
        plain = stationary_distribution(sol.policy_k, m.a_grid, m.P)
        fast = stationary_distribution(sol.policy_k, m.a_grid, m.P,
                                       accel=accel)
        assert float(fast.distance) < 1e-10
        assert int(fast.iterations) < int(plain.iterations)
        np.testing.assert_allclose(np.asarray(fast.mu), np.asarray(plain.mu),
                                   atol=1e-7)

    def test_anderson_at_least_three_times_fewer_distribution_sweeps(self, policies):
        # The ISSUE 3 acceptance target: >= 3x fewer stationary-distribution
        # sweeps at the default tol 1e-10.
        m, sol = policies
        plain = stationary_distribution(sol.policy_k, m.a_grid, m.P)
        fast = stationary_distribution(sol.policy_k, m.a_grid, m.P,
                                       accel=ANDERSON)
        assert int(fast.iterations) * 3 <= int(plain.iterations)

    def test_simplex_invariants_at_exit(self, policies):
        m, sol = policies
        fast = stationary_distribution(sol.policy_k, m.a_grid, m.P,
                                       accel=ANDERSON)
        assert float(fast.mu.min()) >= 0.0
        assert float(fast.mu.sum()) == pytest.approx(1.0, abs=1e-10)

    def test_simplex_invariants_on_every_carried_iterate(self, policies):
        # Drive the accelerated loop by hand and check EVERY iterate the
        # carry holds is a distribution — the projection is per-step, not a
        # final cleanup.
        m, sol = policies
        idx, w_lo = young_lottery(sol.policy_k, m.a_grid)
        N, na = sol.policy_k.shape
        mu = jnp.full((N, na), 1.0 / (N * na))
        accel = AccelConfig(delay=0, memory=3)
        st = accel_init(mu, accel)
        for _ in range(60):
            mu_new = distribution_step(mu, idx, w_lo, m.P)
            mu_new = mu_new / jnp.sum(mu_new)
            mu, st = accel_step(st, mu, mu_new, accel=accel,
                                project=project_simplex)
            assert float(mu.min()) >= 0.0
            assert float(mu.sum()) == pytest.approx(1.0, rel=1e-12)

    def test_traced_tol_and_max_iter_do_not_recompile(self, policies):
        # The satellite fix: tol/max_iter used to be jit static args, so a
        # tolerance sweep recompiled the whole program per value. They are
        # now traced operands of the while_loop cond.
        m, sol = policies
        base = stationary_distribution._cache_size()
        stationary_distribution(sol.policy_k, m.a_grid, m.P, tol=1e-6,
                                max_iter=10_000)
        after_first = stationary_distribution._cache_size()
        stationary_distribution(sol.policy_k, m.a_grid, m.P, tol=1e-8,
                                max_iter=5_000)
        stationary_distribution(sol.policy_k, m.a_grid, m.P, tol=3e-7,
                                max_iter=7_777)
        assert stationary_distribution._cache_size() == after_first
        assert after_first <= base + 1

    def test_warm_start_still_accepted(self, policies):
        m, sol = policies
        first = stationary_distribution(sol.policy_k, m.a_grid, m.P,
                                        accel=ANDERSON)
        again = stationary_distribution(sol.policy_k, m.a_grid, m.P,
                                        mu_init=first.mu, accel=ANDERSON)
        assert int(again.iterations) <= int(first.iterations)
        np.testing.assert_allclose(np.asarray(again.mu),
                                   np.asarray(first.mu), atol=1e-8)


class TestHostAnderson:
    """The ALM host-side update (moved here from equilibrium/alm.py; the
    full KS integration parity is tests/test_ks.py's anderson-vs-damped)."""

    def test_short_history_returns_damped_update(self):
        B, G = np.array([0.0, 1.0, 0.0, 1.0]), np.array([0.1, 0.9, 0.1, 0.9])
        out = host_anderson_step([B], [G], damping=0.3, depth=3)
        np.testing.assert_allclose(out, 0.3 * G + 0.7 * B)

    def test_wild_step_falls_back_to_damped(self):
        # An inconsistent history (G moved O(1) while the residual barely
        # changed) makes the least-squares coefficient ~1e9 and the
        # extrapolated step astronomical; the 10x trust test must reject it
        # and return the reference's damped update.
        B0, G0 = np.zeros(4), np.ones(4)
        B1 = np.array([1.0, 0.0, 0.0, 0.0])
        G1 = B1 + np.ones(4) + 1e-9
        out = host_anderson_step([B0, B1], [G0, G1], damping=0.3, depth=3)
        damped = 0.3 * G1 + 0.7 * B1
        np.testing.assert_allclose(out, damped)

    def test_affine_map_converges_in_few_steps(self):
        rng = np.random.default_rng(3)
        M = 0.5 * np.linalg.qr(rng.standard_normal((4, 4)))[0]
        c = rng.standard_normal(4)
        x_star = np.linalg.solve(np.eye(4) - M, c)
        G = lambda B: M @ B + c

        def run(anderson):
            B = np.zeros(4)
            Bs, Gs = [], []
            for it in range(500):
                GB = G(B)
                if np.max(np.abs(GB - B)) < 1e-12:
                    return it, B
                if anderson:
                    Bs.append(B.copy())
                    Gs.append(GB.copy())
                    Bs, Gs = Bs[-4:], Gs[-4:]
                    B = host_anderson_step(Bs, Gs, damping=0.3, depth=3)
                else:
                    B = 0.3 * GB + 0.7 * B
            return it, B

        it_and, B = run(True)
        it_damp, _ = run(False)
        assert it_and * 2 < it_damp   # measured 18 vs 155 at this spectrum
        np.testing.assert_allclose(B, x_star, atol=1e-10)


class TestGEWiring:
    def test_solver_config_accel_reaches_distribution_closure(self):
        # End-to-end: SolverConfig(accel=...) must cut BOTH the household
        # and the distribution sweep totals of a GE solve, and land on the
        # same rate.
        from aiyagari_tpu.config import EquilibriumConfig
        from aiyagari_tpu.equilibrium.bisection import (
            solve_equilibrium_distribution,
        )

        m = aiyagari_preset(grid_size=120)
        eq = EquilibriumConfig(max_iter=16, tol=1e-3)
        plain = solve_equilibrium_distribution(
            m, solver=SolverConfig(method="egm"), eq=eq)
        fast = solve_equilibrium_distribution(
            m, solver=SolverConfig(method="egm", accel=ANDERSON), eq=eq)
        assert plain.converged and fast.converged
        assert abs(plain.r - fast.r) < 1e-4
        tot = lambda res, key: sum(rec[key] for rec in res.per_iteration)
        assert (tot(fast, "solver_iterations")
                < tot(plain, "solver_iterations"))
        assert (tot(fast, "distribution_iterations")
                < tot(plain, "distribution_iterations"))
