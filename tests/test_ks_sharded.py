"""Grid-sharded Krusell-Smith EGM (SURVEY.md §2.4(1), VERDICT round 3 #4):
the [ns, nK, nk] household fixed point with the fine k-axis sharded over
the 8-virtual-device CPU mesh, the sort/mask/pchip re-interpolation served
by a ring-assembled knot slab (solvers/ks_egm_sharded.py).

Pinned, in order of importance:
  1. TRAJECTORY equality with the single-device solve_ks_egm (bounded
     sweeps — sharding correctness is per-sweep);
  2. a converged solve agrees, stopping rule included;
  3. the compiled program's collectives never carry a full-k-grid operand
     beyond the slab rotation itself;
  4. escape on an undersized slab (NaN + flag), never silent mis-brackets.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.models.krusell_smith import ks_preset
from aiyagari_tpu.parallel.mesh import make_mesh
from aiyagari_tpu.solvers.ks_egm import solve_ks_egm
from aiyagari_tpu.solvers.ks_egm_sharded import (
    ks_ring_slab_size,
    solve_ks_egm_sharded,
)


def _ks_problem(nk):
    model = ks_preset(k_size=nk)
    cfg = model.config
    B = jnp.asarray([0.1, 0.95, 0.1, 0.95], model.dtype)
    k_opt0 = 0.9 * jnp.broadcast_to(
        model.k_grid[None, None, :], (4, cfg.K_size, nk)).astype(model.dtype)
    kw = dict(theta=cfg.preferences.sigma, beta=cfg.preferences.beta,
              mu=cfg.mu, l_bar=cfg.l_bar, delta=cfg.technology.delta,
              k_min=cfg.k_min, k_max=cfg.k_max, tol=1e-6, max_iter=10_000)
    args = (B, model.k_grid, model.K_grid, model.P, model.r_table,
            model.w_table, model.eps_by_state, model.z_by_state,
            model.L_by_state, cfg.technology.alpha)
    return model, cfg, k_opt0, args, kw


class TestShardedKSEGM:
    def test_trajectory_matches_unsharded(self):
        # Bounded sweeps at 1,024 points (>= the verdict's 1,000-point
        # bar): the sharded sweep's Euler/inversion arithmetic is local and
        # the pchip runs the SAME masked kernel on the slab, so per-sweep
        # agreement pins the whole composition; f64, where the reference's
        # sort and our cummax repair are both exact no-ops.
        nk = 1_024
        model, cfg, k_opt0, args, kw = _ks_problem(nk)
        kw.update(tol=1e-30, max_iter=3)
        ref = solve_ks_egm(k_opt0, *args, **kw)
        mesh = make_mesh(("grid",))
        sol, esc = solve_ks_egm_sharded(
            mesh, k_opt0, *args, grid_power=float(cfg.k_power), **kw)
        assert not esc
        assert int(sol.iterations) == int(ref.iterations) == 3
        np.testing.assert_allclose(np.asarray(sol.k_opt),
                                   np.asarray(ref.k_opt), rtol=0, atol=1e-10)

    @pytest.mark.slow
    def test_converged_solve_matches_unsharded(self):
        # Full fixed point at the reference's 1e-6 sup-norm criterion.
        nk = 1_024
        model, cfg, k_opt0, args, kw = _ks_problem(nk)
        ref = solve_ks_egm(k_opt0, *args, **kw)
        mesh = make_mesh(("grid",))
        sol, esc = solve_ks_egm_sharded(
            mesh, k_opt0, *args, grid_power=float(cfg.k_power), **kw)
        assert not esc
        assert float(sol.distance) < kw["tol"]
        assert int(sol.iterations) == int(ref.iterations)
        np.testing.assert_allclose(np.asarray(sol.k_opt),
                                   np.asarray(ref.k_opt), rtol=0, atol=1e-8)

    def test_slab_size_is_static_and_bounded(self):
        # O(nk/D) with margins, capped at the full row + pad; never the
        # 512-block geometry of the windowed Aiyagari kernels.
        assert ks_ring_slab_size(4_096, 8, 2.0, 8) == 2 * 512 + 16 + 6
        assert ks_ring_slab_size(1_024, 8, 2.0, 8) == 2 * 128 + 16 + 6
        # Tiny rows: the margins dominate but the full-row cap bounds it.
        assert ks_ring_slab_size(104, 8, 2.0, 8) == 2 * 13 + 16 + 6
        assert ks_ring_slab_size(104, 8, 8.0, 8) == 104 + 8   # capped

    def test_no_full_grid_crosses_devices(self):
        # The slab-resident assertion: collective-permutes carry one
        # [R, nk/D] rotation channel; everything else is O(D) or O(R).
        nk = 1_024
        model, cfg, k_opt0, args, kw = _ks_problem(nk)
        kw.update(tol=1e-30, max_iter=2)
        mesh = make_mesh(("grid",))
        sol, esc = solve_ks_egm_sharded(
            mesh, k_opt0, *args, grid_power=float(cfg.k_power), **kw)
        assert int(sol.iterations) == 2 and not esc
        from aiyagari_tpu.solvers.ks_egm_sharded import _KS_EGM_PROGRAMS

        # Key tail: (..., tol, max_iter, double_alm, dtype); max_iter=2 is
        # unique to this test among the nk=1024 programs cached by earlier
        # tests in the class.
        (prog,) = [p for k, p in _KS_EGM_PROGRAMS.items()
                   if nk in k and k[-3] == 2]
        hlo = prog.lower(k_opt0, *(args[:7])).compile().as_text()
        R, L = 16, nk // 8
        seen = []
        for ln in hlo.splitlines():
            mm = re.search(r"= \w+\[([0-9,]*)\][^ ]* (all-gather|all-reduce|"
                           r"collective-permute)", ln)
            if mm:
                dims = [int(d) for d in mm.group(1).split(",") if d]
                seen.append((mm.group(2), dims))
        assert seen, "no collectives found — parsing broke or program changed"
        for op, dims in seen:
            elems = int(np.prod(dims)) if dims else 1
            if op == "collective-permute":
                assert elems <= R * L, (op, dims)
            else:
                # Bracket-start psum [R, D], tails gather [D, R], scalars.
                assert elems <= 2 * R * 8, (op, dims)
            assert elems < 16 * nk, (op, dims)

    def test_escape_on_undersized_slab(self):
        # DETERMINISTIC overflow (ADVICE round 4: the old near-k_max crowd
        # never overflowed on this box and the test self-skipped, leaving
        # the escape path unexercised). A k_min -> k_max STEP policy at
        # nk - 2L makes consumption — and with it the endogenous knots —
        # jump DOWN across the step; the global cummax repair then flattens
        # ~2L knots into one cluster value, so the device whose queries
        # straddle that value sees a bracket span ~2L > the capacity-1.0
        # slab (L + 2*pad + 2*stencil) and MUST escape, never clamp
        # silently.
        nk = 1_024
        model, cfg, k_opt0, args, kw = _ks_problem(nk)
        kw.update(tol=1e-30, max_iter=1)
        mesh = make_mesh(("grid",))
        L = nk // 8
        i = jnp.arange(nk)
        step = jnp.where(i < nk - 2 * L, float(cfg.k_min),
                         float(cfg.k_max)).astype(model.dtype)
        pol = jnp.broadcast_to(step[None, None, :], k_opt0.shape)
        sol, esc = solve_ks_egm_sharded(
            mesh, pol, *args,
            grid_power=float(cfg.k_power), capacity=1.0, pad=3, **kw)
        assert esc
        assert np.isnan(np.asarray(sol.k_opt)).all()

    def _raw_endo_degeneracy(self, model, cfg, pol, B):
        """(strict inversions, ties) in the raw f32 endogenous grid across
        all (s, K) rows — the per_sK Euler backout of solve_ks_egm
        replicated WITHOUT the repair step, so the repairs' actual domain
        of discretion is observable."""
        from aiyagari_tpu.solvers.ks_vfi import _alm_next_K_index
        from aiyagari_tpu.utils.utility import (
            crra_marginal,
            crra_marginal_inverse,
        )

        ns, nK = 4, cfg.K_size
        theta, beta = cfg.preferences.sigma, cfg.preferences.beta
        delta = cfg.technology.delta
        labor = model.eps_by_state * cfg.l_bar \
            + (1 - model.eps_by_state) * cfg.mu
        Kp_idx = _alm_next_K_index(B, model.K_grid, ns)
        inv = ties = 0
        for s in range(ns):
            for K_i in range(nK):
                exp = jnp.zeros(pol.shape[-1], pol.dtype)
                for sp in range(ns):
                    Ki2 = int(Kp_idx[s, K_i])
                    rn = model.r_table[sp, Ki2]
                    wn = model.w_table[sp, Ki2]
                    res = (1 + rn - delta) * model.k_grid + wn * labor[sp]
                    cn = jnp.maximum(res - pol[sp, Ki2, :], 1e-8)
                    exp = exp + model.P[s, sp] * (1 + rn - delta) \
                        * crra_marginal(cn, theta)
                c = crra_marginal_inverse(beta * exp, theta)
                ke = np.asarray(
                    (c + model.k_grid
                     - model.w_table[s, K_i] * labor[s])
                    / (1 + model.r_table[s, K_i] - delta))
                kv = ke[(ke >= float(cfg.k_min)) & (ke <= float(cfg.k_max))]
                d = np.diff(kv)
                inv += int((d < 0).sum())
                ties += int((d == 0).sum())
        return inv, ties

    @pytest.mark.slow
    def test_f32_tie_divergence_bounded(self):
        """The f32 contract of the sort-vs-cummax repair pair (VERDICT
        round 4 weak #6), as a tested bound instead of a docstring claim.
        Measured premise first: at f32 the raw endogenous grid is weakly
        monotone — NO strict rounding inversions (each backout stage is a
        monotone float evaluation of monotone inputs), but tied knot runs
        where the power-7 flat bottom collapses below f32 resolution
        (64 pairs at nk=1024). On ties the repairs differ only in which
        tied knot's y-value the pchip bracket reads, so the converged
        routes may diverge — bounded here at 2e-5 of the grid span
        (measured 6e-6)."""
        nk = 1_024
        model = ks_preset(dtype=jnp.float32, k_size=nk)
        cfg = model.config
        B = jnp.asarray([0.1, 0.95, 0.1, 0.95], jnp.float32)
        k_opt0 = 0.9 * jnp.broadcast_to(
            model.k_grid[None, None, :],
            (4, cfg.K_size, nk)).astype(jnp.float32)
        kw = dict(theta=cfg.preferences.sigma, beta=cfg.preferences.beta,
                  mu=cfg.mu, l_bar=cfg.l_bar, delta=cfg.technology.delta,
                  k_min=cfg.k_min, k_max=cfg.k_max, tol=1e-3,
                  max_iter=10_000)
        args = (B, model.k_grid, model.K_grid, model.P, model.r_table,
                model.w_table, model.eps_by_state, model.z_by_state,
                model.L_by_state, cfg.technology.alpha)

        # Premise: the repairs have genuine work at f32 — degenerate
        # (tied) runs exist in the raw endogenous grid; strict inversions
        # do not (the module docstring's measured claim).
        probe = solve_ks_egm(k_opt0, *args, **{**kw, "tol": 1e-30,
                                               "max_iter": 1})
        inv, ties = self._raw_endo_degeneracy(model, cfg, probe.k_opt, B)
        assert inv == 0, f"weak-monotonicity claim broken: {inv} inversions"
        assert ties > 0, "no f32 degeneracy — premise of the bound is gone"

        ref = solve_ks_egm(k_opt0, *args, **kw)
        mesh = make_mesh(("grid",))
        sol, esc = solve_ks_egm_sharded(
            mesh, k_opt0, *args, grid_power=float(cfg.k_power), **kw)
        assert not esc
        assert float(ref.distance) < kw["tol"]
        assert float(sol.distance) < kw["tol"]
        span = float(cfg.k_max - cfg.k_min)
        gap = float(jnp.max(jnp.abs(sol.k_opt - ref.k_opt)))
        assert gap < 2e-5 * span, (gap, span)

    def test_rejects_bad_arguments(self):
        model, cfg, k_opt0, args, kw = _ks_problem(100)
        mesh = make_mesh(("grid",))
        with pytest.raises(ValueError, match="divide"):
            solve_ks_egm_sharded(mesh, k_opt0, *args,
                                 grid_power=float(cfg.k_power), **kw)
        model, cfg, k_opt0, args, kw = _ks_problem(1_024)
        with pytest.raises(ValueError, match="power-spaced"):
            solve_ks_egm_sharded(mesh, k_opt0, *args, grid_power=0.0, **kw)
        with pytest.raises(ValueError, match="stencil"):
            solve_ks_egm_sharded(mesh, k_opt0, *args,
                                 grid_power=float(cfg.k_power), pad=1, **kw)


def _subcell_gap(k_grid, ref_k, sol_k):
    """Max policy divergence as a fraction of the LOCAL golden bracket span
    (the cells [j-1, j+1] around each reference policy point) — a power-7
    grid's global min cell is ~1e-14 at these sizes, so an absolute bound
    would be meaningless."""
    nk = k_grid.shape[0]
    j = jnp.clip(jnp.searchsorted(k_grid, ref_k.ravel()), 1, nk - 2)
    span = (k_grid[j + 1] - k_grid[j - 1]).reshape(ref_k.shape)
    return float(jnp.max(jnp.abs(sol_k - ref_k) / span))


def _ks_vfi_problem(nk, **over):
    # Same shared K-S test problem as _ks_problem (one calibration source);
    # the VFI solvers additionally need a consistent value seed, the VFI
    # loop knobs, and only the first 7 solver args.
    model, cfg, k_opt0, args, kw = _ks_problem(nk)
    v0 = jnp.log(jnp.maximum(0.1 / 0.9 * k_opt0, 1e-12)) \
        / (1.0 - cfg.preferences.beta)
    kw.update(howard_steps=20, improve_every=5, golden_iters=48)
    kw.update(over)
    return model, cfg, v0, k_opt0, args[:7], kw


class TestShardedKSVFI:
    """solvers/ks_vfi_sharded.py (VERDICT round 4 missing #1): the K-S VFI
    with the fine k-axis sharded. The design replicates the SMALL value
    table per sweep (one tiled all_gather) and keeps the O(nk^2) candidate
    tensor device-local — so the pins are exact-trajectory on the discrete
    path, sub-cell agreement through the golden refine (comparison
    amplification of matmul-shape rounding; module docstring), and a
    collective-size contract matched to that design."""

    def test_trajectory_matches_unsharded_discrete(self):
        # golden_iters=0: the discrete improvement + Howard evaluation are
        # the same arithmetic on the gathered table, so the trajectory
        # matches to reassociation noise (~1e-13 at f64).
        nk = 256
        model, cfg, v0, k_opt0, args, kw = _ks_vfi_problem(
            nk, tol=1e-30, max_iter=6, howard_steps=10, golden_iters=0)
        from aiyagari_tpu.solvers.ks_vfi import solve_ks_vfi
        from aiyagari_tpu.solvers.ks_vfi_sharded import solve_ks_vfi_sharded

        ref = solve_ks_vfi(v0, k_opt0, *args, **kw)
        mesh = make_mesh(("grid",))
        sol = solve_ks_vfi_sharded(mesh, v0, k_opt0, *args, **kw)
        assert int(sol.iterations) == int(ref.iterations) == 6
        np.testing.assert_allclose(np.asarray(sol.k_opt),
                                   np.asarray(ref.k_opt), rtol=0, atol=0)
        np.testing.assert_allclose(np.asarray(sol.value),
                                   np.asarray(ref.value), rtol=0, atol=1e-11)

    def test_trajectory_golden_subcell(self):
        # With the golden refine on, per-element comparison flips at
        # ~1e-13 value resolution move the within-cell maximizer — the
        # divergence must stay far below one grid cell.
        nk = 256
        model, cfg, v0, k_opt0, args, kw = _ks_vfi_problem(
            nk, tol=1e-30, max_iter=6, howard_steps=10)
        from aiyagari_tpu.solvers.ks_vfi import solve_ks_vfi
        from aiyagari_tpu.solvers.ks_vfi_sharded import solve_ks_vfi_sharded

        ref = solve_ks_vfi(v0, k_opt0, *args, **kw)
        mesh = make_mesh(("grid",))
        sol = solve_ks_vfi_sharded(mesh, v0, k_opt0, *args, **kw)
        assert _subcell_gap(model.k_grid, ref.k_opt, sol.k_opt) < 0.1
        # A sub-cell policy difference du ~ u'(c)*dk feeds the evaluation
        # fixed point with gain ~1/(1-beta): dk ~ 1e-5 in the large top
        # cells bounds the value divergence near 1e-3 (measured 2.8e-4).
        assert float(jnp.max(jnp.abs(sol.value - ref.value))) < 1e-3

    @pytest.mark.slow
    def test_converged_solve_matches_unsharded(self):
        # Full fixed point at the reference's relative 1e-6 criterion.
        nk = 128
        model, cfg, v0, k_opt0, args, kw = _ks_vfi_problem(nk)
        from aiyagari_tpu.solvers.ks_vfi import solve_ks_vfi
        from aiyagari_tpu.solvers.ks_vfi_sharded import solve_ks_vfi_sharded

        ref = solve_ks_vfi(v0, k_opt0, *args, **kw)
        mesh = make_mesh(("grid",))
        sol = solve_ks_vfi_sharded(mesh, v0, k_opt0, *args, **kw)
        assert float(sol.distance) < kw["tol"]
        assert int(sol.iterations) == int(ref.iterations)
        assert _subcell_gap(model.k_grid, ref.k_opt, sol.k_opt) < 0.1

    def test_no_candidate_tensor_crosses(self):
        # The scale-matched collective contract (module docstring): every
        # collective operand is O(ns*nK*nk) — the replicated value table —
        # and nothing [*, nk, nk']-shaped ever crosses devices.
        nk = 256
        model, cfg, v0, k_opt0, args, kw = _ks_vfi_problem(
            nk, tol=1e-30, max_iter=2, howard_steps=3)
        from aiyagari_tpu.solvers.ks_vfi_sharded import (
            _KS_VFI_PROGRAMS,
            solve_ks_vfi_sharded,
        )

        mesh = make_mesh(("grid",))
        sol = solve_ks_vfi_sharded(mesh, v0, k_opt0, *args, **kw)
        assert int(sol.iterations) == 2
        (prog,) = [p for k, p in _KS_VFI_PROGRAMS.items()
                   if nk in k and k[-6] == 2]   # max_iter=2 is unique
        hlo = prog.lower(v0, k_opt0, *args).compile().as_text()
        ns, nK = 4, int(cfg.K_size)
        table = ns * nK * nk
        seen = []
        for ln in hlo.splitlines():
            mm = re.search(r"= \w+\[([0-9,]*)\][^ ]* (all-gather|all-reduce|"
                           r"collective-permute)", ln)
            if mm:
                dims = [int(d) for d in mm.group(1).split(",") if d]
                seen.append((mm.group(2), dims))
        assert seen, "no collectives found — parsing broke or program changed"
        for op, dims in seen:
            elems = int(np.prod(dims)) if dims else 1
            assert elems <= table, (op, dims)

    def test_rejects_bad_geometry(self):
        from aiyagari_tpu.solvers.ks_vfi_sharded import solve_ks_vfi_sharded

        model, cfg, v0, k_opt0, args, kw = _ks_vfi_problem(100)
        mesh = make_mesh(("grid",))
        with pytest.raises(ValueError, match="divide"):
            solve_ks_vfi_sharded(mesh, v0, k_opt0, *args, **kw)

    def test_alm_routes_vfi_through_grid_mesh(self):
        # The round-4 verdict's silent gap: solve(..., method="vfi",
        # mesh_axes=("grid",)) ran single-device with no warning. It now
        # ROUTES through solve_ks_vfi_sharded (proof: the program cache
        # gains an entry) and reproduces the single-device ALM trajectory
        # to the sub-cell golden-jitter level.
        import aiyagari_tpu as at
        from aiyagari_tpu.solvers.ks_vfi_sharded import _KS_VFI_PROGRAMS

        cfg = at.KrusellSmithConfig(k_size=128)
        kw = dict(
            method="vfi",
            solver=at.SolverConfig(method="vfi", tol=1e-4, max_iter=30,
                                   howard_steps=10),
            alm=at.ALMConfig(T=120, population=400, discard=20, max_iter=2),
        )
        ref = at.solve(cfg, **kw)
        n_progs = len(_KS_VFI_PROGRAMS)
        res = at.solve(cfg, backend=at.BackendConfig(mesh_axes=("grid",)),
                       **kw)
        assert len(_KS_VFI_PROGRAMS) == n_progs + 1
        np.testing.assert_allclose(np.asarray(res.B), np.asarray(ref.B),
                                   rtol=0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(res.r2), np.asarray(ref.r2),
                                   rtol=0, atol=1e-8)
