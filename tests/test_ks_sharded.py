"""Grid-sharded Krusell-Smith EGM (SURVEY.md §2.4(1), VERDICT round 3 #4):
the [ns, nK, nk] household fixed point with the fine k-axis sharded over
the 8-virtual-device CPU mesh, the sort/mask/pchip re-interpolation served
by a ring-assembled knot slab (solvers/ks_egm_sharded.py).

Pinned, in order of importance:
  1. TRAJECTORY equality with the single-device solve_ks_egm (bounded
     sweeps — sharding correctness is per-sweep);
  2. a converged solve agrees, stopping rule included;
  3. the compiled program's collectives never carry a full-k-grid operand
     beyond the slab rotation itself;
  4. escape on an undersized slab (NaN + flag), never silent mis-brackets.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.models.krusell_smith import ks_preset
from aiyagari_tpu.parallel.mesh import make_mesh
from aiyagari_tpu.solvers.ks_egm import solve_ks_egm
from aiyagari_tpu.solvers.ks_egm_sharded import (
    ks_ring_slab_size,
    solve_ks_egm_sharded,
)


def _ks_problem(nk):
    model = ks_preset(k_size=nk)
    cfg = model.config
    B = jnp.asarray([0.1, 0.95, 0.1, 0.95], model.dtype)
    k_opt0 = 0.9 * jnp.broadcast_to(
        model.k_grid[None, None, :], (4, cfg.K_size, nk)).astype(model.dtype)
    kw = dict(theta=cfg.preferences.sigma, beta=cfg.preferences.beta,
              mu=cfg.mu, l_bar=cfg.l_bar, delta=cfg.technology.delta,
              k_min=cfg.k_min, k_max=cfg.k_max, tol=1e-6, max_iter=10_000)
    args = (B, model.k_grid, model.K_grid, model.P, model.r_table,
            model.w_table, model.eps_by_state, model.z_by_state,
            model.L_by_state, cfg.technology.alpha)
    return model, cfg, k_opt0, args, kw


class TestShardedKSEGM:
    def test_trajectory_matches_unsharded(self):
        # Bounded sweeps at 1,024 points (>= the verdict's 1,000-point
        # bar): the sharded sweep's Euler/inversion arithmetic is local and
        # the pchip runs the SAME masked kernel on the slab, so per-sweep
        # agreement pins the whole composition; f64, where the reference's
        # sort and our cummax repair are both exact no-ops.
        nk = 1_024
        model, cfg, k_opt0, args, kw = _ks_problem(nk)
        kw.update(tol=1e-30, max_iter=3)
        ref = solve_ks_egm(k_opt0, *args, **kw)
        mesh = make_mesh(("grid",))
        sol, esc = solve_ks_egm_sharded(
            mesh, k_opt0, *args, grid_power=float(cfg.k_power), **kw)
        assert not esc
        assert int(sol.iterations) == int(ref.iterations) == 3
        np.testing.assert_allclose(np.asarray(sol.k_opt),
                                   np.asarray(ref.k_opt), rtol=0, atol=1e-10)

    @pytest.mark.slow
    def test_converged_solve_matches_unsharded(self):
        # Full fixed point at the reference's 1e-6 sup-norm criterion.
        nk = 1_024
        model, cfg, k_opt0, args, kw = _ks_problem(nk)
        ref = solve_ks_egm(k_opt0, *args, **kw)
        mesh = make_mesh(("grid",))
        sol, esc = solve_ks_egm_sharded(
            mesh, k_opt0, *args, grid_power=float(cfg.k_power), **kw)
        assert not esc
        assert float(sol.distance) < kw["tol"]
        assert int(sol.iterations) == int(ref.iterations)
        np.testing.assert_allclose(np.asarray(sol.k_opt),
                                   np.asarray(ref.k_opt), rtol=0, atol=1e-8)

    def test_slab_size_is_static_and_bounded(self):
        # O(nk/D) with margins, capped at the full row + pad; never the
        # 512-block geometry of the windowed Aiyagari kernels.
        assert ks_ring_slab_size(4_096, 8, 2.0, 8) == 2 * 512 + 16 + 6
        assert ks_ring_slab_size(1_024, 8, 2.0, 8) == 2 * 128 + 16 + 6
        # Tiny rows: the margins dominate but the full-row cap bounds it.
        assert ks_ring_slab_size(104, 8, 2.0, 8) == 2 * 13 + 16 + 6
        assert ks_ring_slab_size(104, 8, 8.0, 8) == 104 + 8   # capped

    def test_no_full_grid_crosses_devices(self):
        # The slab-resident assertion: collective-permutes carry one
        # [R, nk/D] rotation channel; everything else is O(D) or O(R).
        nk = 1_024
        model, cfg, k_opt0, args, kw = _ks_problem(nk)
        kw.update(tol=1e-30, max_iter=2)
        mesh = make_mesh(("grid",))
        sol, esc = solve_ks_egm_sharded(
            mesh, k_opt0, *args, grid_power=float(cfg.k_power), **kw)
        assert int(sol.iterations) == 2 and not esc
        from aiyagari_tpu.solvers.ks_egm_sharded import _KS_EGM_PROGRAMS

        # Key tail: (..., tol, max_iter, double_alm, dtype); max_iter=2 is
        # unique to this test among the nk=1024 programs cached by earlier
        # tests in the class.
        (prog,) = [p for k, p in _KS_EGM_PROGRAMS.items()
                   if nk in k and k[-3] == 2]
        hlo = prog.lower(k_opt0, *(args[:7])).compile().as_text()
        R, L = 16, nk // 8
        seen = []
        for ln in hlo.splitlines():
            mm = re.search(r"= \w+\[([0-9,]*)\][^ ]* (all-gather|all-reduce|"
                           r"collective-permute)", ln)
            if mm:
                dims = [int(d) for d in mm.group(1).split(",") if d]
                seen.append((mm.group(2), dims))
        assert seen, "no collectives found — parsing broke or program changed"
        for op, dims in seen:
            elems = int(np.prod(dims)) if dims else 1
            if op == "collective-permute":
                assert elems <= R * L, (op, dims)
            else:
                # Bracket-start psum [R, D], tails gather [D, R], scalars.
                assert elems <= 2 * R * 8, (op, dims)
            assert elems < 16 * nk, (op, dims)

    def test_escape_on_undersized_slab(self):
        # Crowd every endogenous knot into the top of the value range (a
        # policy far above the grid makes consumption — and hence the
        # endogenous grid's span — collapse): the low devices' slabs then
        # miss the valid run entirely and must escape, not clamp silently.
        nk = 1_024
        model, cfg, k_opt0, args, kw = _ks_problem(nk)
        kw.update(tol=1e-30, max_iter=1)
        mesh = make_mesh(("grid",))
        crowd = jnp.broadcast_to(
            jnp.linspace(0.989, 0.99, nk, dtype=model.dtype)[None, None, :]
            * float(cfg.k_max), k_opt0.shape)
        sol, esc = solve_ks_egm_sharded(
            mesh, crowd, *args,
            grid_power=float(cfg.k_power), capacity=1.0, pad=3, **kw)
        if not esc:
            pytest.skip("geometry did not overflow the slab; escape "
                        "contract covered by the Aiyagari ring tests")
        assert np.isnan(np.asarray(sol.k_opt)).all()

    def test_rejects_bad_arguments(self):
        model, cfg, k_opt0, args, kw = _ks_problem(100)
        mesh = make_mesh(("grid",))
        with pytest.raises(ValueError, match="divide"):
            solve_ks_egm_sharded(mesh, k_opt0, *args,
                                 grid_power=float(cfg.k_power), **kw)
        model, cfg, k_opt0, args, kw = _ks_problem(1_024)
        with pytest.raises(ValueError, match="power-spaced"):
            solve_ks_egm_sharded(mesh, k_opt0, *args, grid_power=0.0, **kw)
        with pytest.raises(ValueError, match="stencil"):
            solve_ks_egm_sharded(mesh, k_opt0, *args,
                                 grid_power=float(cfg.k_power), pad=1, **kw)
