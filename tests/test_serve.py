"""Persistent solve service (ISSUE 15): the quantized solution cache's
contracts (bucket collisions polish, LRU byte budget, warm-vs-cold noise
cone), the warm pool, deadline coalescing with quarantine isolation, and
the serving flight record. Amortized solving (ISSUE 16) adds the predictor
ladder's correctness band: multi-neighbor blending (mismatched grids, the
eviction race), the surrogate's unfit-means-cold contract, the
bad-guess-degrades-to-cold bitwise pins for both the steady and the
transition path, the HTTP front's 401/413/429 hardening, and the load
driver's SLO-knee ramp.

Service tests run at a tiny calibration (grid 40, tol 2e-4 — the serve
bench's measured always-converges point) so the whole file stays
tier-1-sized; every solve is CPU f64 under the suite's virtual-device
conftest."""

import dataclasses
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future
from types import SimpleNamespace

import numpy as np
import pytest

from aiyagari_tpu.config import (
    AiyagariConfig,
    EquilibriumConfig,
    GridSpecConfig,
    MITShock,
    TransitionConfig,
)
from aiyagari_tpu.serve import (
    PolicySurrogate,
    ServeConfig,
    SolveRequest,
    SolveService,
    SolutionCache,
    blend_policies,
    blend_weights,
    calibration_key,
    calibration_params,
    payload_nbytes,
)

BASE = AiyagariConfig(grid=GridSpecConfig(n_points=40))
EQ = EquilibriumConfig(max_iter=48, tol=2e-4)


def with_beta(beta, base=BASE):
    return dataclasses.replace(
        base, preferences=dataclasses.replace(base.preferences,
                                              beta=round(float(beta), 6)))


def svc_config(**kw):
    kw.setdefault("method", "egm")
    kw.setdefault("equilibrium", EQ)
    kw.setdefault("warm_pool", False)
    kw.setdefault("rescue", False)
    return ServeConfig(**kw)


# ---------------------------------------------------------------------------
# solution cache units (no solves)
# ---------------------------------------------------------------------------


class TestCalibrationKey:
    def test_same_bucket_for_nearby_calibrations(self):
        a = calibration_key(with_beta(0.9500), resolution=1e-3)
        b = calibration_key(with_beta(0.95004), resolution=1e-3)
        assert a == b

    def test_distinct_buckets_across_resolution(self):
        a = calibration_key(with_beta(0.950), resolution=1e-3)
        b = calibration_key(with_beta(0.953), resolution=1e-3)
        assert a != b

    def test_structural_knobs_key_exactly(self):
        a = calibration_key(BASE)
        b = calibration_key(dataclasses.replace(
            BASE, grid=GridSpecConfig(n_points=41)))
        c = calibration_key(dataclasses.replace(
            BASE, technology=dataclasses.replace(BASE.technology,
                                                 alpha=0.35)))
        assert a != b and a != c

    def test_kind_and_extra_separate_namespaces(self):
        assert calibration_key(BASE, kind="ss") \
            != calibration_key(BASE, kind="anchor")
        assert calibration_key(BASE, kind="transition", extra=(32,)) \
            != calibration_key(BASE, kind="transition", extra=(64,))

    def test_zero_resolution_rejected(self):
        with pytest.raises(ValueError, match="resolution"):
            calibration_key(BASE, resolution=0.0)


class TestSolutionCache:
    def test_hit_requires_exact_params(self):
        cache = SolutionCache(1 << 20, resolution=1e-3)
        cache.put(with_beta(0.9500), {"r": 0.01})
        outcome, entry = cache.lookup(with_beta(0.9500))
        assert outcome == "hit" and entry.payload["r"] == 0.01

    def test_bucket_collision_is_warm_not_stale(self):
        """Two calibrations in ONE quantization bucket: the second lookup
        must come back as warm-start material ('warm'), never as the
        first's answer — and storing the second's own result must not
        clobber correctness for either contract."""
        cache = SolutionCache(1 << 20, resolution=1e-3)
        a, b = with_beta(0.9500), with_beta(0.95004)
        assert calibration_key(a, resolution=1e-3) \
            == calibration_key(b, resolution=1e-3)
        cache.put(a, {"r": 0.0100})
        outcome, entry = cache.lookup(b)
        assert outcome == "warm"
        assert entry.exact == calibration_params(a) != calibration_params(b)
        # The polished result replaces the bucket entry; the EXACT match
        # now hits for b and warms for a.
        cache.put(b, {"r": 0.0101})
        assert cache.lookup(b)[0] == "hit"
        assert cache.lookup(a)[0] == "warm"

    def test_nearest_neighbor_within_radius(self):
        cache = SolutionCache(1 << 20, resolution=1e-3,
                              neighbor_radius=50.0)
        cache.put(with_beta(0.950), {"r": 0.01})
        # 10 buckets away: inside the radius -> warm.
        outcome, entry = cache.lookup(with_beta(0.960))
        assert outcome == "warm" and entry.payload["r"] == 0.01
        # 80 buckets away: outside -> miss.
        assert cache.lookup(with_beta(0.87))[0] == "miss"

    def test_neighbors_never_cross_structure_or_kind(self):
        cache = SolutionCache(1 << 20, resolution=1e-3)
        cache.put(with_beta(0.950), {"r": 0.01})
        other_grid = with_beta(0.950, dataclasses.replace(
            BASE, grid=GridSpecConfig(n_points=41)))
        assert cache.lookup(other_grid)[0] == "miss"
        assert cache.lookup(with_beta(0.950), kind="anchor")[0] == "miss"

    def test_lru_eviction_respects_byte_budget(self):
        blob = lambda: {"mu": np.zeros(1000)}           # ~8 KB each
        nb = payload_nbytes(blob())
        cache = SolutionCache(3 * nb + 64, resolution=1e-3)
        betas = [0.90, 0.91, 0.92, 0.93]
        for b in betas:
            cache.put(with_beta(b), blob())
        assert cache.nbytes <= cache.byte_budget
        assert len(cache) == 3 and cache.evictions == 1
        # The least-recently-used entry (0.90) was the one evicted.
        assert cache.lookup(with_beta(0.93))[0] == "hit"
        # A lookup refreshes recency: touch 0.91, insert another, and the
        # untouched 0.92 goes instead.
        assert cache.lookup(with_beta(0.91))[0] == "hit"
        cache.put(with_beta(0.94), blob())
        assert cache.lookup(with_beta(0.91))[0] == "hit"
        outcome, entry = cache.lookup(with_beta(0.92))
        assert not (outcome == "hit")

    def test_oversized_payload_not_stored(self):
        cache = SolutionCache(1000, resolution=1e-3)
        assert cache.put(with_beta(0.95), {"mu": np.zeros(1000)}) is None
        assert len(cache) == 0 and cache.evictions == 1

    def test_zero_budget_disables_storage(self):
        cache = SolutionCache(0)
        cache.put(with_beta(0.95), {"r": 0.01})
        assert cache.lookup(with_beta(0.95))[0] == "miss"

    def test_payload_nbytes_counts_array_leaves(self):
        nb = payload_nbytes({"a": np.zeros((10, 10)), "b": 1.0})
        assert nb >= 800


# ---------------------------------------------------------------------------
# warm pool
# ---------------------------------------------------------------------------


class TestWarmPool:
    def test_warm_pool_compiles_and_reports(self, tmp_path):
        from aiyagari_tpu.diagnostics.ledger import RunLedger, read_ledger
        from aiyagari_tpu.serve.warmup import warm_pool

        led = RunLedger(tmp_path / "warm.jsonl")
        report = warm_pool(("distribution",), na=32, ledger=led)
        assert report["compiled"] >= 4
        # The sized hot programs rode along at the requested grid size.
        assert "egm/sweep@na32" in report["programs"]
        for rec in report["programs"].values():
            assert rec["compile_seconds"] > 0
        events = [e for e in read_ledger(tmp_path / "warm.jsonl")
                  if e["kind"] == "warmup"]
        assert len(events) >= report["compiled"]

    def test_warmup_cli_json(self, tmp_path, capsys):
        import json

        from aiyagari_tpu.serve.warmup import warmup_main

        rc = warmup_main(["--families", "distribution", "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["compiled"] >= 4

    def test_bad_na_rejected(self):
        from aiyagari_tpu.serve.warmup import warm_pool

        with pytest.raises(ValueError, match="na"):
            warm_pool(("distribution",), na=2)


# ---------------------------------------------------------------------------
# the service
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def service_ledger(tmp_path_factory):
    return tmp_path_factory.mktemp("serve") / "ledger.jsonl"


class TestServiceSteady:
    def test_hit_replays_and_warm_polishes_not_stale(self, tmp_path):
        """The end-to-end cache contract: an exact repeat replays
        bitwise; a bucket-colliding SECOND calibration gets a polished
        result for ITS OWN parameters (within the solve's own noise
        cone of a direct cold solve), never the first's stale answer."""
        from aiyagari_tpu import dispatch
        from aiyagari_tpu.diagnostics.ledger import read_ledger

        led = tmp_path / "led.jsonl"
        a = with_beta(0.9435)
        b = with_beta(0.94354)      # same quantization bucket as a
        with SolveService(svc_config(max_batch=1), ledger=led) as svc:
            ra = svc.solve(a, timeout=300)
            ra2 = svc.solve(a, timeout=60)
            rb = svc.solve(b, timeout=300)
        assert ra.status == "converged" and ra.cache == "cold"
        assert ra2.cache == "hit" and ra2.r == ra.r
        assert rb.cache == "warm" and rb.status == "converged"
        assert abs(rb.gap) < EQ.tol
        # Not the stale bucket answer: b's own direct solve agrees with
        # the polished response inside the market-clearing noise cone
        # (both roots satisfy |gap| < tol on the same supply curve).
        direct = dispatch.solve(b, method="egm", aggregation="distribution",
                                equilibrium=EQ, on_nonconvergence="raise")
        assert abs(rb.r - direct.r) < 1e-3
        events = read_ledger(led)
        kinds = {e["kind"] for e in events}
        assert {"serve_request", "cache_hit", "route_decision",
                "span", "verdict"} <= kinds
        serve_evs = [e for e in events if e["kind"] == "serve_request"]
        assert [e["cache"] for e in serve_evs] == ["cold", "hit", "warm"]
        for e in serve_evs:
            assert e["status"] == "converged"

    def test_poisoned_request_leaves_batchmates_bitwise_unchanged(self):
        """A NaN calibration inside a coalesced batch quarantines its own
        lane (verdict 'nan') while the healthy batchmates' results are
        BITWISE what the same lockstep sweep produces without the service
        in the loop (PR 10's quarantine contract, served)."""
        from aiyagari_tpu import dispatch

        good1, good2 = with_beta(0.942), with_beta(0.948)
        # The poison must survive model building AND propagate to the
        # excess demand (the diagnostics/faults.py lesson — a NaN
        # PREFERENCE is silently masked by the EGM constraint region's
        # NaN-false comparisons): a NaN borrowing limit NaNs the asset
        # grid, hence the lane's supply and gap.
        poisoned = dataclasses.replace(BASE, borrowing_limit=float("nan"))
        configs = [good1, poisoned, good2]
        with SolveService(svc_config(cache_bytes=0, max_batch=3,
                                     max_wait_s=2.0)) as svc:
            futs = [svc.submit(SolveRequest(c)) for c in configs]
            resps = [f.result(300) for f in futs]
        assert [r.batch for r in resps] == [3, 3, 3]
        assert resps[1].status == "nan" and not resps[1].converged
        assert resps[0].status == "converged"
        assert resps[2].status == "converged"
        ref = dispatch.sweep(configs[0], configs=configs, method="egm",
                             equilibrium=EQ, quarantine=True)
        assert resps[0].r == float(ref.r[0])
        assert resps[2].r == float(ref.r[2])
        assert bool(ref.quarantined[1])

    def test_coalesce_event_and_gauges(self, tmp_path):
        from aiyagari_tpu.diagnostics import metrics
        from aiyagari_tpu.diagnostics.ledger import read_ledger

        led = tmp_path / "led.jsonl"
        cfgs = [with_beta(b) for b in (0.938, 0.942, 0.946)]
        with SolveService(svc_config(max_batch=3,
                                     max_wait_s=2.0), ledger=led) as svc:
            futs = [svc.submit(SolveRequest(c)) for c in cfgs]
            [f.result(300) for f in futs]
            assert svc.queue_depth == 0
        events = read_ledger(led)
        co = [e for e in events if e["kind"] == "coalesce"]
        assert any(e["batch"] == 3 for e in co)
        assert metrics.gauge("aiyagari_serve_queue_depth").value == 0
        assert metrics.gauge("aiyagari_serve_batch_size").value == 3
        txt = metrics.render_prometheus()
        for name in ("aiyagari_serve_queue_depth",
                     "aiyagari_serve_batch_size",
                     "aiyagari_serve_cache_hit_rate",
                     "aiyagari_serve_requests_total",
                     "aiyagari_serve_latency_seconds"):
            assert name in txt, name

    def test_exact_hit_skips_the_coalescing_deadline(self):
        """Replayed hits must not pay max_wait_s: the worker serves them
        before assembling a batch."""
        with SolveService(svc_config(max_batch=4, max_wait_s=0.5)) as svc:
            first = svc.solve(with_beta(0.9480), timeout=300)
            assert first.cache == "cold"
            t0 = time.perf_counter()
            again = svc.solve(with_beta(0.9480), timeout=60)
            wall = time.perf_counter() - t0
        assert again.cache == "hit"
        assert wall < 0.4, wall


class TestServiceTransitions:
    def test_anchor_reuse_replay_and_coalesced_batch(self, tmp_path):
        """One economy through ONE service end-to-end: the first shock
        solves cold (anchor + Jacobian computed and cached), the second
        reuses them (cache 'warm' — the ~10x-less-work path), an exact
        repeat replays ('hit'), and two further shocks submitted together
        coalesce into ONE lockstep sweep_transitions that also rides the
        cached anchor (exactly one sweep span on the ledger)."""
        from aiyagari_tpu.diagnostics.ledger import read_ledger

        led = tmp_path / "led.jsonl"
        trans = TransitionConfig(T=24, max_iter=15, tol=1e-6)
        s1 = MITShock(param="tfp", size=0.01, rho=0.9)
        s2 = MITShock(param="tfp", size=0.005, rho=0.9)
        with SolveService(svc_config(max_batch=2, max_wait_s=2.0,
                                     transition=trans),
                          ledger=led) as svc:
            t0 = time.perf_counter()
            r1 = svc.solve(BASE, kind="transition", shock=s1, timeout=600)
            w1 = time.perf_counter() - t0
            t0 = time.perf_counter()
            r2 = svc.solve(BASE, kind="transition", shock=s2, timeout=600)
            w2 = time.perf_counter() - t0
            r3 = svc.solve(BASE, kind="transition", shock=s1, timeout=60)
            futs = [svc.submit(SolveRequest(BASE, kind="transition",
                                            shock=MITShock(param="tfp",
                                                           size=sz,
                                                           rho=0.9)))
                    for sz in (0.004, 0.007)]
            batch = [f.result(600) for f in futs]
        assert r1.status == "converged" and r1.cache == "cold"
        assert r2.status == "converged" and r2.cache == "warm"
        assert r3.cache == "hit"
        np.testing.assert_array_equal(r3.r_path, r1.r_path)
        assert r1.r_path.shape == (trans.T,)
        # The anchor skip is the measured point of the cache: the warm
        # request does far less work than the cold one (anchor + Jacobian
        # amortized). Generous 0.6x bound — the measured ratio is ~0.05.
        assert w2 < 0.6 * w1, (w1, w2)
        assert all(r.status == "converged" and r.batch == 2
                   for r in batch)
        assert all(r.cache == "warm" for r in batch)   # anchor reused
        # The pair ran as ONE lockstep sweep: exactly one sweep span.
        spans = [e for e in read_ledger(led) if e["kind"] == "span"]
        assert sum(e.get("name") == "mit_transition_sweep"
                   for e in spans) == 1


class TestValidation:
    def test_transition_request_needs_shock(self):
        with pytest.raises(ValueError, match="shock"):
            SolveRequest(BASE, kind="transition")

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="kind"):
            SolveRequest(BASE, kind="bogus")

    def test_serve_config_validated(self):
        with pytest.raises(ValueError, match="max_batch"):
            ServeConfig(max_batch=0)
        with pytest.raises(ValueError, match="method"):
            ServeConfig(method="bogus")
        with pytest.raises(ValueError, match="max_wait"):
            ServeConfig(max_wait_s=-1.0)

    def test_submit_before_start_rejected(self):
        svc = SolveService(svc_config())
        with pytest.raises(RuntimeError, match="start"):
            svc.submit(SolveRequest(BASE))

    def test_warm_start_knob_validated_at_dispatch(self):
        from aiyagari_tpu import dispatch
        from aiyagari_tpu.config import KrusellSmithConfig

        with pytest.raises(ValueError, match="warm_start"):
            dispatch.solve(KrusellSmithConfig(), warm_start=np.zeros(3))
        with pytest.raises(ValueError, match="warm_start"):
            dispatch.solve(BASE, backend="numpy", warm_start=np.zeros(3))


# ---------------------------------------------------------------------------
# amortized solving (ISSUE 16): blend, surrogate, degrade-to-cold
# ---------------------------------------------------------------------------


class TestBlendPredictors:
    def test_blend_policies_interpolates_mismatched_grids(self):
        """Structural keying means in-cache neighbors always share the
        request's grid, but the helper's contract covers the general case:
        each policy is interpolated onto the TARGET grid before weighting.
        Linear policies make np.interp exact, so the blend is checkable in
        closed form."""
        ga = np.linspace(0.0, 10.0, 11)
        gb = np.linspace(0.0, 10.0, 21)
        target = np.linspace(0.5, 9.5, 7)
        pa = np.stack([2.0 * ga, 2.0 * ga + 1.0])       # [n_states, na_a]
        pb = np.stack([3.0 * gb + 1.0, 3.0 * gb])       # [n_states, na_b]
        w = blend_weights([1.0, 3.0])
        out = blend_policies([pa, pb], [ga, gb], w, target)
        assert out.shape == (2, target.size)
        np.testing.assert_allclose(
            out[0], w[0] * (2.0 * target) + w[1] * (3.0 * target + 1.0),
            rtol=1e-12)
        np.testing.assert_allclose(
            out[1], w[0] * (2.0 * target + 1.0) + w[1] * (3.0 * target),
            rtol=1e-12)

    def test_blend_policies_same_grid_is_weighted_sum(self):
        g = np.linspace(0.0, 5.0, 8)
        pa, pb = np.ones((3, 8)), 3.0 * np.ones((3, 8))
        w = blend_weights([1.0, 1.0])
        out = blend_policies([pa, pb], [g, g], w, g)
        np.testing.assert_allclose(out, 2.0 * np.ones((3, 8)))

    def test_blend_weights_zero_distance_takes_all_mass(self):
        w = blend_weights([0.0, 5.0, 9.0])
        assert w[0] > 0.999 and abs(float(w.sum()) - 1.0) < 1e-12

    def test_neighbor_evicted_between_lookup_and_blend(self):
        """The eviction race: the neighborhood empties between the
        classifying lookup and the blend (a future multi-worker cache) —
        the blend must fall back to the entry the lookup already holds,
        not crash or silently go cold."""
        svc = SolveService(svc_config(max_batch=1, surrogate=False))
        payload = {"r": 0.012, "slope": -2.0, "warm": None, "w": 1.0,
                   "capital": 3.0, "gap": 0.0, "converged": True,
                   "status": "converged"}
        entry = svc.cache.put(with_beta(0.9500), payload)
        req = SolveRequest(with_beta(0.9507))
        outcome, looked = svc.cache.lookup(req.config)
        assert outcome == "warm" and looked is entry
        svc.cache._entries.clear()
        source, blended = svc._blend_payload(req, fallback=looked)
        assert source == "neighbor" and blended is payload


class TestSurrogate:
    def test_predict_is_none_until_first_fit(self):
        sur = PolicySurrogate(min_samples=4, fit_every=1)
        key = ("s",)
        rng = np.random.default_rng(0)
        assert sur.predict(key, np.zeros(7)) is None
        for i in range(3):
            sur.observe(key, rng.normal(size=7), 0.01 + 1e-3 * i)
            assert sur.predict(key, np.zeros(7)) is None
        sur.observe(key, rng.normal(size=7), 0.014)
        pred = sur.predict(key, np.zeros(7))
        assert pred is not None and np.isfinite(pred.r)
        assert sur.fits == 1 and sur.predictions == 1

    def test_refit_without_policies_drops_stale_policy_head(self):
        """A head refitted from a policy-free stream (ledger replay, or a
        calibration that shifted the parameter range) must NOT keep its
        old policy basis: mean/std and every weight move atomically, and
        a component not refitted this round is dropped rather than
        applied to the new standardization."""
        sur = PolicySurrogate(min_samples=4, fit_every=1, policy_rank=2,
                              max_samples=8)
        key = ("s",)
        rng = np.random.default_rng(1)
        pol = lambda: rng.normal(size=(2, 5))  # noqa: E731
        for i in range(4):
            sur.observe(key, rng.normal(size=7), 0.01 + 1e-3 * i,
                        policy=pol())
        pred = sur.predict(key, np.zeros(7))
        assert pred is not None and pred.policy is not None
        # A calibration-driven range shift: new observations far from the
        # old cloud, none carrying policies; the rolling window evicts the
        # policy-bearing samples entirely.
        for i in range(8):
            sur.observe(key, 50.0 + rng.normal(size=7), 0.02 + 1e-3 * i)
        pred = sur.predict(key, np.full(7, 50.0))
        assert pred is not None and np.isfinite(pred.r)
        assert pred.policy is None

    def test_unfit_surrogate_serves_cold_not_warm(self):
        """The service consults the surrogate on every cache miss, but an
        unfit head predicts None and the request MUST report cold — the
        ladder never manufactures a warm label out of nothing."""
        with SolveService(svc_config(max_batch=1)) as svc:
            assert svc.surrogate is not None
            resp = svc.solve(with_beta(0.9445), timeout=300)
        assert resp.status == "converged"
        assert resp.cache == "cold" and resp.warm_source == "cold"
        assert not resp.degraded
        assert svc.surrogate.predictions == 0
        assert svc.warm_sources == {"cold": 1}
        assert svc.cold_fraction() == 1.0


class TestDegradeToCold:
    """The correctness band of every predictor: a guess that cannot close
    re-solves cold, and the served answer is BITWISE the cold path's
    answer — amortization buys latency, never a different result."""

    def test_bad_steady_guess_degrades_bitwise_to_cold(self, tmp_path):
        from aiyagari_tpu.diagnostics.ledger import read_ledger

        led = tmp_path / "led.jsonl"
        a, b = with_beta(0.9510), with_beta(0.9515)
        with SolveService(svc_config(max_batch=1), ledger=led) as svc:
            first = svc.solve(a, timeout=300)
            assert first.status == "converged" and first.cache == "cold"
            # Poison the cached neighbor: a rate far from any equilibrium
            # with no slope and no policy, and a single polish evaluation
            # — the warm guess cannot close.
            entry = svc.cache._entries[svc.cache.key_for(a)]
            entry.payload = dict(entry.payload, r=0.04, slope=None,
                                 warm=None)
            svc.config = dataclasses.replace(svc.config, polish_steps=1)
            resp = svc.solve(b, timeout=300)
        assert resp.degraded and resp.warm_source == "cold"
        assert resp.cache == "warm"       # the lookup outcome is kept
        assert resp.status == "converged"
        assert svc.degradations == 1
        with SolveService(svc_config(max_batch=1, cache_bytes=0,
                                     surrogate=False)) as verify:
            ref = verify.solve(b, timeout=300)
        assert float(resp.r) == float(ref.r)
        assert float(resp.capital) == float(ref.capital)
        deg = [e for e in read_ledger(led) if e["kind"] == "degradation"]
        assert len(deg) == 1 and deg[0]["source"] == "neighbor"

    def test_bad_anchor_jacobian_degrades_bitwise_to_cold(self, tmp_path):
        from aiyagari_tpu.diagnostics.ledger import read_ledger

        led = tmp_path / "led.jsonl"
        trans = TransitionConfig(T=24, max_iter=20, tol=1e-6)
        s1 = MITShock(param="tfp", size=0.008, rho=0.9)
        s2 = MITShock(param="tfp", size=0.005, rho=0.9)
        with SolveService(svc_config(max_batch=1, transition=trans),
                          ledger=led) as svc:
            r1 = svc.solve(BASE, kind="transition", shock=s1, timeout=600)
            assert r1.status == "converged" and r1.cache == "cold"
            # Poison the cached anchor's fake-news Jacobian (wrong sign
            # AND wrong scale): Newton gets an unusable matrix and must
            # exhaust its iterations.
            akey = svc.cache.key_for(BASE, kind="anchor", extra=(trans.T,))
            aentry = svc.cache._entries[akey]
            bad = -0.05 * np.asarray(aentry.payload["jacobian"])
            aentry.payload = dict(aentry.payload, jacobian=bad)
            r2 = svc.solve(BASE, kind="transition", shock=s2, timeout=600)
            # The degrading cold re-solve repaired the anchor in place.
            repaired = np.asarray(
                svc.cache._entries[akey].payload["jacobian"])
        assert r2.degraded and r2.warm_source == "cold"
        assert r2.status == "converged" and r2.converged
        assert r2.cache == "warm"
        assert svc.degradations == 1
        assert not np.array_equal(repaired, bad)
        with SolveService(svc_config(max_batch=1, cache_bytes=0,
                                     surrogate=False,
                                     transition=trans)) as verify:
            ref = verify.solve(BASE, kind="transition", shock=s2,
                               timeout=600)
        np.testing.assert_array_equal(r2.r_path, ref.r_path)
        deg = [e for e in read_ledger(led) if e["kind"] == "degradation"]
        assert len(deg) == 1 and deg[0]["source"] == "anchor"


# ---------------------------------------------------------------------------
# the hardened HTTP front and the SLO-knee ramp (ISSUE 16 satellites)
# ---------------------------------------------------------------------------


class TestHttpHardening:
    @staticmethod
    def _serve(svc, **kw):
        from aiyagari_tpu.serve.service import _http_server

        httpd = _http_server(svc, BASE, 0, **kw)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd, httpd.server_address[1]

    @staticmethod
    def _request(port, *, method="GET", path="/healthz", body=None,
                 token=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=body, method=method)
        if token is not None:
            req.add_header("Authorization", f"Bearer {token}")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, r.read(), dict(r.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    def test_auth_required_and_scrape_surface_open(self):
        import json

        with SolveService(svc_config(max_batch=1)) as svc:
            first = svc.solve(BASE, timeout=300)
            assert first.status == "converged"
            httpd, port = self._serve(svc, auth_token="sekrit")
            try:
                code, _, headers = self._request(
                    port, method="POST", path="/solve", body=b"{}")
                assert code == 401
                assert headers.get("WWW-Authenticate") == "Bearer"
                assert self._request(port, method="POST", path="/solve",
                                     body=b"{}", token="wrong")[0] == 401
                # /metrics and /healthz are the scrape surface: open.
                assert self._request(port, path="/metrics")[0] == 200
                code, body, _ = self._request(port, path="/healthz")
                assert code == 200 and json.loads(body)["ok"] is True
                code, body, _ = self._request(
                    port, method="POST", path="/solve", body=b"{}",
                    token="sekrit")
                assert code == 200
                served = json.loads(body)
                assert served["cache"] == "hit"
                assert served["r"] == first.r
            finally:
                httpd.shutdown()
                httpd.server_close()

    def test_body_limit_and_load_shedding(self):
        # Never started: the 413/429 rejections must fire before any
        # solve is admitted.
        svc = SolveService(svc_config(max_batch=1))
        httpd, port = self._serve(svc, max_body_bytes=256)
        try:
            assert self._request(port, method="POST", path="/solve",
                                 body=b"x" * 1024)[0] == 413
        finally:
            httpd.shutdown()
            httpd.server_close()
        httpd, port = self._serve(svc, max_queue_depth=0)
        try:
            code, _, headers = self._request(port, method="POST",
                                             path="/solve", body=b"{}")
            assert code == 429
            assert headers.get("Retry-After") == "1"
        finally:
            httpd.shutdown()
            httpd.server_close()


class TestServeCalibrate:
    """POST /calibrate (ISSUE 17): gradient calibration behind the same
    hardened HTTP front, feeding converged fits back through the normal
    serve path. Runs at the standardized calibration shape (grid 16,
    3 income states, the ci bench's steady-state knobs) so the vmapped
    gradient program compiles once across the suite."""

    BASE = AiyagariConfig(
        grid=GridSpecConfig(n_points=16),
        income=dataclasses.replace(
            AiyagariConfig().income, rho=0.75, sigma_e=0.75, n_states=3,
            method="rouwenhorst"))
    SS = dict(bisect_iters=45, hh_tol=1e-12, hh_max_iter=4000,
              dist_tol=1e-13, dist_max_iter=20_000)

    @staticmethod
    def _serve(svc, base, **kw):
        from aiyagari_tpu.serve.service import _http_server

        httpd = _http_server(svc, base, 0, **kw)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd, httpd.server_address[1]

    @staticmethod
    def _post(port, path, payload, *, token=None, timeout=600):
        import json

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode(), method="POST")
        if token is not None:
            req.add_header("Authorization", f"Bearer {token}")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")

    def test_calibrate_auth_and_validation(self):
        svc = SolveService(svc_config(max_batch=1))
        httpd, port = self._serve(svc, self.BASE, auth_token="sekrit")
        try:
            # The same Bearer gate as /solve.
            assert self._post(port, "/calibrate", {})[0] == 401
            code, body = self._post(port, "/calibrate", {}, token="sekrit")
            assert code == 400 and "targets" in body["error"]
            code, body = self._post(
                port, "/calibrate",
                {"targets": {"gini": 0.38}, "fit": {"bogus": 1}},
                token="sekrit")
            assert code == 400 and "bogus" in body["error"]
            code, body = self._post(
                port, "/calibrate", {"targets": {"not_a_moment": 1.0}},
                token="sekrit")
            assert code == 400 and "moment" in body["error"]
        finally:
            httpd.shutdown()
            httpd.server_close()

    @pytest.mark.slow  # ~17 s: the HTTP-front recovery e2e; calibrate
    # auth/validation and the stalled-fit-withholds-theta contract stay
    # tier-1 here, and planted-parameter recovery is re-gated by every
    # bench ci battery run (test_bench_ci).
    def test_calibrate_end_to_end_feeds_serve_path(self, tmp_path):
        from aiyagari_tpu.calibrate.moments import model_moments
        from aiyagari_tpu.diagnostics.ledger import read_ledger

        targets = model_moments(self.BASE, **self.SS)
        led = tmp_path / "cal.jsonl"
        with SolveService(svc_config(max_batch=1), ledger=led) as svc:
            httpd, port = self._serve(svc, self.BASE, auth_token="sekrit")
            try:
                code, out = self._post(
                    port, "/calibrate",
                    {"targets": targets, "ss": self.SS,
                     "fit": {"lanes": 2, "steps": 2, "jitter": 1e-4,
                             "polish": False}},
                    token="sekrit")
            finally:
                httpd.shutdown()
                httpd.server_close()
            assert code == 200
            # The fit starts lane 0 AT the parameters that generated the
            # targets, so it converges on its first objective read...
            assert out["status"] == "converged" and out["converged"]
            assert out["income_method"] == "rouwenhorst"
            prefs, inc = self.BASE.preferences, self.BASE.income
            assert abs(out["theta"]["beta"] - prefs.beta) < 1e-6
            assert abs(out["theta"]["sigma"] - prefs.sigma) < 1e-6
            assert abs(out["theta"]["rho"] - inc.rho) < 1e-6
            assert abs(out["theta"]["sigma_e"] - inc.sigma_e) < 1e-6
            for k, v in targets.items():
                assert abs(out["moments"][k] - v) <= 1e-6 * max(abs(v), 1.0)
            # ...and the fitted economy went through the NORMAL serve
            # path: solved, cached, counted. On a 16-point grid the
            # supply curve is a step function of r, so the GE solver's
            # strict K-gap tolerance may report max_iter — the contract
            # here is the ROUTE (solve + cache entry), not GE tightness.
            fs = out["fit_solve"]
            assert fs["status"] in ("converged", "max_iter")
            assert fs["cache"] in ("cold", "warm", "hit")
            assert np.isfinite(fs["r"])
            assert out["wall_s"] > 0
        # The flight record: the unconditional step-0 marker plus one
        # calibration_step per Adam step, all before the fit verdict.
        steps = [e for e in read_ledger(led)
                 if e["kind"] == "calibration_step"]
        assert [e["step"] for e in steps][:2] == [0, 1]
        assert steps[0]["lanes"] == 2
        # The scrape surface gained the calibration series.
        text = svc.metrics_text()
        assert "aiyagari_calibration_last_loss" in text
        assert 'kind="calibration"' in text

    def test_calibrate_stalled_fit_withholds_theta(self, tmp_path):
        # Targets no Aiyagari economy on this grid attains: one gradient
        # step cannot reach them, and a fit that cannot certify its
        # parameters must not serve them.
        led = tmp_path / "stall.jsonl"
        with SolveService(svc_config(max_batch=1), ledger=led) as svc:
            httpd, port = self._serve(svc, self.BASE, auth_token="sekrit")
            try:
                code, out = self._post(
                    port, "/calibrate",
                    {"targets": {"gini": 0.95, "k_y": 20.0},
                     "ss": self.SS,
                     "fit": {"lanes": 2, "steps": 1, "polish": False}},
                    token="sekrit")
            finally:
                httpd.shutdown()
                httpd.server_close()
            assert code == 200
            assert out["status"] == "max_iter" and not out["converged"]
            assert "theta" not in out and "moments" not in out
            assert "fit_solve" not in out
            assert out["loss"] > 0


class TestRunRamp:
    def test_knee_is_last_offered_rate_meeting_slo(self):
        from aiyagari_tpu.serve.load import run_ramp

        lat = [0.005, 0.5]

        class Stub:
            step = 0

            def submit(self, req):
                fut = Future()
                fut.set_result(SimpleNamespace(
                    latency_s=lat[Stub.step], status="converged",
                    cache="hit", batch=1, queue_wait_s=0.0,
                    warm_source="hit", degraded=False))
                return fut

        def make_requests(n, step):
            Stub.step = step
            return [object()] * n

        report = run_ramp(Stub(), make_requests,
                          rates=(50.0, 100.0, 200.0), n_per_rate=4,
                          slo_s=0.05)
        # Step 0 meets the SLO; step 1's p99 blows it; step 2 never runs
        # (past the knee the open loop only measures queue growth).
        assert report["knee_rps"] == 50.0
        assert [s["slo_met"] for s in report["steps"]] == [True, False]
        assert report["steps"][0]["warm_sources"] == {"hit": 4}
        assert report["slo_s"] == 0.05

    def test_empty_rates_rejected(self):
        from aiyagari_tpu.serve.load import run_ramp

        with pytest.raises(ValueError, match="rate"):
            run_ramp(None, lambda n, s: [], rates=(), n_per_rate=1,
                     slo_s=1.0)
