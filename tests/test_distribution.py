"""Tests for the non-stochastic (Young 2010) distribution machinery
(sim/distribution.py), the weighted inequality statistics, and the
deterministic distribution-based GE closure.

The reference has no analogue (its aggregation is a Monte-Carlo time average,
Aiyagari_VFI.m:94-129); these tests pin the new capability to first
principles: lottery conservation, fixed-point property, agreement of the
income marginal with the Markov chain's stationary distribution, and
agreement of the distribution-based GE with the simulation-based GE.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.config import (
    AiyagariConfig,
    EquilibriumConfig,
    GridSpecConfig,
    SimConfig,
    SolverConfig,
)
from aiyagari_tpu.equilibrium.bisection import (
    solve_equilibrium,
    solve_equilibrium_distribution,
    solve_household,
)
from aiyagari_tpu.models.aiyagari import AiyagariModel, aiyagari_preset
from aiyagari_tpu.sim.distribution import (
    aggregate_capital,
    distribution_step,
    stationary_distribution,
    young_lottery,
)
from aiyagari_tpu.utils.markov import stationary_distribution as markov_stationary
from aiyagari_tpu.utils.stats import (
    gini,
    quantile_shares,
    weighted_gini,
    weighted_lorenz_curve,
    weighted_quantile_shares,
)


@pytest.fixture(scope="module")
def solved_small():
    """Household solution at a fixed r on a small grid."""
    model = aiyagari_preset(grid_size=80)
    sol = solve_household(model, 0.03, solver=SolverConfig(method="egm"))
    return model, sol


class TestLottery:
    def test_weights_reconstruct_policy(self, solved_small):
        model, sol = solved_small
        idx, w_lo = young_lottery(sol.policy_k, model.a_grid)
        recon = w_lo * model.a_grid[idx] + (1.0 - w_lo) * model.a_grid[idx + 1]
        clipped = jnp.clip(sol.policy_k, model.a_grid[0], model.a_grid[-1])
        np.testing.assert_allclose(np.asarray(recon), np.asarray(clipped), atol=1e-12)

    def test_weights_in_unit_interval(self, solved_small):
        model, sol = solved_small
        _, w_lo = young_lottery(sol.policy_k, model.a_grid)
        assert float(w_lo.min()) >= 0.0 and float(w_lo.max()) <= 1.0

    def test_step_conserves_mass(self, solved_small):
        model, sol = solved_small
        idx, w_lo = young_lottery(sol.policy_k, model.a_grid)
        N, na = sol.policy_k.shape
        mu = jnp.full((N, na), 1.0 / (N * na))
        mu1 = distribution_step(mu, idx, w_lo, model.P)
        assert float(mu1.sum()) == pytest.approx(1.0, abs=1e-12)
        assert float(mu1.min()) >= 0.0


class TestStationaryDistribution:
    @pytest.fixture(scope="class")
    def mu_sol(self, solved_small):
        model, sol = solved_small
        return stationary_distribution(sol.policy_k, model.a_grid, model.P,
                                       tol=1e-12, max_iter=20_000)

    def test_probability_measure(self, mu_sol):
        assert float(mu_sol.mu.sum()) == pytest.approx(1.0, abs=1e-10)
        assert float(mu_sol.mu.min()) >= 0.0

    def test_fixed_point(self, solved_small, mu_sol):
        model, sol = solved_small
        idx, w_lo = young_lottery(sol.policy_k, model.a_grid)
        mu1 = distribution_step(mu_sol.mu, idx, w_lo, model.P)
        np.testing.assert_allclose(np.asarray(mu1), np.asarray(mu_sol.mu), atol=1e-10)

    def test_income_marginal_matches_markov_stationary(self, solved_small, mu_sol):
        model, _ = solved_small
        pi = markov_stationary(model.P)
        np.testing.assert_allclose(
            np.asarray(mu_sol.mu.sum(axis=1)), np.asarray(pi), atol=1e-8
        )

    def test_aggregate_capital_positive_and_on_grid(self, solved_small, mu_sol):
        model, _ = solved_small
        K = float(aggregate_capital(mu_sol.mu, model.a_grid))
        assert float(model.a_grid[0]) <= K <= float(model.a_grid[-1])
        assert K > 0.0

    def test_agrees_with_monte_carlo_supply(self):
        """The deterministic supply should sit near the Monte-Carlo time
        average at the same policies (within MC sampling error). Run at
        r=0.0, where the stationary distribution is interior — at higher r
        the grid cap binds and the simulator's linear policy extrapolation
        beyond amax diverges from the (grid-conditioned) histogram method."""
        import jax

        from aiyagari_tpu.sim.ergodic import simulate_panel
        from aiyagari_tpu.utils.firm import wage_from_r

        r = 0.0
        model = aiyagari_preset(grid_size=80)
        sol = solve_household(model, r, solver=SolverConfig(method="egm"))
        mu_sol = stationary_distribution(sol.policy_k, model.a_grid, model.P,
                                         tol=1e-12, max_iter=20_000)
        tech = model.config.technology
        w = wage_from_r(r, tech.alpha, tech.delta)
        series = simulate_panel(
            sol.policy_k, sol.policy_c, sol.policy_l, model.a_grid, model.s,
            model.P, r, w, jax.random.PRNGKey(7),
            periods=4000, n_agents=64, delta=tech.delta,
        )
        mc = float(jnp.mean(series.k[500:]))
        det = float(aggregate_capital(mu_sol.mu, model.a_grid))
        assert det == pytest.approx(mc, rel=0.05)


class TestWeightedStats:
    def test_uniform_weights_match_unweighted(self, rng):
        x = jnp.asarray(rng.lognormal(0.0, 1.0, size=400))
        w = jnp.ones_like(x)
        assert float(weighted_gini(x, w)) == pytest.approx(float(gini(x)), abs=5e-3)
        np.testing.assert_allclose(
            np.asarray(weighted_quantile_shares(x, w)),
            np.asarray(quantile_shares(x)),
            atol=0.5,
        )

    def test_degenerate_distribution_gini_zero(self):
        x = jnp.full((50,), 3.0)
        w = jnp.ones((50,))
        assert float(weighted_gini(x, w)) == pytest.approx(0.0, abs=1e-8)

    def test_lorenz_endpoints(self, rng):
        x = jnp.asarray(rng.uniform(0.1, 5.0, size=100))
        w = jnp.asarray(rng.uniform(0.5, 2.0, size=100))
        pop, cum = weighted_lorenz_curve(x, w)
        assert float(pop[0]) == 0.0 and float(cum[0]) == 0.0
        assert float(pop[-1]) == pytest.approx(1.0)
        assert float(cum[-1]) == pytest.approx(1.0)

    def test_quantile_shares_sum_to_100(self, rng):
        x = jnp.asarray(rng.lognormal(0.0, 0.8, size=200))
        w = jnp.asarray(rng.uniform(0.1, 1.0, size=200))
        shares = weighted_quantile_shares(x, w)
        assert float(shares.sum()) == pytest.approx(100.0, abs=1e-6)
        # Lorenz dominance: shares increase across quantiles for positive x.
        assert np.all(np.diff(np.asarray(shares)) > 0)

    def test_replicated_weights_equal_expanded_sample(self):
        """A mass-2 point must count exactly like two mass-1 copies."""
        x = jnp.asarray([1.0, 2.0, 5.0])
        w = jnp.asarray([2.0, 1.0, 1.0])
        x_expanded = jnp.asarray([1.0, 1.0, 2.0, 5.0])
        g1 = float(weighted_gini(x, w))
        g2 = float(weighted_gini(x_expanded, jnp.ones(4)))
        assert g1 == pytest.approx(g2, abs=1e-10)


@pytest.mark.slow
class TestDistributionGE:
    @pytest.fixture(scope="class")
    def cfg(self):
        return AiyagariConfig(grid=GridSpecConfig(n_points=80))

    @pytest.fixture(scope="class")
    def dist_result(self, cfg):
        model = AiyagariModel.from_config(cfg)
        return solve_equilibrium_distribution(
            model, solver=SolverConfig(method="egm"), eq=EquilibriumConfig()
        )

    def test_economics(self, dist_result, cfg):
        beta = cfg.preferences.beta
        assert -0.05 < dist_result.r < 1 / beta - 1
        assert dist_result.mu is not None
        assert float(dist_result.mu.sum()) == pytest.approx(1.0, abs=1e-8)

    def test_agrees_with_simulation_ge(self, dist_result, cfg):
        model = AiyagariModel.from_config(cfg)
        sim_result = solve_equilibrium(
            model, solver=SolverConfig(method="egm"),
            sim=SimConfig(periods=2500, n_agents=8, discard=200, seed=3),
            eq=EquilibriumConfig(),
        )
        assert dist_result.r == pytest.approx(sim_result.r, abs=5e-3)

    def test_deterministic(self, cfg):
        """Two runs produce bit-identical r* (no RNG anywhere)."""
        model = AiyagariModel.from_config(cfg)
        eq = EquilibriumConfig(max_iter=4)
        r1 = solve_equilibrium_distribution(model, solver=SolverConfig(method="egm"), eq=eq).r
        r2 = solve_equilibrium_distribution(model, solver=SolverConfig(method="egm"), eq=eq).r
        assert r1 == r2

    def test_dispatch_routes_distribution(self, cfg):
        from aiyagari_tpu import solve

        res = solve(cfg, method="egm", aggregation="distribution",
                    equilibrium=EquilibriumConfig(max_iter=3))
        assert res.mu is not None and res.series is None

    def test_dispatch_rejects_numpy_distribution(self, cfg):
        from aiyagari_tpu import solve

        with pytest.raises(ValueError):
            solve(cfg, backend="numpy", aggregation="distribution")

    def test_weighted_gini_from_mu(self, dist_result, cfg):
        mu = dist_result.mu
        model = AiyagariModel.from_config(cfg)
        wealth = jnp.broadcast_to(model.a_grid[None, :], mu.shape)
        g = float(weighted_gini(wealth, mu))
        assert 0.05 < g < 0.95

    def test_dispatch_rejects_numpy_distribution(self):
        # KS + aggregation="distribution" is now supported (the Young closure,
        # test_ks.py TestHistogramClosure); the remaining invalid combination
        # is the numpy backend, which has no histogram path.
        from aiyagari_tpu import AiyagariConfig, solve

        with pytest.raises(ValueError, match="backend"):
            solve(AiyagariConfig(), aggregation="distribution", backend="numpy")

    def test_report_from_distribution_result(self, dist_result, cfg, tmp_path):
        from aiyagari_tpu.io_utils.report import equilibrium_report

        model = AiyagariModel.from_config(cfg)
        summary = equilibrium_report(dist_result, model, tmp_path)
        assert (tmp_path / "lorenz.png").exists()
        assert (tmp_path / "densities.png").exists()
        assert 0.0 < summary["gini"]["k"] < 1.0
        assert abs(sum(summary["quintile_shares_percent"]) - 100.0) < 1e-6

    def test_checkpoint_resume(self, cfg, tmp_path):
        """The shared bisection driver checkpoints the distribution closure
        too: an interrupted run resumes to the same r* as an uninterrupted
        one (both deterministic)."""
        model = AiyagariModel.from_config(cfg)
        eq = EquilibriumConfig(max_iter=5)
        solver = SolverConfig(method="egm")

        class Stop(Exception):
            pass

        def interrupt(rec):
            if rec["iteration"] == 1:
                raise Stop

        with pytest.raises(Stop):
            solve_equilibrium_distribution(model, solver=solver, eq=eq,
                                           on_iteration=interrupt,
                                           checkpoint_dir=tmp_path)
        resumed = solve_equilibrium_distribution(model, solver=solver, eq=eq,
                                                 checkpoint_dir=tmp_path)
        fresh = solve_equilibrium_distribution(model, solver=solver, eq=eq)
        assert resumed.r == pytest.approx(fresh.r, abs=1e-12)
        assert len(resumed.r_history) == len(fresh.r_history)
