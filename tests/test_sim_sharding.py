"""Simulation determinism and device-mesh sharding tests (SURVEY.md §4.4-4.5):
fixed keys reproduce bitwise-identical paths; sharded panel simulation over the
8-virtual-device CPU mesh matches the unsharded result.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.config import KrusellSmithConfig, SolverConfig
from aiyagari_tpu.equilibrium.bisection import solve_household
from aiyagari_tpu.models.aiyagari import aiyagari_preset
from aiyagari_tpu.models.krusell_smith import KrusellSmithModel
from aiyagari_tpu.parallel.mesh import agents_sharding, make_mesh, shard_panel
from aiyagari_tpu.sim.ergodic import simulate_panel
from aiyagari_tpu.sim.ks_panel import (
    simulate_aggregate_shocks,
    simulate_capital_path,
    simulate_capital_path_shardmap,
    simulate_employment_panel,
)
from aiyagari_tpu.utils.firm import wage_from_r


@pytest.fixture(scope="module")
def aiyagari_setup():
    m = aiyagari_preset(grid_size=60)
    sol = solve_household(m, 0.04, solver=SolverConfig(method="egm"))
    w = float(wage_from_r(0.04, m.config.technology.alpha, m.config.technology.delta))
    return m, sol, w


class TestDeterminism:
    def test_same_key_same_path(self, aiyagari_setup):
        m, sol, w = aiyagari_setup
        args = (sol.policy_k, sol.policy_c, sol.policy_l, m.a_grid, m.s, m.P, 0.04, w)
        s1 = simulate_panel(*args, jax.random.PRNGKey(42), periods=200, n_agents=16)
        s2 = simulate_panel(*args, jax.random.PRNGKey(42), periods=200, n_agents=16)
        np.testing.assert_array_equal(np.asarray(s1.k), np.asarray(s2.k))

    def test_different_keys_differ(self, aiyagari_setup):
        m, sol, w = aiyagari_setup
        args = (sol.policy_k, sol.policy_c, sol.policy_l, m.a_grid, m.s, m.P, 0.04, w)
        s1 = simulate_panel(*args, jax.random.PRNGKey(0), periods=200, n_agents=16)
        s2 = simulate_panel(*args, jax.random.PRNGKey(1), periods=200, n_agents=16)
        assert not np.array_equal(np.asarray(s1.k), np.asarray(s2.k))

    def test_panel_ergodic_mean_stable(self, aiyagari_setup):
        # Time-average of one long path ~ cross-section average of many agents
        # (the ergodicity assumption the reference relies on; SURVEY.md §3.6/8).
        m, sol, w = aiyagari_setup
        args = (sol.policy_k, sol.policy_c, sol.policy_l, m.a_grid, m.s, m.P, 0.04, w)
        long1 = simulate_panel(*args, jax.random.PRNGKey(5), periods=6000, n_agents=1)
        wide = simulate_panel(*args, jax.random.PRNGKey(6), periods=600, n_agents=64)
        t_avg = float(jnp.mean(long1.k[500:]))
        x_avg = float(jnp.mean(wide.k[300:]))
        assert abs(t_avg - x_avg) / x_avg < 0.15


class TestSharding:
    def test_eight_virtual_devices(self):
        assert len(jax.devices()) == 8

    def test_sharded_panel_matches_unsharded(self):
        cfg = KrusellSmithConfig(k_size=20)
        model = KrusellSmithModel.from_config(cfg)
        key = jax.random.PRNGKey(11)
        kz, ke = jax.random.split(key)
        T, pop = 150, 800
        z = simulate_aggregate_shocks(model.pz, kz, T=T)
        eps = simulate_employment_panel(z, model.eps_trans, cfg.shocks.u_good,
                                        cfg.shocks.u_bad, ke, T=T, population=pop)
        k_opt = 0.9 * jnp.broadcast_to(model.k_grid[None, None, :], (4, cfg.K_size, cfg.k_size))
        k0 = jnp.full((pop,), float(model.K_grid[0]))

        K_ref, kpop_ref = simulate_capital_path(k_opt, model.k_grid, model.K_grid,
                                                z, eps, k0, T=T)

        mesh = make_mesh(("agents",))
        eps_sh = shard_panel(eps, mesh, batch_axis=1)
        k0_sh = shard_panel(jnp.full((pop,), float(model.K_grid[0])), mesh, batch_axis=0)
        K_sh, kpop_sh = simulate_capital_path(k_opt, model.k_grid, model.K_grid,
                                              z, eps_sh, k0_sh, T=T)
        np.testing.assert_allclose(np.asarray(K_ref), np.asarray(K_sh), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(kpop_ref), np.asarray(kpop_sh), rtol=1e-12)

    def test_sharded_panel_matches_unsharded_analytic_route(self):
        # The analytic-bucket interpolation (grid_power > 0) must shard
        # identically to the stored-knot route: per-agent elementwise work
        # plus the same mean collective.
        cfg = KrusellSmithConfig(k_size=20)
        model = KrusellSmithModel.from_config(cfg)
        kz, ke = jax.random.split(jax.random.PRNGKey(11))
        T, pop = 150, 800
        z = simulate_aggregate_shocks(model.pz, kz, T=T)
        eps = simulate_employment_panel(z, model.eps_trans, cfg.shocks.u_good,
                                        cfg.shocks.u_bad, ke, T=T, population=pop)
        k_opt = 0.9 * jnp.broadcast_to(model.k_grid[None, None, :], (4, cfg.K_size, cfg.k_size))
        gp = float(cfg.k_power)

        K_ref, kpop_ref = simulate_capital_path(
            k_opt, model.k_grid, model.K_grid, z, eps,
            jnp.full((pop,), float(model.K_grid[0])), T=T, grid_power=gp)
        # The two interpolation routes agree on the whole trajectory to f64
        # interp resolution on this well-resolved 20-point grid.
        K_onehot, _ = simulate_capital_path(
            k_opt, model.k_grid, model.K_grid, z, eps,
            jnp.full((pop,), float(model.K_grid[0])), T=T)
        np.testing.assert_allclose(np.asarray(K_ref), np.asarray(K_onehot),
                                   rtol=1e-8)

        mesh = make_mesh(("agents",))
        eps_sh = shard_panel(eps, mesh, batch_axis=1)
        k0_sh = shard_panel(jnp.full((pop,), float(model.K_grid[0])), mesh, batch_axis=0)
        K_sh, kpop_sh = simulate_capital_path(
            k_opt, model.k_grid, model.K_grid, z, eps_sh, k0_sh, T=T, grid_power=gp)
        np.testing.assert_allclose(np.asarray(K_ref), np.asarray(K_sh), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(kpop_ref), np.asarray(kpop_sh), rtol=1e-12)

    def test_sharded_mean_is_global(self):
        mesh = make_mesh(("agents",))
        x = jnp.arange(8000, dtype=jnp.float64)
        x_sh = jax.device_put(x, agents_sharding(mesh))
        assert float(jnp.mean(x_sh)) == float(jnp.mean(x))

    def test_batch_matches_single_sims(self):
        # The width-batched panel (round 5: simulate_capital_paths_batch,
        # one scan serving W independent sims to amortize the per-step
        # launch overhead that bounds the 10k-agent panel) is the SAME
        # per-sim arithmetic — every lane must match its single-sim run.
        from aiyagari_tpu.sim.ks_panel import simulate_capital_paths_batch

        cfg = KrusellSmithConfig(k_size=20)
        model = KrusellSmithModel.from_config(cfg)
        T, pop, W = 120, 600, 3
        gp = float(cfg.k_power)
        k_opt = 0.9 * jnp.broadcast_to(
            model.k_grid[None, None, :], (4, cfg.K_size, cfg.k_size))
        zs, epss = [], []
        for i in range(W):
            kz, ke = jax.random.split(jax.random.PRNGKey(100 + i))
            z = simulate_aggregate_shocks(model.pz, kz, T=T)
            zs.append(z)
            epss.append(simulate_employment_panel(
                z, model.eps_trans, cfg.shocks.u_good, cfg.shocks.u_bad,
                ke, T=T, population=pop))
        k0 = jnp.full((pop,), float(model.K_grid[0]))
        K_b, kpop_b = simulate_capital_paths_batch(
            k_opt, model.k_grid, model.K_grid, jnp.stack(zs),
            jnp.stack(epss), jnp.broadcast_to(k0, (W, pop)), T=T,
            grid_power=gp)
        assert K_b.shape == (W, T) and kpop_b.shape == (W, pop)
        for i in range(W):
            K_i, kpop_i = simulate_capital_path(
                k_opt, model.k_grid, model.K_grid, zs[i], epss[i], k0,
                T=T, grid_power=gp)
            np.testing.assert_allclose(np.asarray(K_b[i]), np.asarray(K_i),
                                       rtol=0, atol=1e-12)
            np.testing.assert_allclose(np.asarray(kpop_b[i]),
                                       np.asarray(kpop_i), rtol=0,
                                       atol=1e-12)

    def test_shardmap_panel_matches_gspmd(self):
        # The explicit shard_map+pmean collective path (SURVEY.md §2.4(2))
        # agrees with the implicit GSPMD path on the same inputs.
        cfg = KrusellSmithConfig(k_size=20)
        model = KrusellSmithModel.from_config(cfg)
        key = jax.random.PRNGKey(7)
        kz, ke = jax.random.split(key)
        T, pop = 120, 640
        z = simulate_aggregate_shocks(model.pz, kz, T=T)
        eps = simulate_employment_panel(z, model.eps_trans, cfg.shocks.u_good,
                                        cfg.shocks.u_bad, ke, T=T, population=pop)
        k_opt = 0.9 * jnp.broadcast_to(model.k_grid[None, None, :], (4, cfg.K_size, cfg.k_size))
        k0 = jnp.full((pop,), float(model.K_grid[0]))

        K_ref, kpop_ref = simulate_capital_path(k_opt, model.k_grid, model.K_grid,
                                                z, eps, k0, T=T)
        mesh = make_mesh(("agents",))
        k0_fresh = jnp.full((pop,), float(model.K_grid[0]))  # k0 was donated above
        K_sm, kpop_sm = simulate_capital_path_shardmap(
            mesh, k_opt, model.k_grid, model.K_grid, z, eps, k0_fresh
        )
        np.testing.assert_allclose(np.asarray(K_ref), np.asarray(K_sm), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(kpop_ref), np.asarray(kpop_sm), rtol=1e-12)
        # Same agreement on the analytic-bucket route (grid_power > 0): the
        # explicit-collective program must thread grid_power through its
        # cached shard_map build.
        gp = float(cfg.k_power)
        K_ga, kpop_ga = simulate_capital_path(
            k_opt, model.k_grid, model.K_grid, z, eps,
            jnp.full((pop,), float(model.K_grid[0])), T=T, grid_power=gp)
        K_sa, kpop_sa = simulate_capital_path_shardmap(
            mesh, k_opt, model.k_grid, model.K_grid, z, eps,
            jnp.full((pop,), float(model.K_grid[0])), grid_power=gp)
        np.testing.assert_allclose(np.asarray(K_ga), np.asarray(K_sa), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(kpop_ga), np.asarray(kpop_sa), rtol=1e-12)

    def test_shardmap_panel_rejects_ragged_population(self):
        mesh = make_mesh(("agents",))
        with pytest.raises(ValueError, match="not divisible"):
            simulate_capital_path_shardmap(
                mesh, jnp.zeros((4, 4, 8)), jnp.linspace(0.1, 10, 8),
                jnp.linspace(30, 50, 4), jnp.zeros(5, jnp.int32),
                jnp.zeros((5, 9), jnp.int32), jnp.full((9,), 35.0),
            )


class TestGridSharding:
    """Grid-axis (TP-analogue) sharding of the SCALE solvers — the windowed
    EGM path at sizes where sharding actually matters (SURVEY.md §2.4(1);
    the Bellman rows it shards are Aiyagari_VFI.m:70-83)."""

    def _egm_problem(self, n):
        from aiyagari_tpu.models.aiyagari import aiyagari_preset
        from aiyagari_tpu.solvers.egm import initial_consumption_guess
        from aiyagari_tpu.utils.firm import wage_from_r

        m = aiyagari_preset(grid_size=n)
        w = float(wage_from_r(0.04, m.config.technology.alpha,
                              m.config.technology.delta))
        C0 = initial_consumption_guess(m.a_grid, m.s, 0.04, w)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  tol=1e-6, max_iter=2000, grid_power=float(m.config.grid.power))
        return m, w, C0, kw

    @pytest.mark.slow
    def test_windowed_egm_solve_sharded_matches_unsharded(self):
        # Windowed-inversion regime (8,192 points, 2 query blocks per device
        # on the 8-device mesh), consumption iterate sharded along the grid
        # axis. Bounded-sweep trajectory equality (8 sweeps, not full
        # convergence — a cold fine-grid fixed point is minutes on this
        # one-core box; sharding correctness is iterate-by-iterate, so 8
        # sweeps pin it as hard as 300 would).
        from aiyagari_tpu.parallel.mesh import grid_sharding, make_mesh
        from aiyagari_tpu.solvers.egm import solve_aiyagari_egm

        n = 5120   # windowed regime (cutoff 4096); GSPMD compile dominates
        m, w, C0, kw = self._egm_problem(n)
        kw.update(tol=1e-30, max_iter=6)
        ref = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.04, w, m.amin, **kw)

        mesh = make_mesh(("grid",))
        C0_sh = jax.device_put(C0, grid_sharding(mesh, -1, 2))
        a_sh = jax.device_put(m.a_grid, grid_sharding(mesh, -1, 1))
        sol = solve_aiyagari_egm(C0_sh, a_sh, m.s, m.P, 0.04, w, m.amin, **kw)
        assert int(sol.iterations) == int(ref.iterations) == 6
        np.testing.assert_allclose(np.asarray(sol.policy_c),
                                   np.asarray(ref.policy_c), atol=1e-12)
        np.testing.assert_allclose(np.asarray(sol.policy_k),
                                   np.asarray(ref.policy_k), atol=1e-12)

    @pytest.mark.slow
    def test_windowed_inversion_sharded_communication_pattern(self):
        # What does GSPMD actually do with the windowed inversion when the
        # knot array is sharded along the grid axis? The window gather reads
        # KB-granular slabs at data-dependent offsets, so the compiler
        # cannot prove locality: the lowered module materializes the full
        # knot row per device (all-gather, or its all-reduce/dynamic-slice
        # equivalent under Auto axes). This test PINS that measured behavior
        # — the honest answer to "does it partition without gathering the
        # knots?" is NO under GSPMD today; the sharded win at this op comes
        # from the per-block compare-reduce (which does partition over query
        # blocks), and a halo-exchange shard_map variant is the documented
        # next step (docs/DESIGN.md).
        from aiyagari_tpu.ops.interp import inverse_interp_power_grid
        from aiyagari_tpu.parallel.mesh import grid_sharding, make_mesh

        n = 5120
        lo, hi, power = 0.0, 52.0, 2.0
        gk = lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power
        x = jnp.asarray(np.sort((gk + 0.3 * np.sin(gk / 7.0) + 0.8) / 1.04 - 0.5))
        mesh = make_mesh(("grid",))
        x_sh = jax.device_put(x, grid_sharding(mesh, -1, 1))

        fn = jax.jit(lambda xx: inverse_interp_power_grid(xx, lo, hi, power, n))
        lowered = fn.lower(x_sh).compile()
        hlo = lowered.as_text()
        ref = np.asarray(fn(x))
        got = np.asarray(fn(x_sh))
        np.testing.assert_allclose(got, ref, atol=1e-12)
        collective_ops = [ln for ln in hlo.splitlines()
                          if "all-gather" in ln or "all-reduce" in ln
                          or "collective-permute" in ln]
        # Sharded-input correctness holds; the compiled module either
        # re-gathers the row (collectives present) or GSPMD chose full
        # replication of the small [n] operand — both are legal, neither
        # partitions the knots. Pin that at least the OUTPUT stays sharded
        # or a collective exists, so a silent de-sharding regression (e.g.
        # jit constant-folding the input resharding away) gets caught.
        out_sharding = lowered.output_shardings
        assert collective_ops or not out_sharding.is_fully_replicated

    @pytest.mark.slow  # ~11 s: grid-sharded VFI parity is pinned tier-1 by
    # test_ks_sharded's discrete path; this adds only the 2k dense-row scale.
    def test_dense_bellman_rows_shard_cleanly(self):
        # The [N, na, na'] Bellman max (Aiyagari_VFI.m:70-83) partitions on
        # the QUERY axis (na) with the choice axis local: sharded and
        # replicated 20-sweep trajectories agree exactly at 2k points.
        from aiyagari_tpu.models.aiyagari import aiyagari_preset
        from aiyagari_tpu.parallel.mesh import grid_sharding, make_mesh
        from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi

        n = 2048
        m = aiyagari_preset(grid_size=n)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  tol=1e-30, max_iter=20)
        v0 = jnp.zeros((m.P.shape[0], n), m.dtype)
        ref = solve_aiyagari_vfi(v0, m.a_grid, m.s, m.P, 0.04, 1.2, **kw)
        mesh = make_mesh(("grid",))
        v0_sh = jax.device_put(v0, grid_sharding(mesh, -1, 2))
        sol = solve_aiyagari_vfi(v0_sh, m.a_grid, m.s, m.P, 0.04, 1.2, **kw)
        np.testing.assert_allclose(np.asarray(sol.v), np.asarray(ref.v), atol=1e-12)
        np.testing.assert_array_equal(np.asarray(sol.policy_idx),
                                      np.asarray(ref.policy_idx))


class TestHaloShardedInversion:
    """parallel/halo.py: the EGM inversion with the knot array genuinely
    DISTRIBUTED — per-device shards + ppermute neighbor halos, never a full
    re-materialization (the thing GSPMD cannot do for this op; DESIGN.md §4)."""

    def _knots(self, n, distort=True):
        lo, hi, power = 0.0, 52.0, 2.0
        gk = lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power
        if distort:
            x = np.sort((gk + 0.3 * np.sin(gk / 7.0) + 0.8) / 1.04 - 0.5)
        else:
            x = gk * 0.97
        return jnp.asarray(x), lo, hi, power

    def test_matches_unsharded_route(self):
        from aiyagari_tpu.ops.interp import inverse_interp_power_grid
        from aiyagari_tpu.parallel.halo import inverse_interp_power_grid_halo
        from aiyagari_tpu.parallel.mesh import make_mesh

        n = 16_384   # 2,048-knot shards + 1,536-knot halos on 8 devices
        # (the distorted first row's bracket lag at the sqrt-dense bottom
        # scales with n: ~1,180 knots here — past a 1,024 halo, inside
        # 1,536; the escape test below exercises the too-small case on
        # purpose). Down from 40,960 in round 2: the lag/halo geometry is
        # scale-proportional, and the unsharded reference route at 40,960
        # cost ~2.5 min of the one-core suite budget.
        x, lo, hi, power = self._knots(n)
        xq = jnp.stack([x, x * 1.01 + 0.05])
        mesh = make_mesh(("grid",))
        got, esc = inverse_interp_power_grid_halo(mesh, xq, lo, hi, power, n,
                                                  halo=1536)
        want, esc_w = inverse_interp_power_grid(xq, lo, hi, power, n,
                                                with_escape=True)
        assert not bool(esc) and not bool(esc_w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=1e-9)

    def test_below_and_above_range_edges(self):
        # Knots shifted up (first queries below all knots) and truncated
        # (last queries above): the sentinel halos must reproduce the
        # unsharded below-extrapolation and top-truncation exactly.
        from aiyagari_tpu.ops.interp import inverse_interp_power_grid
        from aiyagari_tpu.parallel.halo import inverse_interp_power_grid_halo
        from aiyagari_tpu.parallel.mesh import make_mesh

        n = 16_384
        x, lo, hi, power = self._knots(n, distort=False)
        x = x + 0.5          # queries below the first knot exist
        mesh = make_mesh(("grid",))
        # The +0.5 shift lags brackets by up to ~760 knots at the power
        # grid's dense bottom; 1,024 covers it.
        got, esc = inverse_interp_power_grid_halo(mesh, x, lo, hi, power, n,
                                                  halo=1024)
        want = inverse_interp_power_grid(x, lo, hi, power, n)
        assert not bool(esc)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=1e-9)

    def test_escape_on_cross_shard_cluster(self):
        # A knot cluster wider than the halo inside one query interval: the
        # sharded route must escape (NaN + flag), never return silently
        # wrong brackets.
        from aiyagari_tpu.parallel.halo import inverse_interp_power_grid_halo
        from aiyagari_tpu.parallel.mesh import make_mesh

        n = 16_384
        lo, hi, power = 0.0, 52.0, 2.0
        gq = lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power
        cluster = np.linspace(gq[2000], gq[2001], 4000, endpoint=False)
        rest = gq[np.linspace(0, n - 1, n - 4000).astype(int)]
        x = jnp.asarray(np.sort(np.concatenate([cluster, rest]))[:n])
        mesh = make_mesh(("grid",))
        out, esc = inverse_interp_power_grid_halo(mesh, x, lo, hi, power, n,
                                                  halo=512)
        assert bool(esc)
        assert np.isnan(np.asarray(out)).all()

    def test_rejects_ragged_shapes(self):
        from aiyagari_tpu.parallel.halo import inverse_interp_power_grid_halo
        from aiyagari_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(("grid",))
        with pytest.raises(ValueError, match="divide"):
            inverse_interp_power_grid_halo(mesh, jnp.zeros(1001), 0.0, 1.0,
                                           2.0, 1001, halo=8)


class TestDistributed:
    def test_single_process_is_noop(self, monkeypatch):
        from aiyagari_tpu.parallel.distributed import initialize_distributed

        # Isolate from ambient pod/CI topology env, which would turn the
        # no-op under test into a real (hanging) coordinator handshake.
        for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
            monkeypatch.delenv(var, raising=False)
        ctx = initialize_distributed()
        assert not ctx.initialized
        assert ctx.num_processes == 1 and ctx.process_id == 0
        assert ctx.local_device_count == 8 and ctx.global_device_count == 8

    def test_process_info_snapshot(self):
        from aiyagari_tpu.parallel.distributed import process_info

        ctx = process_info()
        assert ctx.num_processes == 1
        assert ctx.global_device_count == len(jax.devices())

    @pytest.mark.slow
    @pytest.mark.cluster
    def test_two_process_cluster_cross_process_psum(self):
        # The REAL multi-process path (SURVEY.md §5.8; VERDICT round 2 #5):
        # two fresh processes, a localhost coordinator, one CPU device each
        # — initialize_distributed must complete the gRPC handshake, report
        # num_processes==2, and a jitted sum over a process-spanning sharded
        # array must all-reduce ACROSS the processes. This is exactly the
        # topology a TPU pod launcher creates (one process per host), minus
        # the hardware.
        import os
        import socket
        import subprocess
        import sys as _sys

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        worker = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from aiyagari_tpu.parallel.distributed import initialize_distributed

ctx = initialize_distributed(coordinator_address="127.0.0.1:%d",
                             num_processes=2, process_id=int(sys.argv[1]))
assert ctx.initialized and ctx.num_processes == 2, ctx
# Same persistent XLA:CPU compile cache as conftest.py — without it every
# suite run re-pays each worker's sharded-program compiles (minutes, twice
# over; the biggest slow-set cost found in the round-5 budget pass). Must
# come AFTER initialize_distributed: the cache suffix resolves the backend,
# and touching it earlier breaks the coordinator handshake.
from aiyagari_tpu.io_utils.compile_cache import enable_compilation_cache
enable_compilation_cache(os.path.join(os.path.expanduser("~"),
                                      ".cache", "aiyagari_tpu", "xla-tests"))
assert ctx.global_device_count == 2 and ctx.local_device_count == 1, ctx
mesh = jax.make_mesh((2,), ("p",))
sh = NamedSharding(mesh, P("p"))
x = jax.make_array_from_callback(
    (2,), sh, lambda idx: np.asarray([float(jax.process_index() + 1)]))
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
assert float(total) == 3.0, float(total)   # 1 (proc 0) + 2 (proc 1)
print("WORKER_OK", ctx.process_id, float(total))
""" % port

        env = dict(os.environ, PYTHONPATH=os.pathsep.join(
            [os.getcwd()] + os.environ.get("PYTHONPATH", "").split(os.pathsep)))
        for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                    "JAX_PROCESS_ID", "XLA_FLAGS", "JAX_PLATFORMS"):
            env.pop(var, None)
        procs = [subprocess.Popen([_sys.executable, "-c", worker, str(pid)],
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.PIPE, text=True, env=env)
                 for pid in (0, 1)]
        outs = []
        for p in procs:
            try:
                out, err = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("two-process cluster hung (coordinator handshake)")
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0, f"worker failed rc={rc}\n{out}\n{err}"
            assert "WORKER_OK" in out, (out, err)

    @pytest.mark.slow
    @pytest.mark.cluster
    def test_two_process_cluster_real_solves(self):
        # Capability, not just plumbing (VERDICT round 3 #5): a 2-process x
        # 4-virtual-device cluster (the one-process-per-host topology of a
        # TPU pod) runs (a) the explicit-collective K-S panel simulation
        # with the agent axis spanning BOTH processes, and (b) the
        # ring-redistributed sharded EGM fixed point with the grid axis
        # spanning both — each checked against a local single-device
        # reference inside the workers. The pmean/ppermute collectives then
        # demonstrably cross the process boundary (4 shards per side).
        import os
        import socket
        import subprocess
        import sys as _sys

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        worker = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from aiyagari_tpu.parallel.distributed import initialize_distributed

ctx = initialize_distributed(coordinator_address="127.0.0.1:%d",
                             num_processes=2, process_id=int(sys.argv[1]))
assert ctx.initialized and ctx.num_processes == 2, ctx
# Same persistent XLA:CPU compile cache as conftest.py — without it every
# suite run re-pays each worker's sharded-program compiles (minutes, twice
# over; the biggest slow-set cost found in the round-5 budget pass). Must
# come AFTER initialize_distributed: the cache suffix resolves the backend,
# and touching it earlier breaks the coordinator handshake.
from aiyagari_tpu.io_utils.compile_cache import enable_compilation_cache
enable_compilation_cache(os.path.join(os.path.expanduser("~"),
                                      ".cache", "aiyagari_tpu", "xla-tests"))
assert ctx.global_device_count == 8 and ctx.local_device_count == 4, ctx

# (a) Cross-process sharded panel simulation: deterministic shocks, the
# agent axis split 256/256 across the processes' devices.
from aiyagari_tpu.models.krusell_smith import ks_preset
from aiyagari_tpu.sim.ks_panel import (
    simulate_capital_path,
    simulate_capital_path_shardmap,
)

model = ks_preset(k_size=24)
cfg = model.config
T, pop = 40, 512
z_np = (np.arange(T) // 5) %% 2
eps_np = ((np.arange(T)[:, None] + np.arange(pop)[None, :]) %% 3 == 0)
z = jnp.asarray(z_np, jnp.int32)
eps_full = eps_np.astype(np.int64)
k0_full = np.full(pop, float(model.K_grid[0]))
k_opt = 0.9 * jnp.broadcast_to(model.k_grid[None, None, :],
                               (4, cfg.K_size, cfg.k_size))
mesh = jax.make_mesh((8,), ("agents",))
sh_eps = NamedSharding(mesh, P(None, "agents"))
sh_pop = NamedSharding(mesh, P("agents"))
eps_g = jax.make_array_from_callback((T, pop), sh_eps,
                                     lambda idx: eps_full[idx])
k0_g = jax.make_array_from_callback((pop,), sh_pop,
                                    lambda idx: k0_full[idx])
K_sm, _ = simulate_capital_path_shardmap(
    mesh, k_opt, model.k_grid, model.K_grid, z, eps_g, k0_g,
    grid_power=float(cfg.k_power))
K_ref, _ = simulate_capital_path(
    k_opt, model.k_grid, model.K_grid, z, jnp.asarray(eps_full),
    jnp.asarray(k0_full), T=T, grid_power=float(cfg.k_power))
np.testing.assert_allclose(np.asarray(K_sm), np.asarray(K_ref),
                           rtol=0, atol=1e-12)

# (b) Cross-process ring-sharded EGM: the knot rotation's ppermutes span
# the process boundary; compare this process's addressable shards against
# a local single-device solve.
from aiyagari_tpu.models.aiyagari import aiyagari_preset
from aiyagari_tpu.solvers.egm import (
    initial_consumption_guess,
    solve_aiyagari_egm,
)
from aiyagari_tpu.solvers.egm_sharded import solve_aiyagari_egm_sharded
from aiyagari_tpu.utils.firm import wage_from_r

m = aiyagari_preset(grid_size=8192)
w = float(wage_from_r(0.04, m.config.technology.alpha,
                      m.config.technology.delta))
C0 = initial_consumption_guess(m.a_grid, m.s, 0.04, w)
kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
          tol=1e-30, max_iter=3, grid_power=float(m.config.grid.power))
gmesh = jax.make_mesh((8,), ("grid",))
sol = solve_aiyagari_egm_sharded(gmesh, C0, m.a_grid, m.s, m.P, 0.04, w,
                                 m.amin, **kw)
assert int(sol.iterations) == 3 and not bool(sol.escaped)
ref = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.04, w, m.amin, **kw)
ref_np = np.asarray(ref.policy_c)
n_checked = 0
for shd in sol.policy_c.addressable_shards:
    np.testing.assert_allclose(np.asarray(shd.data), ref_np[shd.index],
                               rtol=0, atol=1e-12)
    n_checked += 1
assert n_checked == 4, n_checked   # this process's half of the mesh
print("WORKER_OK", ctx.process_id)
""" % port

        env = dict(os.environ, PYTHONPATH=os.pathsep.join(
            [os.getcwd()] + os.environ.get("PYTHONPATH", "").split(os.pathsep)))
        for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                    "JAX_PROCESS_ID", "XLA_FLAGS", "JAX_PLATFORMS"):
            env.pop(var, None)
        procs = [subprocess.Popen([_sys.executable, "-c", worker, str(pid)],
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.PIPE, text=True, env=env)
                 for pid in (0, 1)]
        outs = []
        for p in procs:
            try:
                # Two sharded-program compiles (panel scan + EGM fixed
                # point) on one core, twice over: minutes, not seconds.
                out, err = p.communicate(timeout=900)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("two-process real-solve cluster hung")
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0, f"worker failed rc={rc}\n{out}\n{err}"
            assert "WORKER_OK" in out, (out, err)

    @pytest.mark.slow
    @pytest.mark.cluster
    def test_two_process_interrupted_resume(self, tmp_path):
        # The pod-preemption story past the process boundary (VERDICT
        # round 4 missing #3): a 2-process x 4-device mesh GE bisection is
        # interrupted mid-run; each process has written ONLY its own
        # `.proc{i}of2` checkpoint file with its addressable warm-start
        # shards (no host gather, no full-array entry anywhere); the
        # resumed 2-process run merges the files — completeness-checked —
        # places shards per process, and finishes with the identical
        # bracket path. Same worker pattern as the real-solves test.
        import os
        import socket
        import subprocess
        import sys as _sys

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        worker = r"""
import os, sys, time, pathlib
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
import jax.numpy as jnp
from aiyagari_tpu.parallel.distributed import initialize_distributed

ctx = initialize_distributed(coordinator_address="127.0.0.1:%d",
                             num_processes=2, process_id=int(sys.argv[1]))
assert ctx.initialized and ctx.num_processes == 2, ctx
# Same persistent XLA:CPU compile cache as conftest.py — without it every
# suite run re-pays each worker's sharded-program compiles (minutes, twice
# over; the biggest slow-set cost found in the round-5 budget pass). Must
# come AFTER initialize_distributed: the cache suffix resolves the backend,
# and touching it earlier breaks the coordinator handshake.
from aiyagari_tpu.io_utils.compile_cache import enable_compilation_cache
enable_compilation_cache(os.path.join(os.path.expanduser("~"),
                                      ".cache", "aiyagari_tpu", "xla-tests"))

from aiyagari_tpu.config import EquilibriumConfig, SolverConfig
from aiyagari_tpu.equilibrium.bisection import solve_equilibrium_distribution
from aiyagari_tpu.io_utils import checkpoint as ck
from aiyagari_tpu.models.aiyagari import aiyagari_preset

n = 6144
m = aiyagari_preset(grid_size=n)
# Starved inner budgets: the claims under test (per-process files, merged
# completeness-checked restore, shard-exact placement, identical bracket
# path) are determinism claims, not convergence claims, and each midpoint
# solve executes 8 virtual devices serially across two gRPC-coupled
# processes on one core — full-tolerance solves measured ~20 min here.
scfg = SolverConfig(method="egm", tol=1e-4, max_iter=600)
eq = EquilibriumConfig(max_iter=2)
dist_kw = dict(dist_tol=1e-6, dist_max_iter=500)
mesh8 = jax.make_mesh((8,), ("grid",))
ckdir = sys.argv[2]

# Uninterrupted reference first (all sharded programs compile here and
# are reused by the interrupted + resumed runs).
ref = solve_equilibrium_distribution(m, solver=scfg, eq=eq, mesh=mesh8,
                                     **dist_kw)

class Stop(Exception):
    pass

def interrupt(rec):
    if rec["iteration"] == 1:
        raise Stop

try:
    solve_equilibrium_distribution(m, solver=scfg, eq=eq, mesh=mesh8,
                                   on_iteration=interrupt,
                                   checkpoint_dir=ckdir, **dist_kw)
    raise SystemExit("expected the interruption to fire")
except Stop:
    pass

# This process wrote ONLY its own file, holding its 4 addressable warm
# shards — per-shard entries, no assembled full-grid array.
base = pathlib.Path(ckdir) / "bisection_egm_dist.ckpt.npz"
own = ck._proc_file(base, ctx.process_id, 2)
assert own.exists(), own
assert not base.exists()
sc_own, arr_own = ck._load_npz(own)
shard_keys = [k for k in arr_own if k.startswith("warm__shard")]
assert len(shard_keys) == 4 and "warm" not in arr_own, sorted(arr_own)
assert arr_own[shard_keys[0]].shape == (7, n // 8), arr_own[shard_keys[0]].shape

# The peer's save is host-side and can skew by ms — wait for its file
# before resuming (a real resume happens at job restart, long after).
peer = ck._proc_file(base, 1 - ctx.process_id, 2)
for _ in range(600):
    if peer.exists():
        break
    time.sleep(0.1)
assert peer.exists(), "peer checkpoint file never appeared"

res = solve_equilibrium_distribution(m, solver=scfg, eq=eq, mesh=mesh8,
                                     checkpoint_dir=ckdir, **dist_kw)
np.testing.assert_allclose(np.asarray(res.r_history),
                           np.asarray(ref.r_history), rtol=0, atol=1e-12)
assert abs(res.r - ref.r) < 1e-12
print("WORKER_OK", ctx.process_id)
""" % port

        env = dict(os.environ, PYTHONPATH=os.pathsep.join(
            [os.getcwd()] + os.environ.get("PYTHONPATH", "").split(os.pathsep)))
        for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                    "JAX_PROCESS_ID", "XLA_FLAGS", "JAX_PLATFORMS"):
            env.pop(var, None)
        procs = [subprocess.Popen(
            [_sys.executable, "-c", worker, str(pid), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in (0, 1)]
        outs = []
        for p in procs:
            try:
                # Cold-cache first run compiles three bisection phases' worth
                # of sharded programs in both processes on one core (~20 min
                # observed); cached runs are minutes.
                out, err = p.communicate(timeout=2400)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("two-process interrupted-resume cluster hung")
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0, f"worker failed rc={rc}\n{out}\n{err}"
            assert "WORKER_OK" in out, (out, err)
