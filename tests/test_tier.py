"""Solve fabric units (ISSUE 20): the shared L2 solution tier's
correctness rule (L2 never answers — cross-worker material re-enters the
predictor ladder as "warm", and a local converged re-store is what earns
back "hit"), its loud-but-non-fatal corruption paths (torn payload, stale
stamp, the two-worker eviction race), the L1 cache's thread-safety under
a concurrent hammer, the fleet front's pure routing/replay helpers, and
the /healthz readiness split (503 warming -> 200 ready).

Everything here is solver-free: payloads are plain dicts, services are
never asked to solve, so the whole file is tier-1 cheap."""

import dataclasses
import json
import pickle
import threading
import urllib.error
import urllib.request

import pytest

from aiyagari_tpu.config import (
    AiyagariConfig,
    EquilibriumConfig,
    GridSpecConfig,
)
from aiyagari_tpu.serve import ServeConfig, SolveService
from aiyagari_tpu.serve.cache import (
    SolutionCache,
    calibration_key,
    calibration_params,
)
from aiyagari_tpu.serve.fleet import grid_class, unacked_from_ledger
from aiyagari_tpu.serve.tier import L2Tier, TieredSolutionCache

BASE = AiyagariConfig(grid=GridSpecConfig(n_points=40))
EQ = EquilibriumConfig(max_iter=48, tol=2e-4)


def with_beta(beta, base=BASE):
    return dataclasses.replace(
        base, preferences=dataclasses.replace(base.preferences,
                                              beta=round(float(beta), 6)))


def svc_config(**kw):
    kw.setdefault("method", "egm")
    kw.setdefault("equilibrium", EQ)
    kw.setdefault("warm_pool", False)
    kw.setdefault("rescue", False)
    return ServeConfig(**kw)


def tiered(tmp_path, **kw):
    """One worker's view of a shared L2 directory: its own L1 + its own
    L2Tier handle on the common dir (exactly the fleet topology)."""
    kw.setdefault("resolution", 1e-3)
    l2 = L2Tier(tmp_path, resolution=kw["resolution"])
    return TieredSolutionCache(1 << 20, l2=l2, **kw)


# ---------------------------------------------------------------------------
# L2 tier: cross-worker semantics
# ---------------------------------------------------------------------------


class TestTierSemantics:
    def test_write_through_is_warm_never_hit(self, tmp_path):
        """Worker A's converged solve reaches worker B as warm-start
        material — outcome 'warm', NEVER 'hit', even on an exact
        calibration match — so the cross-worker payload re-enters the
        polish/degrade ladder instead of being replayed verbatim."""
        a, b = tiered(tmp_path), tiered(tmp_path)
        cfg = with_beta(0.9500)
        a.put(cfg, {"r": 0.0123})
        outcome, entry = b.lookup(cfg)
        assert outcome == "warm"
        assert entry.payload["r"] == 0.0123
        assert entry.promoted
        # Still warm on the SECOND exact lookup: the promoted L1 entry
        # must not turn into a hit-server just because it landed in L1.
        outcome2, _ = b.lookup(cfg)
        assert outcome2 == "warm"

    def test_promoted_entry_invisible_to_peek(self, tmp_path):
        """The HTTP fast path's peek must never short-circuit a request
        onto cross-worker material — peek answers only locally-earned
        exact entries."""
        a, b = tiered(tmp_path), tiered(tmp_path)
        cfg = with_beta(0.9500)
        a.put(cfg, {"r": 0.0123})
        assert a.peek(cfg) is not None          # local store: peekable
        b.lookup(cfg)                           # promotes into B's L1
        assert b.peek(cfg) is None              # promoted: not peekable

    def test_local_put_earns_hit_back(self, tmp_path):
        """After worker B's OWN solve converges and re-stores the key,
        the entry is B's — later exact lookups are ordinary hits."""
        a, b = tiered(tmp_path), tiered(tmp_path)
        cfg = with_beta(0.9500)
        a.put(cfg, {"r": 0.0123})
        assert b.lookup(cfg)[0] == "warm"
        b.put(cfg, {"r": 0.0124})
        outcome, entry = b.lookup(cfg)
        assert outcome == "hit"
        assert entry.payload["r"] == 0.0124
        assert b.peek(cfg) is not None

    def test_neighbor_promotion_within_radius(self, tmp_path):
        """A nearby (different-bucket) calibration stored by worker A is
        in-radius warm material for worker B's request."""
        a, b = tiered(tmp_path), tiered(tmp_path)
        stored, asked = with_beta(0.9500), with_beta(0.9520)
        assert calibration_key(stored) != calibration_key(asked)
        a.put(stored, {"r": 0.0123})
        outcome, entry = b.lookup(asked)
        assert outcome == "warm"
        assert entry.exact == calibration_params(stored)

    def test_out_of_radius_is_miss(self, tmp_path):
        a = tiered(tmp_path, neighbor_radius=5.0)
        b = tiered(tmp_path, neighbor_radius=5.0)
        a.put(with_beta(0.9300), {"r": 0.0123})
        outcome, entry = b.lookup(with_beta(0.9520))
        assert outcome == "miss" and entry is None

    def test_resolution_mismatch_rejected(self, tmp_path):
        """L1/L2 bucket widths must agree or the keys would not line up
        across workers — construction fails loudly."""
        l2 = L2Tier(tmp_path, resolution=1e-2)
        with pytest.raises(ValueError, match="resolution"):
            TieredSolutionCache(1 << 20, resolution=1e-3, l2=l2)

    def test_stats_nest_l2(self, tmp_path):
        a = tiered(tmp_path)
        a.put(with_beta(0.9500), {"r": 0.0123})
        st = a.stats()
        assert st["l2"]["writes"] == 1
        assert st["l2"]["entries"] == 1


# ---------------------------------------------------------------------------
# L2 tier: corruption is loud, counted, never a wrong answer
# ---------------------------------------------------------------------------


class TestTierCorruption:
    KEY = calibration_key(with_beta(0.9500))
    EXACT = calibration_params(with_beta(0.9500))

    def test_torn_payload_degrades_to_miss(self, tmp_path):
        """A killed writer's half-file (or any non-document pickle) is a
        counted, warned degradation and an ordinary miss — never an
        exception, never a deserialized warm start."""
        tier = L2Tier(tmp_path, resolution=1e-3)
        assert tier.put(self.KEY, self.EXACT, {"r": 0.0123})
        tier.path_for(self.KEY).write_bytes(b"\x80\x04torn")
        with pytest.warns(RuntimeWarning, match="torn_payload"):
            doc = tier.lookup(self.KEY, self.EXACT, radius=50.0)
        assert doc is None
        assert tier.degradations >= 1
        assert tier.misses == 1 and tier.hits == 0

    def test_wrong_shape_document_degrades(self, tmp_path):
        """A well-formed pickle that is not a tier document (missing
        key/exact/payload) degrades the same way as a torn one."""
        tier = L2Tier(tmp_path, resolution=1e-3)
        tier.path_for(self.KEY).write_bytes(
            pickle.dumps({"not": "a document"}))
        with pytest.warns(RuntimeWarning, match="torn_payload"):
            assert tier.lookup(self.KEY, self.EXACT, radius=50.0) is None
        assert tier.degradations >= 1

    def test_stale_stamp_degrades_to_miss(self, tmp_path):
        """A document written under another jax lowering / silicon /
        bucket width is stale: skipped loudly, never adopted."""
        tier = L2Tier(tmp_path, resolution=1e-3)
        assert tier.put(self.KEY, self.EXACT, {"r": 0.0123})
        path = tier.path_for(self.KEY)
        doc = pickle.loads(path.read_bytes())
        doc["stamp"] = {"version": -1}
        path.write_bytes(pickle.dumps(doc))
        with pytest.warns(RuntimeWarning, match="stale_stamp"):
            assert tier.lookup(self.KEY, self.EXACT, radius=50.0) is None
        assert tier.degradations >= 1
        assert tier.hits == 0

    def test_eviction_race_degrades_to_miss(self, tmp_path):
        """The index says present, the file is gone (the other worker's
        eviction pass won): a counted 'evicted_during_read' degradation,
        then a miss."""
        tier = L2Tier(tmp_path, resolution=1e-3)
        assert tier.put(self.KEY, self.EXACT, {"r": 0.0123})
        tier.path_for(self.KEY).unlink()
        with pytest.warns(RuntimeWarning, match="evicted_during_read"):
            assert tier.lookup(self.KEY, self.EXACT, radius=50.0) is None
        assert tier.degradations >= 1
        assert tier.misses == 1

    def test_unpicklable_payload_skips_l2_keeps_l1(self, tmp_path):
        """An exotic result object that cannot pickle stays local: the
        write-through degrades (counted, warned), the solve that produced
        it is unharmed, and the L1 still serves it as a hit."""
        cache = tiered(tmp_path)
        cfg = with_beta(0.9500)
        with pytest.warns(RuntimeWarning, match="unwritable"):
            cache.put(cfg, {"r": 0.0123, "fn": lambda x: x})
        assert cache.l2.writes == 0
        assert cache.l2.degradations == 1
        outcome, entry = cache.lookup(cfg)
        assert outcome == "hit" and entry.payload["r"] == 0.0123

    def test_byte_budget_evicts_oldest(self, tmp_path):
        """The directory stays within budget by dropping oldest-mtime
        entries; the survivor is the newest write."""
        tier = L2Tier(tmp_path, byte_budget=1, resolution=1e-3)
        keys = []
        for i, beta in enumerate((0.9400, 0.9450, 0.9500)):
            cfg = with_beta(beta)
            k, e = calibration_key(cfg), calibration_params(cfg)
            keys.append((k, e))
            assert tier.put(k, e, {"r": 0.01 + i})
        assert tier.evictions >= 2
        assert tier.stats()["entries"] == 1
        assert tier.path_for(keys[-1][0]).exists()


# ---------------------------------------------------------------------------
# L1 cache thread-safety (the hammer)
# ---------------------------------------------------------------------------


class TestCacheConcurrency:
    def test_concurrent_hammer_stays_consistent(self):
        """8 threads interleave put/lookup/peek/neighborhood on a small
        byte budget (constant eviction churn). The audit's contract: no
        exceptions, every lookup classifies exactly once, and the
        counters add up."""
        cache = SolutionCache(1 << 12, resolution=1e-3)
        cfgs = [with_beta(0.93 + 0.002 * i) for i in range(12)]
        errors, lookups = [], []
        start = threading.Barrier(8)

        def worker(seed):
            try:
                start.wait(timeout=30)
                n = 0
                for step in range(200):
                    cfg = cfgs[(seed * 7 + step) % len(cfgs)]
                    op = (seed + step) % 4
                    if op == 0:
                        cache.put(cfg, {"r": 0.01, "w": 1.0, "s": seed})
                    elif op == 1:
                        outcome, _ = cache.lookup(cfg)
                        assert outcome in ("hit", "warm", "miss")
                        n += 1
                    elif op == 2:
                        cache.peek(cfg)
                    else:
                        cache.neighborhood(cfg)
                lookups.append(n)
            except Exception as e:  # noqa: BLE001 — the test IS the catch
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        st = cache.stats()
        assert st["hits"] + st["warm"] + st["misses"] == sum(lookups)
        assert st["bytes"] <= 1 << 12 or st["entries"] == 1

    def test_concurrent_tiered_hammer(self, tmp_path):
        """Two workers' caches on one shared directory, hammered from two
        threads each: concurrent write-through, promotion, and eviction
        must neither raise nor ever classify cross-worker material as a
        hit before a local re-store."""
        a, b = tiered(tmp_path), tiered(tmp_path)
        cfgs = [with_beta(0.94 + 0.002 * i) for i in range(6)]
        errors = []
        start = threading.Barrier(4)

        def worker(cache, other_stored, seed):
            try:
                start.wait(timeout=30)
                for step in range(40):
                    cfg = cfgs[(seed + step) % len(cfgs)]
                    if (seed + step) % 2:
                        cache.put(cfg, {"r": 0.01, "s": seed})
                    else:
                        outcome, _ = cache.lookup(cfg)
                        assert outcome in ("hit", "warm", "miss")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(c, o, i))
                   for i, (c, o) in enumerate(
                       [(a, b), (a, b), (b, a), (b, a)])]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors


# ---------------------------------------------------------------------------
# fleet front: pure helpers
# ---------------------------------------------------------------------------


class TestFleetHelpers:
    def test_grid_class_routes_nearest(self):
        assert grid_class((40, 100), 40) == 40
        assert grid_class((40, 100), 95) == 100
        assert grid_class((100, 40), 1000) == 100

    def test_grid_class_ties_to_smaller(self):
        assert grid_class((40, 80), 60) == 40

    def test_grid_class_none_is_first_class(self):
        assert grid_class((100, 40, 40), None) == 40

    def test_grid_class_empty_rejected(self):
        with pytest.raises(ValueError, match="grid classes"):
            grid_class((), 40)

    @staticmethod
    def _ev(kind, rid, *, worker=0, seq=0, run_id="r1"):
        return {"kind": kind, "rid": rid, "worker": worker, "seq": seq,
                "run_id": run_id}

    def test_unacked_is_routed_minus_acked(self):
        events = [
            self._ev("fleet_route", "a", seq=1),
            self._ev("fleet_route", "b", worker=1, seq=2),
            self._ev("fleet_ack", "a", seq=3),
            self._ev("fleet_route", "c", worker=1, seq=4),
        ]
        out = unacked_from_ledger(events)
        assert [ev["rid"] for ev in out] == ["b", "c"]

    def test_unacked_latest_route_wins_and_sorts_by_seq(self):
        events = [
            self._ev("fleet_route", "a", worker=0, seq=5),
            self._ev("fleet_route", "b", worker=1, seq=2),
            self._ev("fleet_route", "a", worker=1, seq=7),  # re-route
        ]
        out = unacked_from_ledger(events)
        assert [ev["rid"] for ev in out] == ["b", "a"]
        assert out[1]["worker"] == 1

    def test_unacked_filters_run_and_worker(self):
        events = [
            self._ev("fleet_route", "a", worker=0, seq=1),
            self._ev("fleet_route", "b", worker=1, seq=2),
            self._ev("fleet_route", "x", worker=0, seq=3, run_id="r2"),
        ]
        assert [ev["rid"] for ev in
                unacked_from_ledger(events, run_id="r1")] == ["a", "b"]
        assert [ev["rid"] for ev in
                unacked_from_ledger(events, worker=1)] == ["b"]
        assert unacked_from_ledger(events, run_id="r3") == []

    def test_unacked_empty_ledger(self):
        assert unacked_from_ledger([]) == []


# ---------------------------------------------------------------------------
# /healthz readiness split
# ---------------------------------------------------------------------------


class TestReadiness:
    @staticmethod
    def _serve(svc, **kw):
        from aiyagari_tpu.serve.service import _http_server

        httpd = _http_server(svc, BASE, 0, **kw)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd, httpd.server_address[1]

    @staticmethod
    def _request(port, *, method="GET", path="/healthz", body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=body, method=method)
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, r.read(), dict(r.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    def test_warming_worker_is_503_not_routed(self):
        """A never-started (still-warming) worker: /healthz is 503
        {'state': 'warming'} with Retry-After, a VALID solve request is
        503 after validation (admission waits, rejections don't), and an
        invalid body still gets its 400 — validation answers while
        warming."""
        svc = SolveService(svc_config(max_batch=1))
        assert svc.ready is False
        httpd, port = self._serve(svc)
        try:
            code, body, headers = self._request(port)
            assert code == 503
            payload = json.loads(body)
            assert payload["ok"] is False and payload["state"] == "warming"
            assert headers.get("Retry-After") == "1"
            code, body, headers = self._request(
                port, method="POST", path="/solve", body=b"{}")
            assert code == 503 and b"warming" in body
            assert headers.get("Retry-After") == "1"
            assert self._request(port, method="POST", path="/solve",
                                 body=b"{nope")[0] == 400
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_ready_worker_is_200_then_503_after_stop(self):
        svc = SolveService(svc_config(max_batch=1))
        httpd, port = self._serve(svc)
        try:
            svc.start()
            assert svc.ready is True
            code, body, _ = self._request(port)
            payload = json.loads(body)
            assert code == 200
            assert payload["ok"] is True and payload["state"] == "ready"
            svc.stop()
            assert svc.ready is False
            assert self._request(port)[0] == 503
        finally:
            httpd.shutdown()
            httpd.server_close()
