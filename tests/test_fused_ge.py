"""One-program equilibrium tests (equilibrium/fused.py, ISSUE 18):

* placement — resolve_ge_loop routes "auto" to the device loop exactly
  where the fused program exists, and an explicit "device" on an
  unsupported combination is loud, never a silent host fallback;
* parity — the fused device bisection lands on the SAME equilibrium rate
  as the host outer loop (both run identical bracket arithmetic on the
  same excess-demand curve), for both solver families at two
  calibrations, and the fused parallel-bracket loop matches the host
  batched loop; the precision ladder's stage switches survive the fusion;
* sentinel/nan — a poisoned solve exits the fused while_loop after ONE
  round (|nan| >= tol is False — the AIYA107 contract) instead of
  burning eq.max_iter device rounds, with and without a sentinel armed;
* quarantine — a nan-poisoned candidate lane in the fused batched round
  is masked and reported while every other lane's outputs stay BITWISE
  equal to the clean round (vmapped lanes are independent);
* donation — donate=True actually donates (the warm/mu operand buffers
  come back deleted), donate=False does not, and a caller-owned warm
  start survives a donated call (fused_ge_operands copies it — the serve
  cache's entries must outlive the solve).

Scale notes follow tests/test_batched_ge.py: 60-point/3-state economies,
eq tol 1e-3 (the inner solves leave ~1e-4 supply noise), EGM for the gap
criterion, VFI pinned on root location only.
"""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

from aiyagari_tpu.config import (
    AiyagariConfig,
    EquilibriumConfig,
    GridSpecConfig,
    IncomeProcess,
    SentinelConfig,
    SolverConfig,
)
from aiyagari_tpu.equilibrium.batched import solve_equilibrium_batched
from aiyagari_tpu.equilibrium.bisection import solve_equilibrium_distribution
from aiyagari_tpu.equilibrium.fused import (
    fused_batched_round,
    fused_ge_batched_operands,
    fused_ge_batched_program,
    fused_ge_operands,
    fused_ge_program,
    resolve_ge_loop,
    solve_equilibrium_fused,
    solve_equilibrium_fused_batched,
)
from aiyagari_tpu.models.aiyagari import AiyagariModel

CFG = AiyagariConfig(income=IncomeProcess(n_states=3),
                     grid=GridSpecConfig(n_points=60))
EQ_TOL = 1e-3
SERIAL_EQ = EquilibriumConfig(max_iter=25, tol=EQ_TOL)
BATCH_EQ = EquilibriumConfig(batch=8, max_iter=8, tol=EQ_TOL)
# Shared solver configs: the fused builders cache compiled programs on the
# static-knob tuple, so reusing these across tests (and calibrations —
# sigma/beta enter as traced operands) keeps the module to a handful of
# trace/compile passes.
SV_EGM = SolverConfig(method="egm")
SV_VFI = SolverConfig(method="vfi")


@pytest.fixture(scope="module")
def model():
    return AiyagariModel.from_config(CFG, jnp.float64)


def _model_at(beta):
    prefs = dataclasses.replace(CFG.preferences, beta=beta)
    return AiyagariModel.from_config(
        dataclasses.replace(CFG, preferences=prefs), jnp.float64)


class TestResolveGeLoop:
    def test_auto_routes_device_where_supported(self):
        sv = SolverConfig(ge_loop="auto")
        assert resolve_ge_loop(sv, aggregation="distribution",
                               endogenous_labor=False) == "device"
        # Every unsupported leg falls back silently under "auto".
        assert resolve_ge_loop(sv, aggregation="simulation",
                               endogenous_labor=False) == "host"
        assert resolve_ge_loop(sv, aggregation="distribution",
                               endogenous_labor=True) == "host"
        assert resolve_ge_loop(sv, aggregation="distribution",
                               endogenous_labor=False,
                               mesh=object()) == "host"

    def test_host_is_always_host(self):
        sv = SolverConfig(ge_loop="host")
        assert resolve_ge_loop(sv, aggregation="distribution",
                               endogenous_labor=False) == "host"

    def test_explicit_device_on_unsupported_combo_is_loud(self):
        sv = SolverConfig(ge_loop="device")
        with pytest.raises(ValueError, match="PRNG panel"):
            resolve_ge_loop(sv, aggregation="simulation",
                            endogenous_labor=False)
        with pytest.raises(ValueError, match="endogenous-labor"):
            resolve_ge_loop(sv, aggregation="distribution",
                            endogenous_labor=True)

    def test_config_validates_the_knob(self):
        with pytest.raises(ValueError, match="ge_loop"):
            SolverConfig(ge_loop="gpu")


class TestSerialParity:
    @pytest.mark.parametrize("beta", [0.94, 0.96])
    def test_egm_same_root_same_rounds(self, beta):
        m = _model_at(beta)
        ser = solve_equilibrium_distribution(m, solver=SV_EGM, eq=SERIAL_EQ)
        dev = solve_equilibrium_fused(m, solver=SV_EGM, eq=SERIAL_EQ)
        assert ser.converged and dev.converged
        # Identical bracket arithmetic: every fused round's midpoint is the
        # host round's midpoint, so the root matches to round-off (the
        # ISSUE 18 acceptance band; measured exactly equal), not just tol.
        assert abs(dev.r - ser.r) <= 1e-10
        assert dev.iterations == ser.iterations
        assert abs(dev.capital - ser.capital) < 1e-6
        # Histories line up round for round.
        np.testing.assert_allclose(dev.r_history, ser.r_history,
                                   rtol=0, atol=1e-12)

    @pytest.mark.parametrize("beta", [0.94, 0.96])
    def test_vfi_same_root(self, beta):
        # Discrete VFI cannot fire |gap| < tol at this grid (its excess
        # demand steps by whole grid cells) — both loops burn max_iter and
        # must localize the same jump point (test_batched_ge's pin).
        m = _model_at(beta)
        ser = solve_equilibrium_distribution(m, solver=SV_VFI, eq=SERIAL_EQ)
        dev = solve_equilibrium_fused(m, solver=SV_VFI, eq=SERIAL_EQ)
        assert abs(dev.r - ser.r) <= 1e-10
        assert dev.iterations == ser.iterations

    def test_ladder_stage_switch_parity(self, model):
        # The mixed-precision ladder's stage switches live inside the inner
        # while_loops; fusing the outer loop around them must not move the
        # root (the ISSUE 18 "thread existing contracts" pin).
        from aiyagari_tpu.ops.precision import default_ladder

        sv = SolverConfig(method="egm", ladder=default_ladder())
        ser = solve_equilibrium_distribution(model, solver=sv, eq=SERIAL_EQ)
        dev = solve_equilibrium_fused(model, solver=sv, eq=SERIAL_EQ)
        assert ser.converged and dev.converged
        # Not the unladdered paths' exact agreement: the fused solves run
        # grid_power=0 (module-docstring deviation) and under the ladder's
        # f32 hot stage that inversion difference sits ABOVE the stage's
        # sign-decision noise floor near the root, so one late bisection
        # branch may differ (measured: one extra host round, |dr| ~ 1.4e-6
        # — stage supply noise over the ~4e2 curve slope). Pin the band.
        assert abs(dev.r - ser.r) <= 1e-4
        assert abs(dev.iterations - ser.iterations) <= 1

    def test_telemetry_ring_records_outer_gaps(self, model):
        from aiyagari_tpu.config import TelemetryConfig

        sv = SolverConfig(method="egm", telemetry=TelemetryConfig())
        dev = solve_equilibrium_fused(model, solver=sv, eq=SERIAL_EQ)
        assert dev.converged
        assert dev.telemetry is not None
        # The outer ring recorded one |gap| per round, ending below tol.
        count = int(np.asarray(dev.telemetry.count))
        assert count == dev.iterations
        resid = np.asarray(dev.telemetry.residuals)[:count]
        assert abs(resid[-1]) < EQ_TOL


class TestBatchedParity:
    def test_fused_batched_matches_host_batched(self, model):
        host = solve_equilibrium_batched(model, solver=SV_EGM, eq=BATCH_EQ)
        dev = solve_equilibrium_fused_batched(model, solver=SV_EGM,
                                              eq=BATCH_EQ)
        assert host.converged and dev.converged
        # Same candidate placement, same sign-change shrink: same root.
        assert abs(dev.r - host.r) <= 1e-10
        assert dev.iterations == host.iterations
        # Histories carry every candidate of every round.
        assert len(dev.r_history) == dev.iterations * BATCH_EQ.batch
        rec = dev.per_iteration[-1]
        assert rec["best_r"] == dev.r
        assert abs(rec["best_gap"]) < EQ_TOL
        assert rec["quarantined"] == [False] * BATCH_EQ.batch

    def test_batch_below_two_rejected(self, model):
        with pytest.raises(ValueError, match="batch >= 2"):
            fused_ge_batched_program(model,
                                     eq=EquilibriumConfig(batch=1))


class TestNanEarlyExit:
    """A nan gap fails `|gap| >= tol`, so the fused while_loop exits after
    the round that produced it — the host loop would burn its remaining
    rounds re-bisecting on garbage (module docstring names the deviation;
    AIYA107 requires the exit)."""

    EQ = EquilibriumConfig(max_iter=10, tol=EQ_TOL)

    def _poisoned_out(self, model, solver):
        # Poison the DEMAND side (labor_raw -> capital_demand -> nan gap):
        # a supply-side poison (nan sigma/warm) is sanitized by the
        # distribution's mass guards into a finite zero-supply gap and
        # keeps bisecting — only a genuinely nan gap exercises the exit.
        fn = fused_ge_program(model, solver=solver, eq=self.EQ,
                              dist_tol=1e-8, dist_max_iter=200,
                              donate=False)
        ops = list(fused_ge_operands(model, self.EQ, solver=solver))
        ops[11] = jnp.asarray(jnp.nan, model.dtype)    # labor_raw
        return fn(*ops)

    def test_plain_loop_exits_after_one_round(self, model):
        out = self._poisoned_out(model, SV_EGM)
        assert int(out["it"]) == 1, "nan gap must exit the loop"
        assert np.isnan(float(out["gap"]))

    def test_sentinel_verdict_on_nan(self, model):
        from aiyagari_tpu.diagnostics.sentinel import verdict_name

        sv = SolverConfig(method="egm", sentinel=SentinelConfig())
        out = self._poisoned_out(model, sv)
        assert int(out["it"]) == 1
        assert verdict_name(int(out["sent"].verdict)) == "nan"


class TestQuarantineBitwise:
    def test_poisoned_lane_leaves_neighbors_bitwise(self, model):
        # One candidate round, one nan-poisoned lane: the mask quarantines
        # exactly that lane, and — vmapped lanes being independent — every
        # other lane's outputs match the clean round BIT FOR BIT.
        sv = SolverConfig(method="egm", max_iter=400)
        kw = dict(solver=sv, eq=EquilibriumConfig(batch=4),
                  dist_tol=1e-8, dist_max_iter=400)
        r_clean = np.array([0.005, 0.010, 0.015, 0.020])
        r_poison = r_clean.copy()
        r_poison[1] = np.nan
        clean = fused_batched_round(model, r_clean, **kw)
        pois = fused_batched_round(model, r_poison, **kw)
        quar = np.asarray(pois["quarantined"])
        assert quar.tolist() == [False, True, False, False]
        assert np.isnan(float(pois["gap"][1]))
        keep = [0, 2, 3]
        for key in ("gap", "supply", "demand"):
            np.testing.assert_array_equal(
                np.asarray(pois[key])[keep], np.asarray(clean[key])[keep],
                err_msg=key)
        np.testing.assert_array_equal(np.asarray(pois["mu"])[keep],
                                      np.asarray(clean["mu"])[keep])
        np.testing.assert_array_equal(np.asarray(pois["warm"])[keep],
                                      np.asarray(clean["warm"])[keep])


class TestDonation:
    def test_donated_operands_are_deleted(self, model):
        fn = fused_ge_program(model, solver=SV_EGM, eq=SERIAL_EQ,
                              donate=True)
        ops = fused_ge_operands(model, SERIAL_EQ, solver=SV_EGM)
        out = fn(*ops)
        assert np.isfinite(float(out["r"]))
        # The donated slots (warm, mu) gave their buffers to XLA.
        assert ops[3].is_deleted()
        assert ops[4].is_deleted()
        # Undonated operands survive.
        assert not ops[5].is_deleted()       # a_grid

    def test_undonated_operands_survive(self, model):
        fn = fused_ge_program(model, solver=SV_EGM, eq=SERIAL_EQ,
                              donate=False)
        ops = fused_ge_operands(model, SERIAL_EQ, solver=SV_EGM)
        fn(*ops)
        assert not ops[3].is_deleted()
        assert not ops[4].is_deleted()

    def test_caller_warm_start_survives_donation(self, model):
        # The serve replay path: a cache-owned warm start must outlive the
        # donated call (fused_ge_operands copies before donation).
        warm = jnp.ones((model.P.shape[0], model.a_grid.shape[0]),
                        model.dtype)
        fn = fused_ge_program(model, solver=SV_EGM, eq=SERIAL_EQ,
                              donate=True)
        ops = fused_ge_operands(model, SERIAL_EQ, solver=SV_EGM,
                                warm_start=warm)
        fn(*ops)
        assert ops[3].is_deleted()           # the copy was donated
        assert not warm.is_deleted()         # the caller's buffer was not
        assert float(warm[0, 0]) == 1.0

    def test_batched_donation(self, model):
        fn = fused_ge_batched_program(model, solver=SV_EGM, eq=BATCH_EQ,
                                      donate=True)
        ops = fused_ge_batched_operands(model, BATCH_EQ, solver=SV_EGM)
        fn(*ops)
        assert ops[2].is_deleted() and ops[3].is_deleted()


class TestDispatchRouting:
    def test_device_loop_matches_host_loop(self):
        from aiyagari_tpu import solve

        kw = dict(method="egm", aggregation="distribution",
                  equilibrium=SERIAL_EQ, on_nonconvergence="ignore")
        host = solve(CFG, solver=SolverConfig(method="egm", ge_loop="host"),
                     **kw)
        dev = solve(CFG, solver=SolverConfig(method="egm",
                                             ge_loop="device"), **kw)
        assert host.converged and dev.converged
        assert abs(dev.r - host.r) <= 1e-10
        assert dev.iterations == host.iterations

    def test_explicit_device_on_simulation_is_loud(self):
        from aiyagari_tpu import solve
        from aiyagari_tpu.config import SimConfig

        with pytest.raises(ValueError, match="ge_loop"):
            solve(CFG, method="egm", aggregation="simulation",
                  sim=SimConfig(periods=200, n_agents=4, discard=50),
                  solver=SolverConfig(method="egm", ge_loop="device"),
                  equilibrium=EquilibriumConfig(max_iter=4, tol=EQ_TOL))
