"""One-program transitions (transition/fused.py, ISSUE 19):

* placement — resolve_transition_loop routes "auto" to the device loop
  exactly where the fused program exists (exogenous labor, no scenario
  mesh, no per-round callback), and an explicit "device" on an
  unsupported combination is loud, never a silent host fallback;
* parity — the fused device Newton lands on the SAME equilibrium price
  path as the host round loop (both apply the identical hoisted
  Jacobian-inverse matmul to the identical excess-demand curve), serial
  and lockstep-sweep, and Newton/damped agree inside the fused loop the
  way they do on the host;
* sentinel/nan — a nan excess demand fails `max_d >= tol`, so the fused
  while_loop exits after the round that produced it (the AIYA107
  contract), raising FloatingPointError bare and returning the "nan"
  verdict with a sentinel armed;
* quarantine — a nan-poisoned scenario lane in the fused sweep is masked
  and reported while every healthy lane's path stays BITWISE equal to
  the clean sweep (vmapped lanes are independent; converged lanes
  freeze);
* donation — donate=True actually donates (the r-path/anchor operand
  buffers come back deleted), donate=False does not, and the caller's
  stationary-anchor arrays survive a donated solve
  (fused_transition_operands copies them — the serve anchor cache's
  entries must outlive the solve);
* dispatch/serve — TransitionConfig.loop threads through
  solve_transition / sweep_transitions with host parity, and a serve
  transition request rides the fused path end-to-end under the
  loop="auto" service default.

Scale notes: 40-point/7-state economy, T=24 — smaller than
tests/test_transition.py (the algorithmic anchors live there; this file
pins placement and parity).
"""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

import aiyagari_tpu as at
from aiyagari_tpu.config import SentinelConfig, SolverConfig
from aiyagari_tpu.models.aiyagari import AiyagariModel
from aiyagari_tpu.transition.fused import (
    fused_transition_operands,
    fused_transition_program,
    resolve_transition_loop,
    solve_transition_fused,
    solve_transitions_sweep_fused,
)
from aiyagari_tpu.transition.mit import (
    solve_transition as host_solve,
    solve_transitions_sweep as host_sweep,
    stationary_anchor,
    transition_jacobian,
)

GRID = 40
T = 24

CFG = at.AiyagariConfig(grid=at.GridSpecConfig(n_points=GRID))
SHOCK = at.MITShock(param="tfp", size=0.01, rho=0.8)
# The fault-injection poison (diagnostics/faults.py): an untempered nan
# TFP path whose first round's excess demand is non-finite.
NAN_SHOCK = at.MITShock(param="tfp", size=float("nan"), rho=0.0)
TC = at.TransitionConfig(T=T, tol=1e-8, method="newton", max_iter=20)


@pytest.fixture(scope="module")
def model():
    return AiyagariModel.from_config(CFG, jnp.float64)


@pytest.fixture(scope="module")
def ss(model):
    return stationary_anchor(model)


@pytest.fixture(scope="module")
def jac(model, ss):
    return transition_jacobian(model, ss, T)


class TestResolveTransitionLoop:
    def test_auto_routes_device_where_supported(self):
        tc = at.TransitionConfig(loop="auto")
        assert resolve_transition_loop(tc, endogenous_labor=False) \
            == "device"
        # Every unsupported leg falls back silently under "auto".
        assert resolve_transition_loop(tc, endogenous_labor=True) == "host"
        assert resolve_transition_loop(tc, endogenous_labor=False,
                                       mesh=object()) == "host"
        assert resolve_transition_loop(
            tc, endogenous_labor=False,
            on_iteration=lambda *a: None) == "host"

    def test_host_is_always_host(self):
        tc = at.TransitionConfig(loop="host")
        assert resolve_transition_loop(tc, endogenous_labor=False) == "host"

    def test_explicit_device_on_unsupported_combo_is_loud(self):
        tc = at.TransitionConfig(loop="device")
        with pytest.raises(ValueError, match="endogenous-labor"):
            resolve_transition_loop(tc, endogenous_labor=True)
        with pytest.raises(ValueError, match="mesh-sharded"):
            resolve_transition_loop(tc, endogenous_labor=False,
                                    mesh=object())
        with pytest.raises(ValueError, match="on_iteration"):
            resolve_transition_loop(tc, endogenous_labor=False,
                                    on_iteration=lambda *a: None)

    def test_config_validates_the_knob(self):
        with pytest.raises(ValueError, match="loop"):
            at.TransitionConfig(loop="gpu")


class TestSerialParity:
    def test_newton_same_path_same_rounds(self, model, ss, jac):
        host = host_solve(model, SHOCK, trans=TC, ss=ss, jacobian=jac)
        dev = solve_transition_fused(model, SHOCK, trans=TC, ss=ss,
                                     jacobian=jac)
        assert host.converged and dev.converged
        # Identical update arithmetic (the hoisted inverse is applied by
        # the same matmul on both sides): the ISSUE 19 acceptance band is
        # 1e-10; measured ~1e-16.
        assert np.max(np.abs(dev.r_path - host.r_path)) <= 1e-10
        assert dev.rounds == host.rounds
        np.testing.assert_allclose(dev.K_ts, host.K_ts, atol=1e-9)
        np.testing.assert_allclose(dev.A_ts, host.A_ts, atol=1e-9)
        # Histories line up round for round.
        np.testing.assert_allclose(dev.max_excess_history,
                                   host.max_excess_history,
                                   rtol=0, atol=1e-12)
        # The capped-result contract rides along: the returned path pairs
        # with the excess measured AT it.
        np.testing.assert_allclose(
            np.max(np.abs(dev.excess)), dev.max_excess_history[-1],
            atol=1e-12)

    def test_newton_vs_damped_inside_fused(self, model, ss, jac):
        rn = solve_transition_fused(model, SHOCK, trans=TC, ss=ss,
                                    jacobian=jac)
        rd = solve_transition_fused(
            model, SHOCK, ss=ss,
            trans=at.TransitionConfig(T=T, tol=1e-8, method="damped",
                                      max_iter=300, damping=0.5))
        assert rn.converged and rd.converged
        # Same residual root, two iterations: one fixed point.
        np.testing.assert_allclose(rn.r_path, rd.r_path, atol=1e-8)
        assert rn.rounds < rd.rounds

    def test_sweep_matches_host_sweep(self, model, ss, jac):
        shocks = [SHOCK, at.MITShock("tfp", 0.005, 0.9),
                  at.MITShock("beta", 0.002, 0.7)]
        host = host_sweep(model, shocks, trans=TC, ss=ss, jacobian=jac)
        dev = solve_transitions_sweep_fused(model, shocks, trans=TC,
                                            ss=ss, jacobian=jac)
        assert bool(np.all(host.converged)) and bool(np.all(dev.converged))
        assert np.max(np.abs(np.asarray(dev.r_paths)
                             - np.asarray(host.r_paths))) <= 1e-10
        assert dev.rounds == host.rounds
        np.testing.assert_allclose(dev.K_ts, host.K_ts, atol=1e-9)
        assert dev.verdicts == ["converged"] * len(shocks)
        assert dev.transitions_per_sec > 0


class TestNanEarlyExit:
    """The fused cond is `max_d >= thr` with max_d seeded +inf: a nan
    excess demand fails it concretely (AIYA107), so the loop exits after
    the round that produced it instead of burning max_iter device
    rounds."""

    def test_raw_program_exits_after_one_round(self, model, ss, jac):
        fn = fused_transition_program(model, trans=TC, donate=False)
        jac_inv = np.linalg.inv(np.asarray(jac, np.float64))
        ops = fused_transition_operands(model, NAN_SHOCK, TC, ss=ss,
                                        jac_inv=jac_inv)
        out = fn(*ops)
        assert int(out["it"]) == 1, "nan excess demand must exit the loop"
        assert np.isnan(float(out["max_d"]))

    def test_bare_solve_raises(self, model, ss, jac):
        with pytest.raises(FloatingPointError, match="non-finite"):
            solve_transition_fused(model, NAN_SHOCK, trans=TC, ss=ss,
                                   jacobian=jac)

    def test_sentinel_verdict_on_nan(self, model, ss, jac):
        sv = SolverConfig(method="egm", sentinel=SentinelConfig())
        res = solve_transition_fused(model, NAN_SHOCK, trans=TC, ss=ss,
                                     jacobian=jac, solver=sv)
        assert not res.converged
        assert res.verdict == "nan"
        assert res.rounds == 1


class TestQuarantineBitwise:
    def test_poisoned_lane_leaves_neighbors_bitwise(self, model, ss, jac):
        clean = [SHOCK, at.MITShock("tfp", 0.005, 0.9),
                 at.MITShock("beta", 0.002, 0.7)]
        poisoned = [clean[0], NAN_SHOCK, clean[2]]
        ref = solve_transitions_sweep_fused(model, clean, trans=TC,
                                            ss=ss, jacobian=jac)
        res = solve_transitions_sweep_fused(model, poisoned, trans=TC,
                                            ss=ss, jacobian=jac)
        assert np.asarray(res.quarantined).tolist() == [False, True, False]
        assert res.verdicts[1] == "nan"
        assert not bool(np.asarray(res.converged)[1])
        # Healthy lanes are untouched by the poison: vmapped lanes are
        # independent and converged lanes freeze, so their paths match
        # the clean sweep BIT FOR BIT.
        for i in (0, 2):
            np.testing.assert_array_equal(np.asarray(res.r_paths)[i],
                                          np.asarray(ref.r_paths)[i])
            np.testing.assert_array_equal(np.asarray(res.K_ts)[i],
                                          np.asarray(ref.K_ts)[i])
            assert bool(np.asarray(res.converged)[i])

    def test_quarantine_off_raises_with_lane(self, model, ss, jac):
        with pytest.raises(FloatingPointError, match=r"scenario\(s\) \[1\]"):
            solve_transitions_sweep_fused(
                model, [SHOCK, NAN_SHOCK], trans=TC, ss=ss, jacobian=jac,
                quarantine=False)


class TestDonation:
    def test_donated_operands_are_deleted(self, model, ss, jac):
        fn = fused_transition_program(model, trans=TC, donate=True)
        jac_inv = np.linalg.inv(np.asarray(jac, np.float64))
        ops = fused_transition_operands(model, SHOCK, TC, ss=ss,
                                        jac_inv=jac_inv)
        out = fn(*ops)
        assert np.isfinite(float(out["max_d"]))
        # The r0 slot seeds the loop carry, so XLA always aliases it and
        # the buffer comes back deleted. The anchor slots (C_term, mu0)
        # are loop-invariant — read every round — so the compiler aliases
        # what it can (at least one here) and leaves the rest alive with
        # the once-per-compile "not usable" warning.
        assert ops[0].is_deleted()
        assert ops[1].is_deleted() or ops[2].is_deleted()
        # Undonated operands survive.
        assert not ops[3].is_deleted()       # a_grid

    def test_undonated_operands_survive(self, model, ss, jac):
        fn = fused_transition_program(model, trans=TC, donate=False)
        jac_inv = np.linalg.inv(np.asarray(jac, np.float64))
        ops = fused_transition_operands(model, SHOCK, TC, ss=ss,
                                        jac_inv=jac_inv)
        fn(*ops)
        assert not ops[0].is_deleted()
        assert not ops[1].is_deleted()
        assert not ops[2].is_deleted()

    def test_anchor_cache_survives_donated_solve(self, model, ss, jac):
        # The serve anchor-reuse path: the cached stationary solution must
        # outlive a donated solve (fused_transition_operands copies the
        # terminal policy / initial distribution before donation).
        res = solve_transition_fused(model, SHOCK, trans=TC, ss=ss,
                                     jacobian=jac, donate=True)
        assert res.converged
        assert not ss.solution.policy_c.is_deleted()
        assert not ss.mu.is_deleted()
        # And the anchor still evaluates.
        assert np.isfinite(float(np.sum(np.asarray(ss.mu))))


class TestDispatchRouting:
    def test_device_loop_matches_host_loop(self, ss, jac):
        host = at.solve_transition(
            CFG, SHOCK, transition=dataclasses.replace(TC, loop="host"),
            ss=ss, jacobian=jac)
        dev = at.solve_transition(
            CFG, SHOCK, transition=dataclasses.replace(TC, loop="device"),
            ss=ss, jacobian=jac)
        assert host.converged and dev.converged
        assert np.max(np.abs(dev.r_path - host.r_path)) <= 1e-10
        assert dev.rounds == host.rounds

    def test_sweep_device_loop_matches_host(self, ss, jac):
        shocks = [SHOCK, at.MITShock("tfp", 0.005, 0.9)]
        host = at.sweep_transitions(
            CFG, shocks, transition=dataclasses.replace(TC, loop="host"),
            ss=ss, jacobian=jac)
        dev = at.sweep_transitions(
            CFG, shocks, transition=dataclasses.replace(TC, loop="device"),
            ss=ss, jacobian=jac)
        assert np.max(np.abs(np.asarray(dev.r_paths)
                             - np.asarray(host.r_paths))) <= 1e-10
        assert dev.rounds == host.rounds

    def test_auto_falls_back_on_mesh_sweep(self, ss, jac):
        # A scenarios-mesh sweep keeps the host lockstep loop under
        # "auto" — placement changes, results do not (the host parity is
        # test_transition's pin; here only the routing must not raise).
        res = at.sweep_transitions(
            CFG, [SHOCK, at.MITShock("tfp", 0.005, 0.9),
                  at.MITShock("beta", 0.002, 0.7),
                  at.MITShock("sigma", 0.05, 0.6)],
            transition=dataclasses.replace(TC, loop="auto"),
            ss=ss, jacobian=jac,
            backend=at.BackendConfig(mesh_axes=("scenarios",),
                                     mesh_shape=(4,)))
        assert bool(np.all(res.converged))

    def test_explicit_device_on_endogenous_labor_is_loud(self):
        with pytest.raises(ValueError, match="endogenous-labor"):
            at.solve_transition(
                at.AiyagariConfig(endogenous_labor=True), SHOCK,
                transition=dataclasses.replace(TC, loop="device"))


class TestServeEndToEnd:
    def test_transition_request_rides_fused_path(self):
        from aiyagari_tpu.serve import ServeConfig, SolveRequest, SolveService

        trans = at.TransitionConfig(T=T, max_iter=15, tol=1e-6,
                                    loop="auto")
        assert ServeConfig().transition.loop == "auto"   # service default
        cfg = ServeConfig(method="egm",
                          equilibrium=at.EquilibriumConfig(max_iter=48,
                                                           tol=2e-4),
                          warm_pool=False, rescue=False, max_batch=2,
                          max_wait_s=2.0, transition=trans)
        s1 = at.MITShock(param="tfp", size=0.01, rho=0.9)
        with SolveService(cfg) as svc:
            r1 = svc.solve(CFG, kind="transition", shock=s1, timeout=600)
            # Two shocks submitted together coalesce into ONE lockstep
            # sweep, which also lowers through the fused loop.
            futs = [svc.submit(SolveRequest(CFG, kind="transition",
                                            shock=at.MITShock(
                                                param="tfp", size=sz,
                                                rho=0.9)))
                    for sz in (0.004, 0.007)]
            batch = [f.result(600) for f in futs]
        assert r1.status == "converged"
        assert r1.r_path.shape == (T,)
        assert all(r.status == "converged" and r.batch == 2
                   for r in batch)
