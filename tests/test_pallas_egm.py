"""Fused Pallas EGM sweep kernel (ops/pallas_egm.py) vs the XLA op chain.

Interpret mode on CPU. The kernel's per-column Euler contraction and the
masked bracket reduces are ordering-identical to the XLA sweep's in exact
arithmetic, so f64 parity is pinned at 1e-9 (observed ~1e-14); f32 rides
the documented ulp band. Also pinned: the escape/retry contract (the fused
route never escapes; injected escapes still drive the sentinel), the
sentinel/telemetry zero-cost off-path bitwise identities, the route-knob
validation, and the AIYA101-107 audit of the registered fused programs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.models.aiyagari import aiyagari_preset
from aiyagari_tpu.ops.egm import (
    EGM_KERNELS,
    egm_step,
    egm_step_transition,
    resolve_egm_kernel,
)
from aiyagari_tpu.solvers.egm import (
    initial_consumption_guess,
    solve_aiyagari_egm,
    solve_aiyagari_egm_safe,
)
from aiyagari_tpu.utils.firm import wage_from_r

R_TEST = 0.04


def _problem(na, dtype=jnp.float64, presweeps=5):
    m = aiyagari_preset(grid_size=na, dtype=dtype)
    w = float(wage_from_r(R_TEST, m.config.technology.alpha,
                          m.config.technology.delta))
    kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta)
    C = initial_consumption_guess(m.a_grid, m.s, R_TEST, w).astype(dtype)
    for _ in range(presweeps):
        C, _ = egm_step(C, m.a_grid, m.s, m.P, R_TEST, w, m.amin, **kw)
    return m, w, C, kw


def _maxdiff(a, b):
    return float(jnp.max(jnp.abs(jnp.asarray(a, jnp.float64)
                                 - jnp.asarray(b, jnp.float64))))


class TestFusedSweepParity:
    @pytest.mark.parametrize("na", [64, 300])
    def test_plain_sweep_and_trajectory(self, na):
        # Single sweep AND a 40-sweep trajectory: the iterate visits the
        # constrained region, the interior, and the grid-top saturation,
        # so every inversion edge case is exercised, not just the warm
        # start's neighborhood.
        m, w, C, kw = _problem(na, presweeps=0)
        Cx = Cf = C
        for _ in range(40):
            Cx, kx = egm_step(Cx, m.a_grid, m.s, m.P, R_TEST, w, m.amin, **kw)
            Cf, kf = egm_step(Cf, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                              egm_kernel="pallas_fused", **kw)
        assert _maxdiff(Cx, Cf) <= 1e-9
        assert _maxdiff(kx, kf) <= 1e-9

    def test_full_solve_parity_f64(self):
        m, w, C, kw = _problem(120, presweeps=0)
        sx = solve_aiyagari_egm(C, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                                tol=1e-6, max_iter=600, **kw)
        sf = solve_aiyagari_egm(C, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                                tol=1e-6, max_iter=600,
                                egm_kernel="pallas_fused", **kw)
        assert float(sf.distance) < 1e-6
        assert int(sx.iterations) == int(sf.iterations)
        assert _maxdiff(sx.policy_c, sf.policy_c) <= 1e-9
        assert _maxdiff(sx.policy_k, sf.policy_k) <= 1e-9

    def test_transition_dated_parity(self):
        # The dated operator with every argument genuinely dated (the
        # generalization the stationary sweep collapses from).
        m, w, C, kw = _problem(90)
        args = (C, m.a_grid, m.s, m.P, 0.05, 0.03, w * 1.02, m.amin)
        dated = dict(sigma_now=kw["sigma"], sigma_next=kw["sigma"] * 1.1,
                     beta_now=kw["beta"] * 0.99)
        cx, kx = egm_step_transition(*args, **dated)
        cf, kf = egm_step_transition(*args, egm_kernel="pallas_fused",
                                     **dated)
        assert _maxdiff(cx, cf) <= 1e-9
        assert _maxdiff(kx, kf) <= 1e-9

    def test_transition_flat_path_collapses_to_plain(self):
        # Stationary-collapse identity ON the fused route itself (the
        # tests/test_transition.py flat-path pin, fused edition).
        m, w, C, kw = _problem(80)
        cs, ks = egm_step(C, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                          egm_kernel="pallas_fused", **kw)
        ct, kt = egm_step_transition(
            C, m.a_grid, m.s, m.P, R_TEST, R_TEST, w, m.amin,
            sigma_now=kw["sigma"], sigma_next=kw["sigma"],
            beta_now=kw["beta"], egm_kernel="pallas_fused")
        assert _maxdiff(cs, ct) == 0.0
        assert _maxdiff(ks, kt) == 0.0

    def test_ladder_f32_stage_band(self):
        # The ladder's hot-stage citizen: a single-stage f32 ladder with
        # the relaxed matmul precision, both routes. The fused kernel's
        # per-column contraction matches the XLA expectation's ordering,
        # so the gap is the f32 rounding of the chain, not a route bias —
        # the documented band is ulp-of-|C| scale (|C| ~ O(10)).
        from aiyagari_tpu.ops.precision import PrecisionLadderConfig

        f32_only = PrecisionLadderConfig(stage_dtypes=("float32",),
                                         matmul_precision=("default",))
        m, w, C, kw = _problem(200, dtype=jnp.float32, presweeps=0)
        common = dict(tol=1e-5, max_iter=400, ladder=f32_only,
                      noise_floor_ulp=24.0, **kw)
        sx = solve_aiyagari_egm(C, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                                **common)
        sf = solve_aiyagari_egm(C, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                                egm_kernel="pallas_fused", **common)
        assert sx.policy_c.dtype == sf.policy_c.dtype == jnp.float32
        assert float(sf.distance) <= float(sf.tol_effective)
        assert _maxdiff(sx.policy_c, sf.policy_c) <= 1e-4

    def test_non_monotone_iterate_not_misbracketed(self):
        # The chunk-skip gates must hold for ANY iterate: the below gate
        # bounds the chunk's a_hat by the chain at the columnwise C-max,
        # so an interior spike inside an otherwise-skippable chunk (an
        # Anderson overshoot, an arbitrary warm start) forces that chunk
        # dense instead of being silently dropped from the brackets and
        # the cummax carry. Regression: the boundary-probe gate diverged
        # from lax.cummax by O(10) absolute here, with no NaN and
        # escaped=False — a silent wrong answer.
        from aiyagari_tpu.ops.pallas_egm import egm_sweep_pallas

        m, w, C0, kw = _problem(150, presweeps=0)
        for col, fac in ((10, 8.0), (40, 3.0), (74, 50.0), (120, 20.0)):
            C = C0.at[:, col].mul(fac)
            a, pa = egm_step(C, m.a_grid, m.s, m.P, R_TEST, w, m.amin, **kw)
            b, pb, _ = egm_sweep_pallas(
                C, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                block_q=30, block_src=30, interpret=True, **kw)
            assert _maxdiff(a, b) <= 1e-9, (col, fac)
            assert _maxdiff(pa, pb) <= 1e-9, (col, fac)

    def test_non_monotone_crossing_spike_carry(self):
        # The cummax CARRY must fold both boundary values of skipped
        # chunks: a spike at the FIRST column of an above-classified chunk
        # plateaus every later effective knot, and dropping it from the
        # carry mis-brackets queries between the later raw values and the
        # spike. Regression: measured 0.075 absolute policy error (silent,
        # escaped=False) before the last_cm carry advance.
        from aiyagari_tpu.ops.pallas_egm import egm_sweep_pallas

        N, na = 2, 768
        a_grid = jnp.linspace(0.0, 10.0, na)
        s = jnp.ones((N,))
        P = jnp.eye(N)
        kw = dict(sigma=1.0, beta=1.0)
        # P=I, sigma=1, beta=1, r=w=0 collapse the chain to a_hat = C +
        # a_grid: the spike geometry is set directly.
        for cols, val in (((256,), 50.0), ((511,), 50.0), ((300,), 50.0),
                          ((256, 600), 25.0)):
            C = jnp.broadcast_to(a_grid * 0.0 + 0.3, (N, na))
            for c in cols:
                C = C.at[:, c].set(val)
            _, wpk = egm_step(C, a_grid, s, P, 0.0, 0.0, 0.0, **kw)
            for bq, bs in ((256, 256), (64, 64), (256, 128)):
                _, gpk, _ = egm_sweep_pallas(
                    C, a_grid, s, P, 0.0, 0.0, 0.0, block_q=bq,
                    block_src=bs, interpret=True, **kw)
                assert _maxdiff(wpk, gpk) <= 1e-9, (cols, val, bq, bs)

    def test_block_tiling_invariance(self):
        # Tiling must be semantics-free: different (block_q, block_src)
        # change only the reduce groupings (max/min — exact) and the
        # cummax carry schedule (exact in f64), never the result.
        from aiyagari_tpu.ops.pallas_egm import egm_sweep_pallas

        m, w, C, kw = _problem(150)
        outs = [
            egm_sweep_pallas(C, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                             block_q=bq, block_src=bs, interpret=True, **kw)
            for bq, bs in ((256, 256), (64, 32), (150, 30))
        ]
        for C2, k2, _ in outs[1:]:
            assert _maxdiff(outs[0][0], C2) == 0.0
            assert _maxdiff(outs[0][1], k2) == 0.0


class TestFusedRouteContract:
    def test_route_names_and_validation(self):
        assert set(EGM_KERNELS) == {"auto", "xla", "pallas_inverse",
                                    "pallas_fused"}
        assert resolve_egm_kernel("auto") == "xla"
        with pytest.raises(ValueError, match="unknown egm_kernel"):
            resolve_egm_kernel("pallas")           # typo-adjacent
        with pytest.raises(ValueError, match="BackendConfig"):
            resolve_egm_kernel("numpy")            # wrong knob, say which
        m, w, C, kw = _problem(40)
        with pytest.raises(ValueError, match="unknown egm_kernel"):
            egm_step(C, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                     egm_kernel="pallas_fussed", **kw)

    def test_dispatch_validates_before_solving(self):
        import aiyagari_tpu as at

        cfg = at.AiyagariConfig()
        with pytest.raises(ValueError, match="unknown egm_kernel"):
            at.solve(cfg, method="egm",
                     solver=at.SolverConfig(method="egm", egm_kernel="xl"))
        with pytest.raises(ValueError, match="backend='jax'"):
            at.solve(cfg, method="egm",
                     backend=at.BackendConfig(backend="numpy"),
                     solver=at.SolverConfig(method="egm",
                                            egm_kernel="pallas_fused"))

    def test_transition_rejects_pallas_inverse(self):
        m, w, C, kw = _problem(40)
        with pytest.raises(ValueError, match="escape retry"):
            egm_step_transition(
                C, m.a_grid, m.s, m.P, R_TEST, R_TEST, w, m.amin,
                sigma_now=kw["sigma"], sigma_next=kw["sigma"],
                beta_now=kw["beta"], egm_kernel="pallas_inverse")
        # Hoisted: the solve-level extractor rejects the route BEFORE the
        # stationary anchor solve spends its work (mit.py _egm_kernel_of),
        # and the batched GE closure rejects it too (its vmapped solves
        # pin grid_power=0, where the windowed route cannot exist).
        from aiyagari_tpu.config import SolverConfig
        from aiyagari_tpu.equilibrium.batched import excess_demand_batch
        from aiyagari_tpu.transition.mit import _egm_kernel_of

        with pytest.raises(ValueError, match="escape retry"):
            _egm_kernel_of(SolverConfig(egm_kernel="pallas_inverse"))
        with pytest.raises(ValueError, match="batched GE"):
            excess_demand_batch(
                m, np.array([0.02]),
                solver=SolverConfig(method="egm", tol=1e-6, max_iter=50,
                                    egm_kernel="pallas_inverse"))

    def test_fused_route_never_escapes(self):
        m, w, C, kw = _problem(64)
        _, _, esc = egm_step(C, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                             with_escape=True, egm_kernel="pallas_fused",
                             grid_power=2.0, **kw)
        assert not bool(esc)

    def test_safe_wrapper_contract_preserved(self):
        # The host-retry wrapper composes: the fused route converges with
        # escaped=False (retry never arms), and an INJECTED escape still
        # raises the flag and drives the sentinel's "escape" verdict — the
        # poisoning/host-retry contract survives the route swap.
        from aiyagari_tpu.config import FaultPlan, SentinelConfig
        from aiyagari_tpu.diagnostics.sentinel import verdict_name

        m, w, C, kw = _problem(80, presweeps=0)
        sol = solve_aiyagari_egm_safe(
            C, m.a_grid, m.s, m.P, R_TEST, w, m.amin, tol=1e-6,
            max_iter=600, grid_power=2.0, egm_kernel="pallas_fused", **kw)
        assert float(sol.distance) < 1e-6
        assert not bool(sol.escaped)
        forced = solve_aiyagari_egm(
            C, m.a_grid, m.s, m.P, R_TEST, w, m.amin, tol=1e-6,
            max_iter=600, egm_kernel="pallas_fused",
            faults=FaultPlan(force_escape=True),
            sentinel=SentinelConfig(), **kw)
        assert bool(forced.escaped)
        assert verdict_name(forced.sentinel.verdict) == "escape"

    def test_labor_family_rejects_pallas_routes_loudly(self):
        # The fused kernel implements the exogenous-labor chain only; a
        # Pallas route on the labor family must fail loudly, never fall
        # back to the XLA sweep silently.
        import aiyagari_tpu as at
        from aiyagari_tpu.ops.egm import require_xla_egm_kernel

        assert require_xla_egm_kernel("auto", "x") == "xla"
        with pytest.raises(ValueError, match="exogenous-labor"):
            require_xla_egm_kernel("pallas_fused", "the labor family")
        cfg = at.AiyagariConfig(endogenous_labor=True,
                                grid=at.GridSpecConfig(n_points=24))
        with pytest.raises(ValueError, match="exogenous-labor"):
            at.solve(cfg, method="egm", aggregation="distribution",
                     solver=at.SolverConfig(method="egm",
                                            egm_kernel="pallas_fused"),
                     equilibrium=at.EquilibriumConfig(max_iter=1))

    def test_knob_reaches_batched_ge_and_transition_rounds(self):
        # Regression: the knob was validated in dispatch but silently
        # dropped by the batched GE closure and the transition round
        # loops. The batched excess-demand program (a vmapped fused solve)
        # must honor it with gap parity vs the XLA route; the transition
        # module's extractor must forward the configured route.
        from aiyagari_tpu.config import SolverConfig
        from aiyagari_tpu.equilibrium.batched import excess_demand_batch
        from aiyagari_tpu.models.aiyagari import aiyagari_preset
        from aiyagari_tpu.transition.mit import _egm_kernel_of

        assert _egm_kernel_of(None) == "auto"
        assert _egm_kernel_of(
            SolverConfig(egm_kernel="pallas_fused")) == "pallas_fused"

        model = aiyagari_preset(grid_size=40, dtype=jnp.float64)
        r_batch = np.array([0.02, 0.035])
        gaps = {}
        for kern in ("xla", "pallas_fused"):
            solver = SolverConfig(method="egm", tol=1e-6, max_iter=400,
                                  egm_kernel=kern)
            gap, _ = excess_demand_batch(model, r_batch, solver=solver,
                                         dist_tol=1e-9, dist_max_iter=2000)
            gaps[kern] = np.asarray(gap)
        np.testing.assert_allclose(gaps["pallas_fused"], gaps["xla"],
                                   rtol=0, atol=1e-9)

    def test_force_interpret_helper(self):
        from aiyagari_tpu.ops.pallas_support import (
            force_interpret,
            pallas_interpret_mode,
        )

        default = pallas_interpret_mode()
        assert default == (jax.default_backend() != "tpu")
        with force_interpret(False):
            assert pallas_interpret_mode() is False
            with force_interpret(True):
                assert pallas_interpret_mode() is True
            assert pallas_interpret_mode() is False
        assert pallas_interpret_mode() == default


class TestFusedCarriesAndAudit:
    def test_telemetry_off_bitwise_pin(self):
        # The recorder is write-only: the telemetry-off fused solve must
        # be BITWISE identical to the recorder-on one, and the on-solve
        # must actually have recorded.
        from aiyagari_tpu.config import TelemetryConfig

        m, w, C, kw = _problem(64, presweeps=0)
        args = (C, m.a_grid, m.s, m.P, R_TEST, w, m.amin)
        common = dict(tol=1e-6, max_iter=300, egm_kernel="pallas_fused",
                      **kw)
        off = solve_aiyagari_egm(*args, **common)
        on = solve_aiyagari_egm(*args, telemetry=TelemetryConfig(capacity=64),
                                **common)
        assert np.array_equal(np.asarray(off.policy_c),
                              np.asarray(on.policy_c))
        assert np.array_equal(np.asarray(off.policy_k),
                              np.asarray(on.policy_k))
        assert int(off.iterations) == int(on.iterations)
        assert off.telemetry is None
        assert int(on.telemetry.count) == int(on.iterations)

    def test_sentinel_off_bitwise_pin(self):
        from aiyagari_tpu.config import SentinelConfig
        from aiyagari_tpu.diagnostics.sentinel import verdict_name

        m, w, C, kw = _problem(64, presweeps=0)
        args = (C, m.a_grid, m.s, m.P, R_TEST, w, m.amin)
        common = dict(tol=1e-6, max_iter=300, egm_kernel="pallas_fused",
                      **kw)
        off = solve_aiyagari_egm(*args, **common)
        on = solve_aiyagari_egm(*args, sentinel=SentinelConfig(), **common)
        assert np.array_equal(np.asarray(off.policy_c),
                              np.asarray(on.policy_c))
        assert int(off.iterations) == int(on.iterations)
        assert off.sentinel is None
        assert verdict_name(on.sentinel.verdict) == "ok"

    def test_registered_fused_programs_audit_clean(self):
        # AIYA101-107 over the registered fused programs: the structural
        # certificate the ISSUE's acceptance names — scatter-free, no
        # precision leak (f64 AND the declared-f32 ladder stage), no host
        # sync in the loop, telemetry-noop, live stable carries, NaN exit.
        from aiyagari_tpu.analysis.jaxpr_audit import audit_program
        from aiyagari_tpu.analysis.registry import registered_programs

        specs = {p.name: p for p in registered_programs(families=("egm",))}
        for name in ("egm/sweep_fused", "egm/sweep_fused_f32_stage"):
            findings = [f for f in audit_program(specs[name])
                        if not f.suppressed]
            assert findings == [], [f.message for f in findings]

    def test_fused_roofline_model(self):
        # The priced fusion claim: one read + one write of the state per
        # sweep instead of one per op — modeled bytes must be well under
        # half the XLA chain's at the same (N, na, dtype) — and the model
        # is dtype-aware like every other cost model.
        from aiyagari_tpu.diagnostics.roofline import (
            achieved_bandwidth_gbs,
            egm_fused_sweep_cost,
            egm_sweep_cost,
        )

        N, na = 7, 40_000
        fused = egm_fused_sweep_cost(N, na, 4)
        chain = egm_sweep_cost(N, na, 4)
        assert fused.hbm_bytes < 0.5 * chain.hbm_bytes
        assert egm_fused_sweep_cost(N, na, 8).hbm_bytes == pytest.approx(
            2.0 * fused.hbm_bytes)
        # The trade is explicit: the fused route pays expectation
        # RECOMPUTE (each query-tile program re-evaluates boundary/straddle
        # columns), so its modeled MXU work exceeds the chain's single
        # full-width matmul; the model must say so, not flatter it.
        assert fused.mxu_flops > chain.mxu_flops
        assert achieved_bandwidth_gbs(fused, 1e-3) == pytest.approx(
            fused.hbm_bytes / 1e-3 / 1e9)
