"""Property-based tests (hypothesis) for the numeric kernels.

These complement the example-based suites with randomized adversarial inputs
against independent oracles (NumPy/SciPy) and invariants (SURVEY.md §4.1-4.2).
Shapes are drawn from small fixed sets so jit compiles a bounded number of
programs; hypothesis varies the VALUES.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Optional dependency (pyproject [test] extra): without it this module must
# SKIP, not abort the whole suite at collection time.
pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st
from hypothesis.extra.numpy import arrays

from aiyagari_tpu.ops.interp import (
    bucket_index,
    inverse_interp_power_grid,
    linear_interp,
    pchip_interp,
    power_bucket_index,
    prolong_power_grid,
)
from aiyagari_tpu.utils.markov import rouwenhorst, stationary_distribution, tauchen
from aiyagari_tpu.utils.stats import gini, lorenz_curve

SET = settings(max_examples=25, deadline=None,
               suppress_health_check=[HealthCheck.too_slow])

# Subnormals excluded: weights at O(1e-311) make cumsum/total carry ~1e-12
# RELATIVE rounding (the subnormal ulp is a fixed 5e-324 absolute), busting
# the 1e-9 share-identity tolerances — found by hypothesis in the Lorenz
# convexity property. Normal-range tiny values (>= ~2.2e-308) keep the usual
# 1e-16 relative ulp and stay in scope.
finite = dict(allow_nan=False, allow_infinity=False, allow_subnormal=False)


def _monotone_knots(raw, span=50.0):
    """Sorted knot vector spanning ~[0, span] from raw uniforms; interior
    duplicate values survive (cumsum of non-negative gaps, some zero) —
    exactly the f32 collision case the kernels must handle."""
    gaps = np.abs(raw)
    total = gaps.sum()
    if total <= 0:
        return np.linspace(0.0, span, raw.shape[0])
    return np.cumsum(gaps) / total * span


class TestInversePowerGridProperties:
    @SET
    @given(
        raw=arrays(np.float64, (400,), elements=st.floats(0.0, 1.0, **finite)),
        power=st.sampled_from([1.0, 2.0, 3.0, 7.0]),
        shift=st.floats(-5.0, 5.0, **finite),
    )
    def test_dense_route_matches_linear_interp_oracle(self, raw, power, shift):
        n_k = n_q = 400      # dense route (<= cutoff)
        lo, hi = 0.0, 52.0
        x = np.sort(_monotone_knots(raw) + shift)
        gk = lo + (hi - lo) * (np.arange(n_k) / (n_k - 1)) ** power
        gq = lo + (hi - lo) * (np.arange(n_q) / (n_q - 1)) ** power
        got = np.asarray(inverse_interp_power_grid(jnp.asarray(x), lo, hi, power, n_q))
        want = np.asarray(linear_interp(jnp.asarray(x), jnp.asarray(gk), jnp.asarray(gq)))
        # Compare on the interior; below the first knots the two routes use
        # different (both valid) degenerate-edge conventions when the first
        # knots collide, and above the last knot the kernel truncates to the
        # grid top by contract.
        interior = (gq > x[1]) & (gq <= x[-1])
        assert np.all(np.abs(got[interior] - want[interior]) < 1e-8)
        top = gq > x[-1]
        if top.any():
            assert np.all(np.abs(got[top] - gk[-1]) < 1e-8)

    @SET
    @given(
        raw=arrays(np.float64, (6000,), elements=st.floats(0.0, 1.0, **finite)),
        power=st.sampled_from([2.0, 7.0]),
    )
    @pytest.mark.slow
    def test_windowed_route_exact_or_loudly_poisoned(self, raw, power):
        n = 6000             # windowed route (> cutoff)
        lo, hi = 0.0, 52.0
        x = _monotone_knots(raw)
        gk = lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power
        gq = gk
        got = np.asarray(inverse_interp_power_grid(jnp.asarray(x), lo, hi, power, n))
        if np.isnan(got).any():
            # The escape contract: poisoning is all-or-nothing, never a
            # silently wrong value.
            assert np.isnan(got).all()
            return
        want = np.asarray(linear_interp(jnp.asarray(x), jnp.asarray(gk), jnp.asarray(gq)))
        interior = (gq > x[1]) & (gq <= x[-1])
        assert np.all(np.abs(got[interior] - want[interior]) < 1e-8)

    @SET
    @given(
        y=arrays(np.float64, (3, 300), elements=st.floats(-100.0, 100.0, **finite)),
        power=st.sampled_from([1.0, 2.0, 7.0]),
        n_new=st.sampled_from([150, 300, 1200]),
    )
    def test_prolong_matches_linear_interp_oracle(self, y, power, n_new):
        lo, hi = 0.0, 52.0
        n_prev = y.shape[-1]
        gp = lo + (hi - lo) * (np.arange(n_prev) / (n_prev - 1)) ** power
        gn = lo + (hi - lo) * (np.arange(n_new) / (n_new - 1)) ** power
        got = np.asarray(prolong_power_grid(jnp.asarray(y), lo, hi, power, n_new))
        want = np.asarray(jax.vmap(
            lambda r: linear_interp(jnp.asarray(gp), r, jnp.asarray(gn))
        )(jnp.asarray(y)))
        np.testing.assert_allclose(got, want, atol=1e-8)


class TestLocatorProperties:
    @SET
    @given(q=arrays(np.float64, (200,), elements=st.floats(-10.0, 60.0, **finite)))
    def test_bucket_index_matches_searchsorted(self, q):
        x = np.sort(np.unique(np.linspace(0.0, 52.0, 80)))
        got = np.asarray(bucket_index(jnp.asarray(x), jnp.asarray(q)))
        want = np.clip(np.searchsorted(x, q, side="right") - 1, 0, len(x) - 2)
        np.testing.assert_array_equal(got, want)

    @SET
    @given(
        q=arrays(np.float64, (200,), elements=st.floats(0.0, 52.0, **finite)),
        power=st.sampled_from([2.0, 7.0]),
    )
    def test_power_bucket_index_brackets_queries(self, q, power):
        n = 5000
        lo, hi = 0.0, 52.0
        x = lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power
        idx = np.asarray(power_bucket_index(jnp.asarray(x), jnp.asarray(q), lo, hi, power))
        assert np.all((idx >= 0) & (idx <= n - 2))
        inside = (q >= x[0]) & (q < x[-1])
        assert np.all(x[idx[inside]] <= q[inside])
        assert np.all(q[inside] < x[idx[inside] + 1])


class TestPchipProperties:
    @SET
    @given(
        gaps=arrays(np.float64, (40,), elements=st.floats(0.05, 2.0, **finite)),
        vals=arrays(np.float64, (40,), elements=st.floats(0.0, 1.0, **finite)),
    )
    def test_monotone_data_gives_monotone_interpolant(self, gaps, vals):
        # Shape preservation is pchip's defining property (Fritsch-Carlson).
        x = np.cumsum(gaps)
        y = np.cumsum(np.abs(vals))
        q = np.linspace(x[0], x[-1], 400)
        out = np.asarray(pchip_interp(jnp.asarray(x), jnp.asarray(y), jnp.asarray(q)))
        assert np.all(np.diff(out) >= -1e-9)
        assert out.min() >= y[0] - 1e-9 and out.max() <= y[-1] + 1e-9


class TestStatsProperties:
    @SET
    @given(
        w=arrays(np.float64, (500,), elements=st.floats(0.0, 1e4, **finite)),
        scale=st.floats(0.1, 100.0, **finite),
    )
    def test_gini_bounds_and_scale_invariance(self, w, scale):
        if w.sum() <= 0:
            return
        g1 = float(gini(jnp.asarray(w)))
        g2 = float(gini(jnp.asarray(w * scale)))
        assert -1e-9 <= g1 <= 1.0
        assert abs(g1 - g2) < 1e-8
        # Permutation invariance.
        g3 = float(gini(jnp.asarray(np.sort(w)[::-1].copy())))
        assert abs(g1 - g3) < 1e-8

    @SET
    @given(w=arrays(np.float64, (300,), elements=st.floats(0.0, 1e4, **finite)))
    def test_lorenz_curve_is_convex_and_below_diagonal(self, w):
        if w.sum() <= 0:
            return
        pop, wealth = lorenz_curve(jnp.asarray(w))
        pop, wealth = np.asarray(pop), np.asarray(wealth)
        assert np.all(wealth <= pop + 1e-9)
        assert np.all(np.diff(wealth) >= -1e-12)
        # Convexity: increments are non-decreasing (shares sorted ascending).
        inc = np.diff(wealth)
        assert np.all(np.diff(inc) >= -1e-9)


class TestMarkovProperties:
    @SET
    @given(
        rho=st.floats(0.0, 0.98, **finite),
        sigma_e=st.floats(0.01, 1.0, **finite),
        n=st.sampled_from([3, 7, 11]),
    )
    def test_discretizers_yield_stochastic_matrices_with_fixed_point(self, rho, sigma_e, n):
        from aiyagari_tpu.config import IncomeProcess

        proc = IncomeProcess(rho=rho, sigma_e=sigma_e, n_states=n)
        for build in (tauchen, rouwenhorst):
            grid, P = build(proc)
            P = np.asarray(P)
            assert P.shape == (n, n)
            assert np.all(P >= -1e-12)
            np.testing.assert_allclose(P.sum(axis=1), 1.0, atol=1e-9)
            pi = np.asarray(stationary_distribution(jnp.asarray(P)))
            np.testing.assert_allclose(pi @ P, pi, atol=1e-8)
            assert np.all(np.asarray(grid)[:-1] <= np.asarray(grid)[1:])
