"""Adversarial source file for the lint level (tests/test_static_analysis.py).

Every statement below violates exactly one source rule (AIYA2xx); the
trailing block demonstrates the `# noqa:` suppression syntax. The file is
only ever READ by the lint — never imported (it does not match test_*.py,
so pytest never collects it either).
"""

import jax  # noqa: F401  (fixture: keep the attribute chains realistic)
from jax.sharding import PartitionSpec  # AIYA201: direct sharding import


def leaky(a_grid, dist):
    lo = float(a_grid[0])          # AIYA202: eager per-element fetch
    tol = dist.item()              # AIYA202: .item() device sync
    jax.debug.print("lo={}", lo)   # AIYA203: bare debug print
    spec = jax.sharding.PartitionSpec()   # AIYA201: direct attribute chain
    spec2 = PartitionSpec("scenarios")    # AIYA201: raw spec construction
    return lo, tol, spec, spec2, PartitionSpec


def deliberate(host_probes):
    # Host numpy after an explicit device_get — the sanctioned suppression.
    return float(host_probes[0])   # noqa: AIYA202
