"""Adversarial source file for AIYA204 (tests/test_static_analysis.py).

Both functions below re-hardcode a route choice outside the sanctioned
resolvers — the first maps the "auto" literal onto a concrete route, the
second splits on the platform probe — and each must trip exactly
route-resolution-discipline (no cross-fire from the other source rules:
nothing here imports jax.sharding, fetches a host scalar, or debug-prints).
The file is only ever READ by the lint, never imported.
"""

import jax  # noqa: F401  (fixture: keep the platform probe realistic)


def my_resolver(backend):
    if backend == "auto":               # AIYA204: "auto" -> literal route
        return "transpose"
    return backend


def my_method_split():
    # AIYA204: platform-split route choice outside the resolvers.
    return "scan" if jax.default_backend() == "cpu" else "sort"
