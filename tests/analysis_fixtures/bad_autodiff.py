"""Adversarial source file for AIYA205 (tests/test_static_analysis.py).

Every call below aims an autodiff operator straight at an unrolled
while_loop solver fixed point — the exact mistake the IFT wrappers
(ops/implicit.py, ISSUE 17) exist to prevent — and each must trip exactly
ift-differentiation-discipline (no cross-fire from the other source
rules: nothing here imports jax.sharding, fetches a host scalar, or
debug-prints). The file is only ever READ by the lint, never imported.
"""

import jax
from jax import grad, value_and_grad  # noqa: F401  (fixture imports)

from aiyagari_tpu.sim.distribution import stationary_distribution  # noqa: F401
from aiyagari_tpu.solvers.egm import solve_aiyagari_egm  # noqa: F401
from aiyagari_tpu.transition.mit import solve_transition  # noqa: F401


def bad_attribute_form(args):
    # AIYA205: jax.grad of the raw EGM sweep's while_loop.
    return jax.grad(solve_aiyagari_egm)(*args)


def bad_bare_name_form(args):
    # AIYA205: bare `grad` from `from jax import grad`.
    return grad(stationary_distribution)(*args)


def bad_vag_form(args):
    # AIYA205: value_and_grad with extra kwargs still names the solver.
    return jax.value_and_grad(solve_transition, argnums=1)(*args)


def sanctioned_wrapper_form(solver_implicit, args):
    # NOT flagged: the implicit wrappers are the sanctioned door.
    return jax.grad(solver_implicit)(*args)
