"""Adversarial programs for the jaxpr auditor (tests/test_static_analysis.py).

Each function is a minimal traceable program engineered to violate EXACTLY
ONE jaxpr-level rule (analysis/rules.py AIYA1xx) — the tier-1 tests pin
both that the rule fires on it and that NO OTHER rule cross-fires, so a
rule implementation that over-matches breaks loudly here before it breaks
a real audit.

Loaded by the test via importlib (the file deliberately does not match
test_*.py); never imported by the package.
"""

import jax
import jax.numpy as jnp


# -- AIYA101: an unguarded scatter on the hot path -------------------------

def scatter_program(mu, idx, w_lo, P):
    """The pre-PR 5 reference formulation, registered as scatter-free: the
    `.at[].add` lottery with no validity fallback around it."""
    rows = jnp.broadcast_to(jnp.arange(mu.shape[0])[:, None], mu.shape)
    out = jnp.zeros_like(mu)
    out = out.at[rows, idx].add(mu * w_lo)
    out = out.at[rows, idx + 1].add(mu * (1.0 - w_lo))
    return jnp.matmul(P.T, out, precision=jax.lax.Precision.HIGHEST)


# -- AIYA102: an f64 leak inside a declared-f32 stage ----------------------

def precision_leak_program(C, P):
    """Declared float32 stage that silently upcasts its expectation to
    float64 mid-sweep (and casts back, hiding the leak from the caller)."""
    ev = jnp.matmul(P.astype(jnp.float64), C.astype(jnp.float64))
    return (C + ev.astype(jnp.float32)) * 0.5


# -- AIYA103: a host callback inside the hot loop --------------------------

def _untagged_callback(x):  # pragma: no cover - never actually invoked
    pass


def host_sync_program(x):
    """A per-sweep debug callback with NO __aiyagari_callback_tag__."""

    def body(c):
        jax.debug.callback(_untagged_callback, c, ordered=False)
        return c - 1.0

    return jax.lax.while_loop(lambda c: c > 0.0, body, x)


def _tagged_callback(x):  # pragma: no cover - never actually invoked
    pass


_tagged_callback.__aiyagari_callback_tag__ = "pushforward-degradation"


def host_sync_tagged_program(x):
    """The same loop with the whitelisted degradation-event tag — must be
    CLEAN (the ops/pushforward._record_fallback contract)."""

    def body(c):
        jax.debug.callback(_tagged_callback, c, ordered=False)
        return c - 1.0

    return jax.lax.while_loop(lambda c: c > 0.0, body, x)


# -- AIYA104: telemetry that does not compile out --------------------------

def telemetry_leak_program(x, capacity: int):
    """Carries a ring buffer UNCONDITIONALLY — the recorder-off trace still
    contains the capacity-shaped value, which is exactly the regression the
    telemetry-noop rule exists to catch."""
    ring = jnp.zeros((capacity,), jnp.float32) + x.astype(jnp.float32)
    return x * 2.0, ring


def telemetry_unwired_program(x):
    """A 'telemetry-on' build that carries NO ring at all: the wiring-broken
    direction of the telemetry-noop check."""
    return x * 2.0


# -- AIYA105: a dead while-loop carry --------------------------------------

def dead_carry_program(x):
    """Carries `junk`, rewritten every sweep, read by nothing: not the
    condition, not another slot, and the caller drops it."""

    def body(c):
        i, y, junk = c
        return i + 1, y * 0.5, junk + y

    def cond(c):
        return c[0] < 10

    _, y_final, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), x, jnp.zeros_like(x)))
    return y_final


# -- AIYA107: a residual cond that keeps running on NaN --------------------

def nan_trap_program(x, tol):
    """The anti-pattern AIYA107 exists to catch: the continue-condition is
    written `~(dist < tol)`, which is TRUE for a NaN dist (NaN comparisons
    are False), so a poisoned iterate runs to max_iter on garbage. The
    framework's `dist >= tol` discipline is False on NaN and exits."""

    def cond(c):
        dist, it = c[1], c[2]
        return jnp.logical_not(dist < tol) & (it < 100)

    def body(c):
        y, _, it = c
        y_new = y * 0.5
        return y_new, jnp.max(jnp.abs(y_new - y)), it + 1

    y, dist, _ = jax.lax.while_loop(
        cond, body, (x, jnp.asarray(jnp.inf, x.dtype), jnp.int32(0)))
    return y, dist


def nan_exit_program(x, tol):
    """The same loop with the sanctioned NaN-exiting comparison — must be
    CLEAN."""

    def cond(c):
        return (c[1] >= tol) & (c[2] < 100)

    def body(c):
        y, _, it = c
        y_new = y * 0.5
        return y_new, jnp.max(jnp.abs(y_new - y)), it + 1

    y, dist, _ = jax.lax.while_loop(
        cond, body, (x, jnp.asarray(jnp.inf, x.dtype), jnp.int32(0)))
    return y, dist


# -- AIYA106: a weak-typed carry -------------------------------------------

def weak_carry_program(x):
    """Bare Python-float carry init: the weak-typed-carry recompile hazard."""
    return jax.lax.while_loop(
        lambda c: c[0] < 3.0,
        lambda c: (c[0] + 1.0, c[1] + jnp.sum(x)),
        (0.0, 0.0))
