"""Unit tests for Markov-chain construction (SURVEY.md §4.1)."""

import numpy as np
import pytest
from scipy.integrate import quad
from scipy.stats import norm

from aiyagari_tpu.config import IncomeProcess, KSShockProcess
from aiyagari_tpu.utils.markov import (
    KS_STATE_GRID_ORDER,
    ks_conditional_eps_matrices,
    ks_transition_matrix,
    normalized_labor,
    stationary_distribution,
    tauchen,
)


class TestTauchen:
    def test_rows_sum_to_one(self):
        _, P = tauchen(IncomeProcess())
        np.testing.assert_allclose(P.sum(axis=1), 1.0, atol=1e-12)

    def test_grid_matches_reference_spec(self):
        # l_i = (i-4)*sigma_e for i=1..7 (Aiyagari_VFI.m:18-21).
        l, _ = tauchen(IncomeProcess(sigma_e=0.75, n_states=7))
        np.testing.assert_allclose(l, (np.arange(1, 8) - 4) * 0.75)

    def test_matches_quadrature(self):
        # The reference integrates the normal pdf numerically
        # (Aiyagari_VFI.m:27-35); our closed form must agree.
        proc = IncomeProcess(rho=0.75, sigma_e=0.75, n_states=7)
        l, P = tauchen(proc)
        sd = proc.sigma_e * np.sqrt(1 - proc.rho**2)
        edges = np.concatenate(([-np.inf], (np.arange(1, 7) - 3.5 + 0.5 - 1 + 0.5) * 0.0, [np.inf]))
        # Rebuild edges exactly as the reference: +/-(0.5,1.5,2.5)*sigma_e.
        edges = np.concatenate(
            ([-np.inf], np.array([-2.5, -1.5, -0.5, 0.5, 1.5, 2.5]) * proc.sigma_e, [np.inf])
        )
        for i in range(7):
            for j in range(7):
                val, _ = quad(
                    lambda x: norm.pdf(x, proc.rho * l[i], sd), edges[j], edges[j + 1]
                )
                assert abs(P[i, j] - val) < 1e-8

    def test_persistence_monotone(self):
        # Higher rho concentrates mass on the diagonal.
        _, P_low = tauchen(IncomeProcess(rho=0.1))
        _, P_high = tauchen(IncomeProcess(rho=0.9))
        assert np.diag(P_high).sum() > np.diag(P_low).sum()


class TestStationaryDistribution:
    def test_is_fixed_point(self):
        _, P = tauchen(IncomeProcess())
        pi = stationary_distribution(P)
        np.testing.assert_allclose(pi @ P, pi, atol=1e-10)
        np.testing.assert_allclose(pi.sum(), 1.0, atol=1e-12)
        assert (pi >= -1e-12).all()

    def test_labor_normalization(self):
        # After normalization aggregate labor s @ pi == 1 (Aiyagari_VFI.m:43-45).
        l, P = tauchen(IncomeProcess())
        pi = stationary_distribution(P)
        s, labor_raw = normalized_labor(l, pi)
        np.testing.assert_allclose(s @ pi, 1.0, atol=1e-12)
        np.testing.assert_allclose(s * labor_raw, np.exp(l), atol=1e-12)


class TestKSChain:
    def test_rows_sum_to_one(self):
        P = ks_transition_matrix(KSShockProcess())
        np.testing.assert_allclose(P.sum(axis=1), 1.0, atol=1e-12)

    def test_aggregate_marginal(self):
        # Summing out employment must recover the 2-state z chain with
        # persistence 1 - 1/duration = 7/8 (Krusell_Smith_VFI.m:24-26).
        P = ks_transition_matrix(KSShockProcess())
        # states: 0=(g,e), 1=(b,e), 2=(g,u), 3=(b,u); z index = s % 2.
        for s in range(4):
            z = s % 2
            stay = P[s, z] + P[s, z + 2]    # prob z'==z summing over eps'
            np.testing.assert_allclose(stay, 7.0 / 8.0, atol=1e-12)

    def test_unemployment_consistency(self):
        # u' = u p00 + (1-u) p10 for each aggregate transition
        # (the identity that pins p10 at Krusell_Smith_VFI.m:39-42).
        sh = KSShockProcess()
        mats = ks_conditional_eps_matrices(sh)
        u = {"g": sh.u_good, "b": sh.u_bad}
        for key, m in mats.items():
            u_from, u_to = u[key[0]], u[key[1]]
            p10, p00 = m[0, 1], m[1, 1]
            np.testing.assert_allclose(u_from * p00 + (1 - u_from) * p10, u_to, atol=1e-12)

    def test_reference_values(self):
        # Spot-check entries against hand-computed reference constants:
        # p00_gg = 1 - 1/1.5 = 1/3; P[(g,u)->(g,u)] = pgg * p00_gg = 7/8 * 1/3.
        P = ks_transition_matrix(KSShockProcess())
        np.testing.assert_allclose(P[2, 2], (7.0 / 8.0) * (1.0 / 3.0), atol=1e-12)
        # p00_bb = 1 - 1/2.5 = 0.6; P[(b,u)->(b,u)] = pbb * 0.6.
        np.testing.assert_allclose(P[3, 3], (7.0 / 8.0) * 0.6, atol=1e-12)

    def test_state_order(self):
        assert KS_STATE_GRID_ORDER == ((0, 1), (1, 1), (0, 0), (1, 0))
