"""Unit tests for Markov-chain construction (SURVEY.md §4.1)."""

import numpy as np
import pytest
from scipy.integrate import quad
from scipy.stats import norm

from aiyagari_tpu.config import IncomeProcess, KSShockProcess
from aiyagari_tpu.utils.markov import (
    KS_STATE_GRID_ORDER,
    ks_conditional_eps_matrices,
    ks_transition_matrix,
    normalized_labor,
    stationary_distribution,
    tauchen,
)


class TestTauchen:
    def test_rows_sum_to_one(self):
        _, P = tauchen(IncomeProcess())
        np.testing.assert_allclose(P.sum(axis=1), 1.0, atol=1e-12)

    def test_grid_matches_reference_spec(self):
        # l_i = (i-4)*sigma_e for i=1..7 (Aiyagari_VFI.m:18-21).
        l, _ = tauchen(IncomeProcess(sigma_e=0.75, n_states=7))
        np.testing.assert_allclose(l, (np.arange(1, 8) - 4) * 0.75)

    def test_matches_quadrature(self):
        # The reference integrates the normal pdf numerically
        # (Aiyagari_VFI.m:27-35); our closed form must agree.
        proc = IncomeProcess(rho=0.75, sigma_e=0.75, n_states=7)
        l, P = tauchen(proc)
        sd = proc.sigma_e * np.sqrt(1 - proc.rho**2)
        edges = np.concatenate(([-np.inf], (np.arange(1, 7) - 3.5 + 0.5 - 1 + 0.5) * 0.0, [np.inf]))
        # Rebuild edges exactly as the reference: +/-(0.5,1.5,2.5)*sigma_e.
        edges = np.concatenate(
            ([-np.inf], np.array([-2.5, -1.5, -0.5, 0.5, 1.5, 2.5]) * proc.sigma_e, [np.inf])
        )
        for i in range(7):
            for j in range(7):
                val, _ = quad(
                    lambda x: norm.pdf(x, proc.rho * l[i], sd), edges[j], edges[j + 1]
                )
                assert abs(P[i, j] - val) < 1e-8

    def test_persistence_monotone(self):
        # Higher rho concentrates mass on the diagonal.
        _, P_low = tauchen(IncomeProcess(rho=0.1))
        _, P_high = tauchen(IncomeProcess(rho=0.9))
        assert np.diag(P_high).sum() > np.diag(P_low).sum()


class TestStationaryDistribution:
    def test_is_fixed_point(self):
        _, P = tauchen(IncomeProcess())
        pi = stationary_distribution(P)
        np.testing.assert_allclose(pi @ P, pi, atol=1e-10)
        np.testing.assert_allclose(pi.sum(), 1.0, atol=1e-12)
        assert (pi >= -1e-12).all()

    def test_labor_normalization(self):
        # After normalization aggregate labor s @ pi == 1 (Aiyagari_VFI.m:43-45).
        l, P = tauchen(IncomeProcess())
        pi = stationary_distribution(P)
        s, labor_raw = normalized_labor(l, pi)
        np.testing.assert_allclose(s @ pi, 1.0, atol=1e-12)
        np.testing.assert_allclose(s * labor_raw, np.exp(l), atol=1e-12)


class TestKSChain:
    def test_rows_sum_to_one(self):
        P = ks_transition_matrix(KSShockProcess())
        np.testing.assert_allclose(P.sum(axis=1), 1.0, atol=1e-12)

    def test_aggregate_marginal(self):
        # Summing out employment must recover the 2-state z chain with
        # persistence 1 - 1/duration = 7/8 (Krusell_Smith_VFI.m:24-26).
        P = ks_transition_matrix(KSShockProcess())
        # states: 0=(g,e), 1=(b,e), 2=(g,u), 3=(b,u); z index = s % 2.
        for s in range(4):
            z = s % 2
            stay = P[s, z] + P[s, z + 2]    # prob z'==z summing over eps'
            np.testing.assert_allclose(stay, 7.0 / 8.0, atol=1e-12)

    def test_unemployment_consistency(self):
        # u' = u p00 + (1-u) p10 for each aggregate transition
        # (the identity that pins p10 at Krusell_Smith_VFI.m:39-42).
        sh = KSShockProcess()
        mats = ks_conditional_eps_matrices(sh)
        u = {"g": sh.u_good, "b": sh.u_bad}
        for key, m in mats.items():
            u_from, u_to = u[key[0]], u[key[1]]
            p10, p00 = m[0, 1], m[1, 1]
            np.testing.assert_allclose(u_from * p00 + (1 - u_from) * p10, u_to, atol=1e-12)

    def test_reference_values(self):
        # Spot-check entries against hand-computed reference constants:
        # p00_gg = 1 - 1/1.5 = 1/3; P[(g,u)->(g,u)] = pgg * p00_gg = 7/8 * 1/3.
        P = ks_transition_matrix(KSShockProcess())
        np.testing.assert_allclose(P[2, 2], (7.0 / 8.0) * (1.0 / 3.0), atol=1e-12)
        # p00_bb = 1 - 1/2.5 = 0.6; P[(b,u)->(b,u)] = pbb * 0.6.
        np.testing.assert_allclose(P[3, 3], (7.0 / 8.0) * 0.6, atol=1e-12)

    def test_state_order(self):
        assert KS_STATE_GRID_ORDER == ((0, 1), (1, 1), (0, 0), (1, 0))


class TestRouwenhorst:
    """Rouwenhorst (1995) matches the AR(1)'s conditional mean, persistence,
    and stationary variance exactly — the properties that define the method."""

    def _build(self, rho=0.95, sigma_e=0.6, n=9):
        from aiyagari_tpu.utils.markov import rouwenhorst

        return rouwenhorst(IncomeProcess(rho=rho, sigma_e=sigma_e, n_states=n))

    def test_rows_sum_to_one(self):
        _, P = self._build()
        np.testing.assert_allclose(P.sum(axis=1), 1.0, atol=1e-12)
        assert (P >= 0).all()

    def test_n2_closed_form(self):
        rho = 0.7
        _, P = self._build(rho=rho, n=2)
        p = (1 + rho) / 2
        np.testing.assert_allclose(P, [[p, 1 - p], [1 - p, p]], atol=1e-12)

    def test_conditional_mean_exact(self):
        rho = 0.95
        l, P = self._build(rho=rho)
        np.testing.assert_allclose(P @ l, rho * l, atol=1e-12)

    def test_stationary_is_symmetric_binomial(self):
        from math import comb

        n = 9
        l, P = self._build(n=n)
        pi = stationary_distribution(P)
        binom = np.array([comb(n - 1, k) for k in range(n)]) / 2.0 ** (n - 1)
        np.testing.assert_allclose(pi, binom, atol=1e-10)

    def test_stationary_variance_exact(self):
        sigma_e = 0.6
        l, P = self._build(sigma_e=sigma_e)
        pi = stationary_distribution(P)
        var = float(pi @ l**2) - float(pi @ l) ** 2
        np.testing.assert_allclose(var, sigma_e**2, atol=1e-10)

    def test_autocorrelation_exact(self):
        rho = 0.95
        l, P = self._build(rho=rho)
        pi = stationary_distribution(P)
        # corr(l_t, l_{t+1}) = E[l * E[l'|l]] / var = rho exactly.
        cov = float(pi @ (l * (P @ l)))
        var = float(pi @ l**2)
        np.testing.assert_allclose(cov / var, rho, atol=1e-10)

    def test_discretize_income_dispatch(self):
        from aiyagari_tpu.utils.markov import discretize_income, rouwenhorst

        proc = IncomeProcess(rho=0.9, sigma_e=0.5, n_states=5, method="rouwenhorst")
        l1, P1 = discretize_income(proc)
        l2, P2 = rouwenhorst(proc)
        np.testing.assert_allclose(l1, l2)
        np.testing.assert_allclose(P1, P2)
        with np.testing.assert_raises(ValueError):
            discretize_income(IncomeProcess(method="golden"))

    def test_model_builds_and_solves_with_rouwenhorst(self):
        from aiyagari_tpu.config import AiyagariConfig, GridSpecConfig, SolverConfig
        from aiyagari_tpu.equilibrium.bisection import solve_household
        from aiyagari_tpu.models.aiyagari import AiyagariModel

        cfg = AiyagariConfig(
            income=IncomeProcess(rho=0.9, sigma_e=0.4, n_states=5, method="rouwenhorst"),
            grid=GridSpecConfig(n_points=60),
        )
        model = AiyagariModel.from_config(cfg)
        sol = solve_household(model, 0.02, solver=SolverConfig(method="egm", max_iter=2000))
        assert float(sol.distance) < 1e-5
