"""Solver property and equivalence tests (SURVEY.md §4.2, §4.4):
contraction, VFI/EGM cross-method agreement, NumPy/JAX backend equivalence,
and Euler-equation residuals off-grid.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.config import AiyagariConfig, GridSpecConfig, IncomeProcess, SolverConfig
from aiyagari_tpu.equilibrium.bisection import solve_household
from aiyagari_tpu.models.aiyagari import AiyagariModel, aiyagari_labor_preset, aiyagari_preset
from aiyagari_tpu.ops.bellman import bellman_step
from aiyagari_tpu.solvers import numpy_backend as nb
from aiyagari_tpu.utils.firm import wage_from_r

R_TEST = 0.04
GRID = 80


@pytest.fixture(scope="module")
def model():
    return aiyagari_preset(grid_size=GRID)


@pytest.fixture(scope="module")
def vfi_sol(model):
    return solve_household(model, R_TEST, solver=SolverConfig(method="vfi"))


@pytest.fixture(scope="module")
def egm_sol(model):
    return solve_household(model, R_TEST, solver=SolverConfig(method="egm"))


class TestContraction:
    def test_bellman_distance_decreasing(self, model):
        prefs = model.preferences
        w = wage_from_r(R_TEST, model.config.technology.alpha, model.config.technology.delta)
        v = jnp.zeros((7, GRID))
        dists = []
        for _ in range(25):
            v_new, _ = bellman_step(v, model.a_grid, model.s, model.P, R_TEST, w,
                                    sigma=prefs.sigma, beta=prefs.beta)
            dists.append(float(jnp.max(jnp.abs(v_new - v))))
            v = v_new
        # beta-contraction: distances eventually decay geometrically.
        assert dists[-1] < dists[5] * prefs.beta ** 10


class TestMethodEquivalence:
    def test_vfi_egm_policies_agree_on_interior(self, model, vfi_sol, egm_sol):
        # Interior = where EGM stays below the top of the grid (the known
        # divergence is VFI grid truncation vs EGM extrapolation at amax).
        pk_v = np.asarray(vfi_sol.policy_k)
        pk_e = np.asarray(egm_sol.policy_k)
        interior = pk_e < model.amax * 0.9
        max_step = float(np.diff(np.asarray(model.a_grid)).max())
        assert np.abs(pk_v - pk_e)[interior].max() < max_step

    def test_vfi_egm_labor_variants_agree(self):
        m = aiyagari_labor_preset(grid_size=60)
        sv = solve_household(m, R_TEST, solver=SolverConfig(method="vfi"))
        se = solve_household(m, R_TEST, solver=SolverConfig(method="egm"))
        pk_v, pk_e = np.asarray(sv.policy_k), np.asarray(se.policy_k)
        interior = pk_e < m.amax * 0.9
        max_step = float(np.diff(np.asarray(m.a_grid)).max())
        assert np.abs(pk_v - pk_e)[interior].max() < 2 * max_step
        # Labor policies close where asset policies agree (discrete 10-pt grid
        # vs continuous FOC -> tolerance is one labor-grid step). The
        # comparison only makes sense where the continuous FOC stays inside
        # the VFI labor grid's bounds — at very low assets EGM labor exceeds
        # the grid cap 1.5 while VFI saturates (same divergence as in the
        # reference pair).
        # ... and only off the borrowing constraint: in the constrained region
        # the reference's EGM extrapolates the consumption policy (its budget
        # identity is violated there — SURVEY.md §3.6 quirk 2) while VFI
        # solves the constrained static problem exactly.
        pl_v, pl_e = np.asarray(sv.policy_l), np.asarray(se.policy_l)
        l_step = float(m.labor_grid[1] - m.labor_grid[0])
        in_bounds = (
            interior
            & (pl_e < float(m.labor_grid[-1]) - l_step)
            & (pk_e > m.amin + 1e-10)
            & (pk_v > m.amin + 1e-10)
        )
        assert np.abs(pl_v - pl_e)[in_bounds].max() < 2 * l_step


class TestContinuousVFI:
    def test_value_dominates_discrete(self, model, vfi_sol):
        """Continuous choice can only improve on the discrete grid search:
        v_cont >= v_discrete pointwise (up to interpolation error)."""
        from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi_continuous

        prefs = model.preferences
        tech = model.config.technology
        w = wage_from_r(R_TEST, tech.alpha, tech.delta)
        v0 = jnp.zeros((7, GRID))
        sc = solve_aiyagari_vfi_continuous(
            v0, model.a_grid, model.s, model.P, R_TEST, w, model.amin,
            sigma=prefs.sigma, beta=prefs.beta, tol=1e-5, max_iter=1000,
            grid_power=2.0,
        )
        assert float(jnp.min(sc.v - vfi_sol.v)) > -1e-6
        # Interior policies agree with the discrete search to ~one grid step.
        pk_d, pk_c = np.asarray(vfi_sol.policy_k), np.asarray(sc.policy_k)
        interior = pk_c < model.amax * 0.9
        step = float(np.diff(np.asarray(model.a_grid)).max())
        assert np.abs(pk_d - pk_c)[interior].max() < 2 * step

    def test_power_locator_matches_generic(self, model):
        from aiyagari_tpu.ops.interp import bucket_index, power_bucket_index

        q = jnp.array(np.random.default_rng(3).uniform(-5, 60, 5000))
        got = power_bucket_index(model.a_grid, q, model.a_grid[0], model.a_grid[-1], 2.0)
        want = bucket_index(model.a_grid, q)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.slow
    def test_slab_route_matches_local_window(self):
        """The monotone-policy slab improvement + one-hot Howard contraction
        (the fine-grid route, BENCHMARKS.md round 3) against the
        local-window gather route, both FORCED via the `slab` flag — the
        claim (identical discrete fixed point and tie-to-previous argmax;
        f64 has no value ties, so the tie rules cannot diverge) is
        geometry-relative, so the smallest slab-sound grid pins it:
        use_slab needs ceil(na/256) >= 6 blocks, and 2,304 = 9 knot-blocks
        exercises the padded-tail geometry too (was 5,120 — ~2.2x the
        wall for no added coverage; round-3 trim technique)."""
        from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi_continuous

        n = 2_304
        m = aiyagari_preset(grid_size=n)
        prefs = m.preferences
        w = wage_from_r(R_TEST, m.config.technology.alpha,
                        m.config.technology.delta)
        v0 = jnp.zeros((7, n), m.dtype)
        # golden_iters=0: the final continuous refine would amplify the
        # routes' sub-1e-9 value differences (different escalation rounds)
        # across the flat objective top; the discrete fixed point is the
        # claim under test.
        # Full convergence, NOT bounded rounds: the routes escalate to the
        # global search in different rounds (different window geometries),
        # so mid-flight iterates differ — only the converged fixed point is
        # the equality claim (measured: bounded-round equality fails).
        kw = dict(sigma=prefs.sigma, beta=prefs.beta, tol=1e-6, max_iter=40,
                  howard_steps=30, golden_iters=0, grid_power=2.0)
        sol_w = solve_aiyagari_vfi_continuous(
            v0, m.a_grid, m.s, m.P, R_TEST, w, m.amin, slab=False, **kw)
        sol_s = solve_aiyagari_vfi_continuous(
            v0, m.a_grid, m.s, m.P, R_TEST, w, m.amin, slab=True, **kw)
        np.testing.assert_array_equal(np.asarray(sol_s.policy_idx),
                                      np.asarray(sol_w.policy_idx))
        np.testing.assert_allclose(np.asarray(sol_s.v), np.asarray(sol_w.v),
                                   rtol=0, atol=1e-9)
        np.testing.assert_array_equal(np.asarray(sol_s.policy_k),
                                      np.asarray(sol_w.policy_k))


class TestBackendEquivalence:
    def test_vfi_numpy_vs_jax(self, model, vfi_sol):
        prefs = model.preferences
        tech = model.config.technology
        w = wage_from_r(R_TEST, tech.alpha, tech.delta)
        a, s, P = (np.asarray(model.a_grid), np.asarray(model.s), np.asarray(model.P))
        v, idx, pk, pc, _, _ = nb.vfi_numpy(
            np.zeros((7, GRID)), a, s, P, R_TEST, w,
            sigma=prefs.sigma, beta=prefs.beta, tol=1e-5, max_iter=1000,
        )
        np.testing.assert_allclose(np.asarray(vfi_sol.policy_k), pk, atol=1e-10)
        np.testing.assert_allclose(np.asarray(vfi_sol.v), v, atol=1e-3)

    def test_egm_numpy_vs_jax(self, model, egm_sol):
        prefs = model.preferences
        tech = model.config.technology
        w = wage_from_r(R_TEST, tech.alpha, tech.delta)
        a, s, P = (np.asarray(model.a_grid), np.asarray(model.s), np.asarray(model.P))
        C0 = np.tile((1.0 + R_TEST) * a + w * s.mean(), (7, 1))
        C, pk, _, _ = nb.egm_numpy(C0, a, s, P, R_TEST, w, model.amin,
                                   sigma=prefs.sigma, beta=prefs.beta, tol=1e-5, max_iter=1000)
        np.testing.assert_allclose(np.asarray(egm_sol.policy_k), pk, atol=1e-6)
        np.testing.assert_allclose(np.asarray(egm_sol.policy_c), C, atol=1e-6)


class TestEulerResiduals:
    def test_accuracy_module_agrees_with_manual_residuals(self, model, egm_sol):
        # The public euler_equation_errors API reports small errors for a
        # converged EGM solution and flags the constrained region.
        from aiyagari_tpu.utils.accuracy import euler_equation_errors

        prefs = model.preferences
        tech = model.config.technology
        w = float(wage_from_r(R_TEST, tech.alpha, tech.delta))
        log10e, mask = euler_equation_errors(
            egm_sol.policy_c, egm_sol.policy_k, model.a_grid, model.s, model.P,
            R_TEST, w, model.amin, sigma=prefs.sigma, beta=prefs.beta,
        )
        vals = np.asarray(log10e)[np.asarray(mask)]
        assert vals.size > 0
        assert vals.mean() < -3.0     # consumption-equivalent errors << 0.1%
        assert np.asarray(mask).sum() < mask.size   # some points constrained

    def test_egm_euler_residual_small_offgrid(self, model, egm_sol):
        """At interior (unconstrained) states the Euler equation
        u'(c) = beta(1+r) E[u'(c')] should hold to high accuracy when policies
        are evaluated *off grid* (midpoints)."""
        prefs = model.preferences
        tech = model.config.technology
        w = float(wage_from_r(R_TEST, tech.alpha, tech.delta))
        a = np.asarray(model.a_grid)
        s = np.asarray(model.s)
        P = np.asarray(model.P)
        C = np.asarray(egm_sol.policy_c)
        K = np.asarray(egm_sol.policy_k)
        mid = 0.5 * (a[:-1] + a[1:])[10:60]  # interior midpoints
        max_rel = 0.0
        for i in range(7):
            c_mid = np.interp(mid, a, C[i])
            k_mid = np.interp(mid, a, K[i])
            if (k_mid <= model.amin + 1e-10).any():
                continue
            cp = np.array([np.interp(k_mid, a, C[m]) for m in range(7)])
            rhs = prefs.beta * (1 + R_TEST) * (P[i] @ cp ** (-prefs.sigma))
            lhs = c_mid ** (-prefs.sigma)
            unconstrained = k_mid > model.amin + 1e-8
            rel = np.abs(lhs - rhs)[unconstrained] / np.abs(lhs)[unconstrained]
            max_rel = max(max_rel, rel.max())
        assert max_rel < 5e-3

    def test_budget_constraint_exact(self, model, vfi_sol, egm_sol):
        tech = model.config.technology
        w = wage_from_r(R_TEST, tech.alpha, tech.delta)
        a = np.asarray(model.a_grid)
        s = np.asarray(model.s)
        for sol in (vfi_sol, egm_sol):
            coh = (1 + R_TEST) * a[None, :] + w * s[:, None]
            np.testing.assert_allclose(
                np.asarray(sol.policy_c) + np.asarray(sol.policy_k), coh, atol=1e-8
            )


class TestConstraint:
    def test_borrowing_constraint_monotone(self, model, egm_sol):
        # The set of states where the constraint binds is a lower interval in assets.
        pk = np.asarray(egm_sol.policy_k)
        binding = pk <= model.amin + 1e-12
        for i in range(7):
            b = binding[i]
            if b.any():
                last = np.max(np.where(b)[0])
                assert b[: last + 1].all()

    def test_policy_monotone_in_assets(self, vfi_sol, egm_sol):
        for sol in (vfi_sol, egm_sol):
            pk = np.asarray(sol.policy_k)
            assert (np.diff(pk, axis=1) >= -1e-9).all()


class TestBlockedBellman:
    def test_blocked_matches_dense(self, model):
        prefs = model.preferences
        tech = model.config.technology
        w = wage_from_r(R_TEST, tech.alpha, tech.delta)
        v = jnp.array(np.random.default_rng(0).normal(size=(7, GRID)))
        dense_v, dense_i = bellman_step(v, model.a_grid, model.s, model.P, R_TEST, w,
                                        sigma=prefs.sigma, beta=prefs.beta)
        blk_v, blk_i = bellman_step(v, model.a_grid, model.s, model.P, R_TEST, w,
                                    sigma=prefs.sigma, beta=prefs.beta, block_size=17)
        np.testing.assert_allclose(dense_v, blk_v, atol=1e-12)
        np.testing.assert_array_equal(np.asarray(dense_i), np.asarray(blk_i))

    def test_pallas_matches_dense(self, model):
        # Interpreted off-TPU; exercises the tiling/masking/accumulation logic
        # of the fused kernel, including non-tile-multiple grid sizes.
        prefs = model.preferences
        tech = model.config.technology
        w = wage_from_r(R_TEST, tech.alpha, tech.delta)
        v = jnp.array(np.random.default_rng(1).normal(size=(7, GRID)))
        dense_v, dense_i = bellman_step(v, model.a_grid, model.s, model.P, R_TEST, w,
                                        sigma=prefs.sigma, beta=prefs.beta)
        from aiyagari_tpu.ops.pallas_bellman import bellman_max_pallas

        EV = prefs.beta * model.P @ v
        coh = (1.0 + R_TEST) * model.a_grid[None, :] + w * model.s[:, None]
        pal_v, pal_i = bellman_max_pallas(coh, model.a_grid, EV, sigma=prefs.sigma,
                                          block_j=32, block_jp=48, interpret=True)
        np.testing.assert_allclose(dense_v, pal_v, atol=1e-11)
        np.testing.assert_array_equal(np.asarray(dense_i), np.asarray(pal_i))


class TestMultiscaleVFI:
    def test_multiscale_matches_direct(self):
        """Value-function grid sequencing reaches the continuous VFI's fixed
        point with far fewer fine-grid improvement rounds."""
        from aiyagari_tpu.solvers.vfi import (
            solve_aiyagari_vfi_continuous,
            solve_aiyagari_vfi_multiscale,
        )

        n = 3000
        m = aiyagari_preset(grid_size=n)
        w = wage_from_r(R_TEST, m.config.technology.alpha, m.config.technology.delta)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  tol=1e-5, max_iter=2000)
        v0 = jnp.zeros((7, n), m.a_grid.dtype)
        direct = solve_aiyagari_vfi_continuous(
            v0, m.a_grid, m.s, m.P, R_TEST, w, m.amin, howard_steps=50, grid_power=2.0, **kw)
        ms = solve_aiyagari_vfi_multiscale(
            m.a_grid, m.s, m.P, R_TEST, w, m.amin, howard_steps=50,
            grid_power=2.0, coarsest=400, **kw)
        assert float(ms.distance) < 1e-5
        # Same discrete argmax fixed point up to tol-ball wobble: compare the
        # refined policies within a couple of grid cells' tolerance.
        gap = float(jnp.max(jnp.abs(ms.policy_k - direct.policy_k)))
        h_max = float(jnp.max(jnp.diff(m.a_grid)))
        assert gap <= 2.0 * h_max
        assert int(ms.iterations) < int(direct.iterations)


class TestWarmStartVFI:
    @pytest.mark.slow
    def test_egm_warmstart_matches_cold(self):
        """The cross-method warm start (EGM policy -> VFI idx_init,
        solvers/vfi.solve_aiyagari_vfi_egm_warmstart) reaches the cold
        multiscale solve's fixed point — same operator, same stopping rule —
        while collapsing the fine-grid improvement rounds (BENCH round 5:
        22.3 s -> 1.3 s at 400k on the TPU). Pinned here at a slab-capable
        grid in f64 so the equality is tolerance-level, not tie-wobble."""
        from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_multiscale
        from aiyagari_tpu.solvers.vfi import (
            solve_aiyagari_vfi_egm_warmstart,
            solve_aiyagari_vfi_multiscale,
        )

        # 4,800 > the 4,096 slab auto-select cutoff, so the final stage runs
        # the slab improvement + one-hot Howard evaluation — the exact route
        # the 400k bench headline rides (a 3,000-point grid would silently
        # pin only the local-window route).
        n = 4_800
        m = aiyagari_preset(grid_size=n)
        w = wage_from_r(R_TEST, m.config.technology.alpha,
                        m.config.technology.delta)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  tol=1e-5, max_iter=2000, grid_power=2.0)
        cold = solve_aiyagari_vfi_multiscale(
            m.a_grid, m.s, m.P, R_TEST, w, m.amin, howard_steps=25, **kw)
        egm = solve_aiyagari_egm_multiscale(
            m.a_grid, m.s, m.P, R_TEST, w, m.amin, **kw)
        # The warm leg runs the SHIPPED defaults (3-stage ladder, hs=15 —
        # the tuned recipe the bench measures), deliberately NOT the cold
        # reference's knobs: the claim is that the fixed point is
        # recipe-independent, so the equality must hold across the two
        # configurations, not just for matched ones.
        warm = solve_aiyagari_vfi_egm_warmstart(
            m.a_grid, m.s, m.P, R_TEST, w, m.amin,
            egm_solution=egm, **kw)
        assert float(warm.distance) < 1e-5
        # Same fixed point: values agree to the stopping tolerance, policies
        # to a couple of grid cells (discrete tie-ball).
        assert float(jnp.max(jnp.abs(warm.v - cold.v))) < 1e-4
        h_max = float(jnp.max(jnp.diff(m.a_grid)))
        gap = float(jnp.max(jnp.abs(warm.policy_k - cold.policy_k)))
        assert gap <= 2.0 * h_max
        # The point of the warm start: improvement rounds collapse to the
        # near-fixed-point verification handful, and the sweep accounting
        # (VFISolution.eval_sweeps) is populated for the roofline model.
        assert int(warm.iterations) <= int(cold.iterations)
        assert int(warm.eval_sweeps) > 0

    def test_warm_policy_respected_in_continuous(self):
        """idx_init is honored: starting AT the cold fixed point's policy,
        the solver verifies it in one improvement round (policy-repeat
        arming is immediate under a warm start)."""
        from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi_continuous

        n = 400
        m = aiyagari_preset(grid_size=n)
        w = wage_from_r(R_TEST, m.config.technology.alpha,
                        m.config.technology.delta)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  tol=1e-5, max_iter=2000, grid_power=2.0, howard_steps=25,
                  golden_iters=0)
        v0 = jnp.zeros((7, n), m.a_grid.dtype)
        cold = solve_aiyagari_vfi_continuous(
            v0, m.a_grid, m.s, m.P, R_TEST, w, m.amin, **kw)
        warm = solve_aiyagari_vfi_continuous(
            v0, m.a_grid, m.s, m.P, R_TEST, w, m.amin,
            idx_init=cold.policy_idx, **kw)
        assert int(warm.iterations) <= 2
        np.testing.assert_array_equal(np.asarray(warm.policy_idx),
                                      np.asarray(cold.policy_idx))
        assert float(jnp.max(jnp.abs(warm.v - cold.v))) < 1e-4


class TestMultiscaleEGM:
    @pytest.mark.slow
    def test_multiscale_matches_direct(self):
        """Grid sequencing reaches the same fixed point as the cold-start
        solve (both stop at the same tolerance on the same final grid), with
        an order-of-magnitude fewer final-grid sweeps."""
        from aiyagari_tpu.solvers.egm import (
            solve_aiyagari_egm,
            solve_aiyagari_egm_multiscale,
        )

        n = 3000
        m = aiyagari_preset(grid_size=n)
        w = wage_from_r(R_TEST, m.config.technology.alpha, m.config.technology.delta)
        mean_s = float(jnp.mean(m.s))
        C0 = jnp.broadcast_to(
            ((1.0 + R_TEST) * m.a_grid + w * mean_s)[None, :], (7, n)
        )
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  tol=1e-5, max_iter=2000)
        direct = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, R_TEST, w, m.amin, **kw)
        ms = solve_aiyagari_egm_multiscale(m.a_grid, m.s, m.P, R_TEST, w, m.amin,
                                           grid_power=2.0, coarsest=400, **kw)
        assert float(ms.distance) < 1e-5
        # Both iterates sit within the tol-ball of the same fixed point:
        # |C_a - C_b| <= 2 * tol * beta/(1-beta).
        bound = 2 * 1e-5 * m.preferences.beta / (1 - m.preferences.beta) + 1e-6
        assert float(jnp.max(jnp.abs(ms.policy_c - direct.policy_c))) < bound
        # The whole point: the warm-started final stage converges in a small
        # fraction of the cold-start sweep count.
        assert int(ms.iterations) < int(direct.iterations) // 5


class TestMultiscaleLaborEGM:
    @pytest.mark.slow
    def test_labor_multiscale_matches_direct(self):
        """The endogenous-labor grid-sequenced ladder (VERDICT round-1 gap:
        the labor family was excluded from grid sequencing) reaches the
        single-grid labor EGM fixed point with far fewer fine-grid sweeps.
        Reference operator: Aiyagari_Endogenous_Labor_EGM.m:67-107."""
        from aiyagari_tpu.config import AiyagariConfig, GridSpecConfig, IncomeProcess
        from aiyagari_tpu.models.aiyagari import AiyagariModel
        from aiyagari_tpu.solvers.egm import (
            solve_aiyagari_egm_labor,
            solve_aiyagari_egm_labor_multiscale,
        )

        n = 2048
        cfg = AiyagariConfig(income=IncomeProcess(rho=0.6, sigma_e=0.2),
                             endogenous_labor=True,
                             grid=GridSpecConfig(n_points=n))
        m = AiyagariModel.from_config(cfg)
        p = cfg.preferences
        w = wage_from_r(R_TEST, cfg.technology.alpha, cfg.technology.delta)
        mean_s = float(jnp.mean(m.s))
        C0 = jnp.broadcast_to(
            ((1.0 + R_TEST) * m.a_grid + w * mean_s)[None, :], (m.P.shape[0], n)
        )
        kw = dict(sigma=p.sigma, beta=p.beta, psi=p.psi, eta=p.eta,
                  tol=1e-5, max_iter=2000)
        direct = solve_aiyagari_egm_labor(C0, m.a_grid, m.s, m.P, R_TEST, w,
                                          m.amin, **kw)
        ms = solve_aiyagari_egm_labor_multiscale(m.a_grid, m.s, m.P, R_TEST, w,
                                                 m.amin, grid_power=2.0,
                                                 coarsest=400, **kw)
        assert float(ms.distance) < 1e-5
        assert not bool(ms.escaped)
        bound = 2 * 1e-5 * p.beta / (1 - p.beta) + 1e-6
        assert float(jnp.max(jnp.abs(ms.policy_c - direct.policy_c))) < bound
        assert float(jnp.max(jnp.abs(ms.policy_l - direct.policy_l))) < 10 * bound
        assert int(ms.iterations) < int(direct.iterations) // 5

    def test_labor_multiscale_rejects_non_power_grid(self):
        import pytest

        from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_labor_multiscale

        with pytest.raises(ValueError, match="power-spaced"):
            solve_aiyagari_egm_labor_multiscale(
                jnp.linspace(0.0, 50.0, 800), jnp.asarray([0.8, 1.2]),
                jnp.asarray([[0.9, 0.1], [0.1, 0.9]]), 0.04, 1.2, 0.0,
                sigma=2.0, beta=0.95, psi=1.0, eta=2.0, tol=1e-5,
                max_iter=1000, grid_power=0.0)
