"""Batched GE layer tests (equilibrium/batched.py + dispatch.sweep):

* parity — the parallel-bracket root finder locates the SAME equilibrium
  rate as the serial bisection (both closures share one excess-demand
  curve), for both solver families, in strictly fewer device rounds;
* the round-count bound — batched rounds <= batched_round_bound(serial
  iterations, B), the (B+1)-ary vs binary bracket-shrink geometry;
* vmap-compatibility — one excess_demand_batch program evaluates a whole
  candidate batch (the traced-sigma/beta solver refactor this layer needs);
* sweeps — lockstep scenario solves agree with one-at-a-time serial GE and
  are invariant to sharding the scenario axis over the 8-device CPU mesh.

Scale notes: 60-point/3-state economies keep each household solve tiny; the
convergence tolerance is 1e-3 because the inner solves (tol 1e-5) leave
~1e-4 noise in the supply curve, and the DISCRETE-choice VFI's excess
demand is a step function (its policy moves in whole grid cells), so only
EGM's continuous policies can actually fire the gap criterion at this grid
size — the VFI assertions pin root location, not the unreachable gap.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from aiyagari_tpu.config import (
    AiyagariConfig,
    BackendConfig,
    EquilibriumConfig,
    GridSpecConfig,
    IncomeProcess,
    SolverConfig,
)
from aiyagari_tpu.equilibrium.batched import (
    batched_round_bound,
    excess_demand_batch,
    solve_equilibrium_batched,
    solve_equilibrium_sweep,
    stack_scenarios,
)
from aiyagari_tpu.equilibrium.bisection import solve_equilibrium_distribution
from aiyagari_tpu.models.aiyagari import AiyagariModel

CFG = AiyagariConfig(income=IncomeProcess(n_states=3),
                     grid=GridSpecConfig(n_points=60))
EQ_TOL = 1e-3
B = 8
SERIAL_EQ = EquilibriumConfig(max_iter=25, tol=EQ_TOL)
BATCH_EQ = EquilibriumConfig(batch=B, max_iter=8, tol=EQ_TOL)


@pytest.fixture(scope="module")
def model():
    return AiyagariModel.from_config(CFG, jnp.float64)


class TestRoundBound:
    def test_geometry(self):
        # (B+1)-ary bracket shrink vs binary: 20 halvings fit in
        # ceil(20 ln2 / ln9) + 1 = 8 rounds of an 8-wide batch.
        assert batched_round_bound(20, 8) == 8
        assert batched_round_bound(30, 32) == 7
        # Degenerate batch falls back to bisection's own count.
        assert batched_round_bound(17, 1) == 17


class TestBatchedParity:
    def test_egm_same_root_fewer_rounds(self, model):
        sv = SolverConfig(method="egm")
        ser = solve_equilibrium_distribution(model, solver=sv, eq=SERIAL_EQ)
        bat = solve_equilibrium_batched(model, solver=sv, eq=BATCH_EQ)
        assert ser.converged and bat.converged
        # Same root within eq.tol (measured agreement ~1e-5: both stop when
        # |K_s - K_d| < tol on a curve with slope ~4e2).
        assert abs(bat.r - ser.r) < EQ_TOL
        # The histories carry ALL candidates; the converged one is the
        # round record's best.
        assert abs(bat.per_iteration[-1]["best_gap"]) < EQ_TOL
        # Strictly fewer device rounds, and within the bracket-geometry bound.
        assert bat.iterations < ser.iterations
        assert bat.iterations <= batched_round_bound(ser.iterations, B)

    def test_vfi_same_root(self, model):
        # Discrete VFI's excess demand steps by ~0.4 at the policy
        # transition, so neither loop can fire |gap| < tol — both must still
        # localize the SAME jump point to their (comparable) bracket
        # resolutions: serial 2^-25 vs batched 9^-8 of the same bracket.
        sv = SolverConfig(method="vfi")
        ser = solve_equilibrium_distribution(model, solver=sv, eq=SERIAL_EQ)
        bat = solve_equilibrium_batched(model, solver=sv, eq=BATCH_EQ)
        assert abs(bat.r - ser.r) < 1e-4
        assert bat.iterations < ser.iterations

    def test_histories_aligned_and_round_records(self, model):
        bat = solve_equilibrium_batched(
            model, solver=SolverConfig(method="egm"), eq=BATCH_EQ)
        assert len(bat.r_history) == len(bat.k_supply) == len(bat.k_demand)
        assert len(bat.r_history) == bat.iterations * B
        assert len(bat.per_iteration) == bat.iterations
        rec = bat.per_iteration[-1]
        assert len(rec["r_candidates"]) == B
        assert rec["best_r"] == bat.r

    def test_batch_below_two_rejected(self, model):
        with pytest.raises(ValueError, match="batch >= 2"):
            solve_equilibrium_batched(
                model, eq=EquilibriumConfig(batch=1))


class TestExcessDemandBatch:
    """vmap-compatibility smoke: one program, a whole candidate batch."""

    @pytest.mark.parametrize("method", ["vfi", "egm"])
    def test_batch_evaluates_monotone_curve(self, model, method):
        rs = np.linspace(0.005, 0.02, 5)
        gap, aux = excess_demand_batch(
            model, rs, solver=SolverConfig(method=method))
        gap = np.asarray(gap)
        assert gap.shape == (5,)
        assert np.all(np.isfinite(gap))
        # Supply rises and FOC demand falls in r: the gap is increasing.
        assert np.all(np.diff(gap) > 0)
        # The batched kernel returns the batched household solutions too.
        assert aux["sol"].policy_k.shape == (5,) + model.P.shape[:1] + (60,)

    def test_matches_serial_household_supply(self, model):
        # One candidate's supply from the fused batch == the serial
        # aggregator's supply at the same rate (same solver, same closure).
        from aiyagari_tpu.equilibrium.bisection import solve_household
        from aiyagari_tpu.sim.distribution import (
            aggregate_capital,
            stationary_distribution,
        )

        r = 0.012
        gap, aux = excess_demand_batch(
            model, np.array([r]), solver=SolverConfig(method="egm"))
        sol = solve_household(model, r, solver=SolverConfig(method="egm"))
        mu = stationary_distribution(sol.policy_k, model.a_grid, model.P).mu
        supply = float(aggregate_capital(mu, model.a_grid))
        assert abs(float(aux["supply"][0]) - supply) < 1e-6


class TestBatchedDispatch:
    def test_solve_batch_optin_matches_serial(self):
        from aiyagari_tpu import solve

        ser = solve(CFG, method="egm", aggregation="distribution",
                    equilibrium=SERIAL_EQ, on_nonconvergence="ignore")
        bat = solve(CFG, method="egm", aggregation="distribution",
                    equilibrium=BATCH_EQ, on_nonconvergence="ignore")
        assert bat.converged
        assert abs(bat.r - ser.r) < EQ_TOL
        assert bat.iterations < ser.iterations

    def test_simulation_closure_smoke(self):
        # The Monte-Carlo closure also runs batched (per-candidate panels,
        # per-round keys); parity there is up to simulation noise, so only
        # economic sanity is pinned.
        from aiyagari_tpu import solve
        from aiyagari_tpu.config import SimConfig

        res = solve(CFG, method="egm",
                    sim=SimConfig(periods=600, n_agents=4, discard=100, seed=0),
                    equilibrium=EquilibriumConfig(batch=4, max_iter=4, tol=EQ_TOL),
                    on_nonconvergence="ignore")
        beta = CFG.preferences.beta
        assert -0.05 < res.r < 1 / beta - 1
        assert res.series is not None

    def test_numpy_backend_rejected(self):
        from aiyagari_tpu import solve

        with pytest.raises(ValueError, match="backend='jax'"):
            solve(CFG, backend="numpy", equilibrium=BATCH_EQ)


class TestSweepQuick:
    def test_two_scenarios_lockstep(self):
        from aiyagari_tpu import sweep

        res = sweep(CFG, method="egm", beta=[0.94, 0.96],
                    equilibrium=EquilibriumConfig(max_iter=8, tol=EQ_TOL))
        assert res.scenarios == 2
        assert res.rounds <= 8
        assert res.scenarios_per_sec > 0
        # A lower beta means more discounting, less saving, higher r*.
        assert res.r[0] > res.r[1]
        assert np.all(np.isfinite(res.capital)) and np.all(res.capital > 0)
        assert res.params == [{"beta": 0.94}, {"beta": 0.96}]


@pytest.mark.slow
class TestSweep:
    BETAS = [0.94, 0.96]
    SIGMAS = [4.0, 5.0]

    def test_lockstep_matches_serial_per_scenario(self, model):
        from aiyagari_tpu import sweep

        eq = EquilibriumConfig(max_iter=18, tol=EQ_TOL)
        res = sweep(CFG, method="egm", beta=self.BETAS, sigma=self.SIGMAS,
                    equilibrium=eq)
        assert res.scenarios == 4 and res.r.shape == (4,)
        assert res.scenarios_per_sec > 0
        import dataclasses

        for i, p in enumerate(res.params):
            prefs = dataclasses.replace(CFG.preferences, **p)
            cfg_i = dataclasses.replace(CFG, preferences=prefs)
            m_i = AiyagariModel.from_config(cfg_i, jnp.float64)
            ser = solve_equilibrium_distribution(
                m_i, solver=SolverConfig(method="egm"), eq=eq)
            # Lockstep bisection == serial bisection per scenario (same
            # bracket updates on the same curve; warm-start noise only).
            assert abs(res.r[i] - ser.r) < EQ_TOL, (i, p)

    def test_sharded_sweep_matches_unsharded(self):
        from aiyagari_tpu import sweep

        eq = EquilibriumConfig(max_iter=10, tol=EQ_TOL)
        kw = dict(method="egm", beta=[0.93, 0.94, 0.95, 0.96],
                  sigma=self.SIGMAS, equilibrium=eq)
        plain = sweep(CFG, **kw)
        sharded = sweep(CFG, backend=BackendConfig(mesh_axes=("scenarios",)),
                        **kw)
        # 8 scenarios over the 8-virtual-device CPU mesh: identical results
        # (the kernel has no cross-scenario communication to reorder).
        np.testing.assert_allclose(sharded.r, plain.r, rtol=0, atol=0)
        np.testing.assert_array_equal(sharded.converged, plain.converged)

    def test_scenario_shape_mismatch_rejected(self):
        import dataclasses

        m1 = AiyagariModel.from_config(CFG, jnp.float64)
        cfg2 = dataclasses.replace(
            CFG, grid=dataclasses.replace(CFG.grid, n_points=40))
        m2 = AiyagariModel.from_config(cfg2, jnp.float64)
        with pytest.raises(ValueError, match="share grid shapes"):
            stack_scenarios([m1, m2])

    def test_param_validation(self):
        from aiyagari_tpu import sweep

        with pytest.raises(ValueError, match="unknown sweep parameter"):
            sweep(CFG, delta=[0.05, 0.08])
        with pytest.raises(ValueError, match="needs scenarios"):
            sweep(CFG)


class TestWarmStageGuard:
    def test_warm_policy_requires_power_grid(self, model):
        # Satellite fix: grid_power=0.0 (legal for the continuous solver)
        # must be rejected loudly, not die in a trace-time ZeroDivisionError
        # inside the warm-stage re-sampler.
        from aiyagari_tpu.solvers.vfi import (
            _warm_stage_idx,
            solve_aiyagari_vfi_multiscale,
        )

        warm = jnp.zeros((3, 60))
        with pytest.raises(ValueError, match="power-spaced"):
            solve_aiyagari_vfi_multiscale(
                model.a_grid, model.s, model.P, 0.04, 1.2, model.amin,
                sigma=5.0, beta=0.96, tol=1e-5, max_iter=100,
                grid_power=0.0, warm_policy_k=warm)
        with pytest.raises(ValueError, match="grid_power must be > 0"):
            _warm_stage_idx(warm, model.a_grid, lo=0.0, hi=10.0,
                            power=0.0, n=60)
