"""Pod observatory (ISSUE 14): host-stamped sharded ledgers + merge, the
mesh skew / straggler probes, heartbeat off-path pins, the live watch CLI,
run-id-keyed span scoping, and the bench-history watchdog.

The multi-host surfaces run single-process here by design: every sharding
behavior has an explicit-process-index simulation path (RunLedger takes
`process_index`/`process_count` so two "hosts" can write shards from one
interpreter), the skew probes run on the 8-virtual-device CPU mesh the
conftest forces (same collectives as a v5e-8 slice), and the merge/watch
layer is pure host-side file consumption either way — so the on-pod
validation run inherits a toolchain whose every piece is already pinned.
"""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.diagnostics.ledger import (
    RunLedger,
    activate,
    merge_ledgers,
    read_ledger,
    shard_path,
    shard_paths,
)


def _write_pod_shards(tmp_path, *, torn=False):
    """Two simulated host shards of ONE run (shared run id, interleaved
    timestamps via alternating writers), optionally with a torn tail line
    on host 1's live shard. Returns (base_path, events_written)."""
    base = tmp_path / "ledger.jsonl"
    run_id = "podrun0000000001"
    leds = [RunLedger(base, run_id=run_id, process_index=k, process_count=2,
                      meta={"entry": "sim"}) for k in (0, 1)]
    written = 2  # the two run_start events
    for k in range(6):
        leds[k % 2].event("heartbeat", context="sim", round=k,
                          gap=[0.1 * (k + 1), 0.2 * (k + 1)])
        written += 1
    if torn:
        with open(leds[1].path, "a") as f:
            f.write('{"run_id": "podrun0000000001", "kind": "torn')
    return base, written


class TestShardedLedger:
    def test_events_carry_host_stamp_and_runtime_identity(self, tmp_path):
        led = RunLedger(tmp_path / "l.jsonl", meta={"entry": "t"})
        led.event("verdict", converged=True)
        events = read_ledger(led.path)
        for ev in events:
            assert ev["process_index"] == 0
            assert ev["process_count"] == 1
        start = events[0]
        assert start["kind"] == "run_start"
        # The runtime identity a merged pod ledger needs per shard.
        assert start["jax_version"] == jax.__version__

    def test_single_process_writes_the_base_path(self, tmp_path):
        led = RunLedger(tmp_path / "l.jsonl")
        assert led.path == tmp_path / "l.jsonl"

    def test_explicit_process_index_selects_the_shard_file(self, tmp_path):
        led = RunLedger(tmp_path / "l.jsonl", process_index=3,
                        process_count=4)
        assert led.path == tmp_path / "l.p3.jsonl"
        assert led.process_index == 3 and led.process_count == 4
        ev = read_ledger(led.path)[0]
        assert ev["process_index"] == 3 and ev["process_count"] == 4

    def test_shard_path_preserves_the_jsonl_suffix(self, tmp_path):
        assert shard_path(tmp_path / "run.jsonl", 2).name == "run.p2.jsonl"
        assert shard_path(tmp_path / "run", 0).name == "run.p0"

    def test_shard_paths_discovers_base_plus_shards_in_index_order(
            self, tmp_path):
        base = tmp_path / "run.jsonl"
        base.write_text('{"run_id": "x", "seq": 0, "ts": 1.0}\n')
        for k in (10, 1, 0):
            shard_path(base, k).write_text(
                f'{{"run_id": "x", "seq": 0, "ts": 1.0, '
                f'"process_index": {k}}}\n')
        found = shard_paths(base)
        assert found[0] == base
        assert [p.name for p in found[1:]] == [
            "run.p0.jsonl", "run.p1.jsonl", "run.p10.jsonl"]

    def test_shard_discovery_ignores_non_shard_siblings(self, tmp_path):
        base = tmp_path / "run.jsonl"
        base.write_text('{"run_id": "x", "seq": 0, "ts": 1.0}\n')
        # Same prefix, not an integer-indexed host shard.
        (tmp_path / "run.prod.jsonl").write_text("{}\n")
        assert shard_paths(base) == [base]

    def test_shard_glob_survives_p0_in_directory_names(self, tmp_path):
        # A ".p0" in a DIRECTORY component (or the stem) must not corrupt
        # the shard glob into matching sibling directories.
        exp = tmp_path / "exp.p0"
        other = tmp_path / "exp.px"
        exp.mkdir()
        other.mkdir()
        base = exp / "ledger.jsonl"
        led = RunLedger(base, run_id="e" * 16, process_index=1,
                        process_count=2)
        (other / "ledger.p1.jsonl").write_text('{"run_id": "z"}\n')
        found = shard_paths(base)
        assert found == [led.path]
        merged = merge_ledgers([base])
        assert {e["run_id"] for e in merged} == {"e" * 16}


class TestMergeLedgers:
    def test_two_shard_round_trip_is_run_joined_and_ordered(self, tmp_path):
        base, written = _write_pod_shards(tmp_path)
        merged = merge_ledgers([base])
        assert len(merged) == written
        # Run-id joined: both hosts' shards collapse into ONE run.
        assert {e["run_id"] for e in merged} == {"podrun0000000001"}
        assert {e["process_index"] for e in merged} == {0, 1}
        # Monotonically ordered: timestamps ascend, ties broken by host
        # then per-host sequence, so each shard's own order is preserved.
        keys = [(e["ts"], e["process_index"], e["seq"]) for e in merged]
        assert keys == sorted(keys)
        for host in (0, 1):
            seqs = [e["seq"] for e in merged if e["process_index"] == host]
            assert seqs == sorted(seqs)

    def test_torn_tail_line_is_tolerated_on_live_shards(self, tmp_path):
        base, written = _write_pod_shards(tmp_path, torn=True)
        merged = merge_ledgers([base])
        assert len(merged) == written   # the torn in-flight line is skipped
        with pytest.raises(json.JSONDecodeError):
            merge_ledgers([base], tolerate_torn=False)

    def test_torn_line_mid_file_is_always_corruption(self, tmp_path):
        p = tmp_path / "l.jsonl"
        p.write_text('{"run_id": "x", "seq": 0, "ts": 1.0}\n'
                     '{"torn\n'
                     '{"run_id": "x", "seq": 1, "ts": 2.0}\n')
        with pytest.raises(json.JSONDecodeError):
            merge_ledgers([p], tolerate_torn=True)

    def test_base_path_without_a_base_file_expands_to_shards(self, tmp_path):
        # The pod case: the operator names `ledger.jsonl`, only the
        # per-host `ledger.p{k}.jsonl` shards exist on disk.
        base, written = _write_pod_shards(tmp_path)
        assert not base.exists()
        assert len(merge_ledgers([base])) == written
        # And a glob pattern reaches the same files.
        assert len(merge_ledgers([str(tmp_path / "ledger.p*.jsonl")])) \
            == written

    def test_duplicate_paths_are_deduplicated(self, tmp_path):
        base, written = _write_pod_shards(tmp_path)
        shards = [str(p) for p in shard_paths(base)]
        assert len(merge_ledgers([base, *shards])) == written

    def test_distinct_runs_stay_grouped_in_first_appearance_order(
            self, tmp_path):
        a = RunLedger(tmp_path / "a.jsonl", run_id="a" * 16)
        b = RunLedger(tmp_path / "b.jsonl", run_id="b" * 16)
        a.event("verdict", converged=True)
        b.event("verdict", converged=False)
        merged = merge_ledgers([a.path, b.path])
        run_seq = [e["run_id"] for e in merged]
        # Each run's events are contiguous, runs ordered by first ts.
        assert run_seq == ["a" * 16] * 2 + ["b" * 16] * 2

    def test_missing_path_is_loud(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            merge_ledgers([tmp_path / "nope.jsonl"])


class TestFollowMode:
    def test_follow_tails_appended_events_and_buffers_torn_lines(
            self, tmp_path):
        p = tmp_path / "l.jsonl"
        p.write_text('{"seq": 0}\n')
        state = {"done": False}
        tail = read_ledger(p, follow=True, poll_seconds=0.01,
                           stop=lambda: state["done"])
        assert next(tail) == {"seq": 0}
        # A torn line stays buffered until its writer finishes it.
        with open(p, "a") as f:
            f.write('{"seq": 1')
            f.flush()
            f.write(', "kind": "late"}\n')
        assert next(tail) == {"seq": 1, "kind": "late"}
        state["done"] = True
        assert list(tail) == []


class TestSkewProbes:
    def test_straggler_verdict_band(self):
        from aiyagari_tpu.diagnostics.skew import SkewConfig, straggler_verdict

        cfg = SkewConfig(straggler_band_seconds=5e-3,
                         straggler_band_factor=3.0)
        # Inside the absolute floor: scheduler noise, never a straggler.
        v = straggler_verdict([0.001, 0.002, 0.003], 1e-4, cfg)
        assert v["verdict"] == "balanced" and v["straggler"] is None
        # One host far outside the band: named by index.
        v = straggler_verdict([0.001, 0.002, 0.5], 1e-4, cfg)
        assert v["verdict"] == "straggler" and v["straggler"] == 2
        assert v["lag_spread_seconds"] > v["band_seconds"]
        # The band scales with the measured rendezvous itself: the same
        # spread is balanced when the collective is slow anyway.
        v = straggler_verdict([0.001, 0.002, 0.5], 0.2, cfg)
        assert v["verdict"] == "balanced"
        # Degenerate inputs.
        assert straggler_verdict([], 1.0, cfg)["verdict"] == "balanced"
        assert straggler_verdict([9.9], 1e-4, cfg)["verdict"] == "balanced"

    def test_skew_config_validates(self):
        from aiyagari_tpu.diagnostics.skew import SkewConfig

        with pytest.raises(ValueError):
            SkewConfig(reps=0)
        with pytest.raises(ValueError):
            SkewConfig(straggler_band_factor=-1.0)

    def test_probe_emits_events_and_gauges_for_both_axes(self, tmp_path):
        from aiyagari_tpu.diagnostics import metrics
        from aiyagari_tpu.diagnostics.skew import SkewConfig, probe_mesh_skew
        from aiyagari_tpu.parallel.mesh import make_mesh_2d

        led = RunLedger(tmp_path / "l.jsonl")
        mesh = make_mesh_2d(scenarios=2, grid=4)
        out = probe_mesh_skew(mesh, config=SkewConfig(reps=2),
                              price={"S": 4, "N": 7, "na": 64},
                              ledger=led)
        assert out["mesh"] == {"scenarios": 2, "grid": 4}
        by_axis = {r["axis"]: r for r in out["axes"]}
        assert set(by_axis) == {"scenarios", "grid"}
        events = [e for e in read_ledger(led.path)
                  if e["kind"] == "host_skew"]
        assert {e["axis"] for e in events} == {"scenarios", "grid"}
        for rec in by_axis.values():
            assert rec["rendezvous_seconds"] > 0
            assert rec["reps"] == 2
            assert rec["verdict"] in ("balanced", "straggler")
            assert len(rec["arrival_lag_seconds"]) == rec["processes"] == 1
            # The priced reconciliation row: scenario axis against DCN
            # sync, grid axis against per-lane-sweep ICI bytes.
            rc = rec["reconciliation"]
            assert rc["link"] == ("dcn" if rec["axis"] == "scenarios"
                                  else "ici")
            assert rc["measured_seconds"] > 0
            # Per-axis gauge, one series per axis label (the event rounds
            # to microseconds; the gauge keeps the raw wall).
            g = metrics.gauge("aiyagari_host_skew_seconds", axis=rec["axis"])
            assert g.value == pytest.approx(rec["rendezvous_seconds"],
                                            abs=1e-6)

    def test_dispatch_sweep_probe_knob_lands_host_skew_events(
            self, tmp_path):
        from aiyagari_tpu.config import (
            AiyagariConfig,
            EquilibriumConfig,
            GridSpecConfig,
            MeshConfig,
            SolverConfig,
        )
        from aiyagari_tpu.diagnostics.progress import configure_heartbeat
        from aiyagari_tpu.dispatch import sweep

        # ONE sweep (shape-matched to test_mesh2d's 2x4 sweep for
        # compiled-program reuse under tier-1's wall budget) serves both
        # dispatch-wiring pins: the skew-probe knob and the lockstep
        # per-scenario heartbeats.
        betas = [0.94, 0.95, 0.955, 0.96]
        path = tmp_path / "sweep.jsonl"
        configure_heartbeat(1)
        sweep(AiyagariConfig(grid=GridSpecConfig(n_points=64)),
              method="egm", beta=betas,
              solver=SolverConfig(method="egm"),
              equilibrium=EquilibriumConfig(max_iter=2, tol=0.0),
              mesh=MeshConfig(scenarios=2, grid=4, skew_probe=True),
              ledger=path)
        events = read_ledger(path)
        skews = [e for e in events if e["kind"] == "host_skew"]
        assert {e["axis"] for e in skews} == {"scenarios", "grid"}
        # Probe events ride the run's own ledger scope (one shared run id)
        # and carry the priced reconciliation (the sweep knows its sizes).
        assert {e["run_id"] for e in events} == {events[0]["run_id"]}
        for e in skews:
            assert e["reconciliation"]["measured_seconds"] > 0
        # The lockstep GE round loop heartbeat at stride 1, one entry per
        # scenario lane per round.
        beats = [e for e in events if e["kind"] == "heartbeat"
                 and e["context"] == "aiyagari_sweep"]
        assert beats, "lockstep GE rounds must heartbeat at stride 1"
        for ev in beats:
            assert len(ev["gap"]) == len(betas)
            assert len(ev["converged"]) == len(betas)
            assert len(ev["r"]) == len(betas)

    def test_mesh_config_validates_skew_probe(self):
        from aiyagari_tpu.config import MeshConfig

        with pytest.raises(ValueError):
            MeshConfig(skew_probe=1)


def _egm_run(model, progress_every=5):
    from aiyagari_tpu.solvers.egm import (
        initial_consumption_guess,
        solve_aiyagari_egm,
    )
    from aiyagari_tpu.utils.firm import wage_from_r

    r = 0.04
    w = float(wage_from_r(r, model.config.technology.alpha,
                          model.config.technology.delta))
    C0 = initial_consumption_guess(model.a_grid, model.s, r, w)

    def run(C):
        return solve_aiyagari_egm(
            C, model.a_grid, model.s, model.P, r, w, model.amin,
            sigma=model.preferences.sigma, beta=model.preferences.beta,
            tol=1e-6, max_iter=100, progress_every=progress_every)

    return run, C0


class TestHeartbeat:
    def test_configure_heartbeat_validates_and_reset_disarms(self):
        from aiyagari_tpu.diagnostics.progress import (
            configure_heartbeat,
            heartbeat_stride,
            reset,
        )

        with pytest.raises(ValueError):
            configure_heartbeat(-1)
        configure_heartbeat(4)
        assert heartbeat_stride() == 4
        reset()
        assert heartbeat_stride() == 0

    def test_off_path_is_jaxpr_and_bitwise_identical(self):
        # THE telemetry-discipline pin: arming the heartbeat stride is
        # host-side fan-out of already-delivered progress records — the
        # traced program depends on progress_every alone, so stride on/off
        # programs are the same jaxpr and the iterates bitwise equal.
        from aiyagari_tpu.diagnostics.progress import configure_heartbeat
        from aiyagari_tpu.models.aiyagari import aiyagari_preset

        run, C0 = _egm_run(aiyagari_preset(grid_size=40))
        configure_heartbeat(0)
        jaxpr_off = str(jax.make_jaxpr(run)(C0))
        sol_off = run(C0)
        configure_heartbeat(3)
        jaxpr_on = str(jax.make_jaxpr(run)(C0))
        sol_on = run(C0)
        jax.effects_barrier()
        assert jaxpr_on == jaxpr_off
        assert bool(jnp.all(sol_on.policy_c == sol_off.policy_c))
        assert float(sol_on.distance) == float(sol_off.distance)

    def test_strided_records_land_on_the_active_ledger(self, tmp_path):
        from aiyagari_tpu.diagnostics.progress import configure_heartbeat
        from aiyagari_tpu.models.aiyagari import aiyagari_preset

        run, C0 = _egm_run(aiyagari_preset(grid_size=40), progress_every=5)
        led = RunLedger(tmp_path / "l.jsonl")
        configure_heartbeat(2)
        with activate(led):
            sol = run(C0)
            jax.block_until_ready(sol.policy_c)
            jax.effects_barrier()
        beats = [e for e in read_ledger(led.path) if e["kind"] == "heartbeat"]
        delivered = int(sol.iterations) // 5
        assert len(beats) == (delivered + 1) // 2   # every 2nd, from the 1st
        for ev in beats:
            assert ev["context"] == "aiyagari_egm"
            assert ev["iteration"] % 5 == 0
            assert ev["distance"] > 0
            # The live stage-dtype signal + the host stamp.
            assert ev["dtype"] == str(C0.dtype)
            assert ev["process_index"] == 0

    def test_off_means_zero_ledger_interaction(self, tmp_path):
        from aiyagari_tpu.diagnostics.progress import heartbeat_stride
        from aiyagari_tpu.models.aiyagari import aiyagari_preset

        assert heartbeat_stride() == 0   # conftest reset
        run, C0 = _egm_run(aiyagari_preset(grid_size=40), progress_every=5)
        led = RunLedger(tmp_path / "l.jsonl")
        with activate(led):
            jax.block_until_ready(run(C0).policy_c)
            jax.effects_barrier()
        assert all(e["kind"] != "heartbeat" for e in read_ledger(led.path))

    def test_sweep_heartbeat_strides_rounds_onto_the_ledger(self, tmp_path):
        from aiyagari_tpu.diagnostics.progress import (
            configure_heartbeat,
            sweep_heartbeat,
        )

        led = RunLedger(tmp_path / "l.jsonl")
        configure_heartbeat(2)
        with activate(led):
            for rnd in range(5):
                sweep_heartbeat("aiyagari_sweep", round_index=rnd,
                                gap=[0.1, 0.2], converged=[False, True],
                                quarantined=[False, False], dtype="float64")
        beats = [e for e in read_ledger(led.path) if e["kind"] == "heartbeat"]
        assert [e["round"] for e in beats] == [0, 2, 4]
        assert beats[0]["gap"] == [0.1, 0.2]
        assert beats[0]["converged"] == [False, True]

class TestWatch:
    def _state(self, tmp_path):
        from aiyagari_tpu.diagnostics.watch import build_state

        base, _ = _write_pod_shards(tmp_path)
        leds = {k: RunLedger(base, run_id="podrun0000000001",
                             process_index=k, process_count=2)
                for k in (0,)}
        leds[0].event("host_skew", axis="scenarios", size=2,
                      rendezvous_seconds=0.001, lag_spread_seconds=0.5,
                      verdict="straggler", straggler=1)
        leds[0].event("quarantine", scenario=1, verdict="rescued")
        leds[0].event("verdict", context="aiyagari_sweep", converged=True,
                      iterations=6)
        return build_state(merge_ledgers([base]))

    def test_build_state_folds_rows_skew_and_verdicts(self, tmp_path):
        runs = self._state(tmp_path)
        assert set(runs) == {"podrun0000000001"}
        run = runs["podrun0000000001"]
        assert run["hosts"] == {0, 1}
        # Per-scenario/per-host/per-context rows from the list-shaped
        # heartbeats: 2 scenarios x 2 writing hosts, one context.
        assert set(run["rows"]) == {(0, 0, "sim"), (0, 1, "sim"),
                                    (1, 0, "sim"), (1, 1, "sim")}
        # The freshest heartbeat wins the row; a context-less quarantine
        # event overrides the lane's verdict in every context.
        assert run["rows"][(1, 0, "sim")]["verdict"] == "rescued"
        assert run["skew"][0]["straggler"] == 1
        assert run["verdicts"][0]["converged"] is True

    def test_render_state_is_a_per_scenario_per_host_table(self, tmp_path):
        from aiyagari_tpu.diagnostics.watch import render_state

        text = render_state(self._state(tmp_path))
        assert "hosts=2" in text
        assert "scenario  host  sweeps  residual" in text
        assert "skew scenarios" in text and "straggler (host 1)" in text
        assert "done aiyagari_sweep: converged after 6 iterations" in text
        # One row per (scenario, host) pair.
        assert len([ln for ln in text.splitlines()
                    if ln.startswith("  0 ") or ln.startswith("  1 ")]) == 4

    def test_watch_cli_once_renders_and_json_folds(self, tmp_path, capsys):
        from aiyagari_tpu.diagnostics.watch import watch_main

        base, _ = _write_pod_shards(tmp_path, torn=True)
        assert watch_main(["--once", str(base)]) == 0
        out = capsys.readouterr().out
        assert "run podrun0000000001" in out
        assert "scenario  host" in out
        assert watch_main(["--once", "--json", str(base)]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["podrun0000000001"]["hosts"] == [0, 1]
        assert "0/0/sim" in doc["podrun0000000001"]["rows"]

    def test_batched_iteration_lists_index_per_lane(self, tmp_path):
        # A vmapped solve's batched progress record carries list-shaped
        # iteration AND distance — each lane's row gets ITS entry, not
        # the whole list.
        from aiyagari_tpu.diagnostics.watch import build_state

        led = RunLedger(tmp_path / "l.jsonl", run_id="f" * 16)
        led.event("heartbeat", context="aiyagari_egm", iteration=[12, 9],
                  distance=[1e-3, 2e-4], dtype="float64")
        run = build_state(read_ledger(led.path))["f" * 16]
        assert run["rows"][(0, 0, "aiyagari_egm")]["sweeps"] == 12
        assert run["rows"][(1, 0, "aiyagari_egm")]["sweeps"] == 9
        assert run["rows"][(1, 0, "aiyagari_egm")]["residual"] == 2e-4

    def test_rows_sort_numerically_past_ten_scenarios(self, tmp_path):
        from aiyagari_tpu.diagnostics.watch import build_state, render_state

        led = RunLedger(tmp_path / "l.jsonl", run_id="g" * 16)
        led.event("heartbeat", context="s", round=1,
                  gap=[0.1] * 12, dtype="float64")
        text = render_state(build_state(read_ledger(led.path)))
        order = [int(ln.split()[0]) for ln in text.splitlines()
                 if ln.strip() and ln.split()[0].isdigit()]
        assert order == list(range(12))

    def test_distinct_contexts_keep_distinct_rows(self, tmp_path):
        # One run carrying two sweep contexts (a transition sweep's
        # stationary-anchor GE rounds + its own rounds) must not fold
        # them into one flip-flopping row.
        from aiyagari_tpu.diagnostics.watch import build_state

        led = RunLedger(tmp_path / "l.jsonl", run_id="d" * 16)
        led.event("heartbeat", context="aiyagari_sweep", round=1,
                  gap=[0.5], dtype="float64")
        led.event("heartbeat", context="mit_transition_sweep", round=2,
                  gap=[0.25], dtype="float64")
        run = build_state(read_ledger(led.path))["d" * 16]
        assert set(run["rows"]) == {(0, 0, "aiyagari_sweep"),
                                    (0, 0, "mit_transition_sweep")}
        assert run["rows"][(0, 0, "aiyagari_sweep")]["residual"] == 0.5
        assert run["rows"][(0, 0, "mit_transition_sweep")][
            "residual"] == 0.25

    def test_watch_cli_waits_for_missing_paths(self, tmp_path, capsys):
        from aiyagari_tpu.diagnostics.watch import watch_main

        assert watch_main(["--once", str(tmp_path / "nope.jsonl")]) == 0
        assert "waiting for" in capsys.readouterr().out

    def test_single_process_ledger_degrades_to_one_host_column(
            self, tmp_path, capsys):
        from aiyagari_tpu.diagnostics.watch import watch_main

        led = RunLedger(tmp_path / "solo.jsonl", meta={"entry": "t"})
        led.event("heartbeat", context="aiyagari_egm", iteration=10,
                  distance=1e-3, dtype="float64")
        assert watch_main(["--once", str(led.path)]) == 0
        out = capsys.readouterr().out
        assert "hosts=1" in out
        assert "aiyagari_egm" in out


class TestSpanRunScoping:
    def test_spans_attribute_to_the_run_not_the_thread(self, tmp_path):
        # Two runs on two threads: each run-keyed collector receives
        # exactly its own run's spans (pre-fix, both pooled into whichever
        # collector was thread-local where the span closed — a merged
        # multi-host report then billed one run's wall-clock to another).
        from aiyagari_tpu.diagnostics.trace import collect_spans, span

        led_a = RunLedger(tmp_path / "a.jsonl", run_id="a" * 16)
        led_b = RunLedger(tmp_path / "b.jsonl", run_id="b" * 16)

        def work(led, name):
            with activate(led), span(name):
                time.sleep(0.01)

        with collect_spans(run_id=led_a.run_id) as got_a, \
                collect_spans(run_id=led_b.run_id) as got_b:
            threads = [threading.Thread(target=work, args=(led_a, "span-a")),
                       threading.Thread(target=work, args=(led_b, "span-b"))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert [r["name"] for r in got_a] == ["span-a"]
        assert [r["name"] for r in got_b] == ["span-b"]
        # Each span record is stamped with its run id for merged reports.
        assert got_a[0]["run_id"] == "a" * 16
        assert got_b[0]["run_id"] == "b" * 16

    def test_dual_registration_delivers_once(self, tmp_path):
        # A collector that is BOTH thread-local and run-keyed (the dispatch
        # _observe scope) must not receive the span twice.
        from aiyagari_tpu.diagnostics.trace import collect_spans, span

        led = RunLedger(tmp_path / "l.jsonl", run_id="c" * 16)
        with activate(led), collect_spans(run_id=led.run_id) as got:
            with span("once"):
                pass
        assert [r["name"] for r in got] == ["once"]

    def test_runless_collection_keeps_thread_local_semantics(self):
        from aiyagari_tpu.diagnostics.trace import collect_spans, span

        with collect_spans() as got:
            with span("plain"):
                pass
        assert [r["name"] for r in got] == ["plain"]
        assert "run_id" not in got[0]


class TestReportCLI:
    def test_report_merges_shards_and_renders_observatory_events(
            self, tmp_path, capsys):
        from aiyagari_tpu.diagnostics.health import report_main

        base, _ = _write_pod_shards(tmp_path, torn=True)
        led = RunLedger(base, run_id="podrun0000000001", process_index=0,
                        process_count=2)
        led.event("host_skew", axis="grid", size=4,
                  rendezvous_seconds=0.002, lag_spread_seconds=0.0001,
                  verdict="balanced", straggler=None)
        led.event("bench_regression", metric="pod_observatory",
                  field="merge.ordered", severity="structural",
                  reason="was true, now false",
                  source="BENCH_r13_observatory.json")
        # The operator names the BASE path; the shards merge implicitly.
        assert report_main([str(base)]) == 0
        out = capsys.readouterr().out
        assert "hosts=2" in out
        assert "skew grid: rendezvous 0.002s" in out and "balanced" in out
        assert "heartbeat sim" in out and "@p0" in out
        assert ("bench regression [structural] "
                "pod_observatory.merge.ordered") in out
        # Explicit multi-path invocation reads the same stream.
        shards = [str(p) for p in shard_paths(base)]
        assert report_main(shards) == 0
        assert "hosts=2" in capsys.readouterr().out

    def test_report_single_file_keeps_strict_torn_semantics(
            self, tmp_path, capsys):
        from aiyagari_tpu.diagnostics.health import report_main

        led = RunLedger(tmp_path / "solo.jsonl")
        led.event("verdict", context="x", converged=True, iterations=3)
        with open(led.path, "a") as f:
            f.write('{"torn')
        # No shards on disk: the historical single-file path still refuses
        # a ledger that cannot round-trip.
        with pytest.raises(json.JSONDecodeError):
            report_main([str(led.path)])
        # A non-shard sibling sharing the prefix must NOT flip the read
        # into the tolerant merge path.
        (tmp_path / "solo.prod.jsonl").write_text("{}\n")
        with pytest.raises(json.JSONDecodeError):
            report_main([str(led.path)])


class TestBenchHistory:
    def _frozen(self):
        return {
            "metric": "pod_observatory", "value": 2.0, "unit": "seconds",
            "devices": 8, "scenarios": 4, "grid": 64,
            "skew": {"axes": {"scenarios": {}, "grid": {}}},
            "heartbeat": {"off_jaxpr_identical": True,
                          "off_bit_identical": True},
            "merge": {"shards": 2, "run_joined": True, "ordered": True},
        }

    def _history(self):
        return {"pod_observatory": [
            {"record": self._frozen(), "source": "BENCH_r13.json"}]}

    def test_matching_record_is_clean(self):
        from aiyagari_tpu.diagnostics.bench_history import check_records

        findings, matched = check_records([self._frozen()],
                                          history=self._history())
        assert findings == [] and matched == ["pod_observatory"]

    def test_unmatched_metric_names_are_ignored(self):
        from aiyagari_tpu.diagnostics.bench_history import check_records

        findings, matched = check_records(
            [{"metric": "never_frozen", "value": 1.0}],
            history=self._history())
        assert findings == [] and matched == []

    def test_structural_regressions_are_flagged(self):
        from aiyagari_tpu.diagnostics.bench_history import check_records

        fresh = self._frozen()
        fresh["heartbeat"]["off_bit_identical"] = False   # bool check
        fresh["merge"]["shards"] = 1                      # count_min
        del fresh["skew"]["axes"]["grid"]                 # keys_min
        findings, _ = check_records([fresh], history=self._history())
        flagged = {f["field"] for f in findings}
        assert flagged == {"heartbeat.off_bit_identical", "merge.shards",
                           "skew.axes"}
        assert all(f["severity"] == "structural" for f in findings)
        assert all(f["source"] == "BENCH_r13.json" for f in findings)

    def test_wall_checks_need_equal_sizing_and_a_catastrophic_band(self):
        from aiyagari_tpu.diagnostics.bench_history import check_records

        fresh = self._frozen()
        fresh["value"] = 15.0    # < 10x frozen 2.0? no: 15 < 20 — inside
        findings, _ = check_records([fresh], history=self._history())
        assert findings == []
        fresh["value"] = 25.0    # outside the 10x catastrophe band
        findings, _ = check_records([fresh], history=self._history())
        assert [f["severity"] for f in findings] == ["wall"]
        # A differently-sized record is never timed against the frozen one.
        fresh["devices"] = 16
        findings, _ = check_records([fresh], history=self._history())
        assert findings == []

    def test_previously_working_metric_now_skipping_is_structural(self):
        from aiyagari_tpu.diagnostics.bench_history import check_records

        findings, _ = check_records(
            [{"metric": "pod_observatory", "skipped": "oom"}],
            history=self._history())
        assert len(findings) == 1
        assert findings[0]["kind"] == "skip"
        assert findings[0]["severity"] == "structural"

    def test_frozen_fields_absent_from_history_hold_nothing(self):
        from aiyagari_tpu.diagnostics.bench_history import check_records

        history = {"pod_observatory": [
            {"record": {"metric": "pod_observatory"},
             "source": "BENCH_r13.json"}]}
        findings, matched = check_records([self._frozen()], history=history)
        assert findings == [] and matched == ["pod_observatory"]

    def test_repo_history_loads_and_matches_itself(self):
        # The real frozen trajectory: every artifact parses, the round-13
        # observatory record is present, and checking a frozen record
        # against its own history finds nothing (the watchdog's fixed
        # point — what `bench.py --preset ci` gates at zero).
        from aiyagari_tpu.diagnostics.bench_history import (
            check_records,
            load_history,
        )

        history = load_history()
        assert "pod_observatory" in history
        frozen = [h[-1]["record"] for h in history.values()]
        findings, matched = check_records(frozen, history=history)
        assert findings == []
        assert "pod_observatory" in matched
