"""Smoke tests for all six runnable example scripts (the reference-script
twins; docs/USAGE.md migration table): they run, converge sanely at --quick
scale, and print the expected summaries.

Subprocesses get a SANITIZED environment (the suite's conftest forces
XLA_FLAGS=--xla_force_host_platform_device_count=8 and JAX_ENABLE_X64, which
users running `python examples/foo.py` do not have) so these pin the actual
single-device user configuration. Slow-marked: ~0.5-2 min each on CPU
(fast once the persistent compile cache is warm).
"""

import os
import re
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _user_env() -> dict:
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("XLA_", "JAX_")):
            del env[k]
    return env


def _run_example(name: str, *extra: str) -> str:
    out = subprocess.run(
        [sys.executable, str(REPO / "examples" / name), "--quick", "--platform", "cpu", *extra],
        capture_output=True, text=True, timeout=540, cwd=REPO, env=_user_env(),
    )
    assert out.returncode == 0, f"{name} failed:\n{out.stdout}\n{out.stderr}"
    return out.stdout


def _check_aiyagari(stdout: str, labor: bool) -> None:
    m = re.search(r"r\* = (-?\d+\.\d+)", stdout)
    assert m, stdout
    # Quick mode starves the bisection; r* must still be inside the bracket.
    assert -0.05 < float(m.group(1)) < 0.05
    g = re.search(r"wealth gini = (\d+\.\d+)", stdout)
    assert g and 0.1 < float(g.group(1)) < 0.7
    if labor:
        l = re.search(r"mean labor supply = (\d+\.\d+)", stdout)
        assert l and 0.2 < float(l.group(1)) < 1.2


def _check_ks(stdout: str) -> None:
    m = re.search(r"per-regime R\^2 = \[(\d+\.\d+), (\d+\.\d+)\]", stdout)
    assert m, stdout
    assert float(m.group(1)) > 0.9 and float(m.group(2)) > 0.9


@pytest.mark.slow
@pytest.mark.parametrize("name,labor", [
    ("aiyagari_vfi.py", False),
    ("aiyagari_egm.py", False),
    ("aiyagari_labor_vfi.py", True),
    ("aiyagari_labor_egm.py", True),
])
def test_aiyagari_examples_smoke(name, labor):
    _check_aiyagari(_run_example(name), labor)


@pytest.mark.slow
def test_sweep_scenarios_example_smoke():
    stdout = _run_example("sweep_scenarios.py")
    m = re.search(r"(\d+) scenarios x", stdout)
    assert m and int(m.group(1)) == 4, stdout
    # The example asserts the beta/sigma comparative statics itself; here we
    # just pin that the batched-bracket solve ran and reported rounds.
    assert re.search(r"batched-bracket solve .*in \d+ rounds", stdout), stdout


@pytest.mark.slow
def test_mit_shock_example_smoke(tmp_path):
    stdout = _run_example("mit_shock.py", "--outdir", str(tmp_path))
    m = re.search(r"newton rounds = (\d+)\s+converged = True", stdout)
    assert m and int(m.group(1)) <= 10, stdout
    assert re.search(r"transitions/sec", stdout), stdout
    assert (tmp_path / "mit_shock_summary.json").exists()


@pytest.mark.slow
def test_krusell_smith_vfi_example_smoke(tmp_path):
    stdout = _run_example("krusell_smith_vfi.py", "--outdir", str(tmp_path))
    _check_ks(stdout)
    # The report surface: figures + summary.json written.
    assert (tmp_path / "summary.json").exists()


@pytest.mark.slow
def test_krusell_smith_egm_example_smoke():
    _check_ks(_run_example("krusell_smith_egm.py", "--closure", "histogram"))
