"""Tier-1 gates for the route observatory's decision half (ISSUE 12):
tuning cache round-trips, resolver-consults-cache end-to-end through
dispatch.solve, prior/default fallbacks, cache hygiene (invalidation +
torn-file), and — the PR 6 zero-cost discipline applied to decisions —
the off-path pin: with tuning disabled and no cache, every resolver
returns today's exact defaults and solve programs/results are bitwise
unchanged.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.config import (
    AiyagariConfig,
    EquilibriumConfig,
    GridSpecConfig,
    SolverConfig,
)
from aiyagari_tpu.diagnostics import metrics
from aiyagari_tpu.diagnostics.ledger import RunLedger, activate, read_ledger
from aiyagari_tpu.dispatch import solve
from aiyagari_tpu.ops.egm import require_xla_egm_kernel, resolve_egm_kernel
from aiyagari_tpu.ops.interp import searchsorted_method
from aiyagari_tpu.ops.pushforward import resolve_backend
from aiyagari_tpu.tuning import autotuner


def _counter_value(name, **labels):
    key = metrics._key(name, labels)
    return metrics.registry._counters.get(key, 0.0)


def _seed_cache(path, knob="pushforward", bucket="b512", dtype="float64",
                choice="scatter", walls=None):
    """A valid measured cache document with one entry."""
    doc = {
        "version": 1,
        "jax_version": jax.__version__,
        "fingerprint": autotuner.platform_fingerprint(),
        "entries": {
            f"{knob}|{bucket}|{dtype}": {
                "choice": choice,
                "source": "measured",
                "walls_us": walls or {choice: 1.0, "transpose": 9.0},
                "na": 512, "reps": 1, "utc": "2026-08-04T00:00:00Z",
            },
        },
    }
    path.write_text(json.dumps(doc))
    return doc


def _tiny_solve(**kw):
    cfg = AiyagariConfig(grid=GridSpecConfig(n_points=24))
    return solve(cfg, method="egm",
                 solver=SolverConfig(method="egm", tol=1e-4, max_iter=150),
                 equilibrium=EquilibriumConfig(max_iter=3, tol=1e-2),
                 aggregation="distribution", on_nonconvergence="ignore",
                 **kw)


class TestOffPathBitIdentity:
    """With tuning disabled and no cache: today's exact defaults
    (ISSUE 12 acceptance: jaxpr/result-pinned)."""

    def test_resolver_defaults(self):
        assert resolve_backend("auto") == "transpose"
        assert resolve_backend(None) == "transpose"
        assert resolve_egm_kernel("auto") == "xla"
        assert require_xla_egm_kernel("auto", "here") == "xla"
        # This suite runs on the CPU host (conftest pins the platform).
        assert searchsorted_method() == "scan"
        assert searchsorted_method(100_000) == "scan"

    def test_explicit_choices_pass_through(self):
        for b in ("scatter", "transpose", "banded", "pallas"):
            assert resolve_backend(b) == b
        for k in ("xla", "pallas_inverse", "pallas_fused"):
            assert resolve_egm_kernel(k) == k

    def test_f32_sim_override_wins_over_cache(self, tmp_path):
        cache = tmp_path / "t.json"
        _seed_cache(cache, choice="banded")
        with autotuner.configure(enabled=True, cache_path=str(cache)):
            # The K-S f32 histogram scan's accuracy constraint is not a
            # tunable decision: scatter regardless of the measured winner.
            assert resolve_backend("auto", f32_sim=True) == "scatter"
            assert resolve_backend("auto", na=512,
                                   dtype=jnp.float64) == "banded"

    def test_auto_jaxpr_identical_to_default_route(self):
        from aiyagari_tpu.sim.distribution import distribution_step

        args = (jnp.ones((3, 16)) / 48.0,
                jnp.clip(jnp.arange(16, dtype=jnp.int32), 0, 14)[None, :]
                * jnp.ones((3, 1), jnp.int32),
                jnp.full((3, 16), 0.5), jnp.full((3, 3), 1.0 / 3))
        auto = jax.make_jaxpr(
            lambda m, i, w, p: distribution_step(m, i, w, p, backend="auto"))
        pinned = jax.make_jaxpr(
            lambda m, i, w, p: distribution_step(m, i, w, p,
                                                 backend="transpose"))
        # The degradation callback's partial repr embeds a host address;
        # everything structural must match exactly.
        import re

        scrub = lambda s: re.sub(r"0x[0-9a-f]+", "0x", s)
        assert scrub(str(auto(*args))) == scrub(str(pinned(*args)))

    def test_solve_results_bitwise_unchanged_by_observability(self, tmp_path):
        """The route_decision emission layer is host-only: a ledger-carrying
        solve returns bit-identical results to a bare one."""
        bare = _tiny_solve()
        led = _tiny_solve(ledger=str(tmp_path / "led.jsonl"))
        assert float(bare.r) == float(led.r)
        np.testing.assert_array_equal(np.asarray(bare.solution.policy_k),
                                      np.asarray(led.solution.policy_k))


class TestCacheRoundTrip:
    def test_autotune_round_trips_deterministically(self, tmp_path):
        cache = tmp_path / "cache.json"
        with autotuner.configure(enabled=True, cache_path=str(cache)):
            entries = autotuner.autotune(["bucket_index"], na=512, reps=1)
            assert list(entries) == ["bucket_index|b512|float64"]
            entry = entries["bucket_index|b512|float64"]
            assert entry["choice"] in ("scan", "sort")
            assert set(entry["walls_us"]) == {"scan", "sort"}
            doc1 = autotuner.load_cache()
            doc2 = autotuner.load_cache()
            assert doc1 == doc2
            assert doc1["entries"]["bucket_index|b512|float64"]["choice"] \
                == entry["choice"]
            # Resolution consults the persisted entry, not process state.
            got = autotuner.resolve_route("bucket_index", "scan", na=512,
                                          dtype=jnp.float64)
            assert got == entry["choice"]

    def test_explain_reproduces_choice_from_walls(self, tmp_path):
        cache = tmp_path / "cache.json"
        _seed_cache(cache, choice="scatter",
                    walls={"scatter": 2.0, "transpose": 5.0, "banded": 9.0})
        with autotuner.configure(cache_path=str(cache)):
            rows = {r["knob"]: r for r in autotuner.explain()}
        pf = rows["pushforward"]
        assert pf["source"] == "measured"
        assert pf["choice"] == "scatter"
        assert pf["reproduced_choice"] == "scatter"
        assert pf["consistent"] is True
        # Knobs without measurements render their shipped default.
        assert rows["egm_kernel"]["source"] == "default"
        assert rows["egm_kernel"]["choice"] == "xla"

    def test_explain_surfaces_inconsistent_entry(self, tmp_path):
        cache = tmp_path / "cache.json"
        _seed_cache(cache, choice="banded",
                    walls={"scatter": 2.0, "banded": 9.0})
        with autotuner.configure(cache_path=str(cache)):
            pf = {r["knob"]: r for r in autotuner.explain()}["pushforward"]
        assert pf["consistent"] is False
        assert pf["reproduced_choice"] == "scatter"


class TestResolveSources:
    def test_measured_beats_default(self, tmp_path):
        cache = tmp_path / "cache.json"
        _seed_cache(cache, choice="scatter")
        with autotuner.configure(enabled=True, cache_path=str(cache)):
            got = autotuner.resolve_route("pushforward", "transpose",
                                          na=512, dtype=jnp.float64)
        assert got == "scatter"
        assert _counter_value("aiyagari_tuning_cache_hits_total",
                              knob="pushforward") >= 1

    def test_nearest_bucket_fallback(self, tmp_path):
        cache = tmp_path / "cache.json"
        _seed_cache(cache, bucket="b512", choice="scatter")
        with autotuner.configure(enabled=True, cache_path=str(cache)):
            # No exact b2048 entry: the nearest measured bucket serves.
            assert autotuner.resolve_route("pushforward", "transpose",
                                           na=2048,
                                           dtype=jnp.float64) == "scatter"
            # And a context-free (dispatch-boundary) lookup still finds it.
            assert autotuner.resolve_route("pushforward",
                                           "transpose") == "scatter"

    def test_miss_falls_back_to_default_on_unmodeled_platform(self, tmp_path):
        with autotuner.configure(enabled=True,
                                 cache_path=str(tmp_path / "none.json")):
            before = _counter_value("aiyagari_tuning_cache_misses_total",
                                    knob="pushforward")
            got = autotuner.resolve_route("pushforward", "transpose",
                                          na=512, dtype=jnp.float64)
        assert got == "transpose"   # CPU has no chip model: no prior
        assert _counter_value("aiyagari_tuning_cache_misses_total",
                              knob="pushforward") == before + 1

    def test_prior_on_modeled_platform(self, tmp_path, monkeypatch):
        monkeypatch.setattr(autotuner, "_platform", lambda: "tpu")
        led = RunLedger(tmp_path / "led.jsonl")
        with autotuner.configure(enabled=True,
                                 cache_path=str(tmp_path / "none.json")):
            with activate(led):
                got = autotuner.resolve_route("pushforward", "transpose",
                                              na=4096, dtype=jnp.float32)
        prior = autotuner._prior_choice("pushforward", 4096, jnp.float32,
                                        "tpu")
        assert prior is not None
        assert got == prior[0]
        ev = [e for e in read_ledger(led.path)
              if e["kind"] == "route_decision"]
        assert len(ev) == 1
        assert ev[0]["source"] == "prior"
        assert set(ev[0]["evidence"]["predicted_us"]) >= {"scatter",
                                                          "transpose"}

    def test_prior_ranks_by_roofline_time(self):
        choice, evidence = autotuner._prior_choice(
            "pushforward", 4096, jnp.float32, "tpu")
        pred = evidence["predicted_us"]
        assert choice == min(pred, key=pred.get)


class TestCacheHygiene:
    def test_stale_jax_version_invalidates(self, tmp_path):
        cache = tmp_path / "cache.json"
        doc = _seed_cache(cache, choice="scatter")
        doc["jax_version"] = "0.0.0-stale"
        cache.write_text(json.dumps(doc))
        with autotuner.configure(enabled=True, cache_path=str(cache)):
            before = _counter_value("aiyagari_tuning_cache_invalidated_total")
            got = autotuner.resolve_route("pushforward", "transpose",
                                          na=512, dtype=jnp.float64)
        assert got == "transpose"
        assert _counter_value("aiyagari_tuning_cache_invalidated_total") \
            == before + 1

    def test_stale_fingerprint_invalidates(self, tmp_path):
        cache = tmp_path / "cache.json"
        doc = _seed_cache(cache, choice="scatter")
        doc["fingerprint"] = "other-box-0000000000"
        cache.write_text(json.dumps(doc))
        with autotuner.configure(enabled=True, cache_path=str(cache)):
            assert autotuner.resolve_route(
                "pushforward", "transpose", na=512,
                dtype=jnp.float64) == "transpose"

    def test_torn_cache_is_loud_but_non_fatal(self, tmp_path):
        cache = tmp_path / "torn.json"
        cache.write_text('{"version": 1, "entr')
        autotuner._torn_warned.discard(str(cache))
        led = RunLedger(tmp_path / "led.jsonl")
        with autotuner.configure(enabled=True, cache_path=str(cache)):
            before = _counter_value("aiyagari_tuning_cache_torn_total")
            with activate(led):
                with pytest.warns(RuntimeWarning, match="torn/corrupt"):
                    got = autotuner.resolve_route("pushforward", "transpose",
                                                  na=512, dtype=jnp.float64)
        assert got == "transpose"
        assert _counter_value("aiyagari_tuning_cache_torn_total") \
            == before + 1
        degr = [e for e in read_ledger(led.path)
                if e["kind"] == "degradation"]
        assert any(e.get("event") == "tuning_cache_torn" for e in degr)

    def test_empty_cache_path_disables_persistence(self, tmp_path):
        """configure(cache_path="") mirrors the env kill switch: no file
        is read or written, resolution keeps the defaults."""
        with autotuner.configure(enabled=True, cache_path=""):
            assert autotuner.tuning_cache_path() is None
            assert autotuner.resolve_route(
                "pushforward", "transpose", na=512,
                dtype=jnp.float64) == "transpose"
            doc = autotuner.load_cache()
            doc["entries"]["pushforward|b512|float64"] = {"choice": "banded"}
            assert autotuner.save_cache(doc) is None

    def test_explain_renders_malformed_walls_without_crashing(self, tmp_path):
        cache = tmp_path / "cache.json"
        _seed_cache(cache, choice="scatter",
                    walls={"scatter": None, "transpose": "10"})
        with autotuner.configure(cache_path=str(cache)):
            rows = {r["knob"]: r for r in autotuner.explain()}
            pf = rows["pushforward"]
            assert pf["reproduced_choice"] is None
            assert pf["consistent"] is False
            # And the text renderer survives the same entry.
            assert "malformed" in autotuner._render_rows([pf])

    def test_save_cache_is_atomic_and_valid_json(self, tmp_path):
        cache = tmp_path / "c.json"
        with autotuner.configure(cache_path=str(cache)):
            doc = autotuner.load_cache()
            doc["entries"]["pushforward|b512|float64"] = {"choice": "banded"}
            autotuner.save_cache(doc)
            assert json.loads(cache.read_text())["entries"]
            assert not list(tmp_path.glob("*.tmp"))


class TestDispatchEndToEnd:
    def test_route_decisions_exactly_once_per_knob(self, tmp_path):
        path = tmp_path / "led.jsonl"
        _tiny_solve(ledger=str(path))
        decisions = [e for e in read_ledger(path)
                     if e["kind"] == "route_decision"]
        by_knob = {}
        for ev in decisions:
            by_knob.setdefault(ev["knob"], []).append(ev)
        # All three knobs resolve at the dispatch boundary every run (the
        # trace-time resolutions inside the plan build dedupe against
        # them — and jit caching may skip them entirely on re-runs, which
        # is exactly why the boundary emission exists).
        assert set(by_knob) == {"pushforward", "egm_kernel", "bucket_index"}
        for knob, evs in by_knob.items():
            assert len(evs) == 1, (knob, evs)
            assert evs[0]["source"] == "default"
            assert "evidence" in evs[0]
        assert by_knob["pushforward"][0]["choice"] == "transpose"
        assert by_knob["egm_kernel"][0]["choice"] == "xla"
        assert by_knob["bucket_index"][0]["choice"] == "scan"
        # The boundary resolution carries the run's own context: grid
        # bucket + solve dtype, not the context-free "any" cell.
        assert by_knob["pushforward"][0]["bucket"] == "b32"
        assert by_knob["pushforward"][0]["dtype"] == "float64"

    def test_rerun_on_same_ledger_emits_again(self, tmp_path):
        path = tmp_path / "led.jsonl"
        led = RunLedger(path)
        _tiny_solve(ledger=led)
        _tiny_solve(ledger=led)
        decisions = [e for e in read_ledger(path)
                     if e["kind"] == "route_decision"
                     and e["knob"] == "pushforward"]
        # Each activation scope is one run: two solves, two decisions.
        assert len(decisions) == 2

    def test_measured_decision_through_dispatch_solve(self, tmp_path):
        cache = tmp_path / "cache.json"
        _seed_cache(cache, choice="scatter",
                    walls={"scatter": 1.0, "transpose": 2.0})
        path = tmp_path / "led.jsonl"
        with autotuner.configure(enabled=True, cache_path=str(cache)):
            res = _tiny_solve(ledger=str(path))
        assert res.converged or res.r is not None
        decisions = {e["knob"]: e for e in read_ledger(path)
                     if e["kind"] == "route_decision"}
        pf = decisions["pushforward"]
        assert pf["source"] == "measured"
        assert pf["choice"] == "scatter"
        assert pf["evidence"]["walls_us"] == {"scatter": 1.0,
                                              "transpose": 2.0}
        assert _counter_value("aiyagari_route_decisions_total",
                              knob="pushforward", choice="scatter",
                              source="measured") >= 1

    def test_measured_route_and_default_route_agree(self, tmp_path):
        """A measured winner actually reroutes the solve — and because
        every DistributionBackend computes the same operator, the
        measured-route result matches the default-route one to roundoff."""
        ref = _tiny_solve()
        cache = tmp_path / "cache.json"
        _seed_cache(cache, choice="scatter")
        with autotuner.configure(enabled=True, cache_path=str(cache)):
            got = _tiny_solve()
        assert abs(float(ref.r) - float(got.r)) < 1e-9


class TestCli:
    def test_tune_explain_renders_cached_table(self, tmp_path, capsys):
        cache = tmp_path / "cache.json"
        _seed_cache(cache, choice="scatter",
                    walls={"scatter": 2.0, "transpose": 5.0})
        from aiyagari_tpu.tuning.autotuner import tune_main

        rc = tune_main(["--explain", "--cache", str(cache), "--json"])
        assert rc == 0
        rows = json.loads(capsys.readouterr().out)
        by_knob = {r["knob"]: r for r in rows}
        assert by_knob["pushforward"]["choice"] == "scatter"
        assert by_knob["pushforward"]["source"] == "measured"
        assert by_knob["bucket_index"]["source"] == "default"
