"""Differentiable solve stack (ISSUE 17): custom_vjp/IFT fixed points and
the gradient-based calibration subsystem built on them.

The correctness anchors, in dependency order:

  primal bit-identity — every *_implicit wrapper runs the SAME iteration
      as its plain counterpart under stop_gradient; the forward answer is
      bitwise equal, so wrapping a solve can never change what it solves.
  adjoint-vs-FD parity — each wrapped fixed point's reverse-mode gradient
      agrees with central finite differences of the UNWRAPPED primal to
      ~1e-6 relative in f64 (FD truncation is the binding error, not the
      adjoint: the Neumann adjoints are measured at 1e-10).
  operator adjoint pairing — the distribution adjoint rides on
      expectation_step being the exact transpose of distribution_step;
      <f, T mu> == <T' f, mu> to machine precision is the structural fact
      the custom_vjp trusts.
  quarantine, not NaN-poisoning — a calibration lane whose objective goes
      non-finite is masked out of the vmapped update; the other lanes
      never see its NaN (same discipline as the serve layer's AIYA107).
  end-to-end recovery — dispatch.calibrate at self-generated targets
      converges immediately (the planted-parameter 1e-3 recovery gate
      runs in the ci bench battery; here we pin the wiring, not the
      walltime).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import aiyagari_tpu as at
from aiyagari_tpu.models.aiyagari import AiyagariModel
from aiyagari_tpu.ops.implicit import fixed_point_vjp, two_point_root_vjp
from aiyagari_tpu.sim.distribution import (
    aggregate_capital,
    distribution_step,
    expectation_step,
    stationary_distribution,
    stationary_distribution_implicit,
    young_lottery,
)
from aiyagari_tpu.solvers.egm import (
    initial_consumption_guess,
    solve_aiyagari_egm,
    solve_aiyagari_egm_implicit,
)

CFG = at.AiyagariConfig(
    grid=at.GridSpecConfig(n_points=24),
    income=at.IncomeProcess(n_states=3, method="rouwenhorst"),
)
R, W = 0.03, 1.1


@pytest.fixture(scope="module")
def model():
    return AiyagariModel.from_config(CFG, jnp.float64)


@pytest.fixture(scope="module")
def hh(model):
    a_grid = jnp.asarray(model.a_grid)
    s = jnp.asarray(model.s)
    P = jnp.asarray(model.P)
    C0 = initial_consumption_guess(a_grid, s, R, W)
    return a_grid, s, P, C0


def test_implicit_ops_analytic():
    # fixed_point_vjp on x* = 0.5 x* + p  =>  x* = 2p, d sum(x*)/dp = 2.
    def step(x, p):
        return 0.5 * x + p

    p = jnp.asarray([0.3, 0.7])

    def f(p):
        x_star = jax.lax.stop_gradient(2.0 * p)
        return jnp.sum(fixed_point_vjp(step, x_star, p))

    g = jax.grad(f)(p)
    np.testing.assert_allclose(np.asarray(g), 2.0, rtol=1e-12)

    # two_point_root_vjp on gap(x, p) = x^2 - p  =>  x* = sqrt(p),
    # dx*/dp = 1 / (2 sqrt(p)).
    def gap(x, p):
        return x * x - p

    def h(p):
        x_star = jax.lax.stop_gradient(jnp.sqrt(p))
        return two_point_root_vjp(gap, x_star, p)

    p0 = jnp.asarray(2.0)
    g = float(jax.grad(h)(p0))
    np.testing.assert_allclose(g, 0.5 / float(jnp.sqrt(p0)), rtol=1e-12)


def test_egm_implicit_bit_identity_and_gradient(model, hh):
    a_grid, s, P, C0 = hh
    amin = float(model.amin)
    sigma = model.preferences.sigma

    def solve(beta):
        return solve_aiyagari_egm_implicit(
            C0, a_grid, s, P, R, W, amin, sigma=sigma, beta=beta,
            tol=1e-13, max_iter=8000)

    plain = solve_aiyagari_egm(
        C0, a_grid, s, P, R, W, amin, sigma=sigma, beta=0.96,
        tol=1e-13, max_iter=8000, egm_kernel="xla")
    wrapped = solve(0.96)
    assert bool(jnp.all(plain.policy_c == wrapped.policy_c))
    assert bool(jnp.all(plain.policy_k == wrapped.policy_k))

    # NOT sum(c) + sum(k): that is the budget identity (1+r)a + ws,
    # constant in beta — its true derivative is zero.
    f = lambda b: jnp.sum(solve(b).policy_c)  # noqa: E731
    g = float(jax.grad(f)(0.96))
    h = 1e-6
    fd = float(f(0.96 + h) - f(0.96 - h)) / (2 * h)
    assert abs(g - fd) / abs(fd) < 1e-6


def test_distribution_implicit_bit_identity_and_gradient(model, hh):
    a_grid, s, P, C0 = hh
    pol = solve_aiyagari_egm(
        C0, a_grid, s, P, R, W, float(model.amin),
        sigma=model.preferences.sigma, beta=0.96,
        tol=1e-13, max_iter=8000, egm_kernel="xla").policy_k

    plain = stationary_distribution(pol, a_grid, P, tol=1e-13,
                                    max_iter=40_000)
    wrapped = stationary_distribution_implicit(pol, a_grid, P, tol=1e-13,
                                               max_iter=40_000)
    assert bool(jnp.all(plain.mu == wrapped.mu))

    def K_of(t):
        polt = pol + t * 0.01 * a_grid[None, :]
        d = stationary_distribution_implicit(polt, a_grid, P, tol=1e-13,
                                             max_iter=40_000)
        return aggregate_capital(d.mu, a_grid)

    g = float(jax.grad(K_of)(0.0))
    fd = float(K_of(1e-5) - K_of(-1e-5)) / 2e-5
    assert abs(g - fd) / abs(fd) < 1e-6


def test_expectation_step_is_distribution_step_transpose(model, hh):
    a_grid, s, P, C0 = hh
    pol = solve_aiyagari_egm(
        C0, a_grid, s, P, R, W, float(model.amin),
        sigma=model.preferences.sigma, beta=0.96,
        tol=1e-13, max_iter=8000, egm_kernel="xla").policy_k
    mu = stationary_distribution(pol, a_grid, P, tol=1e-13,
                                 max_iter=40_000).mu
    idx, w_lo = young_lottery(pol, a_grid)
    f = jnp.sin(jnp.arange(pol.size, dtype=jnp.float64)).reshape(pol.shape)
    lhs = jnp.vdot(f, distribution_step(mu, idx, w_lo, P))
    rhs = jnp.vdot(expectation_step(f, idx, w_lo, P), mu)
    assert abs(float(lhs - rhs)) < 1e-12


@pytest.mark.slow  # ~16 s: the composite-moment FD sweep; the per-stage
# gradient parities stay tier-1 above and the battery's calibration leg
# re-gates grad-vs-FD (<1e-4) on every run (test_bench_ci).
def test_steady_state_map_gradient_parity(model):
    from aiyagari_tpu.calibrate.economy import steady_state_map
    from aiyagari_tpu.calibrate.moments import moments_of

    a_grid = jnp.asarray(model.a_grid)
    kw = dict(n_states=3, alpha=CFG.technology.alpha,
              delta=CFG.technology.delta, amin=model.amin)

    # A composite that exercises every moment AND the GE interest rate,
    # so a wrong cotangent anywhere in the chain (income discretization,
    # EGM pair, distribution adjoint, two-point root) shows up.
    def f(beta, sigma, rho, sige):
        st = steady_state_map(beta, sigma, rho, sige, a_grid, **kw)
        mom = moments_of(st, a_grid, alpha=CFG.technology.alpha)
        return (mom["gini"] + 2.0 * mom["k_y"] + 3.0 * mom["mpc"]
                + 4.0 * mom["top10_share"] + 5.0 * st["r"])

    args = [jnp.asarray(x) for x in (0.96, 5.0, 0.75, 0.75)]
    g = [float(x) for x in jax.grad(f, argnums=(0, 1, 2, 3))(*args)]

    # sigma's FD needs a larger step: the objective is stiff in sigma, so
    # 1e-5 is roundoff-limited there while 1e-4 is truncation-limited
    # elsewhere.
    h = {0: 1e-5, 1: 1e-4, 2: 1e-5, 3: 1e-5}
    for i in range(4):
        ap = list(args)
        am = list(args)
        ap[i] = args[i] + h[i]
        am[i] = args[i] - h[i]
        fd = (float(f(*ap)) - float(f(*am))) / (2 * h[i])
        assert abs(g[i] - fd) / max(abs(fd), 1e-12) < 1e-6, (i, g[i], fd)


def test_transition_implicit_bit_identity_and_gradient(model):
    from aiyagari_tpu.transition.implicit import transition_r_path_implicit
    from aiyagari_tpu.transition.mit import solve_transition

    eq = at.EquilibriumConfig(max_iter=60, tol=1e-11)
    shock = at.MITShock(param="tfp", size=0.01, rho=0.6)
    trans = at.TransitionConfig(T=6, method="newton", tol=1e-12, max_iter=60)
    solver = at.SolverConfig(method="egm", tol=1e-13, max_iter=8000)
    weights = np.arange(1.0, 7.0)

    def full(sz, ss=None, jac=None):
        sh = at.MITShock(param="tfp", size=float(sz), rho=0.6)
        res = solve_transition(model, sh, trans=trans, solver=solver,
                               eq=eq, ss=ss, jacobian=jac)
        return res, float(np.dot(weights, res.r_path))

    res0, _ = full(0.01)
    assert res0.converged

    def f(size):
        rp = transition_r_path_implicit(size, primal=res0, model=model,
                                        shock=shock)
        return jnp.dot(jnp.asarray(weights), rp)

    g = float(jax.grad(f)(jnp.asarray(0.01)))
    # FD re-solves reuse the primal's steady state and sequence-space
    # Jacobian — the SAME frozen-Jacobian map the implicit wrapper
    # differentiates, so FD and adjoint see one function.
    h = 1e-4
    _, fp = full(0.01 + h, ss=res0.ss, jac=res0.jacobian)
    _, fm = full(0.01 - h, ss=res0.ss, jac=res0.jacobian)
    fd = (fp - fm) / (2 * h)
    assert abs(g - fd) / abs(fd) < 1e-6

    rp = transition_r_path_implicit(jnp.asarray(0.01), primal=res0,
                                    model=model, shock=shock)
    assert bool(jnp.all(jnp.asarray(res0.r_path) == rp))


def test_fit_quarantines_nonfinite_lane():
    from aiyagari_tpu.calibrate.optimize import fit

    def loss_for(dtype_str):
        dt = jnp.dtype(dtype_str)

        def loss(z):
            z = z.astype(dt)
            bad = jnp.where(z[0] < 0.0, jnp.nan, 0.0)
            # Minimum at (1, 1), well away from the NaN half-space: the
            # healthy lane must never wander into quarantine territory.
            return jnp.sum((z - 1.0) ** 2) + bad

        return loss

    z0 = np.array([[2.0, 2.0], [-1.0, 1.0]])
    res = fit(loss_for, z0, steps=60, lr=0.2,
              stage_dtypes=("float64",), polish=True)
    # Lane 1's very first evaluation is NaN: quarantined before any
    # update, its iterate frozen at z0; lane 0 never sees the NaN and
    # drives to the minimum.
    assert list(res.alive) == [True, False]
    assert res.status == "converged"
    assert res.best_lane == 0
    assert bool(res.converged[0]) and not bool(res.converged[1])
    np.testing.assert_array_equal(res.z[1], z0[1])
    assert res.loss[0] < 1e-9


@pytest.mark.slow  # ~15 s: the 2-lane dispatch.calibrate e2e; quarantine
# and validation stay tier-1 here, and the battery's calibration leg
# replants and recovers the full parameter vector on every run
# (test_bench_ci gates recovery <1e-3).
def test_dispatch_calibrate_recovers_self_targets():
    from aiyagari_tpu.calibrate.moments import model_moments

    base = at.AiyagariConfig(
        grid=at.GridSpecConfig(n_points=16),
        income=at.IncomeProcess(rho=0.75, sigma_e=0.75, n_states=3,
                                method="rouwenhorst"),
    )
    ss_kwargs = dict(bisect_iters=45, hh_tol=1e-12, hh_max_iter=4000,
                     dist_tol=1e-13, dist_max_iter=20_000)
    targets = model_moments(base, **ss_kwargs)
    assert set(targets) == {"gini", "k_y", "mpc", "top10_share"}

    trail = []
    res = at.dispatch.calibrate(
        base, targets, lanes=2, steps=2, lr=0.05, seed=0, jitter=1e-4,
        polish=False, stage_dtypes=("float64",), ss_kwargs=ss_kwargs,
        on_step=lambda step, loss, alive: trail.append((step, loss.copy())))
    # Lane 0 starts AT the planted truth (jitter only perturbs the other
    # lanes), so the very first objective read is already inside tol.
    assert res.status == "converged"
    assert res.theta is not None and res.moments is not None
    for name in ("beta", "sigma", "rho", "sigma_e"):
        assert name in res.theta
    assert abs(res.theta["beta"] - base.preferences.beta) < 1e-6
    assert abs(res.theta["sigma"] - base.preferences.sigma) < 1e-6
    assert abs(res.theta["rho"] - base.income.rho) < 1e-6
    assert abs(res.theta["sigma_e"] - base.income.sigma_e) < 1e-6
    for name, tv in targets.items():
        assert abs(res.moments[name] - tv) / max(abs(tv), 1e-12) < 1e-6
    assert trail and trail[0][0] == 1
    assert res.lanes == 2
    assert res.fit.grad_evals >= 2


def test_dispatch_calibrate_rejects_bad_inputs():
    base = at.AiyagariConfig(
        grid=at.GridSpecConfig(n_points=16),
        income=at.IncomeProcess(n_states=3, method="rouwenhorst"),
    )
    with pytest.raises(ValueError, match="target"):
        at.dispatch.calibrate(base, {})
    with pytest.raises(ValueError, match="moment"):
        at.dispatch.calibrate(base, {"nope": 1.0})
    with pytest.raises(ValueError, match="rouwenhorst"):
        tauchen = at.AiyagariConfig(
            grid=at.GridSpecConfig(n_points=16),
            income=at.IncomeProcess(n_states=3, method="tauchen"))
        at.dispatch.calibrate(tauchen, {"gini": 0.38})
