"""ISSUE 6 tier-1: the observability subsystem.

What these tests pin, in order of importance:
  1. zero-cost-when-off — a telemetry-None solve traces to a program whose
     jaxpr carries NO ring buffer, and its iterates are BITWISE identical
     to the recorder-on solve's (the recorder is write-only);
  2. every solver family returns a POPULATED SolveTelemetry when telemetry
     is enabled: EGM (plain/labor/safe/multiscale/sharded), VFI
     (dense/labor), the stationary distribution, both GE closures
     (bisection + batched), KS, and the transition Newton loop;
  3. the recorder's ring semantics (last-`capacity` retained, `count`
     truthful), the vmap one-recorder-per-scenario contract, and the
     degradation counters (accel trips, push-forward fallbacks);
  4. the run-ledger/trace/metrics/health layers and the report CLI;
  5. the satellites: sink scalar coercion, progress-state isolation, the
     counted push-forward degradation event, and enforce_convergence
     carrying the loop's final telemetry through policy='raise'.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.config import (
    AiyagariConfig,
    EquilibriumConfig,
    GridSpecConfig,
    SolverConfig,
    TelemetryConfig,
)
from aiyagari_tpu.diagnostics.telemetry import (
    SolveTelemetry,
    host_telemetry,
    telemetry_init,
    telemetry_record,
    telemetry_stages,
    telemetry_summary,
    telemetry_trajectory,
)
from aiyagari_tpu.models.aiyagari import aiyagari_labor_preset, aiyagari_preset
from aiyagari_tpu.solvers.egm import (
    initial_consumption_guess,
    solve_aiyagari_egm,
    solve_aiyagari_egm_labor,
    solve_aiyagari_egm_multiscale,
)
from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi, solve_aiyagari_vfi_labor
from aiyagari_tpu.utils.firm import wage_from_r

R = 0.04
TELE = TelemetryConfig(capacity=64)


def _problem(n=60):
    m = aiyagari_preset(grid_size=n)
    w = float(wage_from_r(R, m.config.technology.alpha,
                          m.config.technology.delta))
    C0 = initial_consumption_guess(m.a_grid, m.s, R, w)
    return m, w, C0


class TestRecorderCore:
    def test_ring_wraps_keeping_tail_and_true_count(self):
        tele = telemetry_init(TelemetryConfig(capacity=4))
        for i in range(7):
            tele = telemetry_record(tele, jnp.float64(10.0 - i))
        assert int(tele.count) == 7
        traj = telemetry_trajectory(tele)
        # Last 4 residuals, chronological: 10-3 .. 10-6.
        np.testing.assert_allclose(traj, [7.0, 6.0, 5.0, 4.0])
        assert list(telemetry_stages(tele)) == [64, 64, 64, 64]

    def test_short_run_keeps_order_and_stage_bits(self):
        tele = telemetry_init(TelemetryConfig(capacity=8))
        tele = telemetry_record(tele, jnp.float32(1.0))
        tele = telemetry_record(tele, jnp.float64(0.5))
        np.testing.assert_allclose(telemetry_trajectory(tele), [1.0, 0.5])
        assert list(telemetry_stages(tele)) == [32, 64]
        s = telemetry_summary(tele)
        assert s["sweeps"] == 2 and s["retained"] == 2
        assert s["final_residual"] == 0.5

    def test_off_is_none_everywhere(self):
        assert telemetry_init(None) is None
        assert telemetry_record(None, 1.0) is None
        assert telemetry_summary(None) is None

    def test_capacity_validation(self):
        with pytest.raises(ValueError, match="capacity"):
            telemetry_init(TelemetryConfig(capacity=0))

    def test_host_telemetry_matches_device_shape(self):
        tele = host_telemetry([3.0, 2.0, 1.0], [32, 32, 64], trips=2,
                              fallbacks=1)
        assert isinstance(tele, SolveTelemetry)
        np.testing.assert_allclose(telemetry_trajectory(tele), [3.0, 2.0, 1.0])
        assert list(telemetry_stages(tele)) == [32, 32, 64]
        s = telemetry_summary(tele)
        assert s["accel_trips"] == 2 and s["pushforward_fallbacks"] == 1

    def test_batched_trajectory_read_is_loud(self):
        tele = telemetry_init(TelemetryConfig(capacity=4))
        batched = jax.tree_util.tree_map(
            lambda l: jnp.stack([l, l]), tele)
        with pytest.raises(ValueError, match="ONE recorder"):
            telemetry_trajectory(batched)


class TestEGMTelemetry:
    def test_populated_and_off_path_identical(self):
        m, w, C0 = _problem()
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  tol=1e-5, max_iter=1000)
        on = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, R, w, m.amin,
                                telemetry=TELE, **kw)
        off = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, R, w, m.amin, **kw)
        assert off.telemetry is None
        assert int(on.telemetry.count) == int(on.iterations)
        # Write-only recorder: the iterates are bitwise unchanged.
        assert bool(jnp.all(on.policy_c == off.policy_c))
        assert bool(jnp.all(on.policy_k == off.policy_k))
        assert float(on.distance) == float(off.distance)
        # The retained trajectory ends at the certified final residual.
        traj = telemetry_trajectory(on.telemetry)
        assert traj[-1] == np.float32(float(on.distance))
        # Monotone-ish decay: the last residual is far below the first.
        assert traj[-1] < traj[0]

    def test_off_jaxpr_carries_no_ring_buffer(self):
        m, w, C0 = _problem(40)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  tol=1e-5, max_iter=50)

        def run(tele):
            return solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, R, w, m.amin,
                                      telemetry=tele, **kw)

        jaxpr_off = str(jax.make_jaxpr(lambda: run(None))())
        jaxpr_on = str(jax.make_jaxpr(lambda: run(TELE))())
        ring = f"f32[{TELE.capacity}]"
        assert ring not in jaxpr_off       # compiled out entirely
        assert ring in jaxpr_on            # the ring rides the on-carry

    def test_labor_family_populated(self):
        m = aiyagari_labor_preset(grid_size=50)
        w = float(wage_from_r(R, m.config.technology.alpha,
                              m.config.technology.delta))
        C0 = initial_consumption_guess(m.a_grid, m.s, R, w)
        sol = solve_aiyagari_egm_labor(
            C0, m.a_grid, m.s, m.P, R, w, m.amin,
            sigma=m.preferences.sigma, beta=m.preferences.beta,
            psi=m.preferences.psi, eta=m.preferences.eta,
            tol=1e-5, max_iter=1000, telemetry=TELE)
        assert int(sol.telemetry.count) == int(sol.iterations) > 0

    def test_multiscale_records_final_stage_only(self):
        # Warm stages are prolongation inputs, not certified solutions: the
        # recorder rides the FINAL stage (whose sweep count is what the
        # ladder reports as `iterations`), and the warm start makes that
        # count far smaller than a cold solve at the same grid would need.
        n = 2000   # > LADDER_MIN_FINE so the ladder actually runs stages
        m, w, _ = _problem(n)
        sol = solve_aiyagari_egm_multiscale(
            m.a_grid, m.s, m.P, R, w, m.amin,
            sigma=m.preferences.sigma, beta=m.preferences.beta,
            tol=1e-5, max_iter=1000,
            grid_power=float(m.config.grid.power), telemetry=TELE)
        assert sol.telemetry is not None
        assert 0 < int(sol.telemetry.count) == int(sol.iterations) < 100
        traj = telemetry_trajectory(sol.telemetry)
        assert traj[-1] == np.float32(float(sol.distance))

    def test_accel_trips_field_tracks_safeguard(self):
        from aiyagari_tpu.config import AccelConfig

        m, w, C0 = _problem()
        sol = solve_aiyagari_egm(
            C0, m.a_grid, m.s, m.P, R, w, m.amin,
            sigma=m.preferences.sigma, beta=m.preferences.beta,
            tol=1e-5, max_iter=1000, accel=AccelConfig(), telemetry=TELE)
        # The shipped calibration converges without safeguard trips — the
        # field exists, is an int, and is consistent with a clean run.
        assert int(sol.telemetry.accel_trips) >= 0
        assert int(sol.telemetry.count) == int(sol.iterations)

    def test_vmap_one_recorder_per_scenario(self):
        m, w, C0 = _problem(40)
        rs = jnp.asarray([0.01, 0.03, 0.05])
        ws = jnp.asarray([float(wage_from_r(float(r),
                                            m.config.technology.alpha,
                                            m.config.technology.delta))
                          for r in rs])

        def one(r, w):
            return solve_aiyagari_egm(
                C0, m.a_grid, m.s, m.P, r, w, m.amin,
                sigma=m.preferences.sigma, beta=m.preferences.beta,
                tol=1e-5, max_iter=1000, telemetry=TELE)

        batch = jax.vmap(one)(rs, ws)
        assert batch.telemetry.residuals.shape == (3, TELE.capacity)
        counts = np.asarray(batch.telemetry.count)
        assert counts.shape == (3,)
        np.testing.assert_array_equal(counts, np.asarray(batch.iterations))
        # Scenarios genuinely differ: each recorder holds its own tail.
        t0 = telemetry_trajectory(jax.tree_util.tree_map(
            lambda l: l[0], batch.telemetry))
        t2 = telemetry_trajectory(jax.tree_util.tree_map(
            lambda l: l[2], batch.telemetry))
        assert not np.array_equal(t0, t2)


class TestVFITelemetry:
    def test_dense_populated_and_off_identical(self):
        m, w, _ = _problem(50)
        v0 = jnp.zeros((m.s.shape[0], m.a_grid.shape[0]), m.dtype)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  tol=1e-5, max_iter=2000)
        on = solve_aiyagari_vfi(v0, m.a_grid, m.s, m.P, R, w, telemetry=TELE,
                                **kw)
        off = solve_aiyagari_vfi(v0, m.a_grid, m.s, m.P, R, w, **kw)
        assert off.telemetry is None
        assert int(on.telemetry.count) == int(on.iterations)
        assert bool(jnp.all(on.v == off.v))
        assert telemetry_trajectory(on.telemetry)[-1] == np.float32(
            float(on.distance))

    def test_labor_populated(self):
        m = aiyagari_labor_preset(grid_size=40)
        w = float(wage_from_r(R, m.config.technology.alpha,
                              m.config.technology.delta))
        v0 = jnp.zeros((m.s.shape[0], m.a_grid.shape[0]), m.dtype)
        sol = solve_aiyagari_vfi_labor(
            v0, m.a_grid, m.labor_grid, m.s, m.P, R, w,
            sigma=m.preferences.sigma, beta=m.preferences.beta,
            psi=m.preferences.psi, eta=m.preferences.eta,
            tol=1e-4, max_iter=2000, telemetry=TELE)
        assert int(sol.telemetry.count) == int(sol.iterations) > 0


class TestDistributionTelemetry:
    def _policy(self, m):
        pk = jnp.clip(0.9 * m.a_grid + 0.1, m.a_grid[0], m.a_grid[-1])
        return jnp.broadcast_to(pk[None, :],
                                (m.s.shape[0], m.a_grid.shape[0]))

    def test_populated_and_off_identical(self):
        from aiyagari_tpu.sim.distribution import stationary_distribution

        m, _, _ = _problem()
        pk = self._policy(m)
        on = stationary_distribution(pk, m.a_grid, m.P, tol=1e-10,
                                     max_iter=5000, telemetry=TELE)
        off = stationary_distribution(pk, m.a_grid, m.P, tol=1e-10,
                                      max_iter=5000)
        assert off.telemetry is None
        assert int(on.telemetry.count) == int(on.iterations)
        assert bool(jnp.all(on.mu == off.mu))
        assert int(on.telemetry.fallbacks) == 0   # monotone policy

    def test_adversarial_policy_counts_fallbacks_and_metrics(self, rng):
        from aiyagari_tpu.diagnostics import ledger, metrics
        from aiyagari_tpu.sim.distribution import stationary_distribution

        m, _, _ = _problem(40)
        pk_bad = jnp.asarray(rng.uniform(
            float(m.a_grid[0]), float(m.a_grid[-1]),
            size=(m.s.shape[0], m.a_grid.shape[0])))
        events = []
        with ledger.activate(_ListLedger(events)):
            sol = stationary_distribution(pk_bad, m.a_grid, m.P, tol=1e-10,
                                          max_iter=200, telemetry=TELE)
            n = int(sol.iterations)
            jax.effects_barrier()   # drain the async degradation callback
        # Every degraded sweep is tallied in the device recorder...
        assert int(sol.telemetry.fallbacks) == n > 0
        # ...the process counter got the plan-level event...
        assert metrics.counter("aiyagari_pushforward_fallback_total",
                               route="transpose").value >= 1
        # ...and the active ledger got the degradation event.
        assert any(e[0] == "degradation"
                   and e[1]["event"] == "pushforward_fallback"
                   for e in events)


class _ListLedger:
    """Minimal active-ledger stand-in capturing emit() calls."""

    def __init__(self, out):
        self._out = out

    def event(self, kind, **fields):
        self._out.append((kind, fields))


class TestShardedTelemetry:
    @pytest.mark.slow  # ~19 s: the sharded flat-leaf recorder crossing's
    # off-path stays tier-1 below, and the recorder-trajectory contract is
    # pinned unsharded per family above.
    def test_sharded_recorder_matches_unsharded(self):
        from aiyagari_tpu.parallel.mesh import make_mesh
        from aiyagari_tpu.solvers.egm_sharded import solve_aiyagari_egm_sharded

        n = 8_192
        m = aiyagari_preset(grid_size=n)
        w = float(wage_from_r(R, m.config.technology.alpha,
                              m.config.technology.delta))
        C0 = initial_consumption_guess(m.a_grid, m.s, R, w)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  tol=1e-30, max_iter=6,
                  grid_power=float(m.config.grid.power))
        ref = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, R, w, m.amin,
                                 telemetry=TELE, **kw)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_sharded(mesh, C0, m.a_grid, m.s, m.P, R, w,
                                         m.amin, telemetry=TELE, **kw)
        assert int(sol.telemetry.count) == int(ref.telemetry.count) == 6
        # The pmax'd global residual trajectory matches the single-device
        # one to the Euler matmul's shard-reassociation bound (recorded in
        # f32, so the comparison is at f32 resolution).
        np.testing.assert_allclose(telemetry_trajectory(sol.telemetry),
                                   telemetry_trajectory(ref.telemetry),
                                   rtol=1e-5)

    def test_sharded_off_returns_none(self):
        from aiyagari_tpu.parallel.mesh import make_mesh
        from aiyagari_tpu.solvers.egm_sharded import solve_aiyagari_egm_sharded

        n = 8_192
        m = aiyagari_preset(grid_size=n)
        w = float(wage_from_r(R, m.config.technology.alpha,
                              m.config.technology.delta))
        C0 = initial_consumption_guess(m.a_grid, m.s, R, w)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_sharded(
            mesh, C0, m.a_grid, m.s, m.P, R, w, m.amin,
            sigma=m.preferences.sigma, beta=m.preferences.beta,
            tol=1e-30, max_iter=2, grid_power=float(m.config.grid.power))
        assert sol.telemetry is None


class TestOuterLoopTelemetry:
    CFG = AiyagariConfig(grid=GridSpecConfig(n_points=50))

    def test_bisection_outer_and_inner_records(self):
        from aiyagari_tpu.dispatch import solve

        # tol=1e-3 like test_batched_ge: the coarse grid's inner-solve noise
        # puts a ~1e-3 floor under the reachable capital gap.
        res = solve(self.CFG, method="egm",
                    solver=SolverConfig(method="egm", telemetry=TELE),
                    aggregation="distribution",
                    equilibrium=EquilibriumConfig(max_iter=40, tol=1e-3))
        assert res.converged
        # Outer host record: one residual per bisection iteration.
        assert int(res.telemetry.count) == res.iterations
        gaps = telemetry_trajectory(res.telemetry)
        np.testing.assert_allclose(
            gaps, [abs(s - d) for s, d in zip(res.k_supply, res.k_demand)],
            rtol=1e-6)
        # Inner device records: household + distribution.
        assert int(res.solution.telemetry.count) > 0
        assert int(res.dist_telemetry.count) > 0
        # The health certificate assembles from all of them.
        h = res.health()
        assert h["converged"] and h["healthy"]
        assert "outer" in h and "inner" in h and "distribution" in h

    def test_batched_ge_records(self):
        from aiyagari_tpu.equilibrium.batched import solve_equilibrium_batched
        from aiyagari_tpu.models.aiyagari import AiyagariModel

        m = AiyagariModel.from_config(self.CFG, jnp.float64)
        res = solve_equilibrium_batched(
            m, solver=SolverConfig(method="egm", telemetry=TELE),
            eq=EquilibriumConfig(batch=4, max_iter=24, tol=1e-3),
            aggregation="distribution")
        assert res.converged
        assert int(res.telemetry.count) == res.iterations   # rounds
        # The best candidate's household + distribution recorders survive
        # the batch indexing (un-batched leaves on the returned solution).
        assert np.ndim(res.solution.telemetry.count) == 0
        assert int(res.solution.telemetry.count) > 0
        assert int(res.dist_telemetry.count) > 0

    def test_sweep_records_batched_per_scenario(self):
        from aiyagari_tpu.dispatch import sweep

        res = sweep(self.CFG, method="egm",
                    solver=SolverConfig(method="egm", telemetry=TELE),
                    equilibrium=EquilibriumConfig(max_iter=30, tol=1e-3),
                    beta=[0.95, 0.96])
        assert bool(np.all(res.converged))
        assert int(res.telemetry.count) == res.rounds
        # One distribution recorder per scenario ([S]-leading leaves).
        assert res.dist_telemetry.residuals.shape[0] == 2

    def test_transition_record_matches_history(self):
        from aiyagari_tpu.dispatch import solve_transition
        from aiyagari_tpu.config import MITShock, TransitionConfig

        res = solve_transition(
            self.CFG, MITShock(param="tfp", size=0.005, rho=0.5),
            transition=TransitionConfig(T=20, method="damped", max_iter=40,
                                        tol=1e-6))
        assert int(res.telemetry.count) == res.rounds
        np.testing.assert_allclose(telemetry_trajectory(res.telemetry),
                                   np.asarray(res.max_excess_history,
                                              np.float32))
        assert list(telemetry_stages(res.telemetry)) == [64] * res.rounds
        h = res.health()
        assert h["kind"] == "TransitionResult"
        assert "outer" in h


class TestTrace:
    def test_span_nesting_and_collection(self):
        from aiyagari_tpu.diagnostics.trace import collect_spans, span

        with collect_spans() as spans:
            with span("outer", round=1):
                with span("inner"):
                    pass
        assert len(spans) == 1
        rec = spans[0]
        assert rec["name"] == "outer" and rec["round"] == 1
        assert rec["seconds"] >= 0.0
        assert rec["children"][0]["name"] == "inner"

    def test_collector_exception_safe(self):
        from aiyagari_tpu.diagnostics.trace import collect_spans, span

        with pytest.raises(RuntimeError):
            with collect_spans():
                with span("doomed"):
                    raise RuntimeError("boom")
        # A later collection starts clean (no leaked stack/sink state).
        with collect_spans() as spans:
            with span("after"):
                pass
        assert [s["name"] for s in spans] == ["after"]

    def test_timed_records_compile_run_split(self):
        from aiyagari_tpu.diagnostics.trace import timed

        @jax.jit
        def f(x):
            return x * 2.0

        out, rec = timed("double", f, jnp.arange(8.0), reps=1)
        np.testing.assert_allclose(np.asarray(out), 2.0 * np.arange(8.0))
        assert rec["compile_and_first_run_s"] > 0
        assert rec["run_s"] >= 0 and rec["compile_s"] >= 0


class TestLedger:
    def test_events_roundtrip_with_array_scalars(self, tmp_path):
        from aiyagari_tpu.diagnostics.ledger import RunLedger, read_ledger

        path = tmp_path / "led.jsonl"
        led = RunLedger(path, meta={"who": "test"})
        led.event("custom", residual=jnp.float64(1.5e-6),
                  n=np.int64(3), name="x")
        led.verdict("loop", converged=True, iterations=7, distance=1e-9,
                    tol=1e-8)
        led.telemetry("inner", host_telemetry([1.0, 0.5]))
        events = read_ledger(path)
        assert [e["kind"] for e in events] == ["run_start", "custom",
                                               "verdict", "telemetry"]
        assert events[1]["residual"] == 1.5e-6 and events[1]["n"] == 3
        assert events[3]["summary"]["sweeps"] == 2
        # Shared run id, monotone seq.
        assert len({e["run_id"] for e in events}) == 1
        assert [e["seq"] for e in events] == [0, 1, 2, 3]

    def test_config_fingerprint_in_run_start(self, tmp_path):
        from aiyagari_tpu.diagnostics.ledger import RunLedger, read_ledger

        led = RunLedger(tmp_path / "l.jsonl", config=AiyagariConfig())
        ev = read_ledger(led.path)[0]
        assert ev["kind"] == "run_start"
        assert isinstance(ev["config_fingerprint"], str)

    def test_activate_emit_and_noop_when_inactive(self, tmp_path):
        from aiyagari_tpu.diagnostics.ledger import (
            RunLedger,
            activate,
            emit,
            read_ledger,
        )

        emit("degradation", event="nobody-listening")   # no-op, no crash
        led = RunLedger(tmp_path / "l.jsonl")
        with activate(led):
            emit("degradation", event="x", n=2)
        emit("degradation", event="after-scope")        # dropped again
        kinds = [e["kind"] for e in read_ledger(led.path)]
        assert kinds == ["run_start", "degradation"]

    def test_raising_solve_still_flushes_spans(self, tmp_path, monkeypatch):
        # A solve that RAISES mid-flight is exactly the run the ledger
        # exists to explain: its wall-clock span and an "error" event must
        # land in the JSONL before the exception propagates
        # (dispatch._observe flushes in a finally).
        from aiyagari_tpu.diagnostics.ledger import read_ledger
        from aiyagari_tpu.dispatch import solve
        from aiyagari_tpu.equilibrium import bisection

        def boom(*a, **k):
            raise RuntimeError("device fell over mid-solve")

        monkeypatch.setattr(bisection, "solve_equilibrium_distribution", boom)
        path = tmp_path / "failed_run.jsonl"
        with pytest.raises(RuntimeError, match="fell over"):
            solve(AiyagariConfig(grid=GridSpecConfig(n_points=40)),
                  method="egm", solver=SolverConfig(method="egm"),
                  aggregation="distribution", ledger=path)
        events = read_ledger(path)
        kinds = [e["kind"] for e in events]
        assert "span" in kinds
        err = next(e for e in events if e["kind"] == "error")
        assert err["error_type"] == "RuntimeError"
        assert err["context"] == "aiyagari_ge"

    def test_torn_final_line_is_loud(self, tmp_path):
        from aiyagari_tpu.diagnostics.ledger import RunLedger, read_ledger

        led = RunLedger(tmp_path / "l.jsonl")
        with open(led.path, "a") as f:
            f.write('{"kind": "torn')
        with pytest.raises(json.JSONDecodeError):
            read_ledger(led.path)

    def test_dispatch_solve_writes_full_record(self, tmp_path):
        from aiyagari_tpu.diagnostics.ledger import read_ledger
        from aiyagari_tpu.dispatch import solve

        path = tmp_path / "run.jsonl"
        solve(AiyagariConfig(grid=GridSpecConfig(n_points=50)),
              method="egm",
              solver=SolverConfig(method="egm", telemetry=TELE),
              aggregation="distribution",
              equilibrium=EquilibriumConfig(max_iter=40, tol=1e-3),
              ledger=path)
        events = read_ledger(path)
        kinds = [e["kind"] for e in events]
        assert kinds[0] == "run_start"
        assert "span" in kinds and "verdict" in kinds
        tele_ctx = {e["context"] for e in events if e["kind"] == "telemetry"}
        assert {"outer", "household", "distribution"} <= tele_ctx
        v = next(e for e in events if e["kind"] == "verdict")
        assert v["converged"] is True
        sp = next(e for e in events if e["kind"] == "span")
        assert sp["name"] == "aiyagari_ge" and sp["seconds"] > 0


class TestMetrics:
    def test_counter_gauge_histogram_and_exporters(self):
        from aiyagari_tpu.diagnostics import metrics

        reg = metrics.MetricsRegistry()
        c = reg.counter("solves_total", method="egm")
        c.inc()
        c.inc(2)
        assert c.value == 3
        with pytest.raises(ValueError):
            c.inc(-1)
        reg.gauge("capacity").set(4)
        h = reg.histogram("residual", buckets=(1e-6, 1e-3, 1.0))
        for v in (1e-7, 5e-4, 0.5, 2.0):
            h.observe(v)
        txt = reg.render_prometheus()
        assert 'solves_total{method="egm"} 3' in txt
        assert "# TYPE capacity gauge" in txt
        assert 'residual_bucket{le="+Inf"} 4' in txt
        assert "residual_count 4" in txt
        js = reg.render_json()
        assert js["counters"][0]["value"] == 3
        assert js["histograms"][0]["counts"] == [1, 2, 3]
        reg.reset()
        assert reg.render_json()["counters"] == []

    def test_prometheus_label_values_are_escaped(self):
        # Exposition-format escaping (ISSUE 14 satellite): a route name or
        # path landing in a label may carry backslashes, quotes, or
        # newlines — pre-fix these produced unparseable exposition. Per
        # the text format v0.0.4, label values escape backslash, quote,
        # and newline (backslash FIRST, or the other two re-escape).
        from aiyagari_tpu.diagnostics import metrics

        reg = metrics.MetricsRegistry()
        reg.counter("routes_total", route='say "hi"').inc()
        reg.counter("routes_total", route="C:\\tmp\\ledger").inc(2)
        reg.counter("routes_total", route="two\nlines").inc(3)
        txt = reg.render_prometheus()
        assert 'routes_total{route="say \\"hi\\""} 1' in txt
        assert 'routes_total{route="C:\\\\tmp\\\\ledger"} 2' in txt
        assert 'routes_total{route="two\\nlines"} 3' in txt
        # The exposition stays line-parseable: no raw newline or naked
        # quote escapes a label value onto its own line.
        for line in txt.splitlines():
            assert line.count('"') % 2 == 0, line

    def test_module_registry_reset_between_tests(self):
        # The autouse conftest fixture resets the process registry: a
        # counter from a previous test must not be visible here.
        from aiyagari_tpu.diagnostics import metrics

        assert metrics.counter("aiyagari_pushforward_fallback_total",
                               route="transpose").value == 0

    def test_dump_json(self, tmp_path):
        from aiyagari_tpu.diagnostics import metrics

        metrics.counter("x").inc()
        metrics.dump_json(tmp_path / "m.json")
        data = json.loads((tmp_path / "m.json").read_text())
        assert data["counters"][0]["name"] == "x"


class TestHealth:
    def test_trajectory_diagnosis_shapes(self):
        from aiyagari_tpu.diagnostics.health import diagnose_trajectory

        geo = diagnose_trajectory([1.0 * 0.5 ** k for k in range(20)])
        assert not geo["stalled"] and not geo["oscillating"]
        assert 0.4 < geo["decay_rate"] < 0.6
        stall = diagnose_trajectory([1.0] * 4 + [0.1] * 30)
        assert stall["stalled"]
        osc = diagnose_trajectory([1.0, 2.0] * 16)
        assert osc["oscillating"]

    def test_nonconverged_solve_flags(self):
        from aiyagari_tpu.dispatch import solve

        res = solve(AiyagariConfig(grid=GridSpecConfig(n_points=50)),
                    method="egm",
                    solver=SolverConfig(method="egm", telemetry=TELE),
                    aggregation="distribution",
                    equilibrium=EquilibriumConfig(max_iter=3),
                    on_nonconvergence="ignore")
        h = res.health()
        assert not h["healthy"]
        assert "not-converged" in h["flags"]

    def test_euler_percentiles_with_model(self):
        from aiyagari_tpu.dispatch import solve
        from aiyagari_tpu.models.aiyagari import AiyagariModel

        cfg = AiyagariConfig(grid=GridSpecConfig(n_points=50))
        res = solve(cfg, method="egm", solver=SolverConfig(method="egm"),
                    aggregation="distribution",
                    equilibrium=EquilibriumConfig(max_iter=40, tol=1e-3))
        h = res.health(model=AiyagariModel.from_config(cfg, jnp.float64))
        e = h["euler_errors"]
        assert e["p50_log10"] < e["p99_log10"] <= e["max_log10"]
        assert h["distribution"]["mass_defect"] < 1e-10
        assert h["policy"]["monotone"]

    def test_render_report_and_cli(self, tmp_path, capsys):
        from aiyagari_tpu.diagnostics.health import render_report, report_main
        from aiyagari_tpu.diagnostics.ledger import RunLedger

        report = {"kind": "X", "converged": True, "healthy": True,
                  "flags": []}
        assert "OK" in render_report(report)
        led = RunLedger(tmp_path / "l.jsonl")
        led.verdict("loop", converged=False, iterations=9, distance=1e-2,
                    tol=1e-5)
        led.event("degradation", event="pushforward_fallback",
                  route="banded", n=3)
        led.telemetry("inner", host_telemetry([1.0, 0.5]))
        led.metric({"metric": "wall", "value": 1.25, "unit": "s"})
        rc = report_main([str(led.path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "NOT CONVERGED" in out
        assert "degradation: pushforward_fallback" in out
        assert "telemetry inner" in out
        assert "metric wall" in out
        rc = report_main([str(led.path), "--json"])
        assert rc == 0
        events = json.loads(capsys.readouterr().out)
        assert events[0]["kind"] == "run_start"


class TestLoggingCoercion:
    """Satellite: sinks must collapse numpy/jnp 0-d scalars (sol.distance
    is a 0-d device array; np.max(...) a numpy scalar) — the console sink
    printed opaque reprs and json.dumps raised TypeError before."""

    def test_console_formats_array_scalars(self, capsys):
        from aiyagari_tpu.diagnostics.logging import ConsoleSink

        ConsoleSink(prefix="[t] ")({
            "distance": jnp.float64(1.25e-6),
            "it": np.int64(12),
            "np_s": np.float64(0.5),
        })
        out = capsys.readouterr().out
        assert "distance=1.25e-06" in out       # %.6g float formatting
        assert "it=12" in out
        assert "Array" not in out and "dtype" not in out

    def test_jsonl_serializes_array_scalars_and_1d(self, tmp_path):
        from aiyagari_tpu.diagnostics.logging import JSONLSink

        sink = JSONLSink(tmp_path / "r.jsonl")
        sink({"distance": jnp.float32(2.0), "hist": np.arange(3),
              "nested": {"d": jnp.float64(1e-8), "l": [np.int32(1), 2]}})
        rec = json.loads((tmp_path / "r.jsonl").read_text())
        assert rec["distance"] == 2.0
        assert rec["hist"] == [0, 1, 2]
        assert rec["nested"] == {"d": 1e-8, "l": [1, 2]}

    def test_coerce_record_passthrough(self):
        from aiyagari_tpu.diagnostics.logging import coerce_record

        rec = coerce_record({"s": "x", "b": True, "none": None,
                             "f": jnp.float64(1.0)})
        assert rec == {"s": "x", "b": True, "none": None, "f": 1.0}
        assert isinstance(rec["f"], float)


class TestProgressIsolation:
    """Satellite: the module-global _SINKS list must be resettable and
    exception-safe — a leaked subscription feeds every later solve."""

    def test_reset_drops_all_sinks(self):
        from aiyagari_tpu.diagnostics import progress

        progress.subscribe(lambda r: None)
        progress.subscribe(lambda r: None)
        progress.reset()
        assert progress._SINKS == []

    def test_capture_progress_unsubscribes_when_barrier_raises(self,
                                                               monkeypatch):
        from aiyagari_tpu.diagnostics import progress

        def boom():
            raise RuntimeError("dead device")

        monkeypatch.setattr(jax, "effects_barrier", boom)
        with pytest.raises(RuntimeError, match="dead device"):
            with progress.capture_progress(lambda r: None):
                pass
        # The subscription did NOT leak past the failed barrier.
        assert progress._SINKS == []


class TestConvergencePolicies:
    """Satellite: enforce_convergence end-to-end — policy='raise' raises
    from the real outer loops with the loop's final telemetry attached."""

    CFG = AiyagariConfig(grid=GridSpecConfig(n_points=50))

    def test_transition_newton_raise_carries_telemetry(self):
        from aiyagari_tpu.config import MITShock, TransitionConfig
        from aiyagari_tpu.diagnostics.errors import ConvergenceError
        from aiyagari_tpu.dispatch import solve_transition

        with pytest.raises(ConvergenceError) as ei:
            solve_transition(
                self.CFG, MITShock(param="tfp", size=0.01, rho=0.8),
                transition=TransitionConfig(T=20, method="newton",
                                            max_iter=1, tol=1e-12),
                on_nonconvergence="raise")
        err = ei.value
        assert err.context == "MIT-shock transition path"
        assert err.iterations == 1
        assert isinstance(err.telemetry, SolveTelemetry)
        # The attached flight record IS the loop's trajectory: one round,
        # final residual == the error's distance.
        traj = telemetry_trajectory(err.telemetry)
        assert len(traj) == 1
        np.testing.assert_allclose(traj[-1], err.distance, rtol=1e-6)

    def test_batched_ge_raise_carries_telemetry(self):
        from aiyagari_tpu.diagnostics.errors import ConvergenceError
        from aiyagari_tpu.dispatch import solve

        with pytest.raises(ConvergenceError) as ei:
            solve(self.CFG, method="egm",
                  solver=SolverConfig(method="egm"),
                  aggregation="distribution",
                  equilibrium=EquilibriumConfig(batch=4, max_iter=2,
                                                tol=1e-12),
                  on_nonconvergence="raise")
        err = ei.value
        assert isinstance(err.telemetry, SolveTelemetry)
        assert int(err.telemetry.count) == 2        # the two rounds ran
        np.testing.assert_allclose(telemetry_trajectory(err.telemetry)[-1],
                                   err.distance, rtol=1e-6)

    def test_warn_and_ignore_still_policy_free(self):
        import warnings

        from aiyagari_tpu.diagnostics.errors import (
            ConvergenceWarning,
            enforce_convergence,
        )

        with warnings.catch_warnings():
            warnings.simplefilter("error")
            enforce_convergence(True, "warn", "x", iterations=1,
                                distance=0.0, tol=1.0)
            enforce_convergence(False, "ignore", "x", iterations=1,
                                distance=2.0, tol=1.0)
        with pytest.warns(ConvergenceWarning):
            enforce_convergence(False, "warn", "x", iterations=1,
                                distance=2.0, tol=1.0)
        with pytest.raises(ValueError, match="policy"):
            enforce_convergence(True, "explode", "x", iterations=1,
                                distance=0.0, tol=1.0)
