"""Transition-dynamics subsystem (transition/): MIT-shock perfect-foresight
paths.

The correctness anchors, in dependency order: the fake-news sequence-space
Jacobian must BE the derivative of the path map (finite differences); the
flat path at the stationary equilibrium must stay flat (the two stationary
anchors and the dated EGM operator agree); Newton and damped updates must
find the SAME equilibrium path (two different iterations, one fixed point);
and the lockstep scenario sweep must reproduce the one-at-a-time solves
exactly (vmap is a batching transform, not a different algorithm).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import aiyagari_tpu as at
from aiyagari_tpu.models.aiyagari import AiyagariModel
from aiyagari_tpu.transition.mit import (
    shock_paths,
    stationary_anchor,
    transition_jacobian,
)
from aiyagari_tpu.transition.path import transition_path
from aiyagari_tpu.utils.firm import wage_from_r

GRID = 64
T = 40

CFG = at.AiyagariConfig(grid=at.GridSpecConfig(n_points=GRID))
SHOCK = at.MITShock(param="tfp", size=0.01, rho=0.8)


@pytest.fixture(scope="module")
def model():
    return AiyagariModel.from_config(CFG, jnp.float64)


@pytest.fixture(scope="module")
def ss(model):
    return stationary_anchor(model)


@pytest.fixture(scope="module")
def jac(model, ss):
    return transition_jacobian(model, ss, T)


def _flat_path_eval(model, ss, horizon):
    """Evaluate the path program at constant stationary prices."""
    prefs = model.preferences
    tech = model.config.technology
    r = float(ss.r)
    w = float(wage_from_r(r, tech.alpha, tech.delta))
    dt = model.dtype
    return transition_path(
        ss.solution.policy_c, ss.mu, model.a_grid, model.s, model.P,
        jnp.full(horizon + 1, r, dt), jnp.full(horizon, w, dt),
        jnp.full(horizon, prefs.beta, dt), jnp.full(horizon + 1, prefs.sigma, dt),
        jnp.full(horizon, model.amin, dt))


class TestFlatPathIdentity:
    def test_capital_path_constant_at_stationary_equilibrium(self, model, ss):
        """An economy at its stationary equilibrium stays there: backward
        sweep from C_ss at flat ss prices reproduces C_ss, forward push of
        mu_ss reproduces K_ss, period after period."""
        out = _flat_path_eval(model, ss, T)
        K_ts = np.asarray(out["K_ts"])
        K_ss = float(np.sum(np.asarray(ss.mu) * np.asarray(model.a_grid)))
        np.testing.assert_allclose(K_ts, K_ss, rtol=1e-6)
        # The dated policies collapse to the stationary one (the transition
        # EGM step reduces to the stationary step at flat prices).
        dC = np.max(np.abs(np.asarray(out["C_ts"])
                           - np.asarray(ss.solution.policy_c)))
        assert dC < 1e-6

    def test_zero_size_shock_converges_immediately(self, model, ss):
        res = at.solve_transition(
            CFG, at.MITShock(param="tfp", size=0.0, rho=0.5), ss=ss,
            transition=at.TransitionConfig(T=T, tol=1e-6, method="damped",
                                           max_iter=5))
        # At most one corrective round: the initial flat path's residual is
        # the stationary anchor's own discretization-level gap.
        assert res.converged and res.rounds <= 2
        np.testing.assert_allclose(res.K_ts, res.K_ss, rtol=1e-5)


class TestJacobian:
    def test_fake_news_matches_finite_differences(self, model, ss):
        """The fake-news J_A[t, s] = dA_t/dr_s against central differences
        of the actual path map (w riding the firm FOC in both, as in the
        solver's round loop) — the one backward jvp + one forward pass must
        BE the derivative, column by column."""
        from aiyagari_tpu.transition.jacobian import fake_news_jacobian

        prefs = model.preferences
        tech = model.config.technology
        Tj = 16
        r_ssv = float(ss.r)
        w_ss = float(wage_from_r(r_ssv, tech.alpha, tech.delta))
        w_slope = -tech.alpha / (1 - tech.alpha) * w_ss / (r_ssv + tech.delta)
        J_A = fake_news_jacobian(
            ss.solution.policy_c, ss.solution.policy_k, ss.mu,
            model.a_grid, model.s, model.P, r_ss=r_ssv, w_ss=w_ss,
            w_slope=w_slope, sigma=prefs.sigma, beta=prefs.beta,
            amin=model.amin, T=Tj)

        def A_of(r_path):
            dt = model.dtype
            w = wage_from_r(np.asarray(r_path), tech.alpha, tech.delta)
            out = transition_path(
                ss.solution.policy_c, ss.mu, model.a_grid, model.s, model.P,
                jnp.asarray(np.concatenate([r_path, [r_ssv]]), dt),
                jnp.asarray(w, dt), jnp.full(Tj, prefs.beta, dt),
                jnp.full(Tj + 1, prefs.sigma, dt),
                jnp.full(Tj, model.amin, dt))
            return np.asarray(out["A_ts"], np.float64)

        eps = 1e-6
        base = np.full(Tj, r_ssv)
        for s_col in (0, 5, Tj - 1):
            hi = base.copy(); hi[s_col] += eps
            lo = base.copy(); lo[s_col] -= eps
            fd = (A_of(hi) - A_of(lo)) / (2 * eps)
            np.testing.assert_allclose(
                J_A[:, s_col], fd, atol=5e-4 * max(1.0, np.abs(fd).max()),
                rtol=5e-4,
                err_msg=f"fake-news column {s_col} disagrees with FD")


class TestNewtonDampedParity:
    def test_same_equilibrium_path(self, model, ss, jac):
        tc = at.TransitionConfig(T=T, tol=1e-8, method="newton", max_iter=20)
        rn = at.solve_transition(CFG, SHOCK, transition=tc, ss=ss,
                                 jacobian=jac)
        rd = at.solve_transition(
            CFG, SHOCK, ss=ss,
            transition=at.TransitionConfig(T=T, tol=1e-8, method="damped",
                                           max_iter=300, damping=0.5))
        assert rn.converged and rd.converged
        # Same residual root, two iterations: paths agree far below tol.
        np.testing.assert_allclose(rn.r_path, rd.r_path, atol=1e-8)
        np.testing.assert_allclose(rn.K_ts, rd.K_ts, atol=1e-7)
        # The Newton rounds are what the sequence-space Jacobian buys.
        assert rn.rounds < rd.rounds
        # Per-round max excess demand is reported and decreasing.
        assert len(rn.max_excess_history) == rn.rounds
        assert rn.max_excess_history[-1] < 1e-8

    def test_expansionary_tfp_economics(self, model, ss, jac):
        tc = at.TransitionConfig(T=T, tol=1e-8, method="newton", max_iter=20)
        res = at.solve_transition(CFG, SHOCK, transition=tc, ss=ss,
                                  jacobian=jac)
        # A positive TFP shock raises the impact return and builds capital
        # above the stationary stock before decaying back to it.
        assert res.r_path[0] > res.r_ss
        assert np.max(res.K_ts) > res.K_ss * (1 + 1e-5)
        np.testing.assert_allclose(res.K_ts[-1], res.K_ss, rtol=2e-3)
        np.testing.assert_allclose(res.r_path[-1], res.r_ss, atol=1e-4)


class TestSweep:
    SHOCKS = [
        at.MITShock("tfp", 0.01, 0.8),
        at.MITShock("beta", 0.002, 0.7),
        at.MITShock("borrowing_limit", 0.05, 0.5),
    ]

    def test_sweep_matches_serial(self, model, ss, jac):
        """Lockstep sweep == one-at-a-time solves: the vmapped path program
        and the shared ss Jacobian change the batching, not the per-scenario
        iteration."""
        tc = at.TransitionConfig(T=T, tol=1e-8, method="newton", max_iter=20)
        sw = at.sweep_transitions(CFG, self.SHOCKS, transition=tc, ss=ss,
                                  jacobian=jac)
        assert bool(np.all(sw.converged))
        assert sw.transitions_per_sec > 0
        for i, sh in enumerate(self.SHOCKS):
            serial = at.solve_transition(CFG, sh, transition=tc, ss=ss,
                                         jacobian=jac)
            np.testing.assert_allclose(sw.r_paths[i], serial.r_path,
                                       atol=1e-10)
            np.testing.assert_allclose(sw.K_ts[i], serial.K_ts, atol=1e-9)

    def test_sweep_sharded_over_scenarios_mesh(self, model, ss, jac):
        """The "scenarios" mesh axis (parallel/mesh.shard_scenario_arrays)
        changes placement, not results: 4 scenarios over the 8-virtual-
        device test mesh reproduce the unsharded sweep."""
        shocks = self.SHOCKS + [at.MITShock("sigma", 0.05, 0.6)]
        tc = at.TransitionConfig(T=T, tol=1e-8, method="newton", max_iter=20)
        plain = at.sweep_transitions(CFG, shocks, transition=tc, ss=ss,
                                     jacobian=jac)
        sharded = at.sweep_transitions(
            CFG, shocks, transition=tc, ss=ss, jacobian=jac,
            backend=at.BackendConfig(mesh_axes=("scenarios",),
                                     mesh_shape=(4,)))
        np.testing.assert_allclose(sharded.r_paths, plain.r_paths,
                                   atol=1e-12)
        np.testing.assert_allclose(sharded.K_ts, plain.K_ts, atol=1e-12)

    def test_dispatch_param_grids_and_errors(self, ss, jac):
        tc = at.TransitionConfig(T=T, tol=1e-6, method="newton", max_iter=20)
        sw = at.sweep_transitions(CFG, params=["tfp"], sizes=[0.005, 0.01],
                                  rhos=[0.8], transition=tc, ss=ss,
                                  jacobian=jac)
        assert sw.scenarios == 2 and sw.r_paths.shape == (2, T)
        with pytest.raises(ValueError, match="not both"):
            at.sweep_transitions(CFG, self.SHOCKS, sizes=[0.01])
        with pytest.raises(ValueError, match="needs scenarios"):
            at.sweep_transitions(CFG)


class TestRoundCapConsistency:
    def test_capped_result_is_self_consistent(self, model, ss):
        """A max_iter-capped result must pair the RETURNED r_path with the
        K_ts/excess measured AT it (review pin): no trailing never-evaluated
        update."""
        import warnings

        from aiyagari_tpu.utils.firm import capital_demand

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            res = at.solve_transition(
                CFG, SHOCK, ss=ss,
                transition=at.TransitionConfig(T=T, tol=1e-14,
                                               method="damped", max_iter=3))
        assert not res.converged and res.rounds == 3
        tech = model.config.technology
        paths = shock_paths(model, SHOCK, T)
        D = res.K_ts[:T] - capital_demand(res.r_path, model.labor_raw,
                                          tech.alpha, tech.delta, paths["z"])
        np.testing.assert_allclose(D, res.excess, atol=1e-12)
        assert abs(np.max(np.abs(D)) - res.max_excess_history[-1]) < 1e-12


class TestValidation:
    def test_shock_paths_guards(self, model):
        with pytest.raises(ValueError, match="unknown shock param"):
            shock_paths(model, at.MITShock(param="delta"), 10)
        with pytest.raises(ValueError, match="transitory"):
            shock_paths(model, at.MITShock(param="tfp", rho=1.0), 10)
        with pytest.raises(ValueError, match="TIGHTEN"):
            shock_paths(model, at.MITShock(param="borrowing_limit",
                                           size=-0.1), 10)
        with pytest.raises(ValueError, match="beta shock"):
            shock_paths(model, at.MITShock(param="beta", size=0.1), 10)

    def test_solver_guards(self, ss):
        with pytest.raises(ValueError, match="newton.*or.*damped"):
            at.solve_transition(
                CFG, SHOCK, ss=ss,
                transition=at.TransitionConfig(method="broyden"))
        with pytest.raises(NotImplementedError, match="exogenous-labor"):
            at.solve_transition(
                at.AiyagariConfig(endogenous_labor=True), SHOCK)
        with pytest.raises(ValueError, match="method='egm'"):
            stationary_anchor(
                AiyagariModel.from_config(CFG, jnp.float64),
                solver=at.SolverConfig(method="vfi"))
