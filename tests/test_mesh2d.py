"""Pod-scale 2-D sharding (ISSUE 13): the partition-rule matcher, the
(scenarios x grid) mesh, the 2-D sweep entry points, and the rule-matched
checkpoint restore.

Everything runs on the 8-virtual-device CPU mesh (conftest forces it —
SURVEY.md §4.4: same shardings and collectives as a v5e-8 slice, no
hardware). The parity contract throughout: a 2-D-sharded sweep reproduces
the unsharded sweep to reassociation noise (<= 1e-12 in f64), healthy
lanes bitwise under quarantine.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu import MeshConfig, sweep, sweep_transitions
from aiyagari_tpu.config import (
    AiyagariConfig,
    EquilibriumConfig,
    FaultPlan,
    GridSpecConfig,
    MITShock,
    SentinelConfig,
    SolverConfig,
    TransitionConfig,
)
from aiyagari_tpu.parallel.mesh import (
    GRID_AXIS,
    PartitionSpec as P,
    SCENARIOS_AXIS,
    factor_axis_sizes,
    make_mesh,
    make_mesh_2d,
)
from aiyagari_tpu.parallel import rules as prules

CFG = AiyagariConfig(grid=GridSpecConfig(n_points=64))
EQ = EquilibriumConfig(max_iter=8, tol=1e-4)
BETAS = [0.94, 0.95, 0.955, 0.96]
SWEEP_KW = dict(method="egm", beta=BETAS, equilibrium=EQ)


@pytest.fixture(scope="module")
def serial_sweep():
    return sweep(CFG, **SWEEP_KW)


@pytest.fixture(scope="module")
def sweep_2x4():
    """One clean 2-D (2 x 4) sweep shared by the parity and quarantine
    pins (the compiled round program is the expensive part of each)."""
    return sweep(CFG, mesh=MeshConfig(scenarios=2, grid=4),
                 solver=SolverConfig(method="egm"), **SWEEP_KW)


class TestFactorization:
    def test_balanced_default(self):
        assert factor_axis_sizes(8, (None, None)) == (4, 2)
        assert factor_axis_sizes(12, (None, None)) == (4, 3)
        assert factor_axis_sizes(7, (None, None)) == (7, 1)
        assert factor_axis_sizes(12, (None, None, None)) == (3, 2, 2)

    def test_partial_request_derives_quotient(self):
        assert factor_axis_sizes(8, (2, None)) == (2, 4)
        assert factor_axis_sizes(8, (None, 8)) == (1, 8)

    def test_loud_when_devices_do_not_factor(self):
        with pytest.raises(ValueError, match="do not factor"):
            factor_axis_sizes(8, (3, None))
        with pytest.raises(ValueError, match="multiply to the device"):
            factor_axis_sizes(8, (2, 2))
        with pytest.raises(ValueError, match=">= 1"):
            factor_axis_sizes(8, (0, None))

    def test_make_mesh_multi_axis_default_no_longer_degenerates(self):
        # The old default sized only the first axis ([ndevices, 1, ...]):
        # a two-axis request silently became a 1-D mesh. Now it factors.
        m = make_mesh(("scenarios", "grid"))
        assert dict(m.shape) == {"scenarios": 4, "grid": 2}

    def test_make_mesh_2d(self):
        assert dict(make_mesh_2d().shape) == {"scenarios": 4, "grid": 2}
        assert dict(make_mesh_2d(scenarios=2).shape) == {
            "scenarios": 2, "grid": 4}
        assert dict(make_mesh_2d(grid=8).shape) == {
            "scenarios": 1, "grid": 8}
        with pytest.raises(ValueError, match="factor"):
            make_mesh_2d(scenarios=3)
        # Unlike the 1-D passthrough, the 2-D mesh must cover every device.
        with pytest.raises(ValueError, match="multiply to the device"):
            make_mesh_2d(scenarios=2, grid=2)


class TestRuleMatcher:
    def test_first_match_wins_precedence(self):
        rules = ((r"a_grid", (SCENARIOS_AXIS, GRID_AXIS)),
                 (r"a_.*", ()),          # later, broader — must not win
                 (r".*", (SCENARIOS_AXIS,)))
        spec = prules.match_rule(rules, "a_grid", np.zeros((4, 64)))
        assert spec == P(SCENARIOS_AXIS, GRID_AXIS)
        assert prules.match_rule(rules, "a_other",
                                 np.zeros((4, 64))) == P()
        assert prules.match_rule(rules, "beta",
                                 np.zeros((4,))) == P(SCENARIOS_AXIS)

    def test_scalars_never_partition(self):
        rules = ((r".*", (SCENARIOS_AXIS,)),)
        assert prules.match_rule(rules, "alpha", np.float64(0.36)) == P()
        assert prules.match_rule(rules, "one", np.zeros((1,))) == P()

    def test_unmatched_leaf_is_loud(self):
        with pytest.raises(ValueError, match="no partition rule matches"):
            prules.match_rule(((r"^a$", ()),), "b", np.zeros((3,)))
        with pytest.raises(ValueError, match="no partition rule matches"):
            prules.match_partition_rules((), {"x": np.zeros((3,))})

    def test_spec_longer_than_leaf_rank_is_loud(self):
        rules = ((r".*", (SCENARIOS_AXIS, None, GRID_AXIS)),)
        with pytest.raises(ValueError, match="more axes than leaf"):
            prules.match_rule(rules, "x", np.zeros((4,)))

    def test_axes_absent_from_mesh_drop(self):
        # A 2-D rule set serves a 1-D mesh unchanged: the missing axis
        # replicates instead of erroring.
        mesh = make_mesh((SCENARIOS_AXIS,))
        rules = ((r".*", (SCENARIOS_AXIS, GRID_AXIS)),)
        spec = prules.match_rule(rules, "a_grid", np.zeros((4, 64)),
                                 mesh=mesh)
        assert spec == P(SCENARIOS_AXIS, None)

    def test_shard_and_gather_round_trip(self):
        mesh = make_mesh_2d(scenarios=2, grid=4)
        tree = {"a_grid": jnp.arange(4 * 64, dtype=jnp.float64
                                     ).reshape(4, 64),
                "warm": jnp.arange(4 * 3 * 64, dtype=jnp.float64
                                   ).reshape(4, 3, 64),
                "beta": jnp.asarray(BETAS),
                "alpha": 0.36}
        placed = prules.shard_by_rules(mesh, tree,
                                       prules.SCENARIO_BATCH_RULES)
        assert placed["a_grid"].sharding.spec == P(SCENARIOS_AXIS,
                                                   GRID_AXIS)
        assert placed["warm"].sharding.spec == P(SCENARIOS_AXIS, None,
                                                 GRID_AXIS)
        gathered = prules.gather_tree(mesh, placed)
        for k in ("a_grid", "warm", "beta"):
            assert gathered[k].sharding.is_fully_replicated
            np.testing.assert_array_equal(np.asarray(gathered[k]),
                                          np.asarray(tree[k]))

    def test_make_shard_and_gather_fns_mirror_specs(self):
        mesh = make_mesh_2d(scenarios=2, grid=4)
        tree = {"a_grid": jnp.zeros((4, 64)), "beta": jnp.zeros((4,))}
        specs = prules.match_partition_rules(
            prules.SCENARIO_BATCH_RULES, tree, mesh=mesh)
        shard_fns, gather_fns = prules.make_shard_and_gather_fns(mesh,
                                                                 specs)
        x = shard_fns["a_grid"](tree["a_grid"])
        assert x.sharding.spec == P(SCENARIOS_AXIS, GRID_AXIS)
        back = gather_fns["a_grid"](x)
        assert back.sharding.is_fully_replicated


class TestSweep2D:
    @pytest.mark.parametrize("axes", [(2, 4), (4, 2)])
    def test_sweep_matches_serial_on_2d_mesh(self, serial_sweep, sweep_2x4,
                                             axes):
        res = (sweep_2x4 if axes == (2, 4) else
               sweep(CFG, mesh=MeshConfig(scenarios=axes[0], grid=axes[1]),
                     solver=SolverConfig(method="egm"), **SWEEP_KW))
        # The bracket path is host arithmetic on device gaps: identical
        # sign decisions -> identical rates; capital differs only by the
        # sharded matmul/cumsum reassociation.
        np.testing.assert_array_equal(res.r, serial_sweep.r)
        assert np.max(np.abs(np.asarray(res.capital)
                             - np.asarray(serial_sweep.capital))) <= 1e-12
        assert res.rounds == serial_sweep.rounds
        assert list(res.verdicts) == list(serial_sweep.verdicts)

    def test_quarantined_lane_bitwise_parity_on_2d_mesh(self, sweep_2x4):
        clean = sweep_2x4
        poisoned = sweep(
            CFG, mesh=MeshConfig(scenarios=2, grid=4),
            solver=SolverConfig(method="egm",
                                faults=FaultPlan(poison_scenario=1)),
            **SWEEP_KW)
        quar = np.asarray(poisoned.quarantined)
        assert quar.tolist() == [False, True, False, False]
        assert poisoned.verdicts[1] == "nan"
        others = [0, 2, 3]
        # Healthy lanes BITWISE equal to the clean 2-D sweep — the ISSUE
        # 10 quarantine contract, unchanged by the 2-D placement.
        np.testing.assert_array_equal(np.asarray(poisoned.r)[others],
                                      np.asarray(clean.r)[others])
        np.testing.assert_array_equal(
            np.asarray(poisoned.capital)[others],
            np.asarray(clean.capital)[others])

    def test_validation_is_loud(self):
        with pytest.raises(TypeError, match="MeshConfig"):
            sweep(CFG, mesh="2x4", **SWEEP_KW)
        with pytest.raises(ValueError, match="positive int"):
            MeshConfig(scenarios=0)
        # 3 scenarios over a 2-wide scenario axis.
        with pytest.raises(ValueError, match="divide evenly"):
            sweep(CFG, mesh=MeshConfig(scenarios=2, grid=4), method="egm",
                  beta=BETAS[:3], equilibrium=EQ)
        # Grid of 60 points over an 8-wide grid axis.
        with pytest.raises(ValueError, match="divide evenly"):
            sweep(dataclasses.replace(
                CFG, grid=GridSpecConfig(n_points=60)),
                mesh=MeshConfig(scenarios=1, grid=8), **SWEEP_KW)
        with pytest.raises(ValueError, match="not both"):
            from aiyagari_tpu.config import BackendConfig

            sweep(CFG, backend=BackendConfig(mesh_axes=("scenarios",)),
                  mesh=MeshConfig(), **SWEEP_KW)

    def test_mesh_topology_event_and_gauges(self, tmp_path):
        from aiyagari_tpu.diagnostics import metrics
        from aiyagari_tpu.diagnostics.ledger import read_ledger

        led = tmp_path / "ledger.jsonl"
        sweep(CFG, method="egm", beta=BETAS, ledger=str(led),
              mesh=MeshConfig(scenarios=2, grid=4),
              equilibrium=EquilibriumConfig(max_iter=2, tol=1e-4))
        events = [e for e in read_ledger(led)
                  if e["kind"] == "mesh_topology"]
        assert len(events) == 1
        assert events[0]["axes"] == {"scenarios": 2, "grid": 4}
        assert events[0]["devices"] == 8
        assert metrics.gauge("aiyagari_mesh_axis_size",
                             axis="scenarios").value == 2
        assert metrics.gauge("aiyagari_mesh_axis_size",
                             axis="grid").value == 4

    def test_no_mesh_no_event(self, tmp_path):
        from aiyagari_tpu.diagnostics.ledger import read_ledger

        led = tmp_path / "ledger.jsonl"
        # Two un-converged rounds suffice: the event (or its absence) is
        # written at mesh activation, before any round runs.
        sweep(CFG, method="egm", beta=BETAS, ledger=str(led),
              equilibrium=EquilibriumConfig(max_iter=2, tol=1e-4))
        assert not [e for e in read_ledger(led)
                    if e["kind"] == "mesh_topology"]


class TestTransitionSweep2D:
    def test_transition_sweep_matches_serial_on_2d_mesh(self):
        shocks = [MITShock("tfp", 0.01, 0.8), MITShock("beta", 0.002, 0.8)]
        tc = TransitionConfig(T=12, tol=1e-7, method="newton", max_iter=10)
        ref = sweep_transitions(CFG, shocks, transition=tc)
        res = sweep_transitions(CFG, shocks, transition=tc,
                                mesh=MeshConfig(scenarios=2, grid=4),
                                ss=ref.ss, jacobian=ref.jacobian)
        assert res.rounds == ref.rounds
        assert np.max(np.abs(np.asarray(res.r_paths)
                             - np.asarray(ref.r_paths))) <= 1e-12
        assert np.max(np.abs(np.asarray(res.K_ts)
                             - np.asarray(ref.K_ts))) <= 1e-12


class TestCheckpointRuleRestore:
    def test_restore_across_topology_change_via_rules(self, tmp_path):
        from aiyagari_tpu.io_utils.checkpoint import (
            load_checkpoint,
            restore_array,
            save_checkpoint,
        )

        mesh_24 = make_mesh_2d(scenarios=2, grid=4)
        tree = {"a_grid": jnp.arange(4 * 64, dtype=jnp.float64
                                     ).reshape(4, 64),
                "warm": jnp.arange(4 * 3 * 64, dtype=jnp.float64
                                   ).reshape(4, 3, 64)}
        placed = prules.shard_by_rules(mesh_24, tree,
                                       prules.SCENARIO_BATCH_RULES)
        path = tmp_path / "mesh.ckpt.npz"
        save_checkpoint(path, scalars={"round": 3}, arrays=placed)
        scalars, arrays = load_checkpoint(path)
        assert scalars["round"] == 3
        # Restore onto the TRANSPOSED topology: the rule matcher derives
        # the 4x2 placement from the same rule set — no hand-built
        # NamedSharding at the call site.
        mesh_42 = make_mesh_2d(scenarios=4, grid=2)
        for name in ("a_grid", "warm"):
            out = restore_array(scalars, arrays, name, mesh=mesh_42,
                                rules=prules.SCENARIO_BATCH_RULES)
            np.testing.assert_array_equal(np.asarray(out),
                                          np.asarray(tree[name]))
            assert out.sharding.mesh.shape[SCENARIOS_AXIS] == 4
            assert out.sharding.spec[0] == SCENARIOS_AXIS

    def test_rule_restore_validation(self, tmp_path):
        from aiyagari_tpu.io_utils.checkpoint import (
            load_checkpoint,
            restore_array,
            save_checkpoint,
        )

        path = tmp_path / "v.ckpt.npz"
        save_checkpoint(path, scalars={},
                        arrays={"a_grid": np.zeros((4, 64))})
        scalars, arrays = load_checkpoint(path)
        mesh = make_mesh_2d(scenarios=2, grid=4)
        with pytest.raises(ValueError, match="not both"):
            restore_array(scalars, arrays, "a_grid",
                          sharding=jax.sharding.NamedSharding(  # noqa: AIYA201 — test-only probe
                              mesh, P()),
                          mesh=mesh, rules=prules.SCENARIO_BATCH_RULES)
        with pytest.raises(ValueError, match="BOTH"):
            restore_array(scalars, arrays, "a_grid", mesh=mesh)
        # Absent names still return None through the rule path.
        assert restore_array(scalars, arrays, "missing", mesh=mesh,
                             rules=prules.SCENARIO_BATCH_RULES) is None


class TestCollectiveCost:
    def test_prices_both_axes(self):
        from aiyagari_tpu.diagnostics.roofline import mesh2d_collective_cost

        c = mesh2d_collective_cost(8, 7, 1024, scenarios=2, grid=4,
                                   itemsize=8, sweeps=100, rounds=5,
                                   devices_per_host=4)
        assert c["ici_bytes"] > 0 and c["hosts"] == 2
        assert c["dcn_bytes"] > 0
        assert c["ici_seconds"] > 0 and c["dcn_seconds"] > 0
        # Single-host layouts pay no DCN at all.
        one = mesh2d_collective_cost(8, 7, 1024, scenarios=1, grid=8,
                                     itemsize=8, sweeps=100, rounds=5)
        assert one["hosts"] == 1 and one["dcn_bytes"] == 0.0
        # A wider grid axis moves more ring bytes per lane sweep.
        narrow = mesh2d_collective_cost(8, 7, 1024, scenarios=4, grid=2)
        wide = mesh2d_collective_cost(8, 7, 1024, scenarios=1, grid=8)
        assert (wide["grid_bytes_per_lane_sweep"]
                > narrow["grid_bytes_per_lane_sweep"])
        # Scenarios-only (grid=1) on one host is the zero-communication
        # design point and must price at EXACTLY zero — a size-1 axis's
        # gathers/reduces move no bytes (the lower-bound contract).
        zero = mesh2d_collective_cost(8, 7, 1024, scenarios=8, grid=1)
        assert zero["ici_bytes"] == 0.0 and zero["dcn_bytes"] == 0.0
        assert zero["hosts"] == 1
        with pytest.raises(ValueError, match=">= 1"):
            mesh2d_collective_cost(8, 7, 1024, scenarios=0, grid=8)


class TestSweep2DProgram:
    """The 2-D shard_map EGM sweep program (solvers/egm_sharded.
    solve_aiyagari_egm_sweep_2d): scenario lanes vmapped over the
    ring-sharded grid solve."""

    def test_registry_audits_2d_program(self):
        from aiyagari_tpu.analysis.jaxpr_audit import audit_program
        from aiyagari_tpu.analysis.registry import registered_programs

        specs = [p for p in registered_programs() if "2d" in p.name]
        assert {p.name for p in specs} == {"egm/sweep_2d",
                                           "egm/sweep_2d_sentinel"}
        for spec in specs:
            assert audit_program(spec) == []

    def test_validation_is_loud(self):
        from aiyagari_tpu.solvers.egm_sharded import (
            solve_aiyagari_egm_sweep_2d,
        )

        mesh_1d = make_mesh((GRID_AXIS,))
        C0 = jnp.zeros((2, 3, 64))
        with pytest.raises(ValueError, match="carrying both"):
            solve_aiyagari_egm_sweep_2d(
                mesh_1d, C0, jnp.zeros(64), jnp.zeros(3),
                jnp.eye(3), jnp.zeros(2), jnp.ones(2), jnp.zeros(2),
                sigma=5.0, beta=0.96, tol=1e-6, max_iter=10,
                grid_power=2.0)
        mesh = make_mesh_2d(scenarios=4, grid=2)
        with pytest.raises(ValueError, match="divide evenly"):
            solve_aiyagari_egm_sweep_2d(
                mesh, C0, jnp.zeros(64), jnp.zeros(3),
                jnp.eye(3), jnp.zeros(2), jnp.ones(2), jnp.zeros(2),
                sigma=5.0, beta=0.96, tol=1e-6, max_iter=10,
                grid_power=2.0)

    @pytest.mark.slow
    def test_lane_parity_and_per_lane_sentinel(self):
        """Each lane of the 2-D program reproduces the single-device
        solver's TRAJECTORY over a fixed sweep budget (<= 1e-12, the 1-D
        ring program's band x 30 sweeps), and a NaN lane's sentinel
        verdict is PER LANE — its neighbor solves bitwise identically to
        the clean run. Slow and sweep-bounded: every collective on the
        8-virtual-device host pays a thread-rendezvous (~0.3s/sweep
        measured), so a run-to-convergence test would take minutes;
        tier-1 covers the same artifact structurally through the
        registry audit above and the dispatch-level 2-D sweep parity."""
        from aiyagari_tpu.models.aiyagari import aiyagari_preset
        from aiyagari_tpu.solvers.egm import (
            initial_consumption_guess,
            solve_aiyagari_egm,
        )
        from aiyagari_tpu.solvers.egm_sharded import (
            solve_aiyagari_egm_sweep_2d,
        )

        m = aiyagari_preset(grid_size=4096, dtype=jnp.float64)
        mesh = make_mesh_2d(scenarios=2, grid=4)
        rs = np.array([0.02, 0.03])
        ws = np.array([1.2, 1.15])
        C0 = jnp.stack([initial_consumption_guess(m.a_grid, m.s, rs[i],
                                                  ws[i])
                        for i in range(2)])
        kw = dict(sigma=5.0, beta=0.96, tol=1e-6, max_iter=30,
                  grid_power=2.0)
        sol = solve_aiyagari_egm_sweep_2d(
            mesh, C0, m.a_grid, m.s, m.P, rs, ws, np.zeros(2),
            capacity=1.0, sentinel=SentinelConfig(), **kw)
        assert not np.asarray(sol.escaped).any()
        assert np.asarray(sol.iterations).tolist() == [30, 30]
        for i in range(2):
            ref = solve_aiyagari_egm(C0[i], m.a_grid, m.s, m.P, rs[i],
                                     ws[i], 0.0, **kw)
            assert float(jnp.max(jnp.abs(sol.policy_c[i]
                                         - ref.policy_c))) <= 1e-12
        poisoned = solve_aiyagari_egm_sweep_2d(
            mesh, C0.at[0].set(jnp.nan), m.a_grid, m.s, m.P, rs, ws,
            np.zeros(2), capacity=1.0, sentinel=SentinelConfig(), **kw)
        verdicts = np.asarray(poisoned.sentinel.verdict)
        # Lane 0's sentinel fires "nan"; lane 1 never notices — and its
        # whole policy is BITWISE the clean run's (the per-lane freeze +
        # globally-synced trip count of _make_egm_local).
        assert verdicts[0] != 0 and verdicts[1] == 0
        assert int(np.asarray(poisoned.iterations)[0]) < 30
        np.testing.assert_array_equal(np.asarray(poisoned.policy_c[1]),
                                      np.asarray(sol.policy_c[1]))
