"""Utility-function and inequality-statistics unit tests (SURVEY.md §4.1)."""

import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.utils.stats import (
    gaussian_kde,
    gini,
    lorenz_curve,
    probability_histogram,
    quantile_shares,
)
from aiyagari_tpu.utils.utility import (
    crra_marginal,
    crra_marginal_inverse,
    crra_utility,
    labor_disutility,
    labor_foc_inverse,
    labor_marginal_disutility,
)


class TestUtility:
    def test_marginal_inverse_roundtrip(self, rng):
        c = rng.uniform(0.1, 10.0, 100)
        for sigma in (1.0, 2.0, 5.0):
            up = crra_marginal(jnp.array(c), sigma)
            np.testing.assert_allclose(crra_marginal_inverse(up, sigma), c, rtol=1e-12)

    def test_log_special_case(self):
        c = jnp.array([0.5, 1.0, 2.0])
        np.testing.assert_allclose(crra_utility(c, 1.0), jnp.log(c), atol=1e-12)

    def test_crra_limit_approaches_log(self):
        c = jnp.array([0.5, 1.5, 3.0])
        near = crra_utility(c, 1.0 + 1e-7)
        np.testing.assert_allclose(near, jnp.log(c), atol=1e-5)

    def test_labor_foc_roundtrip(self, rng):
        l = rng.uniform(0.05, 1.4, 50)
        for psi, eta in ((1.0, 2.0), (2.5, 0.7)):
            x = labor_marginal_disutility(jnp.array(l), psi, eta)
            np.testing.assert_allclose(labor_foc_inverse(x, psi, eta), l, rtol=1e-12)

    def test_labor_disutility_convex(self):
        l = jnp.linspace(0.01, 1.5, 100)
        d2 = jnp.diff(labor_disutility(l, 1.0, 2.0), 2)
        assert (d2 > 0).all()


class TestInequality:
    def test_gini_equal_distribution_zero(self):
        x = jnp.ones(10_000)
        assert abs(float(gini(x))) < 1e-3

    def test_gini_uniform_one_third(self, rng):
        # Uniform[0,1] has G = 1/3.
        x = jnp.array(rng.uniform(0, 1, 200_000))
        assert abs(float(gini(x)) - 1.0 / 3.0) < 5e-3

    def test_gini_exponential_half(self, rng):
        # Exponential has G = 1/2.
        x = jnp.array(rng.exponential(1.0, 200_000))
        assert abs(float(gini(x)) - 0.5) < 5e-3

    def test_lorenz_endpoints(self, rng):
        pop, cum = lorenz_curve(jnp.array(rng.uniform(0, 1, 1000)))
        assert abs(float(cum[-1]) - 1.0) < 1e-12
        assert abs(float(pop[-1]) - 1.0) < 1e-12
        assert (jnp.diff(cum) >= 0).all()

    def test_quintile_shares(self, rng):
        x = jnp.array(rng.uniform(0, 1, 50_000))
        shares = quantile_shares(x, 5)
        np.testing.assert_allclose(float(shares.sum()), 100.0, atol=1e-8)
        assert (jnp.diff(shares) > 0).all()  # increasing for any dispersion

    def test_histogram_probability(self, rng):
        edges, probs = probability_histogram(jnp.array(rng.normal(size=5000)), bins=30)
        np.testing.assert_allclose(float(probs.sum()), 1.0, atol=1e-10)
        assert edges.shape == (31,)

    def test_kde_integrates_to_one(self, rng):
        xi, f = gaussian_kde(jnp.array(rng.normal(size=3000)), n_points=200)
        mass = float(jnp.trapezoid(f, xi))
        assert abs(mass - 1.0) < 2e-2
