"""Tier-1 smoke for the benchmark harness: `bench.py --preset ci` (tiny-grid
CPU battery) must exit 0 with one well-formed JSON record per metric.

Why this exists (ISSUE 2 satellite): the round-5 bench round died mid-battery
with a 208 GB RESOURCE_EXHAUSTED inside bench_scale — a bench-only code path
no test exercised, so the regression was first seen in the round artifact.
The ci preset runs every previously-broken bench path (the multiscale +
windowed-inversion scale solve included) at ~MB scale, so a bench-breaking
change fails HERE, in tier-1, before a bench round does.
"""

import json
import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "bench.py")

# The ci battery's metric set (bench.py main): one record each, in order.
CI_METRICS = ("vfi", "scale", "ge", "ge_fused", "sweep", "transition",
              "transition_fused",
              "accel", "precision", "pushforward", "egm_fused", "telemetry",
              "resilience", "mesh2d", "attribution", "observatory",
              "serve", "amortized", "fleet", "calibration", "analysis")


def test_bench_ci_preset_exits_zero_with_full_battery(tmp_path):
    ledger_path = tmp_path / "bench_ledger.jsonl"
    # Snapshot the round-14 serve knee BEFORE the battery refreezes the
    # artifact in place — the keep-alive no-regress gate below needs the
    # committed value, not this run's own.
    with open(os.path.join(os.path.dirname(BENCH),
                           "BENCH_r14_serve.json")) as f:
        knee_before = json.load(f)["ramp"]["knee_rps"]
    out = subprocess.run(
        [sys.executable, BENCH, "--preset", "ci", "--ledger",
         str(ledger_path)],
        capture_output=True, text=True, timeout=700,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, (
        f"bench.py --preset ci exited {out.returncode}\n"
        f"stdout:\n{out.stdout[-2000:]}\nstderr:\n{out.stderr[-2000:]}")
    records = [json.loads(l) for l in out.stdout.splitlines()
               if l.startswith('{"metric"')]
    # The ci preset closes with the bench-history watchdog's own record
    # (ISSUE 14 satellite) — split it off the per-metric battery.
    hist = next(r for r in records if r["metric"] == "bench_history_check")
    records = [r for r in records if r["metric"] != "bench_history_check"]
    assert len(records) == len(CI_METRICS), (
        f"expected {len(CI_METRICS)} metric records, got {len(records)}:\n"
        + out.stdout[-2000:])
    for rec in records:
        # Tiny grids must never OOM-skip; every record carries a real value.
        assert "skipped" not in rec, f"ci metric skipped: {rec}"
        assert isinstance(rec.get("value"), (int, float)), rec
    # The ge_fused record carries the ISSUE 18 acceptance telemetry: the
    # one-program equilibrium. Three gates — the fused device loop beats
    # the host outer loop (<= 0.8x wall, interleaved minima, so the
    # ratio is drift-immune), both loops land on the SAME root to
    # round-off (they run identical bracket arithmetic; 1e-10 is the
    # acceptance band, the measurement is exact), and buffer donation
    # demonstrably happened — XLA's peak-memory proxy for the donated
    # build strictly below the undonated build of the identical program,
    # with the donated warm buffer deleted after the call.
    gf = records[-18]
    assert gf["metric"].startswith("aiyagari_ge_fused")
    assert gf["host_converged"] and gf["device_converged"], gf
    assert gf["batched_converged"], gf
    assert gf["wall_ratio_device_over_host"] <= 0.8, gf
    assert gf["r_agreement"] <= 1e-10, gf
    mem_d, mem_u = gf["memory_donated"], gf["memory_undonated"]
    assert mem_d["alias_bytes"] > 0, gf
    assert mem_d["peak_proxy_bytes"] < mem_u["peak_proxy_bytes"], gf
    assert gf["donated_input_deleted"] is True, gf
    # The structural win: ONE device program per equilibrium vs two
    # sequential programs (+ fetches) per host iteration; the vmapped
    # candidate round compresses the round count further.
    assert gf["device_programs_fused"] == 1
    assert gf["device_programs_host_loop"] == 2 * gf["host_iterations"]
    assert gf["batched_rounds"] < gf["device_rounds"], gf
    assert gf["modeled_solve"]["hbm_bytes"] > 0, gf
    # The frozen artifact the ci battery owns (ISSUE 18 acceptance).
    with open(os.path.join(os.path.dirname(BENCH),
                           "BENCH_r17_ge_fused.json")) as f:
        frozen_gf = json.load(f)
    assert frozen_gf["metric"].startswith("aiyagari_ge_fused")
    assert frozen_gf["wall_ratio_device_over_host"] <= 0.8
    assert frozen_gf["r_agreement"] <= 1e-10
    assert (frozen_gf["memory_donated"]["peak_proxy_bytes"]
            < frozen_gf["memory_undonated"]["peak_proxy_bytes"])
    assert frozen_gf["donated_input_deleted"] is True
    # The transition record carries the ISSUE 2 acceptance telemetry.
    tr = records[-16]
    assert tr["metric"].startswith("transition_newton")
    assert tr["newton_rounds"] >= 1 and tr["converged"]
    assert tr["sweep_transitions_per_sec"] > 0
    # The transition_fused record carries the ISSUE 19 acceptance
    # telemetry: the one-program MIT-shock solve. Same gate shape as
    # ge_fused above — the fused device Newton loop beats the host round
    # loop (<= 0.8x wall, interleaved minima; the win is LAUNCH-count
    # erasure, ~T*rounds dispatches collapsed to one), both loops land on
    # the same terminal rate to round-off (identical hoisted
    # Jacobian-inverse matmul on identical excess-demand curves; 1e-10 is
    # the acceptance band, the measurement is exact), and path-carry
    # donation demonstrably happened: XLA aliased real input bytes, the
    # donated build's peak-memory proxy sits strictly below the undonated
    # build of the identical program, and the donated r-path carry is
    # deleted after the call.
    tf = records[-15]
    assert tf["metric"].startswith("transition_fused")
    assert tf["host_converged"] and tf["device_converged"], tf
    assert tf["wall_ratio_device_over_host"] <= 0.8, tf
    assert tf["r_agreement"] <= 1e-10, tf
    tf_d, tf_u = tf["memory_donated"], tf["memory_undonated"]
    assert tf_d["alias_bytes"] > 0, tf
    assert tf_d["peak_proxy_bytes"] < tf_u["peak_proxy_bytes"], tf
    assert tf["donated_input_deleted"] is True, tf
    # The structural win: ONE device program per transition solve vs one
    # program (+ fetch) per Newton round on the host loop.
    assert tf["device_programs_fused"] == 1
    assert tf["device_programs_host_loop"] == tf["host_rounds"]
    assert tf["modeled_solve"]["hbm_bytes"] > 0, tf
    # The coalesced sweep rode the same fused program: every scenario
    # converged and the fused sweep's terminal rates agree with the host
    # sweep's to round-off.
    assert tf["sweep_converged"] == tf["sweep_scenarios"], tf
    assert tf["sweep_r_agreement"] <= 1e-10, tf
    assert tf["sweep_transitions_per_sec"] > 0, tf
    # The frozen artifact the ci battery owns (ISSUE 19 acceptance).
    with open(os.path.join(os.path.dirname(BENCH),
                           "BENCH_r18_transition_fused.json")) as f:
        frozen_tf = json.load(f)
    assert frozen_tf["metric"].startswith("transition_fused")
    assert frozen_tf["wall_ratio_device_over_host"] <= 0.8
    assert frozen_tf["r_agreement"] <= 1e-10
    assert (frozen_tf["memory_donated"]["peak_proxy_bytes"]
            < frozen_tf["memory_undonated"]["peak_proxy_bytes"])
    assert frozen_tf["donated_input_deleted"] is True
    # The accel record carries the ISSUE 3 acceptance telemetry: per-solve
    # iteration counts for the plain and accelerated routes, with
    # accelerated <= plain — an acceleration regression fails tier-1 here.
    ac = records[-14]
    assert ac["metric"].startswith("accel_fixed_point")
    assert ac["egm_sweeps_accel"] <= ac["egm_sweeps_plain"]
    assert ac["dist_sweeps_accel"] <= ac["dist_sweeps_plain"]
    # The headline acceptance ratios (>=2x EGM, >=3x distribution) hold
    # with margin even at the ci preset's tiny grid; gate slightly below
    # them so timing-independent sweep-count regressions still fail loudly
    # without flaking on a calibration wiggle.
    assert ac["egm_sweep_ratio"] >= 1.8, ac
    assert ac["dist_sweep_ratio"] >= 2.5, ac
    # The precision record carries the ISSUE 4 acceptance telemetry. The
    # structural (timing-free) claims first: the ladder actually laddered —
    # hot sweeps ran, STOPPED before the pure-f64 count, and a polish
    # certified the reference tolerance with machine-precision mass.
    pr = records[-13]
    assert pr["metric"].startswith("precision_ladder")
    assert pr["egm_sweeps_f32_stage"] > 0
    assert pr["egm_sweeps_f32_stage"] < pr["egm_sweeps_f64"]
    assert pr["egm_sweeps_f64_polish"] > 0
    assert pr["dist_sweeps_f32_stage"] > 0
    assert pr["dist_sweeps_f64_polish"] > 0
    assert pr["dist_mass_error_after_polish"] < 1e-12
    # CPU floor guard on ladder OVERHEAD: the laddered wall must stay close
    # to the pure-f64 wall even on a host where f32 sweeps buy nothing
    # (XLA:CPU's scatter/searchsorted price both dtypes alike) — a
    # regression that makes the ladder pay for its casts/extra stage fails
    # here before a bench round ships it. 1.25x, not the 1.1x the quiet-box
    # BENCH_r07 measurement supports: the ratio sits at 1.04-1.10 standalone
    # but this host's in-battery timing noise swings it past 1.1 (measured),
    # and a real cast/stage regression lands at 1.5x+.
    assert pr["value"] <= 1.25 * pr["baseline_seconds"], pr
    # The pushforward record carries the ISSUE 5 acceptance telemetry:
    # every DistributionBackend present in one valid JSON record, each
    # scatter-free route parity-pinned against the scatter reference, and
    # the no-regression floor — the best scatter-free route must be <=
    # 1.0x the scatter per-sweep wall on this CPU host even at ci sizes
    # (measured 2.9x at grid 200, 8.2x at grid 4000; interleaved minima,
    # so the gate has wide margin against host drift).
    pw = records[-12]
    assert pw["metric"].startswith("pushforward_sweep")
    assert set(pw["routes"]) == {"scatter", "transpose", "banded", "pallas"}
    for name, route in pw["routes"].items():
        assert route["wall_per_sweep_us"] > 0, (name, route)
        if name == "scatter":
            continue
        # The compiled scatter-free routes agree with scatter to machine
        # epsilon; the Pallas route runs INTERPRETED off-TPU, whose lottery
        # accumulation order puts its converged-mu agreement at ~1e-10
        # (measured 9.8e-11 at the ci grid, deterministic) — gate it at its
        # own band rather than the compiled routes' ulp band.
        bound = 1e-9 if name == "pallas" else 1e-12
        assert route["parity_vs_scatter"] < bound, (name, route)
    # The Pallas interpreter is a correctness vehicle off-TPU, never the
    # perf claim; the best-route fields must reflect that.
    assert pw["routes"]["pallas"]["interpreted"] is True
    assert pw["best_scatter_free_route"] in ("transpose", "banded")
    assert pw["vs_baseline"] >= 1.0, pw
    assert pw["value"] <= pw["baseline_seconds"], pw
    # The egm_fused record carries the ISSUE 11 acceptance telemetry: both
    # egm_kernel routes present and timed, the fused route's operator
    # parity against the XLA chain inside the f64 band, and the
    # roofline-priced bytes for BOTH routes with the fused route's model
    # strictly below the chain's (the one-read-one-write claim, priced).
    # The host WALL is advisory only: off-TPU the fused route runs the
    # Pallas interpreter — a correctness vehicle — so no speedup is gated
    # here; the speedup claim is TPU-side (docs/USAGE.md).
    ef = records[-11]
    assert ef["metric"].startswith("egm_fused_sweep")
    assert set(ef["routes"]) == {"xla", "pallas_fused"}
    for name, route in ef["routes"].items():
        assert route["wall_per_sweep_us"] > 0, (name, route)
        assert route["model_hbm_bytes_per_sweep"] > 0, (name, route)
        assert route["achieved_gbs"] > 0, (name, route)
    assert ef["routes"]["pallas_fused"]["interpreted"] is True
    assert ef["parity_vs_xla"] < 1e-9, ef
    assert (ef["routes"]["pallas_fused"]["model_hbm_bytes_per_sweep"]
            < ef["routes"]["xla"]["model_hbm_bytes_per_sweep"]), ef
    assert ef["vs_baseline"] > 0 and ef["value"] > 0, ef
    # The telemetry record carries the ISSUE 6 acceptance telemetry: the
    # recorder compiled OUT must cost nothing. The <= 2% off-overhead claim
    # is gated STRUCTURALLY: `off_jaxpr_noop` pins that the telemetry-off
    # solve traces to a program with no ring buffer at all (i.e. the exact
    # pre-telemetry executable — overhead identically zero, stronger than
    # any timing bound), and `off_bit_identical` that its iterates match
    # the recorder-on solve bitwise. The record's `off_overhead_pct` is the
    # measured same-executable timing delta — this host's scheduler/steal
    # noise floor swings 0.3-3% run to run at second-scale walls (measured
    # back to back), so it documents the box, not the code, and is not
    # gated; the quiet-box measurement is frozen in BENCH_r09_telemetry
    # .json. The wall-ratio sanity bound below catches a REAL recorder
    # regression (an accidental host callback or sync inflates the
    # recorder-on walls many-fold, far beyond timing noise).
    tm = records[-10]
    assert tm["metric"].startswith("telemetry_recorder")
    assert tm["off_bit_identical"] is True, tm
    assert tm["off_jaxpr_noop"] is True, tm
    assert tm["off_overhead_pct"] >= 0.0, tm
    for loop in ("egm", "dist"):
        lo = tm["loops"][loop]
        assert lo["wall_on_s"] > 0 and lo["wall_off_s"] > 0, tm
        assert lo["wall_on_s"] <= 1.5 * lo["wall_off_s"], tm
    # The resilience record carries the ISSUE 10 acceptance gates: the
    # injected-fault battery recovered 100% (every injection point either
    # converged through the rescue ladder or via its compiled-in fallback
    # — zero silent NaN results), the sentinel's stall watch actually
    # saved sweeps on the unreachable-tolerance battery, the poisoned
    # sweep quarantined EXACTLY its one poisoned lane with every other
    # lane parity-equal to the clean sweep, and the quarantine machinery
    # costs <= 1.1x a clean sweep (host-side masks only).
    rs = records[-9]
    assert rs["metric"] == "resilience_fault_battery"
    assert rs["value"] == 1.0, rs
    assert rs["recovered"] == rs["points"]
    for name, point in rs["injection_points"].items():
        assert point["recovered"] is True, (name, point)
    # The multi-stage escalation point actually escalated (forced stage
    # failures walked the ladder past the forced stages).
    assert rs["injection_points"]["rescue_stage_failure"][
        "failed_attempts"] >= 3
    st = rs["sentinel_stall"]
    assert st["verdict"] == "stall"
    assert st["sentinel_sweeps"] < st["plain_sweeps"] == st["max_iter"]
    assert st["sweeps_saved"] > 0
    q = rs["quarantine"]
    assert q["contract_ok"] is True, q
    assert q["quarantined_lanes"] == 1
    assert q["poisoned_lane_verdict"] == "rescued"
    assert q["unpoisoned_parity"] <= 1e-12, q
    assert rs["quarantine_overhead"] <= 1.1, rs
    # The mesh2d record carries the ISSUE 13 acceptance telemetry: the
    # fixed-work sweep ran on all three sharded topologies over the
    # 8-virtual-device mesh (1-D scenarios-only, 1-D grid-only, 2-D) plus
    # the unsharded reference, every sharded topology's capital within
    # reassociation noise (<= 1e-12) of the unsharded sweep with
    # IDENTICAL rates, and the roofline-priced cross-axis collective
    # bytes present per topology. Walls are recorded, not gated: on this
    # one-core host the virtual devices share the core, so topology walls
    # measure partitioning overhead at equal total work (the frozen
    # BENCH_r12_mesh2d.json documents the measured ordering); the
    # chips-scale claim rides the priced-bytes column.
    m2 = records[-8]
    assert m2["metric"] == "mesh2d_sweep"
    assert m2["devices"] >= 8, m2
    assert set(m2["topologies"]) == {"unsharded", "scenarios8", "grid8",
                                     "2x4"}
    for name, topo in m2["topologies"].items():
        assert topo["wall_s"] > 0, (name, topo)
        assert topo["rounds"] == m2["rounds"], (name, topo)
        if name == "unsharded":
            continue
        assert topo["parity_vs_unsharded"] <= 1e-12, (name, topo)
        assert topo["r_equal"] is True, (name, topo)
        coll = topo["collectives_per_sweep"]
        if name == "scenarios8":
            # The design point, priced as a number: a scenarios-only mesh
            # moves NOTHING per sweep (lanes never communicate) and pays
            # no DCN on one host.
            assert coll["ici_bytes"] == 0 and coll["dcn_bytes"] == 0, coll
        else:
            # Any grid-sharded topology pays real per-sweep ICI.
            assert coll["ici_bytes"] > 0, (name, coll)
    # The 2-D composition is priced on BOTH links: grid collectives over
    # ICI plus the scenario axis's per-round sync over DCN (2 hosts at
    # the default one-host-per-grid-group layout).
    coll_2d = m2["topologies"]["2x4"]["collectives_per_sweep"]
    assert coll_2d["hosts"] > 1 and coll_2d["dcn_bytes"] > 0, coll_2d
    assert m2["topologies"]["2x4"]["axes"] == {"scenarios": 2, "grid": 4}
    assert m2["best_1d"] in ("scenarios8", "grid8")
    # The frozen artifact the ci battery owns (ISSUE 13 acceptance).
    bench_dir0 = os.path.dirname(BENCH)
    with open(os.path.join(bench_dir0, "BENCH_r12_mesh2d.json")) as f:
        frozen_m2 = json.load(f)
    assert frozen_m2["metric"] == "mesh2d_sweep"
    assert set(frozen_m2["topologies"]) == set(m2["topologies"])
    # The attribution record carries the ISSUE 12 acceptance telemetry:
    # modeled-vs-compiled attribution for >= 10 registry programs, the
    # compiled/modeled byte ratio inside its checked band for the audited
    # EGM + push-forward programs (the fusion-regression oracle — the
    # shipped tree measures 1.7-8.5x at the registry shapes; a chain that
    # stops fusing and materializes its broadcasts lands at 10-100x), a
    # measured probe with per-candidate walls for every contested knob,
    # and the frozen BENCH_r11_attribution.json artifact.
    at = records[-7]
    assert at["metric"] == "route_attribution"
    assert at["value"] >= 10, at
    assert not at["flagged"], at
    gated = ("egm/sweep", "egm/sweep_f32_stage", "egm/sweep_labor",
             "distribution/step_scatter", "distribution/step_transpose",
             "distribution/step_banded", "distribution/stationary")
    for name in gated:
        prog = at["programs"][name]
        assert prog["modeled_bytes"] and prog["compiled_bytes"], (name, prog)
        assert 0.5 <= prog["byte_ratio"] <= 20.0, (name, prog)
        assert prog["flagged"] is False, (name, prog)
    # The interpreted fused programs are joined but never flagged off-TPU
    # (their compiled artifact is the Pallas interpreter, not the Mosaic
    # kernel).
    assert at["programs"]["egm/sweep_fused"]["flagged"] is False
    assert set(at["knobs"]) >= {"pushforward", "egm_kernel", "bucket_index"}
    for knob, rec in at["knobs"].items():
        assert rec["choice"], (knob, rec)
        assert all(w > 0 for w in rec["walls_us"].values()), (knob, rec)
    # The push-forward and searchsorted probes race real alternatives.
    assert set(at["knobs"]["pushforward"]["walls_us"]) >= {
        "scatter", "transpose", "banded"}
    assert set(at["knobs"]["bucket_index"]["walls_us"]) == {"scan", "sort"}
    # The frozen artifact the ci battery owns (ISSUE 12 acceptance).
    bench_dir = os.path.dirname(BENCH)
    with open(os.path.join(bench_dir, "BENCH_r11_attribution.json")) as f:
        frozen = json.load(f)
    assert frozen["metric"] == "route_attribution"
    assert len(frozen["programs"]) >= 10
    assert len(frozen["knobs"]) >= 3
    # The observatory record carries the ISSUE 14 acceptance telemetry:
    # the whole pod toolchain exercised on the 8-virtual-device mesh.
    # Skew probes timed a fenced rendezvous on BOTH mesh axes with a
    # priced reconciliation row each; arming ledger heartbeats changed NO
    # compiled program (jaxpr-identical, bitwise iterates); the simulated
    # two-host shard pair merged back into one run-id-joined, ordered
    # stream with its torn tail tolerated; and the watch table rendered a
    # row per scenario.
    ob = records[-6]
    assert ob["metric"] == "pod_observatory"
    assert ob["devices"] >= 8, ob
    assert set(ob["skew"]["axes"]) == {"scenarios", "grid"}
    for axis, skew in ob["skew"]["axes"].items():
        assert skew["rendezvous_seconds"] > 0, (axis, skew)
        assert skew["verdict"] in ("balanced", "straggler"), (axis, skew)
        rc = skew["reconciliation"]
        assert rc["link"] == ("dcn" if axis == "scenarios" else "ici")
        assert rc["priced_seconds"] > 0, (axis, rc)
    hb = ob["heartbeat"]
    assert hb["off_jaxpr_identical"] is True, hb
    assert hb["off_bit_identical"] is True, hb
    assert hb["events"] > 0 and hb["per_scenario"] is True, hb
    mg = ob["merge"]
    assert mg["shards"] == 2 and mg["run_joined"] is True, mg
    assert mg["ordered"] is True and mg["torn_tolerated"] is True, mg
    assert mg["events_merged"] == mg["events_written"], mg
    assert ob["watch"]["rows"] >= ob["scenarios"], ob
    assert {"heartbeat", "host_skew", "mesh_topology"} <= \
        set(ob["sweep_event_kinds"]), ob
    # The frozen artifact the ci battery owns (ISSUE 14 acceptance).
    with open(os.path.join(bench_dir, "BENCH_r13_observatory.json")) as f:
        frozen_ob = json.load(f)
    assert frozen_ob["metric"] == "pod_observatory"
    assert set(frozen_ob["skew"]["axes"]) == {"scenarios", "grid"}
    # The bench-history watchdog ran against the frozen BENCH_r*.json
    # trajectory and found NOTHING: zero structural regressions is part
    # of the ci contract (ISSUE 14 acceptance) — a blown parity band, a
    # shrunken attribution table, a heartbeat pin gone false, or a
    # formerly-working metric now skipping all land here.
    assert hist["value"] == 0, hist
    assert hist["structural_findings"] == 0, hist
    assert hist["findings"] == [], hist
    # The battery's in-ci artifacts have frozen counterparts to check.
    assert {"mesh2d_sweep", "route_attribution", "pod_observatory"} <= \
        set(hist["matched_metrics"]), hist
    # The serve record carries the ISSUE 15 acceptance telemetry: the
    # persistent solve service's measured regimes. Warm-cache requests (a
    # secant polish from a quantized-cache neighbor) must cost <= 0.5x a
    # cold solve at p50; exact hits replay with no solve; coalesced
    # transition requests — one lockstep sweep where ONE stationary
    # anchor + ONE fake-news Jacobian serve the whole batch — must beat
    # one-at-a-time serial throughput (measured well above the 2x
    # acceptance bar; gated at the satellite's >= serial with the 2x
    # claim frozen in BENCH_r14_serve.json). Every request leaves a
    # ledger trail and the serve gauges export.
    sv = records[-5]
    assert sv["metric"] == "serve_load"
    reg = sv["regimes"]
    assert reg["warm"]["p50_s"] <= 0.5 * reg["cold"]["p50_s"], sv
    assert sv["warm_vs_cold_p50"] <= 0.5, sv
    assert sv["coalesced_vs_serial"] >= 1.0, sv
    assert reg["coalesced"]["rps"] >= reg["serial_transition"]["rps"], sv
    # Exact hits replay from the cache — orders of magnitude under a cold
    # solve (no solve at all); every cold/warm/hit steady request
    # converged at this calibration.
    assert sv["hit_p50_s"] < 0.1 * reg["cold"]["p50_s"], sv
    for name in ("cold", "warm", "hit"):
        assert reg[name]["statuses"] == {"converged": reg[name]["requests"]}
    assert reg["cold"]["cache_outcomes"] == {"cold": reg["cold"]["requests"]}
    assert reg["warm"]["cache_outcomes"] == {"warm": reg["warm"]["requests"]}
    assert reg["hit"]["cache_outcomes"] == {"hit": reg["hit"]["requests"]}
    # The coalesced batch really coalesced (one batch of n_trans).
    assert reg["coalesced"]["batch_sizes"] == [sv["transition_requests"]]
    assert reg["serial_transition"]["batch_sizes"] == [1]
    # The flight record: every request wrote serve_request + cache_hit
    # events, batches wrote coalesce, and dispatch's route decisions +
    # spans landed on the same ledger (the "every served request leaves a
    # ledger trail" acceptance).
    ev = sv["ledger_events"]
    assert ev["serve_request"] > 0 and ev["cache_hit"] > 0, sv
    assert ev["coalesce"] > 0 and ev["route_decision"] > 0, sv
    assert ev["span"] > 0 and ev["verdict"] > 0, sv
    # The Prometheus scrape surface: queue depth, batch size, cache hit
    # rate all exported (the acceptance's named series).
    assert all(sv["prometheus_gauges"].values()), sv
    assert sv["cache"]["hits"] > 0 and sv["cache"]["warm"] > 0, sv
    # The frozen artifact the ci battery owns (ISSUE 15 acceptance).
    with open(os.path.join(bench_dir, "BENCH_r14_serve.json")) as f:
        frozen_sv = json.load(f)
    assert frozen_sv["metric"] == "serve_load"
    assert frozen_sv["warm_vs_cold_p50"] <= 0.5
    # 1.5x, not the 2.0x a standalone run supports (measured 2.6x solo):
    # the ci battery refreezes this record mid-suite, and with 18 metrics
    # of heap/compile churn ahead of it the in-battery measurement swings
    # to ~1.9x on a loaded host (measured) — a real coalescing regression
    # lands at ~1.0x, far under this band. The in-run gates above keep
    # coalesced >= serial unconditionally.
    assert frozen_sv["coalesced_vs_serial"] >= 1.5
    # The serve layer's latency-SLO gate (ISSUE 16 satellite): the
    # offered-rps ramp found a knee — the service met the SLO at least
    # at its lowest offered rate on exact-hit traffic.
    assert sv["slo_gate"]["met"] is True, sv
    assert sv["ramp"]["knee_rps"] is not None, sv
    assert sv["ramp"]["steps"][0]["slo_met"] is True, sv
    # Keep-alive knee no-regress (ISSUE 18 satellite): with the pipelined
    # worker and persistent HTTP connections in the serve path, the ramp's
    # SLO knee must not fall below the committed round-14 value (the
    # pre-battery snapshot — the battery refreezes the artifact in place).
    assert sv["ramp"]["knee_rps"] >= knee_before, (sv["ramp"], knee_before)
    # The amortized record carries the ISSUE 16 acceptance telemetry: the
    # predictor ladder (hit -> blend -> surrogate -> anchor/anchor_warm)
    # drives the mixed-workload cold-solve fraction under 0.5; the
    # surrogate-warmed and anchor-warmed requests cost <= 0.6x their cold
    # baselines at p50; and the deliberately-poisoned guesses degraded to
    # cold solves whose answers matched a fresh cold service BITWISE
    # (zero wrong-answer degradations — the correctness band).
    am = records[-4]
    assert am["metric"] == "serve_amortized"
    assert am["cold_fraction"] < 0.5, am
    assert am["value"] == am["cold_fraction"], am
    ws = am["warm_sources"]
    assert sum(ws.values()) == am["requests"], am
    assert ws.get("hit", 0) >= 3, am
    assert ws.get("blend", 0) + ws.get("neighbor", 0) >= 3, am
    assert ws.get("surrogate", 0) >= 1, am
    assert ws.get("anchor_warm", 0) >= 1, am
    assert am["surrogate_vs_cold_p50"] is not None, am
    assert am["surrogate_vs_cold_p50"] <= 0.6, am
    assert am["anchor_warm_vs_cold_p50"] is not None, am
    assert am["anchor_warm_vs_cold_p50"] <= 0.6, am
    # Both forced poisonings actually exercised the degrade-to-cold band,
    # and no degraded answer differed from the cold answer.
    assert am["forced_degradations"]["steady"] is True, am
    assert am["forced_degradations"]["transition"] is True, am
    assert am["degradations"] >= 2, am
    assert am["wrong_answer_degradations"] == 0, am
    # The surrogate actually trained from the serve stream (fit events on
    # the ledger) and the new scrape series exported.
    assert am["surrogate"]["heads"] >= 1, am
    ev_am = am["ledger_events"]
    assert ev_am["surrogate_fit"] > 0, am
    assert ev_am["degradation"] >= 2, am
    assert ev_am["serve_request"] == am["requests"], am
    assert all(am["prometheus_gauges"].values()), am
    # The frozen artifact the ci battery owns (ISSUE 16 acceptance).
    with open(os.path.join(bench_dir, "BENCH_r15_amortized.json")) as f:
        frozen_am = json.load(f)
    assert frozen_am["metric"] == "serve_amortized"
    assert frozen_am["cold_fraction"] < 0.5
    assert frozen_am["wrong_answer_degradations"] == 0
    assert frozen_am["surrogate_vs_cold_p50"] <= 0.6
    assert frozen_am["anchor_warm_vs_cold_p50"] <= 0.6
    # The fleet record carries the ISSUE 20 acceptance telemetry: the
    # solve fabric. Four gates — AOT-restored programs start in <= 0.5x
    # their fresh compile wall (restore is a deserialize, not a retrace);
    # the 2-worker fleet's aggregate hit throughput is >= 1.6x one worker
    # (per-worker rates measured sequentially and summed on this
    # single-core host — aggregate fleet capacity); a fresh service fed
    # by a shared L2 directory pays a strictly lower cold fraction than
    # an L2-less one, with every L2 find surfacing as "warm" (never
    # "hit") so payloads re-enter the polish ladder; and a poisoned L2
    # document (valid stamp, garbage payload) degrades to a cold re-solve
    # whose answer is BITWISE the clean cold answer — zero wrong-answer
    # degradations, the tier's correctness band.
    fl = records[-3]
    assert fl["metric"] == "fleet"
    assert fl["gates"]["aot_restore_le_half_fresh"] is True, fl
    assert fl["aot_walls"]["restored_count"] >= 1, fl
    assert fl["aot_walls"]["worst_restore_vs_fresh"] <= 0.5, fl
    assert fl["gates"]["aggregate_ge_1p6x_single"] is True, fl
    assert fl["throughput"]["aggregate_vs_single"] >= 1.6, fl
    assert fl["gates"]["l2_cold_fraction_below"] is True, fl
    l2 = fl["l2_cold_fraction"]
    assert l2["cold_fraction_on"] < l2["cold_fraction_off"], fl
    assert l2["hits_never_from_l2"] is True, fl
    assert fl["gates"]["poisoned_l2_degrades_bitwise"] is True, fl
    ps = fl["poisoned_l2"]
    assert ps["poisoned_files"] >= 1, fl
    assert ps["degraded"] is True, fl
    assert ps["bitwise_equal"] is True, fl
    assert ps["wrong_answer_degradations"] == 0, fl
    # The frozen artifact the ci battery owns (ISSUE 20 acceptance).
    with open(os.path.join(bench_dir, "BENCH_r19_fleet.json")) as f:
        frozen_fl = json.load(f)
    assert frozen_fl["metric"] == "fleet"
    assert all(frozen_fl["gates"].values()), frozen_fl["gates"]
    assert frozen_fl["poisoned_l2"]["wrong_answer_degradations"] == 0
    # The calibration record carries the ISSUE 17 acceptance telemetry:
    # the differentiable solve stack recovered ALL FOUR planted deep
    # parameters (beta, sigma, rho, sigma_e) within 1e-3 by gradient
    # (measured ~1e-11 — the fit lands at the BFGS polish's quadratic
    # floor), and the IFT adjoint chain's gradient agrees with central
    # finite differences per z coordinate (measured ~7e-6 at the bisection
    # primal's resolution; gated at 1e-4 — an adjoint regression lands
    # orders of magnitude above that, FD noise never does).
    cal = records[-2]
    assert cal["metric"] == "calibration_recovery"
    assert cal["status"] == "converged" and cal["converged"] is True, cal
    assert cal["value"] == cal["recovery_max_abs_err"], cal
    assert cal["recovery_max_abs_err"] < 1e-3, cal
    for name in ("beta", "sigma", "rho", "sigma_e"):
        assert cal["recovery_abs_err"][name] < 1e-3, cal
    assert cal["grad_fd_max_rel_err"] < 1e-4, cal
    assert cal["steps"] >= 1 and cal["grad_evals"] > cal["steps"], cal
    assert cal["wall_per_gradient_seconds"] > 0, cal
    assert cal["lanes"] == 2 and len(cal["params"]) == 4, cal
    # The frozen artifact the ci battery owns (ISSUE 17 acceptance).
    with open(os.path.join(bench_dir, "BENCH_r16_calibration.json")) as f:
        frozen_cal = json.load(f)
    assert frozen_cal["metric"] == "calibration_recovery"
    assert frozen_cal["recovery_max_abs_err"] < 1e-3
    assert frozen_cal["grad_fd_max_rel_err"] < 1e-4
    # The analysis record carries the ISSUE 9 acceptance gate: the static
    # analyzer ran over the kernel zoo + source tree and found NOTHING —
    # a scatter regression, a precision leak, a host sync in a loop, a
    # direct jax.sharding import, or a broken telemetry no-op all land
    # HERE as a nonzero finding count, with the offending rule named in
    # rule_counts.
    an = records[-1]
    assert an["metric"] == "static_analysis_findings"
    assert an["value"] == 0, an
    assert all(v == 0 for v in an["rule_counts"].values()), an
    assert an["programs_audited"] >= 13
    assert an["files_linted"] > 50
    # Every metric record also landed in the run ledger, and the ledger
    # JSONL round-trips (read_ledger parses every line back).
    from aiyagari_tpu.diagnostics.ledger import read_ledger

    events = read_ledger(ledger_path)
    assert events[0]["kind"] == "run_start"
    metric_events = [e for e in events if e["kind"] == "metric"]
    # Every battery record plus the closing bench_history_check record.
    assert len(metric_events) == len(CI_METRICS) + 1
    assert [e["metric"] for e in metric_events] == \
        [r["metric"] for r in records] + ["bench_history_check"]
    # A clean battery writes no bench_regression events.
    assert sum(e["kind"] == "bench_regression" for e in events) == 0
    # run_analysis also emitted its own `analysis` event (per-rule counts)
    # on the active ledger — the ISSUE 9 observability satellite.
    analysis_events = [e for e in events if e["kind"] == "analysis"]
    assert len(analysis_events) == 1
    assert analysis_events[0]["findings"] == 0
    assert set(analysis_events[0]["rules"]) >= {"no-scatter",
                                                "mesh-shim-discipline",
                                                "route-resolution-discipline"}
    # The route observatory's events landed on the same ledger: one
    # `attribution` event per compiled registry program, a `tuning_probe`
    # per contested knob, and `route_decision` events from the
    # dispatch-based metrics (sweep/transition run under the active
    # ledger) — the ISSUE 12 observability satellite.
    assert sum(e["kind"] == "attribution" for e in events) >= 10
    assert sum(e["kind"] == "tuning_probe" for e in events) >= 3
    route_events = [e for e in events if e["kind"] == "route_decision"]
    assert route_events, events
    for ev in route_events:
        assert ev["knob"] and ev["choice"], ev
        assert ev["source"] in ("measured", "prior", "default"), ev
    # One shared run id stamps every event of this run.
    assert len({e["run_id"] for e in events}) == 1
