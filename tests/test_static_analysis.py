"""Tier-1 gates for the static-analysis layer (ISSUE 9).

Two contracts:

1. The SHIPPED tree is clean: `run_analysis()` over the whole kernel zoo
   and source tree reports zero active findings — and specifically zero
   mesh-shim findings even counting suppressed ones (the rule ships with
   no baseline and no noqa).
2. Every rule actually FIRES: each adversarial fixture
   (tests/analysis_fixtures/) trips exactly its own rule and nothing else
   — an over-matching rule implementation (false-positive cross-fire)
   breaks here, not in a future PR's audit.
"""

import importlib.util
import json
from pathlib import Path

import jax
import jax.numpy as jnp

from aiyagari_tpu.analysis import (
    RULES,
    load_baseline,
    run_analysis,
    write_baseline,
)
from aiyagari_tpu.analysis.jaxpr_audit import audit_program
from aiyagari_tpu.analysis.lint import lint_file
from aiyagari_tpu.analysis.registry import (
    TELEMETRY_SENTINEL_CAPACITY,
    ProgramSpec,
    registered_programs,
)

FIXTURES = Path(__file__).parent / "analysis_fixtures"


def _load_fixtures():
    spec = importlib.util.spec_from_file_location(
        "analysis_jaxpr_fixtures", FIXTURES / "jaxpr_fixtures.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


fx = _load_fixtures()


def _f64(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.float64)


def _f32(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _i32(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.int32)


def _spec(name, fn, args, **kw):
    return ProgramSpec(name=name, family="fixture",
                       build_off=lambda: (fn, args), **kw)


def _rules_fired(findings):
    return {f.rule.name for f in findings}


# -- contract 1: the shipped tree ------------------------------------------


class TestShippedTreeClean:
    def test_zero_active_findings(self):
        report = run_analysis()
        assert report.active_count == 0, report.render_text()
        # The zoo actually ran: every family is represented (the sharded
        # EGM program requires the >= 2-device mesh tier-1 provides, so it
        # must NOT be in the skip list here).
        assert len(report.programs_audited) >= 13
        assert report.programs_skipped == ()
        audited = set(report.programs_audited)
        for family_member in ("egm/sweep", "egm/sweep_f32_stage",
                              "egm/sweep_sentinel",
                              "egm/sweep_labor", "egm/sweep_sharded",
                              "vfi/step", "distribution/step_transpose",
                              "distribution/stationary",
                              "equilibrium/ge_round_batched",
                              "transition/round", "transition/fused",
                              "transition/fused_sentinel",
                              "transition/fused_sweep",
                              "ks/distribution_step"):
            assert family_member in audited

    def test_mesh_shim_ships_with_zero_suppressions(self):
        """The satellite acceptance: the three seed violations are FIXED
        (routed through parallel/mesh.py), not baselined or noqa'd — so
        not even a suppressed mesh-shim finding exists."""
        report = run_analysis(levels=("source",))
        mesh = [f for f in report.findings
                if f.rule.name == "mesh-shim-discipline"]
        assert mesh == []

    def test_checked_in_baseline_is_empty(self):
        assert load_baseline() == set()

    def test_rule_counts_zero_filled(self):
        report = run_analysis(levels=("source",))
        counts = report.rule_counts()
        assert set(counts) == {r.name for r in RULES}
        assert all(v == 0 for v in counts.values()), counts


# -- contract 2: every rule fires on its fixture, and only it --------------


class TestAdversarialFixtures:
    def test_no_scatter_fires(self):
        spec = _spec("fixture/scatter", fx.scatter_program,
                     (_f64(3, 16), _i32(3, 16), _f64(3, 16), _f64(3, 3)),
                     scatter_free=True, stage_dtype="float64")
        findings = audit_program(spec)
        assert _rules_fired(findings) == {"no-scatter"}, findings
        assert len(findings) == 2      # the two lottery legs

    def test_scatter_allowed_when_backend_is_scatter(self):
        spec = _spec("fixture/scatter_declared", fx.scatter_program,
                     (_f64(3, 16), _i32(3, 16), _f64(3, 16), _f64(3, 3)),
                     scatter_free=False, stage_dtype="float64")
        assert audit_program(spec) == []

    def test_precision_leak_fires(self):
        spec = _spec("fixture/leak", fx.precision_leak_program,
                     (_f32(3, 16), _f32(3, 3)), stage_dtype="float32")
        findings = audit_program(spec)
        assert _rules_fired(findings) == {"no-precision-leak"}, findings
        # The upcasts to f64 are flagged; the hide-the-leak downcast back
        # to the stage dtype is not (it restores the declared dtype).
        assert all("float64" in f.message for f in findings)

    def test_precision_clean_without_stage_declaration(self):
        spec = _spec("fixture/leak_undeclared", fx.precision_leak_program,
                     (_f32(3, 16), _f32(3, 3)), stage_dtype=None)
        findings = audit_program(spec)
        # Mixed-dtype dot check still applies program-wide — but this
        # fixture's dot is pure-f64, so nothing fires.
        assert findings == []

    def test_host_sync_fires_on_untagged_callback(self):
        spec = _spec("fixture/host_sync", fx.host_sync_program, (_f64(),),
                     stage_dtype="float64")
        findings = audit_program(spec)
        assert _rules_fired(findings) == {"no-host-sync-in-loop"}, findings
        assert "untagged" in findings[0].message

    def test_host_sync_clean_with_whitelisted_tag(self):
        spec = _spec("fixture/host_sync_tagged", fx.host_sync_tagged_program,
                     (_f64(),), stage_dtype="float64")
        assert audit_program(spec) == []

    def test_telemetry_noop_fires_on_ring_residue(self):
        cap = TELEMETRY_SENTINEL_CAPACITY
        spec = ProgramSpec(
            name="fixture/telemetry_leak", family="fixture",
            build_off=lambda: (lambda x: fx.telemetry_leak_program(x, cap),
                               (_f64(),)),
            build_on=lambda: (lambda x: fx.telemetry_leak_program(x, cap),
                              (_f64(),)))
        findings = audit_program(spec)
        assert _rules_fired(findings) == {"telemetry-noop"}, findings
        assert "compile out" in findings[0].message

    def test_telemetry_noop_fires_on_broken_wiring(self):
        spec = ProgramSpec(
            name="fixture/telemetry_unwired", family="fixture",
            build_off=lambda: (fx.telemetry_unwired_program, (_f64(),)),
            build_on=lambda: (fx.telemetry_unwired_program, (_f64(),)))
        findings = audit_program(spec)
        assert _rules_fired(findings) == {"telemetry-noop"}, findings
        assert "wiring is broken" in findings[0].message

    def test_dead_carry_fires(self):
        spec = _spec("fixture/dead_carry", fx.dead_carry_program,
                     (_f64(8),), stage_dtype="float64")
        findings = audit_program(spec)
        assert _rules_fired(findings) == {"dead-carry"}, findings
        assert len(findings) == 1      # junk only: i is read by the cond
        assert "slot 2" in findings[0].message

    def test_stable_carry_fires_on_weak_type(self):
        spec = _spec("fixture/weak_carry", fx.weak_carry_program,
                     (_f64(4),), stage_dtype="float64")
        findings = audit_program(spec)
        assert _rules_fired(findings) == {"stable-carry"}, findings
        assert all("weak-typed" in f.message for f in findings)

    def test_nan_exit_fires_on_nan_trap(self):
        """AIYA107 (ISSUE 10 satellite): a residual cond written
        `~(dist < tol)` stays True on a NaN dist — the concrete NaN probe
        must flag it, and ONLY it."""
        spec = _spec("fixture/nan_trap", fx.nan_trap_program,
                     (_f64(8), _f64()), stage_dtype="float64")
        findings = audit_program(spec)
        assert _rules_fired(findings) == {"nan-exit"}, findings
        assert "NaN" in findings[0].message

    def test_nan_exit_clean_on_sanctioned_comparison(self):
        spec = _spec("fixture/nan_exit", fx.nan_exit_program,
                     (_f64(8), _f64()), stage_dtype="float64")
        assert audit_program(spec) == []

    def test_sentinel_program_audited_and_clean(self):
        """The sentinel-carrying EGM sweep is a registered zoo artifact:
        its modified loop condition (verdict == 0 ANDed in) must still
        NaN-exit, its sentinel state slots must not trip the dead-carry /
        stable-carry rules, and the whole program must audit clean."""
        spec = next(p for p in registered_programs()
                    if p.name == "egm/sweep_sentinel")
        assert audit_program(spec) == []


class TestLintFixtures:
    def test_bad_source_trips_each_source_rule(self):
        findings = lint_file(FIXTURES / "bad_source.py", "bad_source.py",
                             hot=True, mesh_exempt=False)
        active = [f for f in findings if not f.suppressed]
        by_rule = {}
        for f in active:
            by_rule.setdefault(f.rule.name, []).append(f)
        assert set(by_rule) == {"mesh-shim-discipline",
                                "no-host-scalar-in-hot-module",
                                "no-bare-debug-print"}
        # import + attribute chain + raw PartitionSpec construction (the
        # ISSUE 13 extension).
        assert len(by_rule["mesh-shim-discipline"]) == 3
        assert len(by_rule["no-host-scalar-in-hot-module"]) == 2
        assert len(by_rule["no-bare-debug-print"]) == 1

    def test_noqa_suppresses_but_still_reports(self):
        findings = lint_file(FIXTURES / "bad_source.py", "bad_source.py",
                             hot=True, mesh_exempt=False)
        suppressed = [f for f in findings if f.suppressed]
        assert len(suppressed) == 1
        assert suppressed[0].rule.id == "AIYA202"
        assert "host_probes" not in suppressed[0].message  # msg is generic

    def test_bad_routes_trips_exactly_route_discipline(self):
        """ISSUE 12 satellite: both re-hardcoding forms — the "auto"
        literal mapped to a route, and a default_backend() platform split
        binding a route — trip exactly AIYA204, nothing else."""
        findings = lint_file(FIXTURES / "bad_routes.py", "bad_routes.py",
                             hot=False, mesh_exempt=False)
        assert [f.rule.id for f in findings] == ["AIYA204", "AIYA204"]
        assert all(f.rule.name == "route-resolution-discipline"
                   for f in findings)

    def test_route_discipline_spares_validation_guards(self, tmp_path):
        """Membership checks against ("auto", ...) that only RAISE (the
        numpy-backend capability guards in dispatch.py) are validation,
        not resolution — no finding."""
        src = ("def check(knob):\n"
               "    if knob not in ('auto', 'scatter'):\n"
               "        raise ValueError('scatter-free backends need jax; "
               "use scatter')\n")
        p = tmp_path / "guard.py"
        p.write_text(src)
        findings = lint_file(p, "guard.py", hot=False, mesh_exempt=False)
        assert "route-resolution-discipline" not in _rules_fired(findings)

    def test_route_discipline_exempts_sanctioned_resolvers(self):
        """The resolver modules and the tuning layer own the literal
        fallbacks by design; a scoping regression must name its file."""
        import aiyagari_tpu

        root = Path(aiyagari_tpu.__file__).resolve().parent
        for rel in ("ops/pushforward.py", "ops/egm.py", "ops/interp.py",
                    "tuning/autotuner.py"):
            findings = lint_file(root / rel, rel)
            assert not [f for f in findings if f.rule.id == "AIYA204"], rel

    def test_bad_autodiff_trips_exactly_ift_discipline(self):
        """ISSUE 17 satellite: jax.grad / bare grad / value_and_grad aimed
        straight at an unrolled solver fixed point trip exactly AIYA205 —
        and the sanctioned `jax.grad(<implicit wrapper>)` form does not."""
        findings = lint_file(FIXTURES / "bad_autodiff.py", "bad_autodiff.py",
                             hot=False, mesh_exempt=False)
        assert [f.rule.id for f in findings] == ["AIYA205"] * 3
        assert all(f.rule.name == "ift-differentiation-discipline"
                   for f in findings)
        named = "".join(f.message for f in findings)
        for solver in ("solve_aiyagari_egm", "stationary_distribution",
                       "solve_transition"):
            assert solver in named

    def test_ift_discipline_exempts_implicit_module(self):
        """ops/implicit.py IS the door: the custom_vjp rules inside may
        reference whatever autodiff machinery they need."""
        import aiyagari_tpu

        root = Path(aiyagari_tpu.__file__).resolve().parent
        findings = lint_file(root / "ops/implicit.py", "ops/implicit.py")
        assert not [f for f in findings if f.rule.id == "AIYA205"]

    def test_mesh_shim_catches_parent_module_import_forms(self, tmp_path):
        """`from jax import sharding` / `from jax.experimental import
        shard_map` bind the forbidden module under a local name — the
        bypass forms the review found; both must fire."""
        src = ("from jax import sharding\n"
               "from jax.experimental import shard_map\n"
               "spec = sharding.PartitionSpec()\n")
        p = tmp_path / "bypass.py"
        p.write_text(src)
        findings = lint_file(p, "bypass.py", hot=False, mesh_exempt=False)
        mesh = [f for f in findings if f.rule.name == "mesh-shim-discipline"]
        # Both import forms fire, and the aliased construction on line 3
        # now fires the ISSUE 13 raw-PartitionSpec extension too.
        assert len(mesh) == 3, findings
        assert {f.line for f in mesh} == {1, 2, 3}

    def test_debug_print_in_else_branch_of_guard_fires(self, tmp_path):
        """The else branch of an `if *DEBUG*:` is the production path —
        a debug print there is bare (review finding)."""
        src = ("import jax\n"
               "_MY_DEBUG = False\n"
               "def f(x):\n"
               "    if _MY_DEBUG:\n"
               "        jax.debug.print('debug {}', x)\n"
               "    else:\n"
               "        jax.debug.print('prod {}', x)\n"
               "    return x\n")
        p = tmp_path / "else_print.py"
        p.write_text(src)
        findings = lint_file(p, "else_print.py", hot=False,
                             mesh_exempt=False)
        bare = [f for f in findings if f.rule.name == "no-bare-debug-print"]
        assert len(bare) == 1, findings
        assert bare[0].line == 7     # the else-branch print, not line 5

    def test_cold_module_scope(self):
        """The same file linted as a NON-hot module keeps the mesh and
        debug-print findings but drops the host-scalar ones — AIYA202 is
        scoped to the hot directories."""
        findings = lint_file(FIXTURES / "bad_source.py", "bad_source.py",
                             hot=False, mesh_exempt=False)
        assert "no-host-scalar-in-hot-module" not in _rules_fired(findings)
        assert "mesh-shim-discipline" in _rules_fired(findings)


class TestBaselineAndCli:
    def test_baseline_suppresses_round_trip(self, tmp_path):
        findings = lint_file(FIXTURES / "bad_source.py", "bad_source.py",
                             hot=True, mesh_exempt=False)
        path = write_baseline(findings, tmp_path / "baseline.json")
        keys = load_baseline(path)
        assert keys     # every active finding keyed
        # Re-applying the baseline marks every finding suppressed.
        remaining = [f for f in findings
                     if not f.suppressed and f.baseline_key() not in keys]
        assert remaining == []

    def test_write_baseline_keeps_baseline_suppressed_findings(self,
                                                               tmp_path):
        """Regenerating the baseline must not drop findings the PREVIOUS
        baseline was suppressing (review finding): they still exist in
        the tree and would resurface as gate failures. noqa-suppressed
        findings are never imported."""
        import dataclasses

        findings = lint_file(FIXTURES / "bad_source.py", "bad_source.py",
                             hot=True, mesh_exempt=False)
        # Simulate a prior run: one active finding was baselined.
        first = next(f for f in findings if not f.suppressed)
        findings = [dataclasses.replace(f, suppressed=True,
                                        suppressed_by="baseline")
                    if f is first else f for f in findings]
        path = write_baseline(findings, tmp_path / "baseline.json")
        keys = load_baseline(path)
        assert first.baseline_key() in keys          # kept, not dropped
        # A file whose only finding is noqa'd contributes NO baseline
        # entry: that suppression lives in the source line.
        src = tmp_path / "only_noqa.py"
        src.write_text("def f(d):\n    return d.item()  # noqa: AIYA202\n")
        only = lint_file(src, "only_noqa.py", hot=True, mesh_exempt=False)
        assert [f.suppressed_by for f in only] == ["noqa"]
        p2 = write_baseline(only, tmp_path / "baseline2.json")
        assert load_baseline(p2) == set()

    def test_cli_json_exits_zero_on_shipped_tree(self, capsys):
        from aiyagari_tpu.analysis.__main__ import main

        rc = main(["--format", "json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["active_findings"] == 0
        assert set(out["rule_counts"]) == {r.name for r in RULES}

    def test_cli_list_rules(self, capsys):
        from aiyagari_tpu.analysis.__main__ import main

        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for r in RULES:
            assert r.id in out

    def test_cli_rules_filter(self, capsys):
        from aiyagari_tpu.analysis.__main__ import main

        rc = main(["--rules", "mesh-shim-discipline", "--format", "json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["programs_audited"] == []   # source-only selection
        assert out["files_linted"] > 0


class TestObservability:
    def test_ledger_analysis_event_and_metrics(self, tmp_path):
        from aiyagari_tpu.diagnostics import metrics
        from aiyagari_tpu.diagnostics.ledger import (
            RunLedger,
            activate,
            read_ledger,
        )

        led = RunLedger(tmp_path / "ledger.jsonl")
        with activate(led):
            run_analysis(levels=("source",))
        events = read_ledger(tmp_path / "ledger.jsonl")
        an = [e for e in events if e["kind"] == "analysis"]
        assert len(an) == 1
        assert an[0]["findings"] == 0
        assert set(an[0]["rules"]) == {r.name for r in RULES}
        # The zero-filled counter series exists even on a clean run — one
        # per rule, so dashboards can tell "clean" from "never ran".
        rendered = metrics.render_json()
        series = [c for c in rendered["counters"]
                  if c["name"] == "aiyagari_analysis_findings_total"]
        assert {c["labels"]["rule"] for c in series} == {r.name
                                                         for r in RULES}
        assert all(c["value"] == 0 for c in series)


class TestRegistryDeterminism:
    def test_abstract_inputs_trace_without_devices(self):
        """The registry's build_off pairs trace under make_jaxpr with
        ShapeDtypeStruct inputs — the eval_shape-style contract that keeps
        the auditor accelerator-free (satellite: deterministic under
        JAX_PLATFORMS=cpu)."""
        for spec in registered_programs():
            if spec.name == "egm/sweep_sharded":
                continue    # needs a mesh; covered by the full run above
            fn, args = spec.build_off()
            closed = jax.make_jaxpr(fn)(*args)
            assert closed.jaxpr.eqns, spec.name
