"""End-to-end general-equilibrium integration tests (SURVEY.md §4.3):
tiny-grid GE solve with economically-sane outcomes, dispatch-boundary routing,
and NumPy-vs-JAX backend agreement at equilibrium.
"""

import numpy as np
import pytest

from aiyagari_tpu import solve
from aiyagari_tpu.config import (
    AiyagariConfig,
    EquilibriumConfig,
    GridSpecConfig,
    IncomeProcess,
    SimConfig,
    SolverConfig,
)
from aiyagari_tpu.equilibrium.bisection import solve_equilibrium
from aiyagari_tpu.models.aiyagari import AiyagariModel
from aiyagari_tpu.utils.stats import gini

SMALL_CFG = AiyagariConfig(grid=GridSpecConfig(n_points=80))
SIM = SimConfig(periods=2500, n_agents=8, discard=200, seed=3)
EQ = EquilibriumConfig()


@pytest.mark.slow
class TestGE:
    @pytest.fixture(scope="class")
    def eq_result(self):
        model = AiyagariModel.from_config(SMALL_CFG)
        return solve_equilibrium(model, solver=SolverConfig(method="egm"), sim=SIM, eq=EQ)

    def test_r_below_complete_markets_rate(self, eq_result):
        # Precautionary saving: r* < 1/beta - 1 (Aiyagari's central result).
        beta = SMALL_CFG.preferences.beta
        assert eq_result.r < 1 / beta - 1
        assert eq_result.r > -0.05

    def test_market_clearing_gap_shrinks(self, eq_result):
        gaps = [abs(s - d) for s, d in zip(eq_result.k_supply, eq_result.k_demand)]
        assert gaps[-1] < gaps[0]

    def test_histories_aligned(self, eq_result):
        assert len(eq_result.r_history) == len(eq_result.k_supply) == len(eq_result.k_demand)
        assert eq_result.iterations <= EQ.max_iter

    def test_wealth_gini_in_plausible_range(self, eq_result):
        g = float(gini(eq_result.series.k[SIM.discard:]))
        assert 0.05 < g < 0.9

    def test_dispatch_jax(self):
        res = solve(SMALL_CFG, method="egm", backend="jax",
                    sim=SIM, equilibrium=EquilibriumConfig(max_iter=3))
        assert len(res.r_history) <= 3

    def test_dispatch_numpy_backend_agrees(self, eq_result):
        res = solve(SMALL_CFG, method="egm", backend="numpy",
                    sim=SimConfig(periods=2500, n_agents=8, discard=200, seed=3),
                    equilibrium=EQ)
        # Same bisection bracket logic and same economics: r* within one
        # bracket width (simulation noise differs across RNGs).
        assert abs(res.r - eq_result.r) < 0.02


@pytest.mark.slow
class TestNonConvergencePolicy:
    """SURVEY.md §5.3: iteration caps surface as typed warnings/errors
    carrying the loop's final state, not silent flags."""

    STARVED = EquilibriumConfig(max_iter=2, tol=1e-12)   # cannot converge

    def test_warn_default_returns_last_iterate(self):
        from aiyagari_tpu import ConvergenceWarning

        with pytest.warns(ConvergenceWarning, match="GE bisection"):
            res = solve(SMALL_CFG, method="egm",
                        sim=SimConfig(periods=600, n_agents=4, discard=100, seed=0),
                        equilibrium=self.STARVED)
        assert not res.converged and len(res.r_history) == 2

    def test_raise_carries_final_state(self):
        from aiyagari_tpu import ConvergenceError

        with pytest.raises(ConvergenceError) as exc:
            solve(SMALL_CFG, method="egm",
                  sim=SimConfig(periods=600, n_agents=4, discard=100, seed=0),
                  equilibrium=self.STARVED, on_nonconvergence="raise")
        assert exc.value.iterations == 2
        assert exc.value.tol == 1e-12
        assert np.isfinite(exc.value.distance)
        assert "r" in exc.value.detail

    def test_ignore_is_silent(self, recwarn):
        res = solve(SMALL_CFG, method="egm",
                    sim=SimConfig(periods=600, n_agents=4, discard=100, seed=0),
                    equilibrium=self.STARVED, on_nonconvergence="ignore")
        assert not res.converged
        assert not [w for w in recwarn if "GE bisection" in str(w.message)]

    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError, match="on_nonconvergence"):
            solve(SMALL_CFG, on_nonconvergence="explode")


class TestGoldenValues:
    def test_tiny_grid_ge_golden(self):
        """SURVEY §4.3: tiny-grid end-to-end GE solve against golden values
        (f64, deterministic histogram closure — no Monte-Carlo noise, so the
        numbers are exactly reproducible). Golden values computed at commit
        384a217's numerics; a drift here means the solver pipeline changed
        behavior, not just speed."""
        import jax.numpy as jnp

        from aiyagari_tpu.models.aiyagari import AiyagariModel
        from aiyagari_tpu.utils.stats import weighted_gini

        cfg = AiyagariConfig(
            income=IncomeProcess(n_states=3), grid=GridSpecConfig(n_points=80)
        )
        # The run intentionally stops at the reference's 10-bisection cap
        # (the capital-market gap is still ~0.5 there): declare that so the
        # test doesn't leak a ConvergenceWarning on every run.
        res = solve(cfg, method="vfi", aggregation="distribution",
                    on_nonconvergence="ignore")
        m = AiyagariModel.from_config(cfg, jnp.float64)
        g = float(weighted_gini(m.a_grid, jnp.asarray(np.asarray(res.mu).sum(0))))
        # 10 bisection iterations on a ~0.09-wide bracket resolve r to ~1e-4.
        assert abs(res.r - 0.0131103516) < 1e-8
        assert abs(res.capital - 9.1481393835) < 1e-6
        assert abs(g - 0.2925894122) < 1e-6
