"""Precision-discipline tests (SURVEY.md §5.2): the kernels that run in f32 on
TPU (bf16/f32 is the native regime there) must still converge to the
reference tolerances and agree with the f64 ground truth.

The suite's conftest enables x64 globally; these tests build f32 models
explicitly, mirroring what `bench.py` and the dispatch layer do on TPU
(BackendConfig.dtype="float32"). The precision-sensitive spots called out in
the survey: CRRA powers at sigma=5 and the EGM marginal-utility inversion
u'^(-1/sigma) (Aiyagari_EGM.m:69).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.config import SolverConfig
from aiyagari_tpu.equilibrium.bisection import solve_household
from aiyagari_tpu.models.aiyagari import aiyagari_preset
from aiyagari_tpu.utils.utility import crra_marginal, crra_marginal_inverse

TOL = 1e-5   # the reference tolerance (Aiyagari_VFI.m:49)


@pytest.fixture(scope="module", params=["vfi", "egm"])
def f32_and_f64(request):
    method = request.param
    sols = {}
    for dtype in (jnp.float32, jnp.float64):
        m = aiyagari_preset(grid_size=120, dtype=dtype)
        sols[dtype] = solve_household(
            m, 0.04, solver=SolverConfig(method=method, tol=TOL, max_iter=1000)
        )
    return method, sols


class TestF32Convergence:
    def test_f32_hits_reference_tolerance(self, f32_and_f64):
        _, sols = f32_and_f64
        sol = sols[jnp.float32]
        assert sol.policy_c.dtype == jnp.float32
        assert float(sol.distance) < TOL
        assert int(sol.iterations) < 1000

    def test_f32_policy_close_to_f64(self, f32_and_f64):
        # Policies agree to well under one grid cell; consumption relative
        # error stays near f32 resolution, not at blowup scale.
        _, sols = f32_and_f64
        c32 = np.asarray(sols[jnp.float32].policy_c, np.float64)
        c64 = np.asarray(sols[jnp.float64].policy_c)
        rel = np.abs(c32 - c64) / (np.abs(c64) + 1e-12)
        assert np.max(rel) < 5e-3
        k32 = np.asarray(sols[jnp.float32].policy_k, np.float64)
        k64 = np.asarray(sols[jnp.float64].policy_k)
        assert np.max(np.abs(k32 - k64)) < 0.05 * float(k64.max() - k64.min() + 1)

    def test_f32_value_distance_monotone_family(self, f32_and_f64):
        # The converged iteration count in f32 is in the same regime as f64
        # (no precision-stall: f32 should not need materially more sweeps).
        _, sols = f32_and_f64
        it32 = int(sols[jnp.float32].iterations)
        it64 = int(sols[jnp.float64].iterations)
        assert it32 <= it64 + 50


class TestMarginalUtilityInversion:
    """u' and its inverse at sigma=5 — the survey's precision-sensitive spot."""

    @pytest.mark.parametrize("dtype,rtol", [(jnp.float32, 2e-5), (jnp.float64, 1e-12)])
    def test_roundtrip_at_sigma5(self, dtype, rtol):
        c = jnp.asarray(np.geomspace(1e-2, 50.0, 64), dtype)
        up = crra_marginal(c, 5.0)
        c_back = crra_marginal_inverse(up, 5.0)
        np.testing.assert_allclose(np.asarray(c_back), np.asarray(c), rtol=rtol)

    def test_f32_no_overflow_at_small_consumption(self):
        # c^-5 at c=1e-2 is 1e10 — representable in f32; the inversion must
        # not round-trip through inf/NaN.
        c = jnp.asarray([1e-2, 5e-2, 1e-1], jnp.float32)
        up = crra_marginal(c, 5.0)
        assert bool(jnp.all(jnp.isfinite(up)))
        assert bool(jnp.all(jnp.isfinite(crra_marginal_inverse(up, 5.0))))
