"""Precision-discipline tests (SURVEY.md §5.2): the kernels that run in f32 on
TPU (bf16/f32 is the native regime there) must still converge to the
reference tolerances and agree with the f64 ground truth.

The suite's conftest enables x64 globally; these tests build f32 models
explicitly, mirroring what `bench.py` and the dispatch layer do on TPU
(BackendConfig.dtype="float32"). The precision-sensitive spots called out in
the survey: CRRA powers at sigma=5 and the EGM marginal-utility inversion
u'^(-1/sigma) (Aiyagari_EGM.m:69).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.config import SolverConfig
from aiyagari_tpu.equilibrium.bisection import solve_household
from aiyagari_tpu.models.aiyagari import aiyagari_preset
from aiyagari_tpu.utils.utility import crra_marginal, crra_marginal_inverse

TOL = 1e-5   # the reference tolerance (Aiyagari_VFI.m:49)


@pytest.fixture(scope="module", params=["vfi", "egm"])
def f32_and_f64(request):
    method = request.param
    sols = {}
    for dtype in (jnp.float32, jnp.float64):
        m = aiyagari_preset(grid_size=120, dtype=dtype)
        sols[dtype] = solve_household(
            m, 0.04, solver=SolverConfig(method=method, tol=TOL, max_iter=1000)
        )
    return method, sols


class TestF32Convergence:
    def test_f32_hits_reference_tolerance(self, f32_and_f64):
        _, sols = f32_and_f64
        sol = sols[jnp.float32]
        assert sol.policy_c.dtype == jnp.float32
        assert float(sol.distance) < TOL
        assert int(sol.iterations) < 1000

    def test_f32_policy_close_to_f64(self, f32_and_f64):
        # Policies agree to well under one grid cell; consumption relative
        # error stays near f32 resolution, not at blowup scale.
        _, sols = f32_and_f64
        c32 = np.asarray(sols[jnp.float32].policy_c, np.float64)
        c64 = np.asarray(sols[jnp.float64].policy_c)
        rel = np.abs(c32 - c64) / (np.abs(c64) + 1e-12)
        assert np.max(rel) < 5e-3
        k32 = np.asarray(sols[jnp.float32].policy_k, np.float64)
        k64 = np.asarray(sols[jnp.float64].policy_k)
        assert np.max(np.abs(k32 - k64)) < 0.05 * float(k64.max() - k64.min() + 1)

    def test_f32_value_distance_monotone_family(self, f32_and_f64):
        # The converged iteration count in f32 is in the same regime as f64
        # (no precision-stall: f32 should not need materially more sweeps).
        _, sols = f32_and_f64
        it32 = int(sols[jnp.float32].iterations)
        it64 = int(sols[jnp.float64].iterations)
        assert it32 <= it64 + 50


class TestMarginalUtilityInversion:
    """u' and its inverse at sigma=5 — the survey's precision-sensitive spot."""

    @pytest.mark.parametrize("dtype,rtol", [(jnp.float32, 2e-5), (jnp.float64, 1e-12)])
    def test_roundtrip_at_sigma5(self, dtype, rtol):
        c = jnp.asarray(np.geomspace(1e-2, 50.0, 64), dtype)
        up = crra_marginal(c, 5.0)
        c_back = crra_marginal_inverse(up, 5.0)
        np.testing.assert_allclose(np.asarray(c_back), np.asarray(c), rtol=rtol)

    def test_f32_no_overflow_at_small_consumption(self):
        # c^-5 at c=1e-2 is 1e10 — representable in f32; the inversion must
        # not round-trip through inf/NaN.
        c = jnp.asarray([1e-2, 5e-2, 1e-1], jnp.float32)
        up = crra_marginal(c, 5.0)
        assert bool(jnp.all(jnp.isfinite(up)))
        assert bool(jnp.all(jnp.isfinite(crra_marginal_inverse(up, 5.0))))


class TestFineGridF32:
    """Regressions for the fine-grid f32 failure modes measured on TPU:
    (a) the default TPU f32 matmul is a single bf16 pass with ~0.5 absolute
    error on values O(100) — expectation() pins HIGHEST precision;
    (b) the EGM endogenous grid loses monotonicity/extrapolates unstably at
    100k+ points in f32 — egm_step monotonizes knots and truncates the policy
    at the grid top;
    (c) continuous golden-section argmax jitters by whole cells on the flat
    choice objective — the coarse-to-fine index search ranks candidates by
    direct value comparison instead."""

    def test_index_argmax_matches_brute_force(self):
        # Concave objective with a per-point feasibility bound, both dtypes.
        from aiyagari_tpu.ops.golden import unimodal_argmax_index

        n = 700
        rng = np.random.default_rng(0)
        peak = rng.uniform(50, 650, size=(5, 40))
        hi = np.minimum((peak + rng.uniform(0, 300, peak.shape)).astype(np.int32), n - 1)
        for dtype in (jnp.float32, jnp.float64):
            peak_j = jnp.asarray(peak, dtype)
            hi_j = jnp.asarray(hi, jnp.int32)

            def f(j):
                return -((j.astype(dtype) - peak_j) ** 2)

            got = np.asarray(unimodal_argmax_index(f, hi_j, n))
            js = np.arange(n)[None, None, :]
            vals = -((js - peak[..., None]) ** 2)
            vals[js > hi[..., None]] = -np.inf
            np.testing.assert_array_equal(got, vals.argmax(-1))

    def test_egm_f32_converges_on_fine_grid(self):
        # 20k points, f32: requires the monotonized endogenous grid and the
        # grid-top clamp (unbounded edge extrapolation oscillates at O(10)).
        from aiyagari_tpu.solvers.egm import solve_aiyagari_egm
        from aiyagari_tpu.utils.firm import wage_from_r

        n = 20_000
        m = aiyagari_preset(grid_size=n, dtype=jnp.float32)
        w = float(wage_from_r(0.04, m.config.technology.alpha, m.config.technology.delta))
        mean_s = float(jnp.mean(m.s))
        C0 = jnp.broadcast_to(
            ((1.04) * m.a_grid + w * mean_s)[None, :], (m.P.shape[0], n)
        ).astype(jnp.float32)
        sol = solve_aiyagari_egm(
            C0, m.a_grid, m.s, m.P, 0.04, w, m.amin,
            sigma=m.preferences.sigma, beta=m.preferences.beta,
            tol=TOL, max_iter=1000,
        )
        assert bool(jnp.all(jnp.isfinite(sol.policy_c)))
        assert float(sol.distance) < TOL

    def test_continuous_vfi_f32_converges_and_matches_dense(self):
        from aiyagari_tpu.solvers.vfi import (
            solve_aiyagari_vfi,
            solve_aiyagari_vfi_continuous,
        )
        from aiyagari_tpu.utils.firm import wage_from_r

        n = 400
        m = aiyagari_preset(grid_size=n, dtype=jnp.float32)
        w = float(wage_from_r(0.04, m.config.technology.alpha, m.config.technology.delta))
        v0 = jnp.zeros((m.P.shape[0], n), jnp.float32)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                  tol=TOL, max_iter=2000)
        sol = solve_aiyagari_vfi_continuous(
            v0, m.a_grid, m.s, m.P, 0.04, w, m.amin,
            howard_steps=50, grid_power=2.0, **kw)
        dense = solve_aiyagari_vfi(v0, m.a_grid, m.s, m.P, 0.04, w,
                                   **{**kw, "max_iter": 1000})
        assert float(sol.distance) < TOL
        assert int(sol.iterations) < 2000
        # Same fixed point as the dense discrete search, up to f32 tie
        # flatness: values match closely, policies within a few cells.
        assert float(jnp.max(jnp.abs(sol.v - dense.v))) < 5e-3
        assert int(jnp.max(jnp.abs(sol.policy_idx - dense.policy_idx))) <= 16

    def test_expectation_highest_precision(self):
        # expectation() must not use the bf16-pass matmul: error vs f64 stays
        # at f32-rounding scale even for adversarial magnitudes.
        from aiyagari_tpu.ops.bellman import expectation

        rng = np.random.default_rng(1)
        P = rng.dirichlet(np.ones(7), 7)
        v = rng.uniform(-300, -30, (7, 512))
        got = np.asarray(expectation(jnp.asarray(P, jnp.float32),
                                     jnp.asarray(v, jnp.float32), 0.96))
        want = 0.96 * P @ v
        assert np.abs(got - want).max() < 5e-4

    @pytest.mark.slow
    def test_noise_floor_rule_semantics(self):
        """noise_floor_ulp widens the stopping tolerance to the f32 rounding
        band (tol_effective > tol, fewer sweeps, near-identical policy) and
        is an exact no-op in f64, where the floor is ~1e-13 (BENCHMARKS.md
        round-2 yardstick pins the 400k quality claim on hardware; this
        pins the rule's mechanics at test scale)."""
        from aiyagari_tpu.solvers.egm import initial_consumption_guess, solve_aiyagari_egm
        from aiyagari_tpu.utils.firm import wage_from_r

        n = 600   # semantics are n-independent; cold sweeps cost n^2 on this box
        for dtype in (jnp.float32, jnp.float64):
            m = aiyagari_preset(grid_size=n, dtype=dtype)
            w = float(wage_from_r(0.04, m.config.technology.alpha,
                                  m.config.technology.delta))
            C0 = initial_consumption_guess(m.a_grid, m.s, 0.04, w).astype(dtype)
            kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                      max_iter=2000, grid_power=2.0)
            # A tolerance just below the f32 floor at this calibration
            # (max|C| ~ 10.2 -> floor_24 = 24*eps*maxC ~ 2.9e-5 in f32,
            # ~5.4e-14 in f64), so the rule engages in f32 only.
            strict = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.04, w,
                                        m.amin, tol=2e-5, **kw)
            floored = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.04, w,
                                         m.amin, tol=2e-5,
                                         noise_floor_ulp=24.0, **kw)
            if dtype == jnp.float32:
                assert float(floored.tol_effective) > 2e-5
                assert int(floored.iterations) <= int(strict.iterations)
                # Same noise cone: both iterates sit within their own
                # stopping distance of the fixed point, so the gap is
                # bounded by the SUM of the two tolerances amplified by the
                # fixed-point sensitivity 1/(1-beta).
                bound = (float(floored.tol_effective) + 2e-5) / (1 - m.preferences.beta)
                assert float(jnp.max(jnp.abs(
                    floored.policy_c - strict.policy_c))) < bound
            else:
                # f64: floor ~ 5.4e-14 << tol -> identical stopping rule.
                assert float(floored.tol_effective) == pytest.approx(2e-5)
                assert int(floored.iterations) == int(strict.iterations)
                np.testing.assert_array_equal(np.asarray(floored.policy_c),
                                              np.asarray(strict.policy_c))

    def test_vfi_noise_floor_rule_semantics(self):
        """The continuous VFI's noise_floor_ulp (round 4): at 400k f32 the
        VALUE sup-norm wanders at ~24 ulp of max|v| (~5e-4) and the strict
        1e-5 never fires — the un-floored loop ran to max_iter in one
        device call until the transport killed the TPU worker. This pins
        the rule's mechanics at test scale: tol_effective reported above
        tol in f32 (values O(100) -> floor_24 ~ 2.9e-4), no more sweeps
        than strict, and an exact no-op in f64."""
        from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi_continuous
        from aiyagari_tpu.utils.firm import wage_from_r

        n = 600
        for dtype in (jnp.float32, jnp.float64):
            m = aiyagari_preset(grid_size=n, dtype=dtype)
            w = float(wage_from_r(0.04, m.config.technology.alpha,
                                  m.config.technology.delta))
            v0 = jnp.zeros((m.P.shape[0], n), dtype)
            kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
                      tol=1e-5, max_iter=2000, howard_steps=25,
                      golden_iters=0, grid_power=2.0)
            strict = solve_aiyagari_vfi_continuous(
                v0, m.a_grid, m.s, m.P, 0.04, w, m.amin, **kw)
            floored = solve_aiyagari_vfi_continuous(
                v0, m.a_grid, m.s, m.P, 0.04, w, m.amin,
                noise_floor_ulp=24.0, **kw)
            assert bool(jnp.all(jnp.isfinite(floored.v)))
            if dtype == jnp.float32:
                assert float(floored.tol_effective) > 1e-5
                assert int(floored.iterations) <= int(strict.iterations)
                # Same noise cone as the EGM rule: both stop within their
                # own tolerance of the fixed point.
                bound = (float(floored.tol_effective) + 1e-5) / (1 - m.preferences.beta)
                assert float(jnp.max(jnp.abs(floored.v - strict.v))) < bound
            else:
                assert float(floored.tol_effective) == pytest.approx(1e-5)
                np.testing.assert_array_equal(np.asarray(floored.v),
                                              np.asarray(strict.v))

    @pytest.mark.slow
    def test_labor_egm_f32_converges_on_fine_grid(self):
        # Same hazard as test_egm_f32_converges_on_fine_grid but through the
        # consumption-policy extrapolation of the endogenous-labor variant.
        from aiyagari_tpu.models.aiyagari import aiyagari_labor_preset
        from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_labor
        from aiyagari_tpu.utils.firm import wage_from_r

        n = 20_000
        m = aiyagari_labor_preset(grid_size=n, dtype=jnp.float32)
        w = float(wage_from_r(0.04, m.config.technology.alpha, m.config.technology.delta))
        mean_s = float(jnp.mean(m.s))
        C0 = jnp.broadcast_to(
            ((1.04) * m.a_grid + w * mean_s)[None, :], (m.P.shape[0], n)
        ).astype(jnp.float32)
        prefs = m.preferences
        sol = solve_aiyagari_egm_labor(
            C0, m.a_grid, m.s, m.P, 0.04, w, m.amin,
            sigma=prefs.sigma, beta=prefs.beta, psi=prefs.psi, eta=prefs.eta,
            tol=TOL, max_iter=1000,
        )
        assert bool(jnp.all(jnp.isfinite(sol.policy_c)))
        assert float(sol.distance) < TOL

    def test_continuous_vfi_respects_borrowing_limit_above_grid_bottom(self):
        # A grid extending below the borrowing limit: the continuous solver
        # must never choose a' < amin (regression: amin was silently unused).
        from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi_continuous
        from aiyagari_tpu.utils.firm import wage_from_r

        m = aiyagari_preset(grid_size=200, dtype=jnp.float64)
        shift = 2.0
        a_grid = m.a_grid - shift          # grid bottom now at -2.0
        amin = 0.0                         # borrowing limit strictly inside
        w = float(wage_from_r(0.04, m.config.technology.alpha, m.config.technology.delta))
        v0 = jnp.zeros((m.P.shape[0], 200))
        sol = solve_aiyagari_vfi_continuous(
            v0, a_grid, m.s, m.P, 0.04, w, amin,
            sigma=m.preferences.sigma, beta=m.preferences.beta,
            tol=TOL, max_iter=2000, howard_steps=50,
        )
        assert float(sol.distance) < TOL
        assert float(jnp.min(sol.policy_k)) >= amin - 1e-12


class TestPrecisionScope:
    def test_f64_honored_without_global_x64(self):
        # BackendConfig defaults to float64; without the scope a float64
        # request silently truncates to f32 when global x64 is off — and the
        # K-S ALM fixed point then limit-cycles at diff_B ~ 5e-2 instead of
        # converging (measured on a v5e; see config.precision_scope).
        import jax

        from aiyagari_tpu.config import precision_scope

        # jax < 0.6 only has the scoped x64 switch under jax.experimental
        # (the same compat probe precision_scope itself performs).
        enable_x64 = getattr(jax, "enable_x64", None)
        if enable_x64 is None:
            from jax.experimental import enable_x64

        with enable_x64(False):
            assert jnp.zeros(1, jnp.float64).dtype == jnp.float32  # the trap
            with precision_scope("float64"):
                assert jnp.zeros(1, jnp.float64).dtype == jnp.float64
            with precision_scope("float32"):
                assert jnp.zeros(1, jnp.float64).dtype == jnp.float32
