"""Test configuration: force the CPU backend with 8 virtual devices and
float64 BEFORE jax initializes (SURVEY.md §4.4 backend-equivalence strategy —
shardings and collectives are exercised on a virtual mesh without TPU
hardware; numerics are validated in f64)."""

import os

# Hard override (the session environment presets JAX_PLATFORMS=axon/TPU).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_ENABLE_X64"] = "1"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# A pytest plugin (jaxtyping) imports jax before this conftest runs, so the
# env vars above can be too late for jax's import-time config — set the flags
# explicitly too (safe while no backend is initialized yet).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent XLA compile cache: the suite's wall-clock is dominated by CPU
# compiles of the solver fixed points (this box has ONE core), and the test
# programs are identical run to run — the cache cuts repeat-suite time ~2x
# (io_utils/compile_cache.py; set AIYAGARI_TPU_COMPILE_CACHE="" to disable,
# e.g. when bisecting a suspected stale-cache miscompile).
from aiyagari_tpu.io_utils.compile_cache import enable_compilation_cache  # noqa: E402

# Dedicated directory (backend-suffixed by enable_compilation_cache, so the
# suite's XLA:CPU artifacts never collide with TPU-session processes whose
# XLA:CPU machine-feature flags differ — the documented SIGILL hazard).
enable_compilation_cache(os.path.join(os.path.expanduser("~"),
                                      ".cache", "aiyagari_tpu", "xla-tests"))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture(autouse=True)
def _reset_observability_state():
    """Module-global observability state must not leak across tests: a
    progress sink a crashed test left subscribed would receive every later
    solve's records, and the process-wide metrics registry would blur one
    test's degradation counts into the next's assertions. Reset AFTER each
    test (the state is empty at entry by induction)."""
    yield
    from aiyagari_tpu.diagnostics import metrics
    from aiyagari_tpu.diagnostics.progress import reset

    reset()
    metrics.reset()
