"""Krusell-Smith component and integration tests (SURVEY.md §4.2-4.3):
golden-section oracle check, shock-panel ergodics, cross-method (VFI vs EGM)
agreement of the ALM fixed point, and ALM R-squared > 0.99.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.optimize import minimize_scalar

from aiyagari_tpu.config import ALMConfig, KrusellSmithConfig, SolverConfig
from aiyagari_tpu.equilibrium.alm import solve_krusell_smith
from aiyagari_tpu.models.krusell_smith import KrusellSmithModel, state_index
from aiyagari_tpu.ops.golden import golden_section_max
from aiyagari_tpu.ops.regression import alm_regression, masked_ols_loglinear
from aiyagari_tpu.sim.ks_panel import (
    simulate_aggregate_shocks,
    simulate_employment_panel,
)

SMALL = KrusellSmithConfig(k_size=25)
ALM_SMALL = ALMConfig(T=400, population=2000, discard=80, max_iter=12, seed=7)
SOLVER_VFI = SolverConfig(method="vfi", tol=1e-5, max_iter=300, howard_steps=20, improve_every=5)
SOLVER_EGM = SolverConfig(method="egm", tol=1e-6, max_iter=3000)


class TestGoldenSection:
    def test_matches_scipy_bounded(self):
        peaks = jnp.array([0.3, 1.7, 4.2, 9.9])

        def f(x):
            return -((x - peaks) ** 2) + jnp.sin(3 * x)

        lo = jnp.zeros(4)
        hi = jnp.full(4, 12.0)
        got = np.asarray(golden_section_max(f, lo, hi, n_iters=60))
        for i in range(4):
            want = minimize_scalar(
                lambda x: -(-((x - float(peaks[i])) ** 2) + np.sin(3 * x)),
                bounds=(0.0, 12.0), method="bounded",
                options={"xatol": 1e-10},
            ).x
            assert abs(got[i] - want) < 1e-6

    def test_endpoint_maximum(self):
        # Monotone objective: maximum at the upper bound.
        f = lambda x: x
        got = golden_section_max(f, jnp.zeros(1), jnp.full(1, 5.0), n_iters=60)
        assert abs(float(got[0]) - 5.0) < 1e-6


class TestRegression:
    def test_masked_ols_matches_lstsq(self, rng):
        x = rng.normal(size=200)
        y = 0.3 + 0.9 * x + 0.01 * rng.normal(size=200)
        mask = rng.random(200) < 0.6
        b0, b1, r2 = masked_ols_loglinear(jnp.array(x), jnp.array(y), jnp.array(mask))
        X = np.stack([np.ones(mask.sum()), x[mask]], 1)
        beta, *_ = np.linalg.lstsq(X, y[mask], rcond=None)
        np.testing.assert_allclose([float(b0), float(b1)], beta, atol=1e-10)
        assert 0.99 < float(r2) <= 1.0

    def test_alm_regression_recovers_truth(self, rng):
        # Generate a path exactly following a two-regime loglinear law.
        T = 500
        z = rng.integers(0, 2, T)
        B_true = np.array([0.2, 0.95, 0.1, 0.96])
        K = np.empty(T)
        K[0] = 40.0
        for t in range(T - 1):
            b0, b1 = (B_true[0], B_true[1]) if z[t] == 0 else (B_true[2], B_true[3])
            K[t + 1] = np.exp(b0 + b1 * np.log(K[t]))
        B, r2 = alm_regression(jnp.array(K), jnp.array(z), discard=50)
        np.testing.assert_allclose(np.asarray(B), B_true, atol=1e-8)
        np.testing.assert_allclose(np.asarray(r2), 1.0, atol=1e-10)


class TestShocks:
    def test_aggregate_duration(self):
        model = KrusellSmithModel.from_config(SMALL)
        z = np.asarray(simulate_aggregate_shocks(model.pz, jax.random.PRNGKey(0), T=60_000))
        # Average spell duration ~ 8 quarters (Krusell_Smith_VFI.m:24).
        switches = np.sum(z[1:] != z[:-1])
        avg_dur = len(z) / max(switches, 1)
        assert 6.0 < avg_dur < 10.5

    def test_unemployment_rates_by_state(self):
        model = KrusellSmithModel.from_config(SMALL)
        sh = SMALL.shocks
        key = jax.random.PRNGKey(3)
        kz, ke = jax.random.split(key)
        z = simulate_aggregate_shocks(model.pz, kz, T=4000)
        eps = simulate_employment_panel(z, model.eps_trans, sh.u_good, sh.u_bad, ke,
                                        T=4000, population=1500)
        z_np, eps_np = np.asarray(z), np.asarray(eps)
        # Conditional unemployment rate per aggregate state (after burn-in).
        u_g = eps_np[200:][z_np[200:] == 0].mean()
        u_b = eps_np[200:][z_np[200:] == 1].mean()
        assert abs(u_g - sh.u_good) < 0.012
        assert abs(u_b - sh.u_bad) < 0.02

    def test_state_index_mapping(self):
        # (z, employed) -> reference meshgrid ordering (Krusell_Smith_VFI.m:18-21).
        assert int(state_index(0, 1)) == 0  # good, employed
        assert int(state_index(1, 1)) == 1  # bad, employed
        assert int(state_index(0, 0)) == 2  # good, unemployed
        assert int(state_index(1, 0)) == 3  # bad, unemployed


class TestDispatchKS:
    def test_default_solver_uses_ks_defaults(self):
        # Regression: solve(KrusellSmithConfig()) without an explicit solver
        # must get the KS Howard defaults (not howard_steps=0, which would
        # leave the value function untouched and "converge" instantly).
        from aiyagari_tpu import solve
        from aiyagari_tpu.config import ALMConfig as A

        res = solve(KrusellSmithConfig(k_size=15), method="vfi",
                    alm=A(T=100, population=200, discard=20, max_iter=1))
        assert res.per_iteration[0]["solver_iterations"] >= 2
        assert res.r2[0] > 0.9

    def test_method_conflict_raises(self):
        from aiyagari_tpu import solve

        with pytest.raises(ValueError, match="conflicting methods"):
            solve(KrusellSmithConfig(k_size=15), method="vfi",
                  solver=SolverConfig(method="egm"))

    def test_nonconvergence_policy_on_alm_loop(self):
        # SURVEY.md §5.3 for the K-S branch: a starved ALM loop surfaces a
        # typed error carrying the coefficient-step distance.
        from aiyagari_tpu import ConvergenceError, solve
        from aiyagari_tpu.config import ALMConfig as A

        starved = A(T=100, population=200, discard=20, max_iter=1, tol=1e-12)
        with pytest.raises(ConvergenceError, match="ALM fixed point") as exc:
            solve(KrusellSmithConfig(k_size=15), method="vfi", alm=starved,
                  on_nonconvergence="raise")
        assert exc.value.iterations == 1
        assert "B" in exc.value.detail

    def test_solver_method_respected_without_method_kwarg(self):
        # solver.method alone selects the method (no silent override).
        from aiyagari_tpu import solve
        from aiyagari_tpu.config import ALMConfig as A

        res = solve(KrusellSmithConfig(k_size=15),
                    solver=SolverConfig(method="egm", tol=1e-5, max_iter=500),
                    alm=A(T=100, population=200, discard=20, max_iter=1))
        assert res.iterations == 1


class TestHistogramClosure:
    """Deterministic Young-method cross-section for K-S
    (sim/ks_distribution.py; no reference analogue)."""

    def test_initial_distribution_shares_and_mass(self):
        from aiyagari_tpu.sim.ks_distribution import initial_distribution

        model = KrusellSmithModel.from_config(SMALL)
        mu = initial_distribution(model.k_grid, model.K_grid, 0.04, jnp.float64)
        assert mu.shape == (2, SMALL.k_size)
        np.testing.assert_allclose(float(mu.sum()), 1.0, rtol=1e-12)
        np.testing.assert_allclose(float(mu[1].sum()), 0.04, rtol=1e-12)  # unemployed
        # All capital mass at (the lottery bracket of) K_grid[0].
        np.testing.assert_allclose(
            float((mu * model.k_grid[None, :]).sum()), float(model.K_grid[0]), rtol=1e-10
        )

    def test_path_conserves_mass_and_unemployment(self):
        from aiyagari_tpu.sim.ks_distribution import (
            distribution_capital_path,
            initial_distribution,
        )

        cfg = SMALL
        model = KrusellSmithModel.from_config(cfg)
        T = 120
        z = simulate_aggregate_shocks(model.pz, jax.random.PRNGKey(3), T=T)
        k_opt = 0.9 * jnp.broadcast_to(
            model.k_grid[None, None, :], (4, cfg.K_size, cfg.k_size)
        ).astype(jnp.float64)
        mu0 = initial_distribution(model.k_grid, model.K_grid,
                                   cfg.shocks.u_good, jnp.float64)
        K_ts, mu = distribution_capital_path(
            k_opt, model.k_grid, model.K_grid, z, model.eps_trans, mu0, T=T
        )
        assert K_ts.shape == (T,)
        np.testing.assert_allclose(float(mu.sum()), 1.0, rtol=1e-10)
        # The conditional employment chains reproduce u(z_T) exactly given
        # u(z_0) — the property the duration construction encodes.
        u_T = cfg.shocks.u_good if int(z[-1]) == 0 else cfg.shocks.u_bad
        np.testing.assert_allclose(float(mu[1].sum()), u_T, atol=1e-8)
        assert bool(jnp.all(K_ts > 0)) and bool(jnp.all(jnp.isfinite(K_ts)))

    @pytest.mark.slow
    def test_alm_fit_beats_panel_and_agrees(self):
        kw = dict(method="vfi", solver=SOLVER_VFI,
                  alm=ALMConfig(T=300, population=2000, discard=50, max_iter=6, seed=7))
        panel = solve_krusell_smith(SMALL, closure="panel", **kw)
        hist = solve_krusell_smith(SMALL, closure="histogram", **kw)
        # Same economics: coefficients within a few percent of each other.
        np.testing.assert_allclose(hist.B, panel.B, atol=0.05)
        # No sampling noise: near-perfect regression fit.
        assert float(np.min(hist.r2)) > 0.9999
        assert float(np.min(hist.r2)) >= float(np.min(panel.r2))
        assert hist.mu is not None and hist.mu.shape == (2, SMALL.k_size)
        assert hist.k_population.size == 0

    def test_dispatch_routes_distribution_aggregation(self, tmp_path):
        from aiyagari_tpu import solve
        from aiyagari_tpu.io_utils.report import krusell_smith_report

        res = solve(SMALL, method="vfi", solver=SOLVER_VFI,
                    alm=ALMConfig(T=120, population=100, discard=20, max_iter=1, seed=1),
                    aggregation="distribution")
        assert res.mu is not None
        assert res.r2[0] > 0.99
        # The report consumes the histogram form (weighted stats, no panel).
        summary = krusell_smith_report(res, tmp_path, discard=20)
        assert 0.0 <= summary["wealth_gini"] <= 1.0
        assert (tmp_path / "wealth_cross_section.png").exists()

    def test_dispatch_rejects_numpy_backend_for_distribution(self):
        from aiyagari_tpu import solve

        with pytest.raises(ValueError, match="backend"):
            solve(SMALL, aggregation="distribution", backend="numpy")

    def test_unknown_closure_rejected(self):
        with pytest.raises(ValueError, match="closure"):
            solve_krusell_smith(SMALL, closure="exact")

    def test_histogram_closure_with_egm_method(self):
        # The closure is orthogonal to the household-solver method.
        res = solve_krusell_smith(
            SMALL, method="egm", solver=SOLVER_EGM,
            alm=ALMConfig(T=120, population=100, discard=20, max_iter=1, seed=1),
            closure="histogram",
        )
        assert res.mu is not None
        assert float(np.min(res.r2)) > 0.999


@pytest.mark.slow
class TestKSIntegration:
    @pytest.fixture(scope="class")
    def vfi_result(self):
        return solve_krusell_smith(SMALL, method="vfi", solver=SOLVER_VFI, alm=ALM_SMALL)

    @pytest.fixture(scope="class")
    def egm_result(self):
        return solve_krusell_smith(SMALL, method="egm", solver=SOLVER_EGM, alm=ALM_SMALL)

    def test_alm_fit_quality(self, vfi_result):
        assert vfi_result.r2[0] > 0.99
        assert vfi_result.r2[1] > 0.99

    def test_alm_coefficients_sane(self, vfi_result):
        B = vfi_result.B
        assert 0.0 < B[1] < 1.0 and 0.0 < B[3] < 1.0  # mean-reverting
        assert B[0] > 0.0 and B[2] > 0.0
        # Good-state intercept above bad-state (higher TFP -> more saving).
        assert B[0] > B[2]

    def test_capital_path_in_range(self, vfi_result):
        K = vfi_result.K_ts[ALM_SMALL.discard:]
        assert K.min() > 20.0 and K.max() < 60.0

    def test_methods_agree(self, vfi_result, egm_result):
        assert np.abs(vfi_result.B - egm_result.B).max() < 0.05
        assert egm_result.r2.min() > 0.99

    def test_policy_monotone(self, vfi_result):
        k_opt = np.asarray(vfi_result.solution.k_opt)
        assert (np.diff(k_opt, axis=-1) >= -1e-6).all()


class TestALMConvergence:
    @pytest.mark.slow
    def test_alm_reaches_reference_tolerance_end_to_end(self):
        """The ALM fixed point must actually reach the reference's 1e-6
        coefficient tolerance (Krusell_Smith_VFI.m:11-12) — in f64; the f32
        pipeline limit-cycles at diff_B ~ 5e-2 (BENCHMARKS.md). Reduced
        scale (40-pt grid, 300-period/1000-agent panel) so the fixed point
        resolves in ~10 s; the iteration count matches the reference-scale
        run (38), so the dynamics are representative."""
        from aiyagari_tpu import solve as _solve

        res = _solve(
            KrusellSmithConfig(k_size=40),
            method="vfi",
            alm=ALMConfig(T=300, population=1000, discard=50, max_iter=100, seed=0),
        )
        assert res.converged
        assert res.diff_B < 1e-6
        assert res.iterations <= 60
        assert min(float(res.r2[0]), float(res.r2[1])) > 0.99
        # Forecast rule in the reference's ballpark: persistent, stable.
        B = [float(b) for b in res.B]
        assert 0.8 < B[1] < 1.0 and 0.8 < B[3] < 1.0
        assert res.solution.k_opt.dtype == jnp.float64

    @pytest.mark.slow
    def test_anderson_acceleration_matches_damped_with_fewer_rounds(self):
        """alm.acceleration='anderson' must reach the same fixed point as the
        reference's damped update — each outer round is a full household
        solve + simulation + regression, so fewer rounds is the whole
        point — and never more rounds than damping at this scale."""
        from aiyagari_tpu import solve as _solve

        kw = dict(method="vfi")
        alm_kw = dict(T=300, population=1000, discard=50, max_iter=100, seed=0)
        damped = _solve(KrusellSmithConfig(k_size=40),
                        alm=ALMConfig(**alm_kw), **kw)
        anderson = _solve(KrusellSmithConfig(k_size=40),
                          alm=ALMConfig(acceleration="anderson", **alm_kw), **kw)
        assert anderson.converged
        assert anderson.diff_B < 1e-6
        np.testing.assert_allclose(anderson.B, damped.B, atol=1e-4)
        assert anderson.iterations <= damped.iterations
        # The acceleration must actually accelerate at this representative
        # scale, not merely not hurt.
        assert anderson.iterations <= int(0.7 * damped.iterations)

    @pytest.mark.slow
    def test_mixed_precision_reaches_reference_tolerance(self):
        """dtype='mixed' (f64 household solve + regression, f32 cross-section
        scan — the dtype split measured fastest on TPU, equilibrium/alm.py
        design note) must reach the reference's 1e-6 ALM tolerance and the
        same coefficients as the plain f64 pipeline. The f32 simulation must
        carry the run (no silent fallback to the f64 sim), otherwise 'mixed'
        is just f64 with extra steps."""
        from aiyagari_tpu.config import BackendConfig
        from aiyagari_tpu.equilibrium.alm import solve_krusell_smith

        cfg = KrusellSmithConfig(k_size=40)
        alm = ALMConfig(T=300, population=1000, discard=50, max_iter=100, seed=0)
        f64 = solve_krusell_smith(cfg, method="vfi", alm=alm,
                                  backend=BackendConfig(dtype="float64"),
                                  closure="histogram")
        mixed = solve_krusell_smith(cfg, method="vfi", alm=alm,
                                    backend=BackendConfig(dtype="mixed"),
                                    closure="histogram")
        assert mixed.converged and mixed.diff_B < 1e-6
        np.testing.assert_allclose(mixed.B, f64.B, atol=1e-3)
        assert all(r["house_dtype"] == "float64" for r in mixed.per_iteration)
        n32 = sum(1 for r in mixed.per_iteration if r["sim_dtype"] == "float32")
        assert n32 == mixed.iterations   # f32 sim carried every round
        assert mixed.solution.k_opt.dtype == jnp.float64

    def test_mixed_routes_aiyagari_to_the_ladder(self):
        # dtype="mixed" used to be rejected for the Aiyagari family; since
        # the mixed-precision solve ladder (ops/precision.py) it ROUTES:
        # dispatch injects the default ladder into SolverConfig.ladder and
        # the solve runs f32 hot sweeps + f64 polish. Routing (not the
        # numerics — tests/test_precision_ladder.py owns those) is pinned
        # here; the numpy backend still rejects loudly (no ladder there).
        from aiyagari_tpu import solve as _solve
        from aiyagari_tpu.config import (
            AiyagariConfig,
            BackendConfig,
            EquilibriumConfig,
            GridSpecConfig,
        )

        res = _solve(AiyagariConfig(grid=GridSpecConfig(n_points=60)),
                     method="egm", backend=BackendConfig(dtype="mixed"),
                     equilibrium=EquilibriumConfig(max_iter=2, tol=1e-3),
                     aggregation="distribution", on_nonconvergence="ignore")
        assert res.solution.policy_c.dtype == jnp.float64
        assert int(res.solution.hot_iterations) > 0
        with pytest.raises(ValueError, match="backend='jax'"):
            _solve(AiyagariConfig(),
                   backend=BackendConfig(backend="numpy", dtype="mixed"))

    def test_unknown_dtype_rejected(self):
        from aiyagari_tpu.config import BackendConfig
        from aiyagari_tpu.equilibrium.alm import solve_krusell_smith

        with pytest.raises(ValueError, match="dtype"):
            solve_krusell_smith(
                KrusellSmithConfig(k_size=10),
                alm=ALMConfig(T=50, population=50),
                backend=BackendConfig(dtype="bfloat16"),
            )

    def test_unknown_acceleration_rejected(self):
        from aiyagari_tpu.equilibrium.alm import solve_krusell_smith

        with pytest.raises(ValueError, match="acceleration"):
            solve_krusell_smith(
                KrusellSmithConfig(k_size=10),
                alm=ALMConfig(T=50, population=50, acceleration="nesterov"),
            )
