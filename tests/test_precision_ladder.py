"""Mixed-precision solve ladder (ISSUE 4, ops/precision.py): dtype
preservation in the hot stages, error-controlled switch mechanics, and
parity of `dtype="mixed"` solves against the pure-f64 reference.

Three contracts pinned here:

  1. DTYPE PRESERVATION — a hot (f32) stage's carries, interp outputs, and
     acceleration history buffers stay f32 end to end: the classic JAX
     weak-type scalar-promotion leak would silently upcast the whole loop
     to f64 and the "mixed" solve would quietly pay full-precision
     bandwidth. Exercised for the single-device, sharded, and labor EGM
     variants plus VFI and the accel ring buffers, via single-stage
     ("float32",) ladders whose outputs are directly inspectable.
  2. SWITCH MECHANICS — the ladder actually ladders: the f32 stage runs a
     positive number of sweeps, STOPS before the pure-f64 solve's total
     (it exits at the f32 noise floor, not at tol), and hands a positive
     residual to a polish stage that runs to the reference criterion.
  3. PARITY — final policies/values/distributions from dtype="mixed" sit
     within the stopping-rule noise cone of the pure-f64 solve (the
     test_precision noise-cone bound: both iterates are within their own
     tolerance of the fixed point, amplified by 1/(1-beta)), the
     distribution's mass error after the f64 polish is < 1e-12, and the
     GE/transition dispatch routes land on the f64 equilibrium.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.config import AccelConfig, PrecisionLadderConfig, SolverConfig
from aiyagari_tpu.models.aiyagari import aiyagari_labor_preset, aiyagari_preset
from aiyagari_tpu.ops.precision import (
    default_ladder,
    hot_only,
    plan_stages,
    require_x64,
    stage_specs,
    validate_ladder,
)
from aiyagari_tpu.solvers.egm import (
    initial_consumption_guess,
    solve_aiyagari_egm,
    solve_aiyagari_egm_labor,
)
from aiyagari_tpu.utils.firm import wage_from_r

TOL = 1e-6   # below the f32 switch floor at these calibrations, so the hot
             # stage exits at its noise floor and the polish has real work —
             # the regime the ladder exists for.

F32_ONLY = PrecisionLadderConfig(stage_dtypes=("float32",),
                                 matmul_precision=("default",))


def _problem(n=160):
    m = aiyagari_preset(grid_size=n, dtype=jnp.float64)
    w = float(wage_from_r(0.04, m.config.technology.alpha,
                          m.config.technology.delta))
    C0 = initial_consumption_guess(m.a_grid, m.s, 0.04, w)
    kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
              tol=TOL, max_iter=3000)
    return m, w, C0, kw


@pytest.fixture(scope="module")
def egm_pair():
    m, w, C0, kw = _problem()
    plain = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.04, w, m.amin, **kw)
    mixed = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.04, w, m.amin,
                               ladder=default_ladder(), **kw)
    return m, plain, mixed


class TestDtypePreservation:
    """Single-stage f32 ladders: every float output must come back f32 —
    a weak-type f64 leak anywhere in the loop body would surface here."""

    def _assert_f32(self, sol):
        for name in ("policy_c", "policy_k", "policy_l", "distance",
                     "tol_effective"):
            leaf = getattr(sol, name)
            assert leaf.dtype == jnp.float32, f"{name} upcast to {leaf.dtype}"

    def test_egm_hot_stage_stays_f32(self):
        m, w, C0, kw = _problem(120)
        sol = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.04, w, m.amin,
                                 ladder=F32_ONLY, **kw)
        self._assert_f32(sol)
        assert float(sol.distance) < TOL * 20   # converged near its floor

    def test_egm_labor_hot_stage_stays_f32(self):
        ml = aiyagari_labor_preset(grid_size=100, dtype=jnp.float64)
        wl = float(wage_from_r(0.04, ml.config.technology.alpha,
                               ml.config.technology.delta))
        C0 = initial_consumption_guess(ml.a_grid, ml.s, 0.04, wl)
        p = ml.preferences
        sol = solve_aiyagari_egm_labor(
            C0, ml.a_grid, ml.s, ml.P, 0.04, wl, ml.amin,
            sigma=p.sigma, beta=p.beta, psi=p.psi, eta=p.eta,
            tol=TOL, max_iter=2000, ladder=F32_ONLY)
        self._assert_f32(sol)

    def test_egm_sharded_hot_stage_stays_f32(self):
        # Dtype preservation is per-sweep, so a handful of capped sweeps on
        # the 8-virtual-device mesh pins it without a full converged solve.
        from aiyagari_tpu.parallel.mesh import make_mesh
        from aiyagari_tpu.solvers.egm_sharded import solve_aiyagari_egm_sharded

        m, w, C0, kw = _problem(4096)
        kw = dict(kw, max_iter=5, grid_power=float(m.config.grid.power))
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_sharded(mesh, C0, m.a_grid, m.s, m.P,
                                         0.04, w, m.amin, ladder=F32_ONLY,
                                         **kw)
        self._assert_f32(sol)

    def test_vfi_hot_stage_stays_f32(self):
        from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi

        m, w, _, kw = _problem(120)
        v0 = jnp.zeros((m.P.shape[0], 120))
        sol = solve_aiyagari_vfi(v0, m.a_grid, m.s, m.P, 0.04, w,
                                 ladder=F32_ONLY, **kw)
        assert sol.v.dtype == jnp.float32
        self._assert_f32(sol)

    def test_interp_outputs_stay_f32(self):
        # The EGM kernel itself (expectation matmul + inversion interp,
        # ops/interp.py): f32 in -> f32 out, on both inversion routes.
        from aiyagari_tpu.ops.egm import egm_step

        m, w, C0, _ = _problem(120)
        C32 = C0.astype(jnp.float32)
        for gp in (0.0, float(m.config.grid.power)):
            C_new, pk = egm_step(
                C32, m.a_grid.astype(jnp.float32), m.s.astype(jnp.float32),
                m.P.astype(jnp.float32), jnp.float32(0.04), jnp.float32(w),
                jnp.float32(m.amin), sigma=jnp.float32(5.0),
                beta=jnp.float32(0.96), grid_power=gp,
                matmul_precision="default")
            assert C_new.dtype == jnp.float32, f"grid_power={gp}"
            assert pk.dtype == jnp.float32, f"grid_power={gp}"

    def test_accel_history_stays_f32(self):
        # The acceleration ring buffers must live at the stage dtype — an
        # upcast history would both waste the hot stage's bandwidth saving
        # and smuggle f64 into the extrapolated carry.
        from aiyagari_tpu.ops.accel import accel_init, accel_step

        accel = AccelConfig(delay=0)
        x = jnp.linspace(1.0, 2.0, 64, dtype=jnp.float32)
        st = accel_init(x, accel)
        assert st.hist_x.dtype == jnp.float32
        assert st.hist_g.dtype == jnp.float32
        assert st.prev_res.dtype == jnp.float32
        for _ in range(3):
            gx = 0.5 * x + 0.25
            x, st = accel_step(st, x, gx, accel=accel)
        assert x.dtype == jnp.float32
        assert st.hist_x.dtype == jnp.float32
        assert st.hist_g.dtype == jnp.float32
        assert st.prev_res.dtype == jnp.float32

    def test_accelerated_egm_ladder_carries_stay_f32(self):
        # accel + single-stage f32 ladder composed: the solver's own loop
        # (accel_step inside the while_loop body) must not upcast either.
        m, w, C0, kw = _problem(120)
        sol = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.04, w, m.amin,
                                 ladder=F32_ONLY, accel=AccelConfig(), **kw)
        self._assert_f32(sol)


class TestSwitchMechanics:
    def test_switch_fires_and_polish_runs(self, egm_pair):
        _, plain, mixed = egm_pair
        hot = int(mixed.hot_iterations)
        total = int(mixed.iterations)
        assert hot > 0, "f32 stage never ran"
        assert hot < int(plain.iterations), (
            "f32 stage ran to the full f64 sweep count — the noise-floor "
            "switch never fired")
        assert total > hot, "f64 polish never ran"
        assert float(mixed.switch_distance) > TOL
        assert float(mixed.distance) < TOL
        assert mixed.policy_c.dtype == jnp.float64

    def test_distribution_switch_and_mass(self, egm_pair):
        from aiyagari_tpu.sim.distribution import stationary_distribution

        m, plain, _ = egm_pair
        dtol = 1e-11
        p64 = stationary_distribution(plain.policy_k, m.a_grid, m.P,
                                      tol=dtol, max_iter=50_000)
        mix = stationary_distribution(plain.policy_k, m.a_grid, m.P,
                                      tol=dtol, max_iter=50_000,
                                      ladder=default_ladder())
        assert int(mix.hot_iterations) > 0
        assert int(mix.iterations) > int(mix.hot_iterations)
        assert int(mix.hot_iterations) < int(p64.iterations)
        assert float(mix.distance) < dtol
        assert mix.mu.dtype == jnp.float64
        # Mass conservation after the f64 polish: the satellite's < 1e-12.
        assert abs(float(jnp.sum(mix.mu)) - 1.0) < 1e-12
        assert float(jnp.max(jnp.abs(mix.mu - p64.mu))) < 1e-9

    @pytest.mark.slow  # ~13 s: switch mechanics are pinned by the cheap
    # egm_pair tests above; the multiscale composition runs in every ci
    # battery (--metric scale) and the accel wiring's slow sibling.
    def test_multiscale_warm_stages_run_hot(self):
        # The multiscale ladder under "mixed": warm stages are f32 citizens
        # (hot-only), the final stage still polishes — so the final solution
        # is f64 with a fired switch.
        from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_multiscale

        m, w, _, kw = _problem(2048)
        sol = solve_aiyagari_egm_multiscale(
            m.a_grid, m.s, m.P, 0.04, w, m.amin,
            grid_power=float(m.config.grid.power), ladder=default_ladder(),
            **kw)
        assert sol.policy_c.dtype == jnp.float64
        assert int(sol.hot_iterations) > 0
        assert float(sol.distance) < TOL


class TestLadderParity:
    def test_egm_policy_parity(self, egm_pair):
        m, plain, mixed = egm_pair
        # Noise-cone bound (test_precision rationale): both solves stop
        # within their own tolerance of the same fixed point.
        bound = 2 * TOL / (1.0 - m.preferences.beta)
        gap = float(jnp.max(jnp.abs(mixed.policy_c - plain.policy_c)))
        assert gap < bound, f"policy gap {gap} vs noise-cone bound {bound}"

    def test_egm_labor_parity(self):
        ml = aiyagari_labor_preset(grid_size=100, dtype=jnp.float64)
        wl = float(wage_from_r(0.04, ml.config.technology.alpha,
                               ml.config.technology.delta))
        C0 = initial_consumption_guess(ml.a_grid, ml.s, 0.04, wl)
        p = ml.preferences
        kw = dict(sigma=p.sigma, beta=p.beta, psi=p.psi, eta=p.eta,
                  tol=TOL, max_iter=3000)
        plain = solve_aiyagari_egm_labor(C0, ml.a_grid, ml.s, ml.P, 0.04,
                                         wl, ml.amin, **kw)
        mixed = solve_aiyagari_egm_labor(C0, ml.a_grid, ml.s, ml.P, 0.04,
                                         wl, ml.amin,
                                         ladder=default_ladder(), **kw)
        bound = 2 * TOL / (1.0 - p.beta)
        assert float(jnp.max(jnp.abs(mixed.policy_c - plain.policy_c))) < bound
        assert float(jnp.max(jnp.abs(mixed.policy_l - plain.policy_l))) < bound

    def test_vfi_parity(self):
        from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi

        m, w, _, kw = _problem(120)
        v0 = jnp.zeros((m.P.shape[0], 120))
        plain = solve_aiyagari_vfi(v0, m.a_grid, m.s, m.P, 0.04, w, **kw)
        mixed = solve_aiyagari_vfi(v0, m.a_grid, m.s, m.P, 0.04, w,
                                   ladder=default_ladder(), **kw)
        assert int(mixed.hot_iterations) > 0
        bound = 2 * TOL / (1.0 - m.preferences.beta)
        assert float(jnp.max(jnp.abs(mixed.v - plain.v))) < bound
        # The discrete policy is exactly stable under the polish.
        assert int(jnp.max(jnp.abs(mixed.policy_idx - plain.policy_idx))) <= 1

    @pytest.mark.slow  # ~230 s: two full grid-4096 sharded solves on the
    # 8-virtual-device CPU mesh; the ladder's sharded wiring stays tier-1
    # via test_egm_sharded_hot_stage_stays_f32 + the dispatch parities.
    def test_sharded_parity(self):
        from aiyagari_tpu.parallel.mesh import make_mesh
        from aiyagari_tpu.solvers.egm_sharded import solve_aiyagari_egm_sharded

        m, w, C0, kw = _problem(4096)
        kw = dict(kw, grid_power=float(m.config.grid.power))
        mesh = make_mesh(("grid",))
        plain = solve_aiyagari_egm_sharded(mesh, C0, m.a_grid, m.s, m.P,
                                           0.04, w, m.amin, **kw)
        mixed = solve_aiyagari_egm_sharded(mesh, C0, m.a_grid, m.s, m.P,
                                           0.04, w, m.amin,
                                           ladder=default_ladder(), **kw)
        assert int(mixed.hot_iterations) > 0
        assert int(mixed.hot_iterations) < int(plain.iterations)
        bound = 2 * TOL / (1.0 - m.preferences.beta)
        assert float(jnp.max(jnp.abs(mixed.policy_c - plain.policy_c))) < bound

    def test_ge_dispatch_parity(self):
        # End-to-end dtype="mixed" through solve(): same bisection path as
        # the f64 reference (the excess-demand signs it sees are identical,
        # so the bracket walk — and therefore r — matches exactly).
        import aiyagari_tpu as at

        cfg = at.AiyagariConfig(grid=at.GridSpecConfig(n_points=100))
        eq = at.EquilibriumConfig(max_iter=8, tol=1e-3)
        f64 = at.solve(cfg, method="egm",
                       backend=at.BackendConfig(dtype="float64"),
                       equilibrium=eq, aggregation="distribution",
                       on_nonconvergence="ignore")
        mix = at.solve(cfg, method="egm",
                       backend=at.BackendConfig(dtype="mixed"),
                       equilibrium=eq, aggregation="distribution",
                       on_nonconvergence="ignore")
        assert abs(mix.r - f64.r) < 1e-8
        assert abs(mix.capital - f64.capital) < 1e-4

    def test_transition_dispatch_parity(self):
        import aiyagari_tpu as at

        cfg = at.AiyagariConfig(grid=at.GridSpecConfig(n_points=80))
        shock = at.MITShock(param="tfp", size=0.01, rho=0.8)
        tc = at.TransitionConfig(T=30, tol=1e-6, method="newton",
                                 max_iter=20)
        plain = at.solve_transition(cfg, shock, transition=tc,
                                    keep_policies=False)
        mixed = at.solve_transition(
            cfg, shock, transition=tc, keep_policies=False,
            backend=at.BackendConfig(dtype="mixed"),
            ss=plain.ss, jacobian=plain.jacobian)
        assert mixed.converged
        assert mixed.hot_rounds >= 1
        assert mixed.switch_excess > 0.0
        assert float(np.max(np.abs(mixed.r_path - plain.r_path))) < 1e-7


class TestConfigAndGuards:
    def test_validate_rejects_bad_configs(self):
        for bad in (
            PrecisionLadderConfig(stage_dtypes=()),
            PrecisionLadderConfig(stage_dtypes=("float16", "float64")),
            PrecisionLadderConfig(stage_dtypes=("float64", "float32"),
                                  matmul_precision=("default", "highest")),
            PrecisionLadderConfig(stage_dtypes=("float32", "float64"),
                                  matmul_precision=("default",)),
            PrecisionLadderConfig(matmul_precision=("bf16!", "highest")),
            PrecisionLadderConfig(switch_ulp=0.0),
        ):
            with pytest.raises(ValueError):
                validate_ladder(bad)

    def test_stage_plan_floors(self):
        specs = stage_specs(default_ladder(), noise_floor_ulp=4.0)
        assert [s.dtype for s in specs] == ["float32", "float64"]
        # Hot stage: the switch floor (>= the caller's); final: the caller's.
        assert specs[0].noise_floor_ulp == 24.0
        assert specs[1].noise_floor_ulp == 4.0
        assert specs[0].is_final is False and specs[1].is_final is True
        # plan_stages fallback: one final stage at the carry dtype.
        (only,) = plan_stages(None, jnp.float32, 7.0)
        assert only.dtype == "float32" and only.noise_floor_ulp == 7.0
        assert only.is_final

    def test_hot_only_truncation(self):
        h = hot_only(default_ladder())
        assert h.stage_dtypes == ("float32",)
        assert h.matmul_precision == ("default",)
        assert hot_only(None) is None
        assert hot_only(h) is h

    def test_require_x64_rejects_without_x64(self):
        enable_x64 = getattr(jax, "enable_x64", None)
        if enable_x64 is None:
            from jax.experimental import enable_x64
        with enable_x64(False):
            with pytest.raises(RuntimeError, match="x64"):
                require_x64(default_ladder())
            # A pure-f32 ladder needs no x64 and must pass.
            require_x64(F32_ONLY)

    def test_pallas_route_rejects_ladder(self):
        from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi

        m, w, _, kw = _problem(64)
        v0 = jnp.zeros((m.P.shape[0], 64))
        with pytest.raises(ValueError, match="Pallas"):
            solve_aiyagari_vfi(v0, m.a_grid, m.s, m.P, 0.04, w,
                               use_pallas=True, ladder=default_ladder(),
                               **dict(kw, sigma=5.0, beta=0.96))

    def test_numpy_backend_rejects_mixed(self):
        import aiyagari_tpu as at

        with pytest.raises(ValueError, match="backend='jax'"):
            at.solve(at.AiyagariConfig(), method="vfi",
                     backend=at.BackendConfig(backend="numpy", dtype="mixed"))

    def test_solver_config_carries_ladder(self):
        # The config object is frozen/hashable (jit-static) and reachable
        # from SolverConfig — the path every GE closure inherits it by.
        sv = SolverConfig(method="egm", ladder=default_ladder())
        hash(sv.ladder)
        assert dataclasses.replace(sv, ladder=None).ladder is None
