"""Fused Pallas windowed inversion vs the XLA route (ops/pallas_inverse.py).

Interpret-mode on CPU: the kernel's chunk-skip contributions are exact (not
approximated), so the two routes must agree exactly, escapes included."""

import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.ops.interp import inverse_interp_power_grid
from aiyagari_tpu.ops.pallas_inverse import inverse_interp_power_grid_pallas


def _grid(n, lo, hi, power):
    return lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power


class TestPallasWindowedInverse:
    @pytest.mark.parametrize("n_k,n_q", [(8192, 8192), (6000, 5000)])
    def test_matches_xla_route(self, n_k, n_q):
        lo, hi, power = 0.0, 52.0, 2.0
        gk = _grid(n_k, lo, hi, power)
        # A smooth monotone distortion of the grid — the EGM endogenous-grid
        # shape (knot density within the windows' 6x budget).
        x = np.sort((gk + 0.3 * np.sin(gk / 7.0) + 0.8) / 1.04 - 0.5)
        xq = jnp.asarray(np.stack([x, x * 1.01 + 0.05]))
        want, esc_want = inverse_interp_power_grid(xq, lo, hi, power, n_q,
                                                   with_escape=True)
        got, esc = inverse_interp_power_grid_pallas(xq, lo, hi, power, n_q,
                                                    interpret=True)
        assert bool(esc) == bool(esc_want) == False  # noqa: E712
        # The bracket data (cnt/x0/x1) is exact in both routes; the only
        # difference is 1-ulp FMA/ordering in the shared finish tail under
        # different fusion contexts. A genuine bracket error would be O(grid
        # step ~ 1e-2), far above this tolerance.
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=1e-9)

    def test_escape_on_oversaturated_window(self):
        # The kernel's 16,384-knot panels escape only when a query block's
        # bracket span exceeds them: 18,000 knots crammed inside one query
        # interval (smallest total size that leaves room for the cluster).
        n = 36_864
        lo, hi, power = 0.0, 52.0, 2.0
        gq = _grid(n, lo, hi, power)
        cluster = np.linspace(gq[9000], gq[9001], 18_000, endpoint=False)
        rest = gq[np.linspace(0, n - 1, n - 18_000).astype(int)]
        x = jnp.asarray(np.sort(np.concatenate([cluster, rest]))[:n])
        out, esc = inverse_interp_power_grid_pallas(x, lo, hi, power, n,
                                                    interpret=True)
        assert bool(esc)
        assert np.isnan(np.asarray(out)).all()

    def test_nonzero_panel_offsets_match_xla(self):
        # 24k knots: programs past the first panel use pan0 > 0 — the regime
        # an earlier hand-rolled-DMA kernel silently miscompiled in (module
        # docstring). Pins the data-dependent index_map path.
        n = 24_576
        lo, hi, power = 0.0, 52.0, 2.0
        gk = _grid(n, lo, hi, power)
        x = np.sort((gk + 0.3 * np.sin(gk / 7.0) + 0.8) / 1.04 - 0.5)
        want, esc_w = inverse_interp_power_grid(jnp.asarray(x), lo, hi, power,
                                                n, with_escape=True)
        got, esc = inverse_interp_power_grid_pallas(jnp.asarray(x), lo, hi,
                                                    power, n, interpret=True)
        assert not bool(esc) and not bool(esc_w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=1e-9)

    def test_wider_windows_solve_where_xla_escapes(self):
        # At 8k knots the kernel's window IS the whole array, so the
        # XLA-escaping clustered case is solved exactly instead (a strict
        # improvement; the escape contract is per-route, conservative).
        from aiyagari_tpu.ops.interp import linear_interp

        n = 8192
        lo, hi, power = 0.0, 52.0, 2.0
        gq = _grid(n, lo, hi, power)
        cluster = np.linspace(gq[3000], gq[3001], 5000, endpoint=False)
        rest = gq[np.linspace(0, n - 1, n - 5000).astype(int)]
        x = np.sort(np.concatenate([cluster, rest]))[:n]
        xla_out, xla_esc = inverse_interp_power_grid(jnp.asarray(x), lo, hi,
                                                     power, n, with_escape=True)
        assert bool(xla_esc)   # the 6-slab XLA windows saturate here
        out, esc = inverse_interp_power_grid_pallas(jnp.asarray(x), lo, hi,
                                                    power, n, interpret=True)
        assert not bool(esc)
        want = np.asarray(linear_interp(jnp.asarray(x), jnp.asarray(gq),
                                        jnp.asarray(gq)))
        # Exclude the cluster interval itself: inside a near-collided
        # segment the strict-< bracket and the generic route pick different
        # (equally valid) inverses, differing by less than the local query
        # spacing (ops/interp.inverse_interp_power_grid docstring).
        skip = (gq > x[-1]) | ((gq >= gq[3000]) & (gq <= gq[3001]))
        np.testing.assert_allclose(np.asarray(out)[~skip], want[~skip], atol=1e-9)

    def test_top_truncation_no_escape(self):
        # Knots end well below the top queries: the last window ends at the
        # top of the knot array, so cnt == L there is truncation, not escape.
        n_k = n_q = 8192
        lo, hi, power = 0.0, 52.0, 2.0
        x = jnp.asarray(_grid(n_k, lo, hi, power) * 0.6)
        want, esc_w = inverse_interp_power_grid(x, lo, hi, power, n_q,
                                                with_escape=True)
        got, esc = inverse_interp_power_grid_pallas(x, lo, hi, power, n_q,
                                                    interpret=True)
        assert not bool(esc) and not bool(esc_w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=1e-9)
