"""End-to-end distributed EGM (SURVEY.md §2.4(1)): the ring-redistribution
inversion (parallel/ring.py) composed into the full sharded fixed point
(solvers/egm_sharded.py), on the 8-virtual-device CPU mesh.

What these tests pin, in order of importance:
  1. the sharded solve's TRAJECTORY matches the unsharded windowed solver
     (iterate-by-iterate; sharding correctness is per-sweep, so bounded
     sweeps pin it as hard as full convergence does);
  2. a full CONVERGED solve agrees, stopping rule included;
  3. the compiled program never materializes a full-grid-sized array per
     device — no collective or temporary carries the whole knot row (the
     memory-scaling property GSPMD cannot deliver for this op, measured in
     test_sim_sharding.TestGridSharding);
  4. the escape contract: an undersized knot slab NaN-poisons and raises
     the flag, never returns silently wrong brackets.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.models.aiyagari import aiyagari_labor_preset, aiyagari_preset
from aiyagari_tpu.ops.interp import (
    interp_monotone_power_grid,
    inverse_interp_power_grid,
)
from aiyagari_tpu.parallel.mesh import make_mesh
from aiyagari_tpu.parallel.ring import (
    interp_monotone_power_grid_ring,
    inverse_interp_power_grid_ring,
    ring_buffer_size,
)
from aiyagari_tpu.solvers.egm import (
    initial_consumption_guess,
    solve_aiyagari_egm,
    solve_aiyagari_egm_labor,
)
from aiyagari_tpu.solvers.egm_sharded import (
    solve_aiyagari_egm_labor_sharded,
    solve_aiyagari_egm_sharded,
)
from aiyagari_tpu.utils.firm import wage_from_r


def _egm_problem(n):
    m = aiyagari_preset(grid_size=n)
    w = float(wage_from_r(0.04, m.config.technology.alpha,
                          m.config.technology.delta))
    C0 = initial_consumption_guess(m.a_grid, m.s, 0.04, w)
    kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
              tol=1e-6, max_iter=2000, grid_power=float(m.config.grid.power))
    return m, w, C0, kw


def _labor_problem(n):
    m = aiyagari_labor_preset(grid_size=n)
    w = float(wage_from_r(0.04, m.config.technology.alpha,
                          m.config.technology.delta))
    C0 = initial_consumption_guess(m.a_grid, m.s, 0.04, w)
    kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta,
              psi=m.preferences.psi, eta=m.preferences.eta,
              tol=1e-6, max_iter=2000, grid_power=float(m.config.grid.power))
    return m, w, C0, kw


class TestRingInversion:
    """The standalone ring kernel vs the single-device exact route."""

    def _lagged_knots(self, n, shift):
        # A value-space shift whose index lag at the power grid's dense
        # bottom is a large FRACTION of the grid — the regime that defeats
        # any one-hop halo (halo < shard; parallel/ring.py docstring) and
        # that the real EGM endogenous grids live in (measured 0.33*n).
        lo, hi, power = 0.0, 52.0, 2.0
        gk = lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power
        x = np.sort((gk + shift + 0.3 * np.sin(gk / 7.0)) / 1.04)
        return jnp.asarray(x), lo, hi, power

    def test_matches_unsharded_route_large_lag(self):
        n = 16_384
        x, lo, hi, power = self._lagged_knots(n, shift=-3.0)
        xq = jnp.stack([x, x * 1.01 + 0.05])
        mesh = make_mesh(("grid",))
        got, esc = inverse_interp_power_grid_ring(mesh, xq, lo, hi, power, n)
        want, esc_w = inverse_interp_power_grid(xq, lo, hi, power, n,
                                                with_escape=True)
        assert not bool(esc) and not bool(esc_w)
        # The bracket integers are identical; the float tail differs only by
        # XLA's per-program FMA contraction of the shared finish arithmetic
        # (measured 3e-14 at f64).
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=1e-12)

    def test_below_and_above_range_edges(self):
        # Knots shifted up (first queries below all knots) and compressed
        # (last queries above): sentinel positions must reproduce the
        # unsharded below-extrapolation and top-truncation exactly.
        n = 8_192
        lo, hi, power = 0.0, 52.0, 2.0
        gk = lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power
        x = jnp.asarray(gk * 0.9 + 0.5)
        mesh = make_mesh(("grid",))
        got, esc = inverse_interp_power_grid_ring(mesh, x, lo, hi, power, n)
        want = inverse_interp_power_grid(x, lo, hi, power, n)
        assert not bool(esc)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=1e-12)

    def test_escape_on_undersized_buffer(self):
        # All knots crowded into the top shard's value range: the receiving
        # device's slab overflows any capacity<D buffer — must escape (NaN +
        # flag), never return silently wrong brackets.
        n = 8_192
        lo, hi, power = 0.0, 52.0, 2.0
        x = jnp.asarray(np.linspace(0.97 * hi, 0.99 * hi, n))
        mesh = make_mesh(("grid",))
        out, esc = inverse_interp_power_grid_ring(mesh, x, lo, hi, power, n,
                                                  capacity=1.5)
        assert bool(esc)
        assert np.isnan(np.asarray(out)).all()

    def test_rejects_ragged_shapes(self):
        mesh = make_mesh(("grid",))
        with pytest.raises(ValueError, match="divide"):
            inverse_interp_power_grid_ring(mesh, jnp.zeros(1001), 0.0, 1.0,
                                           2.0, 1001)

    def test_rejects_unsound_slab_geometry(self):
        # 512 knots over 8 devices: the default-capacity slab (3,584 knots)
        # exceeds the padded knot row, the geometry ring_slab_fits exists to
        # catch — the public entry must refuse loudly, not silently
        # duplicate knot blocks (same contract as solve_aiyagari_egm_sharded).
        mesh = make_mesh(("grid",))
        with pytest.raises(ValueError, match="slab does not fit"):
            inverse_interp_power_grid_ring(mesh, jnp.zeros(512), 0.0, 1.0,
                                           2.0, 512)

    def test_buffer_size_is_static_and_bounded(self):
        # The memory claim: B = capacity*shard + one window of slack — O(n/D)
        # with the measured model constant, NOT the full row.
        n = 409_600
        B8 = ring_buffer_size(n, 8, 4.0)
        assert B8 % 512 == 0
        assert B8 == 4 * (n // 8) + 6 * 512
        assert B8 < n
        # The constant is per-DEVICE: at larger meshes the slab keeps
        # shrinking while GSPMD's re-materialized row would not.
        assert ring_buffer_size(n, 64, 4.0) <= n // 16 + 6 * 512


class TestRingValueInterp:
    """The ring-sharded monotone VALUE interpolation (the labor family's hot
    op) vs the single-device windowed kernel."""

    def _lagged_pairs(self, n, shift):
        # Same large-fraction bracket lag as TestRingInversion, plus a
        # monotone value row riding the knots (the stacked channel).
        lo, hi, power = 0.0, 52.0, 2.0
        gk = lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power
        x = np.sort((gk + shift + 0.3 * np.sin(gk / 7.0)) / 1.04)
        y = 3.0 * np.sqrt(x - x[0] + 0.1) + 0.05 * x
        return jnp.asarray(x), jnp.asarray(y), lo, hi, power

    def test_matches_unsharded_route_large_lag(self):
        n = 16_384
        x, y, lo, hi, power = self._lagged_pairs(n, shift=-3.0)
        xq = jnp.stack([x, x * 1.01 + 0.05])
        yq = jnp.stack([y, y * 1.02 + 0.1])
        mesh = make_mesh(("grid",))
        got, esc = interp_monotone_power_grid_ring(mesh, xq, yq, lo, hi,
                                                   power, n)
        want, esc_w = interp_monotone_power_grid(xq, yq, lo, hi, power, n,
                                                 with_escape=True)
        assert not bool(esc) and not bool(esc_w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=1e-12)

    def test_below_and_above_range_edges(self):
        # First queries below all knots (first-segment extrapolation from
        # the global head pair) and last queries above (nearest / last
        # value) must reproduce the unsharded edge semantics exactly.
        n = 8_192
        lo, hi, power = 0.0, 52.0, 2.0
        gk = lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power
        x = jnp.asarray(gk * 0.9 + 0.5)
        y = jnp.asarray(np.log1p(gk) + 2.0)
        mesh = make_mesh(("grid",))
        got, esc = interp_monotone_power_grid_ring(mesh, x, y, lo, hi,
                                                   power, n)
        want = interp_monotone_power_grid(x, y, lo, hi, power, n)
        assert not bool(esc)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=1e-12)

    def test_escape_on_undersized_buffer(self):
        n = 8_192
        lo, hi, power = 0.0, 52.0, 2.0
        x = jnp.asarray(np.linspace(0.97 * hi, 0.99 * hi, n))
        y = jnp.asarray(np.linspace(1.0, 2.0, n))
        mesh = make_mesh(("grid",))
        out, esc = interp_monotone_power_grid_ring(mesh, x, y, lo, hi, power,
                                                   n, capacity=1.5)
        assert bool(esc)
        assert np.isnan(np.asarray(out)).all()

    def test_rejects_bad_shapes(self):
        mesh = make_mesh(("grid",))
        with pytest.raises(ValueError, match="share a shape"):
            interp_monotone_power_grid_ring(mesh, jnp.zeros(8192),
                                            jnp.zeros(4096), 0.0, 1.0, 2.0,
                                            8192)
        with pytest.raises(ValueError, match="slab does not fit"):
            interp_monotone_power_grid_ring(mesh, jnp.zeros(512),
                                            jnp.zeros(512), 0.0, 1.0, 2.0,
                                            512)


class TestShardedLaborEGMSolver:
    """The labor-family distributed fixed point: ring-redistributed
    (knot, consumption) pairs (VERDICT round 3 #1 — the generalization of
    the exogenous-only round-3 capability)."""

    @pytest.mark.slow  # ~26 s: the labor ring composition stays tier-1 via
    # test_no_full_grid_crosses_devices (same sharded solve, one size down)
    # and TestShardedEGMSolver's exogenous trajectory pin.
    def test_trajectory_matches_unsharded(self):
        # Bounded-sweep trajectory equality at 8,192 points: per-sweep
        # agreement pins the sharded composition (ring value interp +
        # double cummax prefix + constrained region) as hard as full
        # convergence (TestShardedEGMSolver's rationale).
        n = 8_192
        m, w, C0, kw = _labor_problem(n)
        kw.update(tol=1e-30, max_iter=6)
        ref = solve_aiyagari_egm_labor(C0, m.a_grid, m.s, m.P, 0.04, w,
                                       m.amin, **kw)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_labor_sharded(mesh, C0, m.a_grid, m.s, m.P,
                                               0.04, w, m.amin, **kw)
        assert int(sol.iterations) == int(ref.iterations) == 6
        assert not bool(sol.escaped)
        np.testing.assert_allclose(np.asarray(sol.policy_c),
                                   np.asarray(ref.policy_c), atol=1e-12)
        np.testing.assert_allclose(np.asarray(sol.policy_k),
                                   np.asarray(ref.policy_k), atol=1e-12)
        np.testing.assert_allclose(np.asarray(sol.policy_l),
                                   np.asarray(ref.policy_l), atol=1e-12)

    @pytest.mark.slow
    def test_converged_solve_matches_unsharded(self):
        # Full fixed point from a coarse warm start, stopping rule included
        # (the labor mirror of TestShardedEGMSolver's converged test).
        from aiyagari_tpu.ops.interp import prolong_power_grid

        n = 6_144
        m, w, C0, kw = _labor_problem(n)
        kw.update(tol=1e-5)
        coarse = aiyagari_labor_preset(grid_size=512)
        Cc = initial_consumption_guess(coarse.a_grid, coarse.s, 0.04, w)
        kwc = dict(kw, grid_power=float(coarse.config.grid.power))
        sol_c = solve_aiyagari_egm_labor(Cc, coarse.a_grid, coarse.s,
                                         coarse.P, 0.04, w, coarse.amin,
                                         **kwc)
        C_warm = prolong_power_grid(sol_c.policy_c, float(m.a_grid[0]),
                                    float(m.a_grid[-1]), kw["grid_power"], n)
        ref = solve_aiyagari_egm_labor(C_warm, m.a_grid, m.s, m.P, 0.04, w,
                                       m.amin, **kw)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_labor_sharded(mesh, C_warm, m.a_grid, m.s,
                                               m.P, 0.04, w, m.amin, **kw)
        assert not bool(sol.escaped)
        assert float(sol.distance) < float(sol.tol_effective)
        assert int(sol.iterations) == int(ref.iterations)
        np.testing.assert_allclose(np.asarray(sol.policy_c),
                                   np.asarray(ref.policy_c), atol=1e-10)

    @pytest.mark.slow  # ~20 s: the exogenous variant below pins the same
    # no-full-grid jaxpr contract on the cheaper program; this one adds
    # only the stacked-channel labor shapes.
    def test_no_full_grid_crosses_devices(self):
        # The knots-resident assertion for the LABOR program: the ring
        # rotation's collective-permutes carry the stacked [2, N, na/D]
        # channels (2x the inversion's traffic, still O(na/D)); every
        # all-gather/all-reduce is O(D)-sized.
        n = 16_384
        m, w, C0, kw = _labor_problem(n)
        kw.update(tol=1e-30, max_iter=2)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_labor_sharded(mesh, C0, m.a_grid, m.s, m.P,
                                               0.04, w, m.amin, **kw)
        assert int(sol.iterations) == 2
        from aiyagari_tpu.solvers.egm_sharded import _EGM_LABOR_PROGRAMS

        (prog,) = [p for k, p in _EGM_LABOR_PROGRAMS.items() if n in k]
        C0_j = jnp.asarray(C0)
        hlo = prog.lower(
            C0_j, m.a_grid, m.s, m.P,
            jnp.asarray(0.04, C0_j.dtype), jnp.asarray(w, C0_j.dtype),
            jnp.asarray(m.amin, C0_j.dtype),
        ).compile().as_text()
        # Stacked (knot, value) channels: up to 2 * N * (n/8) per permute.
        shard_elems = 2 * 7 * (n // 8)
        seen = []
        for ln in hlo.splitlines():
            mm = re.search(r"= \w+\[([0-9,]*)\][^ ]* (all-gather|all-reduce|"
                           r"collective-permute)", ln)
            if mm:
                dims = [int(d) for d in mm.group(1).split(",") if d]
                seen.append((mm.group(2), dims))
        assert seen, "no collectives found — parsing broke or program changed"
        for op, dims in seen:
            elems = int(np.prod(dims)) if dims else 1
            if op == "collective-permute":
                assert elems <= shard_elems, (op, dims)
            else:
                assert elems <= 1024, (op, dims)
            assert elems < 7 * n, (op, dims)

    @pytest.mark.slow
    def test_escape_contract_on_undersized_slab(self):
        # capacity=0.0 degenerates the buffer to its floor (the same
        # geometry as the exogenous escape test — L must reach the
        # one-window floor, n = 24,576 at D=8); the labor solver must raise
        # the flag and NaN-poison, never silently mis-bracket.
        n = 24_576
        m, w, C0, kw = _labor_problem(n)
        kw.update(tol=1e-30, max_iter=2)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_labor_sharded(mesh, C0, m.a_grid, m.s, m.P,
                                               0.04, w, m.amin,
                                               capacity=0.0, **kw)
        assert bool(sol.escaped)
        assert np.isnan(np.asarray(sol.policy_c)).all()

    @pytest.mark.slow
    def test_mesh_household_route_matches_single_device(self):
        # The solve_household mesh branch for the LABOR family (the gate
        # dropped this round — VERDICT round 3 #1): labor-ladder warm start
        # + sharded labor fine solve equals the single-device route.
        from aiyagari_tpu.config import SolverConfig
        from aiyagari_tpu.equilibrium.bisection import solve_household

        n = 6_144
        m, w, C0, kw = _labor_problem(n)
        scfg = SolverConfig(method="egm", tol=1e-5, max_iter=2000)
        ref = solve_household(m, 0.04, solver=scfg)
        res = solve_household(m, 0.04, solver=scfg,
                              mesh=make_mesh(("grid",)))
        assert not bool(res.escaped)
        np.testing.assert_allclose(np.asarray(res.policy_c),
                                   np.asarray(ref.policy_c), atol=5e-5)
        np.testing.assert_allclose(np.asarray(res.policy_l),
                                   np.asarray(ref.policy_l), atol=5e-5)

    def test_rejects_bad_arguments(self):
        m, w, C0, kw = _labor_problem(1024)
        mesh = make_mesh(("grid",))
        kw["grid_power"] = 0.0
        with pytest.raises(ValueError, match="power-spaced"):
            solve_aiyagari_egm_labor_sharded(mesh, C0, m.a_grid, m.s, m.P,
                                             0.04, w, m.amin, **kw)
        m2, w2, C02, kw2 = _labor_problem(512)
        with pytest.raises(ValueError, match="too small"):
            solve_aiyagari_egm_labor_sharded(mesh, C02, m2.a_grid, m2.s,
                                             m2.P, 0.04, w2, m2.amin, **kw2)


class TestShardedEGMSolver:
    def test_trajectory_matches_unsharded(self):
        # Bounded-sweep trajectory equality at 8,192 points (windowed
        # regime; per-sweep agreement pins the composition as hard as full
        # convergence, cf. TestGridSharding's rationale).
        n = 8_192
        m, w, C0, kw = _egm_problem(n)
        kw.update(tol=1e-30, max_iter=6)
        ref = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.04, w, m.amin, **kw)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_sharded(mesh, C0, m.a_grid, m.s, m.P, 0.04,
                                         w, m.amin, **kw)
        assert int(sol.iterations) == int(ref.iterations) == 6
        assert not bool(sol.escaped)
        # Only the Euler matmul's shard-shape reassociation separates the
        # two (the bracket/cummax arithmetic is exact; solver docstring).
        np.testing.assert_allclose(np.asarray(sol.policy_c),
                                   np.asarray(ref.policy_c), atol=1e-12)
        np.testing.assert_allclose(np.asarray(sol.policy_k),
                                   np.asarray(ref.policy_k), atol=1e-12)

    @pytest.mark.slow
    def test_trajectory_matches_unsharded_at_scale(self):
        # The 100k+-point composition the blueprint demands (VERDICT round 2
        # #1): 102,400 points, 12,800-knot shards on the 8-device mesh vs
        # the single-device windowed solver. ONE sweep: per-sweep equality
        # is the sharding claim (multi-sweep dynamics are pinned at 8,192
        # above), and each extra sweep costs ~2.5 min of one-core CPU here.
        n = 102_400
        m, w, C0, kw = _egm_problem(n)
        kw.update(tol=1e-30, max_iter=1)
        ref = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.04, w, m.amin, **kw)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_sharded(mesh, C0, m.a_grid, m.s, m.P, 0.04,
                                         w, m.amin, **kw)
        assert int(sol.iterations) == 1 and not bool(sol.escaped)
        np.testing.assert_allclose(np.asarray(sol.policy_c),
                                   np.asarray(ref.policy_c), atol=1e-11)

    @pytest.mark.slow
    def test_converged_solve_matches_unsharded(self):
        # Full fixed point, stopping rule included, from the multiscale warm
        # start (a cold 8k fixed point is ~300 sweeps; the warm start cuts
        # it to a handful without changing the fixed point).
        from aiyagari_tpu.ops.interp import prolong_power_grid

        n = 6_144   # windowed regime; sized for the one-core CPU budget
        m, w, C0, kw = _egm_problem(n)
        kw.update(tol=1e-5)
        coarse = aiyagari_preset(grid_size=512)
        Cc = initial_consumption_guess(coarse.a_grid, coarse.s, 0.04, w)
        kwc = dict(kw, grid_power=float(coarse.config.grid.power))
        sol_c = solve_aiyagari_egm(Cc, coarse.a_grid, coarse.s, coarse.P,
                                   0.04, w, coarse.amin, **kwc)
        C_warm = prolong_power_grid(sol_c.policy_c, float(m.a_grid[0]),
                                    float(m.a_grid[-1]), kw["grid_power"], n)
        ref = solve_aiyagari_egm(C_warm, m.a_grid, m.s, m.P, 0.04, w,
                                 m.amin, **kw)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_sharded(mesh, C_warm, m.a_grid, m.s, m.P,
                                         0.04, w, m.amin, **kw)
        assert not bool(sol.escaped)
        assert float(sol.distance) < float(sol.tol_effective)
        assert int(sol.iterations) == int(ref.iterations)
        np.testing.assert_allclose(np.asarray(sol.policy_c),
                                   np.asarray(ref.policy_c), atol=1e-10)

    def test_no_full_grid_crosses_devices(self):
        # The knots-resident assertion (VERDICT round 2 #1): in the compiled
        # SPMD module of the sharded solve, NO collective moves or rebuilds
        # anything full-grid-sized. The ring rotation's collective-permutes
        # carry exactly one [N, na/D] shard; every all-gather/all-reduce is
        # O(D)-sized (cummax tails, head pairs, bracket starts, sup-norms).
        # This is precisely what GSPMD could not do for this op — it
        # re-gathered the whole knot row per device
        # (test_sim_sharding.TestGridSharding).
        n = 16_384
        m, w, C0, kw = _egm_problem(n)
        kw.update(tol=1e-30, max_iter=2)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_sharded(mesh, C0, m.a_grid, m.s, m.P, 0.04,
                                         w, m.amin, **kw)
        assert int(sol.iterations) == 2
        from aiyagari_tpu.solvers.egm_sharded import _EGM_PROGRAMS

        (prog,) = [p for k, p in _EGM_PROGRAMS.items() if n in k]
        C0_j = jnp.asarray(C0)
        hlo = prog.lower(
            C0_j, m.a_grid, m.s, m.P,
            jnp.asarray(0.04, C0_j.dtype), jnp.asarray(w, C0_j.dtype),
            jnp.asarray(m.amin, C0_j.dtype),
        ).compile().as_text()
        shard_elems = 7 * (n // 8)
        seen = []
        for ln in hlo.splitlines():
            mm = re.search(r"= \w+\[([0-9,]*)\][^ ]* (all-gather|all-reduce|"
                           r"collective-permute)", ln)
            if mm:
                dims = [int(d) for d in mm.group(1).split(",") if d]
                seen.append((mm.group(2), dims))
        assert seen, "no collectives found — parsing broke or program changed"
        for op, dims in seen:
            elems = int(np.prod(dims)) if dims else 1
            if op == "collective-permute":
                assert elems <= shard_elems, (op, dims)
            else:
                assert elems <= 1024, (op, dims)
            assert elems < 7 * n, (op, dims)

    @pytest.mark.slow
    def test_escape_contract_on_undersized_slab(self):
        # Undersized slab: capacity=0.0 degenerates the buffer to its floor
        # of exactly one shard (B = L), below the measured 1.11L slab
        # requirement of the real EGM endogenous grids at their FIRST sweep
        # (the per-sweep capped-need profile starts at its 1.111L maximum)
        # — the solver must raise the flag and NaN-poison, never return
        # silently wrong brackets. Smallest geometry where the B = L floor
        # binds (the escape precondition): L = n/8 must reach the one-window
        # floor M*KB = 3,072, i.e. n = 24,576 — the claim is L-relative, so
        # larger grids add compile time, not coverage (was 40,960).
        n = 24_576
        m, w, C0, kw = _egm_problem(n)
        kw.update(tol=1e-30, max_iter=2)
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_sharded(mesh, C0, m.a_grid, m.s, m.P, 0.04,
                                         w, m.amin, capacity=0.0, **kw)
        assert bool(sol.escaped)
        assert np.isnan(np.asarray(sol.policy_c)).all()

    @pytest.mark.slow
    def test_mesh_household_route_matches_single_device(self):
        # The solve_household mesh branch (the BackendConfig.mesh_axes
        # routing target): coarse-ladder warm start + sharded fine solve
        # equals the single-device solve at 6,144 points — the smallest
        # windowed-regime grid whose ring slab is sound at D=8. (A full GE
        # bisection through this route measured ~30 min of one-core CPU —
        # per-iteration fine solves — so the dispatch plumbing above it is
        # pinned by the cheap small-grid at.solve smoke below instead.)
        from aiyagari_tpu.config import SolverConfig
        from aiyagari_tpu.equilibrium.bisection import solve_household

        n = 6_144
        m, w, C0, kw = _egm_problem(n)
        scfg = SolverConfig(method="egm", tol=1e-5, max_iter=2000)
        ref = solve_household(m, 0.04, solver=scfg)
        res = solve_household(m, 0.04, solver=scfg,
                              mesh=make_mesh(("grid",)))
        assert not bool(res.escaped)
        np.testing.assert_allclose(np.asarray(res.policy_c),
                                   np.asarray(ref.policy_c), atol=5e-5)

    @pytest.mark.slow
    def test_mesh_equilibrium_bisection_matches_single_device(self, tmp_path):
        # The full GE composition through the mesh route (VERDICT round 3
        # #6): solve_equilibrium_distribution -> solve_household(mesh) ->
        # ladder warm start (first solve) -> warm-started sharded re-solves
        # at each midpoint — PLUS the sharded-representation checkpointing
        # (VERDICT round 3 #7): the run is interrupted mid-bisection, the
        # checkpoint is verified to hold the warm start PER SHARD (no
        # full-array entry ever materialized on host), and the resumed run
        # restores it shard-by-shard and finishes identically. Runs on the
        # full 8-device mesh at 6,144 points — the same (mesh, na, tol,
        # max_iter) program geometry as test_converged_solve_matches_
        # unsharded, so the sharded compile is SHARED within a suite run
        # (a 4-device variant measured 36 min under load, mostly its extra
        # compile; D=2 never fits the slab at default capacity); 3
        # bisection iterations exercise the warm-start hand-off without
        # round 3's full-depth cost.
        from aiyagari_tpu.config import EquilibriumConfig, SolverConfig
        from aiyagari_tpu.equilibrium.bisection import (
            solve_equilibrium_distribution,
        )
        from aiyagari_tpu.io_utils.checkpoint import load_checkpoint

        n = 6_144
        m, w, C0, kw = _egm_problem(n)
        scfg = SolverConfig(method="egm", tol=1e-5, max_iter=2000)
        # 2 bisection midpoints and a looser distribution fixed point: the
        # composition claims (warm-start hand-off, identical bracket
        # decisions, per-shard checkpoint round trip) are count- and
        # dist-tol-independent, and each midpoint costs a full sharded
        # solve on the one-core mesh (this test measured 38 min of the
        # round-4 suite at max_iter=3 / dist 1e-10).
        eq = EquilibriumConfig(max_iter=2)
        dist_kw = dict(dist_tol=1e-8, dist_max_iter=3000)
        mesh8 = make_mesh(("grid",))
        ref = solve_equilibrium_distribution(m, solver=scfg, eq=eq, **dist_kw)

        class Stop(Exception):
            pass

        def interrupt(rec):
            # Fires BEFORE iteration 1's own save — the checkpoint on disk
            # is iteration 0's, so the resume re-runs iteration 1 from the
            # per-shard warm start.
            if rec["iteration"] == 1:
                raise Stop

        with pytest.raises(Stop):
            solve_equilibrium_distribution(m, solver=scfg, eq=eq, mesh=mesh8,
                                           on_iteration=interrupt,
                                           checkpoint_dir=tmp_path,
                                           **dist_kw)
        # The checkpoint holds the sharded warm start per shard: 8 shard
        # entries of [7, 768], and NO assembled full-grid entry.
        (ckpt,) = tmp_path.glob("*.npz")
        sc, arrays = load_checkpoint(ckpt)
        shard_keys = [k for k in arrays if k.startswith("warm__shard")]
        assert len(shard_keys) == 8 and "warm" not in arrays
        assert arrays["warm__shard0"].shape == (7, n // 8)
        res = solve_equilibrium_distribution(m, solver=scfg, eq=eq,
                                             mesh=mesh8,
                                             checkpoint_dir=tmp_path,
                                             **dist_kw)
        # The sharded solves differ from the single-device ones only by the
        # Euler matmul's reassociation (~1e-12 on f64 policies), so every
        # bisection decision — and hence the bracket path and r* — must be
        # identical, and the final policies agree far inside the solver tol.
        assert res.iterations == ref.iterations
        assert res.r == pytest.approx(ref.r, abs=1e-12)
        np.testing.assert_allclose(np.asarray(res.r_history),
                                   np.asarray(ref.r_history), atol=1e-12)
        np.testing.assert_allclose(np.asarray(res.solution.policy_c),
                                   np.asarray(ref.solution.policy_c),
                                   atol=1e-8)
        assert res.k_supply[-1] == pytest.approx(ref.k_supply[-1], abs=1e-8)

    def test_small_grid_mesh_request_degrades_to_single_device(self):
        # Below the slab-soundness bound the config-level mesh request must
        # silently use the single-device routes (solve_household's gate),
        # and the raw solver must refuse loudly.
        import aiyagari_tpu as at

        cfg = at.AiyagariConfig(grid=at.GridSpecConfig(n_points=512))
        res = at.solve(cfg, method="egm", aggregation="distribution",
                       backend=at.BackendConfig(mesh_axes=("grid",)),
                       equilibrium=at.EquilibriumConfig(max_iter=2))
        assert np.isfinite(res.r)
        m, w, C0, kw = _egm_problem(512)
        with pytest.raises(ValueError, match="too small"):
            solve_aiyagari_egm_sharded(make_mesh(("grid",)), C0, m.a_grid,
                                       m.s, m.P, 0.04, w, m.amin, **kw)

    def test_rejects_bad_arguments(self):
        m, w, C0, kw = _egm_problem(1002)
        mesh = make_mesh(("grid",))
        with pytest.raises(ValueError, match="divide"):
            solve_aiyagari_egm_sharded(mesh, C0, m.a_grid, m.s, m.P, 0.04,
                                       w, m.amin, **kw)
        m2, w2, C02, kw2 = _egm_problem(1024)
        kw2["grid_power"] = 0.0
        with pytest.raises(ValueError, match="power-spaced"):
            solve_aiyagari_egm_sharded(mesh, C02, m2.a_grid, m2.s, m2.P,
                                       0.04, w2, m2.amin, **kw2)
