"""Tier-1 gates for the resilience layer (ISSUE 10): failure sentinels,
the rescue ladder, scenario quarantine, and the fault-injection harness.

The contracts pinned here:

1. Sentinel verdicts are correct and STRUCTURED: nan / stall / explode /
   escape, per solver family, including per-lane verdicts under vmap and
   the sharded EGM shard_map program.
2. Zero-cost off path: a sentinel-off / faults-off solve traces to a
   program whose while-loop carries exactly as many leaves as before (the
   TelemetryConfig discipline), and its results are BITWISE identical to
   the sentinel-on solve on healthy inputs (the sentinel only reads).
3. The rescue ladder escalates deterministically, clears injected faults
   on rescue stages, emits its observability events, and raises a
   ConvergenceError carrying the full attempt history on exhaustion.
4. Scenario quarantine freezes exactly the diverged lanes; the surviving
   lanes are parity-equal to a clean sweep and to serial re-solves.
5. The non-finite-distance "nan" verdict of enforce_convergence is ALWAYS
   loud (warns under "ignore", overrides converged=True), and health
   reports flag nan residual trajectories.
"""

import dataclasses
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.config import (
    AiyagariConfig,
    BackendConfig,
    EquilibriumConfig,
    FaultPlan,
    GridSpecConfig,
    RescueConfig,
    SentinelConfig,
    SolverConfig,
    TransitionConfig,
)
from aiyagari_tpu.diagnostics.errors import ConvergenceError, ConvergenceWarning
from aiyagari_tpu.diagnostics.sentinel import (
    SentinelState,
    host_verdict,
    sentinel_init,
    sentinel_summary,
    sentinel_update,
    verdict_name,
)
from aiyagari_tpu.models.aiyagari import AiyagariModel, aiyagari_preset
from aiyagari_tpu.sim.distribution import stationary_distribution
from aiyagari_tpu.solvers.egm import (
    initial_consumption_guess,
    solve_aiyagari_egm,
)
from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi

SENT = SentinelConfig()


def _problem(n=40, r=0.02, w=1.2):
    m = aiyagari_preset(grid_size=n)
    C0 = initial_consumption_guess(m.a_grid, m.s, r, w)
    kw = dict(sigma=5.0, beta=0.96, tol=1e-6, max_iter=500)
    return m, C0, kw


# -- 1. sentinel mechanics --------------------------------------------------


class TestSentinelUnit:
    def test_config_validation(self):
        with pytest.raises(ValueError, match="stall_window"):
            sentinel_init(SentinelConfig(stall_window=1))
        with pytest.raises(ValueError, match="explode_factor"):
            sentinel_init(SentinelConfig(explode_factor=1.0))
        assert sentinel_init(None) is None

    def test_off_is_none_through_every_helper(self):
        assert sentinel_update(None, 1.0, config=None) is None
        assert sentinel_summary(None) is None
        assert host_verdict([1.0, float("nan")], None) == ""

    def test_nan_verdict(self):
        st = sentinel_init(SENT)
        st = sentinel_update(st, 1.0, config=SENT)
        st = sentinel_update(st, float("nan"), config=SENT)
        assert verdict_name(st.verdict) == "nan"

    def test_escape_verdict_splits_nan(self):
        st = sentinel_init(SENT)
        st = sentinel_update(st, float("nan"), config=SENT,
                             escaped=jnp.array(True))
        assert verdict_name(st.verdict) == "escape"

    def test_explode_verdict(self):
        st = sentinel_init(SENT)
        st = sentinel_update(st, 1.0, config=SENT)
        st = sentinel_update(st, 2e6 * 1.0, config=SENT)   # > factor * first
        assert verdict_name(st.verdict) == "explode"

    def test_stall_verdict_and_healthy_decay_does_not_trip(self):
        cfg = SentinelConfig(stall_window=10)
        # Healthy geometric decay: a new best every sweep, never stalls.
        st = sentinel_init(cfg)
        r = 1.0
        for _ in range(50):
            st = sentinel_update(st, r, config=cfg)
            r *= 0.99
        assert verdict_name(st.verdict) == "ok"
        # Flat residual: stalls after exactly stall_window sweeps.
        st = sentinel_init(cfg)
        for _ in range(12):
            st = sentinel_update(st, 1.0, config=cfg)
        assert verdict_name(st.verdict) == "stall"

    def test_verdict_is_sticky(self):
        st = sentinel_init(SENT)
        st = sentinel_update(st, float("nan"), config=SENT)
        st = sentinel_update(st, 0.5, config=SENT)   # recovery is too late
        assert verdict_name(st.verdict) == "nan"

    def test_summary_shape(self):
        st = sentinel_init(SENT)
        st = sentinel_update(st, 2.0, config=SENT)
        s = sentinel_summary(st)
        assert s["verdict"] == "ok" and s["sweeps_watched"] == 1
        assert s["first_residual"] == pytest.approx(2.0)

    def test_host_verdict(self):
        cfg = SentinelConfig(stall_window=5)
        assert host_verdict([], cfg) == ""
        assert host_verdict([1.0, 0.5, float("nan")], cfg) == "nan"
        assert host_verdict([1.0, 5e6], cfg) == "explode"
        assert host_verdict([1.0, 0.5] + [0.4] * 6, cfg) == "stall"
        assert host_verdict([2.0 * 0.9 ** k for k in range(30)], cfg) == ""


# -- 2. solver-level verdicts + zero-cost off path --------------------------


def _while_carry_arities(jaxpr_text: str):
    # Count the carry leaves of each while in the traced program by its
    # printed signature is brittle; instead re-walk the jaxpr object.
    raise NotImplementedError


def _while_carries(closed):
    """Carry arities of every while_loop reachable in a ClosedJaxpr."""
    from aiyagari_tpu.analysis.jaxpr_audit import walk_jaxpr

    out = []
    for eqn, _ in walk_jaxpr(closed.jaxpr):
        if eqn.primitive.name == "while":
            out.append(len(eqn.params["body_jaxpr"].jaxpr.outvars))
    return out


class TestSolverSentinels:
    def test_egm_nan_fault_early_exit_and_verdict(self):
        m, C0, kw = _problem()
        sol = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.02, 1.2, m.amin,
                                 sentinel=SENT, faults=FaultPlan(nan_sweep=3),
                                 **kw)
        assert verdict_name(sol.sentinel.verdict) == "nan"
        # The loop exited AT the poisoned sweep, not at max_iter.
        assert int(sol.iterations) == 4
        assert not np.isfinite(float(sol.distance))

    def test_egm_escape_fault_verdict(self):
        m, C0, kw = _problem()
        sol = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.02, 1.2, m.amin,
                                 sentinel=SENT,
                                 faults=FaultPlan(force_escape=True), **kw)
        assert verdict_name(sol.sentinel.verdict) == "escape"
        assert bool(sol.escaped)

    def test_vfi_nan_fault_verdict(self):
        m, _, _ = _problem()
        v0 = jnp.zeros((m.P.shape[0], m.a_grid.shape[0]), m.dtype)
        sol = solve_aiyagari_vfi(v0, m.a_grid, m.s, m.P, 0.02, 1.2,
                                 sigma=5.0, beta=0.96, tol=1e-6, max_iter=500,
                                 sentinel=SENT, faults=FaultPlan(nan_sweep=2))
        assert verdict_name(sol.sentinel.verdict) == "nan"
        assert int(sol.iterations) == 3

    def test_distribution_stall_early_exit_saves_sweeps(self):
        m, C0, kw = _problem()
        hh = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.02, 1.2, m.amin,
                                **kw)
        cap = 2000
        plain = stationary_distribution(hh.policy_k, m.a_grid, m.P,
                                        tol=1e-30, max_iter=cap)
        sent = stationary_distribution(hh.policy_k, m.a_grid, m.P,
                                       tol=1e-30, max_iter=cap,
                                       sentinel=SENT)
        assert int(plain.iterations) == cap            # burns the cap
        assert int(sent.iterations) < cap              # early-exits
        assert verdict_name(sent.sentinel.verdict) == "stall"

    def test_healthy_solve_verdict_ok_and_bitwise_equal_to_off(self):
        m, C0, kw = _problem()
        on = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.02, 1.2, m.amin,
                                sentinel=SENT, **kw)
        off = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.02, 1.2, m.amin,
                                 **kw)
        assert verdict_name(on.sentinel.verdict) == "ok"
        assert off.sentinel is None
        # The sentinel only READS the trajectory: iterates are bitwise
        # identical with it on or off.
        np.testing.assert_array_equal(np.asarray(on.policy_c),
                                      np.asarray(off.policy_c))
        assert int(on.iterations) == int(off.iterations)

    def test_off_path_carries_zero_extra_leaves(self):
        """The zero-cost pin: the sentinel-on while_loop carries exactly 5
        more leaves (the SentinelState scalars) than the sentinel-off one,
        and the off trace is byte-identical to a trace that never heard of
        the sentinel arguments (defaults)."""
        m, C0, kw = _problem(n=16)

        def run(sent, flt):
            return solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.02, 1.2,
                                      m.amin, sentinel=sent, faults=flt,
                                      **kw)

        off = jax.make_jaxpr(lambda: run(None, None))()
        on = jax.make_jaxpr(lambda: run(SENT, None))()
        default = jax.make_jaxpr(
            lambda: solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.02, 1.2,
                                       m.amin, **kw))()
        assert str(off) == str(default)
        c_off, c_on = _while_carries(off), _while_carries(on)
        assert len(c_off) == len(c_on) == 1
        assert c_on[0] == c_off[0] + 5

    def test_distribution_off_path_zero_extra_leaves(self):
        m, C0, kw = _problem(n=16)
        hh = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.02, 1.2, m.amin,
                                **kw)

        def run(sent):
            return stationary_distribution(hh.policy_k, m.a_grid, m.P,
                                           tol=1e-8, max_iter=100,
                                           sentinel=sent)

        c_off = _while_carries(jax.make_jaxpr(lambda: run(None))())
        c_on = _while_carries(jax.make_jaxpr(lambda: run(SENT))())
        assert c_on[0] == c_off[0] + 5

    def test_mixed_ladder_polish_not_falsely_stalled(self):
        """Review regression: the sentinel's best/since_best must RESTART
        at a precision-ladder stage boundary (sentinel_stage_reset) — the
        hot stage exits AT its noise floor, and carrying that `best` into
        the f64 polish would trip a false 'stall' on a healthy solve (the
        accel-history lesson). A tight stall window makes the false trip
        certain without the reset."""
        from aiyagari_tpu.ops.precision import ladder_for_dtype

        tight = SentinelConfig(stall_window=5)
        ladder = ladder_for_dtype("mixed")
        m, C0, kw = _problem()
        kw = dict(kw, tol=1e-9, max_iter=2000)
        sol = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.02, 1.2, m.amin,
                                 sentinel=tight, ladder=ladder, **kw)
        assert verdict_name(sol.sentinel.verdict) == "ok"
        assert float(sol.distance) < float(sol.tol_effective)
        assert int(sol.hot_iterations) > 0          # the ladder laddered
        # Same contract on the distribution's hot->polish ladder. Window
        # 10, not 5: the distribution trajectory's own f32-quantization
        # plateaus run up to 5 sweeps WITHIN a stage (measured), which a
        # 5-window legitimately calls a stall; the cross-stage carry this
        # test pins would accumulate a far longer non-improving run.
        hh = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.02, 1.2, m.amin,
                                **_problem()[2])
        d = stationary_distribution(hh.policy_k, m.a_grid, m.P, tol=1e-10,
                                    max_iter=10_000, ladder=ladder,
                                    sentinel=SentinelConfig(stall_window=10))
        assert verdict_name(d.sentinel.verdict) == "ok"
        assert float(d.distance) < 1e-10

    def test_stage_reset_keeps_verdict_sticky(self):
        from aiyagari_tpu.diagnostics.sentinel import sentinel_stage_reset

        st = sentinel_init(SENT)
        st = sentinel_update(st, float("nan"), config=SENT)
        st = sentinel_stage_reset(st)
        assert verdict_name(st.verdict) == "nan"    # a stage cannot launder
        assert sentinel_stage_reset(None) is None

    def test_vmap_per_lane_verdicts(self):
        """One poisoned lane (NaN warm start) in a vmapped batch: ITS
        verdict is nan, every other lane's is ok — the quarantine
        primitive the sweep machinery builds on."""
        m, C0, kw = _problem()
        C_b = jnp.stack([C0, jnp.full_like(C0, jnp.nan), C0])
        sols = jax.vmap(
            lambda C: solve_aiyagari_egm(C, m.a_grid, m.s, m.P, 0.02, 1.2,
                                         m.amin, sentinel=SENT, **kw))(C_b)
        verdicts = np.asarray(sols.sentinel.verdict)
        assert verdicts.tolist() == [0, 1, 0]
        assert np.isfinite(np.asarray(sols.distance)[[0, 2]]).all()


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs a multi-device mesh")
class TestShardedSentinel:
    def _problem(self):
        from aiyagari_tpu.utils.firm import wage_from_r

        m = aiyagari_preset(grid_size=8_192)
        w = float(wage_from_r(0.04, 0.36, 0.08))
        C0 = initial_consumption_guess(m.a_grid, m.s, 0.04, w)
        kw = dict(sigma=5.0, beta=0.96, tol=1e-30, max_iter=6,
                  grid_power=2.0)
        return m, w, C0, kw

    @pytest.mark.slow  # ~30 s: three grid-8192 sharded solves; the sentinel
    # verdict/off-identity contracts stay tier-1 on the single-device paths
    # (TestNanVerdictPolicy, TestQuarantine) at a fraction of the wall.
    def test_sharded_nan_fault_verdict_and_off_identity(self):
        from aiyagari_tpu.parallel.mesh import make_mesh
        from aiyagari_tpu.solvers.egm_sharded import solve_aiyagari_egm_sharded

        m, w, C0, kw = self._problem()
        mesh = make_mesh(("grid",))
        sol = solve_aiyagari_egm_sharded(
            mesh, C0, m.a_grid, m.s, m.P, 0.04, w, m.amin, sentinel=SENT,
            faults=FaultPlan(nan_sweep=2), **kw)
        assert verdict_name(sol.sentinel.verdict) == "nan"
        assert int(sol.iterations) == 3               # early exit, not 6
        # Off path: results bitwise match a sentinel-on healthy run and
        # the off solution carries no sentinel state.
        on = solve_aiyagari_egm_sharded(
            mesh, C0, m.a_grid, m.s, m.P, 0.04, w, m.amin, sentinel=SENT,
            **kw)
        off = solve_aiyagari_egm_sharded(
            mesh, C0, m.a_grid, m.s, m.P, 0.04, w, m.amin, **kw)
        assert off.sentinel is None
        assert verdict_name(on.sentinel.verdict) == "ok"
        np.testing.assert_array_equal(np.asarray(on.policy_c),
                                      np.asarray(off.policy_c))


# -- 3. the rescue ladder ---------------------------------------------------


class TestRescueLadder:
    CFG = AiyagariConfig(grid=GridSpecConfig(n_points=50))
    EQ = EquilibriumConfig(max_iter=16, tol=1e-3)

    def test_apply_stage_semantics(self):
        from aiyagari_tpu.config import AccelConfig
        from aiyagari_tpu.diagnostics.rescue import apply_stage
        from aiyagari_tpu.ops.precision import ladder_for_dtype

        solver = SolverConfig(method="egm", accel=AccelConfig(),
                              use_pallas=False,
                              ladder=ladder_for_dtype("mixed"),
                              faults=FaultPlan(nan_sweep=1), max_iter=100)
        backend = BackendConfig(dtype="mixed")
        eq = EquilibriumConfig(max_iter=10)
        s, b, o = apply_stage("base", solver, backend, eq)
        assert s is solver and b is backend and o is eq
        s, b, o = apply_stage("plain", solver, backend, eq)
        assert s.accel is None and s.faults is None and s.ladder is not None
        s, b, o = apply_stage("safe", solver, backend, eq)
        assert s.pushforward == "scatter"
        s, b, o = apply_stage("float64", solver, backend, eq)
        assert s.ladder is None and b.dtype == "float64"
        s, b, o = apply_stage("patient", solver, backend, eq)
        assert s.max_iter == 200 and o.max_iter == 20
        # Transition outers pick up the damped method + halved damping.
        tc = TransitionConfig(method="newton", damping=0.5, max_iter=10)
        s, b, o = apply_stage("safe", solver, backend, tc)
        assert o.method == "damped"
        s, b, o = apply_stage("patient", solver, backend, tc)
        assert o.method == "damped" and o.damping == 0.25
        assert o.max_iter == 20

    def test_unknown_stage_rejected(self):
        from aiyagari_tpu.diagnostics.rescue import run_rescue

        with pytest.raises(ValueError, match="unknown rescue stage"):
            run_rescue(lambda *a: None,
                       rescue=RescueConfig(stages=("frobnicate",)),
                       solver=SolverConfig(), backend=BackendConfig(),
                       outer=EquilibriumConfig(), context="x", tol=1e-5)

    def test_rescue_recovers_from_injected_nan(self):
        from aiyagari_tpu import solve

        res = solve(self.CFG, method="egm", aggregation="distribution",
                    solver=SolverConfig(method="egm", sentinel=SENT,
                                        faults=FaultPlan(nan_sweep=2)),
                    equilibrium=self.EQ, rescue=RescueConfig())
        assert res.converged and np.isfinite(res.r)
        stages = [a.stage for a in res.rescue_attempts]
        assert stages == ["base", "plain"]
        assert [a.converged for a in res.rescue_attempts] == [False, True]

    def test_forced_stage_failures_escalate(self):
        from aiyagari_tpu import solve

        res = solve(self.CFG, method="egm", aggregation="distribution",
                    solver=SolverConfig(
                        method="egm",
                        faults=FaultPlan(nan_sweep=0,
                                         fail_stage="plain,safe")),
                    equilibrium=self.EQ, rescue=RescueConfig())
        assert res.converged
        assert [(a.stage, a.converged) for a in res.rescue_attempts] == [
            ("base", False), ("plain", False), ("safe", False),
            ("float64", True)]
        # Forced failures are named in the record.
        assert res.rescue_attempts[1].verdict == "injected"

    def test_exhaustion_raises_with_attempt_history(self):
        from aiyagari_tpu import solve

        with pytest.raises(ConvergenceError) as ei:
            solve(self.CFG, method="egm", aggregation="distribution",
                  solver=SolverConfig(
                      method="egm",
                      faults=FaultPlan(
                          nan_sweep=0,
                          fail_stage="plain,safe,float64,patient")),
                  equilibrium=self.EQ, rescue=RescueConfig())
        err = ei.value
        assert len(err.attempts) == 5            # base + 4 stages
        assert [a.stage for a in err.attempts] == [
            "base", "plain", "safe", "float64", "patient"]
        assert not any(a.converged for a in err.attempts)
        assert "rescue ladder exhausted" in str(err)

    def test_rescue_observability(self, tmp_path):
        from aiyagari_tpu import solve
        from aiyagari_tpu.diagnostics import metrics
        from aiyagari_tpu.diagnostics.ledger import read_ledger

        led_path = tmp_path / "rescue.jsonl"
        res = solve(self.CFG, method="egm", aggregation="distribution",
                    solver=SolverConfig(method="egm",
                                        faults=FaultPlan(nan_sweep=1)),
                    equilibrium=self.EQ, rescue=RescueConfig(),
                    ledger=str(led_path))
        assert res.converged
        events = read_ledger(led_path)
        rescues = [e for e in events if e["kind"] == "rescue"]
        assert [e["stage"] for e in rescues] == ["base", "plain"]
        assert rescues[-1]["converged"] is True
        rendered = metrics.render_json()
        series = {(c["labels"]["stage"], c["labels"]["outcome"]): c["value"]
                  for c in rendered["counters"]
                  if c["name"] == "aiyagari_rescue_attempts_total"}
        assert series[("base", "failed")] >= 1
        assert series[("plain", "converged")] >= 1

    def test_rescue_rejected_off_family(self):
        from aiyagari_tpu import solve
        from aiyagari_tpu.config import KrusellSmithConfig

        with pytest.raises(ValueError, match="rescue ladders cover"):
            solve(KrusellSmithConfig(), rescue=RescueConfig())
        with pytest.raises(ValueError, match="rescue ladders cover"):
            solve(self.CFG, backend="numpy", rescue=RescueConfig())
        with pytest.raises(TypeError, match="RescueConfig"):
            solve(self.CFG, rescue="yes please")

    def test_rescue_rejects_conflicting_method(self):
        """Review regression: the rescue branch must reject a
        method=/solver.method conflict exactly as the non-rescue path does
        (never silently overridden)."""
        from aiyagari_tpu import solve

        with pytest.raises(ValueError, match="conflicting methods"):
            solve(self.CFG, method="egm",
                  solver=SolverConfig(method="vfi"),
                  rescue=RescueConfig())


# -- 4. scenario quarantine -------------------------------------------------


class TestQuarantine:
    CFG = AiyagariConfig(grid=GridSpecConfig(n_points=50))
    EQ = EquilibriumConfig(max_iter=20, tol=1e-3)
    BETAS = [0.94, 0.95, 0.96]

    def test_poisoned_sweep_partial_results(self):
        from aiyagari_tpu import sweep

        res = sweep(self.CFG, method="egm", beta=self.BETAS,
                    solver=SolverConfig(method="egm",
                                        faults=FaultPlan(poison_scenario=1)),
                    equilibrium=self.EQ)
        assert res.quarantined.tolist() == [False, True, False]
        assert res.verdicts == ["converged", "nan", "converged"]
        assert np.isfinite(res.r[[0, 2]]).all()

    def test_rescued_lane_matches_serial_and_others_match_clean(self):
        from aiyagari_tpu import sweep

        clean = sweep(self.CFG, method="egm", beta=self.BETAS,
                      solver=SolverConfig(method="egm"), equilibrium=self.EQ)
        res = sweep(self.CFG, method="egm", beta=self.BETAS,
                    solver=SolverConfig(method="egm",
                                        faults=FaultPlan(poison_scenario=1)),
                    equilibrium=self.EQ, rescue=RescueConfig())
        assert res.verdicts == ["converged", "rescued", "converged"]
        assert res.converged.all()
        # Frozen-lane discipline: the healthy lanes ran the identical
        # lockstep rounds, so they match the clean sweep BITWISE; the
        # rescued lane's serial re-solve is the same fixed point.
        np.testing.assert_array_equal(res.r[[0, 2]], clean.r[[0, 2]])
        np.testing.assert_allclose(res.r[1], clean.r[1], atol=1e-12)
        assert 1 in res.rescue_attempts

    def test_quarantine_off_restores_all_or_nothing(self):
        from aiyagari_tpu import sweep

        res = sweep(self.CFG, method="egm", beta=self.BETAS,
                    solver=SolverConfig(method="egm",
                                        faults=FaultPlan(poison_scenario=1)),
                    equilibrium=self.EQ, quarantine=False)
        # No quarantine: the poisoned lane just never converges.
        assert not res.quarantined.any()
        assert not bool(res.converged[1])

    def test_poison_index_validated(self):
        from aiyagari_tpu import sweep

        with pytest.raises(ValueError, match="poison_scenario"):
            sweep(self.CFG, method="egm", beta=self.BETAS,
                  solver=SolverConfig(method="egm",
                                      faults=FaultPlan(poison_scenario=7)),
                  equilibrium=self.EQ)

    @pytest.mark.slow  # ~8 s: the identical quarantine contract (lane
    # count, rescued verdict, unpoisoned parity) is re-gated by every ci
    # battery run (resilience record) and the fused-sweep variant is
    # pinned tier-1 in test_fused_transition.py.
    def test_transition_sweep_quarantine_and_rescue(self):
        from aiyagari_tpu import MITShock, sweep_transitions

        cfg = self.CFG
        shocks = [MITShock(param="tfp", size=0.01, rho=0.8),
                  MITShock(param="tfp", size=0.005, rho=0.8)]
        tc = TransitionConfig(T=25, max_iter=20, tol=1e-6)
        anchor = SolverConfig(method="egm", tol=1e-9, max_iter=5000)
        clean = sweep_transitions(cfg, shocks, transition=tc, solver=anchor)
        res = sweep_transitions(
            cfg, shocks, transition=tc,
            solver=dataclasses.replace(anchor,
                                       faults=FaultPlan(poison_scenario=0)),
            rescue=RescueConfig())
        assert res.quarantined.tolist() == [True, False]
        assert res.verdicts == ["rescued", "converged"]
        np.testing.assert_array_equal(res.r_paths[1], clean.r_paths[1])
        np.testing.assert_allclose(res.r_paths[0], clean.r_paths[0],
                                   atol=1e-10)


# -- 5. loud non-finite verdicts (satellite) --------------------------------


class TestNanVerdictPolicy:
    def test_nan_distance_warns_under_ignore(self):
        with pytest.warns(ConvergenceWarning, match="verdict=nan"):
            from aiyagari_tpu.diagnostics.errors import enforce_convergence

            enforce_convergence(False, "ignore", "x", iterations=3,
                                distance=float("nan"), tol=1e-5)

    def test_nan_distance_overrides_converged_flag(self):
        from aiyagari_tpu.diagnostics.errors import enforce_convergence

        with pytest.warns(ConvergenceWarning, match="verdict=nan"):
            enforce_convergence(True, "warn", "x", iterations=3,
                                distance=float("nan"), tol=1e-5)
        with pytest.raises(ConvergenceError) as ei:
            enforce_convergence(True, "raise", "x", iterations=3,
                                distance=float("inf"), tol=1e-5)
        assert ei.value.verdict == "nan"

    def test_finite_ignore_still_silent(self):
        from aiyagari_tpu.diagnostics.errors import enforce_convergence

        with warnings.catch_warnings():
            warnings.simplefilter("error")
            enforce_convergence(False, "ignore", "x", iterations=1,
                                distance=2.0, tol=1.0)

    def test_sentinel_verdict_named_on_error(self):
        from aiyagari_tpu.diagnostics.errors import enforce_convergence

        with pytest.raises(ConvergenceError) as ei:
            enforce_convergence(False, "raise", "x", iterations=1,
                                distance=2.0, tol=1.0, verdict="stall")
        assert ei.value.verdict == "stall"
        assert "verdict=stall" in str(ei.value)

    def test_health_flags_nan_trajectory(self):
        from aiyagari_tpu.diagnostics.health import diagnose_trajectory

        tr = diagnose_trajectory([1.0, 0.5, float("nan")])
        assert tr["nonfinite"] is True
        tr = diagnose_trajectory([1.0, 0.5, 0.25])
        assert tr["nonfinite"] is False

    def test_health_report_flags_nan_residual(self):
        from aiyagari_tpu.diagnostics.health import health_report
        from aiyagari_tpu.diagnostics.telemetry import host_telemetry

        class R:
            converged = True
            telemetry = host_telemetry([1.0, float("nan")])

        rep = health_report(R())
        assert "outer-nan-residual" in rep["flags"]
        assert rep["healthy"] is False

    def test_health_report_carries_sentinel_verdict(self):
        from aiyagari_tpu.diagnostics.health import health_report

        class R:
            converged = False
            verdict = "stall"

        rep = health_report(R())
        assert rep["verdict"] == "stall"
        assert "verdict-stall" in rep["flags"]

    def test_transition_nan_returns_verdict_when_sentinel_armed(self):
        """Sentinel-armed transitions return a structured 'nan' verdict
        (and enforce_convergence raises loudly) instead of crashing with
        FloatingPointError."""
        from aiyagari_tpu import MITShock, solve_transition

        cfg = AiyagariConfig(grid=GridSpecConfig(n_points=50))
        shock = MITShock(param="tfp", size=float("nan"), rho=0.0)
        with pytest.raises(ConvergenceError) as ei:
            solve_transition(
                cfg, shock,
                transition=TransitionConfig(T=20, max_iter=5),
                solver=SolverConfig(method="egm", tol=1e-9, max_iter=5000,
                                    sentinel=SENT),
                on_nonconvergence="raise")
        assert ei.value.verdict == "nan"


# -- 6. fault-plan mechanics ------------------------------------------------


class TestFaultPlan:
    def test_stage_fails_parsing(self):
        from aiyagari_tpu.diagnostics.faults import stage_fails

        plan = FaultPlan(fail_stage="plain, float64")
        assert stage_fails(plan, "plain")
        assert stage_fails(plan, "float64")
        assert not stage_fails(plan, "safe")
        assert not stage_fails(None, "plain")
        assert not stage_fails(FaultPlan(), "plain")

    def test_default_plan_is_total_noop(self):
        from aiyagari_tpu.diagnostics.faults import (
            force_escape_point,
            forces_fallback,
            poison_iterate,
            poison_scenario_index,
        )

        x = jnp.ones(3)
        esc = jnp.array(False)
        for plan in (None, FaultPlan()):
            assert poison_iterate(plan, x, 0) is x
            assert force_escape_point(plan, x, esc) == (x, esc)
            assert not forces_fallback(plan)
            assert poison_scenario_index(plan) is None

    def test_forced_fallback_counts_degradations(self):
        from aiyagari_tpu.config import TelemetryConfig

        m, C0, kw = _problem()
        hh = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.02, 1.2, m.amin,
                                **kw)
        sol = stationary_distribution(
            hh.policy_k, m.a_grid, m.P, tol=1e-8, max_iter=500,
            telemetry=TelemetryConfig(),
            faults=FaultPlan(force_fallback=True))
        # Every sweep degraded to the scatter fallback and was counted.
        assert int(sol.telemetry.fallbacks) == int(sol.iterations)
        # And the result is still a valid distribution (the fallback IS
        # the recovery path).
        assert float(jnp.abs(jnp.sum(sol.mu) - 1.0)) < 1e-12
