"""Auxiliary-subsystem tests (SURVEY.md §5): logging sinks, profiler harness,
checkpoint/resume of both outer loops, report generation, and the CLI."""

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_tpu.config import (
    ALMConfig,
    AiyagariConfig,
    EquilibriumConfig,
    GridSpecConfig,
    KrusellSmithConfig,
    SimConfig,
    SolverConfig,
)
from aiyagari_tpu.diagnostics.logging import CollectSink, ConsoleSink, JSONLSink, multiplex
from aiyagari_tpu.diagnostics.profiler import Timing, time_fn
from aiyagari_tpu.equilibrium.alm import solve_krusell_smith
from aiyagari_tpu.equilibrium.bisection import solve_equilibrium
from aiyagari_tpu.io_utils.checkpoint import CheckpointManager, load_checkpoint, save_checkpoint
from aiyagari_tpu.models.aiyagari import AiyagariModel

SMALL = AiyagariConfig(grid=GridSpecConfig(n_points=60))
SIM = SimConfig(periods=600, n_agents=4, discard=100, seed=5)


class TestLogging:
    def test_jsonl_and_collect_sinks(self, tmp_path):
        path = tmp_path / "log.jsonl"
        collect = CollectSink()
        sink = multiplex(JSONLSink(path), collect, None)
        sink({"iteration": 0, "dist": 1.5})
        sink({"iteration": 1, "dist": 0.5, "B": [1.0, 2.0]})
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert len(lines) == 2 and lines[1]["dist"] == 0.5
        assert "wall_time" in lines[0]
        assert len(collect.records) == 2

    def test_console_sink_formats(self, capsys):
        ConsoleSink(prefix="x ")({"it": 1, "d": 0.123456789, "B": [1.0, 2]})
        outp = capsys.readouterr().out
        assert outp.startswith("x it=1") and "0.123457" in outp


class TestInJitProgress:
    """SURVEY.md §5.5: device-resident loops report through host callbacks."""

    def _solve(self, progress_every):
        from aiyagari_tpu.config import SolverConfig
        from aiyagari_tpu.equilibrium.bisection import solve_household
        from aiyagari_tpu.models.aiyagari import aiyagari_preset

        m = aiyagari_preset(grid_size=50)
        sol = solve_household(
            m, 0.04,
            solver=SolverConfig(method="egm", progress_every=progress_every),
        )
        jax.block_until_ready(sol.policy_c)
        return sol

    def test_records_emitted_at_cadence(self):
        from aiyagari_tpu.diagnostics import CollectSink, capture_progress

        collect = CollectSink()
        with capture_progress(collect):
            sol = self._solve(progress_every=10)
        iters = int(sol.iterations)
        assert len(collect.records) == iters // 10
        assert all(r["context"] == "aiyagari_egm" for r in collect.records)
        assert all(r["iteration"] % 10 == 0 for r in collect.records)
        # Distances shrink over the run (contraction visible from telemetry).
        dists = [r["distance"] for r in sorted(collect.records, key=lambda r: r["iteration"])]
        assert dists[-1] < dists[0]

    def test_disabled_emits_nothing(self):
        from aiyagari_tpu.diagnostics import CollectSink, capture_progress

        collect = CollectSink()
        with capture_progress(collect):
            self._solve(progress_every=0)
        assert collect.records == []

    def test_labor_paths_emit_too(self):
        from aiyagari_tpu.config import SolverConfig
        from aiyagari_tpu.diagnostics import CollectSink, capture_progress
        from aiyagari_tpu.equilibrium.bisection import solve_household
        from aiyagari_tpu.models.aiyagari import aiyagari_labor_preset

        m = aiyagari_labor_preset(grid_size=40)
        collect = CollectSink()
        with capture_progress(collect):
            sol = solve_household(
                m, 0.04, solver=SolverConfig(method="egm", progress_every=5)
            )
            jax.block_until_ready(sol.policy_c)
        assert collect.records
        assert all(r["context"] == "aiyagari_egm_labor" for r in collect.records)

    def test_unsubscribed_after_scope(self):
        from aiyagari_tpu.diagnostics import CollectSink, capture_progress

        collect = CollectSink()
        with capture_progress(collect):
            pass
        self._solve(progress_every=10)
        assert collect.records == []


class TestProfiler:
    def test_time_fn_fences_and_splits(self):
        import jax

        @jax.jit
        def f(x):
            return (x @ x).sum()

        t = time_fn(f, jnp.ones((200, 200)), reps=2)
        assert isinstance(t, Timing)
        assert t.compile_and_first_run_s >= t.run_s > 0
        assert t.compile_s >= 0


class TestCheckpoint:
    def test_roundtrip(self, tmp_path):
        p = tmp_path / "c.npz"
        save_checkpoint(p, scalars={"it": 3, "hist": [1.0, 2.0]},
                        arrays={"v": np.arange(6.0).reshape(2, 3)})
        sc, arrays = load_checkpoint(p)
        assert sc == {"it": 3, "hist": [1.0, 2.0]}
        np.testing.assert_array_equal(arrays["v"], np.arange(6.0).reshape(2, 3))

    def test_missing_returns_none(self, tmp_path):
        assert load_checkpoint(tmp_path / "nope.npz") is None

    def test_sharded_roundtrip_per_shard_on_disk(self, tmp_path):
        # Sharded device arrays are stored PER SHARD (no assembled
        # full-array entry — the no-host-materialization contract,
        # io_utils/checkpoint._pack_arrays) and restore shard-exactly onto
        # the same sharding, assemble on host without one, and reshard
        # through the fallback when the mesh geometry changed.
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from aiyagari_tpu.io_utils.checkpoint import restore_array
        from aiyagari_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(("grid",))
        sh = NamedSharding(mesh, P(None, "grid"))
        full = np.arange(7 * 64.0).reshape(7, 64)
        x = jax.device_put(jnp.asarray(full), sh)
        p = tmp_path / "s.npz"
        save_checkpoint(p, scalars={"it": 1},
                        arrays={"w": x, "plain": np.ones(3)})
        sc, arrays = load_checkpoint(p)
        shard_keys = sorted(k for k in arrays if k.startswith("w__shard"))
        assert len(shard_keys) == 8 and "w" not in arrays
        assert arrays["w__shard0"].shape == (7, 8)
        np.testing.assert_array_equal(arrays["plain"], np.ones(3))

        back = restore_array(sc, arrays, "w", sharding=sh)
        assert back.sharding.is_equivalent_to(sh, back.ndim)
        np.testing.assert_array_equal(np.asarray(back), full)
        # Host-assembly fallback (no sharding available).
        np.testing.assert_array_equal(restore_array(sc, arrays, "w"), full)
        # Resharding fallback: a different mesh size still restores.
        mesh4 = make_mesh(("grid",), (4,), devices=jax.devices()[:4])
        sh4 = NamedSharding(mesh4, P(None, "grid"))
        back4 = restore_array(sc, arrays, "w", sharding=sh4)
        np.testing.assert_array_equal(np.asarray(back4), full)
        # Plain entries pass through restore_array untouched.
        np.testing.assert_array_equal(
            restore_array(sc, arrays, "plain"), np.ones(3))
        assert restore_array(sc, arrays, "absent") is None

    def test_multiprocess_merge_and_completeness_checks(self, tmp_path):
        """The round-5 multi-process format (io_utils/checkpoint.py module
        docstring): per-process files merge into one view, and each of the
        three loud completeness checks fires. The files are crafted via
        save_checkpoint itself with a monkeypatched process topology — the
        exact bytes a 2-process run writes (the real 2-process flow is
        pinned by test_sim_sharding.test_two_process_interrupted_resume)."""
        from unittest import mock

        from jax.sharding import NamedSharding, PartitionSpec as P

        from aiyagari_tpu.io_utils import checkpoint as ck
        from aiyagari_tpu.parallel.mesh import make_mesh

        full = np.arange(7 * 64.0).reshape(7, 64)
        # Each "process" holds half the devices: a 4-device sharded array
        # carrying its half of the data, saved under a 2-process topology.
        p = tmp_path / "mp.npz"
        for pid in (0, 1):
            mesh4 = make_mesh(("grid",), (4,),
                              devices=jax.devices()[4 * pid:4 * pid + 4])
            sh4 = NamedSharding(mesh4, P(None, "grid"))
            half = jax.device_put(
                jnp.asarray(full[:, 32 * pid:32 * pid + 32]), sh4)
            with mock.patch.object(ck, "_process_topology",
                                   return_value=(pid, 2)):
                # The meta must carry GLOBAL indices: patch the shard
                # index view by saving the half and fixing the meta up —
                # instead, emulate the real layout with a process-spanning
                # array below if addressable. Here: write the half, then
                # rewrite its meta to global coordinates.
                ck.save_checkpoint(p, scalars={"it": 3},
                                   arrays={"w": half, "plain": np.ones(2)})
            f = ck._proc_file(p, pid, 2)
            sc, arrays = ck._load_npz(f)
            # Both mocked "processes" saved from THIS test process, so the
            # per-path save counter gave them different sequences; a real
            # 2-process run stamps the same count in each. Normalize.
            sc[ck._SAVE_SEQ_KEY] = 1
            meta = sc[ck._SHARD_META_KEY]["w"]
            meta["shape"] = [7, 64]
            meta["indices"] = [[[0, 7], [32 * pid + 8 * i, 32 * pid + 8 * (i + 1)]]
                               for i in range(4)]
            payload = {"__scalars__": np.frombuffer(
                json.dumps(sc).encode(), dtype=np.uint8)}
            payload.update(arrays)
            ck._write_npz(f, payload)

        # Merge: all 8 shards, tiling the full array; scalars agree.
        sc, arrays = ck.load_checkpoint(p)
        assert sc["it"] == 3
        shard_keys = [k for k in arrays if k.startswith("w__shard")]
        assert len(shard_keys) == 8
        np.testing.assert_array_equal(ck.restore_array(sc, arrays, "w"), full)
        np.testing.assert_array_equal(arrays["plain"], np.ones(2))

        # Check 1: a missing process file is an incomplete checkpoint.
        f1 = ck._proc_file(p, 1, 2)
        blob = f1.read_bytes()
        f1.unlink()
        with pytest.raises(ValueError, match="incomplete multi-process"):
            ck.load_checkpoint(p)
        f1.write_bytes(blob)

        # Check 2: diverging save sequences across files is a torn save
        # (one process preempted before its write of the same iteration).
        sc1, arrays1 = ck._load_npz(f1)
        sc1[ck._SAVE_SEQ_KEY] = 99
        payload = {"__scalars__": np.frombuffer(
            json.dumps(sc1).encode(), dtype=np.uint8)}
        payload.update(arrays1)
        ck._write_npz(f1, payload)
        with pytest.raises(ValueError, match="torn save"):
            ck.load_checkpoint(p)
        f1.write_bytes(blob)

        # Check 3: shards that do not tile the array are refused (the
        # per-process shard meta is excluded from the torn-save comparison,
        # so the TILING check is the one that fires).
        sc1, arrays1 = ck._load_npz(f1)
        meta = sc1[ck._SHARD_META_KEY]["w"]
        meta["indices"] = meta["indices"][:-1]
        del arrays1["w__shard3"]
        payload = {"__scalars__": np.frombuffer(
            json.dumps(sc1).encode(), dtype=np.uint8)}
        payload.update(arrays1)
        ck._write_npz(f1, payload)
        with pytest.raises(ValueError, match="do not tile"):
            ck.load_checkpoint(p)

    def test_multiprocess_topology_change_and_seq_seeding(self, tmp_path):
        """Round-5 review pins: (a) a save under a NEW process topology
        removes the other representations of the path (a stale
        single-process file would otherwise shadow the proc files at every
        load, silently regressing the run each preemption); (b) restoring
        a merged checkpoint seeds the save counter, so a post-resume save
        continues the sequence instead of restarting at 1 (which would
        make a later torn save undetectable across run generations)."""
        from unittest import mock

        from aiyagari_tpu.io_utils import checkpoint as ck

        p = tmp_path / "topo.npz"
        # Single-process save, then a 2-process save: the single file must
        # be removed by the multi-topology save.
        ck.save_checkpoint(p, scalars={"it": 0}, arrays={"a": np.ones(4)})
        assert p.exists()
        for pid in (0, 1):
            # Each mocked "process" owns its counter in a real run; both
            # must stamp the SAME sequence for the merge to accept them.
            ck._SAVE_COUNTS[str(p)] = 0
            with mock.patch.object(ck, "_process_topology",
                                   return_value=(pid, 2)):
                ck.save_checkpoint(p, scalars={"it": 1},
                                   arrays={"a": np.ones(4)})
        assert not p.exists()
        assert len(list(tmp_path.glob("topo.npz.proc*of2"))) == 2
        # (b) a fresh process's counter starts at 0; loading the merged
        # view re-seeds it from the restored sequence.
        ck._SAVE_COUNTS.pop(str(p), None)
        sc, arrays = ck.load_checkpoint(p)
        assert sc["it"] == 1
        assert ck._SAVE_COUNTS[str(p)] == 1
        # A later single-process save removes the proc files (symmetric
        # topology-change cleanup).
        ck.save_checkpoint(p, scalars={"it": 2}, arrays={"a": np.ones(4)})
        assert p.exists()
        assert not list(tmp_path.glob("topo.npz.proc*of*"))

    def test_stale_single_file_removed_before_first_proc_write(self, tmp_path):
        """ISSUE 2 satellite (ADVICE r5 ~:248): the single->multi topology
        transition must unlink the stale single-process file BEFORE writing
        the first proc file — a preemption between the two steps must leave
        'no checkpoint' (fresh start) or a LOUD incomplete-set error, never
        the stale file silently shadowing the newer proc state."""
        from unittest import mock

        from aiyagari_tpu.io_utils import checkpoint as ck

        p = tmp_path / "order.npz"
        ck.save_checkpoint(p, scalars={"it": 7}, arrays={"a": np.ones(3)})
        assert p.exists()

        # Preempt exactly between the cleanup and the proc write.
        with mock.patch.object(ck, "_write_npz",
                               side_effect=RuntimeError("preempted")):
            with mock.patch.object(ck, "_process_topology",
                                   return_value=(0, 2)):
                with pytest.raises(RuntimeError, match="preempted"):
                    ck.save_checkpoint(p, scalars={"it": 8},
                                       arrays={"a": np.ones(3)})
        # The stale pre-transition file is already gone: a resume starts
        # fresh instead of silently regressing to iteration 7.
        assert not p.exists()
        assert ck.load_checkpoint(p) is None

    def test_lazy_entries_refuse_concurrently_replaced_proc_file(self, tmp_path):
        """ISSUE 2 satellite (ADVICE r5 ~:265): the merged multi-process
        view reads shard data lazily, so a save that atomically replaces a
        proc file AFTER the merge must not serve newer shards against the
        older merged metadata — the lazy open re-verifies the save sequence
        and raises."""
        from unittest import mock

        from aiyagari_tpu.io_utils import checkpoint as ck

        p = tmp_path / "lazy.npz"
        for pid in (0, 1):
            ck._SAVE_COUNTS[str(p)] = 0
            with mock.patch.object(ck, "_process_topology",
                                   return_value=(pid, 2)):
                ck.save_checkpoint(p, scalars={"it": 1},
                                   arrays={"a": np.full(4, 1.0 + pid)})
        sc, arrays = ck.load_checkpoint(p)
        assert arrays.expected_seq == 1
        np.testing.assert_array_equal(arrays["a"], np.ones(4))  # lazy read ok

        # A concurrent save replaces process 0's file (newer sequence).
        ck._SAVE_COUNTS[str(p)] = 5
        with mock.patch.object(ck, "_process_topology",
                               return_value=(0, 2)):
            ck.save_checkpoint(p, scalars={"it": 2},
                               arrays={"a": np.full(4, 9.0)})
        with pytest.raises(ValueError, match="changed under the merged"):
            arrays["a"]
        # A fresh merge of a CONSISTENT generation works again.
        ck._SAVE_COUNTS[str(p)] = 5
        with mock.patch.object(ck, "_process_topology",
                               return_value=(1, 2)):
            ck.save_checkpoint(p, scalars={"it": 2},
                               arrays={"a": np.full(4, 9.0)})
        sc2, arrays2 = ck.load_checkpoint(p)
        assert arrays2.expected_seq == 6
        np.testing.assert_array_equal(arrays2["a"], np.full(4, 9.0))

    def test_bisection_resume(self, tmp_path):
        model = AiyagariModel.from_config(SMALL)
        solver = SolverConfig(method="egm")
        eq = EquilibriumConfig(max_iter=4)
        full = solve_equilibrium(model, solver=solver, sim=SIM, eq=eq)

        # Interrupted run: stop after 2 iterations (checkpointing on).
        class Stop(Exception):
            pass

        def interrupt(rec):
            if rec["iteration"] == 1:
                raise Stop

        with pytest.raises(Stop):
            solve_equilibrium(model, solver=solver, sim=SIM, eq=eq,
                              on_iteration=interrupt, checkpoint_dir=tmp_path)
        resumed = solve_equilibrium(model, solver=solver, sim=SIM, eq=eq,
                                    checkpoint_dir=tmp_path)
        # Resumed run continues the same bisection: identical bracket path.
        np.testing.assert_allclose(resumed.r_history, full.r_history, atol=1e-12)
        assert abs(resumed.r - full.r) < 1e-12

    def test_checkpoint_deleted_on_completion(self, tmp_path):
        model = AiyagariModel.from_config(SMALL)
        solve_equilibrium(model, solver=SolverConfig(method="egm"), sim=SIM,
                          eq=EquilibriumConfig(max_iter=2), checkpoint_dir=tmp_path)
        assert not list(tmp_path.glob("*.npz"))

    def test_fingerprint_mismatch_starts_fresh(self, tmp_path):
        model = AiyagariModel.from_config(SMALL)
        eq = EquilibriumConfig(max_iter=3)

        class Stop(Exception):
            pass

        def interrupt(rec):
            # Interrupt after iteration 0's checkpoint has been written (the
            # save happens post-callback, so trigger on the next iteration).
            if rec["iteration"] == 1:
                raise Stop

        with pytest.raises(Stop):
            solve_equilibrium(model, solver=SolverConfig(method="egm"), sim=SIM, eq=eq,
                              on_iteration=interrupt, checkpoint_dir=tmp_path)
        assert list(tmp_path.glob("*.npz"))
        # Different sim seed => different fingerprint => checkpoint ignored.
        sim2 = SimConfig(periods=600, n_agents=4, discard=100, seed=99)
        with pytest.warns(UserWarning, match="different run configuration"):
            res = solve_equilibrium(model, solver=SolverConfig(method="egm"), sim=sim2,
                                    eq=eq, checkpoint_dir=tmp_path)
        assert res.iterations == 3  # fresh full run, not a resume

    def test_exhausted_run_resume_no_duplicates(self, tmp_path):
        # Interrupt on the LAST iteration so the checkpoint describes a run
        # that used its whole budget; resuming must not duplicate history.
        model = AiyagariModel.from_config(SMALL)
        eq = EquilibriumConfig(max_iter=3)

        class Stop(Exception):
            pass

        def interrupt(rec):
            if rec["iteration"] == 2:
                raise Stop

        with pytest.raises(Stop):
            solve_equilibrium(model, solver=SolverConfig(method="egm"), sim=SIM, eq=eq,
                              on_iteration=interrupt, checkpoint_dir=tmp_path)
        resumed = solve_equilibrium(model, solver=SolverConfig(method="egm"), sim=SIM,
                                    eq=eq, checkpoint_dir=tmp_path)
        assert resumed.iterations <= eq.max_iter
        its = [r["iteration"] for r in resumed.per_iteration]
        assert len(its) == len(set(its))  # no duplicated iteration labels

    def test_ks_resume(self, tmp_path):
        cfg = KrusellSmithConfig(k_size=15)
        alm = ALMConfig(T=120, population=300, discard=30, max_iter=3, seed=2)
        kw = dict(method="vfi",
                  solver=SolverConfig(method="vfi", tol=1e-4, max_iter=50, howard_steps=10))
        full = solve_krusell_smith(cfg, alm=alm, **kw)

        class Stop(Exception):
            pass

        def interrupt(rec):
            if rec["iteration"] == 0:
                raise Stop

        with pytest.raises(Stop):
            solve_krusell_smith(cfg, alm=alm, on_iteration=interrupt,
                                checkpoint_dir=tmp_path, **kw)
        resumed = solve_krusell_smith(cfg, alm=alm, checkpoint_dir=tmp_path, **kw)
        np.testing.assert_allclose(resumed.B, full.B, atol=1e-10)

    def test_ks_resume_preserves_anderson_history(self, tmp_path):
        # The Anderson mixing history is part of the outer-loop state: a
        # resume must continue extrapolating from the pre-crash trajectory,
        # i.e. reproduce the uninterrupted run's iterates exactly. Interrupt
        # AFTER iteration 1 so the saved history is non-empty (depth >= 1)
        # and the post-resume step actually uses it.
        cfg = KrusellSmithConfig(k_size=15)
        alm = ALMConfig(T=120, population=300, discard=30, max_iter=4, seed=2,
                        acceleration="anderson")
        kw = dict(method="vfi",
                  solver=SolverConfig(method="vfi", tol=1e-4, max_iter=50, howard_steps=10))
        full = solve_krusell_smith(cfg, alm=alm, **kw)

        class Stop(Exception):
            pass

        def interrupt(rec):
            if rec["iteration"] == 1:
                raise Stop

        with pytest.raises(Stop):
            solve_krusell_smith(cfg, alm=alm, on_iteration=interrupt,
                                checkpoint_dir=tmp_path, **kw)
        resumed = solve_krusell_smith(cfg, alm=alm, checkpoint_dir=tmp_path, **kw)
        np.testing.assert_allclose(resumed.B, full.B, atol=1e-10)
        for r_full, r_res in zip(full.per_iteration[2:], resumed.per_iteration[2:]):
            np.testing.assert_allclose(r_res["B"], r_full["B"], atol=1e-10)

    def test_ks_resume_restores_tightened_house_tol(self, tmp_path):
        # The mixed-phase switch tightens the household tolerance to
        # alm.tol/10 for the finishing rounds; a resume mid-finishing-phase
        # must keep it (a revert to the loose tol would re-introduce the
        # solver-noise hovering the switch exists to break). Simulate the
        # post-switch state by rewriting the saved scalar, as the switch
        # itself only triggers at real scale.
        cfg = KrusellSmithConfig(k_size=15)
        alm = ALMConfig(T=120, population=300, discard=30, max_iter=3, seed=2)
        kw = dict(method="vfi",
                  solver=SolverConfig(method="vfi", tol=1e-4, max_iter=50, howard_steps=10))

        class Stop(Exception):
            pass

        def interrupt(rec):
            assert rec["house_tol"] == 1e-4    # pre-switch: the solver tol
            if rec["iteration"] == 1:
                raise Stop

        with pytest.raises(Stop):
            solve_krusell_smith(cfg, alm=alm, on_iteration=interrupt,
                                checkpoint_dir=tmp_path, **kw)
        path = tmp_path / "ks_vfi.ckpt.npz"
        scalars, arrays = load_checkpoint(path)
        scalars["house_tol"] = 1e-7            # as the phase switch would set
        save_checkpoint(path, scalars=scalars, arrays=arrays)
        seen = []
        resumed = solve_krusell_smith(cfg, alm=alm, checkpoint_dir=tmp_path,
                                      on_iteration=lambda r: seen.append(r["house_tol"]),
                                      **kw)
        assert seen and all(t == 1e-7 for t in seen)


class TestReports:
    def test_equilibrium_report(self, tmp_path):
        from aiyagari_tpu.io_utils.report import equilibrium_report

        model = AiyagariModel.from_config(SMALL)
        res = solve_equilibrium(model, solver=SolverConfig(method="egm"), sim=SIM,
                                eq=EquilibriumConfig(max_iter=3))
        summary = equilibrium_report(res, model, tmp_path, discard=100)
        for f in ("capital_market.png", "policies.png", "densities.png",
                  "histograms.png", "lorenz.png", "quintiles.png", "summary.json"):
            assert (tmp_path / f).exists(), f
        assert set(summary["gini"]) == {"k", "c", "y", "gy", "sav"}
        assert abs(sum(summary["quintile_shares_percent"]) - 100.0) < 1e-6

    def test_ks_report(self, tmp_path):
        from aiyagari_tpu.io_utils.report import krusell_smith_report

        cfg = KrusellSmithConfig(k_size=15)
        res = solve_krusell_smith(
            cfg, method="vfi",
            solver=SolverConfig(method="vfi", tol=1e-4, max_iter=50, howard_steps=10),
            alm=ALMConfig(T=120, population=300, discard=30, max_iter=2, seed=2),
        )
        summary = krusell_smith_report(res, tmp_path, discard=30)
        assert (tmp_path / "alm.png").exists()
        assert (tmp_path / "wealth_cross_section.png").exists()
        assert summary["r2_good"] > 0.9
        assert summary["alm_path_max_rel_error"] < 0.2


class TestCompileCache:
    def test_enable_sets_and_env_disables(self, tmp_path, monkeypatch):
        import jax

        from aiyagari_tpu.io_utils.compile_cache import enable_compilation_cache

        old = {
            name: getattr(jax.config, name)
            for name in (
                "jax_compilation_cache_dir",
                "jax_persistent_cache_min_entry_size_bytes",
                "jax_persistent_cache_min_compile_time_secs",
            )
        }
        try:
            # Explicit and env dirs are suffixed by backend AND host-CPU
            # tag: an unsuffixed shared dir lets a TPU-attached process's
            # XLA:CPU AOT artifacts (+prefer-no-scatter/-gather machine
            # features) collide with a pure-CPU process's — the documented
            # SIGILL hazard (ADVICE round 2) — and this image reprovisions
            # the SAME home directory onto different CPU steppings, whose
            # AOT artifacts also must not mix.
            from aiyagari_tpu.io_utils.compile_cache import _host_cpu_tag

            suffix = f"-cpu-{_host_cpu_tag()}"
            d = enable_compilation_cache(str(tmp_path / "xla"))
            assert d == str(tmp_path / "xla") + suffix
            assert jax.config.jax_compilation_cache_dir == d
            # Empty env var is the documented opt-out.
            monkeypatch.setenv("AIYAGARI_TPU_COMPILE_CACHE", "")
            assert enable_compilation_cache() is None
            # Env var wins over the default location.
            monkeypatch.setenv("AIYAGARI_TPU_COMPILE_CACHE", str(tmp_path / "env"))
            assert enable_compilation_cache() == str(tmp_path / "env") + suffix
        finally:
            for name, val in old.items():
                jax.config.update(name, val)


@pytest.mark.slow
class TestCLI:
    def test_cli_aiyagari_end_to_end(self, tmp_path):
        out = subprocess.run(
            [sys.executable, "-m", "aiyagari_tpu", "aiyagari", "--method", "egm",
             "--grid", "60", "--periods", "500", "--agents", "4",
             "--platform", "cpu", "--f64", "--quiet",
             "--outdir", str(tmp_path / "run")],
            capture_output=True, text=True, cwd=str(Path(__file__).resolve().parents[1]),
            timeout=500,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        summary = json.loads((tmp_path / "run" / "summary.json").read_text())
        assert -0.05 < summary["r_star"] < 0.05
        assert (tmp_path / "run" / "iterations.jsonl").exists()
