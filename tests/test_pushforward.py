"""Tests for the scatter-free distribution push-forward layer (ISSUE 5):
ops/pushforward.py's DistributionBackend routes — scatter reference,
monotone-transpose, banded block-matmul, fused Pallas (interpret mode on
this CPU suite) — pinned against each other across all four hot
cross-section paths (plain Aiyagari, endogenous labor, the K-S histogram
closure, the transition forward push), plus the adjoint identity every
backend must preserve for the fake-news Jacobian, the loud monotonicity/
band-overflow fallbacks, the young_lottery zero-width-bracket guard, and
the shared-helper contract of ks_distribution.initial_distribution."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import aiyagari_tpu.ops.pushforward as pf
from aiyagari_tpu.config import AiyagariConfig, GridSpecConfig, SolverConfig
from aiyagari_tpu.equilibrium.bisection import solve_household
from aiyagari_tpu.models.aiyagari import AiyagariModel, aiyagari_preset
from aiyagari_tpu.ops.pushforward import (
    BACKENDS,
    apply_pushforward,
    lottery_scatter,
    plan_pushforward,
    pushforward_step,
    resolve_backend,
    shard_banded_plan,
)
from aiyagari_tpu.sim.distribution import (
    distribution_step,
    expectation_step,
    stationary_distribution,
    young_lottery,
)

SCATTER_FREE = ("transpose", "banded", "pallas")


@pytest.fixture(scope="module")
def solved_small():
    model = aiyagari_preset(grid_size=80)
    sol = solve_household(model, 0.03, solver=SolverConfig(method="egm"))
    idx, w_lo = young_lottery(sol.policy_k, model.a_grid)
    N, na = sol.policy_k.shape
    mu = jnp.full((N, na), 1.0 / (N * na))
    return model, sol, idx, w_lo, mu


@pytest.fixture(scope="module")
def labor_solved():
    cfg = AiyagariConfig(endogenous_labor=True,
                         grid=GridSpecConfig(n_points=60))
    model = AiyagariModel.from_config(cfg)
    sol = solve_household(model, 0.03, solver=SolverConfig(method="egm"))
    idx, w_lo = young_lottery(sol.policy_k, model.a_grid)
    N, na = sol.policy_k.shape
    mu = jnp.full((N, na), 1.0 / (N * na))
    return model, idx, w_lo, mu


class TestBackendParity:
    """Every backend is the SAME linear operator; only summation order may
    differ, so agreement holds to f64 ulp bands (the Pallas route runs the
    interpreter here — the tier-1 interpret-equality pin)."""

    @pytest.mark.parametrize("backend", SCATTER_FREE)
    def test_step_parity_plain(self, solved_small, backend):
        model, _, idx, w_lo, mu = solved_small
        ref = pushforward_step(mu, idx, w_lo, model.P, backend="scatter")
        out = pushforward_step(mu, idx, w_lo, model.P, backend=backend)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-14)

    @pytest.mark.parametrize("backend", SCATTER_FREE)
    def test_step_parity_labor(self, labor_solved, backend):
        model, idx, w_lo, mu = labor_solved
        ref = pushforward_step(mu, idx, w_lo, model.P, backend="scatter")
        out = pushforward_step(mu, idx, w_lo, model.P, backend=backend)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-14)

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_mass_conservation_per_step(self, solved_small, backend):
        model, _, idx, w_lo, mu = solved_small
        out = pushforward_step(mu, idx, w_lo, model.P, backend=backend)
        assert float(out.sum()) == pytest.approx(1.0, abs=1e-13)
        # The transpose route's cumsum differences may round individual
        # buckets a hair below zero (O(eps) cancellation); nothing larger.
        assert float(out.min()) >= -1e-15

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_adjoint_identity(self, solved_small, backend):
        """<f, L mu> == <L' f, mu> for EVERY backend — the pairing the
        sequence-space fake-news Jacobian (transition/jacobian.py) relies
        on; expectation_step is the single gather-form adjoint."""
        model, _, idx, w_lo, mu = solved_small
        rng = np.random.default_rng(11)
        f = jnp.asarray(rng.normal(size=mu.shape))
        lhs = float(jnp.sum(
            f * pushforward_step(mu, idx, w_lo, model.P, backend=backend)))
        rhs = float(jnp.sum(expectation_step(f, idx, w_lo, model.P) * mu))
        assert lhs == pytest.approx(rhs, rel=1e-12, abs=1e-14)

    @pytest.mark.parametrize("backend", SCATTER_FREE)
    def test_stationary_distribution_parity(self, solved_small, backend):
        model, sol, _, _, _ = solved_small
        ref = stationary_distribution(sol.policy_k, model.a_grid, model.P,
                                      tol=1e-11, max_iter=20_000,
                                      pushforward="scatter")
        out = stationary_distribution(sol.policy_k, model.a_grid, model.P,
                                      tol=1e-11, max_iter=20_000,
                                      pushforward=backend)
        assert float(out.distance) < 1e-11
        np.testing.assert_allclose(np.asarray(out.mu), np.asarray(ref.mu),
                                   atol=1e-10)
        assert float(out.mu.sum()) == pytest.approx(1.0, abs=1e-12)

    @pytest.mark.parametrize("backend", SCATTER_FREE)
    def test_ks_histogram_path_parity(self, backend):
        from aiyagari_tpu.config import KrusellSmithConfig
        from aiyagari_tpu.models.krusell_smith import KrusellSmithModel
        from aiyagari_tpu.sim.ks_distribution import (
            distribution_capital_path,
            initial_distribution,
        )
        from aiyagari_tpu.sim.ks_panel import simulate_aggregate_shocks

        cfg = KrusellSmithConfig(k_size=50)
        m = KrusellSmithModel.from_config(cfg, jnp.float64)
        z = simulate_aggregate_shocks(m.pz, jax.random.PRNGKey(3), T=120)
        mu0 = initial_distribution(m.k_grid, m.K_grid,
                                   cfg.shocks.u_good, jnp.float64)
        k_opt = 0.9 * jnp.broadcast_to(
            m.k_grid[None, None, :], (4, cfg.K_size, cfg.k_size))
        K_ref, mu_ref = distribution_capital_path(
            k_opt, m.k_grid, m.K_grid, z, m.eps_trans, mu0, T=120,
            pushforward="scatter")
        K_out, mu_out = distribution_capital_path(
            k_opt, m.k_grid, m.K_grid, z, m.eps_trans, mu0, T=120,
            pushforward=backend)
        np.testing.assert_allclose(np.asarray(K_out), np.asarray(K_ref),
                                   rtol=1e-10)
        np.testing.assert_allclose(np.asarray(mu_out), np.asarray(mu_ref),
                                   atol=1e-12)

    @pytest.mark.parametrize("backend", SCATTER_FREE)
    def test_transition_forward_parity(self, solved_small, backend):
        from aiyagari_tpu.transition.path import forward_capital

        model, sol, _, _, mu = solved_small
        # A dated-policy stack: the stationary policy progressively damped
        # toward the grid midpoint — monotone each period, all distinct.
        T = 12
        mid = 0.5 * (model.a_grid[0] + model.a_grid[-1])
        lam = jnp.linspace(0.0, 0.3, T)[:, None, None]
        k_ts = (1.0 - lam) * sol.policy_k[None] + lam * mid
        K_ref, A_ref, muT_ref = forward_capital(mu, k_ts, model.a_grid,
                                                model.P, "scatter")
        K_out, A_out, muT_out = forward_capital(mu, k_ts, model.a_grid,
                                                model.P, backend)
        np.testing.assert_allclose(np.asarray(K_out), np.asarray(K_ref),
                                   rtol=1e-11)
        np.testing.assert_allclose(np.asarray(A_out), np.asarray(A_ref),
                                   rtol=1e-11)
        np.testing.assert_allclose(np.asarray(muT_out), np.asarray(muT_ref),
                                   atol=1e-13)
        # The mean-preservation identity K_{t+1} == A_t survives every
        # backend (the sequence-space Jacobian relies on it).
        np.testing.assert_allclose(np.asarray(K_out[1:]), np.asarray(A_out),
                                   atol=1e-12)

    def test_fake_news_jacobian_backend_parity(self):
        from aiyagari_tpu.transition.mit import (
            stationary_anchor,
            transition_jacobian,
        )

        model = aiyagari_preset(grid_size=40)
        ss = stationary_anchor(model)
        J_ref = transition_jacobian(model, ss, 16, pushforward="scatter")
        J_tr = transition_jacobian(model, ss, 16, pushforward="transpose")
        np.testing.assert_allclose(J_tr, J_ref, atol=1e-10)


class TestFallbacks:
    """Non-monotone lotteries and band overflows must degrade to the
    reference result (cond fallback), never corrupt mass."""

    @pytest.fixture(autouse=True)
    def _quiet(self, monkeypatch):
        # These tests build adversarial lotteries ON PURPOSE; silence the
        # loud fallback print without touching the shipped default.
        monkeypatch.setattr(pf, "WARN_ON_FALLBACK", False)

    @pytest.fixture(scope="class")
    def non_monotone(self, solved_small):
        model, _, idx, w_lo, mu = solved_small
        perm = np.random.default_rng(5).permutation(idx.shape[1])
        return model, idx[:, perm], w_lo[:, perm], mu

    @pytest.mark.parametrize("backend", SCATTER_FREE)
    def test_non_monotone_matches_scatter(self, non_monotone, backend):
        model, idx, w_lo, mu = non_monotone
        assert not bool(jnp.all(idx[:, 1:] >= idx[:, :-1]))
        ref = pushforward_step(mu, idx, w_lo, model.P, backend="scatter")
        out = pushforward_step(mu, idx, w_lo, model.P, backend=backend)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-14)

    def test_plan_flags_non_monotone(self, non_monotone):
        _, idx, w_lo, _ = non_monotone
        plan = plan_pushforward(idx, w_lo, backend="transpose")
        assert not bool(plan.ok)

    def test_band_overflow_falls_back(self, solved_small):
        """A flat policy (every source in one bucket) overflows any narrow
        band; the apply must route to the transpose fallback and still
        match the scatter reference."""
        model, _, idx, w_lo, mu = solved_small
        idx_flat = jnp.zeros_like(idx)
        w_flat = jnp.full_like(w_lo, 0.25)
        plan = plan_pushforward(idx_flat, w_flat, backend="banded",
                                band_block=8, band_width=16)
        assert not bool(plan.ok) and bool(plan.monotone)
        ref = pushforward_step(mu, idx_flat, w_flat, model.P,
                               backend="scatter")
        out = apply_pushforward(plan, mu, model.P)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-14)

    def test_stationary_distribution_non_monotone_policy(self, solved_small):
        """End to end: a (weird but valid) non-monotone policy through the
        default scatter-free stationary solve still converges to the
        scatter fixed point — the fallback is wired inside the loop."""
        model, sol, _, _, _ = solved_small
        pol = jnp.flip(sol.policy_k, axis=-1)
        ref = stationary_distribution(pol, model.a_grid, model.P,
                                      tol=1e-10, pushforward="scatter")
        out = stationary_distribution(pol, model.a_grid, model.P,
                                      tol=1e-10, pushforward="auto")
        np.testing.assert_allclose(np.asarray(out.mu), np.asarray(ref.mu),
                                   atol=1e-10)


class TestLotteryZeroWidthGuard:
    """ISSUE 5 satellite: duplicate adjacent knots used to make
    (hi - policy) / (hi - lo) a 0/0 — NaN mass. The denominator clamp
    collapses the bracket's mass onto the duplicated knot instead."""

    def test_duplicate_knots_no_nan(self):
        grid = jnp.asarray([0.0, 1.0, 1.0, 2.0, 3.0])
        pol = jnp.asarray([[0.5, 1.0, 1.0, 2.5, 3.0]])
        idx, w_lo = young_lottery(pol, grid)
        assert bool(jnp.all(jnp.isfinite(w_lo)))
        assert float(w_lo.min()) >= 0.0 and float(w_lo.max()) <= 1.0
        recon = w_lo * grid[idx] + (1.0 - w_lo) * grid[idx + 1]
        np.testing.assert_allclose(np.asarray(recon), np.asarray(pol),
                                   atol=1e-14)

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_mass_conserved_on_degenerate_grid(self, backend):
        grid = jnp.asarray([0.0, 1.0, 1.0, 2.0, 3.0])
        pol = jnp.asarray([[0.2, 1.0, 1.0, 1.5, 2.9],
                           [0.0, 0.5, 1.0, 2.0, 3.0]])
        idx, w_lo = young_lottery(pol, grid)
        mu = jnp.full((2, 5), 0.1)
        P = jnp.asarray([[0.9, 0.1], [0.2, 0.8]])
        out = pushforward_step(mu, idx, w_lo, P, backend=backend)
        assert bool(jnp.all(jnp.isfinite(out)))
        assert float(out.sum()) == pytest.approx(float(mu.sum()), abs=1e-14)


class TestInitialDistribution:
    """ISSUE 5 satellite: the K-S start-point deposit now rides the shared
    lottery helper and inherits its edge-clipping contract."""

    def _build(self, K0, nk=12, u0=0.07):
        from aiyagari_tpu.sim.ks_distribution import initial_distribution

        k_grid = jnp.linspace(0.0, 10.0, nk)
        K_grid = jnp.asarray([K0, K0 + 1.0, K0 + 2.0, K0 + 3.0])
        return k_grid, initial_distribution(k_grid, K_grid, u0,
                                            jnp.float64), u0

    def test_interior_point_two_point_lottery(self):
        k_grid, mu, u0 = self._build(4.5)
        assert float(mu.sum()) == pytest.approx(1.0, abs=1e-14)
        np.testing.assert_allclose(float(jnp.sum(mu * k_grid[None, :])),
                                   4.5, atol=1e-12)
        np.testing.assert_allclose(np.asarray(mu.sum(axis=1)),
                                   [1.0 - u0, u0], atol=1e-14)

    def test_top_of_grid_edge(self):
        """A start point AT the last knot: all mass on the top gridpoint,
        total exactly 1 — no out-of-bounds write, no dropped mass."""
        k_grid, mu, u0 = self._build(10.0)
        assert float(mu.sum()) == pytest.approx(1.0, abs=1e-14)
        assert float(mu[:, :-1].sum()) == pytest.approx(0.0, abs=1e-14)

    def test_beyond_grid_clips(self):
        k_grid, mu, _ = self._build(25.0)
        assert float(mu.sum()) == pytest.approx(1.0, abs=1e-14)
        assert float(mu[:, -1].sum()) == pytest.approx(1.0, abs=1e-14)


class TestBandedSharding:
    """Grid-axis sharding of the banded operator over the 8-virtual-device
    mesh (parallel/mesh.shard_map shim): each device owns nt/8 target
    tiles; results match the unsharded apply."""

    def test_sharded_banded_apply_matches_unsharded(self):
        from aiyagari_tpu.parallel.mesh import make_mesh

        if jax.device_count() < 2:
            pytest.skip("needs the virtual multi-device CPU mesh")
        na, N = 1024, 4            # nt = 1024/128 = 8 tiles, one per device
        rng = np.random.default_rng(9)
        a_grid = jnp.asarray(np.linspace(0.0, 20.0, na))
        pol = jnp.asarray(
            np.sort(rng.uniform(0.0, 20.0, (N, na)), axis=1))
        idx, w_lo = young_lottery(pol, a_grid)
        mu = jnp.asarray(rng.uniform(size=(N, na)))
        mu = mu / mu.sum()
        P = jnp.asarray(rng.uniform(0.1, 1.0, (N, N)))
        P = P / P.sum(axis=1, keepdims=True)

        plan = plan_pushforward(idx, w_lo, backend="banded",
                                band_width=1024)
        assert bool(plan.ok)
        mesh = make_mesh(("grid",))
        out_sh = shard_banded_plan(plan, mesh, P)(mu)
        ref = apply_pushforward(plan, mu, P)
        np.testing.assert_allclose(np.asarray(out_sh), np.asarray(ref),
                                   atol=1e-14)

    def test_rejects_non_banded_plan(self, solved_small):
        from aiyagari_tpu.parallel.mesh import make_mesh

        model, _, idx, w_lo, _ = solved_small
        plan = plan_pushforward(idx, w_lo, backend="transpose")
        with pytest.raises(ValueError, match="banded"):
            shard_banded_plan(plan, make_mesh(("grid",)), model.P)

    def test_sharded_banded_apply_on_2d_mesh_matches_1d(self):
        """ISSUE 15 satellite: the banded plan's tile axis routes through
        parallel/rules.BANDED_PLAN_RULES, so the SAME shard_banded_plan
        call runs on a 2-D (scenarios x grid) make_mesh_2d mesh — the
        scenario axis replicates, the tile axis still splits over "grid"
        — parity-pinned against both the 1-D sharded apply and the
        unsharded reference."""
        from aiyagari_tpu.parallel.mesh import make_mesh, make_mesh_2d

        if jax.device_count() < 8:
            pytest.skip("needs the 8-virtual-device CPU mesh")
        na, N = 1024, 4
        rng = np.random.default_rng(11)
        a_grid = jnp.asarray(np.linspace(0.0, 20.0, na))
        pol = jnp.asarray(
            np.sort(rng.uniform(0.0, 20.0, (N, na)), axis=1))
        idx, w_lo = young_lottery(pol, a_grid)
        mu = jnp.asarray(rng.uniform(size=(N, na)))
        mu = mu / mu.sum()
        P = jnp.asarray(rng.uniform(0.1, 1.0, (N, N)))
        P = P / P.sum(axis=1, keepdims=True)

        plan = plan_pushforward(idx, w_lo, backend="banded",
                                band_width=1024)
        assert bool(plan.ok)
        ref = np.asarray(apply_pushforward(plan, mu, P))
        out_1d = np.asarray(
            shard_banded_plan(plan, make_mesh(("grid",)), P)(mu))
        mesh_2d = make_mesh_2d(scenarios=2, grid=4)
        out_2d = np.asarray(shard_banded_plan(plan, mesh_2d, P)(mu))
        np.testing.assert_allclose(out_2d, ref, atol=1e-14)
        # 1-D vs 2-D: identical per-tile matmuls, identical summation
        # order — the 2-D composition must not perturb a single bit.
        np.testing.assert_array_equal(out_2d, out_1d)

    def test_rejects_mesh_without_grid_axis(self, solved_small):
        from aiyagari_tpu.parallel.mesh import make_mesh

        model, _, idx, w_lo, _ = solved_small
        plan = plan_pushforward(idx, w_lo, backend="banded")
        with pytest.raises(ValueError, match="grid"):
            shard_banded_plan(plan, make_mesh(("scenarios",)), model.P)


class TestKnobValidation:
    def test_unknown_backend_rejected(self):
        with pytest.raises(ValueError, match="unknown distribution backend"):
            resolve_backend("bogus")

    def test_auto_resolves_scatter_free(self):
        assert resolve_backend("auto") in SCATTER_FREE

    def test_dispatch_rejects_typo(self):
        from aiyagari_tpu import solve

        with pytest.raises(ValueError, match="unknown distribution backend"):
            solve(AiyagariConfig(grid=GridSpecConfig(n_points=40)),
                  method="egm", aggregation="distribution",
                  solver=SolverConfig(method="egm", pushforward="bogus"))

    def test_dispatch_rejects_typo_krusell_smith(self):
        from aiyagari_tpu import KrusellSmithConfig, solve

        with pytest.raises(ValueError, match="unknown distribution backend"):
            solve(KrusellSmithConfig(),
                  solver=SolverConfig(pushforward="bogus"))

    def test_dispatch_rejects_numpy_scatter_free(self):
        from aiyagari_tpu import solve

        with pytest.raises(ValueError, match="backend='jax'"):
            solve(AiyagariConfig(grid=GridSpecConfig(n_points=40)),
                  backend="numpy",
                  solver=SolverConfig(pushforward="banded"))

    def test_distribution_step_backend_knob(self, solved_small):
        model, _, idx, w_lo, mu = solved_small
        ref = distribution_step(mu, idx, w_lo, model.P, backend="scatter")
        out = distribution_step(mu, idx, w_lo, model.P)   # default: auto
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-14)

    def test_solve_end_to_end_banded(self):
        from aiyagari_tpu import EquilibriumConfig, solve

        res = solve(AiyagariConfig(grid=GridSpecConfig(n_points=60)),
                    method="egm", aggregation="distribution",
                    solver=SolverConfig(method="egm", pushforward="banded"),
                    equilibrium=EquilibriumConfig(max_iter=3),
                    on_nonconvergence="ignore")
        assert res.mu is not None
        assert float(res.mu.sum()) == pytest.approx(1.0, abs=1e-9)
