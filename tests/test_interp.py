"""Interpolation-kernel tests against SciPy oracles (SURVEY.md §4.1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.interpolate import PchipInterpolator, RegularGridInterpolator

from aiyagari_tpu.ops.interp import (
    interp2d_linear,
    linear_interp,
    linear_interp_rows,
    masked_pchip_interp,
    pchip_interp,
    pchip_slopes,
)


class TestLinearInterp:
    def test_matches_numpy_inside(self, rng):
        x = np.sort(rng.uniform(0, 10, 40))
        y = np.sin(x)
        q = rng.uniform(x[0], x[-1], 100)
        np.testing.assert_allclose(linear_interp(jnp.array(x), jnp.array(y), jnp.array(q)),
                                   np.interp(q, x, y), atol=1e-12)

    def test_linear_extrapolation(self):
        x = np.array([0.0, 1.0, 2.0])
        y = np.array([0.0, 2.0, 6.0])
        # Below: slope 2 -> y(-1) = -2. Above: slope 4 -> y(3) = 10.
        out = linear_interp(jnp.array(x), jnp.array(y), jnp.array([-1.0, 3.0]))
        np.testing.assert_allclose(out, [-2.0, 10.0], atol=1e-12)

    def test_rows_variant(self, rng):
        x = np.sort(rng.uniform(0, 5, 30))
        Y = rng.normal(size=(8, 30))
        q = rng.uniform(-1, 6, 8)
        got = linear_interp_rows(jnp.array(x), jnp.array(Y), jnp.array(q))
        for i in range(8):
            want = linear_interp(jnp.array(x), jnp.array(Y[i]), jnp.array(q[i]))
            np.testing.assert_allclose(got[i], want, atol=1e-12)


class TestStatePolicyInterp:
    def test_matches_gather_path(self, rng):
        from aiyagari_tpu.ops.interp import state_policy_interp

        x = np.sort(rng.uniform(0, 100, 60))
        policies = rng.normal(size=(4, 60)) * 50
        states = rng.integers(0, 4, 500)
        q = rng.uniform(-10, 120, 500)  # includes extrapolation range
        got = np.asarray(state_policy_interp(jnp.array(x), jnp.array(policies),
                                             jnp.array(states), jnp.array(q)))
        for b in range(500):
            want = float(linear_interp(jnp.array(x), jnp.array(policies[states[b]]),
                                       jnp.array(q[b])))
            assert abs(got[b] - want) < 1e-9, b

    def test_analytic_power_route_matches_stored_knots(self, rng):
        # The analytic-bucket route (no knot array, closed-form brackets)
        # agrees with the stored-knot route on a power grid whose segments
        # are resolvable — including edge-segment extrapolation both sides.
        from aiyagari_tpu.ops.interp import (
            state_policy_interp,
            state_policy_interp_power,
        )

        lo, hi, power, n = 0.5, 100.0, 2.0, 60
        x = lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power
        policies = rng.normal(size=(4, n)) * 50
        states = rng.integers(0, 4, 500)
        q = rng.uniform(-10, 120, 500)
        got = np.asarray(state_policy_interp_power(
            jnp.array(policies), jnp.array(states), jnp.array(q),
            lo=lo, hi=hi, power=power))
        want = np.asarray(state_policy_interp(
            jnp.array(x), jnp.array(policies), jnp.array(states), jnp.array(q)))
        np.testing.assert_allclose(got, want, atol=1e-9)

    def test_analytic_power_route_collapsed_segments_stay_finite(self, rng):
        # The K-S power-7 geometry at f32: bottom segments are narrower than
        # f32 resolution (first segment ~1e-11 at span 1000); the route must
        # degrade them to the left knot value, never divide by a collapsed
        # width (the unguarded form walked a panel mean negative — see the
        # docstring). Values must stay inside the policy row's hull since
        # every query is in range.
        from aiyagari_tpu.ops.interp import state_policy_interp_power

        lo, hi, power, n = 1e-4, 1000.0, 7.0, 100
        policies = jnp.asarray(
            np.sort(rng.uniform(0.0, 900.0, size=(4, n)), axis=1), jnp.float32)
        states = jnp.asarray(rng.integers(0, 4, 4000), jnp.int32)
        q = jnp.asarray(
            np.geomspace(lo, hi, 4000) * rng.uniform(0.9, 1.1, 4000),
            jnp.float32)
        q = jnp.clip(q, lo, hi)
        got = np.asarray(state_policy_interp_power(
            policies, states, q, lo=lo, hi=hi, power=power))
        assert np.isfinite(got).all()
        assert (got >= float(policies.min()) - 1e-3).all()
        assert (got <= float(policies.max()) + 1e-3).all()


class TestPchip:
    def test_matches_scipy(self, rng):
        # SciPy's PchipInterpolator implements the same Fritsch-Carlson
        # algorithm as MATLAB's pchip.
        x = np.sort(rng.uniform(0, 10, 25))
        y = np.cumsum(rng.uniform(0.1, 1.0, 25))  # monotone data
        q = rng.uniform(x[0], x[-1], 200)
        got = pchip_interp(jnp.array(x), jnp.array(y), jnp.array(q))
        want = PchipInterpolator(x, y)(q)
        np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-10)

    def test_matches_scipy_nonmonotone(self, rng):
        x = np.linspace(0, 4 * np.pi, 30)
        y = np.sin(x) + 0.1 * rng.normal(size=30)
        q = rng.uniform(x[0], x[-1], 200)
        got = pchip_interp(jnp.array(x), jnp.array(y), jnp.array(q))
        want = PchipInterpolator(x, y)(q)
        np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-9)

    def test_clamps_outside(self):
        x = np.array([0.0, 1.0, 2.0, 3.0])
        y = np.array([0.0, 1.0, 4.0, 9.0])
        out = pchip_interp(jnp.array(x), jnp.array(y), jnp.array([-5.0, 8.0]))
        np.testing.assert_allclose(out, [0.0, 9.0], atol=1e-12)

    def test_monotonicity_preserved(self, rng):
        x = np.sort(rng.uniform(0, 10, 20))
        y = np.cumsum(rng.uniform(0.0, 1.0, 20))
        q = np.linspace(x[0], x[-1], 500)
        out = np.asarray(pchip_interp(jnp.array(x), jnp.array(y), jnp.array(q)))
        assert (np.diff(out) >= -1e-12).all()

    def test_slopes_shape(self, rng):
        x = np.sort(rng.uniform(0, 1, 12))
        y = rng.normal(size=12)
        assert pchip_slopes(jnp.array(x), jnp.array(y)).shape == (12,)


class TestMaskedPchip:
    def test_matches_scipy_on_valid_subset(self, rng):
        # Emulate the KS-EGM path: some knots invalid, queries within range,
        # nearest extrapolation outside.
        n = 40
        x = np.sort(rng.uniform(0, 10, n))
        y = np.cumsum(rng.uniform(0.05, 1.0, n))
        valid = (x >= 2.0) & (x <= 8.0)
        xs = np.where(valid, x, np.inf)
        order = np.argsort(xs)
        xs, ys = xs[order], y[order]
        n_valid = int(valid.sum())
        q = rng.uniform(0.0, 10.0, 300)
        got = masked_pchip_interp(jnp.array(xs), jnp.array(ys), jnp.int32(n_valid), jnp.array(q))
        ref = PchipInterpolator(x[valid], y[valid])
        want = ref(np.clip(q, x[valid][0], x[valid][-1]))
        np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-9)

    def test_all_valid_matches_plain(self, rng):
        x = np.sort(rng.uniform(0, 10, 20))
        y = np.cumsum(rng.uniform(0.05, 1.0, 20))
        q = rng.uniform(0, 10, 50)
        got = masked_pchip_interp(jnp.array(x), jnp.array(y), jnp.int32(20), jnp.array(q))
        want = pchip_interp(jnp.array(x), jnp.array(y), jnp.array(q))
        np.testing.assert_allclose(got, want, rtol=1e-11, atol=1e-11)


class TestInterp2D:
    def test_matches_scipy(self, rng):
        x = np.sort(rng.uniform(0, 10, 15))
        ygrid = np.sort(rng.uniform(0, 5, 7))
        Z = rng.normal(size=(15, 7))
        qx = rng.uniform(x[0], x[-1], 50)
        qy = rng.uniform(ygrid[0], ygrid[-1], 50)
        got = interp2d_linear(jnp.array(x), jnp.array(ygrid), jnp.array(Z),
                              jnp.array(qx), jnp.array(qy))
        want = RegularGridInterpolator((x, ygrid), Z)(np.stack([qx, qy], 1))
        np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-10)

    def test_extrapolates_linearly(self):
        x = np.array([0.0, 1.0])
        ygrid = np.array([0.0, 1.0])
        Z = np.array([[0.0, 1.0], [2.0, 3.0]])  # Z = 2x + y
        got = interp2d_linear(jnp.array(x), jnp.array(ygrid), jnp.array(Z),
                              jnp.array([2.0, -1.0]), jnp.array([3.0, -2.0]))
        np.testing.assert_allclose(got, [2 * 2.0 + 3.0, 2 * -1.0 + -2.0], atol=1e-12)


class TestPowerGridInversion:
    """ops/interp.inverse_interp_power_grid — the gather-free EGM inversion."""

    def test_matches_generic_linear_interp(self):
        from aiyagari_tpu.ops.interp import inverse_interp_power_grid, linear_interp

        rng = np.random.default_rng(0)
        for (n_k, n_q, power) in [(400, 400, 2.0), (1000, 400, 2.0), (400, 1000, 3.0)]:
            lo, hi = 0.0, 52.0
            gk = lo + (hi - lo) * (np.arange(n_k) / (n_k - 1)) ** power
            x = np.sort((gk + 0.3 * np.sin(gk / 7.0) + 0.8) / 1.04 - 0.5)
            xq = jnp.asarray(np.tile(x, (3, 1)))
            got = np.asarray(inverse_interp_power_grid(xq, lo, hi, power, n_q))
            gq = lo + (hi - lo) * (np.arange(n_q) / (n_q - 1)) ** power
            want = np.asarray(jax.vmap(
                lambda xx: linear_interp(jnp.asarray(xx), jnp.asarray(gk), jnp.asarray(gq))
            )(xq))
            # Above the last knot the fast path truncates to the top knot-grid
            # value (the framework's grid-top rule) instead of extrapolating.
            top = np.tile(gq[None, :] > x[-1], (3, 1))
            assert np.abs(got - want)[~top].max() < 1e-10
            assert np.abs(got[top] - gk[-1]).max() < 1e-10 if top.any() else True

    def test_prolong_power_grid_matches_linear_interp(self):
        # The multigrid prolongation's closed-form bucket must agree with
        # generic linear interpolation between the two analytic grids.
        from aiyagari_tpu.ops.interp import linear_interp, prolong_power_grid

        rng = np.random.default_rng(3)
        for (n_prev, n_new, power) in [(400, 4000, 2.0), (4000, 400, 2.0), (100, 701, 7.0)]:
            lo, hi = 0.0, 52.0
            gp = lo + (hi - lo) * (np.arange(n_prev) / (n_prev - 1)) ** power
            gn = lo + (hi - lo) * (np.arange(n_new) / (n_new - 1)) ** power
            Y = jnp.asarray(rng.normal(size=(3, n_prev)))
            got = np.asarray(prolong_power_grid(Y, lo, hi, power, n_new))
            want = np.asarray(jax.vmap(
                lambda y: linear_interp(jnp.asarray(gp), y, jnp.asarray(gn))
            )(Y))
            np.testing.assert_allclose(got, want, atol=1e-9)

    def test_prolong_overflow_guard_sizes_stay_correct(self):
        # Sizes where jh*m1 + jl*np1 would wrap int32 (n_prev=524288,
        # n_new=1000001): the entry guard must route these off the exact-
        # remainder fast path; results still match the oracle.
        from aiyagari_tpu.ops.interp import linear_interp, prolong_power_grid

        rng = np.random.default_rng(7)
        n_prev, n_new, power = 524_288, 1_000_001, 2.0
        lo, hi = 0.0, 52.0
        gp = lo + (hi - lo) * (np.arange(n_prev) / (n_prev - 1)) ** power
        gn = lo + (hi - lo) * (np.arange(n_new) / (n_new - 1)) ** power
        Y = jnp.asarray(rng.normal(size=(1, n_prev)))
        got = np.asarray(prolong_power_grid(Y, lo, hi, power, n_new))
        want = np.asarray(linear_interp(jnp.asarray(gp), Y[0], jnp.asarray(gn)))
        np.testing.assert_allclose(got[0], want, atol=1e-7)

    def test_windowed_route_matches_generic(self):
        # n_k > 4096 takes the two-level windowed compare-reduce route (the
        # 40k+-point TPU fast path); same contract as the dense route.
        from aiyagari_tpu.ops.interp import inverse_interp_power_grid, linear_interp

        # Smallest sizes in the windowed regime (cutoff 4096) that still
        # cover the n_k == n_q and both n_k != n_q orientations — these
        # compare-reduce programs are the costliest compiles in the suite on
        # this one-core box.
        for (n_k, n_q) in [(5120, 5120), (6144, 4608), (4608, 6144)]:
            lo, hi, power = 0.0, 52.0, 2.0
            gk = lo + (hi - lo) * (np.arange(n_k) / (n_k - 1)) ** power
            x = np.sort((gk + 0.3 * np.sin(gk / 7.0) + 0.8) / 1.04 - 0.5)
            xq = jnp.asarray(np.tile(x, (3, 1)))
            got = np.asarray(inverse_interp_power_grid(xq, lo, hi, power, n_q))
            assert not np.isnan(got).any()
            gq = lo + (hi - lo) * (np.arange(n_q) / (n_q - 1)) ** power
            want = np.asarray(jax.vmap(
                lambda xx: linear_interp(jnp.asarray(xx), jnp.asarray(gk), jnp.asarray(gq))
            )(xq))
            top = np.tile(gq[None, :] > x[-1], (3, 1))
            assert np.abs(got - want)[~top].max() < 1e-10
            if top.any():
                assert np.abs(got[top] - gk[-1]).max() < 1e-10

    def test_windowed_escape_poisons_with_nan(self):
        # >6x local knot density vs the query grid cannot be bracketed by the
        # static windows; the contract is loud NaN poisoning (the host solver
        # then retries on the generic route), never a silently wrong value.
        from aiyagari_tpu.ops.interp import inverse_interp_power_grid

        n = 8192
        lo, hi, power = 0.0, 52.0, 2.0
        gq = lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power
        # 5,000 knots crammed inside one query interval mid-grid.
        cluster = np.linspace(gq[3000], gq[3001], 5000, endpoint=False)
        rest = gq[np.linspace(0, n - 1, n - 5000).astype(int)]
        x = np.sort(np.concatenate([cluster, rest]))[:n]
        out = np.asarray(inverse_interp_power_grid(jnp.asarray(x), lo, hi, power, n))
        assert np.isnan(out).all()

    def test_safe_solver_matches_generic_route(self):
        # solve_aiyagari_egm_safe on a power grid reaches the same fixed
        # point as the generic exact route.
        from aiyagari_tpu.models.aiyagari import aiyagari_preset
        from aiyagari_tpu.solvers.egm import (
            initial_consumption_guess,
            solve_aiyagari_egm,
            solve_aiyagari_egm_safe,
        )
        from aiyagari_tpu.utils.firm import wage_from_r

        m = aiyagari_preset(grid_size=300)
        w = float(wage_from_r(0.04, m.config.technology.alpha, m.config.technology.delta))
        C0 = initial_consumption_guess(m.a_grid, m.s, 0.04, w)
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta, tol=1e-6, max_iter=2000)
        fast = solve_aiyagari_egm_safe(C0, m.a_grid, m.s, m.P, 0.04, w, m.amin,
                                       grid_power=2.0, **kw)
        slow = solve_aiyagari_egm(C0, m.a_grid, m.s, m.P, 0.04, w, m.amin,
                                  grid_power=0.0, **kw)
        np.testing.assert_allclose(np.asarray(fast.policy_c), np.asarray(slow.policy_c),
                                   atol=1e-8)

    @pytest.mark.slow
    def test_safe_solver_retries_generic_route_on_poison(self, monkeypatch):
        # Wiring of the poison-then-retry cycle: stub the jitted solve so the
        # fast path returns a poisoned (NaN-distance, escaped=True) solution
        # on a windowed-regime grid, and check the wrapper re-dispatches the
        # SAME problem on the generic route and returns its converged answer.
        import aiyagari_tpu.solvers.egm as egm_mod

        calls = []
        real = egm_mod.solve_aiyagari_egm

        def stub(C0, a_grid, s, P, r, w, amin, **kw):
            calls.append(kw["grid_power"])
            sol = real(C0, a_grid, s, P, r, w, amin, **kw)
            if kw["grid_power"] > 0.0:
                return egm_mod.EGMSolution(
                    jnp.full_like(sol.policy_c, jnp.nan), sol.policy_k,
                    sol.policy_l, sol.iterations,
                    jnp.array(jnp.nan, sol.distance.dtype),
                    jnp.array(True))
            return sol

        monkeypatch.setattr(egm_mod, "solve_aiyagari_egm", stub)
        n = 4608   # above the windowed cutoff, so the retry is armed
        a_grid = jnp.asarray(52.0 * (np.arange(n) / (n - 1)) ** 2.0)
        s = jnp.asarray([0.8, 1.2]); P = jnp.asarray([[0.9, 0.1], [0.1, 0.9]])
        C0 = egm_mod.initial_consumption_guess(a_grid, s, 0.04, 1.2)
        # beta=0.85: the wiring claim is contraction-rate-independent, and
        # the faster rate cuts the cold solve ~3x on this one-core box.
        sol = egm_mod.solve_aiyagari_egm_safe(
            C0, a_grid, s, P, 0.04, 1.2, 0.0, sigma=2.0, beta=0.85,
            tol=1e-4, max_iter=1000, grid_power=2.0)
        assert calls == [2.0, 0.0]
        assert float(sol.distance) < 1e-4
        assert not np.isnan(np.asarray(sol.policy_c)).any()

    @pytest.mark.slow
    def test_multiscale_retries_whole_ladder_on_poison(self, monkeypatch):
        # Same wiring check for the stage ladder: a poisoned fast ladder must
        # be re-run end-to-end on the generic route.
        import aiyagari_tpu.solvers.egm as egm_mod

        calls = []
        real = egm_mod.solve_aiyagari_egm

        def stub(C0, a_grid, s, P, r, w, amin, **kw):
            calls.append((int(a_grid.shape[-1]), kw["grid_power"]))
            sol = real(C0, a_grid, s, P, r, w, amin, **kw)
            if kw["grid_power"] > 0.0 and a_grid.shape[-1] > 4096:
                return egm_mod.EGMSolution(
                    jnp.full_like(sol.policy_c, jnp.nan), sol.policy_k,
                    sol.policy_l, sol.iterations,
                    jnp.array(jnp.nan, sol.distance.dtype),
                    jnp.array(True))
            return sol

        monkeypatch.setattr(egm_mod, "solve_aiyagari_egm", stub)
        n = 5000
        a_grid = jnp.asarray(52.0 * (np.arange(n) / (n - 1)) ** 2.0)
        s = jnp.asarray([0.8, 1.2]); P = jnp.asarray([[0.9, 0.1], [0.1, 0.9]])
        sol = egm_mod.solve_aiyagari_egm_multiscale(
            a_grid, s, P, 0.04, 1.2, 0.0, sigma=2.0, beta=0.95,
            tol=1e-5, max_iter=1000, grid_power=2.0, coarsest=400,
            refine_factor=10)
        # Fast ladder [400, 500, 5000] then generic ladder, same stages.
        assert calls == [(400, 2.0), (500, 2.0), (5000, 2.0),
                         (400, 0.0), (500, 0.0), (5000, 0.0)]
        assert float(sol.distance) < 1e-5
        assert not np.isnan(np.asarray(sol.policy_c)).any()

    @pytest.mark.slow
    def test_safe_solver_does_not_retry_on_genuine_divergence(self, monkeypatch):
        # A NaN distance WITHOUT the escape flag is genuine numerical
        # divergence: the wrapper must surface it (one dispatch, NaN result),
        # not mask it behind a doubled-cost generic re-solve.
        import aiyagari_tpu.solvers.egm as egm_mod

        calls = []
        real = egm_mod.solve_aiyagari_egm

        def stub(C0, a_grid, s, P, r, w, amin, **kw):
            calls.append(kw["grid_power"])
            sol = real(C0, a_grid, s, P, r, w, amin, **kw)
            return egm_mod.EGMSolution(
                jnp.full_like(sol.policy_c, jnp.nan), sol.policy_k,
                sol.policy_l, sol.iterations,
                jnp.array(jnp.nan, sol.distance.dtype),
                jnp.array(False))

        monkeypatch.setattr(egm_mod, "solve_aiyagari_egm", stub)
        n = 4608   # windowed regime, where the old isnan heuristic would retry
        a_grid = jnp.asarray(52.0 * (np.arange(n) / (n - 1)) ** 2.0)
        s = jnp.asarray([0.8, 1.2]); P = jnp.asarray([[0.9, 0.1], [0.1, 0.9]])
        C0 = egm_mod.initial_consumption_guess(a_grid, s, 0.04, 1.2)
        # beta=0.85: the wiring claim is contraction-rate-independent, and
        # the faster rate cuts the cold solve ~3x on this one-core box.
        sol = egm_mod.solve_aiyagari_egm_safe(
            C0, a_grid, s, P, 0.04, 1.2, 0.0, sigma=2.0, beta=0.85,
            tol=1e-4, max_iter=1000, grid_power=2.0)
        assert calls == [2.0]
        assert np.isnan(float(sol.distance))

    def test_multiscale_egm_rejects_non_power_grid(self):
        from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_multiscale

        a_grid = jnp.linspace(0.0, 52.0, 800)
        s = jnp.asarray([0.8, 1.2]); P = jnp.asarray([[0.9, 0.1], [0.1, 0.9]])
        with pytest.raises(ValueError, match="power-spaced"):
            solve_aiyagari_egm_multiscale(
                a_grid, s, P, 0.04, 1.2, 0.0, sigma=2.0, beta=0.95,
                tol=1e-5, max_iter=1000, grid_power=0.0)

    def test_windowed_escape_flag_reported(self):
        # with_escape=True surfaces the escape bit alongside the NaN poison.
        from aiyagari_tpu.ops.interp import inverse_interp_power_grid

        n = 8192
        lo, hi, power = 0.0, 52.0, 2.0
        gq = lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power
        cluster = np.linspace(gq[3000], gq[3001], 5000, endpoint=False)
        rest = gq[np.linspace(0, n - 1, n - 5000).astype(int)]
        x = np.sort(np.concatenate([cluster, rest]))[:n]
        out, esc = inverse_interp_power_grid(jnp.asarray(x), lo, hi, power, n,
                                             with_escape=True)
        assert bool(esc) and np.isnan(np.asarray(out)).all()
        # Benign knots: flag stays down.
        out2, esc2 = inverse_interp_power_grid(jnp.asarray(gq * 0.97), lo, hi,
                                               power, n, with_escape=True)
        assert not bool(esc2) and not np.isnan(np.asarray(out2)).any()

    def test_monotone_value_interp_dense_matches_linear(self):
        # interp_monotone_power_grid == linear_interp for monotone data on
        # the dense route (plus nearest-above-top semantics).
        from aiyagari_tpu.ops.interp import interp_monotone_power_grid, linear_interp

        n_k, n_q = 1800, 2048
        lo, hi, power = 0.0, 52.0, 2.0
        gk = lo + (hi - lo) * (np.arange(n_k) / (n_k - 1)) ** power
        x = np.sort((gk * 0.9 + 0.3 * np.sin(gk / 5.0) + 0.5))
        y = np.cumsum(np.abs(np.sin(x)) + 0.01)          # monotone values
        gq = lo + (hi - lo) * (np.arange(n_q) / (n_q - 1)) ** power
        got = np.asarray(interp_monotone_power_grid(
            jnp.asarray(x), jnp.asarray(y), lo, hi, power, n_q))
        q_clamped = np.minimum(gq, x[-1])
        want = np.asarray(linear_interp(jnp.asarray(x), jnp.asarray(y),
                                        jnp.asarray(q_clamped)))
        np.testing.assert_allclose(got, want, atol=1e-9)

    def test_monotone_value_interp_windowed_matches_dense(self):
        from aiyagari_tpu.ops.interp import interp_monotone_power_grid

        n_k = n_q = 5120   # windowed regime (cutoff 4096)
        lo, hi, power = 0.0, 52.0, 2.0
        gk = lo + (hi - lo) * (np.arange(n_k) / (n_k - 1)) ** power
        x = np.sort((gk + 0.3 * np.sin(gk / 7.0) + 0.8) / 1.04 - 0.5)
        y = np.cumsum(np.abs(np.cos(x)) + 0.01)
        xq, yq = jnp.asarray(np.tile(x, (2, 1))), jnp.asarray(np.tile(y, (2, 1)))
        got, esc = interp_monotone_power_grid(xq, yq, lo, hi, power, n_q,
                                              with_escape=True)
        assert not bool(esc)
        # Dense oracle: same kernel structure with the windowed route forced
        # off by size — recompute row 0 via linear interpolation.
        from aiyagari_tpu.ops.interp import linear_interp

        gq = lo + (hi - lo) * (np.arange(n_q) / (n_q - 1)) ** power
        want = np.asarray(linear_interp(jnp.asarray(x), jnp.asarray(y),
                                        jnp.asarray(np.minimum(gq, x[-1]))))
        np.testing.assert_allclose(np.asarray(got)[0], want, atol=1e-9)

    def test_monotone_value_interp_escape_poisons(self):
        from aiyagari_tpu.ops.interp import interp_monotone_power_grid

        n = 8192
        lo, hi, power = 0.0, 52.0, 2.0
        gq = lo + (hi - lo) * (np.arange(n) / (n - 1)) ** power
        cluster = np.linspace(gq[3000], gq[3001], 5000, endpoint=False)
        rest = gq[np.linspace(0, n - 1, n - 5000).astype(int)]
        x = np.sort(np.concatenate([cluster, rest]))[:n]
        y = np.cumsum(np.full(n, 0.01))
        out, esc = interp_monotone_power_grid(jnp.asarray(x), jnp.asarray(y),
                                              lo, hi, power, n, with_escape=True)
        assert bool(esc) and np.isnan(np.asarray(out)).all()

    def test_egm_step_labor_fast_path_matches_generic(self):
        from aiyagari_tpu.models.aiyagari import aiyagari_preset
        from aiyagari_tpu.config import AiyagariConfig, GridSpecConfig, IncomeProcess
        from aiyagari_tpu.ops.egm import egm_step_labor
        from aiyagari_tpu.utils.firm import wage_from_r

        cfg = AiyagariConfig(income=IncomeProcess(rho=0.6, sigma_e=0.2),
                             endogenous_labor=True,
                             grid=GridSpecConfig(n_points=1500))
        from aiyagari_tpu.models.aiyagari import AiyagariModel

        m = AiyagariModel.from_config(cfg)
        w = float(wage_from_r(0.04, cfg.technology.alpha, cfg.technology.delta))
        p = cfg.preferences
        kw = dict(sigma=p.sigma, beta=p.beta, psi=p.psi, eta=p.eta)
        C = jnp.broadcast_to(((1.04) * m.a_grid + w)[None, :], (m.P.shape[0], 1500))
        for _ in range(25):
            C, _, _ = egm_step_labor(C, m.a_grid, m.s, m.P, 0.04, w, m.amin, **kw)
        Cg, kg, lg = egm_step_labor(C, m.a_grid, m.s, m.P, 0.04, w, m.amin, **kw)
        Cf, kf, lf = egm_step_labor(C, m.a_grid, m.s, m.P, 0.04, w, m.amin,
                                    grid_power=2.0, **kw)
        np.testing.assert_allclose(np.asarray(Cf), np.asarray(Cg), atol=1e-10)
        np.testing.assert_allclose(np.asarray(kf), np.asarray(kg), atol=1e-9)
        np.testing.assert_allclose(np.asarray(lf), np.asarray(lg), atol=1e-10)

    def test_egm_step_fast_path_matches_generic(self):
        from aiyagari_tpu.models.aiyagari import aiyagari_preset
        from aiyagari_tpu.ops.egm import egm_step
        from aiyagari_tpu.utils.firm import wage_from_r

        m = aiyagari_preset(grid_size=1500)
        w = float(wage_from_r(0.04, m.config.technology.alpha, m.config.technology.delta))
        mean_s = float(jnp.mean(m.s))
        C = jnp.broadcast_to(((1.04) * m.a_grid + w * mean_s)[None, :], (7, 1500))
        kw = dict(sigma=m.preferences.sigma, beta=m.preferences.beta)
        for _ in range(30):
            C, _ = egm_step(C, m.a_grid, m.s, m.P, 0.04, w, m.amin, **kw)
        _, pg = egm_step(C, m.a_grid, m.s, m.P, 0.04, w, m.amin, **kw)
        _, pf = egm_step(C, m.a_grid, m.s, m.P, 0.04, w, m.amin, grid_power=2.0, **kw)
        np.testing.assert_allclose(np.asarray(pf), np.asarray(pg), atol=1e-10)
