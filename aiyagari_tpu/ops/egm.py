"""Endogenous-grid-method operator kernels for the Aiyagari family.

TPU mapping: the Euler-equation RHS is a dense [N,N]x[N,na] matmul (MXU);
the endogenous-grid inversion is elementwise (VPU); the re-interpolation onto
the exogenous grid is a vmapped searchsorted+gather. The reference's per-state
loops (Aiyagari_EGM.m:74-110) collapse into batched array ops.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from aiyagari_tpu.ops.interp import (
    INVERSE_DENSE_CUTOFF,
    interp_monotone_power_grid,
    inverse_interp_power_grid,
    linear_interp,
)
from aiyagari_tpu.ops.bellman import expectation
from aiyagari_tpu.utils.utility import (
    crra_marginal,
    crra_marginal_inverse,
    labor_foc_inverse,
)

__all__ = ["EGM_KERNELS", "egm_step", "egm_step_labor",
           "egm_step_transition", "constrained_consumption_labor",
           "require_xla_egm_kernel", "resolve_egm_kernel"]

# The EGM sweep kernel routes (SolverConfig.egm_kernel):
#   "auto"          — the platform default; resolves to "xla" until the
#                     fused route is chip-validated (the hook the measured
#                     route selection of the autotuner roadmap item feeds).
#   "xla"           — the reference op-by-op sweep below (matmul + inverse
#                     + endogenous grid + inversion + clamp + budget).
#   "pallas_inverse"— the op-by-op sweep with the windowed grid inversion
#                     routed through the fused Pallas kernel
#                     (ops/pallas_inverse.py; power grids above the dense
#                     cutoff only, same escape contract as the XLA windows).
#   "pallas_fused"  — the whole interp→invert→update chain as one
#                     VMEM-resident Pallas kernel (ops/pallas_egm.py;
#                     never escapes, interpreted off-TPU).
EGM_KERNELS = ("auto", "xla", "pallas_inverse", "pallas_fused")


def _validate_egm_kernel(kernel: str) -> None:
    if kernel not in EGM_KERNELS:
        hint = ""
        if kernel in ("numpy", "reference"):
            hint = (" — the NumPy reference backend is selected via "
                    "BackendConfig(backend='numpy'), not the EGM kernel "
                    "route")
        raise ValueError(
            f"unknown egm_kernel {kernel!r}; expected one of "
            f"{EGM_KERNELS}{hint}")


def resolve_egm_kernel(kernel: str, *, na: Optional[int] = None,
                       dtype=None) -> str:
    """Validate an EGM kernel route name loudly (the typo/numpy rejection
    mirror of ops/pushforward.resolve_backend) and resolve "auto". Called
    at config validation (dispatch) and at every egm_step trace, so a bad
    route name fails before any solve.

    The shipped "auto" default is the XLA chain until the fused kernel is
    validated on real hardware (the pallas_inverse round-2 lesson;
    docs/USAGE.md). With tuning active (tuning/autotuner.py) a measured
    probe for this platform/grid-bucket/dtype — or the roofline prior on
    modeled platforms — wins over the default, and every "auto"
    resolution lands on the active run ledger as a `route_decision`
    event. `na`/`dtype` are optional cache-keying context."""
    _validate_egm_kernel(kernel)
    if kernel != "auto":
        return kernel
    from aiyagari_tpu.tuning.autotuner import resolve_route

    return resolve_route("egm_kernel", "xla", na=na, dtype=dtype)


def require_xla_egm_kernel(kernel: str, where: str) -> str:
    """Accept only routes that resolve to the XLA chain, loudly rejecting
    Pallas routes for sweep chains the fused kernel does not implement
    (the endogenous-labor family). Loud, not silent: quietly running the
    XLA chain would let a caller believe they ran or benchmarked the
    fused route — the exact failure mode the loud route validation exists
    to prevent. "auto" resolves straight to "xla" here WITHOUT consulting
    the tuning cache: a measured fused-route winner describes the
    exogenous chain and must not (and cannot) reroute the labor family —
    a routing constraint, not a decision, so no route_decision is
    emitted."""
    _validate_egm_kernel(kernel)
    if kernel in ("auto", "xla"):
        return "xla"
    raise ValueError(
        f"egm_kernel={kernel!r} is not supported by {where}: the fused "
        "Pallas kernel implements the exogenous-labor EGM chain only; "
        "use egm_kernel='auto' or 'xla' there")


@partial(jax.jit, static_argnames=("grid_power", "with_escape", "egm_kernel",
                                   "matmul_precision"))
def egm_step(C, a_grid, s, P, r, w, amin, *, sigma, beta,
             grid_power: float = 0.0, with_escape: bool = False,
             egm_kernel: str = "xla", matmul_precision: str = "highest"):
    """One EGM policy update, exogenous labor.

    C [N, na] (consumption policy on the exogenous grid) ->
    (C_new [N, na], policy_k [N, na]); with_escape=True appends the windowed
    inversion's scalar escape flag (always False off the fast path), which
    host retry wrappers use to tell a window escape from genuine divergence.

    Steps mirror Aiyagari_EGM.m:74-110:
      1. RHS[i,:] = beta*(1+r) * sum_m P[i,m] u'(C[m,:])   (one matmul)
      2. c_next = u'^{-1}(RHS)  — consumption consistent with choosing a'=grid
      3. endogenous grid a_hat = (c_next + a' - w s)/(1+r)
      4. interpolate a' as a function of a_hat back onto the exogenous grid
      5. clamp at the borrowing limit
      6. consumption from the budget constraint

    grid_power > 0 asserts a_grid is power-spaced with that exponent
    (utils/grids.power_grid) and routes step 4 through the windowed
    compare-reduce inversion (ops/interp.inverse_interp_power_grid) — the
    TPU fast path for 100k+-point grids. POISONING CONTRACT: on grids above
    the kernel's dense cutoff that path may return all-NaN when the
    endogenous grid's local knot density exceeds its static windows; the
    NaN propagates into C_new, the solver's while_loop exits on a NaN
    distance, and a host-level caller must retry with grid_power=0.0
    (solvers/egm.solve_aiyagari_egm_safe does). Jitted callers that cannot
    host-retry should pass grid_power=0.0, the generic sort-based exact
    route.

    matmul_precision relaxes the Euler-expectation contraction for the
    mixed-precision ladder's hot stages (ops/precision.py: "default" is the
    TPU bf16 MXU path); the reference value "highest" keeps the historical
    pinned-HIGHEST behavior.

    egm_kernel selects the sweep route (EGM_KERNELS above): "pallas_fused"
    replaces this whole op chain with the single VMEM-resident Pallas
    kernel (ops/pallas_egm.py — generic-inversion semantics, so grid_power
    is ignored there and the escape flag is identically False);
    "pallas_inverse" keeps the chain but routes the windowed power-grid
    inversion through its fused kernel. Both interpret off-TPU via the
    shared platform probe (ops/pallas_support.pallas_interpret_mode).
    """
    from aiyagari_tpu.ops.precision import matmul_precision_of

    kernel = resolve_egm_kernel(egm_kernel, na=a_grid.shape[-1],
                                dtype=C.dtype)
    if kernel == "pallas_fused":
        from aiyagari_tpu.ops.pallas_egm import egm_sweep_pallas
        from aiyagari_tpu.ops.pallas_support import pallas_interpret_mode

        C_new, policy_k, escaped = egm_sweep_pallas(
            C, a_grid, s, P, r, w, amin, sigma=sigma, beta=beta,
            matmul_precision=matmul_precision,
            interpret=pallas_interpret_mode())
        if with_escape:
            return C_new, policy_k, escaped
        return C_new, policy_k

    RHS = (1.0 + r) * expectation(P, crra_marginal(C, sigma), beta,
                                  precision=matmul_precision_of(matmul_precision))  # [N, na]
    c_next = crra_marginal_inverse(RHS, sigma)                    # [N, na]
    a_hat = (c_next + a_grid[None, :] - w * s[:, None]) / (1.0 + r)

    # a_hat is increasing in a' (c_next is) in exact arithmetic, so linear
    # interp + extrapolation matches interp1(a_hat, a_grid, a_grid, 'linear',
    # 'extrap') at :95. In f32 at 100k+-point grids rounding breaks that
    # monotonicity locally and searchsorted then lands in arbitrary buckets;
    # the running max restores sorted knots (exact no-op in f64). lax.cummax,
    # not the generic associative_scan combinator: the dedicated primitive's
    # HLO compiles in seconds where the combinator's takes tens of seconds on
    # this image's remote-compile path at 40k+ points.
    a_hat = jax.lax.cummax(a_hat, axis=1)
    escaped = jnp.array(False)
    if (grid_power > 0.0 and kernel == "pallas_inverse"
            and a_grid.shape[-1] > INVERSE_DENSE_CUTOFF):
        # Fused TPU kernel over the same window tiling (chunk-skipping,
        # ops/pallas_inverse.py); interpreted off-TPU so the routing stays
        # testable everywhere.
        from aiyagari_tpu.ops.pallas_inverse import inverse_interp_power_grid_pallas
        from aiyagari_tpu.ops.pallas_support import pallas_interpret_mode

        policy_k, escaped = inverse_interp_power_grid_pallas(
            a_hat, a_grid[0], a_grid[-1], grid_power, a_grid.shape[-1],
            interpret=pallas_interpret_mode(),
        )
    elif grid_power > 0.0:
        policy_k, escaped = inverse_interp_power_grid(
            a_hat, a_grid[0], a_grid[-1], grid_power, a_grid.shape[-1],
            with_escape=True,
        )
    else:
        policy_k = jax.vmap(lambda ah: linear_interp(ah, a_grid, a_grid))(a_hat)
    # Clamp to the grid top as well as the borrowing limit: above the last
    # endogenous knot the reference extrapolates linearly, but over a long
    # extrapolation range f32 noise in the edge-segment slope feeds back
    # through the Euler RHS and the iteration never settles (measured at grid
    # 40k, f32: oscillation O(10)); truncating at amax matches the discrete
    # VFI solver's choice set.
    policy_k = jnp.clip(policy_k, amin, a_grid[-1])               # :98
    C_new = (1.0 + r) * a_grid[None, :] + w * s[:, None] - policy_k
    if with_escape:
        return C_new, policy_k, escaped
    return C_new, policy_k


@partial(jax.jit, static_argnames=("matmul_precision", "egm_kernel"))
def egm_step_transition(C_next, a_grid, s, P, r_next, r_now, w_now, amin_now,
                        *, sigma_now, sigma_next, beta_now,
                        matmul_precision: str = "highest",
                        egm_kernel: str = "xla"):
    """One backward EGM step along a perfect-foresight transition path
    (transition/path.py): the stationary egm_step generalized to prices and
    preferences that differ between today and tomorrow.

    C_next [N, na] is the consumption policy AT t+1 on the exogenous grid;
    returns (C_now [N, na], policy_k [N, na]) at t. The Euler equation dates
    each object explicitly:

        u'_{sigma_t}(c_t) = beta_t * (1 + r_{t+1}) * E_t u'_{sigma_{t+1}}(c_{t+1})

    so r_next (the return earned between t and t+1) discounts tomorrow's
    marginal utility, while (r_now, w_now) price today's budget constraint
    c_t + a' = (1+r_t) a + w_t s. In a stationary environment every dated
    argument collapses to its steady value and this reduces exactly to
    egm_step's arithmetic (pinned by tests/test_transition.py's flat-path
    identity).

    Every argument is a traced operand — one compile covers the whole time
    scan AND vmapped shock-scenario batches (transition sweeps). Only the
    generic sort-free exact inversion route is offered (the stationary
    kernel's windowed power-grid fast path needs a host-level escape retry
    that a fused time scan cannot perform — the same contract that keeps
    equilibrium/batched.py on grid_power=0). matmul_precision relaxes the
    expectation contraction for the mixed-precision ladder's hot rounds
    (transition/mit.py), exactly as in egm_step.

    egm_kernel="pallas_fused" routes the whole dated chain through the
    VMEM-resident Pallas kernel (ops/pallas_egm.egm_sweep_transition_pallas
    — same generic-inversion semantics as this operator, so every backward
    scan step of transition/path.py reads the policy once instead of per
    op). "pallas_inverse" is rejected here: it rides the windowed
    power-grid fast path, whose host-retry escape contract a fused time
    scan cannot honor (the same reason this operator never takes
    grid_power).
    """
    from aiyagari_tpu.ops.precision import matmul_precision_of

    kernel = resolve_egm_kernel(egm_kernel, na=a_grid.shape[-1],
                                dtype=C_next.dtype)
    if kernel == "pallas_inverse":
        raise ValueError(
            "egm_step_transition supports egm_kernel 'auto'/'xla'/"
            "'pallas_fused' only: the windowed pallas_inverse route needs "
            "a host-level escape retry that a fused time scan cannot "
            "perform (module docstring)")
    if kernel == "pallas_fused":
        from aiyagari_tpu.ops.pallas_egm import egm_sweep_transition_pallas
        from aiyagari_tpu.ops.pallas_support import pallas_interpret_mode

        C_now, policy_k, _ = egm_sweep_transition_pallas(
            C_next, a_grid, s, P, r_next, r_now, w_now, amin_now,
            sigma_now, sigma_next, beta_now,
            matmul_precision=matmul_precision,
            interpret=pallas_interpret_mode())
        return C_now, policy_k

    RHS = (1.0 + r_next) * expectation(P, crra_marginal(C_next, sigma_next),
                                       beta_now,
                                       precision=matmul_precision_of(matmul_precision))  # [N, na]
    c_endo = crra_marginal_inverse(RHS, sigma_now)                  # [N, na]
    a_hat = (c_endo + a_grid[None, :] - w_now * s[:, None]) / (1.0 + r_now)
    # Same f32 monotonicity insurance as egm_step (exact no-op in f64).
    a_hat = jax.lax.cummax(a_hat, axis=1)
    policy_k = jax.vmap(lambda ah: linear_interp(ah, a_grid, a_grid))(a_hat)
    # Borrowing limit may be time-varying (borrowing-limit shocks); the grid
    # top truncation matches the stationary solvers' choice set.
    policy_k = jnp.clip(policy_k, amin_now, a_grid[-1])
    C_now = (1.0 + r_now) * a_grid[None, :] + w_now * s[:, None] - policy_k
    return C_now, policy_k


@jax.jit
def constrained_consumption_labor(a_grid, s, r, w, amin, *, sigma,
                                  psi, eta):
    """Static consumption where the borrowing constraint binds (a' = amin):
    damped fixed point of c = (1+r)a + w s l - amin with l from the
    intratemporal FOC. Loop-invariant across EGM sweeps — compute once per
    solve and pass to egm_step_labor (it depends on prices and the grid, not
    on the consumption iterate)."""
    ws = w * s[:, None]
    c_eps = jnp.asarray(1e-6, a_grid.dtype)
    base = (1.0 + r) * a_grid[None, :] - amin

    def _c_iter(c, _):
        l = labor_foc_inverse(ws * crra_marginal(c, sigma), psi, eta)
        return 0.5 * c + 0.5 * jnp.maximum(base + ws * l, c_eps), None

    c_con, _ = jax.lax.scan(_c_iter, jnp.maximum(base + ws, c_eps), None, length=24)
    return c_con


@partial(jax.jit, static_argnames=("grid_power", "with_escape",
                                   "matmul_precision"))
def egm_step_labor(C, a_grid, s, P, r, w, amin, *, sigma, beta,
                   psi, eta, c_constrained=None,
                   grid_power: float = 0.0, with_escape: bool = False,
                   matmul_precision: str = "highest"):
    """One EGM policy update with endogenous labor via the closed-form
    intratemporal FOC l = ((w s u'(c))/psi)^(1/eta).

    C [N, na] -> (C_new, policy_k, policy_l); with_escape=True appends the
    windowed interpolation's scalar escape flag (always False off the fast
    path).

    Mirrors Aiyagari_Endogenous_Labor_EGM.m:67-107, including its two
    documented sequencing choices (kept because they are no-ops at the
    shipped amin=0 parameterization, and flagged in SURVEY.md §3.6):
    the borrowing constraint is imposed on the interpolated *consumption*
    policy where a_grid < amin (:91), and the asset policy is floored at 0
    (:99) rather than amin.

    grid_power > 0 asserts a_grid is power-spaced with that exponent and
    routes the consumption re-interpolation through the windowed
    compare-reduce value interpolation (ops/interp.
    interp_monotone_power_grid) — the same TPU fast path (and NaN-poisoning
    escape contract) as the exogenous family's grid inversion, generalized
    to tabulated values using the consumption policy's monotonicity in a'.
    matmul_precision relaxes the expectation contraction for ladder hot
    stages, exactly as in egm_step.
    """
    from aiyagari_tpu.ops.precision import matmul_precision_of

    ws = w * s[:, None]                                            # [N, 1]
    RHS = (1.0 + r) * expectation(P, crra_marginal(C, sigma), beta,
                                  precision=matmul_precision_of(matmul_precision))
    c_next = crra_marginal_inverse(RHS, sigma)
    l_endo = labor_foc_inverse(ws * crra_marginal(c_next, sigma), psi, eta)   # :86
    a_hat = (c_next + a_grid[None, :] - ws * l_endo) / (1.0 + r)              # :87

    # Interpolate the consumption (not asset) policy onto the exogenous grid
    # (:90). Same f32 monotonicity insurance as egm_step (no-op in f64) on
    # BOTH arrays — the windowed value kernel's bracketing max/min trick
    # needs c_next non-decreasing too — and the same grid-top discipline:
    # queries above the last endogenous knot take that knot's consumption
    # (nearest) instead of riding the edge segment's slope — unbounded
    # linear extrapolation of g_c feeds straight back into the next Euler
    # RHS and oscillates at O(0.1) on f32 fine grids (measured at 20k
    # points; cf. egm_step's asset-policy variant).
    a_hat = jax.lax.cummax(a_hat, axis=1)
    c_next = jax.lax.cummax(c_next, axis=1)
    escaped = jnp.array(False)
    if grid_power > 0.0:
        g_c, escaped = interp_monotone_power_grid(
            a_hat, c_next, a_grid[0], a_grid[-1], grid_power,
            a_grid.shape[-1], with_escape=True,
        )
    else:
        q = jnp.minimum(a_grid[None, :], a_hat[:, -1:])
        g_c = jax.vmap(linear_interp)(a_hat, c_next, q)

    # Constrained region: below the first endogenous knot the borrowing
    # constraint binds (a' = amin); use the exact static solution
    # (constrained_consumption_labor). The reference linearly extrapolates
    # g_c there instead (correct to first order at 400 points, f64), but on
    # f32 fine grids the first-segment slope is rounding noise and the
    # extrapolated consumption oscillates O(0.5) through the Euler RHS —
    # measured at 20k points, state 0, before this replacement.
    if c_constrained is None:
        c_constrained = constrained_consumption_labor(
            a_grid, s, r, w, amin, sigma=sigma, psi=psi, eta=eta
        )
    g_c = jnp.where(a_grid[None, :] < a_hat[:, :1], c_constrained, g_c)

    g_c = jnp.where(a_grid[None, :] < amin, amin, g_c)                        # :91
    policy_l = labor_foc_inverse(ws * crra_marginal(g_c, sigma), psi, eta)    # :95
    policy_k = (1.0 + r) * a_grid[None, :] + ws * policy_l - g_c              # :98
    # Floored at 0 per the reference quirk (:99); capped at the grid top like
    # every other solver in this framework (ops/egm.egm_step rationale).
    policy_k = jnp.clip(policy_k, 0.0, a_grid[-1])
    if with_escape:
        return g_c, policy_k, policy_l, escaped
    return g_c, policy_k, policy_l
