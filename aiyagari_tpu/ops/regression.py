"""Masked per-regime OLS for the aggregate law of motion: log K' = b0 + b1 log K
fit separately by aggregate state, with R-squared — fully on device with static
shapes (the reference grows per-state design matrices in a Python loop and
mldivides them, Krusell_Smith_VFI.m:250-289).
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["masked_ols_loglinear", "alm_regression"]


def masked_ols_loglinear(x: jnp.ndarray, y: jnp.ndarray, mask: jnp.ndarray):
    """Weighted simple regression y = b0 + b1 x over points where mask=1.

    Returns (b0, b1, r2). Closed-form normal equations from masked sums —
    no dynamic shapes, so regimes of any (data-dependent) size jit cleanly.
    """
    m = mask.astype(x.dtype)
    n = jnp.sum(m)
    sx = jnp.sum(m * x)
    sy = jnp.sum(m * y)
    sxx = jnp.sum(m * x * x)
    sxy = jnp.sum(m * x * y)
    denom = n * sxx - sx * sx
    b1 = jnp.where(denom != 0.0, (n * sxy - sx * sy) / denom, 0.0)
    b0 = jnp.where(n > 0.0, (sy - b1 * sx) / jnp.maximum(n, 1.0), 0.0)
    resid = m * (y - b0 - b1 * x)
    ss_res = jnp.sum(resid**2)
    ybar = jnp.where(n > 0.0, sy / jnp.maximum(n, 1.0), 0.0)
    ss_tot = jnp.sum(m * (y - ybar) ** 2)
    r2 = jnp.where(ss_tot > 0.0, 1.0 - ss_res / ss_tot, 0.0)
    return b0, b1, r2


def alm_regression(K_ts: jnp.ndarray, z_path: jnp.ndarray, discard: int):
    """Fit the two-regime aggregate law of motion from a simulated capital path.

    K_ts [T], z_path [T] (0=good, 1=bad). Uses transitions t -> t+1 for
    t in [discard-1, T-2] (the reference's `for t = T_discard:T-1` with
    1-based indexing, Krusell_Smith_VFI.m:253-261).

    Returns (B [4] = [b0_g, b1_g, b0_b, b1_b], r2 [2]).
    """
    T = K_ts.shape[0]
    x = jnp.log(K_ts[:-1])
    y = jnp.log(K_ts[1:])
    t_idx = jnp.arange(T - 1)
    in_window = t_idx >= (discard - 1)
    good = (z_path[:-1] == 0) & in_window
    bad = (z_path[:-1] == 1) & in_window
    b0g, b1g, r2g = masked_ols_loglinear(x, y, good)
    b0b, b1b, r2b = masked_ols_loglinear(x, y, bad)
    return jnp.stack([b0g, b1g, b0b, b1b]), jnp.stack([r2g, r2b])
