"""Bellman-operator kernels for the Aiyagari family, written as batched tensor
reductions.

TPU mapping: the expectation EV = beta * P @ v is a dense [N,N]x[N,na] matmul
(MXU); the choice dimension a' becomes a trailing reduction axis for the VPU.
The reference's per-(state, asset) scalar loop with a vectorized max
(Aiyagari_VFI.m:70-83) becomes one [N, na, na'] tensor max; for grids too large
for HBM the a'-axis is processed in blocks via lax.scan with a running
max/argmax (same result, bounded memory).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.utils.utility import crra_utility, labor_disutility

__all__ = [
    "expectation",
    "bellman_step",
    "bellman_step_labor",
    "choice_utility_tensor",
    "labor_choice_utility_tensor",
    "bellman_step_precomputed",
    "bellman_step_labor_precomputed",
    "howard_eval_step",
    "howard_eval_step_labor",
]


def _neg_inf(dtype):
    return jnp.array(-jnp.inf, dtype)


def expectation(P, v, beta: float, precision=jax.lax.Precision.HIGHEST):
    """EV = beta * P @ v, HIGHEST precision by default. The TPU default f32
    matmul is a single bf16 pass — measured 0.5 absolute error on values
    O(100), which a Howard-accelerated fixed point amplifies by ~1/(1-beta)
    and never converges below. These [N,N]x[N,na] matmuls are a negligible
    share of sweep cost, so the 6-pass f32 form is free insurance.

    `precision` is overridable for the mixed-precision ladder's HOT stages
    only (ops/precision.py): there the residual sits far above the bf16
    error band and the relaxed contraction rides the MXU peak; pass None for
    the backend default. Polish stages keep HIGHEST."""
    return beta * jnp.matmul(P, v, precision=precision)


def bellman_step(v, a_grid, s, P, r, w, *, sigma, beta, block_size: int = 0,
                 use_pallas: bool = False,
                 precision=jax.lax.Precision.HIGHEST):
    """One application of the Bellman operator, exogenous labor.

    v [N, na] -> (v_new [N, na], policy_idx [N, na] int32).

    For each (i, j): v_new = max_{j'} u((1+r)a_j + w s_i - a_{j'}) + EV[i, j']
    with infeasible (c<=0) choices masked to -inf, EV = beta * P @ v.
    Mirrors Aiyagari_VFI.m:70-83 as a single batched reduction.

    sigma/beta are traced operands (they may vary across a vmapped scenario
    batch — the batched-GE refactor); the Pallas route alone still requires a
    concrete Python-float sigma, baked statically into the fused kernel.

    block_size > 0 processes the a' axis in chunks of that size (memory-bounded
    path for very fine grids); 0 means one dense [N, na, na] tensor.
    use_pallas routes the choice reduction through the fused VMEM-tiled TPU
    kernel (ops/pallas_bellman.py; interpreted off-TPU).
    """
    if use_pallas:
        try:
            # Accept any concrete scalar (Python/NumPy/committed jax value);
            # float() raises on tracers, which cannot be baked in statically.
            sigma_static = float(sigma)
        except Exception as e:
            raise TypeError(
                "bellman_step(use_pallas=True) requires a concrete scalar "
                "sigma (the fused kernel bakes it in statically); got "
                f"{sigma!r}"
            ) from e
        return _bellman_step_pallas(v, a_grid, s, P, r, w, sigma=sigma_static,
                                    beta=beta)
    return _bellman_step_xla(v, a_grid, s, P, r, w, sigma, beta,
                             block_size=block_size, precision=precision)


@partial(jax.jit, static_argnames=("sigma",))
def _bellman_step_pallas(v, a_grid, s, P, r, w, *, sigma: float, beta):
    from aiyagari_tpu.ops.pallas_bellman import bellman_max_pallas
    from aiyagari_tpu.ops.pallas_support import pallas_interpret_mode

    EV = expectation(P, v, beta)                          # [N, na']
    coh = (1.0 + r) * a_grid[None, :] + w * s[:, None]    # [N, na]
    return bellman_max_pallas(
        coh, a_grid, EV, sigma=sigma,
        interpret=pallas_interpret_mode(),
    )


@partial(jax.jit, static_argnames=("block_size", "precision"))
def _bellman_step_xla(v, a_grid, s, P, r, w, sigma, beta, *, block_size: int,
                      precision=jax.lax.Precision.HIGHEST):
    N, na = v.shape
    EV = expectation(P, v, beta, precision=precision)                          # [N, na']
    coh = (1.0 + r) * a_grid[None, :] + w * s[:, None]    # [N, na]

    def block_scores(ap_vals, ev_vals):
        c = coh[:, :, None] - ap_vals[None, None, :]      # [N, na, blk]
        u = jnp.where(c > 0.0, crra_utility(jnp.where(c > 0.0, c, 1.0), sigma), _neg_inf(v.dtype))
        return u + ev_vals[:, None, :]                    # [N, na, blk]

    if block_size <= 0 or block_size >= na:
        # Same masked-utility logic as the hoisted path so the two dense forms
        # cannot drift apart.
        U = choice_utility_tensor(a_grid, s, r, w, sigma=sigma, dtype=v.dtype)
        q = U + EV[:, None, :]
        return jnp.max(q, axis=-1), jnp.argmax(q, axis=-1).astype(jnp.int32)

    nblk = -(-na // block_size)
    pad = nblk * block_size - na
    ap_pad = jnp.pad(a_grid, (0, pad))
    ev_pad = jnp.pad(EV, ((0, 0), (0, pad)), constant_values=-jnp.inf)
    ap_blocks = ap_pad.reshape(nblk, block_size)
    ev_blocks = ev_pad.reshape(N, nblk, block_size).transpose(1, 0, 2)

    def body(carry, blk):
        best, best_idx, offset = carry
        ap_vals, ev_vals = blk
        q = block_scores(ap_vals, ev_vals)
        m = jnp.max(q, axis=-1)
        mi = jnp.argmax(q, axis=-1).astype(jnp.int32) + offset
        take_new = m > best                                # strict: ties keep first (MATLAB max)
        return (jnp.where(take_new, m, best), jnp.where(take_new, mi, best_idx), offset + block_size), None

    init = (jnp.full((N, na), -jnp.inf, v.dtype), jnp.zeros((N, na), jnp.int32), jnp.int32(0))
    (best, best_idx, _), _ = jax.lax.scan(body, init, (ap_blocks, ev_blocks))
    return best, best_idx


@partial(jax.jit, static_argnames=("dtype",))
def choice_utility_tensor(a_grid, s, r, w, *, sigma, dtype=None):
    """The loop-invariant part of the Bellman score: masked flow utility
    u((1+r)a_j + w s_i - a_{j'}) over the full [N, na, na'] choice tensor
    (-inf where infeasible). The Bellman operator's per-sweep work depends on
    v only through EV = beta * P @ v, so this tensor can be computed once per
    solve and reused across every sweep of the fixed point — the reference
    recomputes it per (i, j) per sweep (Aiyagari_VFI.m:72-78)."""
    dtype = dtype or a_grid.dtype
    coh = (1.0 + r) * a_grid[None, :] + w * s[:, None]
    c = coh[:, :, None] - a_grid[None, None, :]
    return jnp.where(
        c > 0.0, crra_utility(jnp.where(c > 0.0, c, 1.0), sigma), _neg_inf(dtype)
    ).astype(dtype)


@partial(jax.jit, static_argnames=("precision",))
def bellman_step_precomputed(v, U, P, *, beta,
                             precision=jax.lax.Precision.HIGHEST):
    """Bellman sweep given the precomputed choice-utility tensor: one MXU
    matmul (EV) + a broadcast add + a trailing-axis max. Identical fixed point
    to bellman_step (pinned by test_solvers), ~3x less per-sweep compute."""
    EV = expectation(P, v, beta, precision=precision)
    q = U + EV[:, None, :]
    return jnp.max(q, axis=-1), jnp.argmax(q, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("dtype",))
def labor_choice_utility_tensor(a_grid, labor_grid, s, r, w, *, sigma,
                                psi, eta, dtype=None):
    """Loop-invariant joint-choice utility for the endogenous-labor Bellman:
    u(c) - psi l^(1+eta)/(1+eta) over the [nl, N, na, na'] grid, -inf where
    infeasible. See choice_utility_tensor; the labor axis is leading so a
    flattened (l, a') argmax keeps the reference's first-feasible tie order."""
    dtype = dtype or a_grid.dtype
    coh = ((1.0 + r) * a_grid[None, None, :]
           + w * labor_grid[:, None, None] * s[None, :, None])   # [nl, N, na]
    c = coh[..., None] - a_grid[None, None, None, :]             # [nl, N, na, na']
    u = jnp.where(c > 0.0, crra_utility(jnp.where(c > 0.0, c, 1.0), sigma),
                  _neg_inf(dtype))
    return (u - labor_disutility(labor_grid, psi, eta)[:, None, None, None]).astype(dtype)


@partial(jax.jit, static_argnames=("precision",))
def bellman_step_labor_precomputed(v, U4, P, *, beta,
                                   precision=jax.lax.Precision.HIGHEST):
    """Endogenous-labor Bellman sweep from the precomputed [nl, N, na, na']
    joint-choice tensor: EV matmul + broadcast add + one flattened argmax over
    (l, a'). Same fixed point and tie order as bellman_step_labor."""
    nl, N, na, nap = U4.shape
    EV = expectation(P, v, beta, precision=precision)                                 # [N, na']
    q = U4 + EV[None, :, None, :]                                # [nl, N, na, na']
    flat = q.transpose(1, 2, 0, 3).reshape(N, na, nl * nap)      # l-major choice
    best_flat = jnp.argmax(flat, axis=-1).astype(jnp.int32)
    return jnp.max(flat, axis=-1), best_flat % nap, best_flat // nap


@partial(jax.jit, static_argnames=("precision",))
def bellman_step_labor(v, a_grid, labor_grid, s, P, r, w, *, sigma, beta,
                       psi, eta, precision=jax.lax.Precision.HIGHEST):
    """One Bellman application with a joint (labor x a') discrete choice.

    v [N, na] -> (v_new, policy_a_idx, policy_l_idx).

    Mirrors Aiyagari_Endogenous_Labor_VFI.m:64-122: utility
    u(c) - psi*l^(1+eta)/(1+eta) over the [nl, na'] choice grid, EV precomputed
    once per sweep. The labor axis is scanned (nl is small) so peak memory is
    one [N, na, na'] block per labor point.
    """
    N, na = v.shape
    EV = expectation(P, v, beta, precision=precision)                           # [N, na']
    base = (1.0 + r) * a_grid[None, :]                     # [N=1 broadcast, na]

    def per_labor(carry, l_val):
        best, best_a, best_l, l_idx = carry
        coh = base + (w * l_val) * s[:, None]              # [N, na]
        c = coh[:, :, None] - a_grid[None, None, :]        # [N, na, na']
        feas = c > 0.0
        u = jnp.where(feas, crra_utility(jnp.where(feas, c, 1.0), sigma), _neg_inf(v.dtype))
        q = u - labor_disutility(l_val, psi, eta) + EV[:, None, :]
        m = jnp.max(q, axis=-1)
        mi = jnp.argmax(q, axis=-1).astype(jnp.int32)
        take = m > best
        return (
            jnp.where(take, m, best),
            jnp.where(take, mi, best_a),
            jnp.where(take, l_idx, best_l),
            l_idx + 1,
        ), None

    init = (
        jnp.full((N, na), -jnp.inf, v.dtype),
        jnp.zeros((N, na), jnp.int32),
        jnp.zeros((N, na), jnp.int32),
        jnp.int32(0),
    )
    (best, best_a, best_l, _), _ = jax.lax.scan(per_labor, init, labor_grid)
    return best, best_a, best_l


@partial(jax.jit, static_argnames=("precision",))
def howard_eval_step(v, policy_idx, a_grid, s, P, r, w, *, sigma, beta,
                     precision=jax.lax.Precision.HIGHEST):
    """Policy-evaluation sweep at a fixed discrete policy (Howard acceleration):
    v <- u(c_pol) + beta * (P @ v) gathered at the policy indices."""
    EV = expectation(P, v, beta, precision=precision)                           # [N, na']
    ap = a_grid[policy_idx]                                # [N, na]
    c = (1.0 + r) * a_grid[None, :] + w * s[:, None] - ap
    u = crra_utility(jnp.maximum(c, 1e-300), sigma)
    return u + jnp.take_along_axis(EV, policy_idx, axis=1)


@partial(jax.jit, static_argnames=("precision",))
def howard_eval_step_labor(v, policy_a_idx, policy_l_idx, a_grid, labor_grid, s, P, r, w, *,
                           sigma, beta, psi, eta,
                           precision=jax.lax.Precision.HIGHEST):
    """Howard evaluation sweep for the endogenous-labor discrete policy."""
    EV = expectation(P, v, beta, precision=precision)
    ap = a_grid[policy_a_idx]
    lv = labor_grid[policy_l_idx]
    c = (1.0 + r) * a_grid[None, :] + w * lv * s[:, None] - ap
    u = crra_utility(jnp.maximum(c, 1e-300), sigma) - labor_disutility(lv, psi, eta)
    return u + jnp.take_along_axis(EV, policy_a_idx, axis=1)
