"""Bellman-operator kernels for the Aiyagari family, written as batched tensor
reductions.

TPU mapping: the expectation EV = beta * P @ v is a dense [N,N]x[N,na] matmul
(MXU); the choice dimension a' becomes a trailing reduction axis for the VPU.
The reference's per-(state, asset) scalar loop with a vectorized max
(Aiyagari_VFI.m:70-83) becomes one [N, na, na'] tensor max; for grids too large
for HBM the a'-axis is processed in blocks via lax.scan with a running
max/argmax (same result, bounded memory).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.utils.utility import crra_utility, labor_disutility

__all__ = ["bellman_step", "bellman_step_labor", "howard_eval_step", "howard_eval_step_labor"]


def _neg_inf(dtype):
    return jnp.array(-jnp.inf, dtype)


@partial(jax.jit, static_argnames=("sigma", "beta", "block_size", "use_pallas"))
def bellman_step(v, a_grid, s, P, r, w, *, sigma: float, beta: float, block_size: int = 0,
                 use_pallas: bool = False):
    """One application of the Bellman operator, exogenous labor.

    v [N, na] -> (v_new [N, na], policy_idx [N, na] int32).

    For each (i, j): v_new = max_{j'} u((1+r)a_j + w s_i - a_{j'}) + EV[i, j']
    with infeasible (c<=0) choices masked to -inf, EV = beta * P @ v.
    Mirrors Aiyagari_VFI.m:70-83 as a single batched reduction.

    block_size > 0 processes the a' axis in chunks of that size (memory-bounded
    path for very fine grids); 0 means one dense [N, na, na] tensor.
    use_pallas routes the choice reduction through the fused VMEM-tiled TPU
    kernel (ops/pallas_bellman.py; interpreted off-TPU).
    """
    N, na = v.shape
    EV = beta * P @ v                                     # [N, na']
    coh = (1.0 + r) * a_grid[None, :] + w * s[:, None]    # [N, na]

    if use_pallas:
        from aiyagari_tpu.ops.pallas_bellman import bellman_max_pallas

        return bellman_max_pallas(
            coh, a_grid, EV, sigma=sigma,
            interpret=(jax.default_backend() != "tpu"),
        )

    def block_scores(ap_vals, ev_vals):
        c = coh[:, :, None] - ap_vals[None, None, :]      # [N, na, blk]
        u = jnp.where(c > 0.0, crra_utility(jnp.where(c > 0.0, c, 1.0), sigma), _neg_inf(v.dtype))
        return u + ev_vals[:, None, :]                    # [N, na, blk]

    if block_size <= 0 or block_size >= na:
        q = block_scores(a_grid, EV)
        return jnp.max(q, axis=-1), jnp.argmax(q, axis=-1).astype(jnp.int32)

    nblk = -(-na // block_size)
    pad = nblk * block_size - na
    ap_pad = jnp.pad(a_grid, (0, pad))
    ev_pad = jnp.pad(EV, ((0, 0), (0, pad)), constant_values=-jnp.inf)
    ap_blocks = ap_pad.reshape(nblk, block_size)
    ev_blocks = ev_pad.reshape(N, nblk, block_size).transpose(1, 0, 2)

    def body(carry, blk):
        best, best_idx, offset = carry
        ap_vals, ev_vals = blk
        q = block_scores(ap_vals, ev_vals)
        m = jnp.max(q, axis=-1)
        mi = jnp.argmax(q, axis=-1).astype(jnp.int32) + offset
        take_new = m > best                                # strict: ties keep first (MATLAB max)
        return (jnp.where(take_new, m, best), jnp.where(take_new, mi, best_idx), offset + block_size), None

    init = (jnp.full((N, na), -jnp.inf, v.dtype), jnp.zeros((N, na), jnp.int32), jnp.int32(0))
    (best, best_idx, _), _ = jax.lax.scan(body, init, (ap_blocks, ev_blocks))
    return best, best_idx


@partial(jax.jit, static_argnames=("sigma", "beta", "psi", "eta"))
def bellman_step_labor(v, a_grid, labor_grid, s, P, r, w, *, sigma: float, beta: float, psi: float, eta: float):
    """One Bellman application with a joint (labor x a') discrete choice.

    v [N, na] -> (v_new, policy_a_idx, policy_l_idx).

    Mirrors Aiyagari_Endogenous_Labor_VFI.m:64-122: utility
    u(c) - psi*l^(1+eta)/(1+eta) over the [nl, na'] choice grid, EV precomputed
    once per sweep. The labor axis is scanned (nl is small) so peak memory is
    one [N, na, na'] block per labor point.
    """
    N, na = v.shape
    EV = beta * P @ v                                      # [N, na']
    base = (1.0 + r) * a_grid[None, :]                     # [N=1 broadcast, na]

    def per_labor(carry, l_val):
        best, best_a, best_l, l_idx = carry
        coh = base + (w * l_val) * s[:, None]              # [N, na]
        c = coh[:, :, None] - a_grid[None, None, :]        # [N, na, na']
        feas = c > 0.0
        u = jnp.where(feas, crra_utility(jnp.where(feas, c, 1.0), sigma), _neg_inf(v.dtype))
        q = u - labor_disutility(l_val, psi, eta) + EV[:, None, :]
        m = jnp.max(q, axis=-1)
        mi = jnp.argmax(q, axis=-1).astype(jnp.int32)
        take = m > best
        return (
            jnp.where(take, m, best),
            jnp.where(take, mi, best_a),
            jnp.where(take, l_idx, best_l),
            l_idx + 1,
        ), None

    init = (
        jnp.full((N, na), -jnp.inf, v.dtype),
        jnp.zeros((N, na), jnp.int32),
        jnp.zeros((N, na), jnp.int32),
        jnp.int32(0),
    )
    (best, best_a, best_l, _), _ = jax.lax.scan(per_labor, init, labor_grid)
    return best, best_a, best_l


@partial(jax.jit, static_argnames=("sigma", "beta"))
def howard_eval_step(v, policy_idx, a_grid, s, P, r, w, *, sigma: float, beta: float):
    """Policy-evaluation sweep at a fixed discrete policy (Howard acceleration):
    v <- u(c_pol) + beta * (P @ v) gathered at the policy indices."""
    EV = beta * P @ v                                      # [N, na']
    ap = a_grid[policy_idx]                                # [N, na]
    c = (1.0 + r) * a_grid[None, :] + w * s[:, None] - ap
    u = crra_utility(jnp.maximum(c, 1e-300), sigma)
    return u + jnp.take_along_axis(EV, policy_idx, axis=1)


@partial(jax.jit, static_argnames=("sigma", "beta", "psi", "eta"))
def howard_eval_step_labor(v, policy_a_idx, policy_l_idx, a_grid, labor_grid, s, P, r, w, *,
                           sigma: float, beta: float, psi: float, eta: float):
    """Howard evaluation sweep for the endogenous-labor discrete policy."""
    EV = beta * P @ v
    ap = a_grid[policy_a_idx]
    lv = labor_grid[policy_l_idx]
    c = (1.0 + r) * a_grid[None, :] + w * lv * s[:, None] - ap
    u = crra_utility(jnp.maximum(c, 1e-300), sigma) - labor_disutility(lv, psi, eta)
    return u + jnp.take_along_axis(EV, policy_a_idx, axis=1)
