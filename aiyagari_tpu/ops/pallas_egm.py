"""Fused TPU (Pallas) EGM sweep kernel: the whole interp→invert→update chain
of ops/egm.egm_step as ONE VMEM-resident pass over the policy.

Why: every bench round says the EGM sweep is HBM-bound, not compute-bound
(BENCH_r03/r04: bound "hbm", membw_frac ~0.4-0.45 against mfu ~0.001). The
XLA sweep runs as separate ops — expectation matmul, u'-inverse, endogenous
grid, cummax, grid inversion, clamp, budget update — and each re-reads the
full [N, na] state from HBM, ~10 array streams per sweep
(diagnostics/roofline.egm_sweep_cost). This kernel reads C, a_grid and P
ONCE, keeps every intermediate in VMEM, and writes only the finished
(C_new, policy_k) tiles back: 3 streams instead of ~10, the direct ~3x on
the memory-bound roofline (priced, not asserted:
diagnostics/roofline.egm_fused_sweep_cost).

Geometry (ops/pallas_pushforward.py is the tiling template): the output is
tiled over the exogenous asset grid (grid = query tiles of `block_q`
lanes); the full C / a_grid / P stay resident across programs (identical
block indices — the pipeline fetches them once). The key fusion obstacle is
the grid inversion: query tile t's bracketing knots a_hat[K] live at
DATA-DEPENDENT columns, possibly far from tile t. Because the chain is
column-separable — a_hat[:, j] needs only C[:, j] (the Euler expectation is
a per-column [N,N]x[N,1] contraction) — each program rebuilds exactly the
knot columns it needs from the resident C instead of reading a materialized
a_hat: it scans the source axis in `block_src`-wide chunks and, per chunk,
evaluates the chain at the chunk's two BOUNDARY columns only (two matvecs +
a few VPU ops). The boundary values drive the pallas_inverse-style
`@pl.when` chunk gating: a chunk entirely below the tile's query span
contributes its last knot/grid value as (x0, y0) candidates, one entirely
above its first as (x1, y1) — O(1) scalar-broadcast work — and only chunks
actually straddling the span (~(1+r) of them for the EGM operator's
endogenous grids, whose knot spacing is bounded below by grid
spacing/(1+r)) pay the dense work: the full chain on the chunk's columns,
a masked-reduce cummax, and the [N, block_src, block_q] bracket
compare-reduce. The skip gates hold for ANY iterate, not only monotone
ones: the below gate bounds the chunk's a_hat by the chain at the chunk's
columnwise C-max (the chain is monotone in C and a_grid — _sweep_kernel),
so a non-monotone iterate — an Anderson overshoot, an arbitrary warm
start — just skips less; it is never silently mis-bracketed. The scan
covers the whole knot row, so unlike the windowed XLA fast path this
kernel needs no escape: `escaped` is identically False, and the route
composes with solve_aiyagari_egm_safe's retry contract trivially (the
retry never arms).

Semantics match egm_step's GENERIC inversion route (grid_power=0:
cummax + linear_interp(a_hat, a_grid, a_grid) + clip + budget) — monotone
bracketing by masked max/min reduces, first-segment linear extrapolation
below the first knot, grid-top saturation above the last — so one kernel
serves plain sweeps, the mixed-precision ladder's hot stages (the Euler
contraction takes the stage's matmul precision), and the dated transition
operator (egm_step_transition is the same chain with per-date prices; the
stationary sweep is the collapsed special case). Parity: bitwise-ordering
identical per column in exact arithmetic; tier-1 pins <= 1e-9 in f64 and
the documented f32 ulp band (tests/test_pallas_egm.py).

The one divergence from lax.cummax, bounded and stated: the running cummax
CARRY between chunks advances by boundary values (plus the true max of
every densely-scanned chunk), so an interior maximum inside a SKIPPED
chunk is carried one chunk late. The below gate guarantees such a maximum
is strictly below the tile's whole query span, which makes every
knot-vs-query mask decision identical to the exact cummax's — the (y0, y1)
bracket VALUES are exact — and only the x0 interpolation abscissa can sit
low, moving the output within its exact bracket: deviation vs the XLA
route is bounded by the local grid spacing, the same class as the
documented tie-handling divergence of the windowed routes (and identically
zero for monotone-in-exact-arithmetic iterates, the EGM operator's normal
regime — there f32 rounding wiggles are sub-ulp of |a_hat|, invisible
under the parity bands).

interpret=True runs the Pallas interpreter off-TPU (CPU tier-1 parity
pins) exactly like pallas_bellman / pallas_inverse / pallas_pushforward;
the route stays opt-in (SolverConfig.egm_kernel="pallas_fused") until
validated on real hardware — the pallas_inverse round-2 lesson: Mosaic
lowerings must be cross-checked on chip before any solver defaults to
them. Compile-time scaling caveat shared with the other fused kernels: the
chunk scan is a static unroll (Mosaic rejects dynamically indexed sublane
loads), so trace size grows with na/block_src.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["egm_sweep_pallas", "egm_sweep_transition_pallas"]

_BLOCK_Q = 256     # queries (output lanes) per program
_BLOCK_SRC = 256   # source columns per scanned chunk


def _sweep_kernel(prm_ref, C_ref, agf_ref, agt_ref, s_ref, P_ref,
                  cnew_ref, pk_ref, x0_ref, x1_ref, y0_ref, y1_ref, m_ref, *,
                  block_q: int, block_src: int, n_chunks: int, na: int,
                  precision):
    """One query-tile program: rebuild the knot columns it needs from the
    resident C, bracket its queries by masked reduces, finish the linear
    inverse, clamp, and emit the budget-consistent consumption tile."""
    S, CH = block_q, block_src
    dtype = C_ref.dtype
    r_next, r_now, w_now, amin_now, sig_now, sig_next, beta_now = (
        prm_ref[0], prm_ref[1], prm_ref[2], prm_ref[3], prm_ref[4],
        prm_ref[5], prm_ref[6])
    sv = s_ref[...]                  # [N, 1]
    Pm = P_ref[...]                  # [N, N]
    q = agt_ref[0, :]                # [S] this tile's exogenous queries
    q_lo = q[0]
    q_hi = q[S - 1]
    neg = jnp.array(-jnp.inf, dtype)
    pos = jnp.array(jnp.inf, dtype)

    def a_hat_of(Cc, agc):
        # The dated EGM chain for columns (Cc [N, k], agc [k]): Euler RHS
        # on the MXU at the ladder stage's precision, marginal-utility
        # inversion, endogenous grid. Column-separable, so evaluating a
        # slice is exact — identical per-column contraction order to the
        # full-width XLA expectation.
        rhs = (1.0 + r_next) * beta_now * jax.lax.dot_general(
            Pm, Cc ** (-sig_next),
            dimension_numbers=(((1,), (0,)), ((), ())),
            precision=precision)
        c_endo = rhs ** (-1.0 / sig_now)
        return (c_endo + agc[None, :] - w_now * sv) / (1.0 + r_now)

    def a_hat_col(j):
        return a_hat_of(C_ref[:, j:j + 1], agf_ref[0, j:j + 1])   # [N, 1]

    # First two knots: the below-range extrapolation segment (linear_interp
    # edge semantics). cummax is a no-op at column 0 by definition.
    h0 = a_hat_col(0)
    h1 = jnp.maximum(h0, a_hat_col(1))

    # Scratch accumulators, re-initialized per program: bracketing knot
    # values (x0, x1), their grid values (y0, y1), and the running cummax
    # carry m (the prefix max of raw a_hat over all columns scanned so
    # far — the kernel-side form of egm_step's f32 monotonicity insurance).
    x0_ref[...] = jnp.full_like(x0_ref, neg)
    x1_ref[...] = jnp.full_like(x1_ref, pos)
    y0_ref[...] = jnp.full_like(y0_ref, neg)
    y1_ref[...] = jnp.full_like(y1_ref, pos)
    m_ref[...] = jnp.full_like(m_ref, neg)

    # Static unroll over source chunks, ascending (the cummax carry is
    # order-dependent; Mosaic rejects dynamically indexed sublane loads —
    # the pallas_inverse/pallas_pushforward pattern).
    for c in range(n_chunks):
        jf, jl = c * CH, (c + 1) * CH - 1
        ab_f = a_hat_col(jf)
        ab_l = a_hat_col(jl)
        first_cm = jnp.maximum(m_ref[...], ab_f)     # [N, 1] effective knots
        last_cm = jnp.maximum(first_cm, ab_l)
        ag_f = agf_ref[0, jf]
        ag_l = agf_ref[0, jl]
        # Skip gates must hold for ANY iterate, monotone or not, so they
        # bound the chunk's cummaxed a_hat EXACTLY from boundary data:
        #   * its minimum IS the first effective knot, max(m, a_hat[jf])
        #     (cummaxed values are non-decreasing) — the above gate;
        #   * its maximum is bounded by the chain evaluated at the chunk's
        #     columnwise C-max and top grid value (a_hat is increasing in
        #     every C entry — u' and its inverse are both decreasing — and
        #     in a_grid), so an interior spike a boundary probe would miss
        #     cannot slip through the below gate; a spiked chunk goes
        #     dense instead. For a monotone chunk the bound equals the
        #     last column's value — zero extra dense work on the normal
        #     path.
        # Gates are per-program scalars (@pl.when predication — a lax.cond
        # with vector carries executes BOTH branches as selects, measured
        # 10x on chip in the pallas_inverse rewrite), so a chunk skips only
        # when EVERY row's span misses the tile; a straddle in any row runs
        # the dense branch for all rows (exact for all of them).
        C_ub = jnp.max(C_ref[:, jf:jl + 1], axis=1, keepdims=True)  # [N, 1]
        ub = a_hat_of(C_ub, agf_ref[0, jl:jl + 1])
        below_all = jnp.max(jnp.maximum(m_ref[...], ub)) < q_lo
        above_all = jnp.min(first_cm) >= q_hi

        @pl.when(below_all)
        def _():
            # Entire chunk < every query, all rows: its last effective knot
            # is an x0 candidate, its last grid value the matching y0.
            x0_ref[...] = jnp.maximum(x0_ref[...], last_cm)
            y0_ref[...] = jnp.maximum(y0_ref[...], ag_l)

        @pl.when(above_all)
        def _():
            # Entire chunk >= every query: only its first knot can be the
            # min-at-or-above bracket.
            x1_ref[...] = jnp.minimum(x1_ref[...], first_cm)
            y1_ref[...] = jnp.minimum(y1_ref[...], ag_f)

        @pl.when(jnp.logical_not(below_all | above_all))
        def _():
            agc = agf_ref[0, jf:jl + 1]                    # [CH]
            ah_raw = a_hat_of(C_ref[:, jf:jl + 1], agc)    # [N, CH]
            # Within-chunk cummax as a masked reduce (k <= j prefix max):
            # lax.cummax has no Mosaic lowering; the [N, CH, CH] compare
            # runs only on straddling chunks.
            kk = jax.lax.broadcasted_iota(jnp.int32, (CH, CH), 0)
            jj = jax.lax.broadcasted_iota(jnp.int32, (CH, CH), 1)
            ah_cm = jnp.max(jnp.where((kk <= jj)[None, :, :],
                                      ah_raw[:, :, None], neg), axis=1)
            ah_cm = jnp.maximum(ah_cm, m_ref[...])         # prefix carry
            lt = ah_cm[:, :, None] < q[None, None, :]      # [N, CH, S]
            agb = agc[None, :, None]
            x0_ref[...] = jnp.maximum(
                x0_ref[...],
                jnp.max(jnp.where(lt, ah_cm[:, :, None], neg), axis=1))
            y0_ref[...] = jnp.maximum(
                y0_ref[...], jnp.max(jnp.where(lt, agb, neg), axis=1))
            x1_ref[...] = jnp.minimum(
                x1_ref[...],
                jnp.min(jnp.where(lt, pos, ah_cm[:, :, None]), axis=1))
            y1_ref[...] = jnp.minimum(
                y1_ref[...], jnp.min(jnp.where(lt, pos, agb), axis=1))
            m_ref[...] = jnp.maximum(
                m_ref[...], jnp.max(ah_raw, axis=1, keepdims=True))

        # Advance the cummax carry for every chunk, scanned or skipped
        # (no-op after the dense branch's true-max update). last_cm, not
        # ab_l: it folds BOTH boundary values in, so a spike at the first
        # column of an above-skipped chunk still reaches later chunks'
        # effective knots — dropping it under-carried the plateau and
        # mis-bracketed queries between the later raw values and the
        # spike (caught by the non-monotone crossing repro in tier-1).
        m_ref[...] = jnp.maximum(m_ref[...], last_cm)

    # Finish: piecewise-linear inverse from the bracket data — the
    # _finish_monotone edge semantics with linear_interp's tie guard.
    x0 = x0_ref[...]
    x1 = x1_ref[...]
    y0 = y0_ref[...]
    y1 = y1_ref[...]
    have_lo = x0 > neg
    dx = x1 - x0
    ok = have_lo & (x1 < pos) & (dx > 0)
    tq = jnp.where(ok, (q[None, :] - x0) / jnp.where(ok, dx, 1.0), 0.0)
    # y1 is +inf when no knot sits at-or-above q (query beyond the top
    # knot): select y0 there BEFORE the fma — 0 * inf would poison it.
    out = y0 + tq * (jnp.where(ok, y1, y0) - y0)
    # Below the first knot: linear extrapolation on the first segment
    # (zero-width first segment degrades to the first grid value, the
    # linear_interp collision guard).
    d0 = h1 - h0
    ag0 = agf_ref[0, 0]
    ag1 = agf_ref[0, 1]
    out_below = jnp.where(
        d0 > 0,
        ag0 + (q[None, :] - h0) * (ag1 - ag0) / jnp.where(d0 > 0, d0, 1.0),
        ag0)
    out = jnp.where(have_lo, out, out_below)
    # Clamp (borrowing limit + grid top, egm_step's truncation rationale)
    # and close the budget: the only values this tile writes back to HBM.
    ag_top = agf_ref[0, na - 1]
    pk = jnp.minimum(jnp.maximum(out, amin_now), ag_top)
    pk_ref[...] = pk
    cnew_ref[...] = (1.0 + r_now) * q[None, :] + w_now * sv - pk


@functools.partial(jax.jit, static_argnames=("matmul_precision", "block_q",
                                             "block_src", "interpret"))
def egm_sweep_transition_pallas(C_next, a_grid, s, P, r_next, r_now, w_now,
                                amin_now, sigma_now, sigma_next, beta_now, *,
                                matmul_precision: str = "highest",
                                block_q: int = _BLOCK_Q,
                                block_src: int = _BLOCK_SRC,
                                interpret: bool = False):
    """One fused dated EGM sweep (the ops/egm.egm_step_transition operator):
    C_next [N, na] tomorrow's consumption policy -> (C_now [N, na],
    policy_k [N, na], escaped). Every price/preference argument is a traced
    operand — one compile covers the whole backward time scan. `escaped` is
    identically False (module docstring: the full-row scan cannot escape);
    it is returned so the fused route plugs into the same (out, escaped)
    plumbing as the windowed XLA fast path. matmul_precision (static) is
    the Euler contraction's precision for the ladder's hot stages
    (ops/precision.matmul_precision_of names)."""
    from aiyagari_tpu.ops.precision import matmul_precision_of

    N, na = C_next.shape
    dtype = C_next.dtype
    S = min(block_q, na)
    CH = min(block_src, S)
    if S % CH:
        raise ValueError(
            f"effective block_src {CH} must divide effective block_q {S} "
            f"(requested block_q={block_q}, block_src={block_src}, both "
            f"clamped to na={na})")
    nt = -(-na // S)
    nap = nt * S
    # Edge padding keeps the padded knot columns exact duplicates of the
    # top knot (tied knots change no bracket) and the padded query lanes
    # duplicates of the top query (their outputs are sliced off).
    C_p = jnp.pad(C_next, ((0, 0), (0, nap - na)), mode="edge")
    ag_p = jnp.pad(a_grid, (0, nap - na), mode="edge")[None, :]
    prm = jnp.stack([jnp.asarray(v).astype(dtype) for v in
                     (r_next, r_now, w_now, amin_now, sigma_now, sigma_next,
                      beta_now)])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt,),
        in_specs=[
            # Full-array blocks with constant index maps: fetched once,
            # resident across every program (the pallas_pushforward
            # pattern); the tile view of a_grid is the same padded buffer
            # blocked per program.
            pl.BlockSpec((N, nap), lambda t, prm: (0, 0)),
            pl.BlockSpec((1, nap), lambda t, prm: (0, 0)),
            pl.BlockSpec((1, S), lambda t, prm: (0, t)),
            pl.BlockSpec((N, 1), lambda t, prm: (0, 0)),
            pl.BlockSpec((N, N), lambda t, prm: (0, 0)),
        ],
        out_specs=(pl.BlockSpec((N, S), lambda t, prm: (0, t)),
                   pl.BlockSpec((N, S), lambda t, prm: (0, t))),
        scratch_shapes=[pltpu.VMEM((N, S), dtype)] * 4
                       + [pltpu.VMEM((N, 1), dtype)],
    )
    kern = functools.partial(
        _sweep_kernel, block_q=S, block_src=CH, n_chunks=nap // CH, na=na,
        precision=matmul_precision_of(matmul_precision))
    C_now, policy_k = pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct((N, nap), dtype),
                   jax.ShapeDtypeStruct((N, nap), dtype)),
        interpret=interpret,
    )(prm, C_p, ag_p, ag_p, s.reshape(N, 1).astype(dtype), P.astype(dtype))
    return C_now[:, :na], policy_k[:, :na], jnp.array(False)


@functools.partial(jax.jit, static_argnames=("matmul_precision", "block_q",
                                             "block_src", "interpret"))
def egm_sweep_pallas(C, a_grid, s, P, r, w, amin, *, sigma, beta,
                     matmul_precision: str = "highest",
                     block_q: int = _BLOCK_Q, block_src: int = _BLOCK_SRC,
                     interpret: bool = False):
    """One fused stationary EGM sweep (the ops/egm.egm_step operator):
    C [N, na] -> (C_new [N, na], policy_k [N, na], escaped) — the dated
    kernel with every dated argument collapsed to its stationary value
    (exactly how egm_step relates to egm_step_transition)."""
    return egm_sweep_transition_pallas(
        C, a_grid, s, P, r, r, w, amin, sigma, sigma, beta,
        matmul_precision=matmul_precision, block_q=block_q,
        block_src=block_src, interpret=interpret)
