"""Fused TPU (Pallas) kernel for the windowed power-grid inversion — the EGM
hot operation at 100k+-point grids (the interp1(a_hat, a_grid, a_grid) of
Aiyagari_EGM.m:95; XLA fallback: ops/interp.inverse_interp_power_grid).

Geometry (adapted to Mosaic's tiling rules, a strict coverage superset of
the XLA route's 512-query/3,072-knot windows): each kernel program handles
1,024 queries (XLA tiles 1-D outputs in 1,024s) and reads ONE 16,384-knot
PANEL from a half-panel-stride overlapped panel family, selected per
program by a scalar-prefetched panel index feeding the BlockSpec index_map
— the idiomatic Pallas data-dependent fetch, auto-double-buffered by the
pipeline. (Two earlier forms failed on real hardware: a hand-rolled
HBM->VMEM DMA hit Mosaic alignment-prover limits and then miscompiled
SILENTLY at dynamic offsets, and a two-consecutive-panel BlockSpec variant
corrupted outputs above 40k knots — both caught only by the cross-route
maxdiff check on chip, which is why this route must stay validated on
hardware before any solver uses it.)

The window pass exploits what a fused kernel can and XLA cannot: DYNAMIC
CHUNK SKIPPING. The panel is scanned in 32 chunks of 512 knots; a chunk
entirely below the program's query span contributes `+512` to every count
and its top knot as an x0 candidate (O(S) scalar-broadcast work), a chunk
entirely above contributes its first knot as an x1 candidate, and only the
chunks actually straddling the span (~2 when knot density ~ query density)
pay the dense [1024, 512] compare-reduce. XLA's static dataflow must run
its full [512, 3072] compare-reduce three times per block; the kernel runs
~2/32 of its panel once — identical cnt/x0/x1 by construction (the skipped
chunks' contributions are exact, not approximated).

Escape contract as in the XLA route (NaN poisoning + escaped flag), firing
only when a program's bracket span exceeds its panel's >= 8,192-knot
headroom — strictly rarer than the XLA route's 3,072-knot windows.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from aiyagari_tpu.ops.interp import _INV_KBLOCK, _finish_inverse

__all__ = ["inverse_interp_power_grid_pallas"]

_QBLOCK = 1024        # queries per program (XLA's 1-D output tile)
_PANEL_SLABS = 32     # 512-knot slabs per panel (16,384 knots)
_CHUNK = 512          # knots per scanned chunk


def _window_kernel(pan_ref, lohi_ref, win_ref,
                   cnt_ref, x0_ref, x1_ref, *, power, n_q, nb, dtype):
    """One (row, query-block) program over its prefetched knot panel."""
    S = _QBLOCK
    PW = _PANEL_SLABS * _INV_KBLOCK                  # knots per panel
    b = pl.program_id(1)

    lo = lohi_ref[0]
    hi = lohi_ref[1]
    j = jnp.minimum(
        b * S + jax.lax.broadcasted_iota(jnp.int32, (S,), 0), n_q - 1
    )
    t = j.astype(dtype) / (n_q - 1)
    q = lo + (hi - lo) * t ** power                  # [S]
    q_lo = q[0]
    q_hi = q[S - 1]

    neg = jnp.array(-jnp.inf, dtype)
    pos = jnp.array(jnp.inf, dtype)

    # The output blocks double as the accumulators (read-modify-write on
    # VMEM refs). Chunk skipping MUST be @pl.when predication: a lax.cond
    # with vector carries lowers to selects that execute BOTH branches —
    # measured on chip as a full dense scan of every chunk, ~10x slower
    # than the XLA route at 400k before this rewrite.
    cnt_ref[:] = jnp.zeros((S,), jnp.int32)
    x0_ref[:] = jnp.full((S,), -jnp.inf, dtype)
    x1_ref[:] = jnp.full((S,), jnp.inf, dtype)

    def chunk_body(s_c):
        w_lo = s_c[0]
        w_hi = s_c[_CHUNK - 1]

        @pl.when(w_hi < q_lo)
        def below():
            # Entire chunk < every query: +_CHUNK to all counts, its top
            # knot is an x0 candidate. O(S) scalar-broadcast work.
            cnt_ref[:] = cnt_ref[:] + _CHUNK
            x0_ref[:] = jnp.maximum(x0_ref[:], w_hi)

        @pl.when(jnp.logical_and(w_hi >= q_lo, w_lo < q_hi))
        def straddle():
            lt = s_c[None, :] < q[:, None]           # [S, _CHUNK]
            cnt_ref[:] = cnt_ref[:] + jnp.sum(lt, axis=1).astype(jnp.int32)
            x0_ref[:] = jnp.maximum(
                x0_ref[:], jnp.max(jnp.where(lt, s_c[None, :], neg), axis=1))
            x1_ref[:] = jnp.minimum(
                x1_ref[:], jnp.min(jnp.where(lt, pos, s_c[None, :]), axis=1))

        @pl.when(w_lo >= q_hi)
        def above():
            # Entire chunk >= every query: its first knot is the only
            # candidate (x1 = min knot at-or-above q). Exact.
            x1_ref[:] = jnp.minimum(x1_ref[:], w_lo)

    # Static unroll (Mosaic rejects dynamically indexed sublane loads).
    for c in range(PW // _CHUNK):
        chunk_body(win_ref[c * _CHUNK:(c + 1) * _CHUNK])


@functools.partial(jax.jit, static_argnames=("power", "n_q", "interpret"))
def inverse_interp_power_grid_pallas(x: jnp.ndarray, lo, hi,
                                     power: float, n_q: int,
                                     interpret: bool = False):
    """Drop-in fused-kernel form of the windowed route of
    ops/interp.inverse_interp_power_grid (same contract, always returns
    (out, escaped)): x [..., n_k] sorted knots, n_k > INVERSE_DENSE_CUTOFF
    expected; returns the piecewise-linear inverse on the n_q-point power
    grid, NaN-poisoned with escaped=True when a double panel cannot cover a
    program's bracket span. interpret=True runs the Pallas interpreter (CPU
    tests)."""
    S, KB, P = _QBLOCK, _INV_KBLOCK, _PANEL_SLABS
    PW = P * KB
    HS = PW // 2                     # panel start stride (half a panel)
    dtype = x.dtype
    n_k = x.shape[-1]
    lead = x.shape[:-1]
    xr = x.reshape((-1, n_k))
    R = xr.shape[0]
    nb = -(-n_q // S)

    # Overlapped panels at half-panel stride: panel i covers knots
    # [i*HS, i*HS + PW). Each program reads the panel whose FIRST half
    # contains its first query's bracket slab, guaranteeing >= HS knots of
    # headroom past the bracket start (>= 2.7x the XLA route's windows).
    # Mosaic constraints shape the materialization: 1-D blocks of a
    # lane-multiple size at data-dependent block indices are the reliably
    # supported form (3-D (1,1,PW) blocks and hand-rolled DMAs both failed —
    # module docstring), so the overlapped panels are laid out as one flat
    # [R * n_panels * PW] buffer: all even-start panels (a plain reshape of
    # the padded rows), then all odd-start panels (the same rows shifted by
    # HS). ~2x the knot bytes in HBM — 22 MB at the 400k north star.
    n_half = -(-n_k // HS)
    pos = jnp.array(jnp.inf, dtype)
    xp = jnp.concatenate(
        [xr, jnp.full((R, (n_half + 1) * HS - n_k), pos)], axis=1
    )
    n_even = (n_half + 1) // 2
    n_odd = n_half // 2
    xeven = xp[:, :n_even * PW].reshape(R, n_even, PW)
    xodd = xp[:, HS:HS + n_odd * PW].reshape(R, n_odd, PW)
    npan = n_even + n_odd
    xcat = jnp.concatenate([xeven, xodd], axis=1).reshape(R * npan * PW)

    # Level 1: the bracket SLAB of each program's first query, counted
    # against the 512-knot slab minima only — [R, nb, n_slabs] compares, not
    # the [R, nb, n_k] monster. Exact: slabs are sorted, so the last knot
    # < q lives in the last slab whose first knot is < q.
    nkb_pad = (n_half + 1) * (HS // KB)
    first_els = xp.reshape(R, nkb_pad, KB)[:, :, 0]              # [R, nkb_pad]
    jq = jnp.minimum(jnp.arange(nb) * S, n_q - 1)
    t0 = jq.astype(dtype) / (n_q - 1)
    q_first = lo + (hi - lo) * t0 ** power                       # [nb]
    cnt_slab = jnp.sum(first_els[:, None, :] < q_first[None, :, None],
                       axis=-1).astype(jnp.int32)                # [R, nb]
    bracket_slab = jnp.clip(cnt_slab - 1, 0, nkb_pad - 1)
    start_i = jnp.clip(bracket_slab // (HS // KB), 0, n_half - 1)  # [R, nb]
    # Flat panel index in the [evens | odds] layout.
    pan_flat = jnp.where(start_i % 2 == 0, start_i // 2,
                         n_even + start_i // 2)

    kernel = functools.partial(
        _window_kernel, power=power, n_q=n_q, nb=nb, dtype=dtype,
    )
    flat_block = pl.BlockSpec((S,), lambda r, b, pan, lohi: (r * nb + b,))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(R, nb),
        in_specs=[
            pl.BlockSpec((PW,),
                         lambda r, b, pan, lohi, _n=npan: (r * _n + pan[r * nb + b],)),
        ],
        out_specs=(flat_block, flat_block, flat_block),
    )
    lohi = jnp.stack([jnp.asarray(lo, dtype), jnp.asarray(hi, dtype)])
    cnt_w, x0, x1 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((R * nb * S,), jnp.int32),
            jax.ShapeDtypeStruct((R * nb * S,), dtype),
            jax.ShapeDtypeStruct((R * nb * S,), dtype),
        ),
        interpret=interpret,
    )(pan_flat.reshape(-1), lohi, xcat)

    cnt_w = cnt_w.reshape(R, nb, S)
    # Global counts: knots before the panel are all < every query of the
    # program (level-1 invariant), so the base is just the panel offset.
    base = start_i * HS                                          # [R, nb]
    cnt = (cnt_w + base[..., None]).reshape(R, nb * S)
    x0 = x0.reshape(R, nb * S)
    x1 = x1.reshape(R, nb * S)
    # Escape: a saturated panel that does not already reach the top of the
    # knot array cannot certify its brackets.
    escaped = jnp.any((cnt_w == PW) & ((base + PW)[..., None] < n_k))

    out = jax.vmap(
        lambda c, a0, a1, row: _finish_inverse(
            c[:n_q], a0[:n_q], a1[:n_q], row, lo=lo, hi=hi, power=power,
            n_q=n_q, n_k=n_k,
        )
    )(cnt, x0, x1, xr)
    out = jnp.where(escaped, jnp.nan, out).reshape(lead + (n_q,))
    return out, escaped
