"""Shared plumbing for the Pallas kernel family (pallas_bellman,
pallas_inverse, pallas_pushforward, pallas_egm): one platform probe deciding
whether a kernel runs compiled (Mosaic, real TPU) or under the Pallas
interpreter (every other backend — the CPU tier-1 parity vehicle).

Why one helper: each kernel call site used to compute
``interpret=(jax.default_backend() != "tpu")`` inline at trace time, which
meant (a) the probe could drift per kernel, and (b) a test could not force
interpret mode without monkeypatching jax itself. Route tests now use
``force_interpret()`` to pin the mode explicitly; the decision stays a
TRACE-TIME host branch (the flag is a jit static arg at every kernel), so
each backend still compiles only its own route.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

__all__ = ["pallas_interpret_mode", "force_interpret"]

# Test hook: None = probe the backend; True/False = forced by
# force_interpret(). Never set directly.
_FORCED: Optional[bool] = None


def pallas_interpret_mode() -> bool:
    """True when Pallas kernels must run the interpreter: any backend that
    is not a real TPU (CPU tier-1, GPU, forced-platform bench runs). The
    single source of truth for every fused kernel's ``interpret=`` flag."""
    if _FORCED is not None:
        return _FORCED
    import jax

    return jax.default_backend() != "tpu"


@contextlib.contextmanager
def force_interpret(value: bool = True) -> Iterator[None]:
    """Force the interpret decision inside the context (tests only).

    The probe is read at TRACE time inside jitted entry points whose cache
    keys do not include it (egm_step, the solvers), so flipping it alone
    would neither retrace already-compiled programs nor stop a forced-mode
    trace leaking into later unforced calls. The context therefore clears
    jax's compilation caches on entry AND exit — every program traced
    inside sees the forced mode, and everything after re-traces with the
    real probe. Heavy-handed (whole-process cache flush) and deliberately
    so: this is a test hook, and silent mode confusion is the one failure
    it must never have."""
    import jax

    global _FORCED
    prev = _FORCED
    _FORCED = bool(value)
    jax.clear_caches()
    try:
        yield
    finally:
        _FORCED = prev
        jax.clear_caches()
