"""Vectorized interpolation kernels: linear (with linear extrapolation, the
interp1 'linear','extrap' analogue), monotone cubic Hermite (pchip,
Fritsch-Carlson slopes matching MATLAB's algorithm), a masked-pchip variant
for data-dependent endogenous grids, and separable bilinear interpolation.

All kernels are gather/searchsorted-based, shape-static, and vmap/jit-safe —
no data-dependent Python control flow. Reference call sites: interp1 linear
at Aiyagari_VFI.m:113; pchip griddedInterpolant at Krusell_Smith_VFI.m:133 and
Krusell_Smith_EGM.m:179,196; 2-D linear griddedInterpolant at
Krusell_Smith_VFI.m:241-244.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

__all__ = [
    "INVERSE_DENSE_CUTOFF",
    "bucket_index",
    "searchsorted_method",
    "inverse_interp_power_grid",
    "bucket_onehot",
    "power_bucket_index",
    "prolong_power_grid",
    "linear_interp",
    "linear_interp_rows",
    "state_policy_interp",
    "state_policy_interp_power",
    "pchip_slopes",
    "pchip_interp",
    "masked_pchip_interp",
    "interp2d_linear",
]

# Above this knot count the O(n*q) comparison matrix stops being worth it and
# we fall back to binary-search searchsorted.
_COMPARE_ALL_MAX = 1024


def searchsorted_method(n: int | None = None) -> str:
    """THE resolver for the searchsorted route split (tuning knob
    "bucket_index") — the one place the platform default lives, per the
    route-resolution discipline (analysis/rules.py AIYA204: no other
    module may re-hardcode a route choice). Shipped default, both
    directions measured (BENCHMARKS.md round 7):

      * CPU: 'scan' — the host executes the binary search's scalar
        gathers in nanoseconds, while the sort route costs 20x more
        (30 ms vs 1.4 ms for 28k queries over 4k knots).
      * accelerators: 'sort' — jnp.searchsorted's 'scan' lowers to
        log2(n) SERIAL gather rounds (the documented TPU pathology,
        ~33 ms vs ~0.4 ms at 40k knots on a v5e); GPU is unmeasured but
        serial gather rounds are the generic accelerator pathology.

    With tuning active (tuning/autotuner.py) the measured probe for this
    platform/grid-bucket wins over the default, and the resolution lands
    on the active run ledger as a `route_decision` event. This is a
    TRACE-time host decision: each backend compiles only its own route.
    """
    from aiyagari_tpu.tuning.autotuner import resolve_route

    default = "scan" if jax.default_backend() == "cpu" else "sort"
    return resolve_route("bucket_index", default, na=n)


def bucket_index(x: jnp.ndarray, q: jnp.ndarray, hi_clip: int | None = None) -> jnp.ndarray:
    """Index i of the grid interval [x[i], x[i+1]) containing each query,
    clipped to [0, n-2] so out-of-range queries use the edge segments.

    TPU note: jnp.searchsorted's default 'scan' method lowers to a serial
    binary-search loop — catastrophic inside a lax.scan over time. For the
    small grids of this workload (100-1024 knots) a branchless comparison
    matrix + row sum is a single fused VPU kernel and an order of magnitude
    faster; larger grids fall back to the unrolled binary search.
    """
    n = x.shape[-1]
    hi = (n - 2) if hi_clip is None else hi_clip
    if n <= _COMPARE_ALL_MAX:
        idx = jnp.sum(x <= q[..., None], axis=-1).astype(jnp.int32) - 1
    else:
        # Platform-split above the compare-all cutoff — the measured
        # rationale, the default, and the tuning-cache consult all live in
        # searchsorted_method (the knob's one resolver, AIYA204).
        method = searchsorted_method(n)
        idx = jnp.searchsorted(x, q, side="right", method=method).astype(jnp.int32) - 1
    return jnp.clip(idx, 0, hi)


def power_bucket_index(x: jnp.ndarray, q: jnp.ndarray, lo: float, hi: float,
                       power: float) -> jnp.ndarray:
    """Closed-form bucket locator for power-spaced grids
    x[i] = lo + (hi-lo) * (i/(n-1))^power  (utils/grids.power_grid).

    Inverts the spacing analytically — float(i) = (n-1) * ((q-lo)/(hi-lo))^(1/p)
    — then applies a bounded +/-1 correction (3 rounds) to absorb float
    rounding. O(1) per query vs O(log n) serial binary-search rounds; this is
    what makes policy evaluation on 100k+-point grids cheap on TPU, where each
    search round is a full gather pass.

    Accuracy domain: exact for lo >= 0 grids in f32/f64 (covers both reference
    grids — quadratic asset grid with amin=0, power-7 capital grid with
    k_min=1e-4). For lo < 0 in f32, cancellation in (q-lo) near the bottom of
    very fine grids can exceed the correction budget — use the generic
    bucket_index there (callers gate on grid_power > 0).
    """
    n = x.shape[-1]
    t = jnp.clip((q - lo) / (hi - lo), 0.0, 1.0) ** (1.0 / power)
    idx = jnp.clip(jnp.floor(t * (n - 1)).astype(jnp.int32), 0, n - 2)
    # Rounding guard: enforce x[idx] <= q < x[idx+1] where representable.
    for _ in range(3):
        idx = jnp.where((x[idx] > q) & (idx > 0), idx - 1, idx)
        idx = jnp.where((x[idx + 1] <= q) & (idx < n - 2), idx + 1, idx)
    return idx


@partial(jax.jit, static_argnames=("lo", "hi", "power", "n_new"))
def prolong_power_grid(Y: jnp.ndarray, lo: float, hi: float, power: float,
                       n_new: int) -> jnp.ndarray:
    """Linearly re-sample values Y[..., n_prev] tabulated on the power grid
    g_prev[i] = lo + (hi-lo)*(i/(n_prev-1))^power onto the n_new-point grid
    with the SAME spacing law. This is the multigrid prolongation
    (solvers/egm.solve_aiyagari_egm_multiscale): because both grids share the
    spacing law, a query's bracket index is closed-form — fractional position
    j*(n_prev-1)/(n_new-1), LINEAR in the query index — so the whole
    re-sample is one jitted program with a single neighbor gather: no search,
    no sort, and one host dispatch instead of an eager op-by-op chain (each
    eager op costs a ~100 ms round trip on this image's remote transport).
    """
    n_prev = Y.shape[-1]
    dtype = Y.dtype
    span = hi - lo
    np1, nn1 = n_prev - 1, n_new - 1
    j = jnp.arange(n_new)
    fi = j.astype(dtype) * (np1 / nn1)
    i0e = jnp.floor(fi).astype(jnp.int32)

    if power == 2.0 and np1 * 4096 + (n_new // 4096 + 1) * nn1 < 2**31:
        # Cancellation-free bracket and weight for the quadratic spacing law.
        # The naive t = (q - g0)/(g1 - g0) subtracts near-equal O(hi) values:
        # in f32 its rounding noise is a few percent of a cell near the grid
        # top, which injects ~4e-5 absolute consumption error into every
        # multigrid warm start (the fine-stage sweep count itself is set by
        # the f32 ulp-noise band of the sup-norm criterion — BENCHMARKS.md).
        # Algebraically
        #   t = (tj^2 - ti0^2)/(ti1^2 - ti0^2)
        #     = num * (tj + ti0) / (nn1 * (ti0 + ti1)),
        # with tj = j/nn1, ti = i/np1, and num = (j*np1) mod nn1, the exact
        # integer remainder — evaluated in int32 by splitting j = jh*4096+jl;
        # the entry guard bounds the SUM jh*m1 + jl*np1 (the actual int32
        # quantity below, jh*m1 < (n_new//4096+1)*nn1 and jl*np1 < 4096*np1),
        # not just each factor. The exact floor i0 is
        # recovered from the f32 position estimate plus the exact fractional
        # part (the estimate's error, ~6e-8*j, is far below 1/2). Every
        # factor is well-conditioned, so t carries only f32 eps relative
        # error and the warm start only true discretization error.
        jh, jl = j // 4096, j % 4096
        m1 = (np1 * 4096) % nn1
        mm = (jh * m1 + jl * np1) % nn1
        frac_true = mm.astype(dtype) / nn1
        k = jnp.round(fi - i0e.astype(dtype) - frac_true).astype(jnp.int32)
        i0 = jnp.clip(i0e + k, 0, n_prev - 2)
        tj = j.astype(dtype) / nn1
        ti0 = i0.astype(dtype) / np1
        ti1 = (i0 + 1).astype(dtype) / np1
        t = mm.astype(dtype) * (tj + ti0) / (nn1 * (ti0 + ti1))
        # The one clipped bracket is the last query (floor == np1, mm == 0):
        # its weight is exactly 1 on the (n_prev-2, n_prev-1) cell.
        t = jnp.where(j == nn1, 1.0, jnp.clip(t, 0.0, 1.0))
    else:
        i0 = jnp.clip(i0e, 0, n_prev - 2)

        def g_prev(i):
            return lo + span * (i.astype(dtype) / np1) ** power

        q = lo + span * (j.astype(dtype) / nn1) ** power
        # Two correction rounds absorb f32 rounding of the fractional
        # position (cf. power_bucket_index).
        for _ in range(2):
            i0 = jnp.where((i0 > 0) & (g_prev(i0) > q), i0 - 1, i0)
            i0 = jnp.where((i0 < n_prev - 2) & (g_prev(i0 + 1) <= q), i0 + 1, i0)
        g0, g1 = g_prev(i0), g_prev(i0 + 1)
        t = jnp.clip((q - g0) / (g1 - g0), 0.0, 1.0)
    y0 = jnp.take(Y, i0, axis=-1)
    y1 = jnp.take(Y, i0 + 1, axis=-1)
    return y0 * (1.0 - t) + y1 * t


def bucket_onehot(x: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """One-hot encoding of bucket_index over the n-1 grid intervals,
    [..., n-1] float of the query dtype.

    Built from differences of step functions over the interior knots, so
    out-of-range queries land in the edge buckets (linear extrapolation
    semantics) without any integer indexing. This is the gather-free route:
    on TPU a gather of B indices costs ~B scalar cycles, while the one-hot
    contraction is dense VPU/MXU work.
    """
    C = (q[..., None] >= x[1:-1]).astype(q.dtype)          # [..., n-2]
    return jnp.concatenate(
        [1.0 - C[..., :1], C[..., :-1] - C[..., 1:], C[..., -1:]], axis=-1
    )


def state_policy_interp(x: jnp.ndarray, policies: jnp.ndarray, state_idx: jnp.ndarray,
                        q: jnp.ndarray) -> jnp.ndarray:
    """Per-agent linear interpolation of each agent's state's policy row,
    entirely gather-free: out[b] = interp(x, policies[state_idx[b]], q[b]).

    x [n] sorted; policies [ns, n]; state_idx [B] int; q [B]. Linearly
    extrapolates via edge segments (interp1 'linear','extrap' semantics).

    This is the agent-panel hot path (Krusell_Smith_VFI.m:241-244 evaluates a
    per-state interpolant for each agent group; Aiyagari_VFI.m:110-117 does it
    per agent). Both the state selection and the bucket selection become
    one-hot contractions: a [B, ns] x [ns, n] matmul picks policy rows, a
    [B, n-1] one-hot picks segments. Contractions run at HIGHEST precision —
    the default TPU f32 matmul is bf16-based and loses ~3 decimal digits,
    which is visible in policy values O(100).
    """
    ns = policies.shape[0]
    hi = jax.lax.Precision.HIGHEST
    ohS = (state_idx[:, None] == jnp.arange(ns)[None, :]).astype(q.dtype)   # [B, ns]
    Y = jnp.matmul(ohS, policies, precision=hi)                             # [B, n]
    sel = bucket_onehot(x, q)                                               # [B, n-1]
    x0 = jnp.matmul(sel, x[:-1], precision=hi)
    x1 = jnp.matmul(sel, x[1:], precision=hi)
    y0 = jnp.sum(sel * Y[:, :-1], axis=1)
    y1 = jnp.sum(sel * Y[:, 1:], axis=1)
    t = (q - x0) / (x1 - x0)
    return y0 + t * (y1 - y0)


def state_policy_interp_power(policies: jnp.ndarray, state_idx: jnp.ndarray,
                              q: jnp.ndarray, *, lo: float, hi: float,
                              power: float) -> jnp.ndarray:
    """state_policy_interp for an ANALYTIC power grid x[i] = lo +
    (hi-lo)*(i/(n-1))**power: the bucket index and both bracketing knot
    values come from closed forms, so the data-dependent work reduces to a
    hat-weighted reduction over the knot axis. The [B, n] hat-weight array
    `w` and the per-state-masked policy rows ARE materialized (and the
    state-selection loop scales with ns — revisit for large state spaces);
    the measured win over state_policy_interp comes from eliminating its
    HIGHEST-precision matmuls and searchsorted, not from avoiding [B, n]
    intermediates. Queries below lo
    clamp into the first segment and above hi into the last (edge-segment
    extrapolation, matching state_policy_interp up to the analytic
    bracket's f32 rounding; agreement is O(segment width) * eps — measured
    4e-6 at the K-S power-7 grid, policies O(100)).

    The win is population-dependent: at the reference's 10,000-agent panel
    the one-hot matmul route is already occupancy-bound and this route is
    ~par; at 100k+ agents per device it is ~2x (HBM traffic drops ~30x).
    Used by the panel simulators when the capital grid is power-spaced
    (sim/ks_panel.py grid_power)."""
    ns, n = policies.shape
    span = hi - lo
    u = jnp.clip((q - lo) / span, 0.0, 1.0)
    pos = (n - 1) * u ** (1.0 / power)
    i0 = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, n - 2)
    g0 = lo + span * (i0.astype(q.dtype) / (n - 1)) ** power
    g1 = lo + span * ((i0 + 1).astype(q.dtype) / (n - 1)) ** power
    d = g1 - g0
    # High-power grids have segments far below f32 resolution near lo (the
    # K-S power-7 bottom segment is ~1e-11 wide at span 1000): there d
    # underflows to ~0 and (q-g0)/d explodes — measured walking the panel
    # mean NEGATIVE. Degrade those segments to their left knot value (error
    # <= the collapsed segment's width); the stored-knot route avoids this
    # only because its comparison-based bucket can never strictly contain a
    # query. t is otherwise NOT clamped: edge-segment extrapolation.
    t = jnp.where(d > 8 * jnp.finfo(q.dtype).eps * jnp.abs(g1),
                  (q - g0) / d, 0.0)
    i_ax = jnp.arange(n)[None, :]
    w = jnp.where(i_ax == i0[:, None], 1.0 - t[:, None], 0.0) + \
        jnp.where(i_ax == i0[:, None] + 1, t[:, None], 0.0)
    sid = state_idx[:, None]
    Y = policies[0][None, :] * (sid == 0)
    for s in range(1, ns):
        Y = Y + policies[s][None, :] * (sid == s)
    return jnp.sum(w * Y, axis=-1)


# Public: grids at or below this knot count take the escape-free dense route
# of inverse_interp_power_grid; larger grids take the windowed route, which
# can poison with NaN (see its docstring). Host-level retry wrappers use this
# to decide whether a NaN can be a window escape at all (solvers/egm.py).
INVERSE_DENSE_CUTOFF = 4096
_INV_QBLOCK = 512       # queries per block in the windowed route
_INV_KBLOCK = 512       # knot-block granularity of the gathered windows
_INV_WBLOCKS = 6        # knot blocks per window (window covers 6x local density)


def _finish_inverse(cnt, x0, x1, xr, *, lo, hi, power, n_q, n_k, q_vals=None):
    """Shared tail of the power-grid inversion: bracket data -> interpolated
    inverse. cnt = #{k: x_k < g_j} per query, (x0, x1) the bracketing knot
    values (±inf where absent), xr the knot row — only its first two knots
    are read (the below-range extrapolation slope), so callers holding a
    shard may pass just those. q_vals overrides the query values for
    callers evaluating a SLICE of the query grid (the halo-sharded route);
    default is the full analytic n_q-point grid. Used by the XLA routes
    here, the fused Pallas kernel (ops/pallas_inverse.py), and the
    halo-exchange sharded route (parallel/halo.py), so they cannot drift."""
    dtype = xr.dtype
    span = hi - lo

    def g_of(i):
        return lo + span * (i.astype(dtype) / (n_q - 1)) ** power

    def gk_of(i):
        return lo + span * (i.astype(dtype) / (n_k - 1)) ** power

    if q_vals is None:
        q_vals = g_of(jnp.arange(n_q))
    idx = cnt - 1
    below = idx < 0
    idx_c = jnp.clip(idx, 0, n_k - 1)
    y0 = gk_of(idx_c)
    y1 = gk_of(jnp.minimum(idx_c + 1, n_k - 1))
    dx = x1 - x0
    ok = jnp.isfinite(dx) & (dx > 0)
    tq = jnp.where(ok, (q_vals - x0) / jnp.where(ok, dx, 1.0), 0.0)
    out = y0 + tq * (y1 - y0)
    # Below the first knot: linear extrapolation on the first segment
    # (interp1 'linear','extrap' bottom semantics).
    sl = (gk_of(jnp.int32(1)) - gk_of(jnp.int32(0))) / jnp.maximum(
        xr[1] - xr[0], jnp.finfo(dtype).tiny
    )
    out_below = gk_of(jnp.int32(0)) + (q_vals - xr[0]) * sl
    return jnp.where(below, out_below, out)


def _finish_monotone(x0, x1, y0, y1, xr, yr, q_vals):
    """Shared tail of the monotone-value interpolation: bracket data ->
    interpolated values. (x0, x1)/(y0, y1) are the bracketing knots/values
    (±inf where absent); only the first two entries of the knot/value rows
    (xr, yr) are read (the below-range extrapolation slope), so callers
    holding a shard may pass just the global head pairs. q_vals is this
    caller's query slice. Used by interp_monotone_power_grid and the
    ring-sharded route (parallel/ring.ring_interp_local), so the edge
    semantics — nearest above the top knot, first-segment linear
    extrapolation below the first — cannot drift between them."""
    dtype = xr.dtype
    have_lo = jnp.isfinite(x0)          # some knot strictly below q
    have_hi = jnp.isfinite(x1)          # some knot at-or-above q
    dx = x1 - x0
    ok = have_lo & have_hi & (dx > 0)
    tq = jnp.where(ok, (q_vals - x0) / jnp.where(ok, dx, 1.0), 0.0)
    out = jnp.where(have_lo, y0, yr[0]) + tq * (y1 - jnp.where(have_lo, y0, yr[0]))
    # Above the top knot: nearest (last) value.
    out = jnp.where(have_lo & ~have_hi, y0, out)
    # Below the first knot: linear extrapolation on the first segment.
    sl = (yr[1] - yr[0]) / jnp.maximum(xr[1] - xr[0], jnp.finfo(dtype).tiny)
    out_below = yr[0] + (q_vals - xr[0]) * sl
    return jnp.where(~have_lo, out_below, out)


def inverse_interp_power_grid(x: jnp.ndarray, lo: float, hi: float, power: float,
                              n_q: int, *, with_escape: bool = False):
    """Interpolate the inverse of a monotone map onto a power-spaced grid:
    given sorted knots x[..., k] = f(g_k) over the grid
    g_k = lo + (hi-lo)*(k/(n_k-1))^power, return, for each query point g_j of
    the n_q-point grid with the SAME spacing law, the piecewise-linear inverse
    out[..., j] = g_K + (g_{K+1}-g_K) * (g_j - x_K)/(x_{K+1} - x_K), where
    K = max{k: x_k < g_j} (x_K is the last knot strictly below the query and
    x_{K+1} the first knot at-or-above it, so a query equal to a knot returns
    that knot's grid value exactly).

    This is the EGM hot operation (policy from the endogenous grid,
    interp1(a_hat, a_grid, a_grid) at Aiyagari_EGM.m:95). TPU mapping: every
    route here is built from broadcast-compare + reduce — no scatter (XLA TPU
    serializes scatters with colliding indices: the previous scatter+cummax
    formulation measured ~90 ms per sweep at [7, 40k], ~60x the memory-bound
    cost), no sort, no associative_scan (the generic combinator's HLO takes
    tens of seconds to compile on this image's remote-compile path), and no
    large element gathers (a [7, 400k] take_along_axis measures ~20 ms).

      * n_k <= 4096: one fused [n_q, n_k] compare-reduce per row gives the
        bracket count and both bracketing knot values directly (VPU work on
        an unmaterialized broadcast).
      * larger n_k: a two-level windowed variant of the same idea. Queries
        are tiled into blocks of 512; one [n_blocks, n_k] compare-reduce
        locates each block's first bracketing knot; each block then gathers a
        3,072-knot window as 6 contiguous 512-knot slabs (block-granular DMA,
        not element gathers) and runs the dense compare-reduce against its
        window only. Exact whenever no query block spans more than the
        window's worth of knots; blocks that would (knot density > 6x the
        query density — not reachable from the EGM operator's endogenous
        grids at the shipped calibrations, whose knot spacing is bounded
        below by grid spacing/(1+r)) POISON the whole result with NaN. The
        EGM fixed point then exits on its NaN distance and the host solver
        retries with the generic exact route (solvers/egm.py
        solve_aiyagari_egm_safe) — correctness is never traded for speed.

    Queries below the first knot extrapolate linearly on the first segment
    (interp1 'linear','extrap'); queries above the last knot return the top
    grid point (the framework's grid-top truncation, see ops/egm.egm_step).
    Duplicated knots (f32 collisions on fine grids): the strict-< bracket
    makes a query equal to a run of tied knots interpolate to the FIRST tied
    knot's grid value, where the generic sort-based route returns the last
    tie's — both are valid inverses of the collided segment (the choices
    differ by less than the local grid spacing, below the solvers'
    tolerance); queries strictly inside a zero-width bracket cannot occur.

    x: [..., n_k] sorted ascending along the last axis. Returns [..., n_q];
    with_escape=True returns (out, escaped) where escaped is a scalar bool
    array that is True iff the windowed route actually escaped (always False
    on the dense route) — this is how host-level retry wrappers distinguish a
    window escape from genuine numerical divergence, which also NaNs
    (solvers/egm.solve_aiyagari_egm_safe).
    Both grids share (lo, hi, power); n_k and n_q may differ (the EGM sweep
    uses n_k == n_q; the mismatched case is kept because the kernel is the
    grid-family-generic inverse, pinned by TestPowerGridInversion's
    n_k != n_q cases).
    """
    n_k = x.shape[-1]
    cnt, x0, x1, _, _, escaped = _bracket_power_grid(x, None, lo, hi, power, n_q)
    out = jax.vmap(
        lambda c, a0, a1, row: _finish_inverse(c, a0, a1, row, lo=lo, hi=hi,
                                               power=power, n_q=n_q, n_k=n_k)
    )(cnt, x0, x1, x.reshape((-1, n_k)))
    out = jnp.where(escaped, jnp.nan, out).reshape(x.shape[:-1] + (n_q,))
    return (out, escaped) if with_escape else out


def _bracket_power_grid(x, y, lo, hi, power, n_q):
    """Shared bracket machinery of the power-grid interpolation kernels:
    for every query g_j of the n_q-point power grid, the count of knots
    strictly below it and the bracketing knot values (±inf where absent) —
    and, when a value row `y` is supplied (interp_monotone_power_grid), the
    bracketing VALUES from the same masked reductions (exact because y is
    monotone). One implementation for the dense and two-level windowed
    routes, so the window geometry and escape rule cannot drift between the
    inverse and monotone-value kernels.

    x [..., n_k] sorted; y None or same shape. Returns
    (cnt [R, n_q] i32, x0, x1, y0, y1, escaped) with rows flattened to R;
    y0/y1 are None when y is None; escaped is the scalar window-escape flag
    (always False on the dense route).
    """
    n_k = x.shape[-1]
    dtype = x.dtype
    span = hi - lo
    neg, pos = jnp.array(-jnp.inf, dtype), jnp.array(jnp.inf, dtype)
    with_y = y is not None

    def g_of(i):
        return lo + span * (i.astype(dtype) / (n_q - 1)) ** power

    q_vals = g_of(jnp.arange(n_q))
    xr_all = x.reshape((-1, n_k))
    # A dummy second operand keeps one vmap signature for both cases.
    yr_all = y.reshape((-1, n_k)) if with_y else xr_all

    if n_k <= INVERSE_DENSE_CUTOFF:
        def dense_row(xr, yr):
            lt = xr[None, :] < q_vals[:, None]                        # [n_q, n_k]
            cnt = jnp.sum(lt, axis=1).astype(jnp.int32)
            x0 = jnp.max(jnp.where(lt, xr[None, :], neg), axis=1)
            x1 = jnp.min(jnp.where(lt, pos, xr[None, :]), axis=1)
            if not with_y:
                return cnt, x0, x1, x0, x1
            y0 = jnp.max(jnp.where(lt, yr[None, :], neg), axis=1)
            y1 = jnp.min(jnp.where(lt, pos, yr[None, :]), axis=1)
            return cnt, x0, x1, y0, y1

        cnt, x0, x1, y0, y1 = jax.vmap(dense_row)(xr_all, yr_all)
        return cnt, x0, x1, (y0 if with_y else None), (y1 if with_y else None), \
            jnp.array(False)

    S, KB, M = _INV_QBLOCK, _INV_KBLOCK, _INV_WBLOCKS
    nkb = -(-n_k // KB)            # >= 8 under the dense gate, so nkb >= M
    nb = -(-n_q // S)
    L = M * KB

    def windowed_row(xr, yr):
        pad = nkb * KB - n_k
        xp = xr if pad == 0 else jnp.concatenate([xr, jnp.full((pad,), pos)])
        xblk = xp.reshape(nkb, KB)
        # Padded query indices clamp to the last real query: duplicates of an
        # existing query, so they introduce no new escapes and are sliced off.
        jq = jnp.minimum(jnp.arange(nb * S), n_q - 1)
        qs = g_of(jq).reshape(nb, S)

        # Level 1: each block's bracket start from one fused compare-reduce.
        s_first = jnp.sum(xr[None, :] < qs[:, :1], axis=1).astype(jnp.int32)  # [nb]
        ab = jnp.minimum(jnp.clip(s_first - 1, 0, n_k - 1) // KB, nkb - M)

        # Level 2: gather each block's window as M contiguous knot slabs and
        # run the dense compare-reduce against the window only. Knots before
        # the window are all < the block's first query by construction of ab.
        seg = xblk[ab[:, None] + jnp.arange(M)[None, :]].reshape(nb, L)
        lt = seg[:, None, :] < qs[:, :, None]                         # [nb, S, L]
        cnt_w = jnp.sum(lt, axis=-1).astype(jnp.int32)
        cnt = ab[:, None] * KB + cnt_w
        x0 = jnp.max(jnp.where(lt, seg[:, None, :], neg), axis=-1)
        x1 = jnp.min(jnp.where(lt, pos, seg[:, None, :]), axis=-1)
        # cnt_w == L means every window knot is below the query, so the true
        # bracket may lie beyond the window — unless the window already ends
        # at the top of the knot array (top-truncation case, exact).
        escape = jnp.any((cnt_w == L) & ((ab[:, None] + M) * KB < n_k))

        def cut(a):
            return a.reshape(-1)[:n_q]

        if not with_y:
            return cut(cnt), cut(x0), cut(x1), cut(x0), cut(x1), escape
        yp = yr if pad == 0 else jnp.concatenate([yr, jnp.full((pad,), pos)])
        segy = yp.reshape(nkb, KB)[ab[:, None] + jnp.arange(M)[None, :]].reshape(nb, L)
        # The y brackets from knots BEFORE the window would be <= the
        # window's by monotonicity, so the window reductions are exact
        # whenever the x bracket is (same saturation rule).
        y0 = jnp.max(jnp.where(lt, segy[:, None, :], neg), axis=-1)
        y1 = jnp.min(jnp.where(lt, pos, segy[:, None, :]), axis=-1)
        return cut(cnt), cut(x0), cut(x1), cut(y0), cut(y1), escape

    cnt, x0, x1, y0, y1, escapes = jax.vmap(windowed_row)(xr_all, yr_all)
    return cnt, x0, x1, (y0 if with_y else None), (y1 if with_y else None), \
        jnp.any(escapes)


def interp_monotone_power_grid(x: jnp.ndarray, y: jnp.ndarray, lo: float,
                               hi: float, power: float, n_q: int, *,
                               with_escape: bool = False):
    """Windowed compare-reduce interpolation of a MONOTONE tabulated function
    onto a power-spaced query grid: given sorted knots x[..., k] with
    non-decreasing values y[..., k], return y interpolated at the n_q-point
    power grid g_j = lo + (hi-lo)*(j/(n_q-1))^power.

    This is the endogenous-labor EGM hot operation (consumption policy from
    the endogenous grid, interp1(a_hat, c_next, a_grid) at
    Aiyagari_Endogenous_Labor_EGM.m:90) in the same gather/sort/scatter-free
    form as inverse_interp_power_grid — that kernel is the special case
    y_k = analytic grid values, where the bracketing VALUES can be
    reconstructed from the count alone. Here y is data, but because it is
    monotone the bracketing values come from the SAME masked max/min
    reductions that locate the bracketing knots: y0 = max{y_k : x_k < q} and
    y1 = min{y_k : x_k >= q} are exactly the bracket's endpoint values.
    Monotonicity is the caller's contract (the EGM consumption iterate is
    increasing in a' in exact arithmetic; callers cummax both arrays to
    absorb f32 rounding, cf. ops/egm.egm_step).

    Semantics at the edges: queries above the last knot return the last
    value (nearest — the labor EGM's grid-top discipline, ops/egm.
    egm_step_labor); queries below the first knot extrapolate linearly on
    the first segment (callers overwrite that region with the exact
    constrained solution anyway). Escape contract and window geometry are
    identical to inverse_interp_power_grid (NaN poisoning + escaped flag).
    """
    n_k = x.shape[-1]
    dtype = x.dtype
    span = hi - lo
    q_vals = lo + span * (jnp.arange(n_q).astype(dtype) / (n_q - 1)) ** power

    _, x0, x1, y0, y1, escaped = _bracket_power_grid(x, y, lo, hi, power, n_q)
    out = jax.vmap(
        lambda a0, a1, b0, b1, xr, yr: _finish_monotone(a0, a1, b0, b1, xr, yr,
                                                        q_vals)
    )(x0, x1, y0, y1, x.reshape((-1, n_k)), y.reshape((-1, n_k)))
    out = jnp.where(escaped, jnp.nan, out).reshape(x.shape[:-1] + (n_q,))
    return (out, escaped) if with_escape else out


def linear_interp(x: jnp.ndarray, y: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Piecewise-linear interpolation of (x, y) at q, linearly extrapolating
    beyond both ends using the edge segments (interp1 'linear','extrap').

    x must be sorted ascending, shape [n]; y shape [..., n] broadcasting over
    leading axes; q any shape. Zero-width intervals (possible when x is a
    data-dependent grid whose adjacent knots collide at f32 resolution — the
    EGM endogenous grid at 100k+ points does this) return the left knot value
    instead of 0/0 = NaN.
    """
    idx = bucket_index(x, q)
    x0 = x[idx]
    x1 = x[idx + 1]
    dx = x1 - x0
    t = jnp.where(dx > 0, (q - x0) / jnp.where(dx > 0, dx, 1.0), 0.0)
    y0 = jnp.take(y, idx, axis=-1)
    y1 = jnp.take(y, idx + 1, axis=-1)
    return y0 * (1.0 - t) + y1 * t


def linear_interp_rows(x: jnp.ndarray, Y: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Row-wise linear interpolation: one query per row of Y on a shared grid.

    x [n] sorted ascending, Y [B, n], q [B] -> [B]. Linearly extrapolates via
    edge segments. This is the agent-panel policy-evaluation gather: each
    agent's row is its state's policy (Aiyagari_VFI.m:110-117 per-agent
    interp1 calls, batched).
    """
    idx = bucket_index(x, q)
    x0 = x[idx]
    x1 = x[idx + 1]
    dx = x1 - x0
    t = jnp.where(dx > 0, (q - x0) / jnp.where(dx > 0, dx, 1.0), 0.0)
    y0 = jnp.take_along_axis(Y, idx[:, None], axis=1)[:, 0]
    y1 = jnp.take_along_axis(Y, (idx + 1)[:, None], axis=1)[:, 0]
    return y0 * (1.0 - t) + y1 * t


def _fc_interior_slopes(h0, h1, d0, d1):
    """Fritsch-Carlson weighted-harmonic-mean slope for an interior point with
    left/right interval widths (h0, h1) and secants (d0, d1)."""
    w1 = 2.0 * h1 + h0
    w2 = h1 + 2.0 * h0
    denom = w1 / jnp.where(d0 == 0.0, 1.0, d0) + w2 / jnp.where(d1 == 0.0, 1.0, d1)
    slope = (w1 + w2) / denom
    # Zero slope where secants change sign or either is zero (preserves monotonicity).
    ok = (jnp.sign(d0) * jnp.sign(d1)) > 0.0
    return jnp.where(ok, slope, 0.0)


def _fc_endpoint_slope(h0, h1, d0, d1):
    """Non-centered three-point endpoint slope with MATLAB pchip's clamping:
    shape-preserving limit to 3*d0, zero if it points the wrong way."""
    d = ((2.0 * h0 + h1) * d0 - h0 * d1) / (h0 + h1)
    d = jnp.where(jnp.sign(d) != jnp.sign(d0), 0.0, d)
    wrong_curv = (jnp.sign(d0) != jnp.sign(d1)) & (jnp.abs(d) > 3.0 * jnp.abs(d0))
    return jnp.where(wrong_curv, 3.0 * d0, d)


def pchip_slopes(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Derivative values d[i] at each knot for shape-preserving cubic Hermite
    interpolation; matches MATLAB pchip (Fritsch-Carlson 1980).

    x sorted ascending [n] (n >= 3), y [n]. Returns d [n].
    """
    h = jnp.diff(x)                       # [n-1]
    delta = jnp.diff(y) / h               # [n-1] secants
    d_int = _fc_interior_slopes(h[:-1], h[1:], delta[:-1], delta[1:])  # [n-2]
    d0 = _fc_endpoint_slope(h[0], h[1], delta[0], delta[1])
    dn = _fc_endpoint_slope(h[-1], h[-2], delta[-1], delta[-2])
    return jnp.concatenate([d0[None], d_int, dn[None]])


def _hermite_eval(x0, x1, y0, y1, d0, d1, q):
    h = x1 - x0
    t = (q - x0) / h
    t2 = t * t
    t3 = t2 * t
    h00 = 2.0 * t3 - 3.0 * t2 + 1.0
    h10 = t3 - 2.0 * t2 + t
    h01 = -2.0 * t3 + 3.0 * t2
    h11 = t3 - t2
    return h00 * y0 + h10 * h * d0 + h01 * y1 + h11 * h * d1


def pchip_interp(x: jnp.ndarray, y: jnp.ndarray, q: jnp.ndarray, d: jnp.ndarray | None = None) -> jnp.ndarray:
    """Shape-preserving cubic interpolation of (x, y) at q. Queries are clamped
    to [x[0], x[-1]] (nearest-style extrapolation, matching the reference's
    clamped pchip use at Krusell_Smith_VFI.m:346-349 and the 'nearest' extrap
    at Krusell_Smith_EGM.m:196). Pass precomputed slopes d to amortize.
    """
    if d is None:
        d = pchip_slopes(x, y)
    qc = jnp.clip(q, x[0], x[-1])
    idx = bucket_index(x, qc)
    return _hermite_eval(x[idx], x[idx + 1], y[idx], y[idx + 1], d[idx], d[idx + 1], qc)


def masked_pchip_interp(xs: jnp.ndarray, ys: jnp.ndarray, n_valid: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """pchip over the first `n_valid` entries of the sorted knot arrays
    (xs, ys); entries beyond n_valid are sentinel knots (xs = +inf) and never
    influence the result. Queries outside the valid range clamp to the nearest
    valid endpoint.

    This is the static-shape device analogue of the reference's sort/mask/
    reinterpolate step (Krusell_Smith_EGM.m:192-198), where the endogenous grid
    is filtered to [k_min, k_max] before building a pchip interpolant — the
    filtered count is data-dependent, so instead of a dynamic-shape gather we
    carry the full array plus a valid count.
    """
    n = xs.shape[-1]
    i = jnp.arange(n)
    last = n_valid - 1

    h = jnp.diff(xs)
    h = jnp.where(jnp.isfinite(h) & (h > 0), h, 1.0)
    delta = jnp.diff(ys) / h

    # Interior FC slopes, then overwrite the two effective endpoints with the
    # one-sided formula; sentinel region slopes are irrelevant (never gathered
    # below index n_valid-1).
    d_int = _fc_interior_slopes(h[:-1], h[1:], delta[:-1], delta[1:])
    d = jnp.concatenate([jnp.zeros((1,), xs.dtype), d_int, jnp.zeros((1,), xs.dtype)])
    d0 = _fc_endpoint_slope(h[0], h[1], delta[0], delta[1])
    dl = _fc_endpoint_slope(
        h[last - 1], h[jnp.maximum(last - 2, 0)], delta[last - 1], delta[jnp.maximum(last - 2, 0)]
    )
    d = d.at[0].set(d0)
    d = d.at[last].set(dl)

    qc = jnp.clip(q, xs[0], xs[last])
    idx = jnp.minimum(bucket_index(xs, qc), last - 1)
    return _hermite_eval(xs[idx], xs[idx + 1], ys[idx], ys[idx + 1], d[idx], d[idx + 1], qc)


def interp2d_linear(x: jnp.ndarray, ygrid: jnp.ndarray, Z: jnp.ndarray, qx: jnp.ndarray, qy: jnp.ndarray) -> jnp.ndarray:
    """Separable bilinear interpolation of Z[nx, ny] at points (qx, qy), with
    linear extrapolation outside the grid (griddedInterpolant 'linear' default,
    Krusell_Smith_VFI.m:241-244). qx, qy broadcast together.
    """
    ix = bucket_index(x, qx)
    iy = bucket_index(ygrid, qy)
    tx = (qx - x[ix]) / (x[ix + 1] - x[ix])
    ty = (qy - ygrid[iy]) / (ygrid[iy + 1] - ygrid[iy])
    z00 = Z[ix, iy]
    z01 = Z[ix, iy + 1]
    z10 = Z[ix + 1, iy]
    z11 = Z[ix + 1, iy + 1]
    return (
        z00 * (1 - tx) * (1 - ty)
        + z10 * tx * (1 - ty)
        + z01 * (1 - tx) * ty
        + z11 * tx * ty
    )
