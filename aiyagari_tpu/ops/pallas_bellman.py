"""Pallas TPU kernel for the Bellman choice reduction — the framework's
hottest dense op.

Computes, for each (state i, asset j):
    v[i,j]  = max_{j'} u(coh[i,j] - a[j']) + EV[i,j']
    idx[i,j] = argmax (first maximizer, MATLAB max semantics)

The XLA path (ops/bellman.py) either materializes the full [N, na, na']
utility tensor or scans a'-blocks with HBM-resident intermediates. This kernel
tiles (j, j') into VMEM, fuses the budget/utility/mask/add/max chain in one
pass, and accumulates the running max/argmax in the revisited output block —
intermediates never touch HBM. The (small) state axis stays whole inside each
block — Mosaic requires the last two block dims be lane/sublane aligned or
span the full array dim, and N (7 states, 4 for K-S) is far below the 8-row
sublane tile, so splitting it is both illegal and wasteful. Grid iterates
(j-tile, j'-tile) with j' innermost; the first j'-step initializes the
accumulators (@pl.when).

Reference semantics: Aiyagari_VFI.m:70-83 (c<=0 masked to -inf via NaN there;
ties resolved to the first index by MATLAB max).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from aiyagari_tpu.utils.utility import crra_utility

__all__ = ["bellman_max_pallas"]


def _kernel(coh_ref, a_ref, ev_ref, v_ref, idx_ref, *, sigma: float, na: int, bjp: int):
    pj = pl.program_id(1)
    coh = coh_ref[...]                        # [N, bj]
    ap = a_ref[0, :]                          # [bjp]
    ev = ev_ref[...]                          # [N, bjp]

    c = coh[:, :, None] - ap[None, None, :]   # [N, bj, bjp]
    feasible = c > 0.0
    u = crra_utility(jnp.where(feasible, c, 1.0), sigma)
    neg_inf = jnp.array(-jnp.inf, u.dtype)
    q = jnp.where(feasible, u + ev[:, None, :], neg_inf)

    # Mask a'-lanes beyond the true grid (last tile may be padded).
    gidx = pj * bjp + jax.lax.broadcasted_iota(jnp.int32, q.shape, 2)
    q = jnp.where(gidx < na, q, neg_inf)

    m = jnp.max(q, axis=2)                                     # [N, bj]
    am = (jnp.argmax(q, axis=2) + pj * bjp).astype(jnp.int32)  # [N, bj] global

    @pl.when(pj == 0)
    def _():
        v_ref[...] = m
        idx_ref[...] = am

    @pl.when(pj != 0)
    def _():
        prev = v_ref[...]
        take = m > prev                       # strict: earlier tile wins ties
        v_ref[...] = jnp.where(take, m, prev)
        idx_ref[...] = jnp.where(take, am, idx_ref[...])


@functools.partial(jax.jit, static_argnames=("sigma", "block_j", "block_jp", "interpret"))
def bellman_max_pallas(coh, a_grid, EV, *, sigma: float, block_j: int = 128,
                       block_jp: int = 2048, interpret: bool = False):
    """Fused Bellman choice reduction.

    coh [N, na] cash-on-hand; a_grid [na]; EV [N, na'] discounted expected
    values (beta * P @ v). Returns (v_new [N, na], idx [N, na] int32).
    Defaults are the best measured config on a v5e chip (5.0 ms/sweep at
    N=7, na=8000); note the XLA blocked path (ops/bellman.py, block_size>0)
    measures ~3.3 ms/sweep on the same problem — XLA's own fusion wins here,
    so this kernel is opt-in (SolverConfig.use_pallas), kept as the
    hand-tiled alternative for shapes where the compiler schedule loses.
    """
    N, na = coh.shape
    bj = min(block_j, na)
    bjp = min(block_jp, na)
    nj = -(-na // bj)
    njp = -(-na // bjp)

    # Pad to tile multiples; padded j-rows produce junk sliced off below, and
    # padded a'-lanes are masked inside the kernel against the true na.
    coh_p = jnp.pad(coh, ((0, 0), (0, nj * bj - na)))
    a_p = jnp.pad(a_grid, (0, njp * bjp - na))[None, :]
    ev_p = jnp.pad(EV, ((0, 0), (0, njp * bjp - na)))

    v, idx = pl.pallas_call(
        functools.partial(_kernel, sigma=sigma, na=na, bjp=bjp),
        grid=(nj, njp),
        in_specs=[
            pl.BlockSpec((N, bj), lambda j, p: (0, j)),
            pl.BlockSpec((1, bjp), lambda j, p: (0, p)),
            pl.BlockSpec((N, bjp), lambda j, p: (0, p)),
        ],
        out_specs=[
            pl.BlockSpec((N, bj), lambda j, p: (0, j)),
            pl.BlockSpec((N, bj), lambda j, p: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, nj * bj), coh.dtype),
            jax.ShapeDtypeStruct((N, nj * bj), jnp.int32),
        ],
        interpret=interpret,
    )(coh_p, a_p, ev_p)
    return v[:, :na], idx[:, :na]
